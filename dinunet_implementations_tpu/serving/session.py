"""Session-slot cache — O(1) recurrent state for streaming inference.

The serving twin of the elastic-rounds MembershipTable
(robustness/membership.py): logical STREAMING SESSIONS float over a fixed
``[slots]`` device-resident carry table, so the compiled streaming step has
one shape for the life of the server and a returning stream ships only its
NEW timesteps. Two halves:

- :class:`SessionTable` — host-side bookkeeping (session id → slot, LRU
  eviction, generation counters). NOT internally locked: the stream lane's
  dispatch thread (resolve) and the caller's thread (close_session, the
  summary rollup) both touch it, and the engine serializes every access
  under its ``_session_lock``. Like the membership table it never touches
  jax state — sessions reach the compiled program only as gathered slot
  indices and a ``fresh`` reset gate (both traced inputs).
- :func:`init_carry_table` — the device-resident ``[slots+1, …]`` pytree the
  streaming executable gathers/scatters by slot index ON-DEVICE: per-session
  ``(h, c)`` LSTM carry plus the scan-accumulated mean-pool state
  (models/icalstm.py ICALstmStream). Row ``slots`` is the TRASH row: padded
  request slots in a partially-filled batch point there, so their (identity)
  scatter writes can never land on a live session.

Generations mirror the membership pattern: every (re)assignment of a slot
bumps its generation, and a fresh assignment zeroes the carry INSIDE the
compiled step (the ``fresh`` gate) — a session resumed after eviction can
never resurrect another session's (or its own stale) recurrent state. The
generation in the result metadata is the client's signal that the server
restarted its stream.
"""

from __future__ import annotations

import numpy as np


class SessionError(ValueError):
    """An invalid session operation (unknown close, zero capacity)."""


class SessionTable:
    """Host-side session id → carry-table slot map with LRU eviction."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise SessionError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.slots: list = [None] * capacity  # session id | None
        self.generations = [0] * capacity  # current occupant's generation
        self._known: dict = {}  # session id -> last generation (join history)
        self._last_used = [0] * capacity  # LRU tick per slot
        self._tick = 0
        self.evictions = 0

    @property
    def trash_slot(self) -> int:
        """The carry-table row padded request slots scatter into — one past
        the last real slot (:func:`init_carry_table` allocates it)."""
        return self.capacity

    @property
    def occupied(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def slot_of(self, session_id: str):
        try:
            return self.slots.index(session_id)
        except ValueError:
            return None

    def resolve(self, session_id: str) -> tuple:
        """``(slot, generation, fresh)`` for a session, assigning (and, at
        capacity, LRU-evicting) as needed. ``fresh=True`` means the carry row
        must be zeroed before use — the streaming executable's reset gate;
        an evicted-then-returning session comes back fresh at a bumped
        generation (its O(1) state was the thing evicted)."""
        if not session_id or not isinstance(session_id, str):
            raise SessionError("session id must be a non-empty string")
        self._tick += 1
        slot = self.slot_of(session_id)
        if slot is not None:
            self._last_used[slot] = self._tick
            return slot, self.generations[slot], False
        try:
            slot = self.slots.index(None)
        except ValueError:
            # LRU eviction: the least recently touched session loses its slot
            slot = min(range(self.capacity), key=lambda i: self._last_used[i])
            self.evictions += 1
        # per-SESSION generation (the membership pattern): a rejoin — after
        # close or eviction — comes back at last + 1, the auditable record
        # that its O(1) carry restarted from zero
        gen = self._known.get(session_id, 0) + 1
        self._known[session_id] = gen
        self.slots[slot] = session_id
        self.generations[slot] = gen
        self._last_used[slot] = self._tick
        return slot, gen, True

    def close(self, session_id: str) -> int:
        """Release a session's slot (its next resolve starts fresh)."""
        slot = self.slot_of(session_id)
        if slot is None:
            raise SessionError(f"unknown session {session_id!r}")
        self.slots[slot] = None
        return slot


def init_carry_table(capacity: int, hidden: int, dtype=np.float32) -> dict:
    """Fresh device-shaped ``[capacity + 1, …]`` carry pytree (as numpy — the
    engine device_puts it once at warmup): LSTM ``h``/``c``, the
    scan-accumulated pooled hidden sum, and the valid-timestep ``count``.
    The extra row is the trash slot (:attr:`SessionTable.trash_slot`)."""
    rows = capacity + 1
    return {
        "h": np.zeros((rows, hidden), dtype),
        "c": np.zeros((rows, hidden), dtype),
        "pooled": np.zeros((rows, hidden), dtype),
        "count": np.zeros((rows,), dtype),
    }
