"""Model tests, incl. numerical parity against the reference torch modules.

The reference model files import only torch, so we load them straight from
/root/reference via importlib (read-only; bypasses the package __init__ which
needs the coinstac_dinunet dependency). We then copy torch weights into our
flax modules and require output parity — the strongest check that the
re-design preserves reference semantics.
"""

import importlib.util
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from dinunet_implementations_tpu.models import ICALstm, LSTMCell, MSANNet


needs_reference = pytest.mark.skipif(
    not os.path.isdir("/root/reference"), reason="reference tree not mounted"
)


def _load_ref(name, path):
    if not os.path.exists(path):
        return None  # guarded: every user is @needs_reference-marked
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


ref_fs = _load_ref("ref_fs_models", "/root/reference/comps/fs/models.py")
ref_ica = _load_ref("ref_ica_models", "/root/reference/comps/icalstm/models.py")


def t2j(t):
    return jnp.asarray(t.detach().numpy())


# ---------------------------------------------------------------------------
# MSANNet
# ---------------------------------------------------------------------------


def _msannet_params_from_torch(tm):
    params = {}
    for i, layer in enumerate(tm.layers):
        lin, bn = layer[0], layer[1]
        params[f"linear_{i}"] = {"kernel": t2j(lin.weight).T}
        params[f"bn_{i}"] = {"scale": t2j(bn.weight), "bias": t2j(bn.bias)}
    params["fc_out"] = {"kernel": t2j(tm.fc_out.weight).T, "bias": t2j(tm.fc_out.bias)}
    return {"params": params}


@needs_reference
def test_msannet_matches_torch():
    torch.manual_seed(0)
    tm = ref_fs.MSANNet(in_size=66, hidden_sizes=[256, 128, 64, 32], out_size=2)
    tm.train()  # track_running_stats=False → batch stats in any mode
    x = torch.randn(16, 66)
    with torch.no_grad():
        ref_out = tm(x).numpy()

    jm = MSANNet(in_size=66, hidden_sizes=(256, 128, 64, 32), out_size=2)
    out = jm.apply(_msannet_params_from_torch(tm), jnp.asarray(x.numpy()), train=True)
    np.testing.assert_allclose(np.asarray(out), ref_out, atol=2e-5)


def test_msannet_mask_equals_subbatch():
    """Masked batch-norm: padded rows must not alter real rows' outputs."""
    jm = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (10, 6))
    params = jm.init(key, x, train=True)
    sub = jm.apply(params, x[:7], train=True)
    padded = jnp.concatenate([x[:7], jnp.zeros((3, 6))])
    mask = jnp.array([1.0] * 7 + [0.0] * 3)
    full = jm.apply(params, padded, train=True, mask=mask)
    np.testing.assert_allclose(np.asarray(full[:7]), np.asarray(sub), atol=1e-5)


def test_msannet_dropout_active_only_in_train():
    jm = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2, dropout_in=(0,))
    key = jax.random.PRNGKey(1)
    x = jax.random.normal(key, (4, 6))
    params = jm.init({"params": key, "dropout": key}, x, train=True)
    e1 = jm.apply(params, x, train=False)
    e2 = jm.apply(params, x, train=False)
    np.testing.assert_allclose(np.asarray(e1), np.asarray(e2))
    t1 = jm.apply(params, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)})
    t2 = jm.apply(params, x, train=True, rngs={"dropout": jax.random.PRNGKey(3)})
    assert not np.allclose(np.asarray(t1), np.asarray(t2))


# ---------------------------------------------------------------------------
# LSTM cell / ICALstm
# ---------------------------------------------------------------------------


def _lstm_cell_params_from_torch(tc):
    return {
        "w_ih": t2j(tc.i2h.weight).T,
        "b_ih": t2j(tc.i2h.bias),
        "w_hh": t2j(tc.h2h.weight).T,
        "b_hh": t2j(tc.h2h.bias),
    }


@pytest.mark.parametrize("T,H,D", [(7, 12, 9)])
@needs_reference
def test_lstm_cell_matches_reference_double_sigmoid(T, H, D):
    """Our double_sigmoid_gates=True reproduces the reference cell bit-for-bit
    (incl. the i/f/o double-sigmoid quirk, comps/icalstm/models.py:31-38)."""
    torch.manual_seed(1)
    tc = ref_ica.LSTMCell(D, H)
    x = torch.randn(3, T, D)
    with torch.no_grad():
        ref_seq, (ref_h, ref_c) = tc(x)

    cell = LSTMCell(H, double_sigmoid_gates=True)
    seq, (h, c) = cell.apply(
        {"params": _lstm_cell_params_from_torch(tc)}, jnp.asarray(x.numpy())
    )
    np.testing.assert_allclose(np.asarray(seq), ref_seq.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h), ref_h.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(c), ref_c.numpy(), atol=1e-5)


def test_lstm_cell_standard_gates_differ():
    """Default (standard) gates intentionally differ from the quirk mode."""
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (2, 5, 6))
    std = LSTMCell(8, double_sigmoid_gates=False)
    params = std.init(key, x)
    quirk = LSTMCell(8, double_sigmoid_gates=True)
    s, _ = std.apply(params, x)
    q, _ = quirk.apply(params, x)
    assert not np.allclose(np.asarray(s), np.asarray(q))


def _icalstm_params_from_torch(tm):
    enc = tm.encoder[0]
    p = {
        "encoder": {"kernel": t2j(enc.weight).T, "bias": t2j(enc.bias)},
        "lstm": {
            "fwd": _lstm_cell_params_from_torch(tm.lstm.lstms[0]),
            "rev": _lstm_cell_params_from_torch(tm.lstm.lstms[1]),
        },
        "cls_fc1": {"kernel": t2j(tm.classifier[1].weight).T, "bias": t2j(tm.classifier[1].bias)},
        "cls_bn": {"scale": t2j(tm.classifier[2].weight), "bias": t2j(tm.classifier[2].bias)},
        "cls_fc2": {"kernel": t2j(tm.classifier[4].weight).T, "bias": t2j(tm.classifier[4].bias)},
        "cls_fc3": {"kernel": t2j(tm.classifier[6].weight).T, "bias": t2j(tm.classifier[6].bias)},
    }
    stats = {
        "cls_bn": {
            "mean": t2j(tm.classifier[2].running_mean),
            "var": t2j(tm.classifier[2].running_var),
        }
    }
    return {"params": p, "batch_stats": stats}


@needs_reference
def test_icalstm_matches_torch_eval():
    """Full-model eval parity (dropout off, BN running stats) with the
    double-sigmoid quirk enabled."""
    torch.manual_seed(2)
    tm = ref_ica.ICALstm(
        input_size=32, hidden_size=24, bidirectional=True, num_cls=2,
        num_comps=5, window_size=4,
    )
    tm.eval()
    x = torch.randn(6, 8, 5, 4)  # [B, S, C, W]
    with torch.no_grad():
        ref_out, _ = tm(x)

    jm = ICALstm(
        input_size=32, hidden_size=24, bidirectional=True, num_cls=2,
        num_comps=5, window_size=4, double_sigmoid_gates=True,
    )
    out = jm.apply(_icalstm_params_from_torch(tm), jnp.asarray(x.numpy()), train=False)
    np.testing.assert_allclose(np.asarray(out), ref_out.numpy(), atol=2e-5)


def test_icalstm_default_shapes_jit():
    """Default config (inputspec: 100 comps, window 10, hidden 348) compiles
    under jit with static shapes."""
    jm = ICALstm(input_size=64, hidden_size=48, num_comps=10, window_size=5)
    key = jax.random.PRNGKey(0)
    x = jnp.ones((4, 6, 10, 5))
    variables = jm.init({"params": key, "dropout": key}, x, train=True)
    fwd = jax.jit(lambda v, xx: jm.apply(v, xx, train=False))
    out = fwd(variables, x)
    assert out.shape == (4, 2)


def test_torch_linear_init_parity():
    """ADVICE regression: TorchLinearInit.kernel must match torch's
    kaiming_uniform_(a=sqrt(5)) bound of 1/sqrt(fan_in) — not sqrt(3/fan_in)."""
    from dinunet_implementations_tpu.models.layers import TorchLinearInit

    fan_in = 64
    k = TorchLinearInit.kernel(jax.random.PRNGKey(0), (fan_in, 4096))
    bound = 1.0 / np.sqrt(fan_in)
    kmax = float(np.abs(np.asarray(k)).max())
    assert kmax <= bound + 1e-7
    assert kmax > 0.98 * bound  # uniform should nearly reach the bound
    # cross-check against torch's actual nn.Linear init
    torch.manual_seed(0)
    tl = torch.nn.Linear(fan_in, 4096)
    tmax = float(tl.weight.detach().abs().max())
    assert abs(kmax - tmax) < 0.05 * bound
    # bias bound is also 1/sqrt(fan_in)
    b = TorchLinearInit.bias_for(fan_in)(jax.random.PRNGKey(1), (4096,))
    assert float(np.abs(np.asarray(b)).max()) <= bound + 1e-7
