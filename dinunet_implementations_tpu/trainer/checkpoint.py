"""Checkpoint / resume.

The reference's persistence is implicit: cross-round module-level ``CACHE``
dicts plus library-side best-model files implied by ``best_val_epoch``
(SURVEY.md §5 checkpoint/resume). Here it is explicit and complete: params +
batch_stats + optimizer state + engine state + RNG + round counter, serialized
with flax msgpack. ``save_best``/warm-start covers the reference's
``pretrain`` largest-site warm start (``compspec.json:120-127``).
"""

from __future__ import annotations

import json
import os
from typing import Any

import flax.serialization
import jax
import jax.numpy as jnp

from .steps import TrainState


def save_checkpoint(path: str, state: TrainState, meta: dict | None = None) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "engine_state": state.engine_state,
        "rng": state.rng,
        "round": state.round,
    }
    with open(path, "wb") as fh:
        fh.write(flax.serialization.to_bytes(payload))
    if meta is not None:
        with open(path + ".meta.json", "w") as fh:
            json.dump(meta, fh, indent=2)
    return path


def load_checkpoint(path: str, like: TrainState) -> TrainState:
    """Restore into the structure of ``like`` (shapes/treedef must match)."""
    template = {
        "params": like.params,
        "batch_stats": like.batch_stats,
        "opt_state": like.opt_state,
        "engine_state": like.engine_state,
        "rng": like.rng,
        "round": like.round,
    }
    with open(path, "rb") as fh:
        restored = flax.serialization.from_bytes(template, fh.read())
    return TrainState(
        params=restored["params"],
        batch_stats=restored["batch_stats"],
        opt_state=restored["opt_state"],
        engine_state=restored["engine_state"],
        rng=jnp.asarray(restored["rng"]),
        round=jnp.asarray(restored["round"]),
    )


def load_params(path: str, like_params: Any):
    """Warm-start: load only params from a checkpoint (pretrain semantics)."""
    with open(path, "rb") as fh:
        raw = flax.serialization.msgpack_restore(fh.read())
    return flax.serialization.from_state_dict(like_params, raw["params"])


def checkpoint_meta(path: str) -> dict:
    mpath = path + ".meta.json"
    if os.path.exists(mpath):
        with open(mpath) as fh:
            return json.load(fh)
    return {}
