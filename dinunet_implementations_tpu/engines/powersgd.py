"""powerSGD — low-rank gradient compression with error feedback.

Reference capability (``comps/__init__.py:16``; measured as the best-AUC
engine in ``nnlogs.ipynb`` cell 2). Classic powerSGD (Vogels et al., 2019)
round, expressed as XLA collectives over the ``site`` axis:

    M_s = G_s + e_s                (error feedback)
    P   = orth( Σ_s w_s · M_s Q )  (weighted psum, then QR)
    Q'  = Σ_s w_s · M_sᵀ P         (weighted psum)
    Ĝ   = P Q'ᵀ                    (identical on every site)
    e_s = M_s − Ĝ                  (local residual carried to next round)

State per compressible leaf: the right factor ``Q`` (warm-started across
rounds — key to powerSGD's convergence) and the residual ``e``. 1-D leaves
aggregate densely. Rank comes from ``dad_reduction_rank`` (the reference GUI
exposes one rank knob for both compressed engines, ``compspec.json:236-238``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..parallel.collectives import (
    ROBUST_AGGS,
    PackedAxis,
    clip_site_gradients,
    payload_dtype,
    resolve_dcn_codec,
    resolve_wire_codec,
    robust_site_reduce,
    site_all_gather,
    site_weight_scale,
    two_level_psum,
    weighted_site_sum,
    wire_compress,
)
from .base import (
    Engine,
    mask_dead_site,
    register_engine,
    robust_gather_dcn_wire,
    robust_gather_wire,
    wire_shapes_bytes,
)
from .lowrank import (
    from_matrix,
    is_compressible,
    lowrank_rank_groups,
    lowrank_wire_bytes,
    lp_matmul,
    orthonormalize,
    to_matrix,
)


@register_engine("powerSGD")
def make_powersgd(
    dad_reduction_rank: int = 10,
    precision_bits="32",
    seed: int = 0,
    wire_quant="none",
    wire_stochastic=False,
    robust_agg="none",
    robust_trim_frac=0.2,
    robust_clip_mult=2.5,
    dcn_wire_quant="",
    secure_agg="off",
    **_unused,
) -> Engine:
    # secure-aggregation masked wires (r20) are a dense-psum construct:
    # this engine ships low-rank factor GATHERS — per-site payloads in the
    # clear by design — so the mode is refused, not silently ignored
    # (privacy/secure_agg.py; dSGD is the masked-wire engine)
    from ..privacy.secure_agg import secure_agg_enabled

    if secure_agg_enabled(secure_agg):
        raise ValueError(
            f"secure_agg={secure_agg!r} is only supported by the dSGD "
            "engine: the low-rank engines gather per-site factors, which "
            "a masked psum wire cannot carry"
        )
    if robust_agg not in ROBUST_AGGS:
        raise ValueError(
            f"robust_agg must be one of {ROBUST_AGGS}, got {robust_agg!r}"
        )
    # robust gather modes (r17): the two factor exchanges switch from psum
    # to per-site gather + robust reduce — P comes from a trimmed/median of
    # the sites' M·q sketches instead of their weighted sum, so a byzantine
    # site cannot steer the shared subspace, and its influence on Q' is
    # capped the same way. The wire genuinely grows ×pack (per-site factors
    # must reach every device); norm_clip keeps the psum wire.
    gather_mode = robust_agg in ("trimmed_mean", "coordinate_median")
    pdtype = payload_dtype(precision_bits)
    # same mixed-precision playbook as rankDAD (engines/rankdad.py): a bf16
    # wire also runs the big M@q / MᵀP products as bf16×bf16→f32 MXU
    # contractions; orthonormalization stays f32. "16-ieee"/"32" keep f32.
    mm_dtype = jnp.bfloat16 if pdtype == jnp.bfloat16 else None
    # quantized wire (r14): the two factor psums ride the codec grid —
    # quantization noise on P/Q' lands in the error-feedback residual e and
    # is flushed over subsequent rounds, exactly the mechanism powerSGD's
    # own low-rank truncation already relies on. "none" keeps the legacy
    # precision_bits wire byte-for-byte (S005-gated).
    codec = resolve_wire_codec(precision_bits, wire_quant, wire_stochastic)
    import numpy as np

    wdtype = np.dtype(codec.dtype)
    # the inter-slice codec (r18): each factor's per-slice partial (and the
    # dense 1-D partials) re-quantize before their slice-only psum; the two
    # factor hops cannot fuse — q' depends on the globally-orthonormalized
    # P, so each factor's DCN reduce is its own collective by data
    # dependency. None = the fused form.
    dcn = resolve_dcn_codec(
        precision_bits, wire_quant, dcn_wire_quant, wire_stochastic
    )
    ddtype = np.dtype(dcn.dtype) if dcn is not None else None

    def _compress(x):
        if codec.quant == "none":
            return wire_compress(x, pdtype)  # the exact legacy program
        return codec.compress(x)

    def _compress_rows(x):
        # per-virtual-site payload compression on a [K, ...]-leading block
        # (the robust gather mode's pre-gather quantization: scale per row)
        if codec.quant == "none":
            return wire_compress(x, pdtype)
        return codec.compress(x, batched=True)

    # what two_level_psum quantizes the packed partial with (the legacy arm
    # must stay lowering-identical, so it keeps the plain-dtype spelling)
    wire_arg = codec if codec.quant != "none" else pdtype

    def init(grads):
        leaves, treedef = jax.tree.flatten(grads)
        qs, es = [], []
        for i, g in enumerate(leaves):
            if is_compressible(g):
                m, n = to_matrix(g).shape
                r = min(dad_reduction_rank, m, n)
                key = jax.random.fold_in(jax.random.PRNGKey(seed), i)
                # Q must start identical on every site: keyed by leaf index only.
                qs.append(jax.random.normal(key, (n, r), jnp.float32))
                es.append(jnp.zeros((m, n), jnp.float32))
            else:
                qs.append(None)
                es.append(None)
        return {
            "q": jax.tree.unflatten(treedef, qs),
            "e": jax.tree.unflatten(treedef, es),
        }

    def wire_bytes(grads, pack: int = 1) -> int:
        # two psum'd factors per compressible leaf — P [m,r] and Q' [n,r] —
        # wire-compressed to the payload dtype; shared low-rank payload
        # model (engines/lowrank.py lowrank_wire_bytes). Pack-INVARIANT:
        # both factor psums and the dense 1-D psums reduce over the packed
        # virtual-site axis in-register before the wire (two_level_psum), so
        # the device ships one partial per factor regardless of K.
        import math

        extras = sum(
            math.prod(s) * d.itemsize
            for s, d in robust_gather_wire(pack, robust_agg)
        )
        if gather_mode:
            # gathered factor exchange: both the factor and dense halves
            # ship every virtual site's payload (×pack)
            return lowrank_wire_bytes(
                grads, dad_reduction_rank, wdtype.itemsize, pack=pack,
                dense_pack=pack,
            ) + extras
        return lowrank_wire_bytes(
            grads, dad_reduction_rank, wdtype.itemsize
        ) + extras

    def wire_shapes(grads, pack: int = 1):
        # per compressible leaf TWO psum'd factors — P [m, r] then Q' [n, r],
        # wire-compressed to the payload dtype — plus a dense f32 psum per
        # 1-D leaf. Same shapes at every pack factor (see wire_bytes). Must
        # sum to wire_bytes (verified by S002).
        import numpy as np

        groups, dense = lowrank_rank_groups(grads, dad_reduction_rank)
        pd = wdtype
        shapes = []
        for r, mns in groups:
            for m, n in mns:
                if gather_mode:
                    # robust gather mode (r17): the device's [pack, ...]
                    # per-site factor blocks cross the wire whole
                    shapes.append(((pack, m, r), pd))
                    shapes.append(((pack, n, r), pd))
                else:
                    shapes.append(((m, r), pd))
                    shapes.append(((n, r), pd))
        if gather_mode:
            shapes += [
                ((pack,) + tuple(s), np.dtype(np.float32)) for s in dense
            ]
        else:
            shapes += [(s, np.dtype(np.float32)) for s in dense]
        return shapes + robust_gather_wire(pack, robust_agg)

    def dcn_wire_shapes(grads, pack: int = 1, sites_per_slice: int = 1):
        # the inter-slice (DCN) tier, per slice per round: TWO slice hops
        # per compressible leaf — P's per-slice partial, then (after the
        # global orthonormalization) q's — each re-quantized through the
        # DCN codec when one is set; dense 1-D partials per leaf. Gather
        # modes ship the slice's assembled [sites_per_slice, ...] factor /
        # dense blocks instead, plus the weight bookkeeping gather at f32.
        import numpy as np

        groups, dense = lowrank_rank_groups(grads, dad_reduction_rank)
        fdtype = ddtype if ddtype is not None else wdtype
        dense_dtype = (
            ddtype if ddtype is not None else np.dtype(np.float32)
        )
        shapes = []
        for r, mns in groups:
            for m, n in mns:
                if gather_mode:
                    shapes.append(((sites_per_slice, m, r), fdtype))
                    shapes.append(((sites_per_slice, n, r), fdtype))
                else:
                    shapes.append(((m, r), fdtype))
                    shapes.append(((n, r), fdtype))
        if gather_mode:
            shapes += [
                ((sites_per_slice,) + tuple(s), dense_dtype) for s in dense
            ]
        else:
            shapes += [(tuple(s), dense_dtype) for s in dense]
        return shapes + robust_gather_dcn_wire(sites_per_slice, robust_agg)

    def dcn_bytes(grads, pack: int = 1, sites_per_slice: int = 1) -> int:
        return wire_shapes_bytes(dcn_wire_shapes(grads, pack, sites_per_slice))

    def aggregate(grads, state, weight, axis_name, live=None, rnd=None):
        # Dead-site round: G zeroed (NaN-safe where) and weight zeroed, so
        # this site's M = e contributes nothing to the psum'd P/Q' (scale 0)
        # and the global Ĝ is the live sites' weighted mean. The trainer
        # freezes a dead site's q/e across the round (trainer/steps.py), so
        # error feedback resumes where it left off when the site returns.
        # Buffered-async rounds (engines/base.py, r13): G is each slot's
        # last DEPOSITED update, `weight` carries the staleness decay, and a
        # stale-in-bound slot's error feedback keeps compressing its
        # buffered gradient — the decayed scale flows through P/Q' exactly
        # like a fractional liveness weight; no engine-side change.
        grads, weight = mask_dead_site(grads, weight, live)
        if robust_agg == "norm_clip":
            # byzantine defense (r17): clip the incoming gradient's norm to
            # the robust median threshold BEFORE error feedback — the
            # residual e is the site's own honest state and stays unclipped
            grads = clip_site_gradients(
                grads, weight, axis_name, robust_clip_mult
            )
        packed = isinstance(axis_name, PackedAxis)
        w_all = None
        if gather_mode:
            w_all = site_all_gather(
                jnp.asarray(weight, jnp.float32), axis_name
            )
            scale = None  # the robust reduce weighs sites itself
        else:
            scale = site_weight_scale(weight, axis_name)

        # Per leaf, NOT lockstep (unlike rankDAD): powerSGD's error-feedback
        # matrix M is a full fp32 gradient copy, and a cross-leaf
        # orthonormalization barrier would pin every leaf's M live at once —
        # a whole-model fp32 peak-HBM bump (review finding, r3). The
        # orthonormalization itself is custom-call-free (lowrank's unrolled
        # Cholesky), so the per-leaf loop costs no LAPACK launches anyway.
        def agg_leaf(g, q, e):
            if q is None and gather_mode:
                # robust dense path: gather the per-site leaf and reduce
                # robustly per coordinate (wire ×pack, modeled above; the
                # slice hop re-quantizes through the DCN codec, matching
                # the dcn_wire_shapes model — rankDAD's dense path ditto)
                return (
                    robust_site_reduce(
                        site_all_gather(
                            g.astype(jnp.float32), axis_name, dcn_wire=dcn
                        ),
                        w_all, robust_agg, robust_trim_frac,
                    ).astype(g.dtype),
                    None,
                    None,
                )
            if q is None:
                if packed:
                    # dense 1-D leaf: two-level weighted psum (K-invariant;
                    # three-level with the DCN codec on sliced axes)
                    return (
                        weighted_site_sum(
                            g, scale, axis_name, dcn_wire=dcn
                        ).astype(g.dtype),
                        None,
                        None,
                    )
                return (
                    jax.lax.psum(g.astype(jnp.float32) * scale, axis_name).astype(g.dtype),
                    None,
                    None,
                )
            if gather_mode and packed:
                # robust gather round (r17): every site's M·q sketch is
                # gathered and the shared subspace P comes from a robust
                # per-coordinate reduce of the sketches — a hostile site
                # contributes one trimmed/median vote, never a weighted-sum
                # steer; Q' is reduced the same way. Quantization rides the
                # per-site payload before the gather (batched rows), so the
                # codec grid is what crosses the wire.
                M = jax.vmap(to_matrix)(g).astype(jnp.float32) + e
                Pg = site_all_gather(
                    _compress_rows(lp_matmul(M, q, mm_dtype)), axis_name,
                    dcn_wire=dcn,
                )  # [S, m, r]
                P = orthonormalize(robust_site_reduce(
                    Pg.astype(jnp.float32), w_all, robust_agg,
                    robust_trim_frac,
                ))
                Qg = site_all_gather(
                    _compress_rows(
                        lp_matmul(jnp.swapaxes(M, 1, 2), P, mm_dtype)
                    ),
                    axis_name,
                    dcn_wire=dcn,
                )  # [S, n, r]
                q_new = robust_site_reduce(
                    Qg.astype(jnp.float32), w_all, robust_agg,
                    robust_trim_frac,
                )
                G_hat = P @ q_new.T
                e_new = M - G_hat[None]
                like = jax.ShapeDtypeStruct(g.shape[1:], g.dtype)
                return (
                    from_matrix(G_hat, like),
                    jnp.broadcast_to(q_new, q.shape),
                    e_new,
                )
            if gather_mode:
                # robust gather round, one site per member (the vmap fold):
                # same semantics, unbatched local halves
                M = to_matrix(g).astype(jnp.float32) + e
                Pg = site_all_gather(
                    _compress(lp_matmul(M, q, mm_dtype)), axis_name
                )  # [S, m, r]
                P = orthonormalize(robust_site_reduce(
                    Pg.astype(jnp.float32), w_all, robust_agg,
                    robust_trim_frac,
                ))
                Qg = site_all_gather(
                    _compress(lp_matmul(M.T, P, mm_dtype)), axis_name
                )  # [S, n, r]
                q_new = robust_site_reduce(
                    Qg.astype(jnp.float32), w_all, robust_agg,
                    robust_trim_frac,
                )
                G_hat = P @ q_new.T
                e_new = M - G_hat
                return from_matrix(G_hat, g), q_new, e_new
            if packed:
                # g [K, …], q [K, n, r], e [K, m, n] — the local halves are
                # batched MXU contractions over the device's K virtual
                # sites; each factor reduces over the pack axis in-register,
                # the PARTIAL is wire-compressed, and ONE psum per factor
                # crosses the mesh (two_level_psum) — per-device wire bytes
                # identical to the unpacked engine's.
                sc = scale[:, None, None]
                M = jax.vmap(to_matrix)(g).astype(jnp.float32) + e
                P = two_level_psum(
                    lp_matmul(M, q, mm_dtype) * sc, axis_name, wire_arg,
                    dcn_wire=dcn,
                )
                P = orthonormalize(P)
                q_new = two_level_psum(
                    lp_matmul(jnp.swapaxes(M, 1, 2), P, mm_dtype) * sc,
                    axis_name, wire_arg, dcn_wire=dcn,
                )
                G_hat = P @ q_new.T  # the global aggregate, replicated
                e_new = M - G_hat[None]
                like = jax.ShapeDtypeStruct(g.shape[1:], g.dtype)
                # every site stores the identical psum'd q' (exactly the
                # unpacked semantics, where each member's q_new IS the psum)
                return (
                    from_matrix(G_hat, like),
                    jnp.broadcast_to(q_new, q.shape),
                    e_new,
                )
            M = to_matrix(g).astype(jnp.float32) + e
            # wire-compress to the payload/codec grid, then accumulate in
            # fp32 (policy in parallel/collectives.py: psum never runs in a
            # narrow dtype)
            P = jax.lax.psum(
                _compress(lp_matmul(M, q, mm_dtype) * scale),
                axis_name,
            )
            P = orthonormalize(P)
            q_new = jax.lax.psum(
                _compress(lp_matmul(M.T, P, mm_dtype) * scale),
                axis_name,
            )
            G_hat = P @ q_new.T
            e_new = M - G_hat
            return from_matrix(G_hat, g), q_new, e_new

        flat_g, treedef = jax.tree.flatten(grads)
        flat_q = treedef.flatten_up_to(state["q"])
        flat_e = treedef.flatten_up_to(state["e"])
        outs = [agg_leaf(g, q, e) for g, q, e in zip(flat_g, flat_q, flat_e)]
        agg = jax.tree.unflatten(treedef, [o[0] for o in outs])
        new_state = {
            "q": jax.tree.unflatten(treedef, [o[1] for o in outs]),
            "e": jax.tree.unflatten(treedef, [o[2] for o in outs]),
        }
        return agg, new_state

    return Engine("powerSGD", init, aggregate, wire_bytes=wire_bytes,
                  wire_shapes=wire_shapes, wire_dtype=wdtype,
                  dcn_bytes=dcn_bytes, dcn_wire_shapes=dcn_wire_shapes,
                  dcn_dtype=ddtype)
