"""Mesh + collectives tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from dinunet_implementations_tpu.core.jaxcompat import shard_map

from dinunet_implementations_tpu.parallel import (
    SITE_AXIS,
    host_mesh,
    make_site_mesh,
    payload_cast,
    payload_uncast,
    site_mean,
    site_sum,
    site_weighted_mean,
)


def test_device_count():
    assert len(jax.devices()) == 8


def test_make_site_mesh_shapes():
    mesh = host_mesh(8)
    assert mesh.shape[SITE_AXIS] == 8
    mesh2 = make_site_mesh(4, model_axis_size=2)
    assert mesh2.shape[SITE_AXIS] == 4
    assert mesh2.shape["model"] == 2
    with pytest.raises(ValueError):
        make_site_mesh(16)


def _run_sharded(mesh, fn, x, in_spec=P(SITE_AXIS), out_spec=P(SITE_AXIS)):
    return shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec)(x)


def test_site_sum_and_mean():
    mesh = host_mesh(8)
    x = jnp.arange(8.0).reshape(8, 1)
    out = _run_sharded(mesh, lambda v: site_sum({"g": v})["g"], x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))
    out = _run_sharded(mesh, lambda v: site_mean({"g": v})["g"], x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.5))


def test_site_weighted_mean_matches_pooled():
    """Weighted site mean == pooled mean over all examples (dSGD invariant)."""
    mesh = host_mesh(4)
    rng = np.random.default_rng(0)
    # 4 sites with heterogeneous example counts (like FS fixture 73-120 subjects)
    counts = np.array([3.0, 5.0, 2.0, 7.0])
    grads = rng.normal(size=(4, 6)).astype(np.float32)  # per-site mean gradient
    pooled = (grads * counts[:, None]).sum(0) / counts.sum()

    def fn(g, w):
        return site_weighted_mean({"g": g}, w[0])["g"]

    out = shard_map(fn, mesh=mesh, in_specs=(P(SITE_AXIS), P(SITE_AXIS)), out_specs=P(SITE_AXIS))(
        jnp.asarray(grads), jnp.asarray(counts)
    )
    for i in range(4):
        np.testing.assert_allclose(np.asarray(out)[i], pooled, rtol=1e-5)


def test_payload_cast_roundtrip():
    tree = {"w": jnp.ones((4, 4), jnp.float32)}
    cast = payload_cast(tree, "16")
    assert cast["w"].dtype == jnp.bfloat16
    back = payload_uncast(cast, tree)
    assert back["w"].dtype == jnp.float32
    same = payload_cast(tree, "32")
    assert same["w"].dtype == jnp.float32
    # compat mode: the reference's literal IEEE fp16 payload
    # (compspec.json:161-176) — "16" is bf16 on TPU, "16-ieee" opts into fp16
    ieee = payload_cast(tree, "16-ieee")
    assert ieee["w"].dtype == jnp.float16


def test_weighted_mean_accumulates_fp32():
    """Review finding: bf16 payloads must still reduce in fp32."""
    mesh = host_mesh(4)
    g = jnp.array([300.0, 0.5, 0.5, 0.5], jnp.bfloat16).reshape(4, 1)
    w = jnp.ones((4,))
    out = shard_map(
        lambda gv, wv: site_weighted_mean({"g": gv}, wv[0])["g"],
        mesh=mesh, in_specs=(P(SITE_AXIS), P(SITE_AXIS)), out_specs=P(SITE_AXIS),
    )(g, w)
    assert out.dtype == jnp.bfloat16
    # true mean 75.375; bf16(75.375)=75.5 but naive bf16 accumulation drifts to 75.0
    np.testing.assert_allclose(np.asarray(out, np.float32), 75.5)
