"""jaxlint (dinunet_implementations_tpu/checks) — analyzer + sanitizer.

Three layers:
- fixture snippets that trigger and suppress every static rule (R001-R006),
  scanned from a synthetic package tree so path-scoped rules behave exactly
  as they do on the real package;
- baseline round-trip (grandfather → rescan clean → new finding still gates);
- the acceptance gate: the REAL package scans clean against the checked-in
  (empty) baseline, and the runtime sanitizer's compile-counter guard passes
  a healthy fit for two engines and trips on a shape-unstable one.
"""

import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.checks import (
    CompileGuard,
    PACKAGE_ROOT,
    SanitizerViolation,
    apply_baseline,
    jit_cache_size,
    load_baseline,
    run_checks,
    sanitize_flags,
    sanitized_fit,
    save_baseline,
)
from dinunet_implementations_tpu.core.config import TrainConfig
from dinunet_implementations_tpu.data.api import SiteArrays
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.trainer.loop import FederatedTrainer


# ---------------------------------------------------------------------------
# fixture-tree scanning
# ---------------------------------------------------------------------------


def _scan(tmp_path, files: dict):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_checks(str(tmp_path))


def _rules(findings):
    return sorted(f.rule for f in findings)


def test_r000_syntax_error_gates(tmp_path):
    fs = _scan(tmp_path, {"trainer/broken.py": "def f(:\n"})
    assert _rules(fs) == ["R000"]


def test_r001_print_flagged_and_allowlisted(tmp_path):
    fs = _scan(tmp_path, {
        "trainer/hot.py": "def f():\n    print('round done')\n",
        "runner/cli.py": "print('json line')\n",
        "data/demo.py": "print('tree ready')\n",
        "analysis.py": "print('report')\n",
    })
    assert _rules(fs) == ["R001"]
    assert fs[0].path == "trainer/hot.py"
    assert "logs.py" in fs[0].fixit


def test_r002_bare_and_base_exception_anywhere(tmp_path):
    fs = _scan(tmp_path, {
        "data/anyfile.py": """
            try:
                work()
            except:
                pass
            try:
                work()
            except BaseException:
                cleanup()
            try:
                work()
            except (ValueError, BaseException):
                cleanup()
        """,
    })
    assert _rules(fs) == ["R002", "R002", "R002"]


def test_r002_swallowing_broad_handler_scoped(tmp_path):
    swallow = """
        try:
            work()
        except Exception:
            pass
    """
    surfaced = """
        import warnings
        try:
            work()
        except Exception as e:
            warnings.warn(f"failed: {e}")
        try:
            work()
        except Exception:
            raise RuntimeError("wrapped")
    """
    fs = _scan(tmp_path, {
        "trainer/x.py": swallow,  # in scope → flagged
        "robustness/y.py": swallow,  # in scope → flagged
        "data/z.py": swallow,  # data/ is NOT in the swallow scope
        "runner/ok.py": surfaced,  # logs or re-raises → fine
    })
    assert _rules(fs) == ["R002", "R002"]
    assert {f.path for f in fs} == {"trainer/x.py", "robustness/y.py"}


def test_r003_literal_axis_names(tmp_path):
    fs = _scan(tmp_path, {
        "engines/bad.py": """
            import jax
            def agg(g):
                a = jax.lax.psum(g, "site")
                b = jax.lax.all_gather(g, axis_name="model")
                i = jax.lax.axis_index(("site", "site_fold"))
                return a, b, i
        """,
        "engines/good.py": """
            import jax
            from parallel.mesh import SITE_AXIS
            def agg(g, axis_name=SITE_AXIS):
                a = jax.lax.psum(g, axis_name)
                return jax.lax.all_gather(a, SITE_AXIS, axis=0, tiled=True)
        """,
    })
    # psum literal + all_gather kw literal + two tuple members
    assert _rules(fs) == ["R003"] * 4
    assert all(f.path == "engines/bad.py" for f in fs)


def test_r003_covers_scatter_broadcast_and_shard_map_kwargs(tmp_path):
    """The collective table the semantic tier shares: psum_scatter /
    pbroadcast positionals and shard_map/vmap-style axis_names= /
    spmd_axis_name= keywords all count as collectives."""
    fs = _scan(tmp_path, {
        "engines/bad.py": """
            import jax
            def agg(g, f):
                a = jax.lax.psum_scatter(g, "site")
                b = jax.lax.pbroadcast(g, "model", 0)
                m = jax.shard_map(f, axis_names=("site",))
                v = jax.vmap(f, spmd_axis_name="site")
                return a, b, m, v
        """,
    })
    assert _rules(fs) == ["R003"] * 4


def test_r004_cfg_mutation(tmp_path):
    fs = _scan(tmp_path, {
        "trainer/bad.py": """
            class T:
                def fit(self, cfg):
                    self.cfg.batch_size = 4
                    cfg.epochs = 2
                    setattr(self.cfg, "seed", 1)
        """,
        "trainer/good.py": """
            class T:
                def __init__(self, cfg):
                    self.cfg = cfg          # binding the attr is fine
                def fit(self):
                    cfg = self.cfg.replace(batch_size=4)  # new object
                    return cfg
        """,
        "core/config.py": """
            def _init(cfg):
                cfg.batch_size = 16  # construction site — allowed
        """,
    })
    assert _rules(fs) == ["R004"] * 3
    assert all(f.path == "trainer/bad.py" for f in fs)


def test_r005_tracer_escapes_in_traced_scopes(tmp_path):
    fs = _scan(tmp_path, {
        "engines/bad.py": """
            import numpy as np
            def aggregate(g, w):
                n = float(w.sum())
                h = np.asarray(g)
                return g.item(), n, h
        """,
        "models/ok.py": """
            import jax.numpy as jnp
            def forward(x):
                return jnp.asarray(x, jnp.float32)  # traced cast — fine
        """,
        "data/host.py": """
            import numpy as np
            def load(rows):
                return np.asarray([int(r) for r in rows])  # host side — fine
        """,
        "data/jitted.py": """
            import jax
            @jax.jit
            def step(x):
                return float(x)  # jitted even outside the traced modules
        """,
    })
    assert _rules(fs) == ["R005"] * 4
    assert {f.path for f in fs} == {"engines/bad.py", "data/jitted.py"}


def test_r005_module_level_is_host_side(tmp_path):
    fs = _scan(tmp_path, {
        "engines/const.py": "RANK = int(1e3)  # import-time, not traced\n",
    })
    assert fs == []


_STEPS_FIXTURE = """
    class TrainState:
        params: object
        opt_state: object
        rng: object
"""


def _ckpt_fixture(payload_keys, template_keys, pops=()):
    payload = ", ".join(f'"{k}": state.{k}' for k in payload_keys)
    template = ", ".join(f'"{k}": like.{k}' for k in template_keys)
    pop_lines = "\n        ".join(f'raw.pop("{k}", None)' for k in pops) or "pass"
    return f"""
    def save_checkpoint(path, state, meta=None):
        payload = {{{payload}, "meta_json": "{{}}"}}
        return payload

    def load_checkpoint(path, like, raw=None):
        template = {{{template}}}
        {pop_lines}
        return template
    """


def test_r007_telemetry_name_stability(tmp_path):
    """Span/event/counter names must be greppable: literals and UPPER_CASE
    constant references pass; f-strings, lowercase variables and
    runtime-built names are flagged (variable parts belong in attrs)."""
    ok = (
        'SPAN_EPOCH = "epoch"\n'
        "def f(tracer, names, e):\n"
        '    with tracer.span("epoch", epoch=e):\n'
        "        pass\n"
        "    with tracer.span(SPAN_EPOCH):\n"
        "        pass\n"
        "    tracer.event(names.CHECKPOINT)\n"
        '    tracer.counter("queue-depth", e)\n'
    )
    assert _scan(tmp_path / "ok", {"trainer/t.py": ok}) == []
    bad = (
        "def f(tracer, e, name):\n"
        "    with tracer.span(f\"epoch-{e}\"):\n"
        "        pass\n"
        "    tracer.event(name)\n"
        '    tracer.counter("x" + str(e), 1)\n'
    )
    fs = _scan(tmp_path / "bad", {"trainer/t.py": bad})
    assert _rules(fs) == ["R007"] * 3
    # the name= keyword form is checked like positional
    kw = "def f(tr, n):\n    tr.event(name=n)\n"
    assert _rules(_scan(tmp_path / "kw", {"trainer/k.py": kw})) == ["R007"]


def test_r006_schema_consistent(tmp_path):
    fs = _scan(tmp_path, {
        "trainer/steps.py": _STEPS_FIXTURE,
        "trainer/checkpoint.py": _ckpt_fixture(
            ["params", "opt_state", "rng"], ["params", "opt_state", "rng"]
        ),
    })
    assert fs == []


def test_r006_schema_drift(tmp_path):
    fs = _scan(tmp_path, {
        "trainer/steps.py": _STEPS_FIXTURE,
        # rng missing from the payload AND load side; stale 'legacy' key
        "trainer/checkpoint.py": _ckpt_fixture(
            ["params", "opt_state", "legacy"], ["params", "opt_state"]
        ),
    })
    msgs = " | ".join(f.message for f in fs)
    assert _rules(fs) == ["R006"] * 3
    assert "'rng' is not serialized" in msgs
    assert "'rng' is not restored" in msgs
    assert "'legacy'" in msgs


def test_r006_real_schema_matches():
    """The real TrainState/checkpoint pair stays in sync (incl. health)."""
    findings = [f for f in run_checks(PACKAGE_ROOT) if f.rule == "R006"]
    assert findings == []


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------

_TRIGGERS = {
    "R001": ("trainer/a.py", "print('x')", "print('x')  # jaxlint: disable=R001"),
    "R002": ("trainer/b.py",
             "try:\n    f()\nexcept:\n    pass",
             "try:\n    f()\nexcept:  # jaxlint: disable=R002\n    pass"),
    "R003": ("engines/c.py",
             "import jax\ndef f(g):\n    return jax.lax.psum(g, 'site')",
             "import jax\ndef f(g):\n    return jax.lax.psum(g, 'site')"
             "  # jaxlint: disable=R003"),
    "R004": ("trainer/d.py",
             "def f(cfg):\n    cfg.epochs = 1",
             "def f(cfg):\n    # jaxlint: disable=R004\n    cfg.epochs = 1"),
    "R005": ("engines/e.py",
             "def f(x):\n    return int(x)",
             "def f(x):\n    return int(x)  # jaxlint: disable=R005"),
    "R007": ("telemetry/f.py",
             "def f(tr, i):\n    with tr.span(f'epoch-{i}'):\n        pass",
             "def f(tr, i):\n    with tr.span(f'epoch-{i}'):"
             "  # jaxlint: disable=R007\n        pass"),
}


@pytest.mark.parametrize("rule", sorted(_TRIGGERS))
def test_inline_suppression_per_rule(tmp_path, rule):
    rel, trigger, suppressed = _TRIGGERS[rule]
    assert _rules(_scan(tmp_path / "t", {rel: trigger})) == [rule]
    assert _scan(tmp_path / "s", {rel: suppressed}) == []


def test_inline_suppression_r006(tmp_path):
    files = {
        "trainer/steps.py": _STEPS_FIXTURE,
        "trainer/checkpoint.py": _ckpt_fixture(
            ["params", "opt_state"], ["params", "opt_state"]
        ),
    }
    assert _rules(_scan(tmp_path / "t", files)) == ["R006"] * 2
    files["trainer/checkpoint.py"] = files["trainer/checkpoint.py"].replace(
        "def save_checkpoint", "# jaxlint: disable=R006\n    def save_checkpoint"
    ).replace(
        "def load_checkpoint", "# jaxlint: disable=R006\n    def load_checkpoint"
    )
    assert _scan(tmp_path / "s", files) == []


def test_suppress_all_keyword(tmp_path):
    fs = _scan(tmp_path, {
        "trainer/a.py": "print('x')  # jaxlint: disable=all\n",
    })
    assert fs == []


def test_baseline_roundtrip(tmp_path):
    files = {"trainer/a.py": "print('one')\nprint('two')\n"}
    findings = _scan(tmp_path / "pkg", files)
    assert _rules(findings) == ["R001", "R001"]
    bl_path = save_baseline(findings, str(tmp_path / "baseline.json"))
    baseline = load_baseline(bl_path)
    assert len(baseline) == 2
    # grandfathered findings no longer gate...
    new, matched = apply_baseline(findings, baseline)
    assert new == [] and matched == 2
    # ...and survive a line shift (keys are snippets, not line numbers)...
    files2 = {"trainer/a.py": "# a new comment shifts lines\n"
                              "print('one')\nprint('two')\n"}
    shifted = _scan(tmp_path / "pkg2", files2)
    new, matched = apply_baseline(shifted, baseline)
    assert new == [] and matched == 2
    # ...but a NEW finding still gates (multiset semantics)
    files3 = {"trainer/a.py": "print('one')\nprint('two')\nprint('three')\n"}
    grown = _scan(tmp_path / "pkg3", files3)
    new, matched = apply_baseline(grown, baseline)
    assert matched == 2 and [f.snippet for f in new] == ["print('three')"]


def test_subpath_scan_keeps_package_relative_scoping():
    """Scanning a file/subdir of the real package must anchor relpaths to the
    package root — otherwise the R001 allowlist misses runner/cli.py (false
    positives) and R002/R005 path scopes silently disarm (false negatives)."""
    import os

    cli = os.path.join(PACKAGE_ROOT, "runner", "cli.py")
    assert [f for f in run_checks(cli) if f.rule == "R001"] == []
    assert run_checks(os.path.join(PACKAGE_ROOT, "trainer")) == []


def test_package_scans_clean_with_empty_baseline():
    """The acceptance gate: the WHOLE package is clean and the checked-in
    baseline is genuinely empty (findings were fixed, not grandfathered)."""
    assert load_baseline() == []
    findings = run_checks(PACKAGE_ROOT)
    assert findings == [], "\n".join(f.format() for f in findings)


# ---------------------------------------------------------------------------
# runtime sanitizer
# ---------------------------------------------------------------------------


def test_sanitize_flags_parsing():
    assert sanitize_flags("") == frozenset()
    assert sanitize_flags("0") == frozenset()
    assert sanitize_flags("1") == {"compile", "leaks", "nans"}
    assert sanitize_flags("compile,nans") == {"compile", "nans"}
    with pytest.raises(ValueError):
        sanitize_flags("compile,bogus")


def _needs_cache_counter(fn):
    if jit_cache_size(fn) is None:
        pytest.skip("this jax build does not expose the jit cache counter")


def test_compile_guard_trips_on_shape_instability():
    f = jax.jit(lambda x: x * 2)
    _needs_cache_counter(f)
    guard = CompileGuard({"f": f}, max_compiles=1, label="toy")
    f(jnp.ones((2,)))
    guard.check()  # one program: fine
    f(jnp.ones((3,)))  # second shape → second program
    with pytest.raises(SanitizerViolation, match="compiled 2 programs"):
        guard.check(context="round=3")


def _toy_sites(ns, n=40, d=6, seed=0):
    out = []
    rng = np.random.default_rng(seed)
    for i in range(ns):
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X.sum(-1) > 0).astype(np.int32)
        out.append(SiteArrays(X, y, np.arange(n, dtype=np.int32)))
    return out


def _toy_trainer(engine):
    cfg = TrainConfig(agg_engine=engine, epochs=3, batch_size=8,
                      validation_epochs=1, monitor_metric="auc")
    return FederatedTrainer(cfg, MSANNet(in_size=6, hidden_sizes=(16,), out_size=2))


@pytest.mark.parametrize("engine", ["dSGD", "powerSGD"])
def test_sanitized_fit_passes_healthy_fit(engine, monkeypatch):
    """Acceptance: a DINUNET_SANITIZE=1 fit passes the compile-counter guard
    (one epoch program per (engine, topology)) for at least two engines."""
    monkeypatch.setenv("DINUNET_SANITIZE", "1")
    tr = _toy_trainer(engine)
    _needs_cache_counter(tr.epoch_fn)
    with sanitized_fit(tr, label=f"{engine}/test") as report:
        res = tr.fit(_toy_sites(2, seed=1), _toy_sites(2, n=24, seed=2),
                     _toy_sites(2, n=24, seed=3), verbose=False)
        report.note_result(res)
    assert jit_cache_size(tr.epoch_fn) == 1
    assert 0 <= res["test_metrics"][0][1] <= 1


def test_sanitized_fit_trips_on_shape_unstable_fit(monkeypatch):
    """A fit whose epoch batch shape drifts compiles a second epoch program
    — the sanitizer must fail it, with the violation naming epoch_fn."""
    monkeypatch.setenv("DINUNET_SANITIZE", "compile")
    tr = _toy_trainer("dSGD")
    _needs_cache_counter(tr.epoch_fn)
    sites = _toy_sites(2, seed=1)
    state = tr.init_state(jnp.ones((8, 6)), num_sites=2)
    with pytest.raises(SanitizerViolation, match="epoch_fn"):
        with sanitized_fit(tr, label="unstable"):
            state, _ = tr.run_epoch(state, sites, epoch=1, batch_size=8)
            state, _ = tr.run_epoch(state, sites, epoch=2, batch_size=4)


def test_sanitized_fit_disabled_is_noop(monkeypatch):
    monkeypatch.delenv("DINUNET_SANITIZE", raising=False)
    tr = _toy_trainer("dSGD")
    with sanitized_fit(tr) as report:
        assert report is None


def test_fed_runner_threads_sanitizer(tmp_path, monkeypatch):
    """The runner surface honors DINUNET_SANITIZE end-to-end (the CLI
    --sanitize flag just sets the same env var)."""
    from dinunet_implementations_tpu.data.demo import make_demo_tree
    from dinunet_implementations_tpu.runner.fed_runner import FedRunner

    root = tmp_path / "demo"
    make_demo_tree(str(root), n_sites=2, subjects=16, seed=0)
    monkeypatch.setenv("DINUNET_SANITIZE", "compile")
    cfg = TrainConfig(agg_engine="dSGD", epochs=2, batch_size=4,
                      split_ratio=(0.7, 0.15, 0.15))
    results = FedRunner(cfg, data_path=str(root),
                        out_dir=str(tmp_path / "out")).run(verbose=False)
    assert len(results) == 1 and "test_metrics" in results[0]
