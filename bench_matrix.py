"""Bench matrix: every BASELINE.json target config, one JSON line each.

Measures the full federated training round (per-site grad → engine
aggregation → Adam) for the five driver-specified configs:

1. FreeSurfer MLP, 2-site dSGD            (reference headline workload)
2. ICA-LSTM, 4-site dSGD
3. ICA-LSTM, 32-site rankDAD              (low-rank compression on ICI)
4. 3D-CNN sMRI, 8-site dSGD               (TPU-build extension)
5. Multimodal FS+ICA transformer, 64-site (TPU-build extension)

All sites fold onto the local chip via the vmapped site axis. Measurement
uses the honest lazy-backend recipe from bench.py: chain N epochs, fully
materialize the final state, report the marginal epoch cost.

Usage: python bench_matrix.py [--epochs N]
"""

import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

from bench import chain_epochs, marginal_distribution, throughput_stats

from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import (
    ICALstm,
    MSANNet,
    MultimodalNet,
    SMRI3DNet,
)
from dinunet_implementations_tpu.trainer import (
    FederatedTask,
    compile_epoch_aot,
    init_train_state,
    make_optimizer,
    make_train_epoch_fn,
)

TIMED_EPOCHS = 16
STEPS = 2

V5E_BF16_PEAK_FLOPS = 197e12


# --- per-config matmul-FLOP models (fwd ≈ listed matmuls; train ≈ 3× fwd
# for fwd+bwd). MFU = samples/sec × FLOPs/sample / v5e bf16 peak; the
# fs-mlp config streams f32, so its mfu reads low against the bf16 peak by
# construction (stated rather than rescaled).


def mlp_flops_per_sample(dims=(66, 256, 128, 64, 32, 2)) -> float:
    return 3.0 * sum(2 * a * b for a, b in zip(dims[:-1], dims[1:]))


def ica_flops_per_sample() -> float:
    from bench import flops_per_sample

    return flops_per_sample()


def smri_flops_per_sample(channels=(16, 32, 64, 128)) -> float:
    """space_to_depth path: 64³×1 → 32³×8, then four stride-2 3³ convs."""
    f, vox, cin = 0.0, 16**3, 8  # conv_0 output grid is 16³
    for c in channels:
        f += 2 * vox * 27 * cin * c
        cin, vox = c, vox // 8
    return 3.0 * f


def multimodal_flops_per_sample(
    T=100, E=256, L=4, mlp_ratio=4, enc_in=1000, n_ica=98, fs_in=66
) -> float:
    """1 CLS + 1 FS token + 98 ICA tokens through 4 pre-LN blocks."""
    per_tok = (2 * 3 * E * E) + (2 * E * E) + (2 * 2 * mlp_ratio * E * E)
    attn_per_tok = 4 * T * E  # logits + weighted sum over T keys
    embed = n_ica * 2 * enc_in * E + 2 * fs_in * E
    return 3.0 * (L * T * (per_tok + attn_per_tok) + embed)


def measure(name, model, x_shape, sites, engine_name, batch, engine_kw=None,
            timed_epochs=TIMED_EPOCHS, flops_sample=None):
    rng = np.random.default_rng(0)
    task = FederatedTask(model)
    engine = make_engine(engine_name, **(engine_kw or {}))
    opt = make_optimizer("adam", 1e-3)
    # inputs pre-cast to the model's compute dtype, as bench.py / the trainer
    x = jnp.asarray(
        rng.normal(size=(sites, STEPS, batch) + x_shape).astype(np.float32),
        dtype=getattr(model, "compute_dtype", None),
    )
    y = jnp.asarray((rng.random((sites, STEPS, batch)) > 0.5).astype(np.int32))
    w = jnp.ones((sites, STEPS, batch), jnp.float32)
    state0 = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=sites
    )
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh=None, local_iterations=1)
    # resident inputs in the executable's preferred layout, as bench.py
    epoch_fn, put_x = compile_epoch_aot(epoch_fn, state0, x, y, w)
    x = put_x(x)

    def run(n):
        return chain_epochs(epoch_fn, state0, x, y, w, n)

    run(1)
    # adaptive: grow N until the marginal compute dominates the ~0.1 s
    # tunnel-round-trip noise floor, else fast configs read as noise
    t1 = min(run(1) for _ in range(2))
    n = max(timed_epochs, 4)
    while True:
        tN = run(n + 1)
        d = tN - t1
        if d > 1.5 or n >= 2048:
            break
        n *= 4
    record = {
        "config": name,
        "engine": engine_name,
        "sites": sites,
        "metric": "samples/sec/chip (full federated round)",
        "unit": "samples/sec/chip",
    }
    if engine_kw:
        record["engine_kw"] = engine_kw
    if d <= 0.2:
        # marginal time is inside the latency jitter even at the epoch cap —
        # refuse to print an inflated number (the failure mode this bench
        # methodology exists to eliminate)
        record.update(value=None, unreliable=True, marginal_seconds=round(d, 4))
    else:
        # final measurement: N paired (half, full) observations at the
        # calibrated chain length → least-contended headline + min/median/
        # spread distribution (bench.py marginal_distribution). The
        # calibration's full chain feeds the HEADLINE's endpoint minimum only
        # (valid for a min estimator; saves one chain) — pairing it with a
        # half chain run minutes later would mix contention windows inside
        # one "paired" observation.
        pairs = [(run(n // 2 + 1), run(n + 1)) for _ in range(3)]
        dist = marginal_distribution(pairs, n, pre_full=tN)
        dt = dist["marginal_seconds_per_epoch"]
        # the reliability gate must judge the estimate actually reported,
        # not the discarded calibration delta
        if dt * (n - n // 2) <= 0.2:
            record.update(
                value=None, unreliable=True,
                marginal_seconds=round(dt * (n - n // 2), 4),
            )
        else:
            stats = throughput_stats(dist, sites * STEPS * batch)
            record["value"] = stats["value"]
            record["samples_per_sec"] = stats
            if flops_sample and record["value"] is not None:
                record["mfu"] = round(
                    record["value"] * flops_sample / V5E_BF16_PEAK_FLOPS, 4
                )
                record["flops_per_sample"] = round(flops_sample)
    print(json.dumps(record), flush=True)
    return record.get("value")


def main():
    epochs = TIMED_EPOCHS
    if "--epochs" in sys.argv:
        epochs = int(sys.argv[sys.argv.index("--epochs") + 1])

    if "--sites" in sys.argv:
        # sites-scaling sweep at the flagship ICA dims (or --small): the
        # packed-mesh arm from bench.py, so the matrix and the headline
        # bench share one measurement path. JSON records sites /
        # sites_per_chip / pack_factor per line.
        from bench import SMALL_DIMS, _ensure_host_devices, measure_sites_scaling

        # jax is imported above but its backend initializes lazily — setting
        # the device-count flags here is still early enough
        _ensure_host_devices(
            int(sys.argv[sys.argv.index("--devices") + 1])
            if "--devices" in sys.argv else 8
        )
        sites_list = [
            int(s) for s in sys.argv[sys.argv.index("--sites") + 1].split(",")
        ]
        packs = None
        if "--pack" in sys.argv:
            raw = sys.argv[sys.argv.index("--pack") + 1]
            if raw != "auto":
                packs = [int(p) for p in raw.split(",")]
                if len(packs) == 1:
                    packs = packs * len(sites_list)
        for rec in measure_sites_scaling(
            sites_list, packs=packs, n=epochs,
            dims=SMALL_DIMS if "--small" in sys.argv else None,
        ):
            print(json.dumps(rec), flush=True)
        return

    dad = dict(dad_reduction_rank=10, dad_num_pow_iters=5, dad_tol=1e-3)

    # 1. FS MLP 2-site dSGD (compspec defaults: 66 → (256,128,64,32) → 2)
    measure("fs-mlp-2site", MSANNet(), (66,), 2, "dSGD", 16,
            timed_epochs=epochs, flops_sample=mlp_flops_per_sample())
    # 2. ICA-LSTM 4-site dSGD (HCP shape)
    ica = ICALstm(input_size=256, hidden_size=348, num_comps=100,
                  window_size=10, num_cls=2, compute_dtype="bfloat16")
    measure("ica-lstm-4site", ica, (98, 100, 10), 4, "dSGD", 16,
            timed_epochs=epochs, flops_sample=ica_flops_per_sample())
    # 3. ICA-LSTM 32-site rankDAD
    measure("ica-lstm-32site-rankdad", ica, (98, 100, 10), 32, "rankDAD", 16,
            engine_kw=dad, timed_epochs=epochs,
            flops_sample=ica_flops_per_sample())
    # 4. 3D-CNN sMRI 8-site dSGD (64³ T1w volumes; space-to-depth folded in
    #    the DATA PIPELINE as the runner does — pre-folded 32³×8 inputs, the
    #    model runs space_to_depth=False with identical params. Measured
    #    2.0–2.6× over the in-model per-step fold (r5,
    #    docs/bench_smri_s2d_ab_r5.jsonl); that fold itself was 3.7–6.9×
    #    over the naive single-channel layout (r3).
    measure("smri-3dcnn-8site",
            SMRI3DNet(num_cls=2, compute_dtype="bfloat16", space_to_depth=False),
            (32, 32, 32, 8), 8, "dSGD", 4, timed_epochs=max(epochs // 2, 2),
            flops_sample=smri_flops_per_sample())
    # 5. Multimodal transformer 64-site dSGD (fs 66 + 98 ICA windows of
    #    1000). bf16 like the other heavy configs: paired A/B measured
    #    1.8× over the f32 stream (docs/bench_mm_bf16_ab_r5.jsonl) —
    #    accuracy tracking pinned by tests/test_extensions.py
    #    (test_multimodal_bf16_tracks_f32).
    mm = MultimodalNet(fs_input_size=66, num_comps=100, window_size=10,
                       compute_dtype="bfloat16")
    measure("multimodal-64site", mm, (66 + 98 * 1000,), 64, "dSGD", 8,
            timed_epochs=max(epochs // 2, 2),
            flops_sample=multimodal_flops_per_sample())


if __name__ == "__main__":
    main()
