#!/usr/bin/env bash
# Regenerate the committed bench artifacts (docs/bench_*.jsonl).
#
# Every "regen on TPU with the same command" note in README.md and
# docs/ARCHITECTURE.md points here: this script IS the list of commands
# that produced the committed lines, one target per artifact, so the
# regen recipe has a single runnable home instead of prose scattered
# across the docs.
#
# Default is the CPU-safe emulated run (JAX_PLATFORMS=cpu, the exact
# flags the committed artifacts were measured with — including --small
# where the committed line used harness-validation dims). `--tpu` drops
# the CPU pin and runs the same sweeps on the attached accelerator;
# numbers land in $OUT_DIR (default: ./bench_regen, NEVER docs/ — diff
# and copy over deliberately, the committed artifacts are review-gated).
#
# Usage:
#   scripts/regen_bench.sh                 # all targets, CPU emulation
#   scripts/regen_bench.sh --tpu           # all targets on the accelerator
#   scripts/regen_bench.sh --only tenants  # one target (name column below)
#   OUT_DIR=/tmp/b scripts/regen_bench.sh --only fleet,serving
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
OUT_DIR="${OUT_DIR:-$REPO/bench_regen}"
ONLY=""
TPU=0
while [ $# -gt 0 ]; do
  case "$1" in
    --tpu) TPU=1 ;;
    --only) ONLY="$2"; shift ;;
    *) echo "unknown arg: $1" >&2; exit 2 ;;
  esac
  shift
done
mkdir -p "$OUT_DIR"

run() { # run <name> <outfile> <bench args...>
  local name="$1" out="$2"; shift 2
  if [ -n "$ONLY" ] && ! [[ ",$ONLY," == *",$name,"* ]]; then return 0; fi
  echo "== $name -> $OUT_DIR/$out" >&2
  if [ "$TPU" = 1 ]; then
    (cd "$REPO" && python bench.py "$@") > "$OUT_DIR/$out"
  else
    (cd "$REPO" && JAX_PLATFORMS=cpu python bench.py "$@") > "$OUT_DIR/$out"
  fi
}

# name       artifact (docs/)                 command (verbatim from the docs)
run sites    bench_sites_scaling_r12.jsonl    --sites 8,32,128,512 --small --sanitize
run slices   bench_slices_scaling_r18.jsonl   --sites 128,512,2048 --slices 1,2,4 --wire-quant int8
run serving  bench_serving_r15.jsonl          --serve
run fleet    bench_fleet_r21.jsonl            --serve --replicas 1,2,4 --swap 4
# r22 composition: the fleet sweep on a sliced pod (replicas pin
# slice-major across 2 bands of 2 devices; rows record the topology)
run fleet-sliced bench_fleet_sliced_r22.jsonl --serve --replicas 1,2 --swap 4 --slices 2 --pack 2
run tenants  bench_tenants_r22.jsonl          --tenants 2
run attacks  bench_attacks_ab_r17.jsonl       --attacks '{"sign_flip": [[3, 0, -1], [11, 0, -1], [19, 0, -1]], "scale": [[27, 0, -1]], "scale_factor": 25}' --robust-agg trimmed_mean
run privacy  bench_privacy_ab_r20.jsonl       --dp-noise 0.5 --dp-clip 1.0 --secure-agg mask
run poweriter bench_poweriter_ab_r14.jsonl    --ab-poweriter --small

echo "done: $(ls "$OUT_DIR" | wc -l) artifact(s) in $OUT_DIR" >&2
