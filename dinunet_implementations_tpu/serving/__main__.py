"""Serving CLI — stand up an InferenceEngine over a trained checkpoint and
drive it with a (scripted or synthetic) mixed batched+streaming workload.

    # train something first
    dinunet-tpu --data-path datasets/demo --epochs 3 --out-dir out
    # then serve its best checkpoint and fire 100 mixed requests
    python -m dinunet_implementations_tpu.serving --data-path datasets/demo \
        --out-dir out --smoke 100 --sanitize compile

Request payloads come from the tree's test split (the real data the trainer
evaluated), so the served numbers are comparable with the trainer's eval
path. ``--script FILE`` replays a JSONL request script instead of the
synthetic smoke mix; each line is one op:

    {"op": "infer", "n": 3, "rows": 2}     # 3 requests of 2 samples each
    {"op": "stream", "session": "s0", "windows": 4}
    {"op": "drain"}                        # barrier: wait for all futures
    {"op": "swap", "checkpoint": "PATH"}   # publish a candidate (CD plane)
    {"op": "rollback_check"}               # one SLO-burn probation verdict
    {"op": "kill_replica", "slot": 0}      # fleet fault drill (--replicas>1)

``--replicas N`` (N > 1) serves through a :class:`~.fleet.ReplicaSet`
instead of a single engine — same script surface, session-sharded routing,
per-replica telemetry series (label ``replica``) plus the fleet rollup.
The ``swap`` / ``rollback_check`` ops drive a
:class:`~.publish.PublishController` against whichever target is live, so
the CI smoke proves zero-compile hot-swaps and SLO-burn rollback on the
exact production wiring.

Telemetry (always on here — a serving run with no latency record is not
evidence): manifest.json + metrics.jsonl (per-dispatch rows + the final
serve_summary row with p50/p95/p99 latency, pad waste, bucket hit-rate) +
trace files under ``<out-dir>/telemetry/serving``, schema-gated by
``telemetry.report --validate`` like every other artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dinunet_implementations_tpu.serving",
        description="AOT-compiled, continuously-batched inference over a "
                    "trained checkpoint (docs/ARCHITECTURE.md Serving r15).",
    )
    p.add_argument("--data-path", required=True,
                   help="dataset tree (simulator layout) — request payloads "
                        "come from its test split")
    p.add_argument("--task", default=None,
                   help="task id (default: TrainConfig/inputspec default)")
    p.add_argument("--checkpoint", default=None,
                   help="checkpoint to serve (default: the fold-0 best "
                        "checkpoint under --out-dir)")
    p.add_argument("--out-dir", default=None,
                   help="output root (default <data-path>/output); serving "
                        "telemetry lands under <out-dir>/telemetry/serving")
    p.add_argument("--script", default=None, metavar="FILE",
                   help="JSONL request script (see module docstring)")
    p.add_argument("--smoke", type=int, default=None, metavar="N",
                   help="synthetic mixed workload: N requests across "
                        "batched + (if supported) streaming lanes")
    p.add_argument("--row-buckets", default="1,2,4,8,16",
                   help="batched-lane shape buckets (row capacities)")
    p.add_argument("--stream-buckets", default="1,4",
                   help="streaming-lane session-count buckets")
    p.add_argument("--stream-chunk", type=int, default=8,
                   help="windows per streaming chunk executable")
    p.add_argument("--stream-slots", type=int, default=32,
                   help="session-slot table capacity (LRU-evicted)")
    p.add_argument("--max-delay-ms", type=float, default=2.0,
                   help="microbatch admission: max wait before a partial "
                        "bucket dispatches")
    p.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="serve through a ReplicaSet of N engine replicas "
                        "(session-sharded affinity, supervised restarts); "
                        "1 = single engine (default)")
    p.add_argument("--max-queue", type=int, default=None, metavar="N",
                   help="admission: shed new requests once a lane's queue "
                        "holds N (default unbounded)")
    p.add_argument("--rollback-burn", type=float, default=1.0,
                   metavar="BURN",
                   help="CD plane: SLO error-budget burn above which a "
                        "rollback_check swaps back (1.0 = the full budget)")
    p.add_argument("--rollback-window", type=int, default=20, metavar="N",
                   help="CD plane: minimum post-swap latency samples before "
                        "a rollback_check returns a verdict")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="persistent XLA compile cache: warm restarts load "
                        "the bucket executables from disk")
    p.add_argument("--statusz-port", type=int, default=None, metavar="PORT",
                   help="serve live observability endpoints on "
                        "127.0.0.1:PORT — /metrics (Prometheus), /healthz, "
                        "/statusz (JSON snapshot incl. SLO burn over the "
                        "request-latency histogram), /tracez. PORT 0 picks "
                        "a free port (printed at startup)")
    p.add_argument("--slo-p99-ms", type=float, default=50.0, metavar="MS",
                   help="p99 latency target for the /statusz SLO "
                        "error-budget burn (default 50 ms)")
    p.add_argument("--linger-s", type=float, default=0.0, metavar="S",
                   help="keep the process (and the --statusz-port "
                        "endpoints) up this many seconds after the request "
                        "script completes — a deterministic scrape window "
                        "for live-observability smoke tests")
    p.add_argument("--sanitize", nargs="?", const="1", default=None,
                   metavar="FLAGS",
                   help="runtime sanitizer flags (checks/sanitize.py); the "
                        "engine's zero-compile guard runs regardless")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="override any TrainConfig / task-args field (must "
                        "match the training run's overrides so the model "
                        "rebuilds identically)")
    p.add_argument("--quiet", action="store_true")
    return p


def default_checkpoint(out_dir: str, task_id: str) -> str:
    """The fold-0 best checkpoint the trainer writes (trainer/logs.py
    fold_dir layout) — without creating directories."""
    return os.path.join(
        out_dir, "remote", "simulatorRun", task_id, "fold_0",
        "checkpoint_best.msgpack",
    )


def smoke_script(n: int, streaming: bool) -> list[dict]:
    """A deterministic mixed workload: ~2/3 batched requests over a cycle of
    row counts (so every bucket gets traffic), ~1/3 streaming chunks over a
    handful of long-lived sessions, drained at the end."""
    ops = []
    rows_cycle = (1, 2, 3, 4, 8)
    for i in range(n):
        if streaming and i % 3 == 2:
            ops.append({
                "op": "stream", "session": f"smoke-{(i // 3) % 4}",
                "windows": 2 + (i % 3),
            })
        else:
            ops.append({"op": "infer", "n": 1,
                        "rows": rows_cycle[i % len(rows_cycle)]})
    ops.append({"op": "drain"})
    return ops


class _Pool:
    """Cycling request-payload pool over the tree's test split."""

    def __init__(self, sites):
        self.inputs = np.concatenate([s.inputs for s in sites if len(s)])
        self._at = 0

    def take(self, n: int) -> np.ndarray:
        ix = [(self._at + i) % len(self.inputs) for i in range(n)]
        self._at = (self._at + n) % len(self.inputs)
        return self.inputs[ix]


def run_script(engine, ops: list[dict], pool: _Pool, verbose: bool,
               publisher=None) -> int:
    """Execute a request script; returns the number of requests fired.
    Futures are collected and resolved at each drain (and at the end), so a
    dispatch error surfaces as a CLI failure, not a lost request.
    ``publisher`` (a :class:`~.publish.PublishController`) enables the
    ``swap`` / ``rollback_check`` CD ops."""
    futures = []
    stream_pos: dict[str, int] = {}
    fired = 0

    def drain():
        engine.drain()
        while futures:
            futures.pop().result()

    for op in ops:
        kind = op.get("op")
        if kind == "infer":
            for _ in range(int(op.get("n", 1))):
                futures.append(engine.submit(
                    pool.take(int(op.get("rows", 1))),
                    trace_id=op.get("trace_id"),
                ))
                fired += 1
        elif kind == "stream":
            sid = str(op.get("session", "s0"))
            t = int(op.get("windows", 1))
            seq = pool.take(1)[0]  # [S, C, W] — one subject's window run
            pos = stream_pos.get(sid, 0)
            chunk = np.take(
                seq, [(pos + j) % seq.shape[0] for j in range(t)], axis=0
            )
            stream_pos[sid] = pos + t
            futures.append(
                engine.stream(sid, chunk, trace_id=op.get("trace_id"))
            )
            fired += 1
        elif kind == "drain":
            drain()
        elif kind == "close_session":
            engine.close_session(str(op["session"]))
        elif kind == "swap":
            if publisher is None:
                raise SystemExit("swap op needs the CD plane (main wires it)")
            from ..trainer.checkpoint import (
                load_inference_state,
                params_digest,
            )

            drain()  # in-flight requests finish on the params they saw
            params, stats, _ = load_inference_state(str(op["checkpoint"]))
            row = publisher.publish(
                params, stats,
                digest=op.get("digest") or params_digest(params, stats),
            )
            if verbose:
                print(json.dumps(row, default=str))
        elif kind == "rollback_check":
            if publisher is None:
                raise SystemExit(
                    "rollback_check op needs the CD plane (main wires it)"
                )
            drain()
            row = publisher.check_rollback()
            if verbose:
                print(json.dumps(row, default=str))
        elif kind == "kill_replica":
            if not hasattr(engine, "kill_replica"):
                raise SystemExit("kill_replica op needs --replicas > 1")
            drain()
            slot = int(op.get("slot", 0))
            want = engine.restarts + 1
            engine.kill_replica(slot)
            if op.get("wait_restart", True):
                import time as _time

                deadline = _time.monotonic() + 60.0
                while _time.monotonic() < deadline:
                    if engine.restarts >= want and engine._replica_alive(slot):
                        break
                    _time.sleep(0.02)
                else:
                    raise SystemExit(
                        f"replica {slot} did not restart within 60s"
                    )
        else:
            raise SystemExit(f"unknown script op {op!r}")
    drain()
    if verbose:
        print(json.dumps({"requests_fired": fired}))
    return fired


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if (args.script is None) == (args.smoke is None):
        raise SystemExit("exactly one of --script or --smoke is required")

    if args.sanitize is not None:
        from ..checks.sanitize import ENV_VAR, sanitize_flags

        try:
            sanitize_flags(args.sanitize)
        except ValueError as e:
            raise SystemExit(f"--sanitize: {e}")
        os.environ[ENV_VAR] = args.sanitize

    from ..core.config import TrainConfig, resolve_site_configs
    from ..runner.cli import _parse_set
    from ..runner.fed_runner import discover_site_dirs, load_site_splits
    from ..telemetry.sink import FitTelemetry, _finite
    from ..telemetry.tracer import SpanTracer

    overrides = _parse_set(args.overrides)
    if args.task is not None:
        overrides["task_id"] = args.task
    if args.compile_cache is not None:
        overrides["compile_cache_dir"] = args.compile_cache
    site_dirs = discover_site_dirs(args.data_path)
    site_cfgs = resolve_site_configs(
        TrainConfig().with_overrides(overrides), args.data_path,
        num_sites=len(site_dirs),
    )
    cfg = site_cfgs[0]
    out_dir = args.out_dir or os.path.join(args.data_path, "output")
    ckpt = args.checkpoint or default_checkpoint(out_dir, cfg.task_id)
    if not (os.path.exists(ckpt) or os.path.exists(ckpt + ".prev")):
        raise SystemExit(
            f"no checkpoint at {ckpt} — train first (dinunet-tpu --data-path "
            f"{args.data_path} --out-dir {out_dir}) or pass --checkpoint"
        )

    # request payloads: the tree's fold-0 test split (what the trainer
    # evaluated — the served numbers are comparable with eval)
    folds = load_site_splits(cfg, site_dirs, site_cfgs)
    pool = _Pool(folds[0]["test"])

    tracer = SpanTracer()
    sink = FitTelemetry.open(
        os.path.join(out_dir, "telemetry", "serving"), cfg, mesh=None,
        fold=0, tracer=tracer,
    )
    from ..checks.sanitize import SanitizerViolation
    from ..telemetry.bus import global_bus
    from ..telemetry.flight import FlightRecorder
    from .engine import InferenceEngine
    from .fleet import ReplicaSet
    from .publish import PublishController

    # live observability plane (r16): process bus + flight recorder (dumps
    # the final spans/bus snapshot on SIGTERM or an unhandled exception),
    # and — with --statusz-port — the /metrics /healthz /statusz /tracez
    # exporter
    bus = global_bus()
    flight = FlightRecorder(out_dir, bus=bus, tracer=tracer)
    flight.install()  # no PreemptionGuard here: own SIGTERM + excepthook

    lane_kwargs = dict(
        row_buckets=[int(b) for b in args.row_buckets.split(",")],
        stream_buckets=[int(b) for b in args.stream_buckets.split(",")],
        stream_chunk=args.stream_chunk, stream_slots=args.stream_slots,
        max_delay_ms=args.max_delay_ms, max_queue=args.max_queue,
        tracer=tracer, sink=sink, bus=bus,
    )
    if args.replicas > 1:
        engine = ReplicaSet(
            cfg, replicas=args.replicas, checkpoint=ckpt, **lane_kwargs
        )
    else:
        engine = InferenceEngine(cfg, checkpoint=ckpt, **lane_kwargs)
    publisher = PublishController(
        engine, bus=bus, sink=sink, p99_target_ms=args.slo_p99_ms,
        rollback_burn=args.rollback_burn,
        min_window_samples=args.rollback_window,
    )
    exporter = None
    if args.statusz_port is not None:
        from ..telemetry.exporter import StatusExporter

        exporter = StatusExporter(
            bus, port=args.statusz_port, tracer=tracer, flight=flight,
            health=engine.health_probes(), statusz=engine.status,
            slo={"histogram": "serving_request_latency_ms",
                 "p99_target_ms": args.slo_p99_ms},
        )
        port = exporter.start()
        if not args.quiet:
            print(json.dumps({
                "statusz": f"http://127.0.0.1:{port}",
                "endpoints": ["/metrics", "/healthz", "/statusz", "/tracez"],
            }))
    try:
        warm = engine.warmup()
        if not args.quiet:
            print(json.dumps({
                "warmup_seconds": engine.warmup_seconds,
                "executables": warm,
                "streaming": engine.streaming,
                "checkpoint": ckpt,
                "replicas": args.replicas,
            }))
        if args.script is not None:
            with open(args.script) as fh:
                ops = [json.loads(ln) for ln in fh if ln.strip()]
        else:
            ops = smoke_script(args.smoke, engine.streaming)
        run_script(engine, ops, pool, verbose=not args.quiet,
                   publisher=publisher)
        if args.linger_s:
            import time

            time.sleep(args.linger_s)
        summary = engine.close()
    except SanitizerViolation as v:
        flight.dump("sanitizer-violation")
        print(json.dumps({"sanitizer_violation": str(v)}), file=sys.stderr)
        return 70
    finally:
        # the crash hooks stay installed on the failure path — an
        # exception unwinding past here still dumps at interpreter exit
        if exporter is not None:
            exporter.stop()
    flight.uninstall()
    print(json.dumps(_finite(summary), default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
