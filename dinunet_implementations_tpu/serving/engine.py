"""InferenceEngine — AOT-compiled, continuously-batched serving of trained
checkpoints.

The first inference surface of the build (ROADMAP item 5): load a trained
checkpoint (params + batch_stats ONLY — optimizer/engine/buffer state
stripped by trainer/checkpoint.py ``load_inference_state``), compile every
program the server will ever run at startup, and answer requests through the
continuous microbatcher. Three invariants the tests and the semantic tier
pin:

- **Compile-free request path.** Warmup ``.lower().compile()``s ONE
  executable per (lane, shape bucket) — against the persistent XLA compile
  cache (PR 4) when ``TrainConfig.compile_cache_dir`` is set, so a restart
  loads machine code from disk instead of recompiling (the cold/warm gap
  ``bench.py --serve`` measures). The request path only ever invokes those
  stored ``Compiled`` executables: a shape outside the bucket set is a loud
  error, never a silent retrace. A :class:`~..checks.sanitize.CompileGuard`
  snapshots the engine's jitted entry points AFTER warmup with
  ``max_compiles=0`` — :meth:`assert_no_compiles` is the zero-compile proof
  the CI smoke and tests gate on.
- **Bit-exactness with the trainer.** The batched lane compiles the SAME
  ``eval_forward`` the trainer's eval path runs (trainer/steps.py) — served
  probabilities on a batch reproduce the trainer's recorded eval outputs
  bit-for-bit (tests/test_serving.py; checks/semantic.py S005 serving cell
  proves the programs lower identically).
- **O(1) streaming.** The ICA-LSTM lane keeps per-session ``(h, c, pooled,
  count)`` carry in a device-resident ``[slots+1, …]`` table
  (serving/session.py); the streaming executable gathers carries by slot
  index, advances only the chunk's NEW windows (models/icalstm.py
  ICALstmStream), and scatters back — per-chunk cost independent of how long
  the session has been running. The table is the executable's DONATED input
  buffer: it updates in place (input/output aliased, proven by the S003
  serving cell), so session state never double-resides in HBM.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..core.config import TrainConfig
from ..telemetry.tracer import NULL_TRACER

#: serving shape buckets: row capacities the batched lane compiles (requests
#: pad into the smallest bucket that fits — a small closed set keeps warmup
#: cheap and the compiled-program set finite)
DEFAULT_ROW_BUCKETS = (1, 2, 4, 8, 16)
#: session capacities per streaming dispatch
DEFAULT_STREAM_BUCKETS = (1, 4)
#: windows per streaming chunk executable (longer runs split; shorter pad
#: with step_valid=0 — exact identities on the carry)
DEFAULT_STREAM_CHUNK = 8


class ServingError(RuntimeError):
    """The serving engine cannot honor a request/configuration."""


class _Req:
    """One queued request (either lane)."""

    __slots__ = ("rows", "weights", "future", "session", "slot", "generation",
                 "fresh", "step_valid", "trace_id", "_submit_t", "_seq",
                 "priority", "deadline_ms")

    def __init__(self, rows, weights=None, session=None, step_valid=None,
                 trace_id=None, priority: int = 0, deadline_ms=None):
        from ..telemetry.tracer import new_trace_id
        from .microbatch import RequestFuture

        self.rows = rows
        self.weights = weights
        self.session = session
        self.step_valid = step_valid
        self.slot = self.generation = 0
        self.fresh = False
        # admission (r21): higher priority collects first; deadline_ms is
        # the submit-relative staleness bound past which the request is
        # SHED instead of dispatched (None = never)
        self.priority = int(priority)
        self.deadline_ms = deadline_ms
        self._seq = 0
        # cross-process trace propagation: a caller-supplied id (a client's
        # request id, a spool event's trace) or a fresh one — it lands in
        # the dispatch row and the serve span, so one request is followable
        # across the telemetry artifacts
        self.trace_id = trace_id or new_trace_id()
        self.future = RequestFuture()
        self.future.trace_id = self.trace_id
        self._submit_t = 0.0


class InferenceEngine:
    """See module docstring. Construct, :meth:`warmup`, then submit; always
    :meth:`close` (or use as a context manager) — it stops the lane threads
    and finalizes the serving telemetry rows."""

    def __init__(self, cfg: TrainConfig, *, checkpoint: str | None = None,
                 params=None, batch_stats=None,
                 row_buckets=DEFAULT_ROW_BUCKETS,
                 stream_buckets=DEFAULT_STREAM_BUCKETS,
                 stream_chunk: int = DEFAULT_STREAM_CHUNK,
                 stream_slots: int = 32,
                 max_delay_ms: float = 2.0,
                 max_queue: int | None = None,
                 streaming: bool | None = None,
                 device=None, bus_labels: dict | None = None,
                 close_sink: bool = True,
                 tracer=None, sink=None, bus=None):
        import jax

        from ..runner.registry import get_task
        from ..trainer.checkpoint import load_inference_state
        from ..trainer.steps import FederatedTask

        from ..telemetry.bus import NULL_BUS

        self.cfg = cfg
        self.tracer = tracer or NULL_TRACER
        self.sink = sink
        self.bus = bus if bus is not None else NULL_BUS
        self.spec = get_task(cfg.task_id)
        if self.spec.serving is None:
            raise ServingError(
                f"task {cfg.task_id!r} has no serving spec "
                "(runner/registry.py ServingSpec)"
            )
        self.meta: dict = {}
        if checkpoint is not None:
            params, batch_stats, self.meta = load_inference_state(checkpoint)
        if params is None:
            raise ServingError("need a checkpoint path or explicit params")
        if cfg.compile_cache_dir:
            from ..core.jaxcompat import enable_compile_cache

            enable_compile_cache(cfg.compile_cache_dir)
        self.model = self.spec.build_model(cfg)
        self.task = FederatedTask(
            self.model, has_batch_stats=bool(batch_stats)
        )
        # every device-resident buffer of this engine — params, batch stats,
        # the streaming carry table, and all AOT executables — pins to ONE
        # device (``device=None`` keeps jax's default, the single-engine
        # behavior). A ReplicaSet (serving/fleet.py) hands each replica its
        # own device, so N replicas are N independent single-device servers:
        # the request path stays collective-free per replica (S001).
        self.device = device
        self._bus_labels = dict(bus_labels or {})
        self._close_sink = close_sink
        # params + batch_stats live as ONE tuple bound by a single attribute
        # store/read (atomic under the GIL): a hot-swap (swap_params) rebinds
        # the tuple while dispatch threads are mid-flight, and a dispatch
        # must never pair new params with old stats
        self._live = (
            jax.device_put(params, device),
            jax.device_put(batch_stats or {}, device),
        )
        self.sample_shape = tuple(self.spec.serving.sample_shape(cfg))
        self.row_buckets = tuple(sorted(set(int(b) for b in row_buckets)))
        self.stream_chunk = int(stream_chunk)
        self.stream_buckets = tuple(sorted(set(int(b) for b in stream_buckets)))
        # streaming lane: auto (the task/config supports it) unless the
        # caller opts out (streaming=False — e.g. a batched-only deployment
        # that wants the persistent-compile-cache warm start; see warmup)
        self.streaming = self.spec.serving.supports_streaming(cfg)
        if streaming is False:
            self.streaming = False
        elif streaming is True and not self.streaming:
            raise ServingError(
                f"task {cfg.task_id!r} with this config cannot stream "
                "(needs a causal recurrent head)"
            )
        self._warm = False
        self._exec: dict = {}  # (lane, bucket) -> Compiled
        self._guard = None
        self._lock = threading.Lock()  # stats + latency list
        # SessionTable bookkeeping is mutated by the stream lane's dispatch
        # thread (resolve) AND the caller's thread (close_session, summary's
        # occupancy read) — every access goes through this lock
        self._session_lock = threading.Lock()
        self._latencies: list = []  # (lane, seconds) per request
        self._t0 = time.monotonic()
        self.warmup_seconds = 0.0
        self.stats = {
            "requests": 0, "samples": 0, "stream_chunks": 0, "swaps": 0,
        }
        self._max_delay_ms = max_delay_ms
        self._max_queue = max_queue
        # mirror ring: the last few batched dispatch payloads, kept for
        # shadow-lane scoring of a publish candidate against REAL recent
        # traffic (serving/publish.py) — the candidate runs through the same
        # stored executables these payloads already ran through
        self._mirror: list = []
        self._mirror_cap = 4

        # -- the two jitted entry points (warmup traces them; the request
        # path only runs their stored AOT executables)
        from ..trainer.steps import eval_forward

        def infer_fn(params, stats, x, w):
            return eval_forward(self.task, params, stats, x, None, w)

        self._infer_jit = jax.jit(infer_fn)
        # the hot-swap graft: an identity over (params, batch_stats) with
        # BOTH arguments donated — XLA aliases every input leaf straight into
        # the output (the S003 fleet cell proves it), so installing a
        # published candidate is a zero-copy buffer donation onto this
        # engine's device, never a recompile (executables are keyed by shape,
        # and swap_params refuses shape drift loudly). Compiled AOT at warmup
        # and counted by the same CompileGuard as the request lanes: the
        # zero-compile proof extends ACROSS publishes.
        self._swap_jit = jax.jit(
            lambda p, s: (p, s), donate_argnums=(0, 1)
        )

        self._stream_jit = None
        self._table = None
        self.sessions = None
        if self.streaming:
            from ..models.icalstm import ICALstmStream
            from .session import SessionTable, init_carry_table

            if stream_slots < self.stream_buckets[-1]:
                # a dispatch of B sessions needs B distinct slots: with
                # fewer, resolving request k can LRU-evict a session
                # resolved EARLIER IN THE SAME BATCH — duplicate slot
                # indices in one scatter, two live streams sharing (and
                # corrupting) one carry row
                raise ServingError(
                    f"stream_slots={stream_slots} is below the largest "
                    f"stream bucket ({self.stream_buckets[-1]}); a single "
                    "dispatch could evict its own batch's sessions"
                )
            a = cfg.ica_args
            self._stream_model = ICALstmStream(
                input_size=a.input_size, hidden_size=a.hidden_size,
                num_cls=a.num_class, num_comps=a.num_components,
                window_size=a.window_size,
                compute_dtype=a.compute_dtype or None,
            )
            self.sessions = SessionTable(stream_slots)
            self._table = jax.device_put(
                init_carry_table(stream_slots, a.hidden_size), device
            )
            self._stream_jit = jax.jit(
                self._stream_step, donate_argnums=(2,)
            )

    # the pre-swap names, kept as views of the atomic live tuple (tests,
    # bench and the semantic cells read them)
    @property
    def _params(self):
        return self._live[0]

    @property
    def _stats(self):
        return self._live[1]

    # -- traced programs -------------------------------------------------

    def _stream_step(self, params, stats, table, slot_ix, fresh, x,
                     step_valid, valid):
        """The streaming executable: gather carries by slot, zero fresh
        sessions in-trace, advance the chunk, scatter back (valid-gated, so
        padded request slots are exact identities on their — trash — row).
        ``table`` is donated: the update aliases in place."""
        import jax
        import jax.numpy as jnp

        h, c, pooled = (
            table["h"][slot_ix], table["c"][slot_ix], table["pooled"][slot_ix]
        )
        count = table["count"][slot_ix]
        keep = (1.0 - fresh)[:, None]
        h, c, pooled = h * keep, c * keep, pooled * keep
        count = count * (1.0 - fresh)
        variables = {"params": params}
        if self.task.has_batch_stats:
            variables["batch_stats"] = stats
        logits, (h2, c2, p2, n2) = self._stream_model.apply(
            variables, x, h, c, pooled, count, step_valid
        )
        probs = jax.nn.softmax(logits, -1)
        vg = valid[:, None] > 0
        new_table = {
            "h": table["h"].at[slot_ix].set(jnp.where(vg, h2, h)),
            "c": table["c"].at[slot_ix].set(jnp.where(vg, c2, c)),
            "pooled": table["pooled"].at[slot_ix].set(jnp.where(vg, p2, pooled)),
            "count": table["count"].at[slot_ix].set(
                jnp.where(valid > 0, n2, count)
            ),
        }
        return probs, new_table

    # -- warmup (the only place anything compiles) -----------------------

    def warmup(self) -> dict:
        """AOT-compile every (lane, bucket) executable; returns
        ``{lane/bucket: seconds}``. After this, the engine is armed: the
        CompileGuard snapshot makes any later compilation a hard failure.

        Persistent-compile-cache caveat (jax 0.4.37 / jaxlib 0.4.36, CPU):
        when ANY cache-DESERIALIZED executable lives in the process,
        invoking the streaming step (whose session table is a donated,
        input-output-aliased buffer) corrupts the heap — reproduced by
        building the engine twice against one cache dir and streaming a few
        chunks (segfault); fresh-compiled executables are fine, and so is a
        cache-restart of the donation-free batched lane alone. The bypass is
        gated on the KNOWN-BAD jaxlib range
        (core/jaxcompat.py ``stream_cache_safe``): on those runtimes a
        streaming engine pays a fresh compile per start (correctness over
        restart latency); on fixed runtimes the cache-warm startup comes
        back, and the tests/test_fleet.py subprocess probe re-runs the repro
        so a still-broken jaxlib fails loudly. A batched-only engine keeps
        the PR 4 cache's cold/warm win everywhere (``bench.py --serve``
        measures it on exactly that shape)."""
        import jax
        import jax.numpy as jnp

        from ..checks.sanitize import CompileGuard
        from ..core.jaxcompat import stream_cache_safe

        t0 = time.monotonic()
        times = {}
        cache_prev = jax.config.jax_enable_compilation_cache
        with self.tracer.span("serve-warmup"):
            try:
                if self.streaming and not stream_cache_safe():
                    jax.config.update("jax_enable_compilation_cache", False)
                for b in self.row_buckets:
                    tb = time.monotonic()
                    x = jnp.zeros((b,) + self.sample_shape, jnp.float32)
                    w = jnp.ones((b,), jnp.float32)
                    self._exec[("infer", b)] = self._infer_jit.lower(
                        self._params, self._stats, x, w
                    ).compile()
                    times[f"infer/{b}"] = round(time.monotonic() - tb, 4)
                if self.streaming:
                    a = self.cfg.ica_args
                    t = self.stream_chunk
                    for b in self.stream_buckets:
                        tb = time.monotonic()
                        args = (
                            self._params, self._stats, self._table,
                            jnp.zeros((b,), jnp.int32),
                            jnp.zeros((b,), jnp.float32),
                            jnp.zeros(
                                (b, t, a.num_components, a.window_size),
                                jnp.float32,
                            ),
                            jnp.zeros((b, t), jnp.float32),
                            jnp.zeros((b,), jnp.float32),
                        )
                        self._exec[("stream", b)] = self._stream_jit.lower(
                            *args
                        ).compile()
                        times[f"stream/{b}"] = round(
                            time.monotonic() - tb, 4
                        )
                tb = time.monotonic()
                self._exec[("swap", 0)] = self._swap_jit.lower(
                    *self._live
                ).compile()
                times["swap/0"] = round(time.monotonic() - tb, 4)
            finally:
                jax.config.update(
                    "jax_enable_compilation_cache", cache_prev
                )
        self.warmup_seconds = round(time.monotonic() - t0, 4)
        # zero-compile proof: the jitted entries must gain NO cached programs
        # from here on (the request path runs only the stored executables —
        # any growth means a silent fallback traced). swap_fn is in the set
        # ON PURPOSE: the proof holds ACROSS params hot-swaps, N publishes
        # included.
        self._guard = CompileGuard(
            {"infer_fn": self._infer_jit, "stream_fn": self._stream_jit,
             "swap_fn": self._swap_jit},
            max_compiles=0, label="serving",
        )
        self._start_lanes()
        self._warm = True
        return times

    def _start_lanes(self) -> None:
        from .microbatch import Microbatcher

        self._infer_lane = Microbatcher(
            self._dispatch_infer, self.row_buckets,
            max_delay_ms=self._max_delay_ms, max_queue=self._max_queue,
            name="infer", on_dispatch=self._record_dispatch, bus=self.bus,
            labels=self._bus_labels,
        )
        self._stream_lane = None
        if self.streaming:
            self._stream_lane = Microbatcher(
                self._dispatch_stream, self.stream_buckets,
                rows_of=lambda req: 1,
                conflict_key=lambda req: req.session,
                max_delay_ms=self._max_delay_ms, max_queue=self._max_queue,
                name="stream", on_dispatch=self._record_dispatch,
                bus=self.bus, labels=self._bus_labels,
            )

    # -- request path (Compiled executables only) ------------------------

    def _record_dispatch(self, lane, batch, bucket, rows, depth) -> None:
        if self.sink is not None:
            self.sink.append({
                "kind": "dispatch", "lane": lane, "bucket": int(bucket),
                "rows": int(rows), "pad_rows": int(bucket - rows),
                "queue_depth": int(depth),
                "trace_ids": [r.trace_id for r in batch],
                **self._bus_labels,
            })

    def _finish(self, reqs, lane: str) -> None:
        now = time.monotonic()
        with self._lock:
            for r in reqs:
                self._latencies.append((lane, now - r._submit_t))
            self.stats["requests"] += len(reqs)
        for r in reqs:
            self.bus.observe(
                "serving_request_latency_ms", (now - r._submit_t) * 1e3,
                lane=lane, **self._bus_labels,
            )
        self.bus.counter(
            "serving_requests_total", len(reqs), lane=lane,
            **self._bus_labels,
        )

    def _dispatch_infer(self, reqs, bucket: int) -> None:
        """Pack collected requests into the bucket's padded batch and run its
        pre-compiled executable. Pad rows carry weight 0 — for batch-stat
        models (MSANNet) the mask keeps them out of the BatchNorm statistics,
        exactly like eval-plan padding."""
        x = np.zeros((bucket,) + self.sample_shape, np.float32)
        w = np.zeros((bucket,), np.float32)
        at = 0
        spans = []
        for r in reqs:
            n = len(r.rows)
            x[at:at + n] = r.rows
            w[at:at + n] = 1.0 if r.weights is None else r.weights
            spans.append((r, at, n))
            at += n
        params, stats = self._live
        with self.tracer.span("serve-infer", bucket=bucket, rows=at,
                              trace_ids=[r.trace_id for r in reqs]):
            probs = np.asarray(self._exec[("infer", bucket)](
                params, stats, x, w
            ))
        with self._lock:
            # mirror the dispatch payload for shadow-lane scoring (a small
            # ring; the arrays are already padded host copies)
            self._mirror.append((bucket, x, w))
            del self._mirror[:-self._mirror_cap]
        for r, lo, n in spans:
            r.future.set_result(probs[lo:lo + n])
        with self._lock:
            self.stats["samples"] += at
        self._finish(reqs, "infer")

    def _dispatch_stream(self, reqs, bucket: int) -> None:
        """One streaming step over up to ``bucket`` sessions: resolve slots
        (assign/evict on the host table), run the chunk executable, rebind
        the donated carry table."""
        a = self.cfg.ica_args
        t = self.stream_chunk
        slot_ix = np.full((bucket,), self.sessions.trash_slot, np.int32)
        fresh = np.zeros((bucket,), np.float32)
        x = np.zeros(
            (bucket, t, a.num_components, a.window_size), np.float32
        )
        sv = np.zeros((bucket, t), np.float32)
        valid = np.zeros((bucket,), np.float32)
        for i, r in enumerate(reqs):
            with self._session_lock:
                slot, gen, is_fresh = self.sessions.resolve(r.session)
            r.slot, r.generation, r.fresh = slot, gen, is_fresh
            slot_ix[i] = slot
            fresh[i] = 1.0 if (is_fresh or r.fresh) else 0.0
            n = len(r.rows)
            x[i, :n] = r.rows
            sv[i, :n] = 1.0 if r.step_valid is None else r.step_valid
            valid[i] = 1.0
        params, stats = self._live
        with self.tracer.span("serve-stream", bucket=bucket, rows=len(reqs),
                              trace_ids=[r.trace_id for r in reqs]):
            probs, self._table = self._exec[("stream", bucket)](
                params, stats, self._table,
                slot_ix, fresh, x, sv, valid,
            )
            probs = np.asarray(probs)
        for i, r in enumerate(reqs):
            r.future.set_result(
                {"probs": probs[i], "session": r.session,
                 "generation": r.generation, "restarted": bool(r.fresh),
                 "trace_id": r.trace_id}
            )
        with self._lock:
            self.stats["samples"] += len(reqs)
            self.stats["stream_chunks"] += len(reqs)
        with self._session_lock:
            occupied, evictions = self.sessions.occupied, self.sessions.evictions
        self.bus.gauge(
            "serving_sessions_occupied", occupied, **self._bus_labels
        )
        self.bus.gauge(
            "serving_session_evictions", evictions, **self._bus_labels
        )
        self._finish(reqs, "stream")

    # -- public API ------------------------------------------------------

    def submit(self, rows, weights=None, trace_id=None, priority: int = 0,
               deadline_ms=None):
        """Batched inference: ``rows [n, ...sample_shape]`` → future of
        ``probs [n, C]``. ``weights`` masks rows (eval semantics);
        ``trace_id`` propagates a caller's request id into the dispatch
        row + span (auto-minted when absent; readable on the returned
        future's ``.trace_id``). ``priority`` (higher first) and
        ``deadline_ms`` (shed when staler than this at collection — the
        future then raises :class:`~.microbatch.RequestError`) feed the
        microbatcher's admission (r21)."""
        self._ensure_warm()
        rows = np.asarray(rows, np.float32)
        if rows.shape[1:] != self.sample_shape:
            raise ServingError(
                f"request rows shaped {rows.shape[1:]} but task "
                f"{self.cfg.task_id!r} serves {self.sample_shape}"
            )
        req = _Req(rows, weights=weights, trace_id=trace_id,
                   priority=priority, deadline_ms=deadline_ms)
        self._infer_lane.submit(req)
        return req.future

    def stream(self, session_id: str, windows, trace_id=None,
               priority: int = 0):
        """Streaming inference: feed ``windows [t, C, W]`` (the session's NEW
        timesteps) and get a future of the classification over everything
        the session has seen. Runs longer than one chunk are split into
        in-order chunk submissions (all sharing one ``trace_id``); the
        returned future is the LAST chunk's (the full-prefix answer).
        ``priority`` raises the chunks in the lane's admission order; there
        is deliberately NO deadline on stream chunks — shedding a middle
        chunk would silently drop windows from the carry, breaking the
        chunked == full-replay exactness contract."""
        self._ensure_warm()
        if not self.streaming:
            raise ServingError(
                "this checkpoint has no streaming lane (streaming needs a "
                "causal recurrent head: ICA-Classification with "
                "bidirectional=false — the reverse direction of a biLSTM "
                "reads the future, so no O(1) carry can serve it)"
            )
        windows = np.asarray(windows, np.float32)
        a = self.cfg.ica_args
        if windows.ndim != 3 or windows.shape[1:] != (
                a.num_components, a.window_size):
            raise ServingError(
                f"stream windows must be [t, {a.num_components}, "
                f"{a.window_size}], got {windows.shape}"
            )
        if len(windows) == 0:
            raise ServingError(
                "stream() needs at least one window (an empty chunk has "
                "nothing to advance the session with)"
            )
        from ..telemetry.tracer import new_trace_id
        from .microbatch import ChainedFuture

        trace_id = trace_id or new_trace_id()
        links = []
        for lo in range(0, len(windows), self.stream_chunk):
            req = _Req(windows[lo:lo + self.stream_chunk], session=session_id,
                       trace_id=trace_id, priority=priority)
            self._stream_lane.submit(req)
            links.append(req.future)
        # the chain surfaces ANY chunk's dispatch error — a failed middle
        # chunk must not be masked by a later chunk succeeding on a carry
        # that silently missed its windows
        if len(links) == 1:
            return links[0]
        chain = ChainedFuture(links)
        chain.trace_id = trace_id
        return chain

    def close_session(self, session_id: str) -> None:
        with self._session_lock:
            self.sessions.close(session_id)

    # -- params hot-swap (train-to-serve CD, serving/publish.py) ---------

    def _swap_shape_mismatch(self, new_params, new_stats) -> list:
        """Human-readable mismatches between a candidate weight tree and the
        live one (treedef + per-leaf shape/dtype). Executables are keyed by
        these shapes, so ANY mismatch means the candidate cannot ride the
        compiled set — the caller must refuse, never recompile."""
        import jax

        problems = []
        for what, new, cur in (
            ("params", new_params, self._live[0]),
            ("batch_stats", new_stats, self._live[1]),
        ):
            if (jax.tree_util.tree_structure(new)
                    != jax.tree_util.tree_structure(cur)):
                problems.append(f"{what}: tree structure differs")
                continue
            for n, c in zip(jax.tree.leaves(new), jax.tree.leaves(cur)):
                if (tuple(n.shape) != tuple(c.shape)
                        or np.dtype(n.dtype) != np.dtype(c.dtype)):
                    problems.append(
                        f"{what}: leaf {tuple(n.shape)}/{n.dtype} vs live "
                        f"{tuple(c.shape)}/{c.dtype}"
                    )
        return problems

    def weights(self) -> tuple:
        """The live ``(params, batch_stats)`` device arrays. A publish
        controller retains this tuple before a swap — it is the rollback
        target (the swap drops the engine's own reference)."""
        return self._live

    def swap_params(self, params, batch_stats=None) -> dict:
        """Install new weights with the pre-compiled donated graft: the
        candidate's buffers are device_put onto this engine's device and
        DONATED into the swap executable, whose outputs alias them in place
        (zero copy, zero compile — the warmup CompileGuard keeps counting).
        The engine takes ownership of the passed arrays if they already live
        on its device. Shape-keyed: any treedef/shape/dtype drift from the
        live weights raises :class:`ServingError` — a retrain that changed
        the architecture needs a new engine, not a swap. Returns
        ``{"pause_ms": ...}`` (the wall time requests could observe)."""
        import jax

        self._ensure_warm()
        new = (
            jax.device_put(params, self.device),
            jax.device_put(batch_stats or {}, self.device),
        )
        problems = self._swap_shape_mismatch(*new)
        if problems:
            raise ServingError(
                "hot-swap refused — candidate weights do not match the "
                "compiled executables' shapes (publish a same-architecture "
                "checkpoint, or stand up a new engine): "
                + "; ".join(problems)
            )
        t0 = time.monotonic()
        grafted = self._exec[("swap", 0)](*new)
        jax.block_until_ready(grafted)
        self._live = tuple(grafted)
        pause_ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            self.stats["swaps"] += 1
        self.bus.counter("serving_swaps_total", **self._bus_labels)
        self.bus.observe(
            "serving_swap_pause_ms", pause_ms, **self._bus_labels
        )
        return {"pause_ms": round(pause_ms, 4)}

    def shadow_score(self, params, batch_stats=None) -> dict:
        """Score a publish candidate against MIRRORED live traffic: replay
        the last few batched dispatch payloads through the same stored
        executables with the candidate's weights (donation-free lane — the
        live state is untouched). Returns finiteness plus the max
        probability shift vs the live weights; the publish controller
        rejects non-finite candidates before any swap. Shape drift raises
        like :meth:`swap_params`."""
        import jax

        self._ensure_warm()
        cand = (
            jax.device_put(params, self.device),
            jax.device_put(batch_stats or {}, self.device),
        )
        problems = self._swap_shape_mismatch(*cand)
        if problems:
            raise ServingError(
                "shadow-score refused — candidate weights do not match the "
                "compiled executables' shapes: " + "; ".join(problems)
            )
        with self._lock:
            ring = list(self._mirror)
        if not ring:
            # no traffic mirrored yet (publish before first dispatch):
            # score on a zero payload at the smallest bucket — still proves
            # the candidate produces finite probabilities
            b = self.row_buckets[0]
            ring = [(
                b, np.zeros((b,) + self.sample_shape, np.float32),
                np.ones((b,), np.float32),
            )]
        live = self._live
        finite = True
        max_delta = 0.0
        rows = 0
        for bucket, x, w in ring:
            got = np.asarray(self._exec[("infer", bucket)](*cand, x, w))
            ref = np.asarray(self._exec[("infer", bucket)](*live, x, w))
            mask = np.asarray(w) > 0
            rows += int(mask.sum())
            if not np.isfinite(got[mask]).all():
                finite = False
            else:
                max_delta = max(
                    max_delta, float(np.abs(got[mask] - ref[mask]).max())
                )
        return {
            "batches": len(ring), "rows": rows, "finite": finite,
            "max_abs_delta": round(max_delta, 6),
        }

    def _ensure_warm(self) -> None:
        if not self._warm:
            raise ServingError("call warmup() before submitting requests")

    def drain(self, timeout: float = 30.0) -> None:
        """Block until both lanes' queues are empty (best effort — used by
        the request-script runner between phases)."""
        deadline = time.monotonic() + timeout
        lanes = [L for L in (self._infer_lane, self._stream_lane) if L]
        while time.monotonic() < deadline:
            if all(L.depth() == 0 for L in lanes):
                return
            time.sleep(0.002)

    # -- proofs + rollup -------------------------------------------------

    def compiles_after_warmup(self) -> dict:
        return self._guard.counts() if self._guard is not None else {}

    def assert_no_compiles(self) -> None:
        """The zero-compile proof: raises
        :class:`~..checks.sanitize.SanitizerViolation` if any jitted serving
        entry compiled a program since warmup."""
        if self._guard is not None:
            self._guard.check(context="serving request path")

    def health_probes(self) -> dict:
        """Per-subsystem readiness probes for the ``/healthz`` endpoint."""
        probes = {
            "warm": lambda: self._warm,
            "infer_lane": lambda: (
                self._warm and self._infer_lane._thread.is_alive()
            ),
        }
        if self.streaming:
            probes["stream_lane"] = lambda: (
                self._warm and self._stream_lane._thread.is_alive()
            )
        return probes

    def status(self) -> dict:
        """The live ``/statusz`` payload: a cheap subset of
        :meth:`summary` plus the served checkpoint's provenance (including
        any ``traces`` the daemon embedded in the checkpoint meta — the
        serve end of cross-process trace propagation)."""
        lanes = [
            L for L in (getattr(self, "_infer_lane", None),
                        getattr(self, "_stream_lane", None)) if L
        ]
        with self._session_lock:
            occupied = self.sessions.occupied if self.sessions else 0
        return {
            "task_id": self.cfg.task_id,
            "warm": self._warm,
            "streaming": self.streaming,
            "requests": self.stats["requests"],
            "samples": self.stats["samples"],
            "swaps": self.stats["swaps"],
            "stream_sessions": occupied,
            "queue_depth": sum(L.depth() for L in lanes),
            "deferrals": sum(L.stats["deferrals"] for L in lanes),
            "shed": sum(L.stats["shed"] for L in lanes),
            "compiles_after_warmup": sum(
                self.compiles_after_warmup().values()
            ),
            "checkpoint_epoch": self.meta.get("epoch"),
            "checkpoint_traces": self.meta.get("traces") or {},
        }

    def summary(self) -> dict:
        with self._lock:
            lats = sorted(s for _, s in self._latencies)
        with self._session_lock:
            occupied = self.sessions.occupied if self.sessions else 0
            evictions = self.sessions.evictions if self.sessions else 0
        lanes = [
            L for L in (getattr(self, "_infer_lane", None),
                        getattr(self, "_stream_lane", None)) if L
        ]
        rows = sum(L.stats["rows"] for L in lanes)
        pads = sum(L.stats["pad_rows"] for L in lanes)
        disp = sum(L.stats["dispatches"] for L in lanes)
        hits = sum(L.stats["bucket_hits"] for L in lanes)
        elapsed = max(time.monotonic() - self._t0, 1e-9)

        def pct(p):
            if not lats:
                return None
            return round(
                1e3 * lats[min(int(p * len(lats)), len(lats) - 1)], 4
            )

        return {
            "kind": "serve_summary",
            "task_id": self.cfg.task_id,
            "requests": self.stats["requests"],
            "samples": self.stats["samples"],
            "stream_chunks": self.stats["stream_chunks"],
            "dispatches": disp,
            "latency_ms_p50": pct(0.50),
            "latency_ms_p95": pct(0.95),
            "latency_ms_p99": pct(0.99),
            "requests_per_s": round(self.stats["requests"] / elapsed, 2),
            "samples_per_s": round(self.stats["samples"] / elapsed, 2),
            "pad_waste_pct": round(100.0 * pads / max(rows + pads, 1), 2),
            "bucket_hit_rate": round(hits / max(disp, 1), 4),
            "max_queue_depth": max(
                (L.stats["max_queue_depth"] for L in lanes), default=0
            ),
            "deferrals": sum(L.stats["deferrals"] for L in lanes),
            "shed": sum(L.stats["shed"] for L in lanes),
            "swaps": self.stats["swaps"],
            **self._bus_labels,
            "checkpoint_traces": self.meta.get("traces") or {},
            "warmup_seconds": self.warmup_seconds,
            "buckets": {
                "infer": list(self.row_buckets),
                "stream": list(self.stream_buckets) if self.streaming else [],
                "stream_chunk": self.stream_chunk if self.streaming else 0,
            },
            "stream_sessions": occupied,
            "stream_evictions": evictions,
            "compiles_after_warmup": sum(self.compiles_after_warmup().values()),
        }

    def close(self) -> dict:
        """Stop the lanes, verify the zero-compile invariant, emit the
        serve_summary telemetry row; returns the summary."""
        for lane in (getattr(self, "_infer_lane", None),
                     getattr(self, "_stream_lane", None)):
            if lane is not None:
                lane.close()
        summary = self.summary()
        if self.sink is not None:
            self.sink.append(summary)
            if self._close_sink:
                # a fleet shares one sink across replicas and closes it
                # once itself (close_sink=False per replica)
                self.sink.close()
        self.assert_no_compiles()
        return summary

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
