"""Aggregation-engine tests (SURVEY.md §4 implication (b): parity of each
engine against analytic expectations)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from dinunet_implementations_tpu.core.jaxcompat import shard_map
from jax.sharding import PartitionSpec as P

from dinunet_implementations_tpu.engines import (
    make_engine,
    available_engines,
    subspace_iteration,
)
from dinunet_implementations_tpu.parallel import SITE_AXIS, host_mesh

S = 4


def _tree(seed, scale=1.0):
    rng = np.random.default_rng(seed)
    return {
        "dense": {"kernel": jnp.asarray(rng.normal(size=(S, 12, 8)) * scale, jnp.float32),
                  "bias": jnp.asarray(rng.normal(size=(S, 8)) * scale, jnp.float32)},
        "head": {"kernel": jnp.asarray(rng.normal(size=(S, 8, 2)) * scale, jnp.float32)},
    }


def _weights():
    return jnp.asarray([3.0, 5.0, 2.0, 7.0])


def _pooled(tree, w):
    w = np.asarray(w)

    def f(g):
        g = np.asarray(g)
        return (g * w.reshape(-1, *([1] * (g.ndim - 1)))).sum(0) / w.sum()

    return jax.tree.map(f, tree)


def _run_engine(name, tree, w, **cfg):
    mesh = host_mesh(S)
    eng = make_engine(name, **cfg)
    state = eng.init(jax.tree.map(lambda g: g[0], tree))

    def fn(g, wv):
        g = jax.tree.map(lambda x: x[0], g)  # shard_map gives [1, ...] per site
        agg, st = eng.aggregate(g, state, wv[0], SITE_AXIS)
        return jax.tree.map(lambda x: x[None], agg)

    out = shard_map(
        fn, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(SITE_AXIS), tree), P(SITE_AXIS)),
        out_specs=jax.tree.map(lambda _: P(SITE_AXIS), tree),
    )(tree, w)
    return jax.tree.map(lambda x: np.asarray(x[0]), out)


def test_registry():
    assert available_engines() == ["dSGD", "powerSGD", "rankDAD"]
    with pytest.raises(ValueError):
        make_engine("nope")


def test_dsgd_equals_pooled():
    tree, w = _tree(0), _weights()
    agg = _run_engine("dSGD", tree, w)
    expect = _pooled(tree, w)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, rtol=1e-4, atol=1e-6), agg, expect
    )


@pytest.mark.slow
def test_rankdad_full_rank_equals_pooled():
    """With rank >= min(m, n) the power iteration is exact → rankDAD == dSGD."""
    tree, w = _tree(1), _weights()
    agg = _run_engine("rankDAD", tree, w, dad_reduction_rank=8, dad_num_pow_iters=25,
                      dad_tol=1e-9)
    expect = _pooled(tree, w)
    jax.tree.map(lambda a, e: np.testing.assert_allclose(a, e, atol=1e-4), agg, expect)


def test_rankdad_low_rank_compresses():
    """rank-1 compression of a rank-1 matrix is exact; of a full-rank matrix
    it is lossy but bounded by the spectral tail."""
    rng = np.random.default_rng(2)
    u = rng.normal(size=(S, 12, 1)).astype(np.float32)
    v = rng.normal(size=(S, 1, 8)).astype(np.float32)
    tree = {"k": jnp.asarray(u @ v)}
    w = _weights()
    agg = _run_engine("rankDAD", tree, w, dad_reduction_rank=1, dad_num_pow_iters=10,
                      dad_tol=1e-9)
    expect = _pooled(tree, w)
    np.testing.assert_allclose(agg["k"], expect["k"], atol=1e-4)


@pytest.mark.slow
def test_powersgd_error_feedback_converges():
    """Error-feedback property: a single compressed round is lossy, but the
    *time-averaged* updates converge to the true gradient — telescoping gives
    (1/T)·Σ Ĝ_t = Ḡ + (Ḡ − M_{T+1})/T with M bounded, so error ~ 1/T."""
    mesh = host_mesh(S)
    tree, w = _tree(3), _weights()
    eng = make_engine("powerSGD", dad_reduction_rank=2)
    expect = _pooled(tree, w)

    def multi_round(g, wv):
        g0 = jax.tree.map(lambda x: x[0], g)
        st = eng.init(g0)
        accs = []
        acc = jax.tree.map(jnp.zeros_like, g0)
        for t in range(24):
            agg, st = eng.aggregate(g0, st, wv[0], SITE_AXIS)
            acc = jax.tree.map(lambda a, x: a + x, acc, agg)
            if t + 1 in (4, 24):
                accs.append(jax.tree.map(lambda a: a / (t + 1), acc))
        return jax.tree.map(lambda x: x[None], {"t4": accs[0], "t24": accs[1]})

    spec_in = jax.tree.map(lambda _: P(SITE_AXIS), tree)
    out = shard_map(
        multi_round, mesh=mesh,
        in_specs=(spec_in, P(SITE_AXIS)),
        out_specs={"t4": spec_in, "t24": spec_in},
    )(tree, w)
    avg4 = jax.tree.map(lambda x: np.asarray(x[0]), out["t4"])
    avg24 = jax.tree.map(lambda x: np.asarray(x[0]), out["t24"])

    def err(a):
        return np.linalg.norm(a["dense"]["kernel"] - expect["dense"]["kernel"])

    assert err(avg24) < err(avg4)  # averaging converges
    np.testing.assert_allclose(
        avg24["dense"]["kernel"], expect["dense"]["kernel"], atol=0.25
    )
    # dense (1-D) path is exact every round
    np.testing.assert_allclose(avg24["dense"]["bias"], expect["dense"]["bias"], rtol=1e-4)


@pytest.mark.slow
def test_powersgd_bias_dense_exact():
    tree, w = _tree(4), _weights()
    agg = _run_engine("powerSGD", tree, w, dad_reduction_rank=2)
    expect = _pooled(tree, w)
    np.testing.assert_allclose(agg["dense"]["bias"], expect["dense"]["bias"], rtol=1e-5)


def test_subspace_iteration_exact_on_lowrank():
    rng = np.random.default_rng(5)
    G = (rng.normal(size=(20, 3)) @ rng.normal(size=(3, 15))).astype(np.float32)
    P, Q = subspace_iteration(jnp.asarray(G), 3, 20, 1e-10)
    np.testing.assert_allclose(np.asarray(P @ Q.T), G, atol=1e-3)


def test_subspace_iteration_explicit_key_used():
    """A caller-supplied PRNG key must actually seed the init Ω (advisor
    finding r3: it was silently discarded): factorization quality holds with
    an explicit key, and on a full-rank wide matrix stopped after a single
    iteration (where Ω still matters) the result differs from the default."""
    rng = np.random.default_rng(11)
    G = jnp.asarray(
        (rng.normal(size=(20, 3)) @ rng.normal(size=(3, 15))).astype(np.float32)
    )
    P, Q = subspace_iteration(G, 3, 20, 1e-10, key=jax.random.PRNGKey(123))
    np.testing.assert_allclose(np.asarray(P @ Q.T), np.asarray(G), atol=1e-3)
    Gf = jnp.asarray(rng.normal(size=(8, 24)).astype(np.float32))
    P_d, _ = subspace_iteration(Gf, 4, 1, 0.0)
    P_k, _ = subspace_iteration(Gf, 4, 1, 0.0, key=jax.random.PRNGKey(123))
    assert not np.allclose(np.asarray(P_d), np.asarray(P_k))


def test_subspace_iteration_tol_early_exit():
    """A huge tol stops after the first refinement (initial delta is inf, so
    exactly one iteration runs) — same result as num_iters=1, under jit."""
    rng = np.random.default_rng(6)
    G = jnp.asarray(rng.normal(size=(16, 10)).astype(np.float32))
    P1, Q1 = jax.jit(lambda g: subspace_iteration(g, 4, 100, 1e9))(G)
    P2, Q2 = subspace_iteration(G, 4, 1, 0.0)
    np.testing.assert_allclose(np.asarray(P1 @ Q1.T), np.asarray(P2 @ Q2.T), atol=1e-5)


@pytest.mark.slow
def test_engines_precision16_still_close():
    tree, w = _tree(7), _weights()
    for name in ("dSGD", "rankDAD", "powerSGD"):
        agg = _run_engine(name, tree, w, precision_bits="16", dad_reduction_rank=8,
                          dad_num_pow_iters=20, dad_tol=1e-9)
        expect = _pooled(tree, w)
        np.testing.assert_allclose(
            agg["dense"]["bias"], expect["dense"]["bias"], rtol=0.02, err_msg=name
        )


def test_subspace_iteration_decaying_spectrum_quality():
    """Review regression (r3): the TPU-friendly CholeskyQR2 orthonormalization
    must not collapse small-singular-value directions — on a 4-decade decaying
    spectrum, P stays orthonormal and the rank-r reconstruction matches the
    optimal truncation (the failure mode was a trace-relative Cholesky shift
    swamping every direction below ~1e-3 of sigma_1)."""
    rng = np.random.default_rng(42)
    m, n, r = 200, 80, 6
    spectrum = np.array([1.0, 0.5, 0.2, 0.1] + [1e-4] * 6, np.float32)
    U, _ = np.linalg.qr(rng.normal(size=(m, len(spectrum))))
    V, _ = np.linalg.qr(rng.normal(size=(n, len(spectrum))))
    G = jnp.asarray((U * spectrum) @ V.T, jnp.float32)

    P, Q = subspace_iteration(G, r, 20, 1e-9)
    orth_err = float(jnp.abs(P.T @ P - jnp.eye(r)).max())
    assert orth_err < 1e-4, f"P not orthonormal: {orth_err:.2e}"
    rec_err = float(jnp.linalg.norm(P @ Q.T - G) / jnp.linalg.norm(G))
    optimal = float(np.linalg.norm(spectrum[r:]) / np.linalg.norm(spectrum))
    assert rec_err < 1.5 * optimal + 1e-6, (
        f"reconstruction {rec_err:.3e} vs optimal truncation {optimal:.3e}"
    )


def test_subspace_iteration_rank_deficient_and_zero_safe():
    """NaN-safety: true gradient rank < r (bounded by batch size) and the
    all-zero leaf must both stay finite."""
    rng = np.random.default_rng(43)
    u = rng.normal(size=(50, 2)).astype(np.float32)
    v = rng.normal(size=(20, 2)).astype(np.float32)
    G_lowrank = jnp.asarray(u @ v.T)  # true rank 2 < r=6
    for G in (G_lowrank, jnp.zeros((50, 20), jnp.float32)):
        P, Q = subspace_iteration(G, 6, 5, 1e-3)
        assert bool(jnp.isfinite(P).all() and jnp.isfinite(Q).all())
    # the low-rank case must still reconstruct its true subspace
    P, Q = subspace_iteration(G_lowrank, 6, 20, 1e-9)
    rec = float(jnp.linalg.norm(P @ Q.T - G_lowrank) / jnp.linalg.norm(G_lowrank))
    assert rec < 1e-3, f"rank-2 reconstruction error {rec:.2e}"


def test_orthonormalize_zero_input_recovers():
    """Review regression (r3): orthonormalize(0) must return an ORTHONORMAL
    basis (as Householder QR does), not zeros — powerSGD warm-starts its q
    factor from P, and P=0 would freeze the leaf's gradient forever."""
    from dinunet_implementations_tpu.engines.lowrank import orthonormalize

    P = orthonormalize(jnp.zeros((12, 4), jnp.float32))
    np.testing.assert_allclose(
        np.asarray(P.T @ P), np.eye(4), atol=1e-5
    )


@pytest.mark.slow
def test_subspace_iteration_multi_matches_solo():
    """Lockstep groups must keep solo semantics: same subspace, same
    reconstruction, per-member trip counts."""
    import numpy as np

    from dinunet_implementations_tpu.engines.lowrank import (
        subspace_iteration,
        subspace_iteration_multi,
    )

    rng = np.random.default_rng(0)
    Gs = [
        jnp.asarray(rng.normal(size=(40, 24)).astype("float32")),
        jnp.asarray(rng.normal(size=(64, 16)).astype("float32")),
        jnp.asarray(rng.normal(size=(24, 48)).astype("float32")),
    ]
    multi = subspace_iteration_multi(Gs, 6, 8, 1e-4)
    for G, (Pm, Qm) in zip(Gs, multi):
        Ps, Qs_ = subspace_iteration(G, 6, 8, 1e-4)
        # same projector (bases may differ by rotation only)
        proj_m = Pm @ Pm.T
        proj_s = Ps @ Ps.T
        np.testing.assert_allclose(np.asarray(proj_m), np.asarray(proj_s),
                                   atol=1e-3)
        np.testing.assert_allclose(np.asarray(Pm @ Qm.T),
                                   np.asarray(Ps @ Qs_.T), atol=1e-3)
        # orthonormality of the lockstep result
        np.testing.assert_allclose(np.asarray(Pm.T @ Pm), np.eye(6),
                                   atol=1e-4)


def test_rankdad_warm_start_round1_identical_to_cold():
    """At init the warm-start state holds the cold-start default Ω draw
    (lowrank.default_omega), so the FIRST aggregate round is identical with
    warm starts on or off."""
    tree, w = _tree(8), _weights()
    kw = dict(dad_reduction_rank=3, dad_num_pow_iters=3, dad_tol=1e-3)
    warm = _run_engine("rankDAD", tree, w, dad_warm_start=True, **kw)
    cold = _run_engine("rankDAD", tree, w, dad_warm_start=False, **kw)
    jax.tree.map(
        lambda a, e: np.testing.assert_allclose(a, e, atol=1e-6), warm, cold
    )


def _run_engine_rounds(name, trees, w, **cfg):
    """Run several aggregate rounds threading the engine state; returns the
    per-round aggregates (list of trees)."""
    mesh = host_mesh(S)
    eng = make_engine(name, **cfg)
    state0 = eng.init(jax.tree.map(lambda g: g[0], trees[0]))

    def fn(w_all, *gs):
        st = state0
        outs = []
        for g in gs:
            g = jax.tree.map(lambda x: x[0], g)
            agg, st = eng.aggregate(g, st, w_all[0], SITE_AXIS)
            outs.append(jax.tree.map(lambda x: x[None], agg))
        return tuple(outs)

    spec = jax.tree.map(lambda _: P(SITE_AXIS), trees[0])
    outs = shard_map(
        fn, mesh=mesh,
        in_specs=(P(SITE_AXIS),) + (spec,) * len(trees),
        out_specs=(spec,) * len(trees),
    )(w, *trees)
    return [jax.tree.map(lambda x: np.asarray(x[0]), o) for o in outs]


def _gapped_tree(seed, m=12, n=8, r=4, gap=1e-3):
    """Per-site matrices with a CLEAN spectral gap after σ_r, so the rank-r
    subspace is well-conditioned and the power iteration actually converges
    within the iteration budget (a random Gaussian's σ_r ≈ σ_{r+1} makes the
    truncated subspace ill-conditioned — convergence rate (σ_{r+1}/σ_r)^k)."""
    rng = np.random.default_rng(seed)
    spec = np.array([1.0, 0.7, 0.5, 0.3] + [gap] * (min(m, n) - r), np.float32)
    mats = []
    for _ in range(S):
        U, _ = np.linalg.qr(rng.normal(size=(m, len(spec))))
        V, _ = np.linalg.qr(rng.normal(size=(n, len(spec))))
        mats.append((U * spec) @ V.T)
    return {"k": jnp.asarray(np.stack(mats).astype(np.float32))}


@pytest.mark.slow
def test_rankdad_warm_start_converged_parity_with_cold():
    """Acceptance (r6): at dad_num_pow_iters high enough to converge, a
    warm-started round-2 aggregate equals the cold-start round-2 aggregate —
    the warm Ω changes the ITERATE, not the converged subspace."""
    trees = [_gapped_tree(9), _gapped_tree(10)]
    w = _weights()
    kw = dict(dad_reduction_rank=4, dad_num_pow_iters=25, dad_tol=1e-9)
    warm = _run_engine_rounds("rankDAD", trees, w, dad_warm_start=True, **kw)
    cold = _run_engine_rounds("rankDAD", trees, w, dad_warm_start=False, **kw)
    for a, e in zip(warm, cold):
        jax.tree.map(
            lambda x, y: np.testing.assert_allclose(x, y, atol=1e-4), a, e
        )


def test_rankdad_warm_state_roundtrips_epoch_scan():
    """Acceptance (r6): the warm-start Ω must round-trip through the jitted
    epoch scan exactly like powerSGD's Q/error-feedback — per-site leaves,
    updated every round, finite — and a second epoch must consume the state
    the first one produced."""
    import jax.numpy as jnp

    from dinunet_implementations_tpu.models import MSANNet
    from dinunet_implementations_tpu.trainer import (
        FederatedTask,
        init_train_state,
        make_optimizer,
        make_train_epoch_fn,
    )

    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    task = FederatedTask(model)
    eng = make_engine("rankDAD", dad_reduction_rank=3, dad_num_pow_iters=3,
                      dad_tol=1e-3)
    opt = make_optimizer("adam", 1e-2)
    rng = np.random.default_rng(0)
    Ssites = 3
    x = jnp.asarray(rng.normal(size=(Ssites, 4, 4, 6)).astype(np.float32))
    y = jnp.asarray((rng.random((Ssites, 4, 4)) > 0.5).astype(np.int32))
    w = jnp.ones((Ssites, 4, 4), jnp.float32)
    state = init_train_state(task, eng, opt, jax.random.PRNGKey(0), x[0, 0],
                             num_sites=Ssites)
    om0 = [np.asarray(o) for o in jax.tree.leaves(state.engine_state["omega"])]
    # per-site leading axis, like powerSGD's q/e
    assert all(o.shape[0] == Ssites for o in om0)
    epoch_fn = make_train_epoch_fn(task, eng, opt, mesh=None, local_iterations=2)
    state1, losses1 = epoch_fn(state, x, y, w)
    om1 = [np.asarray(o) for o in jax.tree.leaves(state1.engine_state["omega"])]
    assert all(np.isfinite(o).all() for o in om1)
    # the scan must actually UPDATE the warm state (Ω ← Q ≠ the random init)
    assert any(not np.allclose(a, b) for a, b in zip(om0, om1))
    state2, losses2 = epoch_fn(state1, x, y, w)
    assert np.isfinite(np.asarray(losses2)).all()


def test_rankdad_mixed_precision_iteration_close_to_f32():
    """precision_bits="16" runs the big power-iteration matmuls in bf16 with
    f32 accumulation — the aggregate must track the f32 engine within bf16
    noise (relative Frobenius error, not bitwise)."""
    tree, w = _tree(12), _weights()
    kw = dict(dad_reduction_rank=8, dad_num_pow_iters=20, dad_tol=1e-9)
    f32 = _run_engine("rankDAD", tree, w, precision_bits="32", **kw)
    b16 = _run_engine("rankDAD", tree, w, precision_bits="16", **kw)

    def rel(a, b):
        return np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-12)

    errs = jax.tree.leaves(jax.tree.map(rel, b16, f32))
    assert max(errs) < 0.05, f"bf16 iteration drifted: {errs}"


def test_subspace_iteration_grouped_mixed_ranks_matches_per_group():
    """One shared while_loop over several rank classes must reproduce the
    per-group results (the rank classes were previously separate while_loops,
    which XLA serializes — audit r6)."""
    from dinunet_implementations_tpu.engines.lowrank import (
        subspace_iteration_grouped,
        subspace_iteration_multi,
    )

    rng = np.random.default_rng(21)
    g1 = [jnp.asarray(rng.normal(size=(24, 16)).astype(np.float32)),
          jnp.asarray(rng.normal(size=(40, 12)).astype(np.float32))]
    g2 = [jnp.asarray(rng.normal(size=(30, 3)).astype(np.float32))]
    grouped = subspace_iteration_grouped(
        [(g1, 6, None), (g2, 6, None)], 8, 1e-4
    )
    solo1 = subspace_iteration_multi(g1, 6, 8, 1e-4)
    solo2 = subspace_iteration_multi(g2, 6, 8, 1e-4)
    for (Pg, Qg), (Ps, Qs_) in zip(grouped[0] + grouped[1], solo1 + solo2):
        np.testing.assert_allclose(
            np.asarray(Pg @ Qg.T), np.asarray(Ps @ Qs_.T), atol=1e-4
        )


def test_rankdad_zero_gradient_round_recovers():
    """A zero gradient zeroes the stored Ω; the next round's CholeskyQR
    fallback re-seeds from canonical basis vectors, so the subspace must
    recover as soon as the gradient returns."""
    rng = np.random.default_rng(22)
    zero = {"k": jnp.zeros((S, 12, 8), jnp.float32)}
    live = {"k": jnp.asarray(rng.normal(size=(S, 12, 8)).astype(np.float32))}
    w = _weights()
    kw = dict(dad_reduction_rank=8, dad_num_pow_iters=25, dad_tol=1e-9)
    out_zero, out_live = _run_engine_rounds(
        "rankDAD", [zero, live], w, dad_warm_start=True, **kw
    )
    np.testing.assert_allclose(out_zero["k"], np.zeros((12, 8)), atol=1e-7)
    expect = _pooled(live, w)
    np.testing.assert_allclose(out_live["k"], expect["k"], atol=1e-4)


@pytest.mark.slow
def test_small_cholesky_and_inverse_match_lapack():
    """The TPU-path unrolled Cholesky / triangular inverse (used to avoid
    the per-matrix-cost LAPACK custom-calls) must match LAPACK numerics."""
    import numpy as np

    from dinunet_implementations_tpu.engines.lowrank import (
        _small_cholesky,
        _small_tril_inverse,
    )

    rng = np.random.default_rng(0)
    for shape in [(10, 10), (7, 4, 4), (32, 7, 10, 10)]:
        r = shape[-1]
        A = rng.normal(size=shape[:-2] + (r, r + 3)).astype("float32")
        G = jnp.asarray(
            A @ np.swapaxes(A, -1, -2) + 0.1 * np.eye(r, dtype="float32")
        )
        L = _small_cholesky(G)
        np.testing.assert_allclose(
            np.asarray(L), np.linalg.cholesky(np.asarray(G)),
            atol=3e-5, rtol=1e-4,
        )
        X = _small_tril_inverse(L)
        np.testing.assert_allclose(
            np.asarray(X @ L), np.broadcast_to(np.eye(r), G.shape), atol=1e-5
        )
