"""Cross-process trace assembly (r23): one pod, one Perfetto timeline.

    python -m dinunet_implementations_tpu.telemetry.assemble <pod-dir> \\
        [--out pod_trace/pod.chrome.json] [--require-cross-process]

Every process's SpanTracer stamps event timestamps relative to its OWN
monotonic birth (``time.perf_counter``), so per-process trace.jsonl files
cannot be overlaid directly — the clocks don't share a zero. This module
aligns them onto the wall clock and emits ONE Chrome trace-event JSON
(Perfetto-loadable) in which a sample is followable spool→train→DCN
hop→publish→serve across process boundaries by its PR 11 trace id.

Clock alignment, in preference order:

1. **Heartbeat-exchanged offsets** — each r23 heartbeat pulse samples
   ``perf`` and ``time_unix`` back to back, so ``time_unix - perf`` is
   that process's monotonic→wall offset; an event's wall time is
   ``offset + t0_perf + ts/1e6`` (``t0_perf`` from the trace's clock_sync
   row). The offset is measured FRESH every pulse, so a process that
   lived hours before tracing still aligns.
2. **The clock_sync row alone** — ``t0_unix + ts/1e6``: every trace.jsonl
   written since r23 opens with the tracer's birth on both clocks, so a
   trace file is assemblable even without the pod's heartbeat directory.

Trace files are discovered under ``<pod-dir>/pod_trace/*.jsonl`` (the
per-process traces the supervised dcn workers write) and any
``trace.jsonl`` below ``<pod-dir>/telemetry/`` (the coordinator's per-fit
sink). Output timestamps are rebased to the earliest aligned event, one
Perfetto process row per source pid.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .collector import read_heartbeats

POD_TRACE_DIR = "pod_trace"
POD_TRACE_FILE = "pod.chrome.json"
CLOCK_SYNC = "clock_sync"


def clock_offsets(pod_dir: str) -> dict[int, float]:
    """Per-pid monotonic→wall offsets from the pod's heartbeat files
    (``time_unix - perf``, both sampled in the same ``beat()``)."""
    out: dict[int, float] = {}
    for hb in read_heartbeats(pod_dir):
        pid, perf, unix = hb.get("pid"), hb.get("perf"), hb.get("time_unix")
        if (isinstance(pid, int) and isinstance(perf, (int, float))
                and isinstance(unix, (int, float))):
            out[pid] = unix - perf
    return out


def find_trace_files(pod_dir: str) -> list[str]:
    """Per-process trace.jsonl files under the pod dir (module
    docstring), sorted for deterministic assembly order."""
    found = []
    pt = os.path.join(pod_dir, POD_TRACE_DIR)
    try:
        found += [
            os.path.join(pt, n) for n in os.listdir(pt)
            if n.endswith(".jsonl")
        ]
    except OSError:
        pass
    tel = os.path.join(pod_dir, "telemetry")
    for root, _dirs, names in os.walk(tel):
        found += [
            os.path.join(root, n) for n in names if n == "trace.jsonl"
        ]
    return sorted(found)


def load_trace(path: str) -> tuple[dict | None, list[dict]]:
    """``(clock_sync_row | None, events)`` from one trace.jsonl."""
    clock = None
    events = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if ev.get("ph") == "M" and ev.get("name") == CLOCK_SYNC:
                clock = ev
            else:
                events.append(ev)
    return clock, events


def align_unix_us(ts_us: float, clock: dict,
                  offset: float | None = None) -> float:
    """An event's wall-clock time in µs-since-epoch, from its tracer-
    relative ``ts``: via the heartbeat-exchanged ``offset`` when one is
    known for this pid (preferred — measured fresh each pulse), else via
    the clock_sync row's own wall sample."""
    if offset is not None and isinstance(clock.get("t0_perf"),
                                         (int, float)):
        return (offset + clock["t0_perf"]) * 1e6 + ts_us
    return float(clock.get("t0_unix", 0.0)) * 1e6 + ts_us


def assemble(pod_dir: str, out_path: str | None = None) -> dict:
    """Build (and optionally write) the merged Chrome trace payload. Each
    source file becomes one Perfetto process row (pid from its clock_sync
    row, process_name from the file name); events keep their span attrs —
    trace ids included — in ``args``."""
    offsets = clock_offsets(pod_dir)
    out_events: list[dict] = []
    sources = []
    t_min = None
    pod_pids: set[int] = set()
    pod_prefix = os.path.join(pod_dir, POD_TRACE_DIR) + os.sep
    for path in find_trace_files(pod_dir):
        clock, events = load_trace(path)
        if clock is None or not events:
            continue
        pid = int(clock.get("pid", 0))
        # a supervised worker writes the SAME tracer buffer twice: its
        # pod_trace/ file and its per-fit telemetry sink. pod_trace/
        # sorts first; skip the sink copy rather than double every span
        # (same-pid sinks from different folds still all assemble)
        if path.startswith(pod_prefix):
            pod_pids.add(pid)
        elif pid in pod_pids:
            sources.append({
                "path": path, "pid": pid, "events": 0,
                "aligned_by": "skipped:duplicate-of-pod-trace",
            })
            continue
        offset = offsets.get(pid)
        aligned = []
        for ev in events:
            if "ts" not in ev:
                continue
            t = align_unix_us(float(ev["ts"]), clock, offset)
            aligned.append((t, ev))
            t_min = t if t_min is None else min(t_min, t)
        sources.append({
            "path": path, "pid": pid, "events": len(aligned),
            "aligned_by": "heartbeat" if offset is not None else CLOCK_SYNC,
        })
        name = os.path.splitext(os.path.basename(path))[0]
        out_events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": name},
        })
        for t, ev in aligned:
            rec = {
                "ph": ev.get("ph", "i"),
                "name": ev.get("name", "?"),
                "ts": t,  # rebased below once t_min is known
                "pid": pid,
                "tid": ev.get("tid", 0),
            }
            if ev.get("ph") == "X":
                rec["dur"] = round(float(ev.get("dur", 0.0)), 3)
            if ev.get("ph") == "i":
                rec["s"] = "t"
            args = {
                k: v for k, v in ev.items()
                if k not in ("ph", "name", "ts", "dur", "tid", "thread",
                             "depth")
            }
            if args:
                rec["args"] = args
            out_events.append(rec)
    base = t_min or 0.0
    for rec in out_events:
        if "ts" in rec:
            rec["ts"] = round(rec["ts"] - base, 3)
    payload = {
        "traceEvents": out_events,
        "displayTimeUnit": "ms",
        "metadata": {
            "pod_dir": pod_dir,
            "t0_unix": base / 1e6,
            "sources": sources,
        },
    }
    if out_path is not None:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        tmp = out_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, out_path)
    return payload


def processes_by_trace(payload: dict) -> dict[str, set]:
    """``{trace_id: {pids}}`` over the assembled events — the
    cross-process-visibility assertion CI gates on (≥ 2 pids sharing a
    trace id means one sample really is followable across the pod)."""
    out: dict[str, set] = {}
    for ev in payload.get("traceEvents", []):
        trace = (ev.get("args") or {}).get("trace")
        if trace:
            out.setdefault(str(trace), set()).add(ev.get("pid"))
    return out


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dinunet_implementations_tpu.telemetry.assemble",
        description="Assemble per-process trace.jsonl files into one "
                    "clock-aligned Perfetto timeline.",
    )
    p.add_argument("pod_dir", help="a supervised run's --out-dir (holds "
                                   "pod_trace/ and/or telemetry/, plus "
                                   "heartbeats/ for clock offsets)")
    p.add_argument("--out", default=None,
                   help=f"output path (default <pod-dir>/{POD_TRACE_DIR}/"
                        f"{POD_TRACE_FILE})")
    p.add_argument("--require-cross-process", action="store_true",
                   help="exit 1 unless at least one trace id spans >= 2 "
                        "processes (the CI gate)")
    args = p.parse_args(argv)
    out = args.out or os.path.join(
        args.pod_dir, POD_TRACE_DIR, POD_TRACE_FILE
    )
    payload = assemble(args.pod_dir, out)
    srcs = payload["metadata"]["sources"]
    shared = {
        t: sorted(str(p_) for p_ in pids)
        for t, pids in processes_by_trace(payload).items()
        if len(pids) >= 2
    }
    print(
        f"pod trace: {len(srcs)} source file(s), "
        f"{sum(s['events'] for s in srcs)} events, "
        f"{len(shared)} trace id(s) spanning >=2 processes -> {out}"
    )
    for s in srcs:
        print(f"  {s['path']}: pid {s['pid']}, {s['events']} events, "
              f"clock via {s['aligned_by']}")
    for t, pids in sorted(shared.items()):
        print(f"  trace {t}: processes {', '.join(pids)}")
    if args.require_cross_process and not shared:
        print("assemble: no trace id spans two processes", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
