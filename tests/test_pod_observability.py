"""Pod observability plane tests (r23): metrics federation
(telemetry/collector.py), cross-process trace assembly
(telemetry/assemble.py), postmortem reconstruction
(telemetry/postmortem.py), the multi-dir report rollup, and
scripts/bench_diff.py — all stdlib-side, fast enough for tier-1 (the full
supervised 2-process drill with real sockets included; the jax.distributed
chaos smoke stays behind tests/test_distributed.py's slow marker)."""

import importlib.util
import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from dinunet_implementations_tpu.runner.supervisor import (
    Heartbeat,
    SliceSupervisor,
    heartbeat_path,
    mark_slice_alive,
    mark_slice_dead,
)
from dinunet_implementations_tpu.telemetry import assemble, postmortem, report
from dinunet_implementations_tpu.telemetry.bus import MetricsBus, series_key
from dinunet_implementations_tpu.telemetry.collector import (
    LabelCollisionError,
    PodCollector,
    discover_targets,
    merge_snapshots,
    merged_histogram_of,
    parse_series,
    stamp_snapshot,
)
from dinunet_implementations_tpu.telemetry.exporter import StatusExporter
from dinunet_implementations_tpu.telemetry.flight import FlightRecorder
from dinunet_implementations_tpu.telemetry.tracer import SpanTracer

from test_supervisor import _stub_spawn


# ---------------------------------------------------------------------------
# series-key parsing and label stamping
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name,labels", [
    ("plain", {}),
    ("epoch_ms", {"tenant": "studyA", "slice": "0"}),
    ("weird", {"q": 'va"lue', "b": "back\\slash", "n": "new\nline"}),
    ("commas", {"a": "x,y", "z": 'trail,"'}),
])
def test_parse_series_inverts_series_key(name, labels):
    key = series_key(name, labels)
    assert parse_series(key) == (name, labels)


def test_stamp_snapshot_stamps_gauges_and_hists_not_counters():
    bus = MetricsBus()
    bus.counter("reqs_total", 3)
    bus.gauge("epoch", 7, tenant="a")
    bus.observe("epoch_ms", 12.0)
    out = stamp_snapshot(bus.snapshot(), process="0", slice="1")
    assert out["counters"] == {"reqs_total": 3}
    assert set(out["gauges"]) == {
        'epoch{process="0",slice="1",tenant="a"}',
    }
    assert set(out["histograms"]) == {'epoch_ms{process="0",slice="1"}'}


def test_stamp_rejects_identity_spoof_but_passes_equal_values():
    snap = {"counters": {}, "histograms": {},
            "gauges": {'g{process="w0"}': 1.0}}
    with pytest.raises(LabelCollisionError):
        stamp_snapshot(snap, process="w1")
    # restamping the SAME identity is a no-op, not a collision
    out = stamp_snapshot(snap, process="w0")
    assert out["gauges"] == {'g{process="w0"}': 1.0}


# ---------------------------------------------------------------------------
# the exact merge — on REAL scraped snapshots
# ---------------------------------------------------------------------------


def _scrape(port: int) -> dict:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/statusz", timeout=5
    ) as resp:
        return json.loads(resp.read().decode())


def _worker_bus(seed: int) -> MetricsBus:
    bus = MetricsBus()
    bus.counter("epochs_total", 2 + seed)
    bus.gauge("round", 10 * seed)
    for i in range(4 + seed):
        bus.observe("epoch_ms", 5.0 * (i + 1) * (seed + 1))
    return bus


def test_merge_commutative_and_tree_invariant_on_scraped_snapshots():
    buses = [_worker_bus(s) for s in range(3)]
    exporters = [StatusExporter(b) for b in buses]
    try:
        ports = [e.start() for e in exporters]
        snaps = [
            stamp_snapshot(_scrape(p)["metrics"],
                           process=str(i), slice=str(i))
            for i, p in enumerate(ports)
        ]
    finally:
        for e in exporters:
            e.stop()
    a, b, c = snaps
    assert merge_snapshots(a, b) == merge_snapshots(b, a)
    left = merge_snapshots(merge_snapshots(a, b), c)
    right = merge_snapshots(a, merge_snapshots(b, c))
    assert left == right
    # counters summed; the pod histogram holds every worker's samples
    assert left["counters"]["epochs_total"] == sum(
        s["counters"]["epochs_total"] for s in snaps
    )
    pod = merged_histogram_of(left, "epoch_ms")
    assert pod.count == sum(
        merged_histogram_of(s, "epoch_ms").count for s in snaps
    )


def test_merge_rejects_unstamped_gauge_collision():
    a = {"counters": {}, "histograms": {}, "gauges": {"round": 4}}
    b = {"counters": {}, "histograms": {}, "gauges": {"round": 9}}
    with pytest.raises(LabelCollisionError):
        merge_snapshots(a, b)
    # equal values union cleanly (idempotent re-scrape)
    assert merge_snapshots(a, dict(a))["gauges"] == {"round": 4}


# ---------------------------------------------------------------------------
# discovery: heartbeats advertise the scrape plane
# ---------------------------------------------------------------------------


def test_heartbeat_carries_discovery_and_clock_fields(tmp_path):
    path = heartbeat_path(str(tmp_path), 0)
    hb = Heartbeat(path, 0)
    hb.beat(statusz_port=12345, process=0)
    with open(path) as fh:
        pulse = json.load(fh)
    assert pulse["statusz_port"] == 12345 and pulse["process"] == 0
    assert pulse["started_unix"] == hb.started_unix
    # perf/time_unix sampled adjacently: their difference must equal this
    # process's monotonic->wall offset to within scheduling noise
    offset = pulse["time_unix"] - pulse["perf"]
    assert abs(offset - (time.time() - time.perf_counter())) < 1.0
    targets = discover_targets(str(tmp_path))
    assert len(targets) == 1 and targets[0]["pid"] == os.getpid()


def test_discovery_skips_dead_pids_and_portless_pulses(tmp_path):
    dead = subprocess.Popen([sys.executable, "-c", "pass"])
    dead.wait()
    hb_dir = tmp_path / "heartbeats"
    hb_dir.mkdir()
    (hb_dir / "slice_0.json").write_text(json.dumps({
        "pid": dead.pid, "slice": 0, "statusz_port": 1,
        "time_unix": time.time(),
    }))
    (hb_dir / "slice_1.json").write_text(json.dumps({
        "pid": os.getpid(), "slice": 1, "time_unix": time.time(),
    }))  # alive but advertises no port
    assert discover_targets(str(tmp_path)) == []


# ---------------------------------------------------------------------------
# the PodCollector end to end (real heartbeats, real HTTP)
# ---------------------------------------------------------------------------


def test_pod_collector_federates_workers_behind_one_statusz(tmp_path):
    buses = [_worker_bus(0), _worker_bus(1)]
    exporters = [
        StatusExporter(b, statusz=lambda t0=time.time(): {
            "started_unix": t0,
        })
        for b in buses
    ]
    pod_exporter = None
    try:
        for i, e in enumerate(exporters):
            port = e.start()
            Heartbeat(heartbeat_path(str(tmp_path), i), i).beat(
                statusz_port=port, process=i,
            )
        local = MetricsBus()
        local.counter("supervisor_polls_total", 5)
        collector = PodCollector(
            str(tmp_path), local_bus=local,
            local_labels={"process": "supervisor"}, cache_s=0.0,
        )
        snap = collector.snapshot()
        # per-slice series exist AND the pod rollup equals their sum
        for i in range(2):
            key = series_key("epoch_ms", {"process": str(i),
                                          "slice": str(i)})
            assert key in snap["histograms"]
        pod_hist = collector.merged_histogram("epoch_ms")
        assert pod_hist.count == sum(
            b.merged_histogram("epoch_ms").count for b in buses
        )
        assert snap["counters"]["epochs_total"] == sum(
            b.snapshot()["counters"]["epochs_total"] for b in buses
        )
        assert snap["counters"]["supervisor_polls_total"] == 5
        assert snap["gauges"][series_key("pod_scrape_targets", {})] == 2
        assert snap["gauges"][series_key("pod_scrape_errors", {})] == 0
        status = collector.status()
        assert status["mode"] == "pod" and len(status["targets"]) == 2

        # the same exporter implementation serves POD scope: /statusz SLO
        # samples must equal the sum of the per-worker scrapes (one cached
        # collect backs both reads in a single request)
        pod_exporter = StatusExporter(
            collector, statusz=collector.status,
            slo={"histogram": "epoch_ms", "p99_target_ms": 1e6},
        )
        payload = _scrape(pod_exporter.start())
        assert payload["slo"]["samples"] == pod_hist.count
        assert payload["status"]["mode"] == "pod"
        assert series_key(
            "epoch_ms", {"process": "0", "slice": "0"}
        ) in payload["metrics"]["histograms"]
    finally:
        for e in exporters:
            e.stop()
        if pod_exporter is not None:
            pod_exporter.stop()
    # workers gone: the pod view degrades to the reachable subset
    collector.cache_s = 0.0
    collector._cached = None
    got = collector.collect()
    assert got["targets"] == [] and len(got["errors"]) == 2


# ---------------------------------------------------------------------------
# clock alignment + trace assembly
# ---------------------------------------------------------------------------


def test_align_prefers_heartbeat_offset_over_clock_sync():
    clock = {"t0_perf": 100.0, "t0_unix": 5000.0}
    # heartbeat-measured offset wins (fresh), clock_sync is the fallback
    assert assemble.align_unix_us(2e6, clock, offset=900.0) == (
        (900.0 + 100.0) * 1e6 + 2e6
    )
    assert assemble.align_unix_us(2e6, clock) == 5000.0 * 1e6 + 2e6


def test_tracer_clock_sync_row_feeds_the_assembler(tmp_path):
    tr = SpanTracer()
    with tr.span("fit-epoch", trace="t1"):
        pass
    path = str(tmp_path / "trace.jsonl")
    tr.write_jsonl(path)
    clock, events = assemble.load_trace(path)
    assert clock["pid"] == os.getpid()
    assert isinstance(clock["t0_perf"], float)
    assert isinstance(clock["t0_unix"], float)
    assert any(e.get("trace") == "t1" for e in events)


def _fake_trace(path, pid, t0_unix, trace_id, name="dcn-epoch"):
    rows = [
        {"ph": "M", "name": "clock_sync", "pid": pid,
         "t0_perf": 50.0 + pid, "t0_unix": t0_unix},
        {"ph": "X", "name": name, "ts": 1000.0, "dur": 500.0,
         "trace": trace_id, "tid": 0},
        {"ph": "i", "name": "pulse", "ts": 2000.0, "trace": trace_id},
    ]
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        for r in rows:
            fh.write(json.dumps(r) + "\n")


def test_assemble_merges_processes_onto_one_timeline(tmp_path):
    pod = str(tmp_path)
    _fake_trace(os.path.join(pod, "pod_trace", "trace_p0.jsonl"),
                pid=111, t0_unix=1000.0, trace_id="abc")
    _fake_trace(os.path.join(pod, "pod_trace", "trace_p1.jsonl"),
                pid=222, t0_unix=2000.0, trace_id="abc")
    # pid 111 has a heartbeat: offset = time_unix - perf = 1500 - 50, so
    # its wall zero is offset + t0_perf = 1450 + 161... exercised below
    hb_dir = os.path.join(pod, "heartbeats")
    os.makedirs(hb_dir)
    with open(os.path.join(hb_dir, "slice_0.json"), "w") as fh:
        json.dump({"pid": 111, "slice": 0, "perf": 50.0,
                   "time_unix": 1500.0}, fh)
    out = os.path.join(pod, "pod_trace", "pod.chrome.json")
    payload = assemble.assemble(pod, out)
    assert os.path.exists(out)
    srcs = {s["pid"]: s for s in payload["metadata"]["sources"]}
    assert srcs[111]["aligned_by"] == "heartbeat"
    assert srcs[222]["aligned_by"] == "clock_sync"
    shared = assemble.processes_by_trace(payload)
    assert shared["abc"] == {111, 222}
    ts = [e["ts"] for e in payload["traceEvents"] if "ts" in e]
    assert min(ts) == 0.0 and all(t >= 0.0 for t in ts)
    # the CLI gate passes: a trace id spans two processes
    assert assemble.main([pod, "--require-cross-process"]) == 0


def test_assemble_cli_fails_without_cross_process_visibility(tmp_path):
    pod = str(tmp_path)
    _fake_trace(os.path.join(pod, "pod_trace", "trace_p0.jsonl"),
                pid=111, t0_unix=1000.0, trace_id="only-one")
    assert assemble.main([pod, "--require-cross-process"]) == 1
    assert assemble.main([pod]) == 0


# ---------------------------------------------------------------------------
# postmortem reconstruction
# ---------------------------------------------------------------------------


def _fabricated_incident(tmp_path) -> str:
    pod = str(tmp_path)
    liveness = os.path.join(pod, "slice_liveness")
    mark_slice_dead(liveness, 1, "exit rc=-9 (signal 9)",
                    heartbeat_age=0.4, generation=1)
    mark_slice_alive(liveness, 1, 2)
    os.makedirs(os.path.join(pod, "consensus"))
    with open(os.path.join(pod, "consensus",
                           "decision_gen1.json"), "w") as fh:
        json.dump({"time_unix": time.time(), "generation": 1,
                   "dead_slice": 1, "round": 14, "epoch": 7,
                   "sha": "abc123", "replaced": True}, fh)
    with open(os.path.join(pod, "grants.jsonl"), "w") as fh:
        fh.write(json.dumps({"time_unix": time.time(), "tick": 3,
                             "grants": {"a": 2}, "preempt_pause_ms": 0.0})
                 + "\n")
    flight = FlightRecorder(pod)
    flight.note("slice-death", slice=1, generation=1)
    flight.dump("slice-death:slice=1")
    Heartbeat(heartbeat_path(pod, 0), 0).beat(epoch=7, round=14)
    return pod


def test_postmortem_orders_all_sources_and_names_the_incident(tmp_path):
    pod = _fabricated_incident(tmp_path)
    rows = postmortem.build_timeline(pod)
    assert [r["t_unix"] for r in rows] == sorted(
        r["t_unix"] for r in rows
    )
    assert {"liveness", "consensus", "scheduler", "heartbeat",
            f"flight:{os.getpid()}"} <= {r["source"] for r in rows}
    inc = postmortem.incident_summary(rows)
    assert inc["killed_slice"] == 1
    assert inc["consensus_round"] == 14
    assert inc["restart_generation"] == 2
    assert postmortem.validate_timeline(rows) == []
    json_out = str(tmp_path / "pm.json")
    assert postmortem.main([pod, "--validate", "--json", json_out]) == 0
    with open(json_out) as fh:
        dumped = json.load(fh)
    assert dumped["incident"]["killed_slice"] == 1


def test_postmortem_validate_fails_on_unfinished_story(tmp_path):
    # a death with no revival and no give-up cannot be narrated
    mark_slice_dead(os.path.join(str(tmp_path), "slice_liveness"),
                    1, "exit rc=-9 (signal 9)", generation=1)
    assert postmortem.main([str(tmp_path), "--validate"]) == 1
    assert postmortem.main([str(tmp_path)]) == 0  # rendering never gates


def test_postmortem_validates_the_supervised_sigkill_drill(tmp_path):
    """The acceptance drill at tier-1 scale: a real SliceSupervisor run
    over stub workers where slice 1 SIGKILLs itself mid-epoch, flight-
    recorded for real — the postmortem must reconstruct killed slice,
    consensus round and restart generation from the directory alone."""
    flight = FlightRecorder(str(tmp_path))

    def on_consensus(generation, dead_slice):
        # persist the decision like dcn_worker's install_consensus does
        os.makedirs(os.path.join(str(tmp_path), "consensus"),
                    exist_ok=True)
        with open(os.path.join(
            str(tmp_path), "consensus", f"decision_gen{generation}.json"
        ), "w") as fh:
            json.dump({"time_unix": time.time(),
                       "generation": generation,
                       "dead_slice": dead_slice, "round": 6,
                       "epoch": 3, "sha": "drill", "replaced": True}, fh)

    sup = SliceSupervisor(
        _stub_spawn(tmp_path, die_rank=1), num_processes=2,
        out_dir=str(tmp_path), heartbeat_timeout_s=10.0, max_restarts=2,
        poll_s=0.1, grace_s=5.0, flight=flight, on_consensus=on_consensus,
    )
    assert sup.run() == 0
    flight.dump("supervisor-exit:rc=0")
    assert postmortem.main([str(tmp_path), "--validate"]) == 0
    inc = postmortem.incident_summary(
        postmortem.build_timeline(str(tmp_path))
    )
    assert inc["killed_slice"] == 1
    assert "signal 9" in inc["death_reason"]
    assert inc["consensus_round"] == 6
    assert inc["restart_generation"] == 2


# ---------------------------------------------------------------------------
# report: multi-dir invocation + per-tenant rollup
# ---------------------------------------------------------------------------


def _fit_dir(tmp_path, name, tenant):
    d = tmp_path / name
    d.mkdir()
    (d / "manifest.json").write_text(json.dumps({
        "task_id": name, "agg_engine": "dSGD", "num_sites": 4,
        "tags": {"tenant": tenant} if tenant else None,
    }))
    rows = [
        {"kind": "epoch", "epoch": 0, "rounds": 2, "transfer_bytes": 256,
         "site_grad_sq_last": [], "site_grad_sq_sum": [],
         "site_residual_sq_sum": []},
        {"kind": "summary", "epoch_compiles": 1},
    ]
    (d / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows)
    )
    return str(d)


def test_report_multi_dir_renders_per_tenant_rollup(tmp_path, capsys):
    d1 = _fit_dir(tmp_path, "fold_0", "studyA")
    d2 = _fit_dir(tmp_path, "fold_1", "studyA")
    d3 = _fit_dir(tmp_path, "fold_2", "studyB")
    assert report.main([d1, d2, d3]) == 0
    out = capsys.readouterr().out
    assert "per-tenant rollup" in out
    rollup = report.tenant_rollup([d1, d2, d3])
    by_tenant = {r["tenant"]: r for r in rollup}
    assert by_tenant["studyA"]["fits"] == 2
    assert by_tenant["studyA"]["epochs"] == 2
    assert by_tenant["studyA"]["transfer_bytes"] == 512
    assert by_tenant["studyB"]["fits"] == 1
    # single-dir invocations keep the old terse output (no rollup)
    report.main([d1])
    assert "per-tenant rollup" not in capsys.readouterr().out


# ---------------------------------------------------------------------------
# scripts/bench_diff.py
# ---------------------------------------------------------------------------


def _bench_diff_mod():
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "scripts", "bench_diff.py")
    spec = importlib.util.spec_from_file_location("bench_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_line(rate, arm=None, **identity):
    rec = {"metric": "samples/sec", "unit": "samples/sec",
           "samples_per_sec": {"value": rate, "median": rate,
                               "min": rate * 0.9, "observations": 3,
                               "spread": rate * 0.1},
           **identity}
    if arm is not None:
        rec["arm"] = arm
    return json.dumps(rec) + "\n"


def test_bench_diff_pairs_by_arm_and_identity(tmp_path, capsys):
    bd = _bench_diff_mod()
    base = tmp_path / "base.jsonl"
    cand = tmp_path / "cand.jsonl"
    base.write_text(
        "bench: warming up\n"  # human banner lines must be skipped
        + _bench_line(100.0, arm="dsgd")
        + _bench_line(50.0, engine="rankDAD", sites=8, pack_factor=1)
        + _bench_line(70.0, engine="rankDAD", sites=32, pack_factor=4)
    )
    cand.write_text(
        _bench_line(110.0, arm="dsgd")
        + _bench_line(40.0, engine="rankDAD", sites=8, pack_factor=1)
        + _bench_line(70.0, engine="powerSGD", sites=32, pack_factor=4)
    )
    assert bd.main([str(base), str(cand), "--min-pairs", "2"]) == 0
    out = capsys.readouterr().out
    assert "2 paired" in out
    assert "+10.00" in out and "-20.00" in out
    assert "baseline-only: engine=rankDAD sites=32" in out
    assert "candidate-only: engine=powerSGD sites=32" in out
    # the structural gate: too few pairs fails
    assert bd.main([str(base), str(cand), "--min-pairs", "3"]) == 1
    # the regression gate: -20% on the rankDAD pair trips a 10% limit
    assert bd.main([str(base), str(cand), "--max-regress", "10"]) == 1
    assert bd.main([str(base), str(cand), "--max-regress", "25"]) == 0


def test_bench_diff_stat_selection(tmp_path):
    bd = _bench_diff_mod()
    rec = {"metric": "m", "unit": "u", "arm": "a",
           "samples_per_sec": {"value": 90.0, "median": 100.0,
                               "min": 80.0, "spread": 5.0}}
    base = tmp_path / "b.jsonl"
    cand = tmp_path / "c.jsonl"
    base.write_text(json.dumps(rec) + "\n")
    cand.write_text(json.dumps(rec) + "\n")
    pairs, _, _ = bd.pair_records(
        bd.load_records(str(base)), bd.load_records(str(cand))
    )
    assert bd.diff_rows(pairs, "median")[0]["base"] == 100.0
    assert bd.diff_rows(pairs, "value")[0]["base"] == 90.0
    assert bd.diff_rows(pairs, "min")[0]["base"] == 80.0


# ---------------------------------------------------------------------------
# the scheduler grant log feeds the postmortem plane
# ---------------------------------------------------------------------------


def test_scheduler_grant_log_format(tmp_path):
    from dinunet_implementations_tpu.runner.scheduler import FleetScheduler

    sched = object.__new__(FleetScheduler)
    sched.root = str(tmp_path)
    sched.ticks = 7
    sched._log_grants({"a": 2, "b": 1}, 12.5)
    rows = postmortem._grant_rows(str(tmp_path))
    assert len(rows) == 1
    assert rows[0]["event"] == "grants" and rows[0]["tick"] == 7
    assert rows[0]["grants"] == {"a": 2, "b": 1}
    assert rows[0]["preempt_pause_ms"] == 12.5
