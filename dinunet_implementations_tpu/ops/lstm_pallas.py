"""Fused Pallas TPU kernel for the LSTM recurrence (forward + BPTT backward).

The ICA-LSTM's hot loop (SURVEY.md §3.4) is the time recurrence: per step a
small ``h @ W_hh`` matmul plus gate math. The XLA scan path (models/icalstm.py)
already hoists the input projection; this kernel goes further and keeps the
carry (h, c) and all four recurrence matrices resident in VMEM across the
whole sequence, streaming per-step inputs/outputs HBM↔VMEM via the grid
pipeline — no per-step HBM round trip for the carry, no per-step kernel
launches.

Layout choice: gates live in four separate ``[T, B, H]`` arrays (not one
``[T, B, 4H]``) so every block's lane dimension is H and no slice ever crosses
a lane boundary (Mosaic-friendly; see pallas_guide.md pitfall #2).

Grid: ``(batch_tiles, T)`` — TPU grids execute sequentially, so VMEM scratch
carries (h, c) across the T dimension; time-reversed index maps drive the
backward kernel.

Two measured design points (flagship shape, 32 vmapped sites, v5e):

- **dW lives OUTSIDE the kernel.** The weight gradient is the only cross-row
  reduction in BPTT; accumulating it in-kernel forced 4 extra outer-product
  dots per backward step AND made the kernel's outputs non-row-wise. Instead
  the backward kernel streams out the gate pre-activation cotangents (which
  are the dxi outputs anyway) and dW is one XLA einsum over the saved hidden
  sequence — a large, MXU-shaped batched matmul.
- **vmap folds into kernel rows, not grid steps.** jax's default vmap rule
  for ``pallas_call`` prepends a grid dimension, which executes
  SEQUENTIALLY on a TPU core — 32 vmapped sites ran as 32 serial passes of
  [16, H] matmuls. Both kernel entry points carry a ``custom_vmap`` rule that
  folds the mapped axis into the batch-row dimension instead ([512, H]
  matmuls, full MXU rows), padding rows to the kernel tile as needed. The
  fold is valid because every kernel output is row-wise (see previous point).

Semantics: standard LSTM gates (single sigmoid). The reference's
double-sigmoid quirk mode stays on the XLA scan path (models/icalstm.py) —
the kernel is the fast path for the default configuration.
``compute_dtype=bfloat16`` runs the matmuls in bf16 with f32 accumulation;
``None`` (default) is full f32, bit-comparable with the scan path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.custom_batching import custom_vmap
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B_TILE = 128


def _interpret() -> bool:
    # Pallas TPU kernels run in interpreter mode on CPU (tests / simulators)
    return jax.default_backend() == "cpu"


def _cdt_name(compute_dtype) -> str | None:
    return jnp.dtype(compute_dtype).name if compute_dtype is not None else None


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(xi_i, xi_f, xi_o, xi_g, w, h0, c0, hs, cs, ai, af, ao, ag, h_s, c_s):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        h_s[:] = h0[:]
        c_s[:] = c0[:]

    h = h_s[:].astype(w.dtype)  # matmul in w's dtype (f32 or bf16), f32 accum
    # preact_k = xi_k[t] + h @ W_k   (W resident in VMEM, [4, H, H]).
    # xi streams may be bf16 (halved HBM traffic); gate math is f32 — the
    # dot's preferred_element_type upcasts, xi upcasts via astype.
    f32 = jnp.float32
    i = jax.nn.sigmoid(xi_i[0].astype(f32) + jnp.dot(h, w[0], preferred_element_type=f32))
    f = jax.nn.sigmoid(xi_f[0].astype(f32) + jnp.dot(h, w[1], preferred_element_type=f32))
    o = jax.nn.sigmoid(xi_o[0].astype(f32) + jnp.dot(h, w[2], preferred_element_type=f32))
    g = jnp.tanh(xi_g[0].astype(f32) + jnp.dot(h, w[3], preferred_element_type=f32))
    c = f * c_s[:] + i * g
    h = o * jnp.tanh(c)
    h_s[:] = h          # carries stay f32 in VMEM across the whole sequence
    c_s[:] = c
    hs[0] = h.astype(hs.dtype)   # streamed outputs may be bf16
    cs[0] = c.astype(cs.dtype)
    ai[0] = i.astype(ai.dtype)
    af[0] = f.astype(af.dtype)
    ao[0] = o.astype(ao.dtype)
    ag[0] = g.astype(ag.dtype)


def _fwd_call(xi4, w4, h0, c0, compute_dtype=None):
    T, B, H = xi4[0].shape
    bt = min(B_TILE, B)
    assert B % bt == 0, (
        f"batch {B} must be a multiple of the kernel tile {bt}; "
        "use lstm_forward(), which pads"
    )
    if compute_dtype is not None:
        # mixed precision: matmuls AND the streamed [T, B, H] arrays (the
        # kernel's bandwidth bottleneck) run at compute_dtype; the recurrence
        # carries and all accumulation stay f32 in VMEM
        w4 = w4.astype(compute_dtype)
        xi4 = tuple(a.astype(compute_dtype) for a in xi4)
    grid = (B // bt, T)
    t_block = lambda b, t: (t, b, 0)
    b_block = lambda b, t: (b, 0)
    spec_t = pl.BlockSpec((1, bt, H), t_block, memory_space=pltpu.VMEM)
    spec_b = pl.BlockSpec((bt, H), b_block, memory_space=pltpu.VMEM)
    spec_w = pl.BlockSpec((4, H, H), lambda b, t: (0, 0, 0), memory_space=pltpu.VMEM)
    stream_dtype = jnp.dtype(compute_dtype) if compute_dtype is not None else jnp.float32
    out_shape = jax.ShapeDtypeStruct((T, B, H), stream_dtype)
    outs = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[spec_t] * 4 + [spec_w, spec_b, spec_b],
        out_specs=[spec_t] * 6,
        out_shape=[out_shape] * 6,
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32)] * 2,
        interpret=_interpret(),
    )(*xi4, w4, h0, c0)
    return outs  # hs, cs, i, f, o, g


# ---------------------------------------------------------------------------
# backward (dW is computed OUTSIDE the kernel — see module docstring)
# ---------------------------------------------------------------------------


def _bwd_kernel(
    T_total,
    ai, af, ao, ag, cs, cs_prev, w, c0, dhs, dhT, dcT,
    dxi_i, dxi_f, dxi_o, dxi_g, dh0, dc0,
    dh_s, dc_s,
):
    t = pl.program_id(1)  # 0..T-1, walking time backwards: time = T-1-t
    first_time = t == 0  # time T-1
    last_time = t == T_total - 1  # time 0

    @pl.when(first_time)
    def _():
        # seed the carries with the terminal-state cotangents (exact dcT/dhT);
        # re-seeded at the start of every batch tile (per-tile state)
        dh_s[:] = dhT[:].astype(jnp.float32)
        dc_s[:] = dcT[:].astype(jnp.float32)

    f32 = jnp.float32
    i, f, o, g = (ai[0].astype(f32), af[0].astype(f32),
                  ao[0].astype(f32), ag[0].astype(f32))
    c = cs[0].astype(f32)
    c_prev = jnp.where(last_time, c0[:].astype(f32), cs_prev[0].astype(f32))

    tanh_c = jnp.tanh(c)
    dh = dhs[0].astype(f32) + dh_s[:]
    do = dh * tanh_c
    dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_s[:]
    di = dc * g
    df = dc * c_prev
    dg = dc * i

    dpi = di * i * (1.0 - i)
    dpf = df * f * (1.0 - f)
    dpo = do * o * (1.0 - o)
    dpg = dg * (1.0 - g * g)

    dxi_i[0] = dpi.astype(dxi_i.dtype)
    dxi_f[0] = dpf.astype(dxi_f.dtype)
    dxi_o[0] = dpo.astype(dxi_o.dtype)
    dxi_g[0] = dpg.astype(dxi_g.dtype)

    # dh_{t-1} = Σ_k dp_k @ W_kᵀ  (matmuls in w's dtype, f32 accumulation)
    cdt = w.dtype
    dh_prev = (
        jnp.dot(dpi.astype(cdt), w[0].T, preferred_element_type=jnp.float32)
        + jnp.dot(dpf.astype(cdt), w[1].T, preferred_element_type=jnp.float32)
        + jnp.dot(dpo.astype(cdt), w[2].T, preferred_element_type=jnp.float32)
        + jnp.dot(dpg.astype(cdt), w[3].T, preferred_element_type=jnp.float32)
    )

    dh_s[:] = dh_prev
    dc_s[:] = dc * f

    @pl.when(last_time)
    def _():
        dh0[:] = dh_s[:].astype(dh0.dtype)
        dc0[:] = dc_s[:].astype(dc0.dtype)


def _bwd_call(acts, cs, w4, c0, dhs, dhT, dcT, compute_dtype=None):
    T, B, H = cs.shape
    bt = min(B_TILE, B)
    assert B % bt == 0, f"batch {B} must be a multiple of the kernel tile {bt}"
    if compute_dtype is not None:
        w4 = w4.astype(compute_dtype)
    grid = (B // bt, T)

    rev = lambda b, t: (T - 1 - t, b, 0)
    b_block = lambda b, t: (b, 0)
    spec_rev = pl.BlockSpec((1, bt, H), rev, memory_space=pltpu.VMEM)
    spec_prev = pl.BlockSpec(
        (1, bt, H), lambda b, t: (jnp.maximum(T - 2 - t, 0), b, 0),
        memory_space=pltpu.VMEM,
    )
    spec_b = pl.BlockSpec((bt, H), b_block, memory_space=pltpu.VMEM)
    spec_w = pl.BlockSpec((4, H, H), lambda b, t: (0, 0, 0), memory_space=pltpu.VMEM)
    # dxi dtype must match the xi primal dtype (= the streamed act dtype);
    # dh0/dc0 match the f32 h0/c0 primals
    t_shape = jax.ShapeDtypeStruct((T, B, H), acts[0].dtype)
    b_shape = jax.ShapeDtypeStruct((B, H), jnp.float32)

    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, T),
        grid=grid,
        in_specs=[spec_rev] * 4  # i, f, o, g
        + [spec_rev, spec_prev, spec_w, spec_b, spec_rev, spec_b, spec_b],
        out_specs=[spec_rev] * 4 + [spec_b, spec_b],
        out_shape=[t_shape] * 4 + [b_shape, b_shape],
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32)] * 2,
        interpret=_interpret(),
    )(*acts, cs, cs, w4, c0, dhs, dhT, dcT)
    return outs  # dxi_i, dxi_f, dxi_o, dxi_g, dh0, dc0


# ---------------------------------------------------------------------------
# vmap folding: mapped axes become kernel batch rows, not serial grid steps
# ---------------------------------------------------------------------------


def _broadcast_unbatched(args, in_batched, axis_size):
    return [
        a if b else jnp.broadcast_to(a[None], (axis_size,) + a.shape)
        for a, b in zip(args, in_batched)
    ]


def _fold_rows(a):
    """[S, T, B, H] → [T, S*B, H]"""
    S, T, B, H = a.shape
    return jnp.moveaxis(a, 0, 1).reshape(T, S * B, H)


def _unfold_rows(a, S, B):
    """[T, S*B, H] → [S, T, B, H]"""
    T, SB, H = a.shape
    return jnp.moveaxis(a.reshape(T, S, B, H), 1, 0)


def _pad_rows(arrs, rows, axis):
    """Pad the row dim of each array up to a kernel-tile multiple."""
    bt = min(B_TILE, rows)
    pad = (-rows) % bt
    if pad == 0:
        return arrs, rows
    padded = []
    for a in arrs:
        widths = [(0, 0)] * a.ndim
        widths[axis] = (0, pad)
        padded.append(jnp.pad(a, widths))
    return padded, rows + pad


@functools.lru_cache(maxsize=None)
def _fwd_callable(cdt_name: str | None):
    cdt = jnp.dtype(cdt_name) if cdt_name else None

    @custom_vmap
    def f(xi_i, xi_f, xi_o, xi_g, w4, h0, c0):
        return tuple(_fwd_call((xi_i, xi_f, xi_o, xi_g), w4, h0, c0, cdt))

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        if in_batched[4]:  # per-element recurrent weights: cannot fold rows
            batched = _broadcast_unbatched(args, in_batched, axis_size)
            outs = jax.lax.map(lambda a: f(*a), tuple(batched))
            return tuple(outs), (True,) * 6
        S = axis_size
        batched = _broadcast_unbatched(
            args, [b or i == 4 for i, b in enumerate(in_batched)], S
        )
        xi4 = [_fold_rows(a) for a in batched[:4]]
        w4 = args[4]
        B = batched[5].shape[1]
        h0 = batched[5].reshape(S * B, -1)
        c0 = batched[6].reshape(S * B, -1)
        (xi4_0, xi4_1, xi4_2, xi4_3, h0, c0), rows_p = _pad_rows(
            [*xi4, h0, c0], S * B, axis=-2
        )
        outs = f(xi4_0, xi4_1, xi4_2, xi4_3, w4, h0, c0)
        outs = [_unfold_rows(o[:, : S * B], S, B) for o in outs]
        return tuple(outs), (True,) * 6

    return f


@functools.lru_cache(maxsize=None)
def _bwd_callable(cdt_name: str | None):
    cdt = jnp.dtype(cdt_name) if cdt_name else None

    @custom_vmap
    def f(ai, af, ao, ag, cs, w4, c0, dhs, dhT, dcT):
        return tuple(_bwd_call((ai, af, ao, ag), cs, w4, c0, dhs, dhT, dcT, cdt))

    @f.def_vmap
    def _rule(axis_size, in_batched, *args):
        if in_batched[5]:  # per-element weights
            batched = _broadcast_unbatched(args, in_batched, axis_size)
            outs = jax.lax.map(lambda a: f(*a), tuple(batched))
            return tuple(outs), (True,) * 6
        S = axis_size
        batched = _broadcast_unbatched(
            args, [b or i == 5 for i, b in enumerate(in_batched)], S
        )
        t_arrs = [_fold_rows(batched[i]) for i in (0, 1, 2, 3, 4, 7)]
        w4 = args[5]
        B = batched[6].shape[1]
        b_arrs = [batched[i].reshape(S * B, -1) for i in (6, 8, 9)]
        rows = S * B
        (ai, af, ao, ag, cs, dhs), _ = _pad_rows(t_arrs, rows, axis=-2)
        (c0, dhT, dcT), _ = _pad_rows(b_arrs, rows, axis=-2)
        outs = f(ai, af, ao, ag, cs, w4, c0, dhs, dhT, dcT)
        dxi = [_unfold_rows(o[:, :rows], S, B) for o in outs[:4]]
        db = [o[:rows].reshape(S, B, -1) for o in outs[4:]]
        return tuple(dxi + db), (True,) * 6

    return f


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def lstm_recurrence(xi4, w4, h0, c0, compute_dtype=None):
    """Run the LSTM time recurrence.

    Args:
      xi4: tuple of four ``[T, B, H]`` input-projection arrays (i, f, o, g
        pre-activations, i.e. ``x_t @ W_ih + b`` split per gate).
      w4: ``[4, H, H]`` recurrent weights (i, f, o, g order).
      h0, c0: ``[B, H]`` initial carry.
      compute_dtype: matmul operand dtype (e.g. ``jnp.bfloat16``) with f32
        accumulation; ``None`` = full f32 (the parity mode).

    Returns: ``(hs [T, B, H], (hT, cT))``.
    """
    hs, cs, *_ = _fwd_callable(_cdt_name(compute_dtype))(*xi4, w4, h0, c0)
    return hs, (hs[-1], cs[-1])


def _vjp_fwd(xi4, w4, h0, c0, compute_dtype):
    hs, cs, i, f, o, g = _fwd_callable(_cdt_name(compute_dtype))(*xi4, w4, h0, c0)
    # xi4 is NOT needed by the backward (dxi == dpreact); don't pin it. Only
    # its dtype rides along (as a zero-size array — residuals must be JAX
    # types) so the dxi cotangents can be cast back to the primal dtype (a
    # direct caller may pass f32 xi with bf16 compute_dtype; custom_vjp
    # requires cotangent avals to match the primal avals exactly)
    xi_proto = jnp.zeros((0,), xi4[0].dtype)
    return (hs, (hs[-1], cs[-1])), (xi_proto, w4, h0, c0, hs, cs, (i, f, o, g))


def _vjp_bwd(compute_dtype, res, grads):
    xi_proto, w4, h0, c0, hs, cs, acts = res
    xi_dtype = xi_proto.dtype
    dhs, (dhT, dcT) = grads
    cdt_name = _cdt_name(compute_dtype)
    dxi_i, dxi_f, dxi_o, dxi_g, dh0, dc0 = _bwd_callable(cdt_name)(
        *acts, cs, w4, c0, dhs, dhT, dcT
    )
    # dW_k = Σ_t h_{t-1}ᵀ dp_k — the only cross-row reduction of BPTT, done
    # here as one MXU-shaped einsum over the saved hidden sequence instead of
    # per-step outer products inside the kernel (batches cleanly under vmap)
    h_prev = jnp.concatenate([h0[None].astype(hs.dtype), hs[:-1]], 0)  # [T, B, H]
    cdt = jnp.dtype(cdt_name) if cdt_name else h_prev.dtype
    hp = h_prev.astype(cdt)
    dw = jnp.stack(
        [
            jnp.einsum(
                "tbh,tbg->hg", hp, dp.astype(cdt),
                preferred_element_type=jnp.float32,
            )
            for dp in (dxi_i, dxi_f, dxi_o, dxi_g)
        ]
    )
    dxi = tuple(d.astype(xi_dtype) for d in (dxi_i, dxi_f, dxi_o, dxi_g))
    return dxi, dw, dh0, dc0


lstm_recurrence.defvjp(_vjp_fwd, _vjp_bwd)


def lstm_forward(xi, w_hh, h0, c0, compute_dtype=None):
    """Convenience wrapper over :func:`lstm_recurrence` in model layout.

    Args:
      xi: ``[B, T, 4H]`` pre-computed input projections (i|f|o|g blocks —
        the LSTMCell layout, ``x @ W_ih + b_ih + b_hh``).
      w_hh: ``[H, 4H]`` recurrent weight in the same blocked layout.
      h0, c0: ``[B, H]``.
      compute_dtype: matmul dtype for the recurrence (f32 accumulation);
        ``None`` = f32 (parity mode).

    Returns ``(hs [B, T, H], (hT, cT))``. Pads the batch to the kernel tile
    and slices it back off. NOTE on lane alignment: zero-padding the hidden
    width 174 → 256 was tried and MEASURED as an ~11% LOSS on v5e (37.8k →
    33.7k samples/s) — the kernel is bound by streaming the [T, B, H] blocks
    from HBM, and padding inflates that traffic 47% while Mosaic's ragged
    lane-edge masking was already cheap. Hence H is deliberately unpadded.
    """
    B, T, H4 = xi.shape
    H = H4 // 4
    in_dtype = xi.dtype
    # the kernel accumulates in f32 (scratch/accumulators); the streamed xi
    # stays at compute_dtype (its cotangent dxi comes back at the same dtype)
    xi = xi.astype(compute_dtype if compute_dtype is not None else jnp.float32)
    w_hh = w_hh.astype(jnp.float32)
    h0 = h0.astype(jnp.float32)
    c0 = c0.astype(jnp.float32)
    bt = min(B_TILE, B)
    pad = (-B) % bt
    if pad:
        xi = jnp.concatenate([xi, jnp.zeros((pad, T, H4), xi.dtype)], 0)
        h0 = jnp.concatenate([h0, jnp.zeros((pad, H), h0.dtype)], 0)
        c0 = jnp.concatenate([c0, jnp.zeros((pad, H), c0.dtype)], 0)
    xi_t = jnp.swapaxes(xi, 0, 1)  # [T, B, 4H]
    xi4 = tuple(xi_t[..., k * H : (k + 1) * H] for k in range(4))
    w4 = jnp.stack([w_hh[:, k * H : (k + 1) * H] for k in range(4)])
    hs, (hT, cT) = lstm_recurrence(xi4, w4, h0, c0, compute_dtype)
    hs = jnp.swapaxes(hs, 0, 1)
    if pad:
        hs, hT, cT = hs[:B], hT[:B], cT[:B]
    return hs.astype(in_dtype), (hT.astype(in_dtype), cT.astype(in_dtype))
