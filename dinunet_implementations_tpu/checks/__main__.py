"""CLI: ``python -m dinunet_implementations_tpu.checks [paths...]``.

Two tiers behind one gate:

- default: the stdlib-only AST tier (jaxlint, rules R001-R007) over source
  files;
- ``--semantic``: the traced-program tier (jaxprlint, rules S001-S005,
  semantic.py) — traces the real epoch programs for the
  engine × topology × pipeline matrix on CPU virtual devices and verifies
  collective axes, wire-byte models, donation aliasing, precision flow, and
  program identity. Each tier has its own baseline file
  (``baseline.json`` / ``baseline_semantic.json``, both shipped empty).

Exit code 0 when every finding is baselined (or there are none), 1 when new
findings exist — the tier-1/CI gate. ``--baseline`` regenerates the active
tier's baseline from the current findings. ``--format json`` emits one JSON
object per finding (CI artifact); ``--format sarif`` emits a SARIF 2.1.0
document for code-scanning annotation; human text stays the default.
"""

from __future__ import annotations

import argparse
import json
import sys

from .core import (
    DEFAULT_BASELINE,
    PACKAGE_ROOT,
    apply_baseline,
    load_baseline,
    run_checks,
    save_baseline,
)


def _sarif(findings: list, tool: str) -> dict:
    """Minimal SARIF 2.1.0 document — enough for GitHub code-scanning /
    generic SARIF viewers to annotate findings by file/line."""
    rules = sorted({f.rule for f in findings})
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message + (f"\nfix: {f.fixit}" if f.fixit else "")},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {
                        "startLine": max(f.line, 1),
                        "startColumn": f.col + 1,
                    },
                },
            }],
        })
    return {
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                   "master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": tool,
                "informationUri": "https://github.com/trendscenter/"
                                  "dinunet_implementations",
                "rules": [{"id": r} for r in rules],
            }},
            "results": results,
        }],
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dinunet_implementations_tpu.checks",
        description="jaxlint: codebase-specific SPMD-invariant analyzer "
                    "(AST rules R001-R007; --semantic adds the traced-"
                    "program rules S001-S005 — see the checks package and "
                    "semantic.py docstrings).",
    )
    p.add_argument("paths", nargs="*",
                   help="files/directories to scan (default: the installed "
                        "dinunet_implementations_tpu package; ignored with "
                        "--semantic, which traces programs, not files)")
    p.add_argument("--semantic", action="store_true",
                   help="run the semantic tier: trace the real epoch "
                        "programs on CPU and verify collectives/mesh axes "
                        "(S001), wire-byte models (S002), donation aliasing "
                        "(S003), precision flow (S004), and lowering "
                        "identity (S005)")
    p.add_argument("--baseline", action="store_true",
                   help="regenerate the active tier's baseline file from "
                        "the current findings and exit 0")
    p.add_argument("--baseline-file", default=None,
                   help="baseline path (default: the active tier's shipped "
                        f"baseline, e.g. {DEFAULT_BASELINE})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--format", choices=("human", "json", "sarif"),
                   default=None, dest="fmt",
                   help="output format (default: human; json = one object "
                        "per finding, sarif = one SARIF 2.1.0 document)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="(deprecated) same as --format json")
    args = p.parse_args(argv)
    fmt = args.fmt or ("json" if args.as_json else "human")

    if args.semantic:
        # late import: the semantic tier needs jax + virtual CPU devices;
        # the AST tier must stay stdlib-only
        from .semantic import SEMANTIC_BASELINE, run_semantic_checks

        findings = run_semantic_checks()
        default_baseline = SEMANTIC_BASELINE
        tool = "jaxprlint"
    else:
        findings = []
        for root in (args.paths or [PACKAGE_ROOT]):
            findings.extend(run_checks(root))
        default_baseline = DEFAULT_BASELINE
        tool = "jaxlint"
    baseline_file = args.baseline_file or default_baseline

    if args.baseline:
        path = save_baseline(findings, baseline_file)
        print(f"{tool}: wrote {len(findings)} baseline entries to {path}")
        return 0

    baseline = [] if args.no_baseline else load_baseline(baseline_file)
    new, matched = apply_baseline(findings, baseline)
    if fmt == "json":
        for f in new:
            print(json.dumps(f.to_dict()))
    elif fmt == "sarif":
        print(json.dumps(_sarif(new, tool), indent=2))
    else:
        for f in new:
            print(f.format())
    tail = f"{tool}: {len(new)} finding(s)"
    if matched:
        tail += f" ({matched} baselined)"
    print(tail, file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
