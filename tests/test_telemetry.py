"""Telemetry tests (telemetry/): span tracer nesting/closing across the
prefetch thread and on Preempted, the telemetry="off" program-identity
regression, on-device round metrics vs bit-exact host recomputation for dSGD
and rankDAD, manifest/metrics.jsonl schema round-trip, and the report CLI.
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.checks import CompileGuard
from dinunet_implementations_tpu.data.api import SiteArrays
from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.parallel.mesh import SITE_AXIS
from dinunet_implementations_tpu.robustness import FaultPlan, Preempted
from dinunet_implementations_tpu.telemetry import SpanTracer, duration
from dinunet_implementations_tpu.telemetry.metrics import (
    TELEMETRY_KEYS,
    default_round_telemetry,
    payload_bytes_of,
    telemetry_summary,
    tree_sq_sum,
)
from dinunet_implementations_tpu.telemetry.sink import (
    MANIFEST_FILE,
    METRICS_FILE,
    TRACE_CHROME_FILE,
    TRACE_JSONL_FILE,
    load_metrics,
    validate_manifest,
    validate_metrics_rows,
)
from dinunet_implementations_tpu.trainer import (
    FederatedTask,
    FederatedTrainer,
    init_train_state,
    load_checkpoint,
    make_optimizer,
    make_train_epoch_fn,
    save_checkpoint,
)
from dinunet_implementations_tpu.trainer.logs import telemetry_log_fields


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


def test_spans_nest_and_close_across_threads():
    """One tracer serves the main loop AND a worker thread (the prefetch
    planner): spans nest per thread, depths/threads are recorded, and the
    cross-thread events land in one buffer."""
    tracer = SpanTracer()

    def worker():
        for _ in range(2):
            with tracer.span("plan-build"):
                pass

    with tracer.span("fit"):
        t = threading.Thread(target=worker, name="worker")
        with tracer.span("epoch"):
            t.start()
            t.join()
    evs = tracer.events()
    by_name = {e["name"]: e for e in evs}
    assert by_name["fit"]["depth"] == 0
    assert by_name["epoch"]["depth"] == 1  # nested under fit on main thread
    builds = [e for e in evs if e["name"] == "plan-build"]
    assert len(builds) == 2
    assert all(e["depth"] == 0 for e in builds)  # worker has its own stack
    assert builds[0]["tid"] != by_name["fit"]["tid"]
    assert all(e["ok"] for e in evs)
    # inner spans close (are recorded) before their parent
    order = [e["name"] for e in evs]
    assert order.index("epoch") < order.index("fit")


def test_span_closes_on_preempted():
    """Preempted (a BaseException) unwinding through a span still closes it,
    flagged not-ok — the trainer's fit span survives preemption."""
    tracer = SpanTracer()
    with pytest.raises(Preempted):
        with tracer.span("fit"):
            raise Preempted("signal 15 during epoch 2", signum=15, epoch=2)
    (ev,) = tracer.events()
    assert ev["name"] == "fit" and ev["ph"] == "X" and not ev["ok"]


def test_chrome_trace_is_perfetto_loadable_shape(tmp_path):
    tracer = SpanTracer()
    with tracer.span("fit", fold=0):
        tracer.event("checkpoint", epoch=1)
        tracer.counter("queue-depth", 1)
    path = tracer.write_chrome_trace(str(tmp_path / "trace.chrome.json"))
    with open(path) as fh:
        trace = json.load(fh)
    evs = trace["traceEvents"]
    phases = {e["ph"] for e in evs}
    assert {"M", "X", "i", "C"} <= phases
    meta = [e for e in evs if e["ph"] == "M"]
    assert meta[0]["name"] == "thread_name"
    x = next(e for e in evs if e["ph"] == "X")
    assert {"name", "ts", "dur", "pid", "tid"} <= set(x)
    assert x["args"]["fold"] == 0  # span attrs ride the args dict


def test_disabled_tracer_is_noop_and_duration_helper():
    tracer = SpanTracer(enabled=False)
    with tracer.span("fit"):
        tracer.event("x")
    assert tracer.events() == []
    # the ONE reference-keyed duration helper (moved from trainer/logs.py):
    # starts come from the tracer's monotonic clock (perf_counter)
    cache: dict = {}
    import time

    t0 = time.perf_counter()
    d1 = duration(cache, t0, "time_spent_on_computation")
    duration(cache, t0, "time_spent_on_computation")
    assert len(cache["time_spent_on_computation"]) == 2
    assert cache["time_spent_on_computation"][0] == d1 >= 0


def test_duration_survives_stepped_wall_clock(monkeypatch):
    """Regression (r16): ``duration`` read ``time.time()`` while every span
    (and every caller's start) used the monotonic ``perf_counter`` clock —
    an NTP/DST wall-clock step mid-fit corrupted the checkpointed duration
    cache with wildly wrong (even negative) entries. Stepping the wall
    clock by a day in either direction must not perturb the recorded
    durations."""
    import time

    cache: dict = {}
    t0 = time.perf_counter()
    monkeypatch.setattr(time, "time", lambda: 1e9)  # wall clock steps back
    d1 = duration(cache, t0, "time_spent_on_computation")
    monkeypatch.setattr(time, "time", lambda: 4e9)  # ...and jumps forward
    d2 = duration(cache, t0, "time_spent_on_computation")
    assert 0 <= d1 <= d2 < 60  # monotonic, sane magnitudes
    assert cache["time_spent_on_computation"] == [d1, d2]


# ---------------------------------------------------------------------------
# on-device round metrics
# ---------------------------------------------------------------------------


def _epoch_setup(engine_name, S=2, steps=1, B=8, D=6, engine_kw=None,
                 telemetry=True):
    task = FederatedTask(MSANNet(in_size=D, hidden_sizes=(8,), out_size=2))
    engine = make_engine(engine_name, **(engine_kw or {}))
    opt = make_optimizer("adam", 1e-2)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(S, steps, B, D)).astype(np.float32))
    y = jnp.asarray((rng.random((S, steps, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)
    state0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                              x[0, 0], num_sites=S, telemetry=telemetry)
    return task, engine, opt, state0, x, y, w


def _host_recompute_round(task, engine, opt, state, x, y, w):
    """From-scratch mirror of ONE round (local_iterations=1, every site
    live): the same rng derivation, micro-scan accumulation ops, engine
    aggregate, rounds-scan structure and tree_sq_sum reduction order as
    trainer/steps.py. The scan/vmap structure is replicated deliberately —
    XLA's fusion choices depend on it, and a flat re-expression of the same
    math lands 1 ULP away. Returns per-site (grad_sq, residual_sq) and the
    global update_sq."""
    from dinunet_implementations_tpu.trainer.steps import cross_entropy

    S, B = x.shape[0], x.shape[2]

    def loss_fn(params, stats, rng, xb, yb, wb):
        logits, new_stats = task.apply(
            params, stats, xb, train=True, rng=rng, mask=wb, mutable=True
        )
        return cross_entropy(logits, yb, wb), new_stats

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    rng_epoch = jax.random.fold_in(state.rng, state.round)
    _, sub = jax.random.split(rng_epoch)

    def site(es, xb, yb, wb):
        # xb: [L=1, B, ...] — the per-round micro-batch block
        site_ix = jax.lax.axis_index(SITE_AXIS)

        def micro(acc, mb):
            g_sum, n_sum, stats = acc
            xm, ym, wm, i = mb
            key = jax.random.fold_in(jax.random.fold_in(sub, site_ix), i)
            (loss, new_stats), grads = grad_fn(
                state.params, stats, key, xm, ym, wm
            )
            n = wm.sum()
            g_sum = jax.tree.map(lambda a, g: a + g * n, g_sum, grads)
            return (g_sum, n_sum + n, new_stats), loss * n

        g0 = jax.tree.map(jnp.zeros_like, state.params)
        (g_sum, n_sum, _), _ = jax.lax.scan(
            micro, (g0, jnp.zeros(()), state.batch_stats),
            (xb, yb, wb, jnp.arange(1)),
        )
        site_grad = jax.tree.map(
            lambda g: g / jnp.maximum(n_sum, 1.0), g_sum
        )
        # guard is active at the default quarantine_rounds, so the epoch
        # passes live=contribute (1.0 for a healthy site) into aggregate
        agg, _ = engine.aggregate(
            site_grad, es, n_sum, SITE_AXIS, live=jnp.asarray(1.0)
        )
        gsq = tree_sq_sum(site_grad)
        rsq = tree_sq_sum(jax.tree.map(lambda g, a: g - a, site_grad, agg))
        return gsq, rsq, agg

    def mirror(es, x, y, w):
        x_r = x.reshape((S, 1, 1) + x.shape[2:])
        y_r, w_r = y.reshape(S, 1, 1, B), w.reshape(S, 1, 1, B)

        def one_round(carry, xs):
            gsq, rsq, agg = jax.vmap(site, axis_name=SITE_AXIS)(es, *xs)
            agg0 = jax.tree.map(lambda a: a[0], agg)
            updates, _ = opt.update(agg0, state.opt_state, state.params)
            return carry, (gsq, rsq, tree_sq_sum(updates))

        _, (gsq, rsq, usq) = jax.lax.scan(
            one_round, 0,
            tuple(jnp.moveaxis(a, 1, 0) for a in (x_r, y_r, w_r)),
        )
        return gsq[0], rsq[0], usq[0]

    return jax.jit(mirror)(state.engine_state, x, y, w)


@pytest.mark.parametrize("engine_name,engine_kw", [
    ("dSGD", {}),
    ("rankDAD", dict(dad_reduction_rank=4, dad_num_pow_iters=3,
                     dad_tol=0.0)),
])
def test_on_device_metrics_match_host_recompute(engine_name, engine_kw):
    """The acceptance gate: the accumulators the rounds scan maintains equal
    a from-scratch host recomputation of the same quantities BIT-EXACTLY,
    under the CompileGuard (one program per fit)."""
    task, engine, opt, state0, x, y, w = _epoch_setup(
        engine_name, engine_kw=engine_kw
    )
    fn = make_train_epoch_fn(task, engine, opt, mesh=None, telemetry=True)
    guard = CompileGuard({"epoch_fn": fn})
    st, _ = fn(state0, x, y, w)
    t = {k: np.asarray(v) for k, v in st.telemetry.items()}
    gsq, rsq, usq = _host_recompute_round(task, engine, opt, state0, x, y, w)
    np.testing.assert_array_equal(t["grad_sq_last"], np.asarray(gsq))
    np.testing.assert_array_equal(t["grad_sq_sum"], np.asarray(gsq))
    np.testing.assert_array_equal(t["grad_sq_max"], np.asarray(gsq))
    np.testing.assert_array_equal(t["residual_sq_sum"], np.asarray(rsq))
    # Adam's update norm goes through rsqrt chains whose fusion the mirror
    # cannot pin across two distinct programs — held to a couple of ULPs
    # rather than bit-exact (the norms above ARE bit-exact)
    np.testing.assert_array_max_ulp(
        t["update_sq_last"],
        np.full_like(t["update_sq_last"], np.asarray(usq)), maxulp=4,
    )
    assert (t["payload_bytes"] == payload_bytes_of(engine, state0.params)).all()
    assert (t["rounds"] == 1).all()
    # a second chained epoch accumulates (and still compiles nothing new)
    st2, _ = fn(st, x, y, w)
    t2 = {k: np.asarray(v) for k, v in st2.telemetry.items()}
    assert (t2["rounds"] == 2).all()
    np.testing.assert_array_equal(
        t2["grad_sq_sum"], t["grad_sq_sum"] + t2["grad_sq_last"]
    )
    guard.check(context=f"telemetry epoch, engine={engine_name}")


def test_telemetry_off_program_identical_and_outputs_bitwise():
    """telemetry="off" (the default) must compile the exact pre-telemetry
    program: identical lowering to a build that never mentions telemetry,
    state.telemetry stays None, and the on-arm trains bitwise-identically
    (the metrics observe, never perturb). Program identity goes through the
    shared normalized differ (checks/lowering.py) — the parametrized
    off==baseline harness in tests/test_lowering_identity.py and the S005
    semantic gate run the same comparison."""
    from dinunet_implementations_tpu.checks.lowering import diff_report

    task, engine, opt, _, x, y, w = _epoch_setup("dSGD", steps=3,
                                                 telemetry=False)
    state0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                              x[0, 0], num_sites=2, telemetry=False)
    fn_off = make_train_epoch_fn(task, engine, opt, mesh=None,
                                 telemetry=False)
    fn_default = make_train_epoch_fn(task, engine, opt, mesh=None)
    report = diff_report(
        fn_off.lower(state0, x, y, w).as_text(),
        fn_default.lower(state0, x, y, w).as_text(),
        "telemetry=False", "default-build",
    )
    assert report is None, report
    st_off, losses_off = fn_off(state0, x, y, w)
    assert st_off.telemetry is None
    state_t = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                               x[0, 0], num_sites=2, telemetry=True)
    fn_on = make_train_epoch_fn(task, engine, opt, mesh=None, telemetry=True)
    st_on, losses_on = fn_on(state_t, x, y, w)
    np.testing.assert_array_equal(
        np.asarray(losses_off), np.asarray(losses_on)
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        st_off.params, st_on.params,
    )
    # an off-program fed a telemetry-carrying state drops the accumulators
    # (trace-time normalization), keeping the legacy program
    st_mixed, _ = fn_off(state_t, x, y, w)
    assert st_mixed.telemetry is None


def test_nonfinite_round_poisons_last_not_sums():
    """A NaN round shows in grad_sq_last (the blow-up signal) but is
    excluded from the sum/max accumulators, which must stay usable."""
    task, engine, opt, state0, x, y, w = _epoch_setup("dSGD", steps=2)
    x = x.at[1, 1].set(jnp.nan)  # site 1's second round is poisoned
    fn = make_train_epoch_fn(task, engine, opt, mesh=None, telemetry=True)
    st, _ = fn(state0, x, y, w)
    t = {k: np.asarray(v) for k, v in st.telemetry.items()}
    assert np.isnan(t["grad_sq_last"][1])
    assert np.isfinite(t["grad_sq_last"][0])
    assert np.isfinite(t["grad_sq_sum"]).all()
    assert np.isfinite(t["grad_sq_max"]).all()


def test_telemetry_checkpoint_roundtrip(tmp_path):
    """TrainState.telemetry rides the checkpoint (R006 enforces the schema
    statically; this is the dynamic round-trip)."""
    task, engine, opt, state0, x, y, w = _epoch_setup("dSGD", steps=2)
    fn = make_train_epoch_fn(task, engine, opt, mesh=None, telemetry=True)
    st, _ = fn(state0, x, y, w)
    p = str(tmp_path / "ckpt.msgpack")
    save_checkpoint(p, st)
    fresh = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                             x[0, 0], num_sites=2, telemetry=True)
    restored = load_checkpoint(p, fresh)
    for k in TELEMETRY_KEYS:
        np.testing.assert_array_equal(
            np.asarray(st.telemetry[k]), np.asarray(restored.telemetry[k])
        )
    # a telemetry-off resume tolerates the stored accumulators (dropped)
    fresh_off = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                                 x[0, 0], num_sites=2, telemetry=False)
    assert load_checkpoint(p, fresh_off).telemetry is None


# ---------------------------------------------------------------------------
# the fit-level artifact pipeline
# ---------------------------------------------------------------------------


def _toy_sites(ns, n=24, d=6, seed=0):
    out = []
    rng = np.random.default_rng(seed)
    for _ in range(ns):
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X.sum(-1) > 0).astype(np.int32)
        out.append(SiteArrays(X, y, np.arange(n, dtype=np.int32)))
    return out


def _fit(cfg, out_dir, fault_plan=None):
    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    tr = FederatedTrainer(cfg, model, mesh=None, out_dir=out_dir,
                          fault_plan=fault_plan)
    res = tr.fit(_toy_sites(2), _toy_sites(2, n=16, seed=9),
                 _toy_sites(2, n=16, seed=5), verbose=False)
    return tr, res


def test_fit_emits_schema_valid_artifacts(tmp_path):
    """A telemetry="on" fit leaves manifest.json + metrics.jsonl + both
    trace forms, all schema-valid, with exactly one epoch compile and the
    prefetch thread's plan-build spans in the trace."""
    cfg = TrainConfig(epochs=3, batch_size=8, patience=50, telemetry="on")
    tr, res = _fit(cfg, str(tmp_path))
    d = tmp_path / "telemetry" / "fold_0"
    with open(d / MANIFEST_FILE) as fh:
        manifest = json.load(fh)
    assert validate_manifest(manifest) == []
    assert manifest["agg_engine"] == "dSGD"
    assert manifest["num_sites"] == 2
    assert manifest["jax_version"] == jax.__version__
    rows = load_metrics(str(d / METRICS_FILE))
    assert validate_metrics_rows(rows) == []
    epochs = [r for r in rows if r["kind"] == "epoch"]
    assert [r["epoch"] for r in epochs] == [1, 2, 3]
    assert all(len(r["site_grad_sq_last"]) == 2 for r in epochs)
    assert all(r["transfer_bytes"] > 0 for r in epochs)
    (summary,) = [r for r in rows if r["kind"] == "summary"]
    assert summary["epoch_compiles"] == 1  # CompileGuard invariant, recorded
    assert summary["epochs_run"] == 3
    assert "prefetch_stall_s" in summary
    # trace: both forms parse; plan-build ran on the prefetch thread
    spans = [json.loads(ln) for ln in open(d / TRACE_JSONL_FILE)]
    names = {e["name"] for e in spans if e["ph"] == "X"}
    assert {"fit", "epoch", "eval", "plan-build", "test"} <= names
    main_tid = next(e["tid"] for e in spans if e["name"] == "fit")
    build_threads = {
        e["thread"] for e in spans if e["name"] == "plan-build"
    }
    assert build_threads == {"dinunet-epoch-prefetch"}
    assert all(e["tid"] != main_tid for e in spans
               if e["name"] == "plan-build")
    with open(d / TRACE_CHROME_FILE) as fh:
        chrome = json.load(fh)
    assert isinstance(chrome["traceEvents"], list) and chrome["traceEvents"]
    # the results dict carries the rollup
    assert len(res["site_telemetry"]["site_grad_norm_last"]) == 2


def test_logs_json_telemetry_fields_roundtrip(tmp_path):
    """Satellite contract: write_logs_json surfaces the per-site grad-norm
    rollup next to health_log_fields — remote lists, per-site scalars —
    and the values round-trip through the JSON."""
    cfg = TrainConfig(epochs=2, batch_size=8, patience=50, telemetry="on")
    _, res = _fit(cfg, str(tmp_path))
    remote = json.load(open(
        tmp_path / "remote/simulatorRun/FS-Classification/fold_0/logs.json"))
    rollup = res["site_telemetry"]
    assert remote["site_grad_norm_last"] == rollup["site_grad_norm_last"]
    assert remote["site_grad_norm_max"] == rollup["site_grad_norm_max"]
    assert remote["site_residual_norm_mean"] == rollup["site_residual_norm_mean"]
    assert remote["update_norm_last"] == rollup["update_norm_last"]
    # health fields still present next to them (the "next to" contract)
    assert "site_skipped_rounds" in remote
    local1 = json.load(open(
        tmp_path / "local1/simulatorRun/FS-Classification/fold_0/logs.json"))
    assert local1["grad_norm_last"] == rollup["site_grad_norm_last"][1]
    assert local1["grad_norm_mean"] == rollup["site_grad_norm_mean"][1]
    # helper symmetry on the same rollup dict
    assert telemetry_log_fields(rollup)["site_grad_norm_last"] == \
        rollup["site_grad_norm_last"]
    assert telemetry_log_fields(None) == {}


def test_telemetry_off_fit_writes_nothing(tmp_path):
    cfg = TrainConfig(epochs=2, batch_size=8, patience=50)  # default off
    tr, res = _fit(cfg, str(tmp_path))
    assert not (tmp_path / "telemetry").exists()
    assert "site_telemetry" not in res
    remote = json.load(open(
        tmp_path / "remote/simulatorRun/FS-Classification/fold_0/logs.json"))
    assert "site_grad_norm_last" not in remote


def test_preempted_fit_still_finalizes_artifacts(tmp_path):
    """A FaultPlan kill mid-fit raises Preempted through the trainer — the
    sink's finally still writes the trace files, the preempted event is in
    metrics.jsonl, and the fit span is closed (ok=false)."""
    cfg = TrainConfig(epochs=4, batch_size=8, patience=50, telemetry="on")
    with pytest.raises(Preempted):
        # 24 samples / batch 8 → 3 rounds/epoch; kill inside epoch 2
        _fit(cfg, str(tmp_path), fault_plan=FaultPlan(kill_at_round=4))
    d = tmp_path / "telemetry" / "fold_0"
    rows = load_metrics(str(d / METRICS_FILE))
    assert validate_metrics_rows(rows) == []
    assert any(
        r["kind"] == "event" and r["name"] == "preempted" for r in rows
    )
    (summary,) = [r for r in rows if r["kind"] == "summary"]
    assert summary["epochs_run"] == 2
    spans = [json.loads(ln) for ln in open(d / TRACE_JSONL_FILE)]
    fit_span = next(e for e in spans if e["name"] == "fit")
    assert fit_span["ok"] is False


@pytest.mark.slow
def test_xprof_window_captures_epoch_range(tmp_path):
    """--xprof-dir: the jax.profiler capture brackets exactly the
    configured epoch window of a real fit and finalizes its trace file.

    Slow tier: a full 3-epoch fit under the profiler (~40s on the CPU
    container) — well past the >~10s line the ``slow`` marker draws.
    """
    from dinunet_implementations_tpu.telemetry.xprof import trace_files

    cfg = TrainConfig(epochs=3, batch_size=8, patience=50,
                      xprof_dir=str(tmp_path / "xprof"),
                      xprof_window=(2, 2))
    _fit(cfg, str(tmp_path / "out"))
    assert trace_files(str(tmp_path / "xprof" / "fold_0"))


def test_xprof_window_fires_when_resume_starts_inside_it(tmp_path):
    """A resumed fit whose start epoch lands INSIDE the window (preempted
    mid-window) must still capture the remaining windowed epochs."""
    from dinunet_implementations_tpu.telemetry.xprof import (
        XprofWindow,
        trace_files,
    )

    w = XprofWindow(str(tmp_path), (2, 3))
    f = jax.jit(lambda x: x + 1)
    w.epoch_begin(3)  # resume skipped epochs 1-2
    f(jnp.ones(4)).block_until_ready()
    w.epoch_end(3)
    w.close()
    assert trace_files(str(tmp_path))


def test_metrics_jsonl_is_strict_json(tmp_path):
    """NaN rides the metrics rows by design (the blow-up signal), but the
    emitted JSONL must be strict RFC 8259 — non-finite reals become null,
    never a bare NaN/Infinity token that breaks JSON.parse/jq."""
    from dinunet_implementations_tpu.telemetry.sink import FitTelemetry

    sink = FitTelemetry(str(tmp_path), SpanTracer(enabled=False))
    sink.append({"kind": "event", "name": "blowup", "v": float("nan"),
                 "l": [1.0, np.float32("inf"), 2]})
    raw = open(tmp_path / METRICS_FILE).read()
    assert "NaN" not in raw and "Infinity" not in raw
    (row,) = load_metrics(str(tmp_path / METRICS_FILE))
    assert row["v"] is None and row["l"] == [1.0, None, 2]


def test_invalid_telemetry_value_rejected():
    with pytest.raises(ValueError, match="telemetry"):
        FederatedTrainer(
            TrainConfig(telemetry="yes"),
            MSANNet(in_size=6, hidden_sizes=(8,), out_size=2), mesh=None,
        )


def test_profile_and_xprof_dirs_mutually_exclusive(tmp_path):
    with pytest.raises(ValueError, match="mutually exclusive"):
        FederatedTrainer(
            TrainConfig(profile_dir=str(tmp_path / "a"),
                        xprof_dir=str(tmp_path / "b")),
            MSANNet(in_size=6, hidden_sizes=(8,), out_size=2), mesh=None,
        )


# ---------------------------------------------------------------------------
# schema validators + report CLI
# ---------------------------------------------------------------------------


def test_schema_validators_reject_drift():
    good = {"kind": "event", "name": "checkpoint"}
    assert validate_metrics_rows([good]) == []
    assert validate_metrics_rows([{"kind": "nonsense"}])
    assert validate_metrics_rows([{"kind": "epoch", "fold": 0}])  # missing
    assert validate_manifest({"schema_version": 1})  # missing keys
    assert validate_manifest([1, 2])  # not an object
    # version bump without a validator update must fail loudly
    assert any(
        "schema_version" in p
        for p in validate_manifest({"schema_version": 99})
    )


def test_schema_validators_unknown_kind_and_serving_rows():
    """An unknown ``kind`` is a finding, not a silent pass (a typo'd kind
    would otherwise vanish from the report), and the serving row kinds'
    required-key sets are enforced key by key (negative fixtures: each
    missing key must be NAMED in a problem string)."""
    problems = validate_metrics_rows([{"kind": "dsipatch"}])  # typo
    assert problems and "unknown kind" in problems[0]
    good_dispatch = {
        "kind": "dispatch", "lane": "infer", "bucket": 4, "rows": 3,
        "pad_rows": 1, "queue_depth": 0,
    }
    assert validate_metrics_rows([good_dispatch]) == []
    for key in ("lane", "bucket", "rows", "pad_rows", "queue_depth"):
        bad = {k: v for k, v in good_dispatch.items() if k != key}
        problems = validate_metrics_rows([bad])
        assert problems and key in problems[0], (key, problems)
    good_summary = {
        "kind": "serve_summary", "task_id": "FS-Classification",
        "requests": 1, "samples": 1, "dispatches": 1,
        "latency_ms_p50": 1.0, "latency_ms_p95": 1.0, "latency_ms_p99": 1.0,
        "requests_per_s": 1.0, "samples_per_s": 1.0, "pad_waste_pct": 0.0,
        "bucket_hit_rate": 1.0, "warmup_seconds": 0.1,
        "compiles_after_warmup": 0,
    }
    assert validate_metrics_rows([good_summary]) == []
    for key in ("latency_ms_p50", "latency_ms_p95", "latency_ms_p99",
                "requests", "dispatches", "compiles_after_warmup"):
        bad = {k: v for k, v in good_summary.items() if k != key}
        problems = validate_metrics_rows([bad])
        assert problems and key in problems[0], (key, problems)


def test_report_cli_smoke(tmp_path, capsys):
    cfg = TrainConfig(epochs=2, batch_size=8, patience=50, telemetry="on")
    _fit(cfg, str(tmp_path))
    from dinunet_implementations_tpu.telemetry import report

    # --validate gates clean artifacts
    assert report.main([str(tmp_path / "telemetry"), "--validate"]) == 0
    capsys.readouterr()
    # rendering finds the fold dir from the run root and prints the tables
    assert report.main([str(tmp_path / "telemetry")]) == 0
    out = capsys.readouterr().out
    assert "phase time" in out and "per-site rollup" in out
    assert "epoch_compiles=1" in out
    # validation failure path: corrupt the manifest
    mpath = tmp_path / "telemetry" / "fold_0" / MANIFEST_FILE
    mpath.write_text(json.dumps({"schema_version": 99}))
    assert report.main([str(tmp_path / "telemetry"), "--validate"]) == 1
    with pytest.raises(FileNotFoundError):
        report.fit_dirs(str(tmp_path))  # no manifest anywhere


def test_telemetry_summary_rollup_shapes():
    t = default_round_telemetry(3)
    t = {k: np.asarray(v) for k, v in t.items()}
    t["grad_sq_last"] = np.asarray([4.0, 9.0, np.nan], np.float32)
    t["rounds"] = np.asarray([2, 2, 2], np.int32)
    s = telemetry_summary(t)
    assert s["site_grad_norm_last"][:2] == [2.0, 3.0]
    assert np.isnan(s["site_grad_norm_last"][2])
    assert s["rounds"] == 2
    assert telemetry_summary(None) is None
