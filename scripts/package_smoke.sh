#!/usr/bin/env bash
# Package smoke test (VERDICT r2 #8): build the wheel, install it into a
# clean target directory (this environment has no network and is itself a
# venv, so a nested venv can't see jax — PYTHONPATH-target isolation proves
# the same thing: OUR wheel, not the repo checkout, provides the package),
# and run the README quick-start on the reference fixture from a neutral
# working directory.
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
WORK="${1:-$(mktemp -d)}"
# default fixture: the self-generated demo tree (VERDICT r3 #5 — no reference
# checkout required); set FIXTURE=/path/to/datasets/test_fsl to smoke against
# the reference fixture instead
FIXTURE="${FIXTURE:-}"

cd "$WORK"
python -m pip wheel --no-deps --no-build-isolation -w "$WORK/dist" "$REPO" >/dev/null
# setuptools writes build/ + *.egg-info into the source tree under
# --no-build-isolation; don't leave artifacts in the repo (they must never
# be committed — a stale copy shadowing the real module is a trap)
rm -rf "$REPO/build" "$REPO"/*.egg-info
WHEEL="$(ls "$WORK"/dist/dinunet_implementations_tpu-*.whl)"
python -m pip install --no-deps --target "$WORK/site" "$WHEEL" >/dev/null

cd "$WORK"  # neutral cwd: the repo checkout must NOT be importable
if [ -z "$FIXTURE" ]; then
  FIXTURE="$WORK/datasets/demo"
  PYTHONPATH="$WORK/site" python -m dinunet_implementations_tpu.data.demo \
    "$FIXTURE" --subjects 16 >/dev/null
fi
PYTHONPATH="$WORK/site" JAX_PLATFORMS=cpu python - <<EOF
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: the quickstart below is vmap-folded, 1 CPU device is fine
import dinunet_implementations_tpu as dt
assert dt.__file__.startswith("$WORK/site"), (
    f"imported from {dt.__file__}, not the installed wheel"
)

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.runner import FedRunner

cfg = TrainConfig(agg_engine="dSGD", epochs=2, batch_size=8,
                  split_ratio=(0.7, 0.15, 0.15))
results = FedRunner(cfg, data_path="$FIXTURE", out_dir="$WORK/out").run(verbose=False)
loss, auc = results[0]["test_metrics"][0]
assert 0 <= auc <= 1 and loss > 0
print(f"package smoke OK: wheel install + quick-start trained (loss={loss}, auc={auc})")
EOF
