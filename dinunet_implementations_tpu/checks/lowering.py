"""Normalized-lowering differ — the "off == compiled out" claims as a library.

The repo stakes several correctness/perf claims on PROGRAM IDENTITY, not
value identity: ``telemetry="off"`` must compile the exact pre-telemetry
epoch, the fault machinery's static opt-out must really remove it, the
sanitizer's observation modes must not perturb what they observe. PR 2/PR 5
asserted those with ad-hoc ``lowered.as_text() == ...`` string comparisons —
a raw equality whose failure mode is a useless multi-megabyte diff. This
module is the shared replacement:

- :func:`normalize_lowering` canonicalizes a lowered program's text
  (StableHLO MLIR from ``Lowered.as_text()`` or post-optimization HLO from
  ``Compiled.as_text()``): location/metadata stripped, SSA/instruction ids
  renamed to appearance order, module names unified — so an identity check
  survives cosmetic churn (id renumbering, debug-info toggles) while any
  STRUCTURAL change (one extra op) still diverges;
- :func:`diff_report` compares two normalized programs (the
  ``Lowered.as_text()`` strings) and returns ``None`` on identity or a
  compact human-readable first-divergence report (the thing a failed `==`
  never gave us).

Used by the S005 semantic rule (checks/semantic.py) as a CLI gate and by the
parametrized off==baseline test harness (tests/test_lowering_identity.py).
"""

from __future__ import annotations

import difflib
import re

#: ``loc(...)`` MLIR location attributes (one level of nested parens is
#: enough for jax's emitted forms: ``loc("x"("f.py":1:2))``)
_LOC_RE = re.compile(r"\s*loc\((?:[^()]|\([^()]*\))*\)")
#: HLO-text ``metadata={op_name=... source_file=...}`` operand suffixes
_METADATA_RE = re.compile(r",?\s*metadata=\{[^{}]*\}")
#: SSA values / HLO instruction names: ``%arg0``, ``%123``, ``%add.42``
_ID_RE = re.compile(r"%[A-Za-z_][\w.]*|%\d+")
#: module headers carry build-dependent names: ``module @jit_epoch_fn_impl``,
#: ``HloModule jit_epoch_fn_impl, ...``
_MODULE_RE = re.compile(r"(module @)\S+|(HloModule )\S+?(?=[, ])")


def normalize_lowering(text: str) -> list[str]:
    """Canonicalize one lowered program's text into comparable lines.

    Order of appearance drives id renaming, so two programs are equal after
    normalization iff they consist of the same ops with the same structure
    and dataflow — the property the "off == compiled out" claims mean.
    """
    text = _LOC_RE.sub("", text)
    text = _METADATA_RE.sub("", text)
    text = _MODULE_RE.sub(lambda m: (m.group(1) or m.group(2)) + "<m>", text)
    ids: dict[str, str] = {}

    def rename(m: re.Match) -> str:
        tok = m.group(0)
        if tok not in ids:
            ids[tok] = f"%v{len(ids)}"
        return ids[tok]

    text = _ID_RE.sub(rename, text)
    lines = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#loc"):
            continue
        lines.append(re.sub(r"\s+", " ", ln))
    return lines


def diff_report(
    a: str,
    b: str,
    label_a: str = "baseline",
    label_b: str = "variant",
    context: int = 2,
    max_lines: int = 12,
) -> str | None:
    """``None`` when the two programs are identical after normalization;
    otherwise a human-readable report of the FIRST structural divergence
    (with ``context`` surrounding lines) plus total divergence counts.

    Divergences come from ``difflib`` edit opcodes, not positional
    comparison, so one inserted instruction mid-program reads as ONE
    insertion at its true location — not as every subsequent line
    "differing" by a one-line offset."""
    la, lb = normalize_lowering(a), normalize_lowering(b)
    if la == lb:
        return None
    opcodes = difflib.SequenceMatcher(a=la, b=lb, autojunk=False).get_opcodes()
    edits = [op for op in opcodes if op[0] != "equal"]
    differing = sum(max(i2 - i1, j2 - j1) for _, i1, i2, j1, j2 in edits)
    tag, i1, i2, j1, j2 = edits[0]
    out = [
        f"lowering divergence: {label_a} ({len(la)} lines) != "
        f"{label_b} ({len(lb)} lines); {differing} differing line(s), "
        f"first at line {i1 + 1} ({tag}):",
    ]
    body = [f"  [{k + 1}]: {la[k]}" for k in range(max(0, i1 - context), i1)]
    body += [f"> {label_a}[{k + 1}]: {la[k]}" for k in range(i1, i2)]
    body += [f"> {label_b}[{k + 1}]: {lb[k]}" for k in range(j1, j2)]
    body += [f"  [{k + 1}]: {la[k]}" for k in range(i2, min(len(la), i2 + context))]
    out += body[:max_lines]
    if len(body) > max_lines:
        out.append(f"  ... ({len(body) - max_lines} more line(s) at this edit)")
    return "\n".join(out)
