"""Fused Pallas TPU kernel for the LSTM recurrence (forward + BPTT backward).

The ICA-LSTM's hot loop (SURVEY.md §3.4) is the time recurrence: per step a
small ``h @ W_hh`` matmul plus gate math. The XLA scan path (models/icalstm.py)
already hoists the input projection; this kernel goes further and keeps the
carry (h, c) and all four recurrence matrices resident in VMEM across the
whole sequence, streaming per-step inputs/outputs HBM↔VMEM via the grid
pipeline — no per-step HBM round trip for the carry, no per-step kernel
launches.

Layout choice: gates live in four separate ``[T, B, H]`` arrays (not one
``[T, B, 4H]``) so every block's lane dimension is H and no slice ever crosses
a lane boundary (Mosaic-friendly; see pallas_guide.md pitfall #2).

Grid: ``(batch_tiles, T)`` — TPU grids execute sequentially, so VMEM scratch
carries (h, c) across the T dimension; time-reversed index maps drive the
backward kernel. The backward accumulates dW in a revisited output block.

Semantics: standard LSTM gates (single sigmoid). The reference's
double-sigmoid quirk mode stays on the XLA scan path (models/icalstm.py) —
the kernel is the fast path for the default configuration.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B_TILE = 128


def _interpret() -> bool:
    # Pallas TPU kernels run in interpreter mode on CPU (tests / simulators)
    return jax.default_backend() == "cpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(xi_i, xi_f, xi_o, xi_g, w, h0, c0, hs, cs, ai, af, ao, ag, h_s, c_s):
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        h_s[:] = h0[:]
        c_s[:] = c0[:]

    h = h_s[:]
    # preact_k = xi_k[t] + h @ W_k   (W resident in VMEM, [4, H, H])
    i = jax.nn.sigmoid(xi_i[0] + jnp.dot(h, w[0], preferred_element_type=jnp.float32))
    f = jax.nn.sigmoid(xi_f[0] + jnp.dot(h, w[1], preferred_element_type=jnp.float32))
    o = jax.nn.sigmoid(xi_o[0] + jnp.dot(h, w[2], preferred_element_type=jnp.float32))
    g = jnp.tanh(xi_g[0] + jnp.dot(h, w[3], preferred_element_type=jnp.float32))
    c = f * c_s[:] + i * g
    h = o * jnp.tanh(c)
    h_s[:] = h
    c_s[:] = c
    hs[0] = h
    cs[0] = c
    ai[0] = i
    af[0] = f
    ao[0] = o
    ag[0] = g


def _fwd_call(xi4, w4, h0, c0):
    T, B, H = xi4[0].shape
    bt = min(B_TILE, B)
    assert B % bt == 0, (
        f"batch {B} must be a multiple of the kernel tile {bt}; "
        "use lstm_forward(), which pads"
    )
    grid = (B // bt, T)
    t_block = lambda b, t: (t, b, 0)
    b_block = lambda b, t: (b, 0)
    spec_t = pl.BlockSpec((1, bt, H), t_block, memory_space=pltpu.VMEM)
    spec_b = pl.BlockSpec((bt, H), b_block, memory_space=pltpu.VMEM)
    spec_w = pl.BlockSpec((4, H, H), lambda b, t: (0, 0, 0), memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((T, B, H), jnp.float32)
    outs = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[spec_t] * 4 + [spec_w, spec_b, spec_b],
        out_specs=[spec_t] * 6,
        out_shape=[out_shape] * 6,
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32)] * 2,
        interpret=_interpret(),
    )(*xi4, w4, h0, c0)
    return outs  # hs, cs, i, f, o, g


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_kernel(
    T_total,
    ai, af, ao, ag, cs, cs_prev, hs_prev, w, h0, c0, dhs, dhT, dcT,
    dxi_i, dxi_f, dxi_o, dxi_g, dh0, dc0, dw,
    dh_s, dc_s,
):
    t = pl.program_id(1)  # 0..T-1, walking time backwards: time = T-1-t
    first_time = t == 0  # time T-1
    last_time = t == T_total - 1  # time 0

    @pl.when(first_time)
    def _():
        # seed the carries with the terminal-state cotangents (exact dcT/dhT);
        # re-seeded at the start of every batch tile (per-tile state)
        dh_s[:] = dhT[:]
        dc_s[:] = dcT[:]

    @pl.when(jnp.logical_and(first_time, pl.program_id(0) == 0))
    def _():
        # dW accumulates across ALL tiles and timesteps — zero it exactly once
        dw[:] = jnp.zeros_like(dw)

    i, f, o, g = ai[0], af[0], ao[0], ag[0]
    c = cs[0]
    c_prev = jnp.where(last_time, c0[:], cs_prev[0])
    h_prev = jnp.where(last_time, h0[:], hs_prev[0])

    tanh_c = jnp.tanh(c)
    dh = dhs[0] + dh_s[:]
    do = dh * tanh_c
    dc = dh * o * (1.0 - tanh_c * tanh_c) + dc_s[:]
    di = dc * g
    df = dc * c_prev
    dg = dc * i

    dpi = di * i * (1.0 - i)
    dpf = df * f * (1.0 - f)
    dpo = do * o * (1.0 - o)
    dpg = dg * (1.0 - g * g)

    dxi_i[0] = dpi
    dxi_f[0] = dpf
    dxi_o[0] = dpo
    dxi_g[0] = dpg

    # dh_{t-1} = Σ_k dp_k @ W_kᵀ ; dW_k += h_{t-1}ᵀ @ dp_k
    dh_prev = (
        jnp.dot(dpi, w[0].T, preferred_element_type=jnp.float32)
        + jnp.dot(dpf, w[1].T, preferred_element_type=jnp.float32)
        + jnp.dot(dpo, w[2].T, preferred_element_type=jnp.float32)
        + jnp.dot(dpg, w[3].T, preferred_element_type=jnp.float32)
    )
    dw[0] += jnp.dot(h_prev.T, dpi, preferred_element_type=jnp.float32)
    dw[1] += jnp.dot(h_prev.T, dpf, preferred_element_type=jnp.float32)
    dw[2] += jnp.dot(h_prev.T, dpo, preferred_element_type=jnp.float32)
    dw[3] += jnp.dot(h_prev.T, dpg, preferred_element_type=jnp.float32)

    dh_s[:] = dh_prev
    dc_s[:] = dc * f

    @pl.when(last_time)
    def _():
        dh0[:] = dh_s[:]
        dc0[:] = dc_s[:]


def _bwd_call(res, dhs, dhT, dcT):
    w4, h0, c0, hs, cs, acts = res
    T, B, H = hs.shape
    bt = min(B_TILE, B)
    grid = (B // bt, T)

    rev = lambda b, t: (T - 1 - t, b, 0)
    b_block = lambda b, t: (b, 0)
    spec_rev = pl.BlockSpec((1, bt, H), rev, memory_space=pltpu.VMEM)
    spec_prev = pl.BlockSpec(
        (1, bt, H), lambda b, t: (jnp.maximum(T - 2 - t, 0), b, 0),
        memory_space=pltpu.VMEM,
    )
    spec_b = pl.BlockSpec((bt, H), b_block, memory_space=pltpu.VMEM)
    spec_w = pl.BlockSpec((4, H, H), lambda b, t: (0, 0, 0), memory_space=pltpu.VMEM)
    t_shape = jax.ShapeDtypeStruct((T, B, H), jnp.float32)

    outs = pl.pallas_call(
        functools.partial(_bwd_kernel, T),
        grid=grid,
        in_specs=[spec_rev] * 4  # i, f, o, g
        + [spec_rev, spec_prev, spec_prev, spec_w, spec_b, spec_b, spec_rev,
           spec_b, spec_b],
        out_specs=[spec_rev] * 4 + [spec_b, spec_b, spec_w],
        out_shape=[t_shape] * 4
        + [
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((4, H, H), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bt, H), jnp.float32)] * 2,
        interpret=_interpret(),
    )(*acts, cs, cs, hs, w4, h0, c0, dhs, dhT, dcT)
    dxi = outs[:4]
    dh0, dc0, dw = outs[4], outs[5], outs[6]
    return dxi, dw, dh0, dc0


# ---------------------------------------------------------------------------
# public op with custom VJP
# ---------------------------------------------------------------------------


@jax.custom_vjp
def lstm_recurrence(xi4, w4, h0, c0):
    """Run the LSTM time recurrence.

    Args:
      xi4: tuple of four ``[T, B, H]`` input-projection arrays (i, f, o, g
        pre-activations, i.e. ``x_t @ W_ih + b`` split per gate).
      w4: ``[4, H, H]`` recurrent weights (i, f, o, g order).
      h0, c0: ``[B, H]`` initial carry.

    Returns: ``(hs [T, B, H], (hT, cT))``.
    """
    hs, cs, *_ = _fwd_call(xi4, w4, h0, c0)
    return hs, (hs[-1], cs[-1])


def _vjp_fwd(xi4, w4, h0, c0):
    hs, cs, i, f, o, g = _fwd_call(xi4, w4, h0, c0)
    # xi4 is NOT needed by the backward (dxi == dpreact); don't pin it
    return (hs, (hs[-1], cs[-1])), (w4, h0, c0, hs, cs, (i, f, o, g))


def _vjp_bwd(res, grads):
    dhs, (dhT, dcT) = grads
    dxi, dw, dh0, dc0 = _bwd_call(res, dhs, dhT, dcT)
    return tuple(dxi), dw, dh0, dc0


lstm_recurrence.defvjp(_vjp_fwd, _vjp_bwd)


def lstm_forward(xi, w_hh, h0, c0):
    """Convenience wrapper over :func:`lstm_recurrence` in model layout.

    Args:
      xi: ``[B, T, 4H]`` pre-computed input projections (i|f|o|g blocks —
        the LSTMCell layout, ``x @ W_ih + b_ih + b_hh``).
      w_hh: ``[H, 4H]`` recurrent weight in the same blocked layout.
      h0, c0: ``[B, H]``.

    Returns ``(hs [B, T, H], (hT, cT))``. Pads the batch to the kernel tile
    internally and slices the padding off.
    """
    B, T, H4 = xi.shape
    H = H4 // 4
    in_dtype = xi.dtype
    # the kernel computes in f32 (scratch/accumulators); cast at the boundary
    xi = xi.astype(jnp.float32)
    w_hh = w_hh.astype(jnp.float32)
    h0 = h0.astype(jnp.float32)
    c0 = c0.astype(jnp.float32)
    bt = min(B_TILE, B)
    pad = (-B) % bt
    if pad:
        xi = jnp.concatenate([xi, jnp.zeros((pad, T, H4), xi.dtype)], 0)
        h0 = jnp.concatenate([h0, jnp.zeros((pad, H), h0.dtype)], 0)
        c0 = jnp.concatenate([c0, jnp.zeros((pad, H), c0.dtype)], 0)
    xi_t = jnp.swapaxes(xi, 0, 1)  # [T, B, 4H]
    xi4 = tuple(xi_t[..., k * H : (k + 1) * H] for k in range(4))
    w4 = jnp.stack([w_hh[:, k * H : (k + 1) * H] for k in range(4)])
    hs, (hT, cT) = lstm_recurrence(xi4, w4, h0, c0)
    hs = jnp.swapaxes(hs, 0, 1)
    if pad:
        hs, hT, cT = hs[:B], hT[:B], cT[:B]
    return hs.astype(in_dtype), (hT.astype(in_dtype), cT.astype(in_dtype))
