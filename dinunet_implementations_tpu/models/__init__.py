from .cnn3d import SMRI3DNet
from .icalstm import BiLSTM, ICALstm, ICALstmStream, LSTMCell
from .layers import BatchNorm, masked_moments
from .msannet import MSANNet
from .transformer import MultimodalNet
