"""Benchmark: ICA-LSTM federated training throughput, 32 simulated sites.

The north-star metric (BASELINE.json): samples/sec/chip for the ICA-LSTM
fMRI classifier trained across 32 simulated federated sites, vs the
CPU reference baseline. One chip simulates all 32 sites via the vmap-folded
site axis (trainer/steps.py); the measured step is the FULL federated round:
per-site grad, dSGD example-weighted aggregation across the 32 sites, Adam
update — i.e. what the reference needs a 32-container COINSTAC deployment
plus a remote to do.

Baseline: the reference's torch ICALstm (loaded from
/root/reference/comps/icalstm/models.py) doing fwd+bwd+Adam on one CPU site
measured in this environment = 67.3 samples/sec (B=16, 238 ms/iter; falls back
to this recorded constant when the live measurement is unavailable).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

# Recorded in this environment (see module docstring); re-measured live when
# --live-baseline is passed.
CPU_BASELINE_SAMPLES_PER_SEC = 67.3

NUM_SITES = 32
BATCH_PER_SITE = 16
STEPS_PER_EPOCH = 2
TIMED_EPOCHS = 64  # large so the ~110ms tunnel round-trip amortizes


def measure_tpu() -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.models import ICALstm
    from dinunet_implementations_tpu.trainer import (
        FederatedTask,
        init_train_state,
        make_optimizer,
        make_train_epoch_fn,
    )

    # HCP inputspec shape (datasets/icalstm/inputspec.json:32-43); bf16
    # matmuls AND streamed activations with f32 carries/accumulation
    # (ops/lstm_pallas.py) — the kernel is HBM-bandwidth-bound, so halving
    # the streams is the dominant win (37.8k → 74.8k samples/s on v5e)
    model = ICALstm(input_size=256, hidden_size=348, num_comps=100,
                    window_size=10, num_cls=2, compute_dtype="bfloat16")
    task = FederatedTask(model)
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-3)

    S, steps, B = NUM_SITES, STEPS_PER_EPOCH, BATCH_PER_SITE
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(S, steps, B, 98, 100, 10)).astype(np.float32))
    y = jnp.asarray((rng.random((S, steps, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)

    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S
    )
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh=None, local_iterations=1)

    # warmup/compile (fetch a value — on the tunneled axon backend
    # block_until_ready alone does not synchronize; only a D2H fetch does)
    state, losses = epoch_fn(state, x, y, w)
    float(np.asarray(losses)[0])

    # estimate the fixed host↔device round-trip so it can be subtracted
    triv = jax.jit(lambda v: v + 1)
    float(np.asarray(triv(jnp.zeros(()))))
    r0 = time.time()
    for _ in range(3):
        float(np.asarray(triv(jnp.zeros(()))))
    rtt = (time.time() - r0) / 3

    # fuse EPOCHS_PER_DISPATCH epochs into one device program so the tunnel's
    # per-dispatch host overhead (~35ms here) doesn't pollute the chip metric
    E = 8

    @jax.jit
    def multi_epoch(st, x, y, w):
        return jax.lax.fori_loop(
            0, E, lambda i, s: epoch_fn(s, x, y, w)[0], st
        )

    state = multi_epoch(state, x, y, w)
    float(np.asarray(state.round))  # sync after compile

    t0 = time.time()
    q = max(TIMED_EPOCHS // E, 1)
    for _ in range(q):
        state = multi_epoch(state, x, y, w)
    float(np.asarray(state.round))
    dt = max(time.time() - t0 - rtt, 1e-6)
    TIMED = q * E

    n_chips = 1  # the folded site axis runs on one chip
    samples = S * steps * B * TIMED
    return samples / dt / n_chips


def measure_cpu_baseline() -> float:
    """Live re-measurement of the torch reference (optional)."""
    import importlib.util

    import torch

    spec = importlib.util.spec_from_file_location(
        "ref_ica", "/root/reference/comps/icalstm/models.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    m = mod.ICALstm(input_size=256, hidden_size=348, bidirectional=True,
                    num_cls=2, num_comps=100, window_size=10)
    opt = torch.optim.Adam(m.parameters(), lr=1e-3)
    crit = torch.nn.CrossEntropyLoss()
    B = 16
    x = torch.randn(B, 98, 100, 10)
    y = torch.randint(0, 2, (B,))
    for _ in range(2):
        opt.zero_grad(); out, _ = m(x); crit(out, y).backward(); opt.step()
    t = time.time()
    iters = 4
    for _ in range(iters):
        opt.zero_grad(); out, _ = m(x); crit(out, y).backward(); opt.step()
    return iters * B / (time.time() - t)


def main():
    baseline = CPU_BASELINE_SAMPLES_PER_SEC
    if "--live-baseline" in sys.argv:
        try:
            baseline = measure_cpu_baseline()
        except Exception:
            pass
    value = measure_tpu()
    print(json.dumps({
        "metric": "samples/sec/chip (ICA-LSTM, 32 sites, full federated round)",
        "value": round(value, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(value / baseline, 2),
    }))


if __name__ == "__main__":
    main()
