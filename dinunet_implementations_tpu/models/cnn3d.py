"""SMRI3DNet — 3D-CNN classifier for structural MRI (T1w) volumes.

TPU-build extension (BASELINE.json configs: "3D-CNN sMRI (T1w volumes)
federated classifier, 8 sites"); no reference implementation exists, so the
design is TPU-first throughout:

- NDHWC (channels-last) layout — the native TPU conv layout;
- downsampling via stride-2 convolutions (keeps everything on the MXU; no
  pooling ops between matmul-like kernels);
- mask-aware batch-stat BatchNorm (models/layers.py) so SPMD padding rows
  don't perturb statistics, matching the MSANNet convention;
- global average pool + linear head.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .layers import BatchNorm, compute_dtype_of, dense


def space_to_depth_222(x):
    """Fold each 2×2×2 spatial block of ``[B, D, H, W, 1]`` into 8 channels:
    voxel ``(2i+di, 2j+dj, 2k+dk)`` lands in channel ``di·4 + dj·2 + dk`` at
    ``(i, j, k)``. A faithful relayout (no information change) that raises
    the first conv's contraction dim from 27 to 216 — MXU-shaped."""
    B, D, H, W, _ = x.shape
    x = x.reshape(B, D // 2, 2, H // 2, 2, W // 2, 2)
    return jnp.transpose(x, (0, 1, 3, 5, 2, 4, 6)).reshape(
        B, D // 2, H // 2, W // 2, 8
    )


class SMRI3DNet(nn.Module):
    channels: tuple = (16, 32, 64, 128)
    num_cls: int = 2
    dropout_rate: float = 0.25
    # "bfloat16" runs the convolutions (all the FLOPs) in bf16 on the MXU
    # (f32 accumulation in hardware); BatchNorm statistics and the head stay
    # f32. None = full f32.
    compute_dtype: str | None = None
    # Opt-in :func:`space_to_depth_222` before the first conv (measured 3.7×
    # at f32 / 6.9× with bf16 on v5e — a single-channel first conv starves
    # the MXU). Default OFF: turning it on changes the architecture (conv_0
    # kernel shape, spatial grid), so existing checkpoints would not restore.
    # Wire via SMRI3DArgs.space_to_depth for runner-driven training.
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        # x: [B, D, H, W] or [B, D, H, W, C]
        if x.ndim == 4:
            x = x[..., None]
        if self.space_to_depth:
            if x.shape[-1] == 8:
                # already folded by the data pipeline
                # (data/smri.py:space_to_depth_222_np) — 8 channels cannot
                # occur on this path otherwise (raw input must be
                # single-channel), so the flag keeps meaning "the s2d
                # architecture" whether or not the dataset pre-folds
                pass
            elif x.shape[-1] != 1 or any(d % 2 for d in x.shape[1:4]):
                # fail loudly rather than silently skipping the fold: a
                # no-op here would mean a different architecture than
                # configured (and an opaque conv shape error later if a
                # trained model meets odd-sized data)
                raise ValueError(
                    "space_to_depth needs single-channel input with even "
                    f"spatial dims (or pipeline-prefolded 8-channel input); "
                    f"got shape {x.shape[1:]}. Pad/crop the volumes or set "
                    "space_to_depth=False."
                )
            else:
                x = space_to_depth_222(x)
        cdt = compute_dtype_of(self.compute_dtype)
        for i, ch in enumerate(self.channels):
            x = nn.Conv(ch, kernel_size=(3, 3, 3), strides=(2, 2, 2),
                        use_bias=False, name=f"conv_{i}", dtype=cdt,
                        param_dtype=jnp.float32)(x)
            x = x.astype(jnp.float32)  # BN moments at full precision
            # per-channel statistics over (B, D, H, W) — BatchNorm3d semantics
            x = BatchNorm(
                ch, track_running_stats=False, reduce_axes=(0, 1, 2, 3),
                name=f"bn_{i}",
            )(x, train=train, mask=mask)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2, 3))  # global average pool → [B, C]
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return dense(self.num_cls, fan_in=x.shape[-1], name="head")(x)
