"""Multimodal FS+ICA transformer classifier.

TPU-build extension (BASELINE.json configs: "Multimodal FS+ICA Transformer,
64-site DP-SGD on v4-128"). Fuses the two reference modalities into one token
sequence:

- FS branch: the 66 aseg volumes → one token;
- ICA branch: each temporal window (``num_components × window_size``) → one
  token (same windowing semantics as the ICA dataset, data/ica.py);
- a learned CLS token is prepended; learned positional embeddings; pre-LN
  transformer blocks; the CLS state feeds the classifier head.

Attention is a custom q/k/v implementation (not ``nn.SelfAttention``) so the
sequence-parallel ring variant (parallel/sequence.py) can swap in for long
sequences: set ``attention="ring"`` with a bound mesh ``model`` axis.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..core.jaxcompat import axis_size
from .layers import compute_dtype_of, dense


def dot_product_attention(q, k, v):
    """[B, T, N, Hd] q/k/v → [B, T, N, Hd]; plain softmax attention.
    Logits accumulate and softmax runs in f32 regardless of input dtype
    (bf16 q/k/v under mixed precision); output returns at v's dtype."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum(
        "btnh,bsnh->bnts", q, k, preferred_element_type=jnp.float32
    ) * scale
    weights = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bnts,bsnh->btnh", weights, v)


class MultiHeadAttention(nn.Module):
    embed_dim: int
    num_heads: int
    attention: str = "local"  # "local" | "ring" (sequence-parallel)
    axis_name: str | None = None  # mesh axis for ring attention
    compute_dtype: str | None = None  # bf16 matmuls, f32 softmax/accum

    @nn.compact
    def __call__(self, x):
        B, T, E = x.shape
        N = self.num_heads
        Hd = E // N
        cdt = compute_dtype_of(self.compute_dtype)
        qkv = dense(3 * E, fan_in=E, name="qkv", dtype=cdt)(x).reshape(
            B, T, 3, N, Hd
        )
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if self.attention == "ring":
            from ..parallel.sequence import ring_attention

            out = ring_attention(q, k, v, axis_name=self.axis_name)
        else:
            out = dot_product_attention(q, k, v)
        return dense(E, fan_in=E, name="proj", dtype=cdt)(out.reshape(B, T, E))


class TransformerBlock(nn.Module):
    embed_dim: int
    num_heads: int
    mlp_ratio: int = 4
    dropout_rate: float = 0.1
    attention: str = "local"
    axis_name: str | None = None
    compute_dtype: str | None = None  # bf16 matmuls; LayerNorm/residual f32

    def _dropout(self, h, train: bool):
        if not train or self.dropout_rate == 0.0:
            return h
        if self.attention == "ring" and self.axis_name is not None:
            # h is this device's token chunk; the dropout rng is replicated
            # across the model axis, so plain nn.Dropout would draw the SAME
            # mask for every chunk (correlated dropout, tiled over the token
            # axis). Fold the axis index in so each chunk gets its own mask.
            rng = jax.random.fold_in(
                self.make_rng("dropout"), jax.lax.axis_index(self.axis_name)
            )
            keep = 1.0 - self.dropout_rate
            mask = jax.random.bernoulli(rng, keep, h.shape)
            return jnp.where(mask, h / keep, jnp.zeros_like(h))
        return nn.Dropout(self.dropout_rate, deterministic=False)(h)

    @nn.compact
    def __call__(self, x, train: bool = True):
        cdt = compute_dtype_of(self.compute_dtype)
        h = nn.LayerNorm(name="ln1")(x)  # LN stats at f32 (x is f32 stream)
        h = MultiHeadAttention(
            self.embed_dim, self.num_heads, self.attention, self.axis_name,
            self.compute_dtype, name="attn",
        )(h)
        # residual stream stays f32 (f32 + bf16 promotes to f32)
        x = x + self._dropout(h.astype(jnp.float32), train)
        h = nn.LayerNorm(name="ln2")(x)
        h = dense(self.embed_dim * self.mlp_ratio, fan_in=self.embed_dim,
                  name="mlp1", dtype=cdt)(h)
        h = nn.gelu(h)
        h = dense(self.embed_dim, fan_in=self.embed_dim * self.mlp_ratio,
                  name="mlp2", dtype=cdt)(h)
        return x + self._dropout(h.astype(jnp.float32), train)


class MultimodalNet(nn.Module):
    fs_input_size: int = 66
    num_comps: int = 100
    window_size: int = 10
    embed_dim: int = 256
    num_heads: int = 8
    num_layers: int = 4
    mlp_ratio: int = 4
    num_cls: int = 2
    dropout_rate: float = 0.1
    attention: str = "local"
    axis_name: str | None = None
    # "bfloat16" runs every matmul (embeddings, qkv/proj, MLPs) in bf16 with
    # f32 softmax/LayerNorm/residual stream; None = full f32
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        """``x``: packed ``[B, fs_input_size + S*num_comps*window_size]``
        (data/multimodal.py packs both modalities into one flat vector so the
        standard site-batch pipeline applies); unpacked here."""
        B = x.shape[0]
        fs = x[:, : self.fs_input_size]
        ica = x[:, self.fs_input_size :].reshape(
            B, -1, self.num_comps * self.window_size
        )  # [B, S, C*W]

        cdt = compute_dtype_of(self.compute_dtype)
        fs_tok = dense(self.embed_dim, fan_in=self.fs_input_size,
                       name="fs_embed", dtype=cdt)(fs)
        ica_tok = dense(
            self.embed_dim, fan_in=self.num_comps * self.window_size,
            name="ica_embed", dtype=cdt,
        )(ica)
        cls = self.param(
            "cls", nn.initializers.normal(0.02), (1, 1, self.embed_dim)
        )
        tokens = jnp.concatenate(
            [jnp.tile(cls, (B, 1, 1)),
             fs_tok[:, None, :].astype(jnp.float32),
             ica_tok.astype(jnp.float32)], axis=1
        )  # token/residual stream is f32; block matmuls re-cast internally
        T = tokens.shape[1]
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, T, self.embed_dim)
        )
        h = tokens + pos
        ring = self.attention == "ring" and self.axis_name is not None
        if ring:
            # sequence parallelism: shard the token axis over the mesh axis —
            # each device keeps its chunk through every block (attention is
            # the only cross-chunk op, handled by ring_attention's K/V ring)
            from ..parallel.sequence import gather_sequence, shard_sequence

            n = axis_size(self.axis_name)
            if T % n:
                raise ValueError(
                    f"ring attention needs tokens ({T}) divisible by the "
                    f"{self.axis_name!r} axis size ({n})"
                )
            h = shard_sequence(h, self.axis_name, axis=1)
        for i in range(self.num_layers):
            h = TransformerBlock(
                self.embed_dim, self.num_heads, self.mlp_ratio, self.dropout_rate,
                self.attention, self.axis_name, self.compute_dtype,
                name=f"block_{i}",
            )(h, train=train)
        h = nn.LayerNorm(name="ln_f")(h)
        if ring:
            # the CLS token lives in chunk 0; gather so every device returns
            # identical logits (all_gather transposes to reduce-scatter — AD
            # routes the CLS cotangent back to the owning chunk)
            h = gather_sequence(h, self.axis_name, axis=1)
        return dense(self.num_cls, fan_in=self.embed_dim, name="head")(h[:, 0])
