"""Sequence/context parallelism tests (parallel/sequence.py).

VERDICT round-1 #3: ring_attention's online-softmax accumulation and
ring_lstm's wavefront carry relay are exactly the kind of code that is wrong
in subtle ways — these tests pin both against their dense single-device
equivalents on a real ``model``-axis host mesh.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from dinunet_implementations_tpu.core.jaxcompat import shard_map
from jax.sharding import PartitionSpec as P

from dinunet_implementations_tpu.models.icalstm import LSTMCell
from dinunet_implementations_tpu.models.transformer import dot_product_attention
from dinunet_implementations_tpu.parallel.mesh import MODEL_AXIS, host_mesh
from dinunet_implementations_tpu.parallel.sequence import (
    gather_sequence,
    ring_attention,
    ring_lstm,
    shard_sequence,
)


def _model_mesh(n):
    return host_mesh(1, model_axis_size=n)


def test_ring_attention_matches_dense():
    """Exact softmax attention over the global sequence, T sharded 4 ways."""
    rng = np.random.default_rng(0)
    B, T, N, Hd = 2, 16, 2, 4
    q, k, v = (
        jnp.asarray(rng.normal(size=(B, T, N, Hd)).astype(np.float32)) for _ in range(3)
    )
    dense_out = dot_product_attention(q, k, v)

    mesh = _model_mesh(4)
    ring = shard_map(
        functools.partial(ring_attention, axis_name=MODEL_AXIS),
        mesh=mesh,
        in_specs=(P(None, MODEL_AXIS), P(None, MODEL_AXIS), P(None, MODEL_AXIS)),
        out_specs=P(None, MODEL_AXIS),
        check_vma=False,
    )
    out = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_out), atol=2e-5)


def test_ring_attention_extreme_logits_stable():
    """Online-softmax must stay finite/correct with large-magnitude scores."""
    rng = np.random.default_rng(1)
    B, T, N, Hd = 1, 8, 1, 4
    q = jnp.asarray(rng.normal(size=(B, T, N, Hd)).astype(np.float32)) * 30.0
    k = jnp.asarray(rng.normal(size=(B, T, N, Hd)).astype(np.float32)) * 30.0
    v = jnp.asarray(rng.normal(size=(B, T, N, Hd)).astype(np.float32))
    dense_out = dot_product_attention(q, k, v)
    mesh = _model_mesh(2)
    out = shard_map(
        functools.partial(ring_attention, axis_name=MODEL_AXIS),
        mesh=mesh,
        in_specs=(P(None, MODEL_AXIS),) * 3,
        out_specs=P(None, MODEL_AXIS),
        check_vma=False,
    )(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense_out), atol=1e-4)


def test_ring_attention_no_axis_falls_back_to_dense():
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(1, 4, 1, 4)).astype(np.float32))
    out = ring_attention(q, q, q, axis_name=None)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dot_product_attention(q, q, q)), atol=1e-6
    )


@pytest.mark.slow
def test_ring_lstm_matches_scan_cell():
    """The wavefront carry relay must reproduce the dense scan LSTM exactly:
    per-chunk hidden sequences AND the terminal carry on every device."""
    rng = np.random.default_rng(3)
    B, T, D, H = 2, 12, 5, 7
    model = LSTMCell(hidden_size=H, use_pallas=False)
    x = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
    params = model.init(jax.random.PRNGKey(0), x)
    dense_hs, (dense_h, dense_c) = model.apply(params, x)

    n = 4
    mesh = _model_mesh(n)
    h0 = jnp.zeros((B, H), jnp.float32)
    c0 = jnp.zeros((B, H), jnp.float32)

    def cell_fn(x_chunk, carry):
        return model.apply(params, x_chunk, carry)

    def shard_fn(x_local, h0, c0):
        hs, (hT, cT) = ring_lstm(cell_fn, x_local, h0, c0, axis_name=MODEL_AXIS)
        return hs, hT, cT

    hs, hT, cT = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(None, MODEL_AXIS), P(), P()),
        out_specs=(P(None, MODEL_AXIS), P(), P()),
        check_vma=False,
    )(x, h0, c0)
    np.testing.assert_allclose(np.asarray(hs), np.asarray(dense_hs), atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT), np.asarray(dense_h), atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT), np.asarray(dense_c), atol=1e-5)


def test_shard_gather_roundtrip():
    x = jnp.arange(2 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 3)
    mesh = _model_mesh(4)

    def fn(x_full):
        local = shard_sequence(x_full, MODEL_AXIS)
        assert local.shape == (2, 2, 3)
        return gather_sequence(local, MODEL_AXIS)

    out = shard_map(
        fn, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
    )(x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


@pytest.mark.slow
def test_ring_lstm_microbatch_overlap_matches_dense():
    """Pipelined wavefront (explicit microbatches) must still reproduce the
    dense scan exactly — hidden sequences and terminal carries."""
    rng = np.random.default_rng(4)
    B, T, D, H = 8, 8, 5, 7
    model = LSTMCell(hidden_size=H, use_pallas=False)
    x = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
    params = model.init(jax.random.PRNGKey(0), x)
    dense_hs, (dense_h, dense_c) = model.apply(params, x)
    h0 = jnp.zeros((B, H), jnp.float32)

    for n, m in [(2, 4), (2, 8), (4, 2)]:
        mesh = _model_mesh(n)

        def shard_fn(x_local, h0, c0):
            hs, (hT, cT) = ring_lstm(
                lambda xc, carry: model.apply(params, xc, carry),
                x_local, h0, c0, axis_name=MODEL_AXIS, microbatches=m,
            )
            return hs, hT, cT
        hs, hT, cT = shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(None, MODEL_AXIS), P(), P()),
            out_specs=(P(None, MODEL_AXIS), P(), P()),
            check_vma=False,
        )(x, h0, h0)
        np.testing.assert_allclose(
            np.asarray(hs), np.asarray(dense_hs), atol=1e-5, err_msg=f"n={n} m={m}"
        )
        np.testing.assert_allclose(np.asarray(hT), np.asarray(dense_h), atol=1e-5)
        np.testing.assert_allclose(np.asarray(cT), np.asarray(dense_c), atol=1e-5)


@pytest.mark.slow
def test_ring_lstm_microbatch_grads_match_dense():
    """Gradients through the pipelined relay (dynamic slices + ppermute)
    must equal the dense scan's."""
    rng = np.random.default_rng(5)
    B, T, D, H = 8, 6, 4, 5
    model = LSTMCell(hidden_size=H, use_pallas=False)
    x = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
    params = model.init(jax.random.PRNGKey(1), x)
    h0 = jnp.zeros((B, H), jnp.float32)

    def dense_loss(p):
        hs, (hT, cT) = model.apply(p, x)
        return jnp.sum(hs**2) + jnp.sum(jnp.sin(hT) + cT)

    mesh = _model_mesh(2)

    def ring_loss(p):
        def shard_fn(x_local, h0, c0):
            hs, (hT, cT) = ring_lstm(
                lambda xc, carry: model.apply(p, xc, carry),
                x_local, h0, c0, axis_name=MODEL_AXIS, microbatches=4,
            )
            return jax.lax.psum(jnp.sum(hs**2), MODEL_AXIS), hT, cT
        sq, hT, cT = shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(None, MODEL_AXIS), P(), P()),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )(x, h0, h0)
        return sq + jnp.sum(jnp.sin(hT) + cT)

    g_d = jax.grad(dense_loss)(params)
    g_r = jax.grad(ring_loss)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        ),
        g_r, g_d,
    )


def test_ring_lstm_overlap_flop_reduction():
    """VERDICT r4 #7: the microbatched wavefront must cut compiled FLOPs by
    >1.5x vs the masked (m=1) wavefront at model_axis=2. Measured via XLA's
    own cost model, so it holds machine-independently."""
    rng = np.random.default_rng(6)
    # recurrence-dominated shape (H >> D): the masked wavefront's repeated
    # i2h projection on identical x CSEs away, so the measurable redundancy
    # is the n x recurrence — the part the pipeline actually removes
    B, T, D, H = 64, 8, 4, 64
    model = LSTMCell(hidden_size=H, use_pallas=False)
    x = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
    params = model.init(jax.random.PRNGKey(2), x)
    h0 = jnp.zeros((B, H), jnp.float32)
    mesh = _model_mesh(2)

    def flops(m):
        def shard_fn(x_local, h0, c0):
            hs, fin = ring_lstm(
                lambda xc, carry: model.apply(params, xc, carry),
                x_local, h0, c0, axis_name=MODEL_AXIS, microbatches=m,
            )
            return hs, fin
        f = jax.jit(shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(None, MODEL_AXIS), P(), P()),
            out_specs=(P(None, MODEL_AXIS), (P(), P())),
            check_vma=False,
        ))
        ca = f.lower(x, h0, h0).compile().cost_analysis()
        # older jax wraps the per-device dict in a list
        return (ca[0] if isinstance(ca, list) else ca)["flops"]

    masked, piped = flops(1), flops(8)
    # analytic: masked = 2·B row-steps, piped = (8+1)/8·B → ~1.78x; XLA's
    # count includes the fixed dense head so demand a bit less
    assert piped * 1.5 < masked, (masked, piped)


@pytest.mark.slow
def test_ring_microbatches_reachable_from_config():
    """TrainConfig.sequence_microbatches threads through the registry to the
    ring path and reproduces the auto result exactly."""
    from dinunet_implementations_tpu.core.config import TrainConfig
    from dinunet_implementations_tpu.runner.registry import get_task

    cfg = TrainConfig(task_id="ICA-Classification", model_axis_size=2,
                      sequence_microbatches=4)
    model = get_task(cfg.task_id).build_model(cfg)
    assert model.sequence_microbatches == 4
    assert model.sequence_axis is not None

    # and through a real 2-device ring: explicit m == auto == dense
    rng = np.random.default_rng(7)
    B, T, D, H = 8, 8, 4, 6
    cell = LSTMCell(hidden_size=H, use_pallas=False)
    x = jnp.asarray(rng.normal(size=(B, T, D)).astype(np.float32))
    params = cell.init(jax.random.PRNGKey(0), x)
    dense_hs, _ = cell.apply(params, x)
    mesh = _model_mesh(2)
    h0 = jnp.zeros((B, H), jnp.float32)

    def run(m):
        def shard_fn(x_local, h0, c0):
            hs, fin = ring_lstm(
                lambda xc, c: cell.apply(params, xc, c), x_local, h0, c0,
                axis_name=MODEL_AXIS, microbatches=m,
            )
            return hs
        return shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(None, MODEL_AXIS), P(), P()),
            out_specs=P(None, MODEL_AXIS), check_vma=False,
        )(x, h0, h0)

    np.testing.assert_allclose(np.asarray(run(4)), np.asarray(dense_hs), atol=1e-5)
    np.testing.assert_allclose(np.asarray(run(None)), np.asarray(run(4)), atol=1e-6)
