"""Device mesh construction — the communication backend.

This replaces the reference's COINSTAC transport layer (L0): Docker containers
exchanging JSON payloads through a message bus (reference ``entry.py:5``,
``local.py:19``, ``remote.py:13``). In the TPU build, every federated site lives
on a slice of a ``jax.sharding.Mesh`` with a ``"site"`` axis; the local→remote
gradient ship + remote→local broadcast collapses into XLA collectives over ICI.
See SURVEY.md §2.2.

Axes:
  - ``slice`` — optional OUTER axis over TPU slices / hosts (r18 multi-slice
                scale-out): collectives over it cross DCN, the slow
                inter-slice fabric. Absent on single-slice meshes.
  - ``site``  — one federated site per mesh index (or per core-group);
                collectives over it ride intra-slice ICI.
  - ``model`` — optional inner axis for tensor/sequence sharding within a site
                (a TPU-build extension; the reference is single-device per site).

Site packing (r12): the mesh's ``site`` axis is the PHYSICAL half of a
virtual site axis. ``S`` virtual sites pack ``K = sites_per_device`` per mesh
member (:func:`packed_site_mesh`): every ``[S, …]`` per-site array shards
``P(site)`` into contiguous ``[K, …]`` device blocks, so virtual site
``d·K + j`` lives at row ``j`` on mesh member ``d`` (device-major global
order — the same order ``axis_index((site, fold))`` linearizes to inside the
epoch). Aggregation is then two-level (parallel/collectives.py PackedAxis):
a local in-register reduce over the packed rows followed by one cross-device
collective over ``site`` — which is how an 8-device mesh runs 512+ sites in
one compiled SPMD program without site count ever touching device count.

Multi-slice (r18): once one mesh is the ceiling, the site axis grows an
outer ``slice`` tier (:func:`sliced_site_mesh`). Per-site arrays shard
``P((slice, site))`` — slice-major global order, so virtual site
``(sl·D + d)·K + j`` lives at row ``j`` on slice ``sl``'s member ``d``, the
same order ``axis_index((slice, site, fold))`` linearizes to. Aggregation
becomes three-tier (parallel/collectives.py ``three_level_psum``): the
in-register packed reduce (tier 0), one intra-slice collective over ICI
(tier 1), and an inter-slice hop over DCN (tier 2) that ships only the
already-reduced per-slice partial — quantizable independently of the ICI
wire (``TrainConfig.dcn_wire_quant``). What used to be an aside ("multi-host:
DCN") is a real mode: single-process CPU emulation lays the slice axis over
virtual devices so the whole tier-1 suite exercises it, and
``runner/dcn_worker.py`` launches one process per slice over
``jax.distributed`` for real hosts.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SITE_AXIS = "site"
MODEL_AXIS = "model"
# outer inter-slice axis (r18 multi-slice scale-out): present only on meshes
# built by sliced_site_mesh with num_slices > 1 — collectives over it are the
# DCN tier of the three-level aggregation (parallel/collectives.py)
SLICE_AXIS = "slice"
# vmap axis name for sites folded onto one device (several simulated sites per
# chip, e.g. 32 sites on 8 chips): the trainer nests a vmap over the local
# site block inside shard_map, and cross-site collectives run over the
# (SITE_AXIS, FOLD_AXIS) pair. Never a mesh axis.
FOLD_AXIS = "site_fold"


def make_site_mesh(
    num_sites: int | None = None,
    devices: list | None = None,
    model_axis_size: int = 1,
) -> Mesh:
    """Build a ``(site, model)`` mesh.

    ``num_sites`` defaults to ``len(devices) // model_axis_size``. When fewer
    devices than sites are available, callers should fold multiple sites onto
    one device via a batched site dimension instead (see trainer); this function
    requires num_sites * model_axis_size == number of devices used.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_sites is None:
        num_sites = len(devices) // model_axis_size
    need = num_sites * model_axis_size
    if need > len(devices):
        raise ValueError(
            f"need {need} devices for {num_sites} sites × model={model_axis_size}, "
            f"have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(num_sites, model_axis_size)
    return Mesh(arr, (SITE_AXIS, MODEL_AXIS))


def packed_site_mesh(
    num_sites: int,
    sites_per_device: int = 1,
    devices: list | None = None,
    model_axis_size: int = 1,
) -> Mesh:
    """A ``(site, model)`` mesh for ``num_sites`` VIRTUAL sites packed
    ``sites_per_device`` per mesh member.

    The mesh's site axis has ``num_sites // sites_per_device`` entries; the
    trainer's ``P(site)`` sharding then hands each device a contiguous
    ``[sites_per_device, …]`` block of every per-site array (the packed
    layout above). ``sites_per_device=1`` is exactly :func:`make_site_mesh`.
    Raises when the pack factor doesn't divide the site count or the mesh
    doesn't fit the device set.
    """
    if sites_per_device < 1:
        raise ValueError(f"sites_per_device must be >= 1, got {sites_per_device}")
    if num_sites % sites_per_device:
        raise ValueError(
            f"sites_per_device={sites_per_device} must divide the virtual "
            f"site count ({num_sites})"
        )
    return make_site_mesh(
        num_sites // sites_per_device, devices, model_axis_size
    )


def sliced_site_mesh(
    num_slices: int,
    sites_per_slice: int,
    sites_per_device: int = 1,
    devices: list | None = None,
    model_axis_size: int = 1,
) -> Mesh:
    """A three-tier ``(slice, site, model)`` mesh: ``num_slices`` slices,
    each holding ``sites_per_slice`` VIRTUAL sites packed ``sites_per_device``
    per mesh member.

    ``num_slices == 1`` collapses to the legacy ``(site, model)`` mesh from
    :func:`packed_site_mesh` — the S005-gated opt-out: a single-slice config
    compiles the exact single-mesh program, no slice axis anywhere.

    Single-process emulation lays the slice axis over virtual (CPU) devices
    in slice-major order; a multi-process (``jax.distributed``) runtime maps
    processes to slices instead (parallel/distributed.py
    ``multihost_sliced_site_mesh`` — same axes, DCN-granule-aware layout).
    """
    if num_slices < 1:
        raise ValueError(f"num_slices must be >= 1, got {num_slices}")
    if sites_per_device < 1:
        raise ValueError(f"sites_per_device must be >= 1, got {sites_per_device}")
    if sites_per_slice % sites_per_device:
        raise ValueError(
            f"sites_per_device={sites_per_device} must divide the per-slice "
            f"site count ({sites_per_slice})"
        )
    if num_slices == 1:
        return packed_site_mesh(
            sites_per_slice, sites_per_device, devices, model_axis_size
        )
    per_slice = sites_per_slice // sites_per_device  # site-axis members/slice
    devices = list(devices if devices is not None else jax.devices())
    need = num_slices * per_slice * model_axis_size
    if need > len(devices):
        raise ValueError(
            f"need {need} devices for {num_slices} slices × {per_slice} "
            f"site-axis members × model={model_axis_size}, have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(
        num_slices, per_slice, model_axis_size
    )
    return Mesh(arr, (SLICE_AXIS, SITE_AXIS, MODEL_AXIS))


def slice_count(mesh: Mesh | None) -> int:
    """Number of slices on ``mesh`` (1 for single-slice/legacy meshes and
    the vmap-folded ``mesh=None`` topology)."""
    if mesh is None:
        return 1
    return dict(mesh.shape).get(SLICE_AXIS, 1)


def site_axis_of(mesh: Mesh):
    """The partition-spec entry for the leading per-site dim on ``mesh``:
    the ``(slice, site)`` pair on sliced meshes (slice-major global order),
    plain ``site`` otherwise. Everything that shards a ``[S, …]`` per-site
    array goes through this, so the layout convention lives in ONE place.

    Width-1 tiers are dropped from the pair: partitioning over a size-1
    axis is a no-op, and XLA canonicalizes it out of the sharding it
    reports on program OUTPUTS. If we committed inputs to the un-dropped
    spec, epoch 1's emitted state would carry a spec that no longer
    equals the placed one and epoch 2 would silently retrace (seen on
    packed sliced meshes, where the site tier collapses to width 1)."""
    if SLICE_AXIS in getattr(mesh, "axis_names", ()):
        shape = dict(mesh.shape)
        tiers = tuple(
            ax for ax in (SLICE_AXIS, SITE_AXIS) if shape.get(ax, 1) > 1
        )
        if len(tiers) == 1:
            return tiers[0]
        return tiers or None
    return SITE_AXIS


def pack_factor(mesh: Mesh | None, num_sites: int) -> int:
    """The site-packing factor K a ``[num_sites, …]`` per-site array gets on
    ``mesh``: virtual sites per device along the mesh's (slice, site) axes.
    ``mesh=None`` (the vmap-folded single-device topology) packs everything
    onto one device — K = num_sites."""
    if mesh is None:
        return num_sites
    mesh_sites = dict(mesh.shape)[SITE_AXIS] * slice_count(mesh)
    if num_sites % mesh_sites:
        raise ValueError(
            f"{num_sites} virtual sites do not divide over the mesh's "
            f"{mesh_sites} site-axis members"
        )
    return num_sites // mesh_sites


def site_sharding(mesh: Mesh, *trailing_axes) -> NamedSharding:
    """Sharding with the leading dim split over the site tier(s) — ``site``,
    or ``(slice, site)`` on a sliced mesh (per-site data)."""
    return NamedSharding(mesh, P(site_axis_of(mesh), *trailing_axes))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (global params — all sites hold the same
    weights between rounds, as in the reference where the remote broadcasts the
    aggregated update back to every site)."""
    return NamedSharding(mesh, P())


def host_mesh(num_sites: int, model_axis_size: int = 1) -> Mesh:
    """Mesh over CPU host devices, for the simulator path (tests / local dev).

    Requires ``XLA_FLAGS=--xla_force_host_platform_device_count=N``; this is the
    TPU-build replacement for the reference's Docker-based COINSTAC simulator
    (SURVEY.md §4.1).
    """
    cpus = [d for d in jax.devices() if d.platform == "cpu"]
    if not cpus:
        raise RuntimeError(
            "host_mesh needs CPU host devices; set "
            'jax.config.update("jax_platforms", "cpu") and '
            'jax.config.update("jax_num_cpu_devices", N) before first jax use '
            "(see tests/conftest.py)"
        )
    return make_site_mesh(num_sites, cpus, model_axis_size)
