"""Multi-host worker entry point — one process per host (or per TPU slice).

Graduated from the r8 test fixture (``tests/dcn_worker.py``) into the real
multi-slice launch path (r18): each invocation joins a ``jax.distributed``
runtime as ONE process of an N-process cluster and trains the shared
federated program over the resulting global mesh. With ``--slices N`` the
mesh is the three-tier ``(slice, site, model)`` topology
(parallel/distributed.py ``multihost_sliced_site_mesh`` via
``TrainConfig.num_slices``) — processes map to slices, so the ONLY
per-round DCN traffic is the inter-slice hop of the hierarchical
aggregation, carrying one (optionally ``--dcn-wire-quant``-quantized)
per-slice partial.

Typical per-slice launch (one process per TPU slice / host)::

    python -m dinunet_implementations_tpu.runner.dcn_worker \
        --coordinator host0:1234 --num-processes 4 --process-id $RANK \
        --slices 4 --data-path /data/tree --out-dir /shared/out

Every process computes identical replicated results; only process 0 writes
logs/checkpoints (trainer/loop.py ``_coordinator``). ``--report PATH``
writes a JSON record of the run — mesh shape, per-epoch losses, a params
checksum (bit-compared across processes by the multihost smoke test), the
epoch compile count, and the process-0-only write counters.

Capability probe: a jaxlib whose CPU backend cannot execute cross-process
collectives at all exits with code 66 (``UNSUPPORTED``), distinct from a
real failure — the CI/tier-1 smoke skips instead of failing red.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

#: exit code for "this backend cannot run multiprocess collectives" — the
#: tier-1/CI smokes skip on it (tests/test_distributed.py)
UNSUPPORTED_RC = 66


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="dcn_worker",
        description="multi-host/multi-slice federated training worker",
    )
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="jax.distributed coordinator address (process 0 "
                        "hosts it); omit with --num-processes 1 for the "
                        "single-process reference run")
    p.add_argument("--num-processes", type=int, default=1)
    p.add_argument("--process-id", type=int, default=0)
    p.add_argument("--data-path", required=True,
                   help="dataset tree (reference simulator layout); every "
                        "process loads the same tree and feeds its own "
                        "addressable mesh slices")
    p.add_argument("--out-dir", default=None,
                   help="shared output dir (process 0 writes)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="write the run-report JSON here")
    p.add_argument("--slices", type=int, default=1,
                   help="num_slices for the three-tier (slice, site, model) "
                        "mesh; must divide --num-processes (1 = the legacy "
                        "hybrid (site, model) mesh)")
    p.add_argument("--dcn-wire-quant", default="",
                   choices=["", "none", "bf16", "int8", "fp8"],
                   help="inter-slice wire codec (TrainConfig.dcn_wire_quant; "
                        "'' follows --set wire_quant)")
    p.add_argument("--devices-per-process", type=int, default=4,
                   help="virtual CPU devices per process (emulation; "
                        "ignored on real accelerator backends)")
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--task", default="FS-Classification")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="raw TrainConfig overrides (JSON-parsed values)")
    return p.parse_args(argv)


def _config_overrides(pairs):
    out = {}
    for kv in pairs:
        k, _, v = kv.partition("=")
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def _params_checksum(state) -> str:
    """Order-stable digest of the replicated params — every process of a
    correct run reports the SAME hex (params are replicated by the
    aggregation collectives; the multihost smoke bit-compares this across
    processes after one round). ``addressable_data(0)`` reads the local
    replica, so no cross-process fetch is needed."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state.params):
        a = leaf.addressable_data(0) if hasattr(leaf, "addressable_data") else leaf
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    return h.hexdigest()


def main(argv=None) -> int:
    args = _parse(argv if argv is not None else sys.argv[1:])

    # Belt and braces across jax versions: the XLA_FLAGS env var is consumed
    # at backend-client creation (lazy — still effective even when
    # sitecustomize imported jax at interpreter start, as long as no device
    # was queried), and newer jax prefers the jax_num_cpu_devices knob.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count="
            f"{args.devices_per_process}"
        ).strip()

    import jax

    if not os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", args.devices_per_process)
        except AttributeError:
            pass  # older jax: the XLA_FLAGS device-count flag applies

    from dinunet_implementations_tpu.parallel import (
        distributed_init,
        distributed_shutdown,
    )

    multi = distributed_init(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    ) if args.num_processes > 1 else distributed_init()

    import dinunet_implementations_tpu.trainer.loop as loop_mod
    from dinunet_implementations_tpu import TrainConfig
    from dinunet_implementations_tpu.parallel.distributed import (
        spans_processes,
    )
    from dinunet_implementations_tpu.runner import FedRunner

    writes = {"logs": 0, "ckpt": 0}
    _orig_logs = loop_mod.write_logs_json
    _orig_ckpt = loop_mod.save_checkpoint

    def _count_logs(*a, **k):
        writes["logs"] += 1
        return _orig_logs(*a, **k)

    def _count_ckpt(*a, **k):
        writes["ckpt"] += 1
        return _orig_ckpt(*a, **k)

    loop_mod.write_logs_json = _count_logs
    loop_mod.save_checkpoint = _count_ckpt

    # keep the final epoch state visible for the params checksum (the fit
    # result dict carries metrics, not weights) — and the trainer for the
    # CompileGuard-style epoch compile count
    final = {"state": None, "trainer": None}
    _orig_run_epoch = loop_mod.FederatedTrainer.run_epoch

    def _record_run_epoch(self, state, *a, **k):
        out = _orig_run_epoch(self, state, *a, **k)
        final["state"], final["trainer"] = out[0], self
        return out

    loop_mod.FederatedTrainer.run_epoch = _record_run_epoch

    cfg = TrainConfig(
        task_id=args.task, epochs=args.epochs, validation_epochs=2,
        patience=10, batch_size=args.batch_size,
        split_ratio=(0.7, 0.15, 0.15), seed=0,
        num_slices=args.slices, dcn_wire_quant=args.dcn_wire_quant,
    ).with_overrides(_config_overrides(args.overrides))
    runner = FedRunner(cfg, data_path=args.data_path, out_dir=args.out_dir)
    try:
        res = runner.run(verbose=False)[0]
    except Exception as e:  # noqa: BLE001 — capability probe, see below
        if "Multiprocess computations aren't implemented" in str(e):
            # this jaxlib's CPU backend cannot execute cross-process
            # collectives at all (e.g. 0.4.x): report "unsupported",
            # distinct from a real failure, so callers can skip
            print(f"UNSUPPORTED: {e}", flush=True)
            distributed_shutdown()
            return UNSUPPORTED_RC
        raise

    if args.report:
        from dinunet_implementations_tpu.checks.sanitize import jit_cache_size

        trainer = final["trainer"]
        report = {
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "global_devices": len(jax.devices()),
            "local_devices": len(jax.local_devices()),
            "multi": bool(multi),
            "mesh_spans_processes": spans_processes(runner.mesh),
            "mesh_shape": dict(runner.mesh.shape),
            "mesh_axes": list(runner.mesh.axis_names),
            "num_slices": args.slices,
            "epoch_losses": [float(x) for x in res["epoch_losses"]],
            "test_metrics": res["test_metrics"],
            "n_log_writes": writes["logs"],
            "n_ckpt_writes": writes["ckpt"],
            # bit-compared across processes by the multihost smoke: the
            # replicated params after the final round
            "params_sha256": (
                _params_checksum(final["state"])
                if final["state"] is not None else None
            ),
            # the one-epoch-compile-per-process contract (CompileGuard's
            # counter): churnless multi-host training must compile the
            # epoch exactly once in EVERY process
            "epoch_compiles": (
                jit_cache_size(trainer.epoch_fn)
                if trainer is not None else None
            ),
        }
        with open(args.report, "w") as fh:
            json.dump(report, fh)

    # clean teardown: leave the runtime re-entrant (the coordinated barrier
    # in shutdown also surfaces a wedged peer as a nonzero exit, instead of
    # letting a caller's timeout mask it)
    distributed_shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
