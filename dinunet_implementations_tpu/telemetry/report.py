"""Run-summary CLI over telemetry artifacts.

    python -m dinunet_implementations_tpu.telemetry.report <dir> [<dir> ...] \\
        [--validate]

Each ``<dir>`` is a per-fit telemetry directory (``.../telemetry/fold_0``)
or a run-level ``telemetry/`` root (every ``fold_*`` child is summarized).
Multiple dirs render in order; when the fits span more than one dir — the
fleet-scheduler case, one spool root per tenant — a per-tenant rollup
table closes the report (tenant from the r22 manifest tags). Renders, per
fit:

- the manifest header (engine, task, mesh, versions, git rev);
- a phase time table from ``trace.jsonl`` (count / total / mean / max per
  span name — where the epoch's host-blocked time went);
- a per-site rollup from the last epoch row + summary row (grad/residual
  norms, skipped rounds, quarantine);
- counters: epoch compiles, per-epoch transfer bytes, modeled collective
  payload, prefetch stall time.

``--validate`` checks the artifacts against the schema contract
(telemetry/sink.py) instead of rendering, exiting 1 on any problem — the CI
telemetry smoke job's gate.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

from .sink import (
    MANIFEST_FILE,
    METRICS_FILE,
    TRACE_CHROME_FILE,
    TRACE_JSONL_FILE,
    load_metrics,
    validate_manifest,
    validate_metrics_rows,
)


def fit_dirs(path: str) -> list[str]:
    """Per-fit artifact dirs under ``path``: itself when it holds a
    manifest, else its ``fold_*`` children."""
    if os.path.exists(os.path.join(path, MANIFEST_FILE)):
        return [path]
    subs = sorted(
        os.path.join(path, d) for d in os.listdir(path)
        if (d.startswith("fold_") or d.startswith("serv"))
        and os.path.exists(os.path.join(path, d, MANIFEST_FILE))
    )
    if not subs:
        raise FileNotFoundError(
            f"{path}: no {MANIFEST_FILE} here or in fold_* children"
        )
    return subs


def _load_trace(dirpath: str) -> list[dict]:
    path = os.path.join(dirpath, TRACE_JSONL_FILE)
    if not os.path.exists(path):
        return []
    events = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


def phase_table(events: list[dict]) -> list[dict]:
    """Aggregate span durations by name (seconds), longest total first."""
    stats: dict[str, list[float]] = {}
    for e in events:
        if e.get("ph") == "X":
            stats.setdefault(e["name"], []).append(float(e["dur"]) / 1e6)
    return sorted(
        (
            {"phase": name, "count": len(ds), "total_s": sum(ds),
             "mean_ms": 1e3 * sum(ds) / len(ds), "max_ms": 1e3 * max(ds)}
            for name, ds in stats.items()
        ),
        key=lambda r: -r["total_s"],
    )


def _norm(sq) -> float:
    try:
        return math.sqrt(max(float(sq), 0.0))
    except (TypeError, ValueError):
        return float("nan")


def render_fit(dirpath: str) -> None:
    with open(os.path.join(dirpath, MANIFEST_FILE)) as fh:
        manifest = json.load(fh)
    rows = load_metrics(os.path.join(dirpath, METRICS_FILE))
    epochs = [r for r in rows if r.get("kind") == "epoch"]
    events = [r for r in rows if r.get("kind") == "event"]
    summary = next(
        (r for r in rows if r.get("kind") == "summary"), {}
    )
    mesh = manifest.get("mesh")
    print(f"== {dirpath}")
    print(
        f"run: {manifest.get('task_id')} · {manifest.get('agg_engine')} · "
        f"{manifest.get('num_sites')} sites · pipeline="
        f"{manifest.get('pipeline')} · fold {manifest.get('fold')}"
    )
    print(
        f"env: jax {manifest.get('jax_version')} / jaxlib "
        f"{manifest.get('jaxlib_version')} · backend "
        f"{manifest.get('backend')} · mesh "
        f"{mesh if mesh else 'vmap-folded'} · pkg "
        f"{manifest.get('package_version')} · git "
        f"{(manifest.get('git_rev') or 'n/a')[:12]} · cfg "
        f"{manifest.get('config_hash')}"
    )
    table = phase_table(_load_trace(dirpath))
    if table:
        print("-- phase time (from trace.jsonl)")
        print(f"{'phase':<22}{'count':>7}{'total s':>12}{'mean ms':>12}{'max ms':>12}")
        for r in table:
            print(
                f"{r['phase']:<22}{r['count']:>7}{r['total_s']:>12.3f}"
                f"{r['mean_ms']:>12.3f}{r['max_ms']:>12.3f}"
            )
    if epochs:
        last = epochs[-1]
        n_sites = len(last.get("site_grad_sq_last", []))
        skips = summary.get("site_skipped_rounds") or [0] * n_sites
        quar = summary.get("site_quarantined") or [0] * n_sites
        print(f"-- per-site rollup (epoch {last.get('epoch')}, last of "
              f"{len(epochs)} recorded)")
        print(f"{'site':>5}{'grad‖·‖ last':>14}{'grad‖·‖ mean':>14}"
              f"{'resid‖·‖':>11}{'skips':>7}{'quar':>6}")
        rounds = max(float(last.get("rounds", 1)), 1.0)
        for s in range(n_sites):
            print(
                f"{s:>5}"
                f"{_norm(last['site_grad_sq_last'][s]):>14.5f}"
                f"{_norm(last['site_grad_sq_sum'][s] / rounds):>14.5f}"
                f"{_norm(last['site_residual_sq_sum'][s] / rounds):>11.5f}"
                f"{skips[s] if s < len(skips) else 0:>7}"
                f"{quar[s] if s < len(quar) else 0:>6}"
            )
        print(
            f"-- counters: epoch_compiles="
            f"{summary.get('epoch_compiles', 'n/a')} · "
            f"transfer_bytes/epoch={last.get('transfer_bytes', 'n/a')} · "
            f"payload_bytes/round="
            f"{round(float(last.get('payload_bytes', 0)) / rounds)} · "
            f"dcn_bytes/round="
            f"{round(float(last.get('dcn_bytes', 0)) / rounds)} · "
            f"update‖·‖ last={_norm(last.get('update_sq_last', 0)):.5f} · "
            f"prefetch_stall_s={summary.get('prefetch_stall_s', 'n/a')}"
        )
        # privacy plane (r20): the spent (ε, δ) trail — rendered whenever
        # the manifest says a DP mechanism ran, so a noiseless/off run
        # stays terse
        priv = manifest.get("privacy")
        if priv and priv.get("dp_noise_multiplier", 0) > 0:
            eps = last.get("dp_epsilon")
            eps_s = "inf" if eps is None else f"{float(eps):.4f}"
            print(
                f"-- privacy: ε={eps_s} at δ={priv.get('dp_delta')} "
                f"(σ={priv.get('dp_noise_multiplier')}, "
                f"C={priv.get('dp_clip')}, "
                f"budget={priv.get('dp_epsilon_budget') or 'none'}, "
                f"secure_agg={priv.get('secure_agg')}, "
                f"personalize={priv.get('personalize') or '[]'})"
            )
    serve = next(
        (r for r in rows if r.get("kind") == "serve_summary"), None
    )
    if serve:
        def ms(key):
            v = serve.get(key)
            return "n/a" if v is None else format(float(v), ".2f")

        print(
            "-- serving: "
            f"{serve.get('requests')} requests / "
            f"{serve.get('samples')} samples in "
            f"{serve.get('dispatches')} dispatches · latency ms "
            f"p50={ms('latency_ms_p50')} p95={ms('latency_ms_p95')} "
            f"p99={ms('latency_ms_p99')} · "
            f"{serve.get('requests_per_s')} req/s · "
            f"pad_waste={serve.get('pad_waste_pct')}% · "
            f"bucket_hit_rate={serve.get('bucket_hit_rate')} · "
            f"warmup={serve.get('warmup_seconds')}s · "
            f"compiles_after_warmup={serve.get('compiles_after_warmup')}"
        )
    membership = summary.get("membership")
    if membership:
        stale = membership.get("mean_staleness")
        print(
            "-- membership: "
            f"{membership.get('slots_occupied')}/"
            f"{membership.get('capacity')} slots occupied · "
            f"membership_epoch={membership.get('membership_epoch')} · "
            f"mean_staleness="
            f"{'n/a' if stale is None else format(stale, '.2f')} · "
            f"held_rounds={membership.get('held_rounds')}"
        )
    if events:
        counts: dict[str, int] = {}
        for e in events:
            counts[str(e.get("name"))] = counts.get(str(e.get("name")), 0) + 1
        print("-- events: " + ", ".join(f"{n}×{c}" for n, c in counts.items()))
    trace = os.path.join(dirpath, TRACE_CHROME_FILE)
    if os.path.exists(trace):
        print(f"-- trace: load {trace} in Perfetto (ui.perfetto.dev)")


def tenant_rollup(dirs: list[str]) -> list[dict]:
    """Per-tenant aggregate over many fit dirs — the multi-tenant report
    (r23). Tenancy comes from the manifest's r22 ``tags.tenant`` (the
    scheduler stamps each tenant's sink); untagged fits roll up under
    ``-``. Unreadable artifacts degrade to zeros rather than aborting the
    report — a rollup over a live fleet must tolerate a tenant mid-write."""
    acc: dict[str, dict] = {}
    for d in dirs:
        try:
            with open(os.path.join(d, MANIFEST_FILE)) as fh:
                manifest = json.load(fh)
        except (OSError, json.JSONDecodeError):
            manifest = {}
        try:
            rows = load_metrics(os.path.join(d, METRICS_FILE))
        except (OSError, json.JSONDecodeError):
            rows = []
        tenant = str((manifest.get("tags") or {}).get("tenant") or "-")
        epochs = [r for r in rows if r.get("kind") == "epoch"]
        summary = next(
            (r for r in rows if r.get("kind") == "summary"), {}
        )
        serve = next(
            (r for r in rows if r.get("kind") == "serve_summary"), {}
        )
        r = acc.setdefault(tenant, {
            "tenant": tenant, "fits": 0, "epochs": 0, "compiles": 0,
            "transfer_bytes": 0, "serve_requests": 0, "engines": set(),
        })
        r["fits"] += 1
        r["epochs"] += len(epochs)
        r["compiles"] += int(summary.get("epoch_compiles") or 0)
        r["transfer_bytes"] += sum(
            int(e.get("transfer_bytes") or 0) for e in epochs
        )
        r["serve_requests"] += int(serve.get("requests") or 0)
        if manifest.get("agg_engine"):
            r["engines"].add(str(manifest["agg_engine"]))
    return sorted(acc.values(), key=lambda r: r["tenant"])


def render_rollup(rows: list[dict]) -> None:
    print("== per-tenant rollup")
    print(f"{'tenant':<16}{'fits':>6}{'epochs':>8}{'compiles':>10}"
          f"{'xfer MiB':>10}{'serve req':>11}  engines")
    for r in rows:
        print(
            f"{r['tenant']:<16}{r['fits']:>6}{r['epochs']:>8}"
            f"{r['compiles']:>10}"
            f"{r['transfer_bytes'] / 2**20:>10.2f}"
            f"{r['serve_requests']:>11}  "
            f"{','.join(sorted(r['engines'])) or '-'}"
        )


def validate_fit(dirpath: str) -> list[str]:
    problems = []
    mpath = os.path.join(dirpath, MANIFEST_FILE)
    try:
        with open(mpath) as fh:
            problems += [f"{mpath}: {p}" for p in validate_manifest(json.load(fh))]
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"{mpath}: unreadable ({e})")
    rpath = os.path.join(dirpath, METRICS_FILE)
    try:
        problems += [
            f"{rpath}: {p}" for p in validate_metrics_rows(load_metrics(rpath))
        ]
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"{rpath}: unreadable ({e})")
    tpath = os.path.join(dirpath, TRACE_CHROME_FILE)
    try:
        with open(tpath) as fh:
            trace = json.load(fh)
        if not isinstance(trace.get("traceEvents"), list):
            problems.append(f"{tpath}: no traceEvents array")
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"{tpath}: unreadable ({e})")
    return problems


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dinunet_implementations_tpu.telemetry.report",
        description="Render (or --validate) a run summary from telemetry "
                    "artifacts (manifest.json / metrics.jsonl / trace.*).",
    )
    p.add_argument("paths", nargs="+",
                   help="per-fit telemetry dirs (.../telemetry/fold_0) "
                        "and/or telemetry/ roots with fold_* children; "
                        "several dirs get a per-tenant rollup table")
    p.add_argument("--validate", action="store_true",
                   help="check artifacts against the schema contract "
                        "instead of rendering; exit 1 on any problem")
    args = p.parse_args(argv)
    dirs = [d for path in args.paths for d in fit_dirs(path)]
    if args.validate:
        problems = [p for d in dirs for p in validate_fit(d)]
        for prob in problems:
            print(prob, file=sys.stderr)
        print(f"telemetry: validated {len(dirs)} fit(s), "
              f"{len(problems)} problem(s)")
        return 1 if problems else 0
    for d in dirs:
        render_fit(d)
    if len(args.paths) > 1:
        render_rollup(tenant_rollup(dirs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
