"""Deterministic fault injection — the chaos harness behind every robustness
claim in this package.

A :class:`FaultPlan` describes, in *global round* coordinates, which faults a
run should experience:

- ``drop``: scheduled site outages — ``(site, first_round, last_round)``
  triples (inclusive; ``last_round = -1`` means "until the end of training").
  A dropped site is zero-weighted in the round's aggregate (the weighted mean
  renormalizes over live weight only — trainer/steps.py);
- ``flaky_prob``/``flaky_seed``: per-(site, round) random drops under a
  seeded counter-based RNG, so the same plan replays the same outage pattern
  regardless of epoch chunking or resume point;
- ``nan_at``: ``(round, site)`` pairs whose *inputs* are poisoned with NaN in
  the data layer — the gradient then goes non-finite for real and must be
  caught by the in-jit finiteness check + quarantine counters, not by a
  shortcut in the test;
- ``kill_at_round``: simulated preemption — the trainer saves a checkpoint
  and raises :class:`~.preemption.Preempted` once the global round counter
  passes this value (the deterministic arm of the SIGTERM handler);
- ``slice_drop_at``/``slice_delay_at``/``kill_slice_at``: SLICE-tier faults
  (r19) — whole-slice outages on the multi-slice DCN topology
  (parallel/mesh.py ``sliced_site_mesh``), in the same global-round
  coordinates. ``slice_drop_at`` is ``(slice, first_round, last_round)``
  windows (inclusive; ``-1`` = to the end), ``slice_delay_at`` is
  ``(slice, round, delay)`` straggler triples (the slice's DCN hop misses
  rounds ``[round, round + delay)`` — a preempted-and-rescheduled slice),
  and ``kill_slice_at`` is ``(slice, round)`` pairs: the slice dies at that
  round and STAYS dead until a supervisor restarts it. All three render
  into the ``[num_slices, rounds]`` mask of :meth:`FaultPlan
  .slice_liveness` — a traced epoch input exactly like the site mask, so
  ONE compiled program per fit covers any slice-fault pattern. Under the
  supervised multi-process runner (runner/dcn_worker.py) ``kill_slice_at``
  is realized PHYSICALLY instead — the slice's worker process SIGKILLs
  itself when its round counter crosses the kill, and the supervisor's
  restart/consensus-rejoin path is what brings it back — so emulated and
  real runs exercise the same declarative plan
  (``slice_liveness(include_kills=False)`` keeps the mask arm out when the
  process arm owns the fault);
- ``delay_at``: deterministic STRAGGLERS — ``(site, round, delay)`` triples:
  the site's fresh update for rounds ``[round, round + delay)`` never
  arrives (it is "in flight" for ``delay`` rounds). In the bulk-sync
  engines this is indistinguishable from a drop — an update that misses its
  round is lost. Under the buffered-async mode
  (``TrainConfig.staleness_bound > 0``, trainer/steps.py) the site's LAST
  deposited update keeps contributing with staleness-decayed weight until
  the bound masks it — exactly the semantics the staleness buffer exists
  for, exercisable from this same chaos harness.

Masks are plain numpy arrays fed to the compiled epoch as traced inputs:
changing the plan never recompiles the program. ``site`` indices are always
VIRTUAL site ids: under site packing (r12) the ``[S, rounds]`` masks shard
``P(site)`` into per-device ``[K, rounds]`` blocks, so a plan that drops or
poisons site 137 of 512 affects exactly that packed row
(tests/test_packing.py pins packed == unpacked chaos).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass

import numpy as np


def _tuplize(rows, width: int, name: str) -> tuple:
    out = []
    for row in rows:
        row = tuple(int(v) for v in row)
        if len(row) != width:
            raise ValueError(
                f"FaultPlan.{name} entries need {width} integers, got {row!r}"
            )
        out.append(row)
    return tuple(out)


@dataclass(frozen=True)
class FaultPlan:
    """Deterministic fault schedule in global-round coordinates."""

    drop: tuple = ()  # (site, first_round, last_round) triples; -1 = forever
    flaky_prob: float = 0.0
    flaky_seed: int = 0
    nan_at: tuple = ()  # (round, site) pairs
    kill_at_round: int | None = None
    delay_at: tuple = ()  # (site, round, delay) straggler triples
    # -- slice-tier faults (r19, module docstring) -----------------------
    slice_drop_at: tuple = ()  # (slice, first_round, last_round); -1 = forever
    slice_delay_at: tuple = ()  # (slice, round, delay) straggler triples
    kill_slice_at: tuple = ()  # (slice, round): dead from round until restart

    def __post_init__(self):
        object.__setattr__(self, "drop", _tuplize(self.drop, 3, "drop"))
        object.__setattr__(self, "nan_at", _tuplize(self.nan_at, 2, "nan_at"))
        object.__setattr__(self, "delay_at", _tuplize(self.delay_at, 3, "delay_at"))
        object.__setattr__(
            self, "slice_drop_at",
            _tuplize(self.slice_drop_at, 3, "slice_drop_at"),
        )
        object.__setattr__(
            self, "slice_delay_at",
            _tuplize(self.slice_delay_at, 3, "slice_delay_at"),
        )
        object.__setattr__(
            self, "kill_slice_at",
            _tuplize(self.kill_slice_at, 2, "kill_slice_at"),
        )
        if not 0.0 <= float(self.flaky_prob) <= 1.0:
            raise ValueError(
                f"FaultPlan.flaky_prob must be in [0, 1], got {self.flaky_prob}"
            )
        for site, first, last in self.drop:
            if site < 0 or first < 0 or (last != -1 and last < first):
                raise ValueError(f"bad FaultPlan.drop entry {(site, first, last)}")
        for rnd, site in self.nan_at:
            if rnd < 0 or site < 0:
                raise ValueError(f"bad FaultPlan.nan_at entry {(rnd, site)}")
        for site, rnd, delay in self.delay_at:
            if site < 0 or rnd < 0 or delay < 1:
                raise ValueError(
                    f"bad FaultPlan.delay_at entry {(site, rnd, delay)} "
                    "(need site >= 0, round >= 0, delay >= 1)"
                )
        for sl, first, last in self.slice_drop_at:
            if sl < 0 or first < 0 or (last != -1 and last < first):
                raise ValueError(
                    f"bad FaultPlan.slice_drop_at entry {(sl, first, last)}"
                )
        for sl, rnd, delay in self.slice_delay_at:
            if sl < 0 or rnd < 0 or delay < 1:
                raise ValueError(
                    f"bad FaultPlan.slice_delay_at entry {(sl, rnd, delay)} "
                    "(need slice >= 0, round >= 0, delay >= 1)"
                )
        for sl, rnd in self.kill_slice_at:
            if sl < 0 or rnd < 0:
                raise ValueError(
                    f"bad FaultPlan.kill_slice_at entry {(sl, rnd)}"
                )

    # -- round-window mask generation ------------------------------------

    def _flaky_uniform(self, num_sites: int, round_start: int,
                       num_rounds: int) -> np.ndarray:
        """Counter-based uniform ``[num_sites, num_rounds]`` draw keyed by
        (seed, site, GLOBAL round) — a pure vectorized function of the plan
        (splitmix64 finalizer over per-cell counters), so the outage pattern
        is independent of epoch chunking / resume point and costs one numpy
        pass instead of one Generator construction per cell."""
        seed_term = (int(self.flaky_seed) * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        site = np.arange(num_sites, dtype=np.uint64)[:, None]
        rnd = (np.uint64(round_start) + np.arange(num_rounds, dtype=np.uint64))[None, :]
        with np.errstate(over="ignore"):  # uint64 wraparound is the point
            x = (
                np.uint64(seed_term)
                + site * np.uint64(0xD1B54A32D192ED03)
                + rnd * np.uint64(0x8CB92BA72F3D8DD7)
            )
            x ^= x >> np.uint64(30)
            x *= np.uint64(0xBF58476D1CE4E5B9)
            x ^= x >> np.uint64(27)
            x *= np.uint64(0x94D049BB133111EB)
            x ^= x >> np.uint64(31)
        return (x >> np.uint64(11)).astype(np.float64) * 2.0 ** -53

    def liveness(self, num_sites: int, round_start: int, num_rounds: int) -> np.ndarray:
        """``[num_sites, num_rounds]`` float32 mask for the round window
        ``[round_start, round_start + num_rounds)``: 1 = live, 0 = dropped."""
        live = np.ones((num_sites, num_rounds), np.float32)
        for site, first, last in self.drop:
            if site >= num_sites:
                continue
            lo = max(first - round_start, 0)
            hi = num_rounds if last == -1 else min(last + 1 - round_start, num_rounds)
            if lo < hi:
                live[site, lo:hi] = 0.0
        for site, rnd, delay in self.delay_at:
            # a straggling update is a missing ARRIVAL for its in-flight
            # window: zero liveness for [round, round + delay) — the async
            # buffer (trainer/steps.py) then serves the site's previous
            # deposit, decayed; the sync engines see a plain drop
            if site >= num_sites:
                continue
            lo = max(rnd - round_start, 0)
            hi = min(rnd + delay - round_start, num_rounds)
            if lo < hi:
                live[site, lo:hi] = 0.0
        if self.flaky_prob > 0.0:
            draws = self._flaky_uniform(num_sites, round_start, num_rounds)
            live[draws < self.flaky_prob] = 0.0
        return live

    def nan_mask(self, num_sites: int, round_start: int, num_rounds: int) -> np.ndarray:
        """``[num_sites, num_rounds]`` bool mask of (site, round) cells whose
        inputs get poisoned with NaN."""
        mask = np.zeros((num_sites, num_rounds), bool)
        for rnd, site in self.nan_at:
            r = rnd - round_start
            if 0 <= r < num_rounds and site < num_sites:
                mask[site, r] = True
        return mask

    def slice_liveness(self, num_slices: int, round_start: int,
                       num_rounds: int, include_kills: bool = True
                       ) -> np.ndarray:
        """``[num_slices, num_rounds]`` float32 mask for the round window
        ``[round_start, round_start + num_rounds)``: 1 = slice live, 0 =
        slice dead. Pure function of the plan and GLOBAL round coordinates
        (chunk/resume-independent, like :meth:`liveness`).

        ``include_kills=False`` leaves the ``kill_slice_at`` windows out of
        the mask — the supervised multi-process runner realizes those as
        real process deaths (runner/dcn_worker.py), and masking them too
        would keep a restarted slice dead forever."""
        live = np.ones((num_slices, num_rounds), np.float32)
        for sl, first, last in self.slice_drop_at:
            if sl >= num_slices:
                continue
            lo = max(first - round_start, 0)
            hi = num_rounds if last == -1 else min(last + 1 - round_start, num_rounds)
            if lo < hi:
                live[sl, lo:hi] = 0.0
        for sl, rnd, delay in self.slice_delay_at:
            # a straggling slice misses its DCN hop for the in-flight
            # window, exactly like a site-level delay_at misses its arrival
            if sl >= num_slices:
                continue
            lo = max(rnd - round_start, 0)
            hi = min(rnd + delay - round_start, num_rounds)
            if lo < hi:
                live[sl, lo:hi] = 0.0
        if include_kills:
            for sl, rnd in self.kill_slice_at:
                # a killed slice stays dead to the end of the mask: only a
                # supervisor restart (which re-renders without the kill)
                # brings it back
                if sl >= num_slices:
                    continue
                lo = max(rnd - round_start, 0)
                if lo < num_rounds:
                    live[sl, lo:] = 0.0
        return live

    def kill_round_for_slice(self, slice_id: int) -> int | None:
        """The earliest ``kill_slice_at`` round for ``slice_id``, or None —
        the supervised worker's deterministic self-kill arm keys on this."""
        rounds = [r for sl, r in self.kill_slice_at if sl == slice_id]
        return min(rounds) if rounds else None

    def injects_faults(self) -> bool:
        """True when the plan perturbs training rounds (drops / flaky / NaN /
        stragglers) — a kill-only plan needs no per-round masks. Slice-tier
        windows are separate (:meth:`injects_slice_faults`): they render
        into the ``[num_slices, rounds]`` mask, not the site mask."""
        return (
            bool(self.drop) or self.flaky_prob > 0.0 or bool(self.nan_at)
            or bool(self.delay_at)
        )

    def injects_slice_faults(self, include_kills: bool = True) -> bool:
        """True when the plan perturbs the SLICE tier (r19) — the trainer
        then feeds the ``[num_slices, rounds]`` slice mask as a traced
        input. Same ``include_kills`` semantics as :meth:`slice_liveness`."""
        return bool(
            self.slice_drop_at or self.slice_delay_at
            or (include_kills and self.kill_slice_at)
        )

    # -- JSON round-trip (CLI / bench surface) ---------------------------

    def to_json(self) -> dict:
        return {
            "drop": [list(t) for t in self.drop],
            "flaky_prob": self.flaky_prob,
            "flaky_seed": self.flaky_seed,
            "nan_at": [list(t) for t in self.nan_at],
            "kill_at_round": self.kill_at_round,
            "delay_at": [list(t) for t in self.delay_at],
            "slice_drop_at": [list(t) for t in self.slice_drop_at],
            "slice_delay_at": [list(t) for t in self.slice_delay_at],
            "kill_slice_at": [list(t) for t in self.kill_slice_at],
        }

    @classmethod
    def from_json(cls, spec) -> "FaultPlan":
        """Build from a dict or a JSON string (the CLI/bench flag payload)."""
        if isinstance(spec, (str, bytes)):
            spec = json.loads(spec)
        if not isinstance(spec, dict):
            raise ValueError(f"FaultPlan spec must be a JSON object, got {type(spec)}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(
                f"unknown FaultPlan keys {sorted(unknown)} (have {sorted(known)})"
            )
        return cls(**spec)


def parse_fault_plan(arg: str | None) -> FaultPlan | None:
    """Parse the ``--faults`` flag: inline JSON, or ``@path`` to a JSON file."""
    if not arg:
        return None
    if arg.startswith("@"):
        with open(arg[1:]) as fh:
            return FaultPlan.from_json(fh.read())
    if os.path.exists(arg):  # a bare path also works
        with open(arg) as fh:
            return FaultPlan.from_json(fh.read())
    return FaultPlan.from_json(arg)


def fault_window(plan: FaultPlan | None, num_sites: int, round0: int,
                 rounds: int):
    """The per-epoch fault masks for the global round window
    ``[round0, round0 + rounds)``: ``(liveness, nan_mask)``, or
    ``(None, None)`` when the plan injects nothing. The ONE place both input
    pipelines (trainer/loop.py host materialization and device index plans)
    derive their window math from, so the device==host bit-exactness
    contract cannot drift between them."""
    if plan is None or not plan.injects_faults():
        return None, None
    return (
        plan.liveness(num_sites, round0, rounds),
        plan.nan_mask(num_sites, round0, rounds),
    )


def slice_fault_window(plan: FaultPlan | None, num_slices: int, round0: int,
                       rounds: int, include_kills: bool = True):
    """The per-epoch SLICE-liveness mask for the global round window
    ``[round0, round0 + rounds)`` — ``[num_slices, rounds]`` float32, or
    ``None`` when the plan has no slice-tier faults (or the topology has no
    slice tier to fault). The one place both pipelines derive the slice
    window from, mirroring :func:`fault_window`."""
    if (
        plan is None or num_slices <= 1
        or not plan.injects_slice_faults(include_kills)
    ):
        return None
    return plan.slice_liveness(
        num_slices, round0, rounds, include_kills=include_kills
    )


def poison_inputs(inputs: np.ndarray, nan_mask: np.ndarray,
                  local_iterations: int) -> np.ndarray:
    """Data-layer NaN injection: overwrite the poisoned (site, round) cells'
    step blocks with NaN in a copy of the epoch inputs ``[S, steps, B, ...]``.

    Each round spans ``local_iterations`` consecutive steps (the gradient-
    accumulation block — trainer/steps.py), so the poisoned site's gradient
    for that round goes non-finite end to end, exercising the real in-jit
    finiteness check rather than a synthetic gradient override.
    """
    if not nan_mask.any():
        return inputs
    out = np.array(inputs, copy=True)
    L = max(int(local_iterations), 1)
    for site, rnd in zip(*np.nonzero(nan_mask)):
        lo = rnd * L
        out[site, lo:lo + L] = np.nan
    return out
