"""Worker process for the real-SIGTERM crash-resume test (test_robustness.py).

    python preempt_worker.py <out_dir> <epochs> [--resume]

Runs a deterministic toy federated fit (data generated from fixed seeds, so
every invocation — full, killed, resumed — sees identical inputs). Prints one
line per validation epoch (the parent uses those to time its SIGTERM). On
:class:`Preempted` the trainer has already saved the rotating checkpoint; the
worker exits with the signal convention code (143 for SIGTERM). On completion
it writes ``<out_dir>/results.json``.
"""

import json
import os
import sys

# env before the jax import (conftest.py does the same for the test process)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from dinunet_implementations_tpu import TrainConfig  # noqa: E402
from dinunet_implementations_tpu.data.api import SiteArrays  # noqa: E402
from dinunet_implementations_tpu.models import MSANNet  # noqa: E402
from dinunet_implementations_tpu.parallel import host_mesh  # noqa: E402
from dinunet_implementations_tpu.robustness import Preempted  # noqa: E402
from dinunet_implementations_tpu.trainer import FederatedTrainer  # noqa: E402


def toy_sites(ns, n, seed):
    out = []
    rng = np.random.default_rng(seed)
    for _ in range(ns):
        X = rng.normal(size=(n, 6)).astype(np.float32)
        y = (X.sum(-1) > 0).astype(np.int32)
        out.append(SiteArrays(X, y, np.arange(n, dtype=np.int32)))
    return out


def main():
    out_dir = sys.argv[1]
    epochs = int(sys.argv[2])
    resume = "--resume" in sys.argv

    cfg = TrainConfig(epochs=epochs, patience=100, batch_size=8,
                      validation_epochs=1)
    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    tr = FederatedTrainer(cfg, model, host_mesh(2), out_dir=out_dir)
    train = toy_sites(2, 40, seed=4)
    val = toy_sites(2, 16, seed=5)
    test = toy_sites(2, 16, seed=6)
    try:
        res = tr.fit(train, val, test, fold=0, verbose=True, resume=resume)
    except Preempted as p:
        print(f"PREEMPTED epoch={p.epoch}", flush=True)
        sys.exit(p.exit_code)
    with open(os.path.join(out_dir, "results.json"), "w") as fh:
        json.dump({
            "test_metrics": res["test_metrics"],
            "best_val_epoch": res["best_val_epoch"],
            "epoch_losses": res["epoch_losses"],
        }, fh)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
