"""Mesh + collectives tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from dinunet_implementations_tpu.core.jaxcompat import shard_map

from dinunet_implementations_tpu.parallel import (
    SITE_AXIS,
    host_mesh,
    make_site_mesh,
    payload_cast,
    payload_uncast,
    site_mean,
    site_sum,
    site_weighted_mean,
)


def test_device_count():
    assert len(jax.devices()) == 8


def test_make_site_mesh_shapes():
    mesh = host_mesh(8)
    assert mesh.shape[SITE_AXIS] == 8
    mesh2 = make_site_mesh(4, model_axis_size=2)
    assert mesh2.shape[SITE_AXIS] == 4
    assert mesh2.shape["model"] == 2
    with pytest.raises(ValueError):
        make_site_mesh(16)


def _run_sharded(mesh, fn, x, in_spec=P(SITE_AXIS), out_spec=P(SITE_AXIS)):
    return shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec)(x)


def test_site_sum_and_mean():
    mesh = host_mesh(8)
    x = jnp.arange(8.0).reshape(8, 1)
    out = _run_sharded(mesh, lambda v: site_sum({"g": v})["g"], x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))
    out = _run_sharded(mesh, lambda v: site_mean({"g": v})["g"], x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 3.5))


def test_site_weighted_mean_matches_pooled():
    """Weighted site mean == pooled mean over all examples (dSGD invariant)."""
    mesh = host_mesh(4)
    rng = np.random.default_rng(0)
    # 4 sites with heterogeneous example counts (like FS fixture 73-120 subjects)
    counts = np.array([3.0, 5.0, 2.0, 7.0])
    grads = rng.normal(size=(4, 6)).astype(np.float32)  # per-site mean gradient
    pooled = (grads * counts[:, None]).sum(0) / counts.sum()

    def fn(g, w):
        return site_weighted_mean({"g": g}, w[0])["g"]

    out = shard_map(fn, mesh=mesh, in_specs=(P(SITE_AXIS), P(SITE_AXIS)), out_specs=P(SITE_AXIS))(
        jnp.asarray(grads), jnp.asarray(counts)
    )
    for i in range(4):
        np.testing.assert_allclose(np.asarray(out)[i], pooled, rtol=1e-5)


def test_payload_cast_roundtrip():
    tree = {"w": jnp.ones((4, 4), jnp.float32)}
    cast = payload_cast(tree, "16")
    assert cast["w"].dtype == jnp.bfloat16
    back = payload_uncast(cast, tree)
    assert back["w"].dtype == jnp.float32
    same = payload_cast(tree, "32")
    assert same["w"].dtype == jnp.float32
    # compat mode: the reference's literal IEEE fp16 payload
    # (compspec.json:161-176) — "16" is bf16 on TPU, "16-ieee" opts into fp16
    ieee = payload_cast(tree, "16-ieee")
    assert ieee["w"].dtype == jnp.float16


def test_weighted_mean_accumulates_fp32():
    """Review finding: bf16 payloads must still reduce in fp32."""
    mesh = host_mesh(4)
    g = jnp.array([300.0, 0.5, 0.5, 0.5], jnp.bfloat16).reshape(4, 1)
    w = jnp.ones((4,))
    out = shard_map(
        lambda gv, wv: site_weighted_mean({"g": gv}, wv[0])["g"],
        mesh=mesh, in_specs=(P(SITE_AXIS), P(SITE_AXIS)), out_specs=P(SITE_AXIS),
    )(g, w)
    assert out.dtype == jnp.bfloat16
    # true mean 75.375; bf16(75.375)=75.5 but naive bf16 accumulation drifts to 75.0
    np.testing.assert_allclose(np.asarray(out, np.float32), 75.5)


# ---------------------------------------------------------------------------
# wire codecs (r14 — parallel/collectives.py WireCodec)
# ---------------------------------------------------------------------------


def test_wire_codec_none_is_legacy_roundtrip():
    from dinunet_implementations_tpu.parallel.collectives import (
        resolve_wire_codec,
        wire_compress,
    )

    x = jnp.linspace(-2.0, 2.0, 32)
    for bits in ("32", "16", "16-ieee"):
        c = resolve_wire_codec(bits, "none")
        np.testing.assert_array_equal(
            np.asarray(c.compress(x)), np.asarray(wire_compress(x, c.dtype))
        )


def test_wire_codec_int8_error_bound_and_grid():
    """Scale-per-payload symmetric int8: relative error bounded by half a
    grid step of the payload's amax, grid values round-trip exactly."""
    from dinunet_implementations_tpu.parallel.collectives import (
        resolve_wire_codec,
    )

    c = resolve_wire_codec("32", "int8")
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(scale=3e-4, size=(64, 32)).astype(np.float32))
    y = c.compress(x)
    amax = float(jnp.abs(x).max())
    assert float(jnp.abs(y - x).max()) <= 0.5 * amax / 127 + 1e-12
    # exact grid points survive the round trip bit-for-bit
    grid = jnp.asarray([0.0, 127.0, -127.0, 64.0])
    np.testing.assert_array_equal(np.asarray(c.compress(grid)),
                                  np.asarray(grid))


def test_wire_codec_fp8_scales_small_gradients():
    """Raw-cast fp8 flushes ~1e-4 gradients to zero; the scale-per-payload
    codec must preserve them to e4m3 relative precision (~6%)."""
    from dinunet_implementations_tpu.parallel.collectives import (
        resolve_wire_codec,
    )

    c = resolve_wire_codec("32", "fp8")
    x = jnp.asarray(
        np.random.default_rng(1).normal(scale=1e-4, size=(128,))
        .astype(np.float32)
    )
    y = c.compress(x)
    assert float(jnp.abs(y).max()) > 0
    rel = float(jnp.abs(y - x).max() / jnp.abs(x).max())
    assert rel < 0.07, rel
    # a raw cast (no scaling) really does lose these values — the scale is
    # doing the work
    raw = x.astype(jnp.float8_e4m3fn).astype(jnp.float32)
    assert float(jnp.abs(raw).max()) == 0.0


def test_wire_codec_zero_and_batched_scales():
    from dinunet_implementations_tpu.parallel.collectives import (
        resolve_wire_codec,
    )

    c = resolve_wire_codec("32", "int8")
    # an all-zero (dead-site-masked) payload stays exactly zero, no NaN
    z = c.compress(jnp.zeros((4, 4)))
    np.testing.assert_array_equal(np.asarray(z), 0.0)
    # batched=True: one scale per leading (virtual-site) row — rows at
    # wildly different magnitudes each keep their own relative precision
    rows = jnp.stack([
        jnp.linspace(-1e-4, 1e-4, 16), jnp.linspace(-1e3, 1e3, 16)
    ])
    y = c.compress(rows, batched=True)
    for i in range(2):
        rel = float(jnp.abs(y[i] - rows[i]).max() / jnp.abs(rows[i]).max())
        assert rel <= 0.5 / 127 + 1e-9, (i, rel)


def test_wire_codec_stochastic_rounding_unbiased():
    """Stochastic int8 rounding: deterministic (value-hashed dither) yet
    unbiased in expectation — the mean quantization error over many values
    must be far below half a grid step (RNE on a one-sided distribution
    would not be)."""
    from dinunet_implementations_tpu.parallel.collectives import (
        resolve_wire_codec,
    )

    sr = resolve_wire_codec("32", "int8", stochastic=True)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.uniform(-1.0, 1.0, size=(200_000,))
                    .astype(np.float32))
    y = sr.compress(x)
    step = 1.0 / 127
    assert abs(float(jnp.mean(y - x))) < 0.02 * step
    # deterministic: same input, same output
    np.testing.assert_array_equal(np.asarray(sr.compress(x)), np.asarray(y))
    # stochastic only applies to int8
    assert resolve_wire_codec("32", "fp8", stochastic=True).stochastic is False


def test_two_level_psum_accepts_codec():
    """The packed partial re-quantizes through the codec before the
    cross-device hop — values equal the codec round-trip of the local sum."""
    from dinunet_implementations_tpu.parallel.collectives import (
        PackedAxis,
        resolve_wire_codec,
        two_level_psum,
    )

    c = resolve_wire_codec("32", "int8")
    x = jnp.asarray(
        np.random.default_rng(3).normal(size=(4, 8)).astype(np.float32)
    )
    out = two_level_psum(x, PackedAxis(None, 4), wire_dtype=c)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(c.compress(jnp.sum(x, axis=0)))
    )


def test_wire_codec_rejects_unknown_quant():
    from dinunet_implementations_tpu.parallel.collectives import (
        resolve_wire_codec,
    )

    with pytest.raises(ValueError, match="wire_quant"):
        resolve_wire_codec("32", "int4")


def test_quantized_engines_approximate_f32_aggregate():
    """dSGD/rankDAD/powerSGD under int8 and fp8 wires: the aggregate stays
    within the codec's error envelope of the f32 aggregate — quantization
    compresses the wire, it does not change the math."""
    from dinunet_implementations_tpu.engines import make_engine

    rng = np.random.default_rng(4)
    S = 3
    grads = {
        "k": jnp.asarray(rng.normal(size=(S, 6, 4)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(S, 4)).astype(np.float32)),
    }
    row = jax.tree.map(lambda g: g[0], grads)
    w = jnp.ones((S,))

    def run(eng):
        st = jax.tree.map(lambda a: jnp.stack([a] * S), eng.init(row))
        agg, _ = jax.vmap(
            lambda g, s, ww: eng.aggregate(g, s, ww, "site"),
            axis_name="site",
        )(grads, st, w)
        return agg

    for name in ("dSGD", "rankDAD", "powerSGD"):
        ref = run(make_engine(name, dad_reduction_rank=2))
        for quant, tol in (("int8", 0.02), ("fp8", 0.1)):
            got = run(make_engine(name, dad_reduction_rank=2,
                                  wire_quant=quant))
            for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
                err = float(jnp.abs(a - b).max())
                assert err < tol, (name, quant, err)
