"""Multi-host (DCN) layer — single-process behavior and mesh topology.

True multi-process execution needs a pod; what IS testable on one host (and
what these tests pin) is the contract everything else relies on:
``distributed_init`` no-ops for single-process runs, ``multihost_site_mesh``
degenerates to the plain ``(site, model)`` mesh, and the mesh it builds
carries working collectives. The hybrid-DCN branch itself is exercised by the
same ``mesh_utils.create_hybrid_device_mesh`` JAX ships for pod meshes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from dinunet_implementations_tpu.parallel import (
    MODEL_AXIS,
    SITE_AXIS,
    distributed_init,
    multihost_site_mesh,
)


def test_single_process_init_is_noop():
    assert distributed_init() is False
    assert distributed_init(num_processes=1) is False


def test_mesh_shape_and_axis_names():
    mesh = multihost_site_mesh(sites_per_process=4, model_axis_size=2)
    assert dict(mesh.shape) == {SITE_AXIS: 4, MODEL_AXIS: 2}
    assert mesh.axis_names == (SITE_AXIS, MODEL_AXIS)


def test_mesh_defaults_fill_the_process():
    mesh = multihost_site_mesh()
    assert dict(mesh.shape) == {SITE_AXIS: len(jax.devices()), MODEL_AXIS: 1}


def test_mesh_uses_leading_subset_when_devices_surplus():
    # 3 sites x model=2 on 8 devices: 6 used, 2 idle (same contract as
    # make_site_mesh's devices[:need] on one host)
    mesh = multihost_site_mesh(sites_per_process=3, model_axis_size=2)
    assert dict(mesh.shape) == {SITE_AXIS: 3, MODEL_AXIS: 2}
    assert list(mesh.devices.flat) == jax.devices()[:6]


def test_mesh_rejects_oversubscription():
    with pytest.raises(ValueError, match="devices per process"):
        multihost_site_mesh(sites_per_process=5, model_axis_size=2)


def test_collectives_run_on_the_mesh():
    mesh = multihost_site_mesh(sites_per_process=4, model_axis_size=2)
    x = jnp.arange(8.0).reshape(4, 2)

    out = jax.jit(
        shard_map(
            lambda v: jax.lax.psum(v, (SITE_AXIS, MODEL_AXIS)),
            mesh=mesh,
            in_specs=P(SITE_AXIS, MODEL_AXIS),
            out_specs=P(SITE_AXIS, MODEL_AXIS),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((4, 2), x.sum()))


def test_put_site_batch_single_process_commits_site_sharding():
    from dinunet_implementations_tpu.parallel.distributed import put_site_batch

    mesh = multihost_site_mesh(sites_per_process=8)
    a = np.arange(8 * 3 * 2, dtype=np.float32).reshape(8, 3, 2)
    arr = put_site_batch(mesh, a)
    assert arr.sharding.spec == P(SITE_AXIS)
    np.testing.assert_array_equal(np.asarray(arr), a)
    cast = put_site_batch(mesh, a, dtype="bfloat16")
    assert str(cast.dtype) == "bfloat16"


def test_fetch_site_outputs_single_process_is_numpy_identity():
    from dinunet_implementations_tpu.parallel.distributed import (
        fetch_site_outputs,
    )

    mesh = multihost_site_mesh(sites_per_process=8)
    tree = (jnp.arange(8.0), {"x": jnp.ones((8, 2))})
    out = fetch_site_outputs(tree, mesh)
    assert isinstance(out[0], np.ndarray)
    np.testing.assert_array_equal(out[0], np.arange(8.0))
    np.testing.assert_array_equal(out[1]["x"], np.ones((8, 2)))


def test_trainer_on_mesh_with_committed_batches():
    """The put/fetch plumbing drives a real federated fit on a host mesh and
    matches the vmap (mesh=None) path's losses."""
    from dinunet_implementations_tpu.core.config import TrainConfig
    from dinunet_implementations_tpu.data.api import SiteArrays
    from dinunet_implementations_tpu.models import MSANNet
    from dinunet_implementations_tpu.trainer import FederatedTrainer

    rng = np.random.default_rng(0)
    sites = []
    for s in range(4):
        y = (rng.random(16) > 0.5).astype(np.int64)
        x = rng.normal(size=(16, 6)).astype(np.float32) + y[:, None]
        sites.append(SiteArrays(x, y, np.arange(16)))
    cfg = TrainConfig(task_id="FS-Classification", batch_size=8, epochs=3,
                      validation_epochs=1, patience=10)
    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    mesh = multihost_site_mesh(sites_per_process=4)
    res_mesh = FederatedTrainer(cfg, model, mesh=mesh).fit(
        sites, sites, sites, verbose=False)
    res_vmap = FederatedTrainer(cfg, model, mesh=None).fit(
        sites, sites, sites, verbose=False)
    np.testing.assert_allclose(res_mesh["epoch_losses"],
                               res_vmap["epoch_losses"], rtol=1e-5)
