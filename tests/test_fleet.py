"""Serving fleet (r21): replicated engines with sharded session affinity,
zero-recompile params hot-swap, SLO-driven admission, and the train-to-serve
CD plane.

The load-bearing claims, as tests:

- a streaming session NEVER splits across replicas — every chunk of a
  session lands on its home replica (crc32 shard), and the per-replica
  session tables partition the session space (eviction and generation
  discipline hold per shard);
- a crashed replica's sessions re-home through the FRESH gate: the
  supervisor restarts the slot at a bumped membership generation, and a
  re-homed session's replay is BIT-EXACT with a fresh single-engine run —
  stale carries cannot resurrect across restarts or route moves;
- served probabilities from the fleet are BITWISE the single-engine
  reference at every bucket, before AND after params hot-swaps, and the
  CompileGuard zero-compile proof extends across ≥2 swaps;
- the publish gauntlet (serving/publish.py): stale-digest gate, shadow-lane
  rejection of non-finite candidates, SLO-error-budget rollback that
  restores the retained weights — all as pure buffer donation;
- admission (r21 microbatcher): priority lanes over FIFO, deadline
  shedding, max_queue shedding at submit — and the p99-targeted max-delay
  autotuner whose dual-conservative histogram bounds give it a dead band
  (no oscillation on bucket error).

The host-side logic (shard function, admission, autotuner, histogram
windows, watcher, version gate) runs in the fast tier; every test that
warms real engines (multi-replica AOT warmups + donated swap grafts) is
``slow`` — the fast gate's wall-clock budget has no headroom for ~10
fleet warmups, and the CI fleet smoke drives the same claims end to end
through the CLI on every PR anyway.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.core.config import NNComputation, TrainConfig
from dinunet_implementations_tpu.core.jaxcompat import stream_cache_safe
from dinunet_implementations_tpu.runner.registry import get_task
from dinunet_implementations_tpu.serving import (
    AutotunerDaemon,
    CheckpointWatcher,
    DelayAutotuner,
    InferenceEngine,
    Microbatcher,
    PublishController,
    ReplicaSet,
    RequestError,
    RequestFuture,
    home_slot,
)
from dinunet_implementations_tpu.serving.engine import ServingError
from dinunet_implementations_tpu.telemetry.bus import MetricsBus
from dinunet_implementations_tpu.telemetry.hist import (
    HistogramShapeError,
    LogHistogram,
)
from dinunet_implementations_tpu.trainer.steps import FederatedTask


# ---------------------------------------------------------------------------
# fixtures (tiny CPU corners; conftest forces 8 virtual devices)
# ---------------------------------------------------------------------------


def _ica_cfg():
    return TrainConfig(
        task_id=NNComputation.TASK_ICA, epochs=1, batch_size=4, seed=5,
    ).with_overrides({"ica_args": {
        "num_components": 3, "window_size": 4, "temporal_size": 32,
        "window_stride": 4, "input_size": 8, "hidden_size": 6,
        "bidirectional": False,
    }})


def _fs_cfg():
    return TrainConfig(
        task_id=NNComputation.TASK_FREE_SURFER, epochs=1, batch_size=4,
        seed=3,
    ).with_overrides({"fs_args": {"input_size": 6, "hidden_sizes": [8]}})


def _init(cfg, sample):
    task = FederatedTask(get_task(cfg.task_id).build_model(cfg))
    params, stats = task.init_variables(jax.random.PRNGKey(0), sample)
    return task, params, stats


@pytest.fixture(scope="module")
def ica_env():
    cfg = _ica_cfg()
    task, params, stats = _init(cfg, jnp.ones((2, 8, 3, 4)))
    return cfg, task, params, stats


@pytest.fixture(scope="module")
def fs_env():
    cfg = _fs_cfg()
    task, params, stats = _init(cfg, jnp.ones((4, 6)))
    return cfg, task, params, stats


def _make_fleet(env, replicas=2, **kw):
    cfg, _, params, stats = env
    kw.setdefault("row_buckets", (1, 2, 4))
    kw.setdefault("stream_buckets", (1, 2))
    kw.setdefault("stream_chunk", 4)
    kw.setdefault("stream_slots", 4)
    kw.setdefault("max_delay_ms", 1.0)
    kw.setdefault("supervise_interval_s", 0.05)
    kw.setdefault("bus", MetricsBus())
    fleet = ReplicaSet(cfg, replicas=replicas, params=params,
                       batch_stats=stats, **kw)
    fleet.warmup()
    return fleet


def _seq(seed=1, windows=12):
    return np.random.default_rng(seed).normal(
        size=(windows, 3, 4)
    ).astype(np.float32)


def _wait_restart(fleet, slot, want, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fleet.restarts >= want and fleet._replica_alive(slot):
            return
        time.sleep(0.02)
    raise AssertionError(f"replica {slot} did not restart in {timeout}s")


# ---------------------------------------------------------------------------
# sharded session affinity
# ---------------------------------------------------------------------------


def test_home_slot_is_stable_and_covers_shards():
    sids = [f"session-{i}" for i in range(64)]
    slots = [home_slot(s, 4) for s in sids]
    assert slots == [home_slot(s, 4) for s in sids]  # deterministic
    assert set(slots) == {0, 1, 2, 3}  # every shard gets sessions
    assert all(0 <= s < 4 for s in slots)


@pytest.mark.slow
def test_sessions_never_split_across_replicas(ica_env):
    """Every chunk of a session routes to its home replica; afterwards each
    session id is resident in EXACTLY one replica's session table."""
    fleet = _make_fleet(ica_env, replicas=2, stream_slots=8)
    try:
        sids = [f"aff-{i}" for i in range(6)]
        for sid in sids:
            seq = _seq(seed=hash(sid) % 1000)
            for lo in range(0, 12, 4):
                fleet.stream(sid, seq[lo:lo + 4]).result()
            assert fleet.replica_of(sid) == home_slot(sid, 2)
        for sid in sids:
            residents = [
                i for i, eng in enumerate(fleet._engines)
                if eng.sessions.slot_of(sid) is not None
            ]
            assert residents == [home_slot(sid, 2)], sid
    finally:
        fleet.close()


@pytest.mark.slow
def test_eviction_and_generation_discipline_per_shard(ica_env):
    """LRU eviction and generation bumps happen inside ONE shard's table —
    traffic on one replica cannot evict the other replica's sessions."""
    fleet = _make_fleet(ica_env, replicas=2, stream_slots=2)
    try:
        # pin one session on each shard, then overflow shard 0 only
        by_home = {0: [], 1: []}
        i = 0
        while len(by_home[0]) < 4 or len(by_home[1]) < 1:
            sid = f"evict-{i}"
            i += 1
            h = home_slot(sid, 2)
            if len(by_home[h]) < (4 if h == 0 else 1):
                by_home[h].append(sid)
        keeper = by_home[1][0]
        fleet.stream(keeper, _seq()[:4]).result()
        for sid in by_home[0]:  # 4 sessions through 2 slots → evictions
            fleet.stream(sid, _seq()[:4]).result()
        e0, e1 = fleet._engines
        assert e0.sessions.evictions >= 2
        assert e1.sessions.evictions == 0
        assert e1.sessions.slot_of(keeper) is not None  # untouched shard
        # an evicted session comes back FRESH at a bumped generation
        victim = by_home[0][0]
        assert e0.sessions.slot_of(victim) is None
        slot, gen, fresh = e0.sessions.resolve(victim)
        assert fresh and gen == 2
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# crash → supervised restart → fresh-gate re-home
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_rehomed_session_replays_bit_exact_from_fresh_gate(ica_env):
    """Kill a replica mid-conversation: the supervisor restarts the slot at
    a bumped membership generation, the router drops every route into it,
    and a client replaying its session from the start lands BITWISE on the
    original answers — the fresh gate zeroed the carry, nothing stale
    carried over."""
    fleet = _make_fleet(ica_env, replicas=2)
    try:
        sid = next(
            f"victim-{i}" for i in range(100)
            if home_slot(f"victim-{i}", 2) == 0
        )
        seq = _seq(seed=9)
        ref = [
            np.asarray(fleet.stream(sid, seq[lo:lo + 4]).result()["probs"])
            for lo in range(0, 12, 4)
        ]
        gen_before = fleet.table.generation_of("replica-0")
        fleet.kill_replica(0)
        _wait_restart(fleet, 0, want=1)
        assert fleet.table.generation_of("replica-0") == gen_before + 1
        assert fleet.replica_of(sid) is None  # route dropped with the slot
        got = [
            np.asarray(fleet.stream(sid, seq[lo:lo + 4]).result()["probs"])
            for lo in range(0, 12, 4)
        ]
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(a, b)
        assert fleet.restarts == 1
        fleet.assert_no_compiles()
    finally:
        fleet.close()


@pytest.mark.slow
def test_restarted_replica_serves_current_weights(ica_env):
    """A replica restarted AFTER a hot-swap must serve the published
    params, not the boot checkpoint — the fleet re-seeds restarts from its
    host-side live-weights copy."""
    cfg, task, params, stats = ica_env
    fleet = _make_fleet(ica_env, replicas=2)
    try:
        new_params = jax.tree.map(lambda x: np.asarray(x) + 0.01, params)
        fleet.swap_params(new_params, stats)
        fleet.kill_replica(0)
        _wait_restart(fleet, 0, want=1)
        sid = next(
            f"w-{i}" for i in range(100) if home_slot(f"w-{i}", 2) == 0
        )
        seq = _seq(seed=11)
        got = np.asarray(fleet.stream(sid, seq[:4]).result()["probs"])
        with InferenceEngine(
            cfg, params=new_params, batch_stats=stats, row_buckets=(1,),
            stream_buckets=(1,), stream_chunk=4, stream_slots=2,
            max_delay_ms=1.0,
        ) as ref_eng:
            ref_eng.warmup()
            ref = np.asarray(ref_eng.stream("r", seq[:4]).result()["probs"])
        np.testing.assert_array_equal(got, ref)
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# bit-exactness vs the single-engine reference, across swaps
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_bit_exact_vs_single_engine_every_bucket(ica_env):
    cfg, task, params, stats = ica_env
    rng = np.random.default_rng(3)
    fleet = _make_fleet(ica_env, replicas=2)
    try:
        with InferenceEngine(
            cfg, params=params, batch_stats=stats, row_buckets=(1, 2, 4),
            streaming=False, max_delay_ms=1.0,
        ) as ref_eng:
            ref_eng.warmup()
            for rows in (1, 2, 4):
                x = rng.normal(size=(rows, 8, 3, 4)).astype(np.float32)
                got = np.asarray(fleet.submit(x).result())
                ref = np.asarray(ref_eng.submit(x).result())
                np.testing.assert_array_equal(got, ref)
    finally:
        fleet.close()


@pytest.mark.slow
def test_two_hot_swaps_zero_compile_and_bit_exact(ica_env):
    """The acceptance claim: CompileGuard stays at max_compiles=0 ACROSS
    two publishes, and after each swap the fleet's answers are bitwise the
    single-engine reference built directly on the swapped params."""
    cfg, task, params, stats = ica_env
    rng = np.random.default_rng(4)
    probes = {
        rows: rng.normal(size=(rows, 8, 3, 4)).astype(np.float32)
        for rows in (1, 2, 4)
    }

    def reference(p):
        with InferenceEngine(
            cfg, params=p, batch_stats=stats, row_buckets=(1, 2, 4),
            streaming=False, max_delay_ms=1.0,
        ) as eng:
            eng.warmup()
            return {
                rows: np.asarray(eng.submit(x).result())
                for rows, x in probes.items()
            }

    p1 = jax.tree.map(lambda x: np.asarray(x) + 0.01, params)
    p2 = jax.tree.map(lambda x: np.asarray(x) - 0.02, params)
    fleet = _make_fleet(ica_env, replicas=2, streaming=False)
    try:
        for cand in (p1, p2):
            got_pause = fleet.swap_params(cand, stats)
            assert got_pause["pause_ms"] >= 0
            assert len(got_pause["per_replica"]) == 2
            ref = reference(cand)
            for rows, x in probes.items():
                np.testing.assert_array_equal(
                    np.asarray(fleet.submit(x).result()), ref[rows]
                )
        fleet.assert_no_compiles()  # the guard spans both publishes
        summary = fleet.close()
        assert summary["swaps"] == 4  # 2 publishes × 2 replicas
        assert summary["compiles_after_warmup"] == 0
    except BaseException:
        fleet.close()
        raise


@pytest.mark.slow
def test_swap_refuses_shape_mismatch(ica_env):
    cfg, task, params, stats = ica_env
    fleet = _make_fleet(ica_env, replicas=2, streaming=False)
    try:
        bad = jax.tree.map(
            lambda x: np.zeros(np.asarray(x).shape + (1,), np.float32),
            params,
        )
        with pytest.raises(ServingError, match="hot-swap refused"):
            fleet.swap_params(bad, stats)
        # the live weights never moved
        x = np.zeros((1, 8, 3, 4), np.float32)
        got = np.asarray(fleet.submit(x).result())
        assert np.all(np.isfinite(got))
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# publish plane: gauntlet + rollback
# ---------------------------------------------------------------------------


class _ListSink:
    def __init__(self):
        self.rows = []

    def append(self, row):
        self.rows.append(row)

    def close(self):
        pass


@pytest.mark.slow
def test_publish_gauntlet_and_slo_rollback(fs_env):
    """Stale-digest gate, shadow rejection of a non-finite candidate,
    healthy probation release, and an induced SLO-burn rollback restoring
    the retained weights — every step emitting its schema row."""
    cfg, task, params, stats = fs_env
    bus = MetricsBus()
    sink = _ListSink()
    rng = np.random.default_rng(0)
    with InferenceEngine(
        cfg, params=params, batch_stats=stats, row_buckets=(2, 4),
        streaming=False, max_delay_ms=1.0, bus=bus,
    ) as eng:
        eng.warmup()
        for _ in range(8):
            eng.submit(rng.normal(size=(2, 6)).astype(np.float32)).result()
        pc = PublishController(
            eng, bus=bus, sink=sink, p99_target_ms=50.0,
            rollback_burn=1.0, min_window_samples=5,
        )
        cand = jax.tree.map(lambda x: np.asarray(x) + 0.01, params)
        assert pc.publish(cand, stats, digest="d1")["outcome"] == "swapped"
        assert pc.publish(
            cand, stats, digest="d1"
        )["outcome"] == "rejected-stale"
        bad = jax.tree.map(
            lambda x: np.full_like(np.asarray(x), np.nan), params
        )
        row = pc.publish(bad, stats, digest="d2")
        assert row["outcome"] == "rejected-shadow"
        assert row["shadow"]["finite"] is False
        assert pc.live_digest == "d1"  # live params never moved

        # probation: too-thin window → no verdict; then a healthy release
        assert pc.check_rollback() is None
        for _ in range(6):
            eng.submit(rng.normal(size=(2, 6)).astype(np.float32)).result()
        verdict = pc.check_rollback()
        assert verdict["rolled_back"] is False
        assert pc.check_rollback() is None  # probation is one verdict

        # induced burn: swap again, poison the latency series, roll back
        assert pc.publish(
            jax.tree.map(lambda x: np.asarray(x) + 0.02, params),
            stats, digest="d3",
        )["outcome"] == "swapped"
        for _ in range(30):
            bus.observe("serving_request_latency_ms", 500.0, lane="infer")
        verdict = pc.check_rollback()
        assert verdict["rolled_back"] is True
        assert verdict["burn"] > 1.0
        assert pc.live_digest == "d1"  # the retained weights are live again
        eng.assert_no_compiles()  # every swap + rollback was a donation

    # schema: every emitted row carries its kind's required keys
    from dinunet_implementations_tpu.telemetry.sink import ROW_REQUIRED

    kinds = [r["kind"] for r in sink.rows]
    assert kinds.count("publish") == 4 and kinds.count("rollback") == 2
    for row in sink.rows:
        assert ROW_REQUIRED[row["kind"]] <= set(row), row


def test_checkpoint_watcher_fingerprint_and_digest(tmp_path):
    path = str(tmp_path / "publish.json")
    w = CheckpointWatcher(path)
    assert w.poll() is None  # missing file

    def announce(digest, epoch):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"path": "ck.msgpack", "digest": digest,
                       "epoch": epoch}, f)
        os.replace(tmp, path)

    announce("aaa", 1)
    got = w.poll()
    assert got is not None and got["digest"] == "aaa"
    assert w.poll() is None  # unchanged fingerprint
    announce("aaa", 2)  # rewritten, same digest → still stale
    assert w.poll() is None
    announce("bbb", 3)
    assert w.poll()["digest"] == "bbb"
    with open(path + ".tmp2", "w") as f:
        f.write("{not json")
    os.replace(path + ".tmp2", path)
    assert w.poll() is None  # unparseable: skip, don't raise


def test_params_digest_keyed_by_values_and_shapes(fs_env):
    from dinunet_implementations_tpu.trainer.checkpoint import params_digest

    cfg, task, params, stats = fs_env
    d1 = params_digest(params, stats)
    assert d1 == params_digest(params, stats)  # deterministic
    moved = jax.tree.map(lambda x: np.asarray(x) + 1e-6, params)
    assert params_digest(moved, stats) != d1


# ---------------------------------------------------------------------------
# admission: priority, deadline, max_queue
# ---------------------------------------------------------------------------


class _Req:
    def __init__(self, n, priority=0, deadline_ms=None):
        self.rows = np.zeros((n, 2), np.float32)
        self.priority = priority
        self.deadline_ms = deadline_ms
        self.future = RequestFuture()


def _gated_dispatch(order, gate):
    """Dispatch that records batch identity and blocks on ``gate`` for the
    FIRST batch only — holds the lane so later submissions pile up
    pending."""
    first = threading.Event()

    def dispatch(batch, bucket):
        if not first.is_set():
            first.set()
            gate.wait(10)
        order.append([r.tag for r in batch])
        for r in batch:
            r.future.set_result(None)

    return dispatch


def test_priority_overtakes_fifo_within_pending():
    order, gate = [], threading.Event()
    mb = Microbatcher(
        _gated_dispatch(order, gate), buckets=(2,), max_delay_ms=5.0
    )
    reqs = {}
    for tag, prio in (("blocker", 0), ("lo", 0), ("mid", 1), ("hi", 5)):
        r = _Req(2, priority=prio)
        r.tag = tag
        reqs[tag] = r
    mb.submit(reqs["blocker"])
    while not mb.stats["dispatches"] and mb.depth():
        time.sleep(0.002)  # blocker is IN dispatch, lane held
    for tag in ("lo", "mid", "hi"):  # FIFO arrival, priority order out
        mb.submit(reqs[tag])
    gate.set()
    for r in reqs.values():
        r.future.result(timeout=10)
    mb.close()
    assert order == [["blocker"], ["hi"], ["mid"], ["lo"]]


def test_default_priority_preserves_fifo():
    order, gate = [], threading.Event()
    mb = Microbatcher(
        _gated_dispatch(order, gate), buckets=(2,), max_delay_ms=5.0
    )
    reqs = []
    for i in range(4):
        r = _Req(2)
        r.tag = i
        reqs.append(r)
        mb.submit(r)
    gate.set()
    for r in reqs:
        r.future.result(timeout=10)
    mb.close()
    assert order == [[0], [1], [2], [3]]


def test_deadline_shedding_fails_fast():
    order, gate = [], threading.Event()
    mb = Microbatcher(
        _gated_dispatch(order, gate), buckets=(2,), max_delay_ms=1.0
    )
    blocker = _Req(2)
    blocker.tag = "blocker"
    mb.submit(blocker)
    doomed = _Req(2, deadline_ms=5.0)
    doomed.tag = "doomed"
    survivor = _Req(2, deadline_ms=60_000.0)
    survivor.tag = "survivor"
    mb.submit(doomed)
    mb.submit(survivor)
    time.sleep(0.05)  # doomed's 5 ms deadline lapses while the lane holds
    gate.set()
    with pytest.raises(RequestError, match="deadline"):
        doomed.future.result(timeout=10)
    survivor.future.result(timeout=10)
    mb.close()
    assert mb.stats["shed"] == 1
    assert ["survivor"] in order and ["doomed"] not in order


def test_max_queue_sheds_at_admission():
    bus = MetricsBus()
    order, gate = [], threading.Event()
    mb = Microbatcher(
        _gated_dispatch(order, gate), buckets=(2,), max_delay_ms=1.0,
        max_queue=1, bus=bus,
    )
    blocker = _Req(2)
    blocker.tag = "blocker"
    mb.submit(blocker)
    while not mb.stats["dispatches"] and mb.depth():
        time.sleep(0.002)
    queued = _Req(2)
    queued.tag = "queued"
    mb.submit(queued)  # depth 1 = bound
    with pytest.raises(RequestError, match="queue full"):
        mb.submit(_Req(2))
    gate.set()
    queued.future.result(timeout=10)
    mb.close()
    assert mb.stats["shed"] == 1
    sheds = {
        k: v for k, v in bus.snapshot()["counters"].items()
        if k.startswith("serving_shed_total") and 'why="queue_full"' in k
    }
    assert list(sheds.values()) == [1]


# ---------------------------------------------------------------------------
# the p99-targeted max-delay autotuner
# ---------------------------------------------------------------------------


class _Lane:
    def __init__(self, delay_ms=2.0):
        self.max_delay_s = delay_ms / 1e3
        self.name = "infer"
        self.labels = {}


def _hist(values):
    h = LogHistogram()
    for v in values:
        h.record(v)
    return h


def test_autotuner_shrinks_only_on_certain_violations():
    lane = _Lane(delay_ms=2.0)
    t = DelayAutotuner(lane, p99_target_ms=10.0, budget=0.01,
                       min_samples=10)
    # 10% of samples certainly above 10 ms target → shrink
    assert t.step(_hist([1.0] * 90 + [100.0] * 10)) == "shrink"
    assert lane.max_delay_s == pytest.approx(1e-3)
    # samples NEAR the target (same bucket) are not certain violations:
    # the dead band holds instead of flapping
    assert t.step(_hist([10.0] * 100)) == "hold"


def test_autotuner_grows_only_with_proven_slack():
    lane = _Lane(delay_ms=2.0)
    t = DelayAutotuner(lane, p99_target_ms=100.0, budget=0.01,
                       headroom=0.5, min_samples=10)
    # upper-edge p99 well under target × headroom → provable slack
    assert t.step(_hist([1.0] * 100)) == "grow"
    assert lane.max_delay_s == pytest.approx(2.5e-3)
    # p99 between headroom and target: neither certainty → hold
    assert t.step(_hist([80.0] * 100)) == "hold"


def test_autotuner_holds_on_thin_windows_and_clamps():
    lane = _Lane(delay_ms=0.05)
    t = DelayAutotuner(lane, p99_target_ms=10.0, min_samples=50,
                       min_delay_ms=0.05)
    assert t.step(_hist([100.0] * 10)) == "hold"  # too few samples
    assert t.step(None) == "hold"
    # parked at the min clamp: a shrink that cannot move reports hold
    assert t.step(_hist([100.0] * 60)) == "hold"
    assert lane.max_delay_s == pytest.approx(5e-5)
    with pytest.raises(ValueError):
        DelayAutotuner(_Lane(), p99_target_ms=1.0, headroom=1.5)
    with pytest.raises(ValueError):
        DelayAutotuner(_Lane(), p99_target_ms=1.0, shrink=1.5)


def test_autotuner_daemon_steps_on_window_deltas():
    bus = MetricsBus()
    lane = _Lane(delay_ms=2.0)
    tuner = DelayAutotuner(lane, p99_target_ms=10.0, budget=0.01,
                           min_samples=10, bus=bus)
    daemon = AutotunerDaemon(bus, [tuner], interval_s=60.0)
    for _ in range(20):
        bus.observe("serving_request_latency_ms", 1.0, lane="infer")
    daemon.tick()  # first tick: baseline only, no window yet
    assert tuner.decisions == {"shrink": 0, "grow": 0, "hold": 1}
    for _ in range(20):
        bus.observe("serving_request_latency_ms", 100.0, lane="infer")
    daemon.tick()  # window = the 20 slow samples only → shrink
    assert tuner.decisions["shrink"] == 1
    assert lane.max_delay_s == pytest.approx(1e-3)
    daemon.stop()


@pytest.mark.slow
def test_engine_wires_priority_and_deadline(fs_env):
    cfg, task, params, stats = fs_env
    with InferenceEngine(
        cfg, params=params, batch_stats=stats, row_buckets=(2,),
        streaming=False, max_delay_ms=1.0, max_queue=64,
    ) as eng:
        eng.warmup()
        x = np.zeros((2, 6), np.float32)
        got = eng.submit(x, priority=3, deadline_ms=60_000.0).result()
        assert np.all(np.isfinite(np.asarray(got)))
        assert eng.status()["shed"] == 0


# ---------------------------------------------------------------------------
# histogram windows
# ---------------------------------------------------------------------------


def test_hist_delta_is_exact_window():
    a = _hist([1.0, 5.0, 50.0])
    snap = a.copy()
    for v in (2.0, 200.0):
        a.record(v)
    d = a.delta(snap)
    assert d.count == 2
    assert d.sum == pytest.approx(202.0)
    merged = snap.copy().merge(d)
    assert merged.counts == a.counts and merged.count == a.count


def test_hist_delta_rejects_backwards_series():
    a = _hist([1.0, 2.0, 3.0])
    b = _hist([1.0])
    with pytest.raises(HistogramShapeError, match="backwards"):
        b.delta(a)  # b is not a later snapshot of a's series


# ---------------------------------------------------------------------------
# streaming-warmup cache bypass: version gate + regression probe
# ---------------------------------------------------------------------------


def test_stream_cache_gate_versions():
    """The PR 10 cache bypass is now a jaxlib-version gate: closed (bypass
    on) through 0.4.x, open from 0.5 — and unparseable versions stay on
    the safe side."""
    assert stream_cache_safe("0.4.36") is False
    assert stream_cache_safe("0.4.99") is False
    assert stream_cache_safe("0.5.0") is True
    assert stream_cache_safe("1.0.0") is True
    assert stream_cache_safe("garbage") is False
    import jaxlib

    assert stream_cache_safe() is stream_cache_safe(jaxlib.__version__)


@pytest.mark.slow
def test_streaming_warmup_applies_gate(ica_env, monkeypatch):
    """While the gate is closed on the running jaxlib, a streaming warmup
    must turn the compilation cache OFF for the duration of warmup (the
    heap-corruption guard) and restore it after; once a fixed jaxlib opens
    the gate, warmup must NOT touch the cache toggle."""
    cfg, task, params, stats = ica_env
    toggles = []
    real_update = jax.config.update

    def spy(key, value):
        if key == "jax_enable_compilation_cache":
            toggles.append(value)
        return real_update(key, value)

    monkeypatch.setattr(jax.config, "update", spy)
    prev = jax.config.jax_enable_compilation_cache
    with InferenceEngine(
        cfg, params=params, batch_stats=stats, row_buckets=(1,),
        stream_buckets=(1,), stream_chunk=4, stream_slots=2,
        max_delay_ms=1.0,
    ) as eng:
        eng.warmup()
        assert eng.streaming
    if stream_cache_safe():
        assert toggles == [prev]  # gate open: no bypass, no-op restore only
    else:
        assert toggles == [False, prev]  # bypass on, then restored
    assert jax.config.jax_enable_compilation_cache == prev


@pytest.mark.skipif(
    not stream_cache_safe(),
    reason="jaxlib still in the cache-deserialization heap-corruption "
           "range — the repro below is expected to crash; run it when a "
           "fixed jaxlib opens the gate to retire the bypass",
)
def test_stream_cache_regression_probe(tmp_path):
    """The retirement probe: on a gated-OPEN jaxlib, a subprocess that
    deserializes a streaming executable from the compile cache and then
    runs donated-table stream steps must exit cleanly. While the gate is
    closed this test SKIPS (running it would segfault the worker)."""
    import subprocess
    import sys

    code = """
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax, jax.numpy as jnp
from dinunet_implementations_tpu.core.config import NNComputation, TrainConfig
from dinunet_implementations_tpu.runner.registry import get_task
from dinunet_implementations_tpu.serving.engine import InferenceEngine
from dinunet_implementations_tpu.trainer.steps import FederatedTask
import numpy as np

cfg = TrainConfig(task_id=NNComputation.TASK_ICA).with_overrides({
    "ica_args": {"num_components": 3, "window_size": 4,
                 "temporal_size": 32, "window_stride": 4,
                 "input_size": 8, "hidden_size": 6,
                 "bidirectional": False},
}).replace(compile_cache_dir=%r)
task = FederatedTask(get_task(cfg.task_id).build_model(cfg))
params, stats = task.init_variables(jax.random.PRNGKey(0),
                                    jnp.ones((2, 8, 3, 4)))
for round in range(2):  # round 1 compiles+serializes, round 2 deserializes
    eng = InferenceEngine(cfg, params=params, batch_stats=stats,
                          row_buckets=(1,), stream_buckets=(1,),
                          stream_chunk=4, stream_slots=2, max_delay_ms=1.0)
    eng.warmup()
    x = np.zeros((4, 3, 4), np.float32)
    for _ in range(8):
        eng.stream("s", x).result()
    eng.close()
print("CLEAN")
""" % str(tmp_path / "cache")
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CLEAN" in proc.stdout


# ---------------------------------------------------------------------------
# fleet rollup + status surfaces
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_fleet_summary_and_status_shapes(ica_env):
    from dinunet_implementations_tpu.telemetry.sink import ROW_REQUIRED

    sink = _ListSink()
    fleet = _make_fleet(ica_env, replicas=2, sink=sink)
    rng = np.random.default_rng(7)
    for _ in range(4):
        fleet.submit(rng.normal(size=(2, 8, 3, 4)).astype(np.float32)).result()
    st = fleet.status()
    assert st["replicas"] == 2 and st["replicas_live"] == 2
    assert set(st["per_replica"]) == {"replica-0", "replica-1"}
    assert st["membership"]["slots"] == ["replica-0", "replica-1"]
    probes = fleet.health_probes()
    assert all(p() for p in probes.values())
    fleet.close()
    # per-replica rows + ONE fleet rollup row, all schema-complete
    rollups = [r for r in sink.rows if r.get("replica") == "fleet"]
    assert len(rollups) == 1
    per_replica = [
        r for r in sink.rows
        if r.get("kind") == "serve_summary" and r.get("replica") != "fleet"
    ]
    assert {r["replica"] for r in per_replica} == {"0", "1"}
    for row in rollups + per_replica:
        assert ROW_REQUIRED["serve_summary"] <= set(row), row
    assert rollups[0]["requests"] == 4
    assert rollups[0]["compiles_after_warmup"] == 0


def test_fleet_rejects_bad_arguments(ica_env):
    cfg = ica_env[0]
    with pytest.raises(ServingError, match=">= 1 replica"):
        ReplicaSet(cfg, replicas=0, params={})
    with pytest.raises(ServingError, match="checkpoint path or explicit"):
        ReplicaSet(cfg, replicas=1)
    fleet = ReplicaSet(cfg, replicas=1, params=ica_env[2],
                       batch_stats=ica_env[3])
    with pytest.raises(ServingError, match="warmup"):
        fleet.submit(np.zeros((1, 8, 3, 4), np.float32))
