"""Multi-host (DCN) layer — mesh topology AND live multi-process execution.

Two layers of coverage:
- single-process contracts: ``distributed_init`` no-ops, mesh degeneration,
  collectives on the host mesh, put/fetch plumbing;
- a LIVE 2-process jax.distributed CPU run (VERDICT r3 #1):
  ``test_two_process_dcn_runtime_live`` launches two coordinated worker
  processes (tests/dcn_worker.py, 4 virtual devices each) that train
  FedRunner end-to-end over a real spans-processes mesh — executing the
  ``make_array_from_process_local_data`` feed, ``process_allgather`` fetch,
  and process-0-only write branches that no single-process test can reach.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from dinunet_implementations_tpu.core.jaxcompat import shard_map
from jax.sharding import PartitionSpec as P

from dinunet_implementations_tpu.parallel import (
    MODEL_AXIS,
    SITE_AXIS,
    distributed_init,
    multihost_site_mesh,
)


def test_single_process_init_is_noop():
    assert distributed_init() is False
    assert distributed_init(num_processes=1) is False


def test_mesh_shape_and_axis_names():
    mesh = multihost_site_mesh(sites_per_process=4, model_axis_size=2)
    assert dict(mesh.shape) == {SITE_AXIS: 4, MODEL_AXIS: 2}
    assert mesh.axis_names == (SITE_AXIS, MODEL_AXIS)


def test_mesh_defaults_fill_the_process():
    mesh = multihost_site_mesh()
    assert dict(mesh.shape) == {SITE_AXIS: len(jax.devices()), MODEL_AXIS: 1}


def test_mesh_uses_leading_subset_when_devices_surplus():
    # 3 sites x model=2 on 8 devices: 6 used, 2 idle (same contract as
    # make_site_mesh's devices[:need] on one host)
    mesh = multihost_site_mesh(sites_per_process=3, model_axis_size=2)
    assert dict(mesh.shape) == {SITE_AXIS: 3, MODEL_AXIS: 2}
    assert list(mesh.devices.flat) == jax.devices()[:6]


def test_mesh_rejects_oversubscription():
    with pytest.raises(ValueError, match="devices per process"):
        multihost_site_mesh(sites_per_process=5, model_axis_size=2)


def test_collectives_run_on_the_mesh():
    mesh = multihost_site_mesh(sites_per_process=4, model_axis_size=2)
    x = jnp.arange(8.0).reshape(4, 2)

    out = jax.jit(
        shard_map(
            lambda v: jax.lax.psum(v, (SITE_AXIS, MODEL_AXIS)),
            mesh=mesh,
            in_specs=P(SITE_AXIS, MODEL_AXIS),
            out_specs=P(SITE_AXIS, MODEL_AXIS),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((4, 2), x.sum()))


def test_put_site_batch_single_process_commits_site_sharding():
    from dinunet_implementations_tpu.parallel.distributed import put_site_batch

    mesh = multihost_site_mesh(sites_per_process=8)
    a = np.arange(8 * 3 * 2, dtype=np.float32).reshape(8, 3, 2)
    arr = put_site_batch(mesh, a)
    assert arr.sharding.spec == P(SITE_AXIS)
    np.testing.assert_array_equal(np.asarray(arr), a)
    cast = put_site_batch(mesh, a, dtype="bfloat16")
    assert str(cast.dtype) == "bfloat16"


def test_coordinator_join_deadline_fails_fast(monkeypatch):
    """Satellite regression (r19): the DCN coordinator-join path keeps its
    with_retry(deadline_s=) contract — a coordinator that never comes up
    fails the worker within the wall-clock budget instead of retrying
    forever (the hung-coordinator fail-fast PR 8 gave
    jax.distributed.initialize)."""
    import time

    from dinunet_implementations_tpu.parallel import distributed as dist

    calls = {"n": 0}

    def refused(**kw):
        calls["n"] += 1
        raise ConnectionRefusedError("coordinator not up")

    monkeypatch.setattr(dist.jax.distributed, "initialize", refused)
    monkeypatch.setattr(dist.jax.distributed, "shutdown", lambda: None)
    monkeypatch.setattr(dist, "_jax_distributed_client", lambda: None)
    monkeypatch.setattr(dist, "_initialized", False)
    t0 = time.monotonic()
    with pytest.raises(ConnectionRefusedError):
        dist.distributed_init(
            coordinator_address="127.0.0.1:1", num_processes=2,
            process_id=1, join_deadline_s=0.6, join_timeout_s=None,
        )
    elapsed = time.monotonic() - t0
    # at least one retry happened, and the deadline capped the total —
    # never the unbounded 3-attempt exponential backoff
    assert calls["n"] >= 2
    assert elapsed < 5.0
    assert dist._initialized is False


def test_coordinator_join_attempt_timeout_is_fatal(monkeypatch):
    """A join attempt that HANGS (wedged coordinator accepting the TCP
    connect and never completing the handshake) is abandoned after
    join_timeout_s and FAILS the operation — a timed-out attempt's zombie
    thread may still be mutating jax's global distributed state, so
    retrying would race it (distributed_init retry_on_timeout=False)."""
    import time

    from dinunet_implementations_tpu.parallel import distributed as dist
    from dinunet_implementations_tpu.robustness.retry import RetryTimeout
    from dinunet_implementations_tpu.telemetry.bus import global_bus

    def hung(**kw):
        time.sleep(30)

    monkeypatch.setattr(dist.jax.distributed, "initialize", hung)
    monkeypatch.setattr(dist, "_jax_distributed_client", lambda: None)
    monkeypatch.setattr(dist, "_initialized", False)
    t0 = time.monotonic()
    with pytest.raises(RetryTimeout):
        dist.distributed_init(
            coordinator_address="127.0.0.1:1", num_processes=2,
            process_id=1, join_deadline_s=30.0, join_timeout_s=0.3,
        )
    assert time.monotonic() - t0 < 5.0
    assert dist._initialized is False
    # the dcn_timeout observability: the failure landed on the live bus
    counters = global_bus().snapshot().get("counters", {})
    assert any("dcn_join_timeouts_total" in k for k in counters)


def test_fetch_site_outputs_single_process_is_numpy_identity():
    from dinunet_implementations_tpu.parallel.distributed import (
        fetch_site_outputs,
    )

    mesh = multihost_site_mesh(sites_per_process=8)
    tree = (jnp.arange(8.0), {"x": jnp.ones((8, 2))})
    out = fetch_site_outputs(tree, mesh)
    assert isinstance(out[0], np.ndarray)
    np.testing.assert_array_equal(out[0], np.arange(8.0))
    np.testing.assert_array_equal(out[1]["x"], np.ones((8, 2)))


# ---------------------------------------------------------------------------
# Live multi-process DCN execution (VERDICT r3 #1): two coordinated
# jax.distributed CPU processes (4 virtual devices each) drive FedRunner
# end-to-end through the spans_processes branches — put_site_batch's
# make_array_from_process_local_data, fetch_site_outputs' process_allgather,
# and the process-0-only output writes. The reference's execution model IS
# multi-process (one container per site, entry.py:5); this is its live
# TPU-native equivalent, scaled to what one host can test.
# ---------------------------------------------------------------------------


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_dcn_workers(data_path, out_dir, reports, nproc, timeout=420,
                     extra=()):
    """Launch the coordinated workers with stdout redirected to files —
    the workers are barrier-coupled through jax.distributed, so a full
    OS pipe on one would deadlock them all; files also survive a timeout
    for the failure diagnostics. ``extra`` appends module flags (the
    worker graduated to runner/dcn_worker.py in r18 — e.g.
    ``["--slices", "2"]`` for the multi-slice smoke)."""
    import subprocess
    import sys
    import time

    worker = os.path.join(os.path.dirname(__file__), "dcn_worker.py")
    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    log_paths = [f"{rep}.log" for rep in reports]
    procs = []
    for r in range(nproc):
        with open(log_paths[r], "w") as log:
            procs.append(subprocess.Popen(
                [sys.executable, worker, str(port), str(nproc), str(r),
                 str(data_path), str(out_dir), str(reports[r]),
                 *[str(a) for a in extra]],
                stdout=log, stderr=subprocess.STDOUT, env=env,
            ))
    deadline = time.monotonic() + timeout
    try:
        for p in procs:
            p.wait(timeout=max(deadline - time.monotonic(), 1))
    except subprocess.TimeoutExpired:
        pass
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    if any(p.returncode == 66 for p in procs):
        # worker-side capability probe (dcn_worker.py): this jaxlib's CPU
        # backend cannot execute cross-process collectives at all
        pytest.skip("multiprocess CPU collectives unsupported by this jaxlib")
    for r, p in enumerate(procs):
        out = open(log_paths[r]).read()
        assert p.returncode == 0, f"worker {r} rc={p.returncode}:\n{out[-4000:]}"
    return [json.load(open(rep)) for rep in reports]


@pytest.mark.slow
def test_two_process_dcn_runtime_live(tmp_path):
    """The multi-host runtime executes for real: identical losses on every
    process AND vs the single-process run, with exactly one process writing
    the shared output directory."""
    from dinunet_implementations_tpu.data.demo import make_demo_tree

    data = tmp_path / "demo"
    make_demo_tree(str(data))  # 4 sites → 2 per process

    # --- 2-process coordinated run (shared out dir, like a shared FS)
    out2 = tmp_path / "out_2proc"
    reps = [tmp_path / f"rep{r}.json" for r in range(2)]
    r0, r1 = _run_dcn_workers(data, out2, reps, nproc=2)

    for r in (r0, r1):
        assert r["multi"] is True
        assert r["process_count"] == 2
        assert r["global_devices"] == 8 and r["local_devices"] == 4
        assert r["mesh_spans_processes"] is True
        assert r["mesh_shape"] == {SITE_AXIS: 4, MODEL_AXIS: 1}
    assert r0["process_index"] == 0 and r1["process_index"] == 1

    # every process computes identical replicated results...
    np.testing.assert_array_equal(r0["epoch_losses"], r1["epoch_losses"])
    assert r0["test_metrics"] == r1["test_metrics"]
    # ...and only process 0 touches the shared output directory
    assert r0["n_log_writes"] > 0 and r0["n_ckpt_writes"] > 0
    assert r1["n_log_writes"] == 0 and r1["n_ckpt_writes"] == 0
    logs = sorted(p.relative_to(out2).as_posix()
                  for p in out2.rglob("logs.json"))
    assert any(l.startswith("remote/") for l in logs), logs

    # --- single-process reference run: the DCN topology must not change math
    out1 = tmp_path / "out_1proc"
    (r_solo,) = _run_dcn_workers(data, out1, [tmp_path / "rep_solo.json"],
                                 nproc=1)
    assert r_solo["multi"] is False
    assert r_solo["mesh_spans_processes"] is False
    # cross-process results are bit-identical (asserted above); vs the
    # single-process topology XLA lowers the site-psum differently (gloo
    # cross-process collective vs intra-process reduction), so the losses
    # agree to 1 ulp rather than bitwise
    np.testing.assert_allclose(
        r0["epoch_losses"], r_solo["epoch_losses"], rtol=3e-7, atol=0,
    )
    # test_metrics are rounded to 5 decimals — the 1-ulp divergence can
    # still flip a rounding boundary, so compare at that granularity
    np.testing.assert_allclose(
        r0["test_metrics"], r_solo["test_metrics"], atol=1.1e-5,
    )


@pytest.mark.slow
def test_two_process_multislice_smoke(tmp_path):
    """r18 multi-slice over real processes: 2 coordinated workers form a
    (slice=2, site, model) mesh — one process per slice, the inter-slice
    aggregation hop is the only per-round DCN traffic — and after training
    the replicated params agree BIT-FOR-BIT across processes (sha256 of
    every leaf) with the epoch compiled exactly once per process (the
    CompileGuard one-program contract, reported as the jit cache size)."""
    from dinunet_implementations_tpu.data.demo import make_demo_tree

    data = tmp_path / "demo"
    make_demo_tree(str(data))  # 4 sites → 2 per slice

    out = tmp_path / "out_slices"
    reps = [tmp_path / f"slrep{r}.json" for r in range(2)]
    r0, r1 = _run_dcn_workers(
        data, out, reps, nproc=2,
        extra=["--slices", "2", "--epochs", "2"],
    )
    for r in (r0, r1):
        assert r["multi"] is True and r["mesh_spans_processes"] is True
        assert r["mesh_axes"] == ["slice", "site", "model"]
        assert r["mesh_shape"]["slice"] == 2
        assert r["num_slices"] == 2
        # one epoch compile per process — multi-slice must not retrace
        assert r["epoch_compiles"] == 1, r["epoch_compiles"]
    # cross-process param agreement after the rounds: the replicated
    # params digest is identical on every process
    assert r0["params_sha256"] is not None
    assert r0["params_sha256"] == r1["params_sha256"]
    np.testing.assert_array_equal(r0["epoch_losses"], r1["epoch_losses"])
    # process-0-only output contract survives the sliced topology
    assert r0["n_log_writes"] > 0 and r1["n_log_writes"] == 0


@pytest.mark.slow
def test_supervised_chaos_kill_one_worker_completes(tmp_path):
    """r19 chaos smoke (the tier-1 mirror of the CI multislice job): a
    2-process supervised multi-slice run whose FaultPlan SIGKILLs slice
    1's worker mid-run. The supervisor must record the death (liveness
    spool + flight dump carrying the slice id and heartbeat age), restart
    the fleet from the cross-slice checkpoint consensus, and complete —
    with final params bit-identical to a no-fault reference run (resume
    is bit-exact, so the surviving-slice trajectory reconverges on the
    uninterrupted one). Skips on jaxlibs without multiprocess CPU
    collectives (rc 66)."""
    import glob
    import subprocess
    import sys

    from dinunet_implementations_tpu.data.demo import make_demo_tree
    from dinunet_implementations_tpu.runner.supervisor import (
        read_slice_liveness,
    )

    data = tmp_path / "demo"
    make_demo_tree(str(data))  # 4 sites → 2 per slice
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

    def supervised(out, rep, faults=None):
        argv = [
            sys.executable, "-m",
            "dinunet_implementations_tpu.runner.dcn_worker",
            "--supervise", "--num-processes", "2", "--slices", "2",
            "--epochs", "4", "--data-path", str(data),
            "--out-dir", str(out), "--report", str(rep),
            "--heartbeat-timeout-s", "120",
        ]
        if faults:
            argv += ["--faults", faults]
        return subprocess.run(
            argv, env=env, capture_output=True, text=True, timeout=900,
        )

    chaos = supervised(
        tmp_path / "chaos", tmp_path / "chaos_rep.json",
        faults='{"kill_slice_at":[[1,2]]}',
    )
    if chaos.returncode == 66:
        pytest.skip("multiprocess CPU collectives unsupported (rc 66)")
    assert chaos.returncode == 0, chaos.stdout[-4000:] + chaos.stderr[-4000:]
    events = read_slice_liveness(str(tmp_path / "chaos" / "slice_liveness"))
    kinds = [(e["event"], e["slice"]) for e in events]
    assert ("dead", 1) in kinds and ("alive", 1) in kinds, kinds
    dumps = glob.glob(str(tmp_path / "chaos" / "flight_*.json"))
    reasons = [json.load(open(p))["reason"] for p in dumps]
    assert any(r.startswith("slice-death:slice=1") for r in reasons), reasons

    ref = supervised(tmp_path / "ref", tmp_path / "ref_rep.json")
    assert ref.returncode == 0, ref.stdout[-4000:] + ref.stderr[-4000:]
    r_chaos = json.load(open(tmp_path / "chaos_rep_p0.json"))
    r_ref = json.load(open(tmp_path / "ref_rep_p0.json"))
    assert r_chaos["restart_generation"] == 2  # the rejoined incarnation
    assert r_chaos["params_sha256"] == r_ref["params_sha256"] is not None


@pytest.mark.slow
def test_trainer_on_mesh_with_committed_batches():
    """The put/fetch plumbing drives a real federated fit on a host mesh and
    matches the vmap (mesh=None) path's losses."""
    from dinunet_implementations_tpu.core.config import TrainConfig
    from dinunet_implementations_tpu.data.api import SiteArrays
    from dinunet_implementations_tpu.models import MSANNet
    from dinunet_implementations_tpu.trainer import FederatedTrainer

    rng = np.random.default_rng(0)
    sites = []
    for s in range(4):
        y = (rng.random(16) > 0.5).astype(np.int64)
        x = rng.normal(size=(16, 6)).astype(np.float32) + y[:, None]
        sites.append(SiteArrays(x, y, np.arange(16)))
    cfg = TrainConfig(task_id="FS-Classification", batch_size=8, epochs=3,
                      validation_epochs=1, patience=10)
    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    mesh = multihost_site_mesh(sites_per_process=4)
    res_mesh = FederatedTrainer(cfg, model, mesh=mesh).fit(
        sites, sites, sites, verbose=False)
    res_vmap = FederatedTrainer(cfg, model, mesh=None).fit(
        sites, sites, sites, verbose=False)
    np.testing.assert_allclose(res_mesh["epoch_losses"],
                               res_vmap["epoch_losses"], rtol=1e-5)
