"""Dataset / data-handle abstraction surface.

Keeps the reference's framework contract (SURVEY.md §2.3: ``COINNDataset`` with
``cache``/``state``/``indices``/``path()`` + hooks ``load_index`` /
``_load_indices`` / ``__getitem__``; ``COINNDataHandle`` with ``list_files``)
so reference workloads port 1:1 — but adds the TPU-first path: every dataset
can **materialize** to dense numpy arrays once (:class:`SiteArrays`), which the
trainer stacks across sites and ships to the mesh. The reference re-reads files
per item per epoch (``comps/fs/__init__.py:33-39``); we pay I/O once.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np


@dataclass
class SiteArrays:
    """One site's full dataset as dense arrays (the unit of SPMD feeding)."""

    inputs: np.ndarray  # [n, ...] float32
    labels: np.ndarray  # [n] int32
    indices: np.ndarray  # [n] int32 — position in the site's sample inventory

    def __len__(self):
        return len(self.labels)

    def take(self, ix) -> "SiteArrays":
        ix = np.asarray(ix)
        return SiteArrays(self.inputs[ix], self.labels[ix], self.indices[ix])


@dataclass
class SiteInventory:
    """Every site's full dataset stacked on a common ``[S, N_max, ...]`` grid
    — the unit of DEVICE residency (uploaded to the mesh once per fit; each
    epoch then gathers its batches on-device from a compact index plan,
    trainer/steps.py). Sites smaller than ``N_max`` are zero-padded; a plan
    never points a live slot at a pad row (``counts`` bounds the valid
    prefix), so the padding is inert ballast, not data."""

    inputs: np.ndarray  # [S, N_max, ...] float32 (cast to compute dtype at upload)
    labels: np.ndarray  # [S, N_max] int32
    counts: np.ndarray  # [S] int32 — valid rows per site

    @property
    def num_sites(self):
        return self.inputs.shape[0]

    @property
    def nbytes(self) -> int:
        return self.inputs.nbytes + self.labels.nbytes


def stack_site_inventory(
    sites: list["SiteArrays"], rows: int | None = None
) -> SiteInventory:
    """Pad heterogeneous sites (73–120 subjects in the FS fixture) onto one
    dense ``[S, N_max, ...]`` grid. Host-side and cheap: one copy of the
    dataset, paid once per fit instead of once per epoch.

    ``rows`` PINS ``N_max`` (elastic rounds, r13): the daemon-mode runner
    re-stacks the inventory on every membership change, and a joining site
    larger than any predecessor would otherwise grow the resident grid's
    traced shape and retrace the epoch. Must cover the largest site (the
    daemon enforces this at admission)."""
    n_max = max((len(s) for s in sites), default=0)
    assert n_max > 0, "all sites empty"
    if rows is not None:
        assert rows >= n_max, (
            f"pinned inventory rows ({rows}) below the largest site "
            f"({n_max} samples)"
        )
        n_max = rows
    feat_shape = next(s.inputs.shape[1:] for s in sites if len(s))
    S = len(sites)
    inputs = np.zeros((S, n_max) + feat_shape, np.float32)
    labels = np.zeros((S, n_max), np.int32)
    counts = np.zeros((S,), np.int32)
    for si, s in enumerate(sites):
        n = len(s)
        counts[si] = n
        if n:
            inputs[si, :n] = s.inputs
            labels[si, :n] = s.labels
    return SiteInventory(inputs, labels, counts)


class SiteDataset:
    """Base dataset (capability parity with ``COINNDataset``, reconstructed
    from call sites — see SURVEY.md §2.3).

    Parameters
    ----------
    cache: dict-like task configuration (the reference's flat cache dict; here
        usually ``dataclasses.asdict`` of a task-args block merged with the
        train config).
    state: dict with at least ``baseDirectory`` — the site's data root
        (reference ``comps/fs/__init__.py:19``).
    mode: 'train' | 'test' (parity field).
    """

    def __init__(self, cache=None, state=None, mode: str = "train", **kw):
        self.cache = dict(cache or {})
        self.state = dict(state or {})
        self.mode = mode
        self.indices: list = []

    # -- reference API ---------------------------------------------------

    def path(self, cache_key: str = "data_file") -> str:
        """Resolve a cache key to a path under the site's base directory
        (reference ``comps/fs/__init__.py:35``, ``comps/icalstm/__init__.py:27``).
        With no/empty cache value, returns the base directory itself."""
        base = self.state.get("baseDirectory", "")
        name = self.cache.get(cache_key) or ""
        return os.path.join(base, name) if name else base

    def load_index(self, file):
        """Register one inventory entry. Subclasses override (reference hook)."""
        self.indices.append(file)

    def _load_indices(self, files, **kw):
        """Bulk variant (reference hook, ``comps/icalstm/__init__.py:26``)."""
        for f in files:
            self.load_index(f)

    def __getitem__(self, ix) -> dict:
        raise NotImplementedError

    def __len__(self):
        return len(self.indices)

    # -- TPU-first API ---------------------------------------------------

    def as_arrays(self) -> SiteArrays:
        """Materialize the whole site to dense arrays. Default implementation
        stacks ``__getitem__`` outputs; subclasses override with a vectorized
        loader when they can."""
        items = [self[i] for i in range(len(self))]
        inputs = np.stack([np.asarray(it["inputs"], np.float32) for it in items])
        labels = np.asarray([int(it["labels"]) for it in items], np.int32)
        ixs = np.asarray([int(it.get("ix", i)) for i, it in enumerate(items)], np.int32)
        return SiteArrays(inputs, labels, ixs)


class DataHandle:
    """Base data handle (capability parity with ``COINNDataHandle``): defines a
    site's sample inventory via ``list_files`` (reference
    ``comps/fs/__init__.py:66-71``, ``comps/icalstm/__init__.py:73-77``)."""

    def __init__(self, cache=None, state=None, **kw):
        self.cache = dict(cache or {})
        self.state = dict(state or {})

    def list_files(self) -> list:
        raise NotImplementedError


def build_site_dataset(
    dataset_cls, handle_cls, cache: dict, state: dict, mode: str = "train"
) -> SiteDataset:
    """Wire a (Dataset, DataHandle) pair the way ``COINNLocal`` does on the
    first round (SURVEY.md §3.2): handle.list_files → dataset._load_indices."""
    handle = handle_cls(cache=cache, state=state)
    ds = dataset_cls(cache=cache, state=state, mode=mode)
    ds._load_indices(handle.list_files())
    return ds
