"""``jax.profiler`` capture hooks — the device-trace half of telemetry.

Two consumers:

- the trainer (trainer/loop.py): :class:`XprofWindow` starts/stops a
  profiler capture around a configurable epoch window
  (``TrainConfig.xprof_dir`` + ``xprof_window``, CLI ``--xprof-dir``) —
  profile epochs 3..5 of a long fit without paying trace overhead for the
  whole run. Complements ``profile_dir`` (whole-fit trace, SURVEY.md §5);
  the two are mutually exclusive per fit.
- scripts/profile_epoch.py: :func:`capture` (an explicit trace context) and
  :func:`summarize_device_ops` (top device ops by total duration from a
  written trace) — the script is a thin consumer of these instead of owning
  its own gzip/trace-parsing code.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import shutil
from contextlib import contextmanager


class XprofWindow:
    """Start/stop a ``jax.profiler`` trace around epochs
    ``[first, last]`` (inclusive, 1-based — ``TrainConfig.xprof_window``).

    Call :meth:`epoch_begin` / :meth:`epoch_end` from the epoch loop and
    :meth:`close` from its ``finally`` — an early stop or ``Preempted``
    inside the window still finalizes the trace file."""

    def __init__(self, xprof_dir: str, window=(1, 1), label: str = ""):
        self.dir = xprof_dir
        w = tuple(window or (1, 1))
        self.first, self.last = int(w[0]), int(w[-1])
        self.label = label
        self._active = False

    def epoch_begin(self, epoch: int) -> None:
        # range test, not equality: a resumed fit whose start_epoch lands
        # INSIDE the window (preempted mid-window) must still capture the
        # remaining windowed epochs
        if (self.dir and not self._active
                and self.first <= epoch <= self.last):
            import jax

            jax.profiler.start_trace(os.path.join(self.dir, self.label))
            self._active = True

    def epoch_end(self, epoch: int) -> None:
        if self._active and epoch >= self.last:
            self.close()

    def close(self) -> None:
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False


@contextmanager
def capture(trace_dir: str, fresh: bool = True):
    """One explicit profiler capture into ``trace_dir`` (``fresh=True``
    clears a previous capture first — jax appends run dirs otherwise)."""
    import jax

    if fresh:
        shutil.rmtree(trace_dir, ignore_errors=True)
    with jax.profiler.trace(trace_dir):
        yield trace_dir


def trace_files(trace_dir: str) -> list[str]:
    """The ``.trace.json.gz`` files a capture wrote under ``trace_dir``."""
    return sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")
    ))


def summarize_device_ops(trace_dir: str, top: int = 25) -> list[dict]:
    """Top device ops by total duration from a written profiler trace.

    Aggregates complete (``"X"``) events on the XLA/module device lanes of
    the first trace file — the analysis scripts/profile_epoch.py prints
    (the tool that found the conv-emitter dW_hh lowering and the
    whole-input relayout copy). Returns
    ``[{"name", "total_us", "count"}, ...]``, longest first."""
    paths = trace_files(trace_dir)
    if not paths:
        raise FileNotFoundError(f"no .trace.json.gz under {trace_dir}")
    with gzip.open(paths[0]) as fh:
        d = json.load(fh)
    names = {}
    for e in d.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            names[(e["pid"], e["tid"])] = e["args"]["name"]
    agg: collections.Counter = collections.Counter()
    cnt: collections.Counter = collections.Counter()
    for e in d.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        tname = str(names.get((e["pid"], e["tid"]), "?"))
        if "XLA" not in tname and "Module" not in tname:
            continue
        agg[e["name"]] += float(e.get("dur", 0))
        cnt[e["name"]] += 1
    return [
        {"name": n, "total_us": v, "count": cnt[n]}
        for n, v in agg.most_common(top)
    ]
