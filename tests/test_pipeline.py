"""Device-resident input pipeline tests: index plans, on-device gather
bit-exactness, state donation, prefetch lifecycle, and the persistent
compilation cache (ISSUE 4 tentpole)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.core.config import TrainConfig
from dinunet_implementations_tpu.data.api import SiteArrays, stack_site_inventory
from dinunet_implementations_tpu.data.batching import (
    epoch_steps,
    materialize_plan,
    plan_epoch,
    plan_epoch_positions,
)
from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.parallel import host_mesh
from dinunet_implementations_tpu.robustness import FaultPlan, Preempted, poison_inputs
from dinunet_implementations_tpu.trainer import (
    FederatedTask,
    FederatedTrainer,
    init_train_state,
    make_optimizer,
    make_train_epoch_fn,
)


def _mk_site(n, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    return SiteArrays(X, (X.sum(-1) > 0).astype(np.int32),
                      np.arange(n, dtype=np.int32))


def _hetero_sites():
    # heterogeneous sizes: wrap recycling, an undersized site, a multi-wrap
    # site — the shapes the FS fixture (73-120 subjects) produces
    return [_mk_site(40, seed=1), _mk_site(21, seed=2), _mk_site(33, seed=3)]


def _toy_sites(ns, n=40, seed=0):
    return [_mk_site(n, seed=seed + i) for i in range(ns)]


# ---------------------------------------------------------------------------
# plan_epoch refactor: index plans + the wrap-mode tiling (satellite)
# ---------------------------------------------------------------------------


def _legacy_plan_epoch(sites, batch_size, seed=0, shuffle=True,
                       drop_last=True, pad_mode="wrap"):
    """The pre-refactor plan_epoch (repeated list concatenation per site),
    kept verbatim as the behavioral reference for the index-math rewrite."""
    def site_batches(order):
        n = len(order)
        if drop_last:
            n = (n // batch_size) * batch_size
        return [order[i:i + batch_size] for i in range(0, n, batch_size)]

    S = len(sites)
    feat_shape = next(s.inputs.shape[1:] for s in sites if len(s))
    rng = np.random.default_rng(seed)
    per_site = []
    for s in sites:
        order = rng.permutation(len(s)) if shuffle else np.arange(len(s))
        per_site.append(site_batches(order))
    steps = max(len(b) for b in per_site)
    inputs = np.zeros((S, steps, batch_size) + feat_shape, np.float32)
    labels = np.zeros((S, steps, batch_size), np.int32)
    weights = np.zeros((S, steps, batch_size), np.float32)
    indices = np.full((S, steps, batch_size), -1, np.int32)
    for si, (site, batches) in enumerate(zip(sites, per_site)):
        if pad_mode == "wrap" and batches:
            while len(batches) < steps:
                order = rng.permutation(len(site)) if shuffle else np.arange(len(site))
                batches = batches + site_batches(order)
            batches = batches[:steps]
        for bi, ix in enumerate(batches):
            k = len(ix)
            sel = site.take(ix)
            inputs[si, bi, :k] = sel.inputs
            labels[si, bi, :k] = sel.labels
            weights[si, bi, :k] = 1.0
            indices[si, bi, :k] = sel.indices
    return inputs, labels, weights, indices


@pytest.mark.parametrize("pad_mode,drop_last", [
    ("wrap", True), ("mask", True), ("mask", False), ("wrap", False),
])
@pytest.mark.parametrize("seed", [0, 7])
def test_plan_epoch_bitstable_across_tiling_refactor(pad_mode, drop_last, seed):
    """The wrap-mode tiling rewrite (single computed tiling of reshuffled
    orders instead of repeated list concatenation) must reproduce the legacy
    planner bit-for-bit — same RNG draw sequence, same batches."""
    sites = _hetero_sites() + [_mk_site(0, seed=9)]  # incl. an empty site
    fb = plan_epoch(sites, 8, seed=seed, pad_mode=pad_mode, drop_last=drop_last)
    li, ll, lw, lx = _legacy_plan_epoch(
        sites, 8, seed=seed, pad_mode=pad_mode, drop_last=drop_last
    )
    np.testing.assert_array_equal(fb.inputs, li)
    np.testing.assert_array_equal(fb.labels, ll)
    np.testing.assert_array_equal(fb.weights, lw)
    np.testing.assert_array_equal(fb.indices, lx)


def test_plan_positions_are_compact_and_consistent():
    sites = _hetero_sites()
    plan = plan_epoch_positions(sites, 8, seed=3, pad_mode="wrap")
    assert plan.positions.dtype == np.int32
    assert plan.steps == epoch_steps(sites, 8)
    # every live position indexes into its own site's inventory
    for si, s in enumerate(sites):
        pos = plan.positions[si]
        assert pos.max() < len(s)
        live = pos[pos >= 0]
        assert (live >= 0).all()
    # the plan is ~bytes where the dense tensor is ~kilobytes per sample
    fb = materialize_plan(sites, plan)
    assert plan.nbytes * 4 < fb.inputs.nbytes


# ---------------------------------------------------------------------------
# device path == host path, bit-exact (tentpole invariant)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("pad_mode", ["wrap", "mask"])
@pytest.mark.parametrize("use_mesh", [False, True])
def test_device_epoch_matches_host_bit_exact(pad_mode, use_mesh):
    """The on-device gather epoch must equal the host-materialized epoch
    bit-for-bit: params, losses, and health, for both pad modes, on both the
    vmap-folded and shard_map topologies."""
    sites = _hetero_sites()
    mesh = host_mesh(3) if use_mesh else None
    task = FederatedTask(MSANNet(in_size=6, hidden_sizes=(16,), out_size=2))
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-2)
    plan = plan_epoch_positions(sites, 8, seed=7, pad_mode=pad_mode,
                                drop_last=(pad_mode == "wrap"))
    fb = materialize_plan(sites, plan)
    inv = stack_site_inventory(sites)
    s0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                          jnp.ones((4, 6)), num_sites=3)
    fh = make_train_epoch_fn(task, engine, opt, mesh, 2)
    fd = make_train_epoch_fn(task, engine, opt, mesh, 2, pipeline="device",
                             donate_state=True)
    sh, lh = fh(s0, jnp.asarray(fb.inputs), jnp.asarray(fb.labels),
                jnp.asarray(fb.weights))
    s0d = jax.tree.map(jnp.copy, s0)
    sd, ld = fd(s0d, jnp.asarray(inv.inputs), jnp.asarray(inv.labels),
                jnp.asarray(plan.positions))
    np.testing.assert_array_equal(np.asarray(lh), np.asarray(ld))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        (sh.params, sh.health), (sd.params, sd.health),
    )


@pytest.mark.parametrize("use_mesh", [False, True])
def test_device_epoch_matches_host_with_fault_plan(use_mesh):
    """Scheduled drops + data-layer NaN poisoning: the device path's traced
    poison gate must reproduce the host path's poisoned dense tensor —
    identical losses, params, and quarantine counters."""
    import dataclasses

    sites = _hetero_sites()
    mesh = host_mesh(3) if use_mesh else None
    L = 2
    fp = FaultPlan(drop=((1, 1, 1),), nan_at=((0, 2),))
    task = FederatedTask(MSANNet(in_size=6, hidden_sizes=(16,), out_size=2))
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-2)
    plan = plan_epoch_positions(sites, 8, seed=7, pad_mode="wrap")
    fb = materialize_plan(sites, plan)
    rounds = plan.steps // L
    live = fp.liveness(3, 0, rounds)
    nan = fp.nan_mask(3, 0, rounds)
    fb = dataclasses.replace(fb, inputs=poison_inputs(fb.inputs, nan, L))
    inv = stack_site_inventory(sites)
    s0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                          jnp.ones((4, 6)), num_sites=3)
    fh = make_train_epoch_fn(task, engine, opt, mesh, L)
    fd = make_train_epoch_fn(task, engine, opt, mesh, L, pipeline="device",
                             donate_state=True)
    sh, lh = fh(s0, jnp.asarray(fb.inputs), jnp.asarray(fb.labels),
                jnp.asarray(fb.weights), jnp.asarray(live))
    sd, ld = fd(jax.tree.map(jnp.copy, s0), jnp.asarray(inv.inputs),
                jnp.asarray(inv.labels), jnp.asarray(plan.positions),
                jnp.asarray(live), jnp.asarray(nan.astype(np.float32)))
    np.testing.assert_array_equal(np.asarray(lh), np.asarray(ld))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        (sh.params, sh.health), (sd.params, sd.health),
    )


def test_trainer_device_fit_matches_host_fit():
    """End-to-end: a full fit under cfg.pipeline='device' (donation +
    prefetch included) equals the host-pipeline fit exactly — losses,
    selection, and test metrics."""
    res = {}
    for pipe in ("host", "device"):
        cfg = TrainConfig(epochs=5, batch_size=8, pipeline=pipe)
        tr = FederatedTrainer(
            cfg, MSANNet(in_size=6, hidden_sizes=(16,), out_size=2),
            host_mesh(2),
        )
        res[pipe] = tr.fit(_toy_sites(2, seed=1), _toy_sites(2, n=16, seed=2),
                           _toy_sites(2, n=16, seed=3), verbose=False)
    np.testing.assert_array_equal(res["host"]["epoch_losses"],
                                  res["device"]["epoch_losses"])
    assert res["host"]["test_metrics"] == res["device"]["test_metrics"]
    assert res["host"]["best_val_epoch"] == res["device"]["best_val_epoch"]


def test_trainer_device_fit_matches_host_fit_with_faults():
    """Chaos stays green AND identical on the device path: drops + NaN
    poisoning through the full trainer produce the same epoch losses and
    health counters as the host path."""
    fp = FaultPlan(drop=((1, 2, 3),), nan_at=((1, 0),))
    res = {}
    for pipe in ("host", "device"):
        cfg = TrainConfig(epochs=4, batch_size=8, pipeline=pipe)
        tr = FederatedTrainer(
            cfg, MSANNet(in_size=6, hidden_sizes=(16,), out_size=2),
            host_mesh(2), fault_plan=fp,
        )
        res[pipe] = tr.fit(_toy_sites(2, seed=1), _toy_sites(2, n=16, seed=2),
                           _toy_sites(2, n=16, seed=3), verbose=False)
    np.testing.assert_allclose(res["host"]["epoch_losses"],
                               res["device"]["epoch_losses"], rtol=0, atol=0)
    assert res["host"]["site_health"] == res["device"]["site_health"]
    assert res["host"]["test_metrics"] == res["device"]["test_metrics"]


# ---------------------------------------------------------------------------
# donation sanity (satellite): donated buffers are consumed, never reused
# ---------------------------------------------------------------------------


def test_donated_state_buffers_are_released():
    """donate_state=True must actually donate: the input state's buffers are
    deleted after dispatch, and chaining from the RETURNED state works."""
    sites = _hetero_sites()
    task = FederatedTask(MSANNet(in_size=6, hidden_sizes=(8,), out_size=2))
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-2)
    plan = plan_epoch_positions(sites, 8, seed=1)
    inv = stack_site_inventory(sites)
    s0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                          jnp.ones((4, 6)), num_sites=3)
    fd = make_train_epoch_fn(task, engine, opt, None, 1, pipeline="device",
                             donate_state=True)
    args = (jnp.asarray(inv.inputs), jnp.asarray(inv.labels),
            jnp.asarray(plan.positions))
    s1, _ = fd(s0, *args)
    leaf = s0.params["linear_0"]["kernel"]
    if not hasattr(leaf, "is_deleted"):
        pytest.skip("jax build does not expose buffer deletion state")
    assert leaf.is_deleted(), "input state must be consumed by donation"
    s2, _ = fd(s1, *args)  # chaining from the returned state stays valid
    assert np.isfinite(np.asarray(s2.params["linear_0"]["kernel"])).all()
    # the INVENTORY is not donated: it must survive every epoch
    assert not args[0].is_deleted()


def test_trainer_never_references_donated_buffers():
    """Guard for future refactors (the donation-sanity satellite): a full
    fit with donation enabled must keep best-state tracking on live buffers
    — the selected state evaluates and serializes after epochs that donated
    the states it was snapshotted from."""
    cfg = TrainConfig(epochs=6, batch_size=8, patience=50, pipeline="device",
                      donate_epoch_state=True)
    tr = FederatedTrainer(cfg, MSANNet(in_size=6, hidden_sizes=(16,), out_size=2),
                          host_mesh(2))
    res = tr.fit(_toy_sites(2, seed=4), _toy_sites(2, n=16, seed=5),
                 _toy_sites(2, n=16, seed=6), verbose=False)
    # best_state materializes fully (a donated alias would raise here)
    leaves = jax.tree.leaves(jax.tree.map(np.asarray, res["state"].params))
    assert all(np.isfinite(a).all() for a in leaves)
    assert np.isfinite(res["epoch_losses"]).all()
    # donation off must give the identical trajectory
    cfg2 = cfg.replace(donate_epoch_state=False)
    tr2 = FederatedTrainer(cfg2, MSANNet(in_size=6, hidden_sizes=(16,), out_size=2),
                           host_mesh(2))
    res2 = tr2.fit(_toy_sites(2, seed=4), _toy_sites(2, n=16, seed=5),
                   _toy_sites(2, n=16, seed=6), verbose=False)
    np.testing.assert_array_equal(res["epoch_losses"], res2["epoch_losses"])
    assert res["test_metrics"] == res2["test_metrics"]


# ---------------------------------------------------------------------------
# prefetch lifecycle (satellite): clean shutdown on Preempted, resume intact
# ---------------------------------------------------------------------------


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("dinunet-epoch-prefetch") and t.is_alive()]


def test_prefetch_thread_shutdown_clean_on_preempted(tmp_path):
    """A FaultPlan kill mid-fit raises Preempted AFTER the checkpoint; the
    prefetch thread must be joined (no leak into the resumed run), and the
    resumed fit must finish with the exact uninterrupted trajectory."""
    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    train = _toy_sites(2, seed=4)
    val, test = _toy_sites(2, n=16, seed=5), _toy_sites(2, n=16, seed=6)
    cfg = TrainConfig(epochs=6, batch_size=8, pipeline="device")

    full = FederatedTrainer(cfg, model, host_mesh(2),
                            out_dir=str(tmp_path / "full"))
    res_full = full.fit(train, val, test, verbose=False)
    assert not _prefetch_threads()

    # rounds/epoch = 40//8 = 5 → kill crossing round 12 fires during epoch 3
    fp = FaultPlan(kill_at_round=12)
    killed = FederatedTrainer(cfg, model, host_mesh(2),
                              out_dir=str(tmp_path / "killed"), fault_plan=fp)
    with pytest.raises(Preempted):
        killed.fit(train, val, test, verbose=False)
    assert not _prefetch_threads(), "prefetch thread leaked across Preempted"

    resumed = FederatedTrainer(cfg, model, host_mesh(2),
                               out_dir=str(tmp_path / "killed"))
    res_res = resumed.fit(train, val, test, verbose=False, resume=True)
    assert not _prefetch_threads()
    assert len(res_res["epoch_losses"]) == len(res_full["epoch_losses"])
    np.testing.assert_allclose(res_res["epoch_losses"],
                               res_full["epoch_losses"], atol=1e-6)
    assert res_res["test_metrics"] == res_full["test_metrics"]


def test_prefetcher_builder_error_surfaces():
    """A crash on the builder thread must re-raise in the consumer, not
    vanish into the thread (and close() must still be clean)."""
    from dinunet_implementations_tpu.trainer.prefetch import EpochPlanPrefetcher

    def bad_build(epoch):
        raise RuntimeError(f"boom at {epoch}")

    pf = EpochPlanPrefetcher(bad_build, 1, 3)
    with pytest.raises(RuntimeError, match="boom"):
        pf.get(1)
    assert not _prefetch_threads()


def test_prefetcher_early_stop_close_joins():
    """Stopping mid-sequence (early stopping) leaves no thread behind even
    while the builder is blocked on the full queue."""
    from dinunet_implementations_tpu.trainer.prefetch import EpochPlanPrefetcher

    pf = EpochPlanPrefetcher(lambda e: e * 10, 1, 100)
    assert pf.get(1) == 10
    pf.close()
    pf.close()  # idempotent
    assert not _prefetch_threads()


# ---------------------------------------------------------------------------
# persistent compile cache (tentpole layer c)
# ---------------------------------------------------------------------------


def test_compile_cache_dir_populates(tmp_path):
    """cfg.compile_cache_dir wires jax's persistent compilation cache: a fit
    populates the directory so re-runs/fold re-fits skip XLA."""
    import os

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_secs = jax.config.jax_persistent_cache_min_compile_time_secs
    prev_size = jax.config.jax_persistent_cache_min_entry_size_bytes
    cache = str(tmp_path / "xla-cache")
    try:
        cfg = TrainConfig(epochs=1, batch_size=8, compile_cache_dir=cache)
        tr = FederatedTrainer(cfg, MSANNet(in_size=6, hidden_sizes=(8,), out_size=2),
                              host_mesh(2))
        assert jax.config.jax_compilation_cache_dir == cache
        tr.fit(_toy_sites(2, seed=1), _toy_sites(2, n=16, seed=2),
               _toy_sites(2, n=16, seed=3), verbose=False)
        assert os.listdir(cache), "fit should populate the compilation cache"
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", prev_secs)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", prev_size)


def test_cli_exposes_pipeline_and_compile_cache():
    from dinunet_implementations_tpu.runner.cli import build_parser

    args = build_parser().parse_args(
        ["--data-path", ".", "--pipeline", "host", "--compile-cache", "/tmp/cc"]
    )
    assert args.pipeline == "host"
    assert args.compile_cache == "/tmp/cc"


# ---------------------------------------------------------------------------
# sanitizer: one epoch compilation with the device pipeline + donation
# ---------------------------------------------------------------------------


def test_device_pipeline_one_epoch_compile_under_sanitizer(monkeypatch):
    """CompileGuard acceptance: the device pipeline with donation enabled
    still compiles exactly ONE epoch program per (engine, topology) fit."""
    from dinunet_implementations_tpu.checks.sanitize import (
        jit_cache_size,
        sanitized_fit,
    )

    monkeypatch.setenv("DINUNET_SANITIZE", "compile")
    cfg = TrainConfig(epochs=4, batch_size=8, pipeline="device",
                      donate_epoch_state=True)
    tr = FederatedTrainer(cfg, MSANNet(in_size=6, hidden_sizes=(16,), out_size=2),
                          host_mesh(2))
    if jit_cache_size(tr.epoch_fn) is None:
        pytest.skip("jax build exposes no jit cache counter")
    with sanitized_fit(tr, label="device-pipeline") as report:
        res = tr.fit(_toy_sites(2, seed=1), _toy_sites(2, n=16, seed=2),
                     _toy_sites(2, n=16, seed=3), verbose=False)
        report.note_result(res)
    assert jit_cache_size(tr.epoch_fn) == 1
