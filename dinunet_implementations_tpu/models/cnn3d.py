"""SMRI3DNet — 3D-CNN classifier for structural MRI (T1w) volumes.

TPU-build extension (BASELINE.json configs: "3D-CNN sMRI (T1w volumes)
federated classifier, 8 sites"); no reference implementation exists, so the
design is TPU-first throughout:

- NDHWC (channels-last) layout — the native TPU conv layout;
- downsampling via stride-2 convolutions (keeps everything on the MXU; no
  pooling ops between matmul-like kernels);
- mask-aware batch-stat BatchNorm (models/layers.py) so SPMD padding rows
  don't perturb statistics, matching the MSANNet convention;
- global average pool + linear head.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from .layers import BatchNorm, compute_dtype_of, dense


class SMRI3DNet(nn.Module):
    channels: tuple = (16, 32, 64, 128)
    num_cls: int = 2
    dropout_rate: float = 0.25
    # "bfloat16" runs the convolutions (all the FLOPs) in bf16 on the MXU
    # (f32 accumulation in hardware); BatchNorm statistics and the head stay
    # f32. None = full f32.
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        # x: [B, D, H, W] or [B, D, H, W, C]
        if x.ndim == 4:
            x = x[..., None]
        cdt = compute_dtype_of(self.compute_dtype)
        for i, ch in enumerate(self.channels):
            x = nn.Conv(ch, kernel_size=(3, 3, 3), strides=(2, 2, 2),
                        use_bias=False, name=f"conv_{i}", dtype=cdt,
                        param_dtype=jnp.float32)(x)
            x = x.astype(jnp.float32)  # BN moments at full precision
            # per-channel statistics over (B, D, H, W) — BatchNorm3d semantics
            x = BatchNorm(
                ch, track_running_stats=False, reduce_axes=(0, 1, 2, 3),
                name=f"bn_{i}",
            )(x, train=train, mask=mask)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2, 3))  # global average pool → [B, C]
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return dense(self.num_cls, fan_in=x.shape[-1], name="head")(x)
