"""Shared low-rank machinery for the compressed engines (rankDAD / powerSGD).

The reference exposes three knobs (``compspec.json:236-238,268-270``):
``dad_reduction_rank`` (default 10), ``dad_num_pow_iters`` (default 5), and
``dad_tol`` (default 1e-3). Tolerance-based early exit inside jit is a
``lax.while_loop`` whose carry tracks the singular-value estimates — shapes
stay static, only the trip count is dynamic (bounded by ``num_iters``).

Matrix convention: a gradient leaf with ndim ≥ 2 is reshaped to
``[prod(leading), last]`` (Dense kernels are already [in, out]; conv kernels
[h, w, cin, cout] → [h*w*cin, cout]); ndim ≤ 1 leaves are "dense" and bypass
compression.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def is_compressible(g, min_rank_dim: int = 2) -> bool:
    return g.ndim >= 2 and min(_matrix_shape(g)) >= min_rank_dim


def _matrix_shape(g):
    m = 1
    for d in g.shape[:-1]:
        m *= d
    return m, g.shape[-1]


def to_matrix(g):
    return g.reshape(_matrix_shape(g))


def from_matrix(mat, like):
    return mat.reshape(like.shape).astype(like.dtype)


def _cholqr(Y):
    """Column-normalized shifted CholeskyQR2 of ``Y [m, r]`` → ``(Q, colnorm)``.

    TPU-first replacement for ``jnp.linalg.qr``: Householder QR lowers to a
    long sequential scalar loop on TPU, while this is two matmuls plus an
    ``[r, r]`` Cholesky + triangular solve per round (r ≤ rank, default 10) —
    MXU/batch friendly, and (unlike an eigh-based Löwdin orthonormalization,
    which was tried and reverted) CONTINUOUS in Y: float-noise between the
    vmapped and unbatched lowerings stays proportional instead of being
    amplified by near-degenerate eigen-subspace mixing.

    Each round first normalizes columns, so the trace-relative Cholesky shift
    is a PER-COLUMN relative floor rather than a global one — a naive
    ``shift·trace`` floor is dominated by σ₁ and collapses every direction
    with σᵢ² ≲ √shift·σ₁² (review finding r3; measured rec-error 16× worse on
    a decaying spectrum). With normalization the variant matches Householder
    QR's orthogonality (~6e-7) and reconstruction error on spectra spanning
    4 decades, while staying NaN-safe for rank-deficient / all-zero Y (true
    gradient rank is routinely < r, e.g. bounded by the batch size).
    ``colnorm`` is the pre-normalization column-norm vector of the first
    round — the σ-scale convergence proxy.
    """
    r = Y.shape[1]
    eye = jnp.eye(r, dtype=Y.dtype)

    def once(Y, shift):
        nc = jnp.linalg.norm(Y, axis=0)
        # exactly-zero columns take canonical basis vectors, so a zero input
        # still yields an ORTHONORMAL Q — matching Householder QR's behavior.
        # powerSGD warm-starts its q factor from the previous round's P; a
        # P=0 here would make q die permanently (q_new = MᵀP = 0 forever)
        # while its error-feedback residual grows unflushed (review, r3).
        fallback = jnp.eye(Y.shape[0], Y.shape[1], dtype=Y.dtype)
        Y = jnp.where(nc > 0, Y / jnp.maximum(nc, 1e-30), fallback)
        Gm = Y.T @ Y
        L = jnp.linalg.cholesky(Gm + (shift * jnp.trace(Gm) + 1e-30) * eye)
        Q = jax.scipy.linalg.solve_triangular(L, Y.T, lower=True).T
        return Q, nc

    Q1, colnorm = once(Y, 1e-6)
    Q2, _ = once(Q1, 1e-7)
    return Q2, colnorm


def subspace_iteration(G, rank: int, num_iters: int, tol: float, key=None):
    """Rank-r factorization ``G ≈ P @ Q^T`` by subspace (block power) iteration.

    P is [m, r] orthonormal, Q = G^T P is [n, r]. Early-exits when the relative
    change of the singular-value estimates drops below ``tol`` (the
    ``dad_tol`` semantics), else runs ``num_iters`` (``dad_num_pow_iters``).

    Orthonormalization is column-normalized CholeskyQR2 (see :func:`_cholqr`)
    and the singular-value estimates come from its column norms for free —
    ``‖(G Gᵀ P)ᵢ‖`` estimates σᵢ², so ``sqrt`` puts the convergence test on
    the same σ scale the reference's ``dad_tol`` means, without the extra
    full ``Gᵀ P`` matmul per iteration a direct estimate would cost.
    """
    G = G.astype(jnp.float32)
    m, n = G.shape
    r = min(rank, m, n)
    if key is None:
        key = jax.random.PRNGKey(m * 1000003 + n)
    omega = jax.random.normal(key, (n, r), jnp.float32)
    Y = G @ omega  # [m, r]
    P0, _ = _cholqr(Y)
    sig0 = jnp.linalg.norm(G.T @ P0, axis=0)  # [r] σ estimates, column order

    def cond(carry):
        i, _, _, delta = carry
        return jnp.logical_and(i < num_iters, delta > tol)

    def body(carry):
        i, P, sig, _ = carry
        P_new, colnorm = _cholqr(G @ (G.T @ P))
        sig_new = jnp.sqrt(colnorm)  # ‖G Gᵀ p‖ ≈ σ² → σ scale (see docstring)
        delta = jnp.linalg.norm(sig_new - sig) / jnp.maximum(jnp.linalg.norm(sig), 1e-12)
        return i + 1, P_new, sig_new, delta

    # Tie the initial delta to G so its device-varying annotation matches the
    # loop body's output under shard_map (per-site G ⇒ per-site delta).
    delta0 = jnp.float32(jnp.inf) + 0.0 * jnp.sum(sig0)
    _, P, _, _ = jax.lax.while_loop(cond, body, (jnp.zeros((), jnp.int32), P0, sig0, delta0))
    Q = G.T @ P  # [n, r]
    return P, Q


def orthonormalize(P):
    """Orthonormalize columns (shifted CholeskyQR2 — see :func:`_cholqr`)."""
    Q, _ = _cholqr(P)
    return Q
