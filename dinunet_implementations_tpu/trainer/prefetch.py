"""Double-buffered epoch-plan prefetch — the host never blocks the device.

With the device-resident pipeline (trainer/steps.py ``pipeline="device"``)
the only per-epoch host work is building the compact int32 index plan
(data/batching.py) and dispatching its KB-sized transfer. This module moves
that work off the critical path: a single background thread builds epoch
``N+1``'s plan (and dispatches its device put) while epoch ``N``'s fused XLA
dispatch runs — the Podracer split of host-side orchestration from
device-side compute (PAPERS.md).

Plans are keyed by VIRTUAL site throughout: the ``[S, steps, B]`` grid is
indexed by global site id regardless of the mesh's pack factor
(parallel/mesh.py site packing) — ``P(site)`` placement hands each device
its contiguous ``[K, steps, B]`` block, so a pack-factor change never
touches the planner.

Design constraints honored here:

- plans are pure functions of ``(epoch, global round window)`` — the builder
  needs NO feedback from the training state, so prefetching never changes
  results (resume included: the round window extrapolates linearly from the
  resume point exactly as the epoch program advances it);
- a bounded queue (depth 1) keeps at most one epoch in flight — double
  buffering, not an unbounded plan pile;
- shutdown is cooperative and prompt: ``close()`` unblocks the builder,
  joins the thread, and is safe to call twice — the trainer calls it in a
  ``finally`` so a ``Preempted`` (SIGTERM / FaultPlan kill) never leaks a
  thread into the resumed run;
- a builder crash re-raises in the consumer (``get``), not silently in the
  thread.
"""

from __future__ import annotations

import queue
import threading
import time

from .logs import log_warning


class EpochPlanPrefetcher:
    """Build epoch plans one epoch ahead on a background thread.

    ``build(epoch)`` must return the (already device-dispatched) plan payload
    for that epoch. Epochs are consumed strictly in order ``first..last`` via
    :meth:`get`; a mismatch (defensive — the trainer consumes sequentially)
    falls back to building synchronously.

    Telemetry: the prefetcher keeps its own counters — time the consumer
    spent BLOCKED waiting on the builder (``stall_s``: the double-buffering
    failure signal), gets served, inline-build fallbacks, and the summed
    queue depth at get time — surfaced via :meth:`stats` into the fit's
    ``metrics.jsonl`` summary row (telemetry/sink.py).
    """

    def __init__(self, build, first_epoch: int, last_epoch: int):
        self._build = build
        self._queue: queue.Queue = queue.Queue(maxsize=1)
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._stall_s = 0.0
        self._gets = 0
        self._inline_builds = 0
        self._depth_sum = 0
        self._thread = threading.Thread(
            target=self._run, args=(first_epoch, last_epoch),
            name="dinunet-epoch-prefetch", daemon=True,
        )
        self._thread.start()

    # -- producer (background thread) ------------------------------------

    def _run(self, first: int, last: int) -> None:
        try:
            for epoch in range(first, last + 1):
                if self._stop.is_set():
                    return
                payload = self._build(epoch)
                while not self._stop.is_set():
                    try:
                        self._queue.put((epoch, payload), timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except Exception as exc:
            # surface in the consumer: stored for re-raise from get(); the
            # warning covers the case where the consumer never calls get()
            # again (e.g. it is mid-epoch and about to be preempted)
            self._error = exc
            log_warning(f"[warn] epoch-plan prefetch thread failed: {exc!r}")

    # -- consumer (training loop) ----------------------------------------

    def get(self, epoch: int):
        """The prefetched payload for ``epoch`` (blocking briefly if the
        builder is still working on it). Re-raises a builder crash."""
        t0 = time.perf_counter()
        self._gets += 1
        self._depth_sum += self._queue.qsize()
        try:
            while True:
                if self._error is not None:
                    err, self._error = self._error, None
                    self.close()
                    raise err
                if not self._thread.is_alive() and self._queue.empty():
                    # builder finished (or died after its warning): build inline
                    self._inline_builds += 1
                    return self._build(epoch)
                try:
                    got_epoch, payload = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
                if got_epoch == epoch:
                    return payload
                # out-of-order consumption (defensive): drop and build inline
                self._inline_builds += 1
                return self._build(epoch)
        finally:
            self._stall_s += time.perf_counter() - t0

    def stats(self) -> dict:
        """Counters for the telemetry summary row: consumer-blocked seconds,
        gets served, inline-build fallbacks, mean queue depth at get."""
        return {
            "stall_s": round(self._stall_s, 6),
            "gets": self._gets,
            "inline_builds": self._inline_builds,
            "mean_queue_depth": round(
                self._depth_sum / max(self._gets, 1), 3
            ),
        }

    def close(self) -> None:
        """Stop the builder and join the thread. Idempotent; called from the
        trainer's ``finally`` so early stopping / ``Preempted`` / crashes all
        leave zero threads behind."""
        self._stop.set()
        # drain so a producer blocked on put() observes the stop event
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)
