"""CLI entry point (runner/cli.py) — the reference's `python entry.py` /
site_run.py operational surface as one command."""

import json
import os

import pytest

from dinunet_implementations_tpu.runner.cli import build_parser, main

FSL = "/root/reference/datasets/test_fsl"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(FSL), reason="reference fixture not mounted"
)


@pytest.mark.slow
def test_cli_federated_run(tmp_path, capsys):
    rc = main([
        "--data-path", FSL, "--task", "FS-Classification",
        "--engine", "dSGD", "--epochs", "2", "--batch-size", "8",
        "--out-dir", str(tmp_path), "--quiet",
        "--set", "split_ratio=[0.7,0.15,0.15]",
    ])
    assert rc == 0
    lines = [l for l in capsys.readouterr().out.splitlines() if l.strip()]
    rec = json.loads(lines[-1])
    assert rec["fold"] == 0 and "test_auc" in rec
    assert os.path.isdir(tmp_path / "remote/simulatorRun/FS-Classification/fold_0")


def test_cli_single_site(tmp_path, capsys):
    rc = main([
        "--data-path", FSL, "--site", "1", "--epochs", "2",
        "--batch-size", "8", "--quiet", "--out-dir", str(tmp_path),
    ])
    assert rc == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert 0 <= rec["test_auc"] <= 1


@pytest.mark.slow
def test_cli_resume_and_folds(tmp_path, capsys):
    args = [
        "--data-path", FSL, "--epochs", "2", "--batch-size", "8",
        "--num-folds", "3", "--folds", "1", "--out-dir", str(tmp_path),
        "--quiet",
    ]
    assert main(args) == 0
    assert os.path.isdir(tmp_path / "remote/simulatorRun/FS-Classification/fold_1")
    # resume path exercises the checkpoint reload
    assert main(args + ["--resume"]) == 0
    rec = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert rec["fold"] == 1


def test_cli_set_parses_json_and_bare_strings():
    from dinunet_implementations_tpu.runner.cli import _parse_set

    out = _parse_set(["a=[1,2]", "b=0.5", "c=hello", "d=true"])
    assert out == {"a": [1, 2], "b": 0.5, "c": "hello", "d": True}
    with pytest.raises(SystemExit):
        _parse_set(["novalue"])


def test_cli_rejects_unknown_task():
    with pytest.raises(SystemExit):
        build_parser().parse_args(["--data-path", ".", "--task", "nope"])


@pytest.mark.slow
def test_cli_site_mode_with_mode_flag(tmp_path, capsys):
    """Review regression (r3): --site + --mode must not double-pass 'mode'."""
    # train first so mode=test has a checkpoint... simpler: just train with
    # an explicit --mode train (the crashing combination)
    rc = main([
        "--data-path", FSL, "--site", "0", "--mode", "train",
        "--epochs", "1", "--batch-size", "8", "--quiet",
        "--out-dir", str(tmp_path),
    ])
    assert rc == 0


def test_cli_site_mode_rejects_federated_flags():
    with pytest.raises(SystemExit, match="federated-mode"):
        main(["--data-path", FSL, "--site", "0", "--resume"])
    with pytest.raises(SystemExit, match="federated-mode"):
        main(["--data-path", FSL, "--site", "0", "--folds", "1"])
