"""Declarative byzantine-site attack injection — the hostile twin of
:mod:`.faults`.

An :class:`AttackPlan` describes, in *global round* coordinates, which sites
behave adversarially and how. Where a :class:`~.faults.FaultPlan` models
sites that FAIL (drops, stragglers, data corruption), an AttackPlan models
sites that LIE: their local training runs normally, but the gradient they
hand the aggregation engine is adversarially transformed. Five attack
families, each a list of ``(site, first_round, last_round)`` windows
(inclusive; ``last_round = -1`` means "until the end of training"):

- ``sign_flip`` — the classic model-destruction attack: the site ships
  ``-g`` (steepest ASCENT) at full claimed example weight;
- ``scale`` — gradient-scaling: ``scale_factor · g`` (default 10×), the
  model-steering amplification attack;
- ``noise`` — additive Gaussian noise ``g + noise_std · ε`` with ε drawn
  per (site, round, leaf) from a counter-based key, so the attack replays
  identically regardless of epoch chunking or resume point;
- ``free_rider`` — the site ships an all-zero gradient while still claiming
  its example weight (diluting the honest mean without training);
- ``collude`` — a colluding clique: every attacking site ships the SAME
  pseudo-random direction (keyed by round only, identical across clique
  members) scaled to ``collude_scale ×`` its own gradient norm — the
  coordinated attack that defeats per-site outlier tests and stresses the
  trimmed-mean breakdown point.

Execution model (trainer/steps.py): :func:`attack_window` renders the plan
into an ``[S, rounds]`` int32 CODE mask for the epoch's global round window
— one attack code per (site, round) cell — fed to the compiled epoch as a
TRACED input exactly like the FaultPlan liveness mask. The static transform
parameters (``scale_factor``, ``noise_std``, seeds) are closed over at trace
time (:func:`make_attack_fn`), so ONE program per fit covers every
(site, round) pattern of the plan — CompileGuard-asserted in the bench/CI
smokes — and the plan composes freely with FaultPlan drops/delays/NaN
poisoning and with site packing (``site`` ids are VIRTUAL site ids; the
``[S, rounds]`` mask shards ``P(site)`` into per-device ``[K, rounds]``
blocks like every other per-site input).

Attacks are applied to the site's ROUND GRADIENT, before the engine's
aggregation (and before compression for rankDAD/powerSGD) — the attacker
controls what it ships, not what the honest sites compute. Defense lives in
the engines' ``robust_agg`` reducers (engines/, parallel/collectives.py)
and the anomaly-scored reputation layer (health.py, trainer/steps.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass

import numpy as np

# attack codes in the [S, rounds] mask (0 = honest). Order is the overlap
# precedence: a (site, round) cell may carry ONE attack; overlapping windows
# are rejected at plan construction so the declared plan is unambiguous.
ATTACK_NONE = 0
ATTACK_SIGN_FLIP = 1
ATTACK_SCALE = 2
ATTACK_NOISE = 3
ATTACK_FREE_RIDER = 4
ATTACK_COLLUDE = 5

#: field name -> code, in declaration order (the JSON surface)
ATTACK_FIELDS = {
    "sign_flip": ATTACK_SIGN_FLIP,
    "scale": ATTACK_SCALE,
    "noise": ATTACK_NOISE,
    "free_rider": ATTACK_FREE_RIDER,
    "collude": ATTACK_COLLUDE,
}


def _windows(rows, name: str) -> tuple:
    out = []
    for row in rows:
        row = tuple(int(v) for v in row)
        if len(row) != 3:
            raise ValueError(
                f"AttackPlan.{name} entries need (site, first_round, "
                f"last_round) triples, got {row!r}"
            )
        site, first, last = row
        if site < 0 or first < 0 or (last != -1 and last < first):
            raise ValueError(f"bad AttackPlan.{name} entry {row}")
        out.append(row)
    return tuple(out)


@dataclass(frozen=True)
class AttackPlan:
    """Deterministic byzantine-attack schedule in global-round coordinates."""

    sign_flip: tuple = ()  # (site, first_round, last_round) triples; -1 = forever
    scale: tuple = ()
    scale_factor: float = 10.0
    noise: tuple = ()
    noise_std: float = 1.0
    noise_seed: int = 0
    free_rider: tuple = ()
    collude: tuple = ()
    collude_seed: int = 0
    collude_scale: float = 5.0

    def __post_init__(self):
        for name in ATTACK_FIELDS:
            object.__setattr__(self, name, _windows(getattr(self, name), name))
        if float(self.noise_std) < 0.0:
            raise ValueError(f"AttackPlan.noise_std must be >= 0, got {self.noise_std}")
        # one attack per (site, round) cell: overlapping windows on the same
        # site would make the rendered code mask depend on field order —
        # reject them so the declared plan is unambiguous
        spans = []
        for name in ATTACK_FIELDS:
            for site, first, last in getattr(self, name):
                spans.append((site, first, last, name))
        for i, (s, f, l, n) in enumerate(spans):
            for s2, f2, l2, n2 in spans[i + 1:]:
                if s != s2:
                    continue
                hi, hi2 = (np.inf if l == -1 else l), (np.inf if l2 == -1 else l2)
                if f <= hi2 and f2 <= hi:
                    raise ValueError(
                        f"AttackPlan windows overlap on site {s}: "
                        f"{n}[{f}, {l}] vs {n2}[{f2}, {l2}] — one attack "
                        "per (site, round) cell"
                    )

    # -- round-window mask generation ------------------------------------

    def codes(self, num_sites: int, round_start: int, num_rounds: int) -> np.ndarray:
        """``[num_sites, num_rounds]`` int32 attack-code mask for the round
        window ``[round_start, round_start + num_rounds)`` (0 = honest)."""
        mask = np.zeros((num_sites, num_rounds), np.int32)
        for name, code in ATTACK_FIELDS.items():
            for site, first, last in getattr(self, name):
                if site >= num_sites:
                    continue
                lo = max(first - round_start, 0)
                hi = num_rounds if last == -1 else min(
                    last + 1 - round_start, num_rounds
                )
                if lo < hi:
                    mask[site, lo:hi] = code
        return mask

    def attacker_sites(self) -> tuple:
        """Sorted distinct site ids the plan ever attacks from."""
        sites = set()
        for name in ATTACK_FIELDS:
            sites.update(site for site, _, _ in getattr(self, name))
        return tuple(sorted(sites))

    def injects_attacks(self) -> bool:
        return any(getattr(self, name) for name in ATTACK_FIELDS)

    # -- JSON round-trip (CLI / bench surface) ---------------------------

    def to_json(self) -> dict:
        return {
            "sign_flip": [list(t) for t in self.sign_flip],
            "scale": [list(t) for t in self.scale],
            "scale_factor": self.scale_factor,
            "noise": [list(t) for t in self.noise],
            "noise_std": self.noise_std,
            "noise_seed": self.noise_seed,
            "free_rider": [list(t) for t in self.free_rider],
            "collude": [list(t) for t in self.collude],
            "collude_seed": self.collude_seed,
            "collude_scale": self.collude_scale,
        }

    @classmethod
    def from_json(cls, spec) -> "AttackPlan":
        """Build from a dict or a JSON string (the CLI/bench flag payload)."""
        if isinstance(spec, (str, bytes)):
            spec = json.loads(spec)
        if not isinstance(spec, dict):
            raise ValueError(f"AttackPlan spec must be a JSON object, got {type(spec)}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(
                f"unknown AttackPlan keys {sorted(unknown)} (have {sorted(known)})"
            )
        return cls(**spec)


def parse_attack_plan(arg: str | None) -> AttackPlan | None:
    """Parse the ``--attacks`` flag: inline JSON, or ``@path`` to a JSON file."""
    if not arg:
        return None
    if arg.startswith("@"):
        with open(arg[1:]) as fh:
            return AttackPlan.from_json(fh.read())
    if os.path.exists(arg):  # a bare path also works
        with open(arg) as fh:
            return AttackPlan.from_json(fh.read())
    return AttackPlan.from_json(arg)


def attack_window(plan: AttackPlan | None, num_sites: int, round0: int,
                  rounds: int):
    """The per-epoch ``[S, rounds]`` attack-code mask for the global round
    window ``[round0, round0 + rounds)``, or ``None`` when the plan attacks
    nothing — the one place both input pipelines derive the window math from
    (the :func:`~.faults.fault_window` pattern)."""
    if plan is None or not plan.injects_attacks():
        return None
    return plan.codes(num_sites, round0, rounds)


def make_attack_fn(plan: AttackPlan):
    """Build the traced per-site gradient transform for ``plan``.

    Returns ``attack(site_grad, code, rnd, site_ix) -> site_grad`` operating
    on ONE site's (unbatched) gradient pytree: ``code`` is the site's int32
    attack code for this round (a traced value from the ``[S, rounds]``
    mask), ``rnd`` the global round counter, ``site_ix`` the global virtual
    site id (``jax.lax.axis_index`` over the bound site axes — identical
    under packing and the vmap fold, so attacks replay bit-identically
    across topologies). The transform parameters are trace-time statics
    closed over from the plan; noise/collusion directions come from
    counter-based keys ``(seed, site, round)`` / ``(seed, round)``, so the
    attack pattern is independent of epoch chunking and resume point.

    All branches are ``jnp.where`` selects on the traced code — one compiled
    program per plan SHAPE (which attack families are present), never per
    pattern. NaN-safe by construction only in the sense that an attacked
    gradient that was already non-finite (FaultPlan NaN poisoning on the
    same cell) stays non-finite and is caught by the liveness gate.
    """
    import jax
    import jax.numpy as jnp

    has_noise = bool(plan.noise)
    has_collude = bool(plan.collude)
    has_scalework = bool(plan.sign_flip or plan.scale or plan.free_rider)
    scale_factor = float(plan.scale_factor)
    noise_std = float(plan.noise_std)
    collude_scale = float(plan.collude_scale)

    def attack(site_grad, code, rnd, site_ix):
        leaves, treedef = jax.tree.flatten(site_grad)
        out = list(leaves)
        if has_scalework:
            # sign_flip / scale / free_rider are all one multiplicative gate
            mult = jnp.where(
                code == ATTACK_SIGN_FLIP, jnp.float32(-1.0),
                jnp.where(
                    code == ATTACK_SCALE, jnp.float32(scale_factor),
                    jnp.where(
                        code == ATTACK_FREE_RIDER, jnp.float32(0.0),
                        jnp.float32(1.0),
                    ),
                ),
            )
            out = [
                (g.astype(jnp.float32) * mult).astype(g.dtype) for g in out
            ]
        if has_noise:
            key = jax.random.fold_in(
                jax.random.fold_in(
                    jax.random.PRNGKey(plan.noise_seed), site_ix
                ),
                rnd,
            )
            noisy = code == ATTACK_NOISE
            out = [
                jnp.where(
                    noisy,
                    g + (noise_std * jax.random.normal(
                        jax.random.fold_in(key, i), g.shape, jnp.float32
                    )).astype(g.dtype),
                    g,
                )
                for i, g in enumerate(out)
            ]
        if has_collude:
            # the whole clique ships ONE shared direction per round (keyed by
            # round only), scaled to collude_scale × this site's own gradient
            # norm — coordinated, magnitude-plausible, outlier-test-resistant
            ckey = jax.random.fold_in(
                jax.random.PRNGKey(plan.collude_seed), rnd
            )
            dirs = [
                jax.random.normal(
                    jax.random.fold_in(ckey, i), g.shape, jnp.float32
                )
                for i, g in enumerate(leaves)
            ]
            gsq = jnp.zeros((), jnp.float32)
            dsq = jnp.zeros((), jnp.float32)
            for g, d in zip(leaves, dirs):
                gsq = gsq + jnp.sum(jnp.square(g.astype(jnp.float32)))
                dsq = dsq + jnp.sum(jnp.square(d))
            mag = collude_scale * jnp.sqrt(gsq) / jnp.maximum(
                jnp.sqrt(dsq), 1e-30
            )
            colluding = code == ATTACK_COLLUDE
            out = [
                jnp.where(colluding, (d * mag).astype(g.dtype), g)
                for g, d in zip(out, dirs)
            ]
        return jax.tree.unflatten(treedef, out)

    return attack
