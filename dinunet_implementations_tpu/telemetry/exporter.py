"""Live observability endpoints: ``/metrics`` ``/healthz`` ``/statusz``
``/tracez``.

A stdlib-only :class:`StatusExporter` wraps a ``ThreadingHTTPServer`` so a
RUNNING daemon or serving engine can be asked what it is doing — the gap
every pre-r16 surface (metrics.jsonl, trace files, the report CLI) left
open, because they are all post-hoc. Wired behind ``--statusz-port`` on the
daemon CLI (``dinunet-tpu --serve``) and the serving CLI
(``python -m dinunet_implementations_tpu.serving``):

- ``GET /metrics`` — Prometheus text exposition (version 0.0.4) of the
  MetricsBus: counters, gauges, and log-histograms (cumulative ``_bucket``
  series + ``_sum``/``_count``). Names are sanitized and prefixed
  ``dinunet_``; a standard Prometheus scrape config points at it as-is.
- ``GET /healthz`` — per-subsystem readiness: each registered probe is a
  callable returning truthy (ready) / falsey (not ready) / raising
  (broken). 200 when all ready, 503 otherwise, JSON body either way.
- ``GET /statusz`` — one JSON snapshot: uptime, pid, the full bus snapshot,
  the caller's status dict (round number, membership, queue depths...), and
  the SLO error-budget burn computed from the configured latency histogram
  against the configured p99 target (see :func:`slo_burn`).
- ``GET /tracez`` — the most recent spans/events (from the flight
  recorder's bounded ring when one is attached, else the tracer's tail) —
  "what was this process doing just now", without waiting for trace.jsonl.

The server runs on daemon threads and binds loopback by default; ``port=0``
picks a free port (returned by :meth:`start`). Handlers only ever READ
(bus snapshots, probe calls) — a scrape cannot mutate training state.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .bus import MetricsBus
from .hist import LogHistogram

#: Prometheus metric-name charset; everything else becomes "_"
_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
#: default SLO: fraction of requests allowed over the p99 target
SLO_BUDGET = 0.01

METRIC_PREFIX = "dinunet_"


def _prom_name(name: str) -> str:
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return METRIC_PREFIX + name


def _split_series(key: str) -> tuple[str, str]:
    """A bus series key back into (name, "{labels}" | "")."""
    brace = key.find("{")
    if brace < 0:
        return key, ""
    return key[:brace], key[brace:]


def _prom_value(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if not f.is_integer() else str(int(f))


def _merge_labels(labels: str, extra: str) -> str:
    """Append ``extra`` (e.g. ``le="0.5"``) into a ``{...}`` label blob."""
    if not labels:
        return "{" + extra + "}"
    return labels[:-1] + "," + extra + "}"


def render_prometheus(snapshot: dict) -> str:
    """The Prometheus text exposition (0.0.4) of a bus snapshot. Pure
    function of the snapshot — the format-validity tests run it without a
    server."""
    lines: list[str] = []
    typed: set[str] = set()

    def type_line(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for key, val in sorted(snapshot.get("counters", {}).items()):
        name, labels = _split_series(key)
        pname = _prom_name(name)
        type_line(pname, "counter")
        lines.append(f"{pname}{labels} {_prom_value(val)}")
    for key, val in sorted(snapshot.get("gauges", {}).items()):
        name, labels = _split_series(key)
        pname = _prom_name(name)
        type_line(pname, "gauge")
        lines.append(f"{pname}{labels} {_prom_value(val)}")
    for key, hd in sorted(snapshot.get("histograms", {}).items()):
        name, labels = _split_series(key)
        pname = _prom_name(name)
        type_line(pname, "histogram")
        hist = LogHistogram.from_dict(hd)
        for le, cum in hist.cumulative():
            le_s = "+Inf" if math.isinf(le) else _prom_value(le)
            le_label = _merge_labels(labels, 'le="' + le_s + '"')
            lines.append(f"{pname}_bucket{le_label} {cum}")
        lines.append(f"{pname}_sum{labels} {_prom_value(hist.sum)}")
        lines.append(f"{pname}_count{labels} {hist.count}")
    return "\n".join(lines) + "\n"


def slo_burn(hist: LogHistogram | None, p99_target: float,
             budget: float = SLO_BUDGET) -> dict:
    """Error-budget burn of a latency histogram against a p99 target.

    The SLO is "``(1 - budget)`` of samples at or under ``p99_target``"
    (budget defaults to 1%, i.e. a p99 objective). ``burn`` is the
    violation rate over the allowed rate: 1.0 = burning exactly the budget,
    <1 healthy, >1 violating. Violations come from
    :meth:`~.hist.LogHistogram.over` — buckets certainly above the target —
    so the burn never overstates; ``p99_observed`` is the (upper-edge,
    conservative the other way) histogram estimate for eyeballing."""
    if hist is None or hist.count == 0:
        return {
            "p99_target": p99_target, "budget": budget, "samples": 0,
            "violations": 0, "violation_rate": None, "burn": None,
            "p99_observed": None,
        }
    over = hist.over(p99_target)
    rate = over / hist.count
    return {
        "p99_target": p99_target,
        "budget": budget,
        "samples": hist.count,
        "violations": over,
        "violation_rate": round(rate, 6),
        "burn": round(rate / budget, 4),
        "p99_observed": hist.quantile(0.99),
    }


class StatusExporter:
    """See module docstring.

    ``health``: ``{subsystem: callable}`` readiness probes.
    ``statusz``: callable returning the caller's live status dict (merged
    into ``/statusz``).
    ``slo``: ``{"histogram": bus series NAME, "p99_target_ms": float}`` —
    the latency series the burn is computed over (all label variants
    merged).
    """

    def __init__(self, bus: MetricsBus, *, port: int = 0,
                 host: str = "127.0.0.1", tracer=None, flight=None,
                 health: dict | None = None, statusz=None,
                 slo: dict | None = None, tracez_limit: int = 256):
        self.bus = bus
        self.tracer = tracer
        self.flight = flight
        self.health = dict(health or {})
        self.statusz = statusz
        self.slo = dict(slo or {})
        self.tracez_limit = tracez_limit
        self._host = host
        self._port = port
        self._t0 = time.monotonic()
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- payload builders (also the test surface) -------------------------

    def metrics_text(self) -> str:
        return render_prometheus(self.bus.snapshot())

    def healthz(self) -> tuple[int, dict]:
        subsystems = {}
        ok = True
        for name, probe in self.health.items():
            try:
                ready = bool(probe())
                subsystems[name] = {"ready": ready}
            except Exception as e:  # a broken probe IS the finding
                ready = False
                subsystems[name] = {"ready": False, "error": str(e)}
            ok &= ready
        return (200 if ok else 503), {
            "status": "ok" if ok else "unavailable",
            "subsystems": subsystems,
        }

    def slo_status(self) -> dict | None:
        if not self.slo:
            return None
        hist = self.bus.merged_histogram(self.slo.get("histogram", ""))
        return {
            "histogram": self.slo.get("histogram"),
            **slo_burn(
                hist, float(self.slo.get("p99_target_ms", 0.0)),
                float(self.slo.get("budget", SLO_BUDGET)),
            ),
        }

    def statusz_payload(self) -> dict:
        payload = {
            "pid": os.getpid(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "slo": self.slo_status(),
            "metrics": self.bus.snapshot(),
        }
        if self.statusz is not None:
            try:
                payload["status"] = self.statusz()
            except Exception as e:
                payload["status"] = {"error": str(e)}
        return payload

    def tracez_payload(self) -> dict:
        if self.flight is not None:
            events = self.flight.recent(self.tracez_limit)
        elif self.tracer is not None:
            events = self.tracer.events()[-self.tracez_limit:]
        else:
            events = []
        return {"recent": events, "count": len(events)}

    # -- HTTP plumbing ----------------------------------------------------

    def _handler_class(self):
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # a scrape is not a log line
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, payload: dict) -> None:
                from .sink import _finite

                self._send(
                    code,
                    json.dumps(
                        _finite(payload), default=str, allow_nan=False
                    ).encode(),
                    "application/json",
                )

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/statusz"
                try:
                    if path == "/metrics":
                        self._send(
                            200, exporter.metrics_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        code, payload = exporter.healthz()
                        self._json(code, payload)
                    elif path == "/statusz":
                        self._json(200, exporter.statusz_payload())
                    elif path == "/tracez":
                        self._json(200, exporter.tracez_payload())
                    else:
                        self._json(404, {
                            "error": f"unknown path {path!r}",
                            "endpoints": ["/metrics", "/healthz",
                                          "/statusz", "/tracez"],
                        })
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper went away mid-response

        return Handler

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        if self._server is not None:
            return self._port
        self._server = ThreadingHTTPServer(
            (self._host, self._port), self._handler_class()
        )
        self._server.daemon_threads = True
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="statusz-exporter",
            daemon=True,
        )
        self._thread.start()
        return self._port

    @property
    def port(self) -> int:
        return self._port

    def url(self, path: str = "/statusz") -> str:
        return f"http://{self._host}:{self._port}{path}"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "StatusExporter":
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
