"""Thread-safe host-side span tracer.

One tracer instance serves a whole fit: the training loop opens phase spans
(``fit`` / ``epoch`` / ``eval`` / ``checkpoint``), the prefetch planner
thread opens ``plan-build`` spans concurrently, and bench.py times its
per-epoch feed path — all into one event buffer. Spans nest per thread
(each thread keeps its own stack), timestamps come from ONE monotonic clock
(``time.perf_counter`` relative to the tracer's birth), so cross-thread
ordering in the emitted trace is real.

Output formats:

- ``write_jsonl(path)`` — one JSON object per event (machine-diffable; the
  report CLI's input);
- ``write_chrome_trace(path)`` — Chrome trace-event JSON (``traceEvents``
  with complete ``"X"`` spans + thread-name metadata), loadable in Perfetto
  (ui.perfetto.dev) or ``chrome://tracing``.

Span/event names must be string literals or module-level constants at the
call site — jaxlint R007 enforces it — so traces stay greppable and stable
across runs.

Deliberately stdlib-only: the report CLI and bench's host-side timing must
not pull jax in.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from contextlib import contextmanager


def duration(cache: dict, start: float, key: str):
    """Append elapsed seconds since ``start`` to ``cache[key]`` (reference
    ``coinstac_dinunet.utils.duration``, used at ``local.py:51-52``). The ONE
    reference-keyed duration-list helper — formerly trainer/logs.py, moved
    here so every timing helper lives with the tracer.

    ``start`` MUST come from ``time.perf_counter()`` — the tracer's one
    monotonic clock. (This helper read ``time.time()`` until r16 while every
    span used ``perf_counter``: an NTP step or DST jump mid-fit corrupted
    the checkpointed duration bookkeeping with negative or wildly wrong
    entries that a resume then carried forward.)"""
    cache.setdefault(key, []).append(time.perf_counter() - start)
    return cache[key][-1]


def new_trace_id() -> str:
    """A fresh 16-hex-char trace/request id for cross-process propagation:
    spool membership events, serving requests and checkpoint metadata carry
    these so one sample is followable from spool ingest through round
    aggregation and checkpoint publish to serve (dispatch rows + spans
    record them as ``trace_ids``)."""
    return os.urandom(8).hex()


class SpanTracer:
    """Collect nested spans + instant events + counters across threads.

    ``enabled=False`` builds a no-op tracer (every call returns immediately)
    so call sites can thread one tracer object unconditionally —
    :data:`NULL_TRACER` is the shared disabled instance.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._events: list[dict] = []
        self._listeners: list = []
        self._local = threading.local()
        # perf and unix birth times sampled back-to-back: every event ts is
        # relative to _t0 (monotonic), and the clock_sync row write_jsonl
        # emits lets the pod trace assembler (telemetry/assemble.py) map it
        # onto the wall clock shared across processes
        self._t0 = time.perf_counter()
        self._t0_unix = time.time()

    def clock_sync(self) -> dict:
        """The per-process clock anchor: this tracer's birth on both the
        monotonic (``t0_perf``) and wall (``t0_unix``) clocks, plus the
        pid. An event's wall time is ``t0_unix + ts/1e6`` — or, preferring
        the heartbeat-exchanged offset, ``offset + t0_perf + ts/1e6``."""
        return {
            "ph": "M", "name": "clock_sync", "pid": os.getpid(),
            "t0_perf": self._t0, "t0_unix": self._t0_unix,
        }

    # -- recording --------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _record(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)
            listeners = tuple(self._listeners)
        for fn in listeners:
            fn(ev)

    def add_listener(self, fn) -> None:
        """Mirror every recorded event into ``fn(event_dict)`` — the flight
        recorder's bounded ring feeds from here. Listeners run outside the
        tracer lock and must not raise; on a disabled tracer nothing is ever
        recorded, so nothing is ever delivered."""
        with self._lock:
            self._listeners.append(fn)

    @contextmanager
    def span(self, name: str, **attrs):
        """Context manager for one named span. Nests per thread; closes (and
        records) on ANY exit — normal return, early ``break``, or an
        exception unwinding through (``Preempted`` included), with
        ``ok: false`` marking the exceptional exits."""
        if not self.enabled:
            yield self
            return
        stack = self._stack()
        depth = len(stack)
        stack.append(name)
        start = time.perf_counter()
        try:
            yield self
        finally:
            stack.pop()
            end = time.perf_counter()
            self._record({
                "ph": "X",
                "name": name,
                "ts": (start - self._t0) * 1e6,  # trace-event µs
                "dur": (end - start) * 1e6,
                "tid": threading.get_ident(),
                "thread": threading.current_thread().name,
                "depth": depth,
                # sys.exc_info survives into finally only while an exception
                # is actually unwinding through the with-body
                "ok": sys.exc_info()[0] is None,
                **attrs,
            })

    def event(self, name: str, **attrs) -> None:
        """Instant event (checkpoint written, site quarantined, retry...)."""
        if not self.enabled:
            return
        self._record({
            "ph": "i",
            "name": name,
            "ts": (time.perf_counter() - self._t0) * 1e6,
            "tid": threading.get_ident(),
            "thread": threading.current_thread().name,
            **attrs,
        })

    def counter(self, name: str, value) -> None:
        """Named counter sample (compile count, queue depth, bytes...)."""
        if not self.enabled:
            return
        self._record({
            "ph": "C",
            "name": name,
            "ts": (time.perf_counter() - self._t0) * 1e6,
            "tid": threading.get_ident(),
            "value": value,
        })

    # -- aggregation (bench / report helpers) -----------------------------

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def total_seconds(self, name: str) -> float:
        """Summed duration of every closed span named ``name``."""
        return sum(
            e["dur"] for e in self.events()
            if e["ph"] == "X" and e["name"] == name
        ) / 1e6

    def count(self, name: str) -> int:
        return sum(
            1 for e in self.events()
            if e["ph"] in ("X", "i") and e["name"] == name
        )

    def reset(self) -> None:
        """Drop recorded events (the clock keeps running) — bench uses this
        to exclude warmup from its feed-timing stats."""
        with self._lock:
            self._events.clear()

    # -- emission ---------------------------------------------------------

    def write_jsonl(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            # first row: the clock anchor, so a bare trace.jsonl is
            # assemblable into a cross-process timeline even without the
            # heartbeat offsets (consumers filter on ph, so the metadata
            # row is invisible to the phase tables)
            fh.write(json.dumps(self.clock_sync()) + "\n")
            for ev in self.events():
                fh.write(json.dumps(ev) + "\n")
        return path

    def write_chrome_trace(self, path: str) -> str:
        """Perfetto/chrome://tracing-loadable trace-event JSON."""
        pid = os.getpid()
        events = self.events()
        out: list[dict] = []
        seen_threads: dict[int, str] = {}
        for ev in events:
            tid = ev.get("tid", 0)
            if tid not in seen_threads:
                seen_threads[tid] = str(ev.get("thread", tid))
        for tid, tname in seen_threads.items():
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        for ev in events:
            rec = {
                "ph": ev["ph"],
                "name": ev["name"],
                "ts": round(ev["ts"], 3),
                "pid": pid,
                "tid": ev.get("tid", 0),
            }
            if ev["ph"] == "X":
                rec["dur"] = round(ev["dur"], 3)
            if ev["ph"] == "i":
                rec["s"] = "t"  # thread-scoped instant
            args = {
                k: v for k, v in ev.items()
                if k not in ("ph", "name", "ts", "dur", "tid", "thread")
            }
            if args:
                rec["args"] = args
            out.append(rec)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as fh:
            json.dump({"traceEvents": out, "displayTimeUnit": "ms"}, fh)
        return path


#: shared no-op tracer — thread it where telemetry is off instead of None
NULL_TRACER = SpanTracer(enabled=False)
