"""Task registry: task_id → (model builder, Dataset, DataHandle).

Mirrors the reference's dispatch tables (``local.py:40-47``,
``remote.py:28-35``) and the ``NNComputation``/``AggEngine`` enums
(``comps/__init__.py:7-16``). Adding a computation = registering one entry
(the reference's "Add new NN computation Here" comment, made a table).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Callable

from ..core.config import NNComputation, TrainConfig
from ..data.api import DataHandle, SiteDataset
from ..parallel.mesh import MODEL_AXIS
from ..data.freesurfer import FreeSurferDataset, FSVDataHandle
from ..data.ica import ICADataHandle, ICADataset
from ..data.multimodal import MultimodalDataHandle, MultimodalDataset
from ..data.smri import SMRIDataHandle, SMRIDataset
from ..models.cnn3d import SMRI3DNet
from ..models.icalstm import ICALstm
from ..models.msannet import MSANNet
from ..models.transformer import MultimodalNet


@dataclass(frozen=True)
class TaskSpec:
    task_id: str
    build_model: Callable[[TrainConfig], object]
    dataset_cls: type[SiteDataset]
    handle_cls: type[DataHandle]
    # per-task inference forward spec (serving/engine.py): how the serving
    # path shapes a request for this task. None = the task has no serving
    # surface yet (it cannot be loaded into an InferenceEngine).
    serving: "ServingSpec | None" = None


def _ica_windows(a) -> int:
    """Window count per subject — the reference's rule: count from
    window_size, offset from stride (data/ica.py window_timecourses)."""
    return int(a.temporal_size / a.window_size)


@dataclass(frozen=True)
class ServingSpec:
    """What the serving engine needs to know about a task, statically.

    ``sample_shape(cfg)`` is ONE example's feature shape (no batch axis) —
    the shape the microbatcher's row buckets pad to, and the shape a
    request's rows must carry. ``stream_shape(cfg)`` is one STREAMING
    timestep's shape (None = the task has no recurrent session semantics);
    ``streaming_ok(cfg)`` gates the streaming lane on the config actually
    being causal — the ICA-LSTM streams iff ``bidirectional=False`` (the
    reverse direction of a biLSTM reads the future; models/icalstm.py
    ICALstmStream)."""

    sample_shape: Callable[[TrainConfig], tuple]
    stream_shape: Callable[[TrainConfig], tuple] | None = None
    streaming_ok: Callable[[TrainConfig], bool] | None = None

    def supports_streaming(self, cfg: TrainConfig) -> bool:
        return (
            self.stream_shape is not None
            and (self.streaming_ok is None or bool(self.streaming_ok(cfg)))
        )


def _build_msannet(cfg: TrainConfig):
    a = cfg.fs_args
    return MSANNet(
        in_size=a.input_size,
        hidden_sizes=tuple(a.hidden_sizes),
        out_size=a.num_class,
    )


def _build_icalstm(cfg: TrainConfig):
    a = cfg.ica_args
    return ICALstm(
        input_size=a.input_size,
        hidden_size=a.hidden_size,
        bidirectional=a.bidirectional,
        num_cls=a.num_class,
        num_comps=a.num_components,
        window_size=a.window_size,
        num_layers=a.num_layers,
        compute_dtype=a.compute_dtype or None,
        # model_axis_size > 1 → window axis sharded over the mesh model axis
        # (ring LSTM; parallel/sequence.py)
        sequence_axis=MODEL_AXIS if cfg.model_axis_size > 1 else None,
        sequence_microbatches=cfg.sequence_microbatches,
    )


def _build_smri3d(cfg: TrainConfig):
    a = cfg.smri3d_args
    return SMRI3DNet(
        channels=tuple(a.channels), num_cls=a.num_class,
        compute_dtype=a.compute_dtype or None,
        # The fold itself is applied ONCE in the data pipeline
        # (data/smri.py:space_to_depth_222_np; 2.0-2.6x end-to-end vs the
        # per-step in-model fold, docs/bench_smri_s2d_ab_r5.jsonl). The
        # model still takes the flag: it recognizes pre-folded 8-channel
        # input and no-ops, but keeps honoring the configured architecture
        # if a custom dataset_cls bypasses the pipeline fold.
        space_to_depth=a.space_to_depth,
    )


def _build_multimodal(cfg: TrainConfig):
    a = cfg.multimodal_args
    attention = a.attention or ("ring" if cfg.model_axis_size > 1 else "local")
    if attention == "ring" and cfg.model_axis_size < 2:
        # forced ring without a model axis would crash much later with an
        # opaque "unbound axis name" trace error on the vmap-folded path
        raise ValueError(
            'attention="ring" needs model_axis_size >= 2 (the token axis '
            "shards over the mesh model axis)"
        )
    return MultimodalNet(
        fs_input_size=a.fs_input_size,
        num_comps=a.num_components,
        window_size=a.window_size,
        embed_dim=a.embed_dim,
        num_heads=a.num_heads,
        num_layers=a.num_layers,
        mlp_ratio=a.mlp_ratio,
        num_cls=a.num_class,
        attention=attention,
        axis_name=MODEL_AXIS if attention == "ring" else None,
        compute_dtype=a.compute_dtype or None,
    )


TASKS: dict[str, TaskSpec] = {
    NNComputation.TASK_FREE_SURFER: TaskSpec(
        NNComputation.TASK_FREE_SURFER, _build_msannet, FreeSurferDataset,
        FSVDataHandle,
        serving=ServingSpec(
            sample_shape=lambda cfg: (cfg.fs_args.input_size,),
        ),
    ),
    NNComputation.TASK_ICA: TaskSpec(
        NNComputation.TASK_ICA, _build_icalstm, ICADataset, ICADataHandle,
        serving=ServingSpec(
            sample_shape=lambda cfg: (
                _ica_windows(cfg.ica_args),
                cfg.ica_args.num_components,
                cfg.ica_args.window_size,
            ),
            # one streaming timestep = one temporal window [C, W]
            stream_shape=lambda cfg: (
                cfg.ica_args.num_components, cfg.ica_args.window_size,
            ),
            streaming_ok=lambda cfg: not cfg.ica_args.bidirectional,
        ),
    ),
    NNComputation.TASK_SMRI_3D: TaskSpec(
        NNComputation.TASK_SMRI_3D, _build_smri3d, SMRIDataset, SMRIDataHandle,
        serving=ServingSpec(
            # pipeline-folded shape when space_to_depth is on (data/smri.py
            # space_to_depth_222_np — requests arrive pre-folded, like the
            # training inventory), the raw single-channel volume otherwise
            sample_shape=lambda cfg: (
                tuple(d // 2 for d in cfg.smri3d_args.volume_shape) + (8,)
                if cfg.smri3d_args.space_to_depth
                else tuple(cfg.smri3d_args.volume_shape)
            ),
        ),
    ),
    NNComputation.TASK_MULTIMODAL: TaskSpec(
        NNComputation.TASK_MULTIMODAL, _build_multimodal,
        MultimodalDataset, MultimodalDataHandle,
        serving=ServingSpec(
            sample_shape=lambda cfg: (
                cfg.multimodal_args.fs_input_size
                + _ica_windows(cfg.multimodal_args)
                * cfg.multimodal_args.num_components
                * cfg.multimodal_args.window_size,
            ),
        ),
    ),
}


def get_task(task_id: str) -> TaskSpec:
    if task_id not in TASKS:
        raise ValueError(f"Invalid task: {task_id!r} (have {sorted(TASKS)})")
    return TASKS[task_id]


def register_task(spec: TaskSpec):
    TASKS[spec.task_id] = spec


def task_cache(cfg: TrainConfig) -> dict:
    """The flat cache dict datasets consume (the reference merges GUI input
    into one cache; our datasets read the same keys)."""
    return dataclasses.asdict(cfg.task_args())
