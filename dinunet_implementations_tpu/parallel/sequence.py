"""Sequence / context parallelism over the ``model`` mesh axis.

The reference has no sequence sharding (SURVEY.md §2.2: its longest-sequence
handling is a single-device Python-loop LSTM over ≤98 windows). For the TPU
build, long-context is first-class: sequences too long for one device's HBM
shard their time axis across the ``model`` axis, with collectives carrying the
cross-chunk dependencies:

- :func:`ring_attention` — blockwise attention with online-softmax
  accumulation while K/V blocks rotate around the ring via ``ppermute``
  (the standard ring-attention recipe; memory per device is O(T/n)).
- :func:`ring_lstm` — the LSTM carry relayed around the ring: device d
  computes microbatch j's chunk in wavefront stage ``j + d`` and hands
  (h, c) to device d+1. A recurrence is inherently sequential, so a single
  sequence incurs n-stage latency; splitting the batch into ``m``
  microbatches pipelines the wavefront so devices work on different
  microbatches concurrently. Per-device row-steps are ``(m + n - 1)·B/m``
  vs the dense ``B`` — an overhead factor of ``(m + n - 1)/m`` (→ 1 as m
  grows), NOT the n× of the unpipelined masked wavefront (``m=1``), which
  recomputes every stage on every device. What the ring buys is *memory*
  scaling (n× longer sequences than fit on one device) at modest extra
  FLOPs; the microbatch count trades pipeline overhead against MXU row
  utilization (B/m rows per kernel call).

All functions run inside ``shard_map``/``vmap`` with a bound axis name.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.jaxcompat import axis_size
from .mesh import MODEL_AXIS


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def ring_attention(q, k, v, axis_name: str | None = MODEL_AXIS):
    """Ring attention over a sequence sharded on ``axis_name``.

    q/k/v: ``[B, T_local, N, Hd]`` per device (full heads, local time chunk).
    Returns ``[B, T_local, N, Hd]`` — exact (non-causal) softmax attention
    over the *global* sequence, computed with online-softmax accumulation as
    K/V blocks rotate around the ring.
    """
    if axis_name is None:
        from ..models.transformer import dot_product_attention

        return dot_product_attention(q, k, v)

    n = axis_size(axis_name)
    scale = q.shape[-1] ** -0.5
    B, T, N, Hd = q.shape

    num = jnp.zeros((B, T, N, Hd), jnp.float32)
    den = jnp.zeros((B, N, T), jnp.float32)
    mx = jnp.full((B, N, T), -jnp.inf, jnp.float32)

    def step(carry, _):
        k_blk, v_blk, num, den, mx = carry
        logits = jnp.einsum(
            "btnh,bsnh->bnts", q, k_blk, preferred_element_type=jnp.float32
        ) * scale
        blk_max = logits.max(axis=-1)
        new_mx = jnp.maximum(mx, blk_max)
        corr = jnp.exp(mx - new_mx)
        p = jnp.exp(logits - new_mx[..., None])  # [B, N, T, S]
        num_new = num * jnp.moveaxis(corr, 1, 2)[..., None] + jnp.einsum(
            "bnts,bsnh->btnh", p, v_blk.astype(jnp.float32)
        )
        den_new = den * corr + p.sum(axis=-1)
        k_nxt = jax.lax.ppermute(k_blk, axis_name, _ring_perm(n))
        v_nxt = jax.lax.ppermute(v_blk, axis_name, _ring_perm(n))
        return (k_nxt, v_nxt, num_new, den_new, new_mx), None

    (k_f, v_f, num, den, mx), _ = jax.lax.scan(
        step, (k, v, num, den, mx), None, length=n
    )
    out = num / jnp.moveaxis(den, 1, 2)[..., None]
    return out.astype(q.dtype)


def _auto_microbatches(B: int, n: int) -> int:
    """Pick the microbatch count that minimizes hardware row-tile work:
    ``(m + n - 1)`` stages × ``ceil((B/m)/8)`` sublane tiles per stage (rows
    tile to 8 on the MXU, so a 1-row call costs a full tile). Ties break
    toward smaller ``m`` (fewer ppermute rounds). m=1 — the masked
    wavefront — wins naturally when B is a single tile; capped at 4n (the
    pipeline is full by then)."""
    if n <= 1:
        return 1

    def tile_cost(m):
        return (m + n - 1) * -(-(B // m) // 8)

    return min(
        (m for m in range(1, min(4 * n, B) + 1) if B % m == 0),
        key=lambda m: (tile_cost(m), m),
    )


def ring_lstm(cell_fn, x_local, h0, c0, axis_name: str = MODEL_AXIS,
              microbatches: int | None = None):
    """Run an LSTM over a time-sharded sequence by relaying the carry around
    the ring, pipelined over batch microbatches (wavefront overlap).

    ``cell_fn(x_chunk, (h, c)) -> (hs_chunk, (hT, cT))`` — any full-sequence
    cell (e.g. a bound ``LSTMCell``). ``x_local`` is this device's
    ``[B, T_local, D]`` chunk; ``h0``/``c0`` [B, H] seed the sequence start.

    The batch splits into ``m = microbatches`` slices (``None`` → heuristic,
    :func:`_auto_microbatches`). Microbatch j's chunk-d rows are computed on
    device d at wavefront stage ``j + d`` (``m + n - 1`` stages total), so
    devices work on *different* microbatches concurrently instead of
    recomputing every stage SPMD-uniformly and masking — per-device
    row-steps are ``(m + n - 1)·B/m`` vs the masked wavefront's ``n·B``
    (``m=1`` reproduces exactly that masked behavior). Stages at the
    pipeline fill/drain still execute (SPMD uniformity) on clamped dummy
    slices whose writes are masked out.

    Returns ``(hs_local [B, T_local, H], (hT, cT))`` where the terminal
    carry is valid on every device (broadcast from the last ring position).
    """
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B = x_local.shape[0]
    m = _auto_microbatches(B, n) if microbatches is None else microbatches
    if m < 1 or B % m:
        raise ValueError(
            f"microbatches={m} must be >= 1 and divide the batch ({B})"
        )
    mb = B // m

    def fresh(j):  # h0/c0 rows seeding microbatch j (clamped at fill/drain)
        row = jnp.clip(j, 0, m - 1) * mb
        return (
            jax.lax.dynamic_slice_in_dim(h0, row, mb, 0),
            jax.lax.dynamic_slice_in_dim(c0, row, mb, 0),
        )

    # device 0 seeds microbatch 0 at stage 0; everyone else idles until the
    # wavefront arrives (their stage-0 compute is masked garbage)
    carry = jax.tree.map(
        lambda f: jnp.where(idx == 0, f, jnp.zeros_like(f)), fresh(0)
    )
    out = None
    finals = None
    # Python loop over stages (static: m + n - 1 is mesh/config-determined):
    # cell_fn is typically a bound flax submodule, which cannot be called
    # inside a lax.scan body from a compact parent.
    for s in range(m + n - 1):
        j = s - idx  # the microbatch this device advances at stage s
        valid = (j >= 0) & (j < m)
        row = jnp.clip(j, 0, m - 1) * mb
        x_mb = jax.lax.dynamic_slice_in_dim(x_local, row, mb, 0)
        hs, (hT, cT) = cell_fn(x_mb, carry)
        if out is None:
            out = jnp.zeros((B,) + hs.shape[1:], hs.dtype)
            finals = (
                jnp.zeros((B,) + hT.shape[1:], hT.dtype),
                jnp.zeros((B,) + cT.shape[1:], cT.dtype),
            )
        out = jnp.where(
            valid,
            jax.lax.dynamic_update_slice_in_dim(out, hs.astype(out.dtype), row, 0),
            out,
        )
        # the last ring position finishes microbatch j: record its terminal
        done = valid & (idx == n - 1)
        finals = jax.tree.map(
            lambda f, t: jnp.where(
                done,
                jax.lax.dynamic_update_slice_in_dim(f, t.astype(f.dtype), row, 0),
                f,
            ),
            finals, (hT, cT),
        )
        # relay microbatch j's carry to device d+1 (stage s+1); device 0
        # instead seeds the NEXT microbatch fresh
        send = jax.tree.map(
            lambda t: jnp.where(valid, t, jnp.zeros_like(t)), (hT, cT)
        )
        recv = jax.tree.map(
            lambda t: jax.lax.ppermute(t, axis_name, _ring_perm(n)), send
        )
        carry = jax.tree.map(
            lambda f, r: jnp.where(idx == 0, f, r), fresh(s + 1), recv
        )
    # only device n-1 wrote finals; a psum broadcasts them everywhere
    final = jax.tree.map(
        lambda t: jax.lax.psum(t, axis_name) if n > 1 else t, finals
    )
    return out, final


def reverse_sequence(x_local, axis_name: str = MODEL_AXIS, axis: int = 1):
    """Time-reverse a sequence that is sharded on ``axis_name``.

    If device i holds chunk i of the global sequence, after this call device i
    holds chunk i of the *reversed* global sequence: one ``ppermute`` swaps
    chunk i ↔ chunk n-1-i, and a local flip reverses within the chunk. Used by
    the ring bidirectional LSTM (the reference's reverse direction runs the
    cell over ``torch.flip(x, (1,))``, ``comps/icalstm/models.py:60-65``).
    Self-inverse, and its AD transpose is itself (ppermute + flip are both
    linear and self-inverse here), so gradients route back to the owning chunk.
    """
    n = axis_size(axis_name)
    swapped = jax.lax.ppermute(
        x_local, axis_name, [(i, n - 1 - i) for i in range(n)]
    )
    return jnp.flip(swapped, axis=axis)


def shard_sequence(x, axis_name: str = MODEL_AXIS, axis: int = 1):
    """Split a gathered [B, T, ...] array into this device's chunk."""
    n = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    T = x.shape[axis]
    chunk = T // n
    return jax.lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=axis)


def gather_sequence(x_local, axis_name: str = MODEL_AXIS, axis: int = 1):
    """Inverse of :func:`shard_sequence` — all-gather chunks back to [B, T, ...]."""
    return jax.lax.all_gather(x_local, axis_name, axis=axis, tiled=True)
