"""Privacy plane (r20) — the scenario axis the source system exists for.

The reference trains across hospitals *without centralizing patient data*;
this package adds the machinery that makes that claim quantitative, built
the way every other scenario shipped (faults r7, packing r12, attacks r17):
traced, retrace-free program inputs over the site axis, statically compiled
out when off (S005-gated), with wire costs proven by S002 rather than
asserted.

- :mod:`.dpsgd` — per-site DP-SGD inside the rounds scan: gradient clipping
  + calibrated Gaussian noise, counter-keyed by ``(seed, site, round)`` so
  replays are chunk/resume/packing-independent;
- :mod:`.accounting` — the host-side RDP accountant (subsampled-Gaussian
  moments) surfacing (ε, δ) per epoch in telemetry/logs/report/statusz,
  with a clean checkpointed stop at ``dp_epsilon_budget``;
- :mod:`.secure_agg` — secure-aggregation masked wires for dSGD: pairwise
  antisymmetric one-time pads over the site axis on a shared fixed-point
  grid, canceling EXACTLY (integer arithmetic) in the weighted site sum;
- :mod:`.personalize` — FedProx-style personalized per-site heads: a
  param-path partition mask keeps designated leaves out of aggregation
  entirely; per-site head rows ride ``TrainState.personal`` P(site)-sharded
  like health.
"""

from .accounting import (
    RdpAccountant,
    effective_noise_multiplier,
    sampling_fraction,
)
from .dpsgd import dp_enabled, make_dp_fn
from .personalize import (
    head_leaf_paths,
    merge_head,
    personal_row_template,
    strip_tree,
)
from .secure_agg import SECURE_AGGS, secure_agg_enabled

__all__ = [
    "RdpAccountant",
    "SECURE_AGGS",
    "dp_enabled",
    "effective_noise_multiplier",
    "head_leaf_paths",
    "make_dp_fn",
    "merge_head",
    "personal_row_template",
    "sampling_fraction",
    "secure_agg_enabled",
    "strip_tree",
]
