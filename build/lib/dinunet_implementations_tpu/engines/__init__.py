from .base import Engine, available_engines, make_engine
from . import dsgd, powersgd, rankdad  # noqa: F401 — register engines
from .lowrank import is_compressible, orthonormalize, subspace_iteration, to_matrix
