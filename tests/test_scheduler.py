"""Fleet scheduler (r22): one pod, many tenants.

The load-bearing claims, as tests:

- ``fair_share`` is a law, not a heuristic: strictly descending priority
  bands, weighted max-min within a band, demand caps, deterministic
  tiebreak — the same inputs always produce the same grants;
- the scheduler spool speaks the membership-spool dialect (sorted
  filenames, remove-on-apply, ``.rejected`` quarantine) and a malformed
  register cannot take the pod down;
- preempt-and-yield is checkpoint-then-yield and resume is BIT-EXACT: a
  tenant preempted by a higher-priority arrival finishes with the SAME
  params digest as a never-preempted reference run, and its per-tenant
  CompileGuard counts ONE epoch compile across the whole
  grant/yield/resume sequence;
- tenants are isolated directory-deep: tenant A exhausting its DP
  ε-budget (clean checkpointed stop) and quarantining a poisoned site
  leaves tenant B's trajectory bit-identical to B's solo run;
- ONE exporter serves the pod: /statusz nests every tenant's daemon view
  and /metrics carries tenant-labeled series from the shared bus;
- per-tenant telemetry sinks carry the ``{"tenant": id}`` manifest tag
  and pass ``report --validate`` independently;
- a BackfillLane soaks up leftover slices with a serving ReplicaSet and
  closes with zero post-warmup compiles.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from dinunet_implementations_tpu.core.config import FSArgs, TrainConfig
from dinunet_implementations_tpu.data.demo import make_fs_demo_tree
from dinunet_implementations_tpu.robustness.faults import FaultPlan
from dinunet_implementations_tpu.runner.scheduler import (
    BackfillLane,
    FleetScheduler,
    SchedulerError,
    TenantSpec,
    fair_share,
)
from dinunet_implementations_tpu.telemetry.bus import MetricsBus
from dinunet_implementations_tpu.telemetry.exporter import StatusExporter


# ---------------------------------------------------------------------------
# fixtures (tiny CPU corners; conftest forces 8 virtual devices)
# ---------------------------------------------------------------------------


def _cfg(**kw):
    base = dict(
        task_id="FS-Classification", batch_size=4, staleness_bound=2,
        num_slices=2, fs_args=FSArgs(input_size=8, hidden_sizes=(8,)),
        # donation off: the global XLA compile cache + donated buffers
        # corruption corner (serving/engine.py warmup note) — these tests
        # re-fit identical tiny programs, the exact cache-hit recipe
        donate_epoch_state=False,
    )
    base.update(kw)
    return TrainConfig(**base)


@pytest.fixture(scope="module")
def trees(tmp_path_factory):
    root = tmp_path_factory.mktemp("sched_trees")
    return [
        make_fs_demo_tree(str(root / f"tree{i}"), n_sites=4, subjects=32,
                          n_features=8, seed=i)
        for i in range(2)
    ]


def _spec(tenant, tree, **kw):
    base = dict(tenant=tenant, data_path=tree, config=_cfg(), capacity=4,
                inventory_rows=48, quorum=1)
    base.update(kw)
    return TenantSpec(**base)


def _run_to_done(sched, max_ticks=60):
    for _ in range(max_ticks):
        sched.tick(sleep_when_idle=False)
        if sched.done():
            return
    raise AssertionError("scheduler did not converge")


# ---------------------------------------------------------------------------
# fair_share: the allocation law
# ---------------------------------------------------------------------------


def test_fair_share_priority_bands_drain_first():
    req = [
        {"tenant": "lo", "priority": 1.0, "weight": 1.0, "demand": 4},
        {"tenant": "hi", "priority": 2.0, "weight": 1.0, "demand": 3},
    ]
    # the higher band takes all it can use before the lower band sees
    # the pool — that asymmetry IS preemption
    assert fair_share(4, req) == {"hi": 3, "lo": 1}
    assert fair_share(2, req) == {"hi": 2, "lo": 0}


def test_fair_share_weighted_max_min_within_band():
    req = [
        {"tenant": "a", "priority": 1.0, "weight": 2.0, "demand": 8},
        {"tenant": "b", "priority": 1.0, "weight": 1.0, "demand": 8},
    ]
    # 2:1 weights → 2:1 grants (max-min on grants-per-unit-weight)
    assert fair_share(6, req) == {"a": 4, "b": 2}


def test_fair_share_demand_caps_and_residue():
    req = [
        {"tenant": "a", "priority": 1.0, "weight": 1.0, "demand": 1},
        {"tenant": "hold", "priority": 1.0, "weight": 1.0, "demand": 0},
    ]
    # a holding tenant (demand 0) gets nothing; the unallocatable
    # residue (3 slices here) is the backfill's rent
    assert fair_share(4, req) == {"a": 1, "hold": 0}


def test_fair_share_deterministic_tiebreak_by_tenant_id():
    rows = [
        {"tenant": t, "priority": 1.0, "weight": 1.0, "demand": 4}
        for t in ("c", "a", "b")
    ]
    assert fair_share(1, rows) == {"a": 1, "b": 0, "c": 0}
    assert fair_share(1, list(reversed(rows))) == {"a": 1, "b": 0, "c": 0}


# ---------------------------------------------------------------------------
# scheduler spool: the admission wire
# ---------------------------------------------------------------------------


def test_scheduler_spool_register_shutdown_and_quarantine(tmp_path, trees):
    root = str(tmp_path / "pod")
    bus = MetricsBus()
    sched = FleetScheduler(root, pod_slices=2, bus=bus, poll_s=0.0,
                           verbose=False)
    # the JSON register form an operator (or GUI) writes — flat config
    # overrides, exactly like a membership join's "config" key
    ev = {
        "event": "register", "tenant": "study0", "data_path": trees[0],
        "capacity": 4, "inventory_rows": 48, "max_epochs": 1,
        "config": {
            "task_id": "FS-Classification", "batch_size": 4,
            "staleness_bound": 2, "num_slices": 2,
            "donate_epoch_state": False,
            "fs_args": {"input_size": 8, "hidden_sizes": [8]},
        },
    }
    with open(os.path.join(sched.spool_dir, "ev000.json"), "w") as fh:
        json.dump(ev, fh)
    with open(os.path.join(sched.spool_dir, "ev001.json"), "w") as fh:
        fh.write("{not json")  # malformed → .rejected quarantine
    with open(os.path.join(sched.spool_dir, "ev002.json"), "w") as fh:
        json.dump({"event": "register", "tenant": "../evil"}, fh)
    sched.tick(sleep_when_idle=False)
    assert "study0" in sched.tenants
    assert "../evil" not in sched.tenants
    assert os.path.exists(
        os.path.join(sched.spool_dir, "ev001.json.rejected")
    )
    assert not os.path.exists(os.path.join(sched.spool_dir, "ev000.json"))
    snap = bus.snapshot()
    assert snap["counters"]['sched_events_total{kind="register"}'] == 1
    assert snap["counters"]['sched_events_total{kind="rejected"}'] >= 1
    # duplicate registration is an explicit refusal, not a silent replace
    with pytest.raises(SchedulerError):
        sched.register(_spec("study0", trees[0]))
    _run_to_done(sched)
    assert sched.tenants["study0"].status == "done"
    assert sched.tenants["study0"].daemon.epochs_run == 1
    # deregister on a finished tenant is a no-op; shutdown latches stop
    with open(os.path.join(sched.spool_dir, "zz_down.json"), "w") as fh:
        json.dump({"event": "shutdown"}, fh)
    sched.ingest()
    assert sched._stop
    out = sched.close()
    assert out["tenants"]["study0"]["epoch_compiles"] == 1


# ---------------------------------------------------------------------------
# preempt-and-yield: the drill the ISSUE names
# ---------------------------------------------------------------------------


def test_preempt_resume_bit_exact_one_compile(tmp_path, trees):
    """A tenant preempted by a higher-priority arrival (checkpoint-then-
    yield, mask flip) resumes and finishes with the SAME params digest as
    a never-preempted reference run — and its CompileGuard counts ONE
    epoch compile across grant, yield, reload, and regrant."""
    ref = FleetScheduler(str(tmp_path / "ref"), pod_slices=2,
                         bus=MetricsBus(), poll_s=0.0, verbose=False)
    ra = ref.register(_spec("a", trees[0], max_epochs=4))
    _run_to_done(ref)
    ref_digest = ra.params_digest()
    ref_out = ref.close()
    assert ref_out["tenants"]["a"]["epoch_compiles"] == 1
    assert ref_out["goodput"]["preempt_count"] == 0

    sched = FleetScheduler(str(tmp_path / "pod"), pod_slices=2,
                           bus=MetricsBus(), poll_s=0.0, verbose=False)
    a = sched.register(_spec("a", trees[0], max_epochs=4, priority=1.0))
    sched.tick(sleep_when_idle=False)
    sched.tick(sleep_when_idle=False)
    assert a.daemon.epochs_run == 2 and a.granted == 2
    # a higher-priority tenant claims the whole pod mid-study
    b = sched.register(_spec("b", trees[1], max_epochs=2, priority=2.0))
    r = sched.tick(sleep_when_idle=False)
    assert r["grants"] == {"b": 2, "a": 0}
    assert a.preempted and a.preempt_count == 1 and a.granted == 0
    assert a.daemon.epochs_run == 2  # frozen while yielded
    assert r["preempt_pause_ms"] > 0  # the checkpoint IS the pause
    _run_to_done(sched)
    assert b.status == "done" and b.daemon.epochs_run == 2
    assert a.status == "done" and a.daemon.epochs_run == 4
    assert not a.preempted  # resumed through the reload path
    assert a.params_digest() == ref_digest  # bit-exact resume
    out = sched.close()
    # ONE compile per tenant across the whole preemption drill — the
    # mask flip stayed inside the compiled program
    assert out["tenants"]["a"]["epoch_compiles"] == 1
    assert out["tenants"]["b"]["epoch_compiles"] == 1
    assert out["goodput"]["preempt_count"] == 1
    assert out["goodput"]["preempt_pause_ms_p99"] > 0


# ---------------------------------------------------------------------------
# tenant isolation: ε-budget stop + quarantine cannot cross tenants
# ---------------------------------------------------------------------------


def test_epsilon_budget_stop_and_quarantine_are_isolated(tmp_path, trees):
    """Tenant A trains under DP with a tiny ε-budget (exhausts after one
    epoch → clean checkpointed stop) AND a NaN-poisoned site (quarantine
    latch). Tenant B, sharing the pod, must finish bit-identical to its
    own solo run — budgets, ledgers, and quarantine state are per-tenant."""
    solo = FleetScheduler(str(tmp_path / "solo"), pod_slices=2,
                          bus=MetricsBus(), poll_s=0.0, verbose=False)
    sb = solo.register(_spec("b", trees[1], max_epochs=3, slice_quota=1))
    _run_to_done(solo)
    solo_digest = sb.params_digest()
    solo.close()

    bus = MetricsBus()
    sched = FleetScheduler(str(tmp_path / "pod"), pod_slices=2, bus=bus,
                           poll_s=0.0, verbose=False)
    a = sched.register(_spec(
        "a", trees[0], max_epochs=6, slice_quota=1,
        config=_cfg(dp_clip=1.0, dp_noise_multiplier=0.8,
                    dp_epsilon_budget=1e-3, quarantine_rounds=1),
        fault_plan=FaultPlan(nan_at=((1, 0),)),
    ))
    b = sched.register(_spec("b", trees[1], max_epochs=3, slice_quota=1))
    _run_to_done(sched)
    # A: ε-budget exhaustion is a clean per-tenant stop, not a crash
    assert a.status == "stopped"
    assert a.daemon.epochs_run < 6
    assert a.daemon.trainer._dp_epsilon is not None
    assert a.daemon.trainer._dp_epsilon >= 1e-3
    # A's poisoned site is quarantined in A's OWN health state...
    assert np.asarray(a.daemon.state.health["quarantined"]).max() > 0
    # ...and B never saw any of it: bit-exact with the solo run
    assert np.asarray(b.daemon.state.health["quarantined"]).max() == 0
    assert b.daemon.trainer._dp_epsilon is None  # no DP leakage either
    assert b.status == "done" and b.daemon.epochs_run == 3
    assert b.params_digest() == solo_digest
    snap = bus.snapshot()
    # the budget stop is attributable on the pod bus, tenant-labeled
    assert snap["counters"][
        'serve_dp_budget_stops_total{tenant="a"}'
    ] == 1
    out = sched.close()
    assert out["tenants"]["a"]["epoch_compiles"] == 1
    assert out["tenants"]["b"]["epoch_compiles"] == 1


# ---------------------------------------------------------------------------
# one exporter, many fits: /statusz + /metrics + per-tenant sinks
# ---------------------------------------------------------------------------


def test_statusz_and_telemetry_sinks_are_tenant_scoped(tmp_path, trees):
    from dinunet_implementations_tpu.telemetry import report

    bus = MetricsBus()
    root = str(tmp_path / "pod")
    sched = FleetScheduler(root, pod_slices=2, bus=bus, poll_s=0.0,
                           verbose=False)
    for i, name in enumerate(("alpha", "beta")):
        sched.register(_spec(
            name, trees[i], max_epochs=2, slice_quota=1,
            config=_cfg(telemetry="on"),
        ))
    _run_to_done(sched)
    ex = StatusExporter(bus, port=0, health=sched.health_probes(),
                        statusz=sched.status)
    with ex:
        url = f"http://127.0.0.1:{ex.port}"
        with urllib.request.urlopen(f"{url}/statusz", timeout=5) as r:
            payload = json.loads(r.read())
        assert payload["status"]["mode"] == "scheduler"
        tv = payload["status"]["tenants"]
        assert set(tv) == {"alpha", "beta"}
        assert tv["alpha"]["epochs_run"] == 2
        assert tv["alpha"]["daemon"]["slice_grant"] is not None
        with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
            text = r.read().decode()
        # the shared bus carries every series tenant-labeled
        assert 'tenant="alpha"' in text and 'tenant="beta"' in text
        with urllib.request.urlopen(f"{url}/healthz", timeout=5) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok"
        assert health["subsystems"]["tenant_alpha"]["ready"]
    out = sched.close()
    # per-tenant sinks: manifest-tagged, each passes report --validate
    for name in ("alpha", "beta"):
        tdir = os.path.join(root, "tenants", name, "output", "telemetry",
                            "serve")
        man = json.load(open(os.path.join(tdir, "manifest.json")))
        assert man["tags"] == {"tenant": name}
        assert report.main([tdir, "--validate"]) == 0
    assert all(
        v["epoch_compiles"] == 1 for v in out["tenants"].values()
    )


# ---------------------------------------------------------------------------
# backfill: the residue serves
# ---------------------------------------------------------------------------


def test_backfill_lane_serves_leftover_and_never_compiles(tmp_path):
    import jax
    import jax.numpy as jnp

    from dinunet_implementations_tpu.runner.registry import get_task
    from dinunet_implementations_tpu.trainer.steps import FederatedTask

    cfg = TrainConfig(
        task_id="FS-Classification", batch_size=4, seed=3,
    ).with_overrides({"fs_args": {"input_size": 6, "hidden_sizes": [8]}})
    task = FederatedTask(get_task(cfg.task_id).build_model(cfg))
    params, stats = task.init_variables(
        jax.random.PRNGKey(0), jnp.ones((4, 6))
    )
    rng = np.random.default_rng(0)

    def feed():
        return rng.normal(size=(2, 6)).astype(np.float32)

    lane = BackfillLane(
        cfg, feed, params=params, batch_stats=stats, replicas=1,
        requests_per_quantum=3,
        engine_kwargs=dict(row_buckets=(1, 2, 4), max_delay_ms=1.0,
                           supervise_interval_s=0.05),
    )
    bus = MetricsBus()
    sched = FleetScheduler(str(tmp_path / "pod"), pod_slices=2, bus=bus,
                           poll_s=0.0, verbose=False, backfill=lane)
    # an empty pod: the whole pool is residue, the lane rents all of it
    r = sched.tick(sleep_when_idle=False)
    assert r["leftover"] == 2
    assert r["served"]["requests"] == 3
    r = sched.tick(sleep_when_idle=False)
    assert r["served"]["samples"] == 6
    snap = bus.snapshot()
    assert snap["gauges"]["sched_backfill_requests"] == 6.0
    # lane series are lane-labeled on the same pod bus
    assert any('lane="backfill"' in k for k in snap["gauges"])
    out = sched.close()  # asserts zero post-warmup lane compiles
    assert out["backfill"]["requests_served"] == 6
    assert out["backfill"]["samples_served"] == 12
    st = lane.status()
    assert st["started"] is False  # closed lanes release their fleet


def test_backfill_lane_requires_a_feed():
    with pytest.raises(SchedulerError):
        BackfillLane(TrainConfig(), None)
