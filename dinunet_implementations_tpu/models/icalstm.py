"""ICALstm — the ICA-timecourse bidirectional LSTM classifier.

Capability parity with reference ``comps/icalstm/models.py:5-110``:

- per-window encoder ``Linear(num_comps*window → input_size) + ReLU``
  (the reference applies it in a Python loop over the batch,
  ``models.py:107``; here it is one batched matmul over ``[B*S]`` rows);
- hand-rolled (bi)LSTM: per direction a cell with ``i2h: (D → 4H)``,
  ``h2h: (H → 4H)``; ``hidden_size`` is split across directions
  (``models.py:55-57``); the reverse direction runs over the time-flipped
  input and hidden sequences concat on the feature dim (``models.py:60-65``);
- mean-pool over time, then the classifier head
  ``Dropout(0.25) → Linear(H→256) → BatchNorm1d(256) → ReLU → Linear(256→64)
  → ReLU → Linear(64→num_cls)`` (``models.py:96-104``).

**Gate math.** The reference cell has a numerical quirk
(``models.py:31-38``): it applies ``sigmoid`` to the i/f/o pre-activations
*twice* (``gates = preact[:, :3H].sigmoid()`` then ``sigmoid(gates[...])``),
while ``g`` uses ``tanh`` of the raw pre-activation. ``double_sigmoid_gates``
reproduces that bit-for-bit for parity runs; the default is standard LSTM
gates (single sigmoid), which trains strictly better.

TPU-first shape of the recurrence: the input projection for *all* timesteps is
hoisted out of the loop into one ``[B*T, D] @ [D, 4H]`` MXU matmul; only the
``h @ W_hh`` recurrence stays inside ``lax.scan`` (sequential by nature).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..core.jaxcompat import axis_size
from .layers import BatchNorm, TorchLinearInit, compute_dtype_of, dense


def _lstm_gates(preact, H, double_sigmoid: bool):
    if double_sigmoid:
        gates = jax.nn.sigmoid(preact[..., : 3 * H])
        i = jax.nn.sigmoid(gates[..., :H])
        f = jax.nn.sigmoid(gates[..., H : 2 * H])
        o = jax.nn.sigmoid(gates[..., 2 * H : 3 * H])
    else:
        i = jax.nn.sigmoid(preact[..., :H])
        f = jax.nn.sigmoid(preact[..., H : 2 * H])
        o = jax.nn.sigmoid(preact[..., 2 * H : 3 * H])
    g = jnp.tanh(preact[..., 3 * H :])
    return i, f, o, g


def _auto_pallas() -> bool:
    # The fused kernel uses TPU-only pltpu.VMEM specs; any other accelerator
    # (e.g. GPU) must fall back to the lax.scan path rather than crash.
    return jax.default_backend() == "tpu"


class LSTMCell(nn.Module):
    """One direction over a full sequence: x [B, T, D] → hidden seq [B, T, H].

    Reference ``comps/icalstm/models.py:5-45`` — but the Python
    loop-over-timesteps becomes ``lax.scan`` (or the fused Pallas recurrence
    kernel, ops/lstm_pallas.py) and the i2h projection one batched matmul.

    ``use_pallas``: None = auto (fused kernel on accelerators, scan on CPU);
    the double-sigmoid compat mode always uses the scan path.
    """

    hidden_size: int
    double_sigmoid_gates: bool = False
    use_pallas: bool | None = None
    compute_dtype: str | None = None  # e.g. "bfloat16"; None = f32 (parity)

    @nn.compact
    def __call__(self, x, h0=None):
        B, T, D = x.shape
        H = self.hidden_size
        w_ih = self.param("w_ih", TorchLinearInit.kernel, (D, 4 * H))
        b_ih = self.param("b_ih", TorchLinearInit.bias_for(D), (4 * H,))
        w_hh = self.param("w_hh", TorchLinearInit.kernel, (H, 4 * H))
        b_hh = self.param("b_hh", TorchLinearInit.bias_for(H), (4 * H,))

        cdt = compute_dtype_of(self.compute_dtype)
        if h0 is None:
            # carry is always f32: the scan body computes an f32 carry (scan
            # requires carry-type invariance) and the kernel keeps f32 carries
            h0 = (jnp.zeros((B, H), jnp.float32), jnp.zeros((B, H), jnp.float32))

        use_pallas = (
            self.use_pallas if self.use_pallas is not None else _auto_pallas()
        ) and not self.double_sigmoid_gates
        if use_pallas:
            # fused kernel: i2h projection runs in-kernel with W_ih resident
            # in VMEM — streams x [T, B, D] once instead of a pre-projected
            # [T, B, 4H] (no XLA-side xi materialization at all)
            from ..ops.lstm_pallas import lstm_forward_fused

            return lstm_forward_fused(
                x, w_ih, b_ih + b_hh, w_hh, h0[0], h0[1], compute_dtype=cdt
            )

        if cdt is not None:
            # scan path: hoist the i2h projection for all timesteps into one
            # bf16 MXU matmul (f32 accum); XLA fuses the downcast epilogue
            xi = (jnp.dot(
                x.astype(cdt), w_ih.astype(cdt),
                preferred_element_type=jnp.float32,
            ) + (b_ih + b_hh)).astype(cdt)
        else:
            xi = x @ w_ih + (b_ih + b_hh)  # [B, T, 4H] — one matmul

        def step(carry, xt):
            h, c = carry
            if cdt is not None:
                preact = xt + jnp.dot(
                    h.astype(cdt), w_hh.astype(cdt),
                    preferred_element_type=jnp.float32,
                )
            else:
                preact = xt + h @ w_hh
            i, f, o, g = _lstm_gates(preact, H, self.double_sigmoid_gates)
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h

        (hT, cT), hs = jax.lax.scan(step, h0, jnp.swapaxes(xi, 0, 1))
        return jnp.swapaxes(hs, 0, 1), (hT, cT)


class _LSTMCellParams(nn.Module):
    """Parameter-only twin of :class:`LSTMCell` — declares the exact same
    param tree (names, shapes, inits) without running the recurrence, so the
    fused bidirectional kernel (one pallas_call spanning both directions,
    ops/lstm_pallas.py) can own the compute while checkpoints/params remain
    interchangeable with the per-direction cell modules."""

    in_dim: int
    hidden: int

    @nn.compact
    def __call__(self):
        D, H = self.in_dim, self.hidden
        w_ih = self.param("w_ih", TorchLinearInit.kernel, (D, 4 * H))
        b_ih = self.param("b_ih", TorchLinearInit.bias_for(D), (4 * H,))
        w_hh = self.param("w_hh", TorchLinearInit.kernel, (H, 4 * H))
        b_hh = self.param("b_hh", TorchLinearInit.bias_for(H), (4 * H,))
        return w_ih, b_ih + b_hh, w_hh


class BiLSTM(nn.Module):
    """Bidirectional wrapper (reference ``comps/icalstm/models.py:48-66``):
    ``hidden_size`` is the *total* width, split across directions.

    ``sequence_axis``: when set (a bound mesh axis name, normally
    ``parallel.mesh.MODEL_AXIS``), ``x`` is this device's time chunk of a
    sequence sharded over that axis; each direction runs as a ring LSTM
    (parallel/sequence.py) with the carry relayed around the ring. Submodule
    names match the dense path, so params are interchangeable.
    """

    hidden_size: int
    bidirectional: bool = True
    double_sigmoid_gates: bool = False
    use_pallas: bool | None = None
    compute_dtype: str | None = None
    sequence_axis: str | None = None
    # ring-LSTM wavefront microbatches (parallel/sequence.py): 0 = auto
    sequence_microbatches: int = 0
    # True opts in to the fused bidirectional pooled kernel (one pallas
    # sweep advancing both directions, site-native residuals under vmap —
    # ops/lstm_pallas.py). Default (None/False) runs the per-direction
    # kernels: the r5 A/B on the flagship 32-site bench measured the fused
    # path 27% SLOWER (80,531 vs 110,009 samples/sec/chip,
    # docs/bench_ab_bidir_r5.jsonl) despite its fewer relayout copies, so
    # the measured winner is the default and the fused path is the A/B arm.
    fused_bidir: bool | None = None
    # time_pool="mean": return the time-mean [B, H_total] instead of the
    # hidden sequence. Numerically identical to mean-pooling the concat
    # (column blocks reduce independently), but the [B, T, 2*per_dir] concat
    # never materializes — its per-direction boundary sits at a non-lane-
    # aligned feature offset (e.g. 174), and profiling the 32-site bench
    # showed XLA spending ~0.5 ms/round on relayout copies plus a slowed
    # reverse-direction backward kernel whose dhs cotangent arrived
    # lane-rotated. Dense path only (the ring path pools in ICALstm).
    time_pool: str | None = None

    @nn.compact
    def __call__(self, x, h0=None):
        if self.time_pool not in (None, "mean"):
            raise ValueError(f"unknown time_pool {self.time_pool!r}")
        if self.time_pool is not None and self.sequence_axis is not None:
            # a local-chunk mean would silently violate the global-mean
            # contract on a sequence-sharded input; pooling across chunks is
            # the caller's job (ICALstm's all_gather reduction)
            raise ValueError("time_pool requires sequence_axis=None")
        pool = (lambda s: jnp.mean(s, axis=1)) if self.time_pool == "mean" else (lambda s: s)
        per_dir = self.hidden_size // (2 if self.bidirectional else 1)

        use_pallas = (
            self.use_pallas if self.use_pallas is not None else _auto_pallas()
        ) and not self.double_sigmoid_gates
        if (self.bidirectional and use_pallas and self.time_pool == "mean"
                and self.fused_bidir is True):
            # fused bidirectional kernel: ONE pallas sweep advances both
            # directions (rev reads x through a time-flipped index map) and
            # the VJP runs flip-free. Param trees are identical to the
            # per-cell path (_LSTMCellParams). Restricted to the mean-pooled
            # path because the kernel returns hs_r in x-time convention —
            # the pool is time-order-invariant, while the sequence-returning
            # path must preserve the reference's no-flip-back concat order.
            # (time_pool == "mean" implies sequence_axis is None, checked
            # above.)
            from ..ops.lstm_pallas import bilstm_pool_forward_fused

            pf = _LSTMCellParams(x.shape[-1], per_dir, name="fwd")()
            pr = _LSTMCellParams(x.shape[-1], per_dir, name="rev")()
            h02 = None if h0 is None else jnp.stack([h0[0], h0[0]])
            c02 = None if h0 is None else jnp.stack([h0[1], h0[1]])
            pooled, (hT2, cT2) = bilstm_pool_forward_fused(
                x, pf, pr, h02, c02,
                compute_dtype=compute_dtype_of(self.compute_dtype),
            )
            return (
                pooled,
                (jnp.concatenate([hT2[0], hT2[1]], 1),
                 jnp.concatenate([cT2[0], cT2[1]], 1)),
            )

        fwd_cell = LSTMCell(
            per_dir, self.double_sigmoid_gates, self.use_pallas,
            self.compute_dtype, name="fwd"
        )
        if self.sequence_axis is None:
            fwd, (h, c) = fwd_cell(x, h0)
        else:
            from ..parallel.sequence import reverse_sequence, ring_lstm

            if h0 is None:
                z = jnp.zeros((x.shape[0], per_dir), jnp.float32)
                h0 = (z, z)
            fwd, (h, c) = ring_lstm(
                lambda xc, carry: fwd_cell(xc, carry), x, h0[0], h0[1],
                axis_name=self.sequence_axis,
                microbatches=self.sequence_microbatches or None,
            )
        if not self.bidirectional:
            return pool(fwd), (h, c)
        rev_cell = LSTMCell(
            per_dir, self.double_sigmoid_gates, self.use_pallas,
            self.compute_dtype, name="rev"
        )
        if self.sequence_axis is None:
            rev, (hr, cr) = rev_cell(jnp.flip(x, axis=1), h0)
        else:
            # reverse direction = the cell over the time-reversed GLOBAL
            # sequence; reverse_sequence re-shards it so device i holds
            # reversed-chunk i, making the local concat line up with the dense
            # path's (no flip-back, as the reference) hidden concat
            rev, (hr, cr) = ring_lstm(
                lambda xc, carry: rev_cell(xc, carry),
                reverse_sequence(x, self.sequence_axis, axis=1),
                h0[0], h0[1], axis_name=self.sequence_axis,
                microbatches=self.sequence_microbatches or None,
            )
        return (
            jnp.concatenate([pool(fwd), pool(rev)], axis=-1),
            (jnp.concatenate([h, hr], 1), jnp.concatenate([c, cr], 1)),
        )


class _StreamLSTM(nn.Module):
    """Streaming (single-direction) LSTM step over a CHUNK of new windows,
    with the mean-pool accumulator folded into the recurrence carry — the
    O(1) autoregressive state of the serving path (serving/session.py).

    Declares the exact ``fwd`` cell param tree of the dense path
    (:class:`_LSTMCellParams`), so a trained unidirectional :class:`ICALstm`
    checkpoint drives this module unchanged. The carry is ``(h, c, pooled,
    count)``: hidden/cell state plus the running hidden-state SUM and valid
    timestep count — everything the classifier head needs, at a size
    independent of how many windows the session has already consumed.

    Bit-exact chunk composition: the pooled sum accumulates INSIDE the
    ``lax.scan`` (a strict left fold in time order), so feeding windows
    ``[0..t1)`` then ``[t1..T)`` performs literally the same sequence of
    additions as feeding ``[0..T)`` in one chunk — streaming in chunks is
    bitwise identical to full-sequence replay through this module
    (tests/test_serving.py). ``step_valid`` gates padded chunk slots: an
    invalid step is an exact identity on all four carry parts, so
    time-padding a short chunk up to its shape bucket cannot perturb the
    session."""

    hidden: int
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, enc, h, c, pooled, count, step_valid):
        D = enc.shape[-1]
        w_ih, b, w_hh = _LSTMCellParams(D, self.hidden, name="fwd")()
        cdt = compute_dtype_of(self.compute_dtype)
        if cdt is not None:
            # mirror LSTMCell's mixed-precision scan path op-for-op: bf16
            # MXU matmuls with f32 accumulation, bf16 xi stream
            xi = (jnp.dot(
                enc.astype(cdt), w_ih.astype(cdt),
                preferred_element_type=jnp.float32,
            ) + b).astype(cdt)
        else:
            xi = enc @ w_ih + b  # [B, t, 4H] — one hoisted matmul

        H = self.hidden

        def step(carry, inp):
            h, c, pooled, count = carry
            xt, sv = inp  # [B, 4H] pre-projected window, [B] valid gate
            if cdt is not None:
                preact = xt + jnp.dot(
                    h.astype(cdt), w_hh.astype(cdt),
                    preferred_element_type=jnp.float32,
                )
            else:
                preact = xt + h @ w_hh
            i, f, o, g = _lstm_gates(preact, H, False)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            live = sv[:, None] > 0
            # invalid steps are exact identities: h/c/pooled hold, count
            # adds sv == 0 — a padded slot can never move the session
            return (
                jnp.where(live, h_new, h),
                jnp.where(live, c_new, c),
                jnp.where(live, pooled + h_new, pooled),
                count + sv,
            ), None

        (h, c, pooled, count), _ = jax.lax.scan(
            step,
            (h, c, pooled, count),
            (jnp.swapaxes(xi, 0, 1), jnp.swapaxes(step_valid, 0, 1)),
        )
        return h, c, pooled, count


class ICALstmStream(nn.Module):
    """Streaming twin of :class:`ICALstm` — the serving path's O(1) per-chunk
    step (serving/engine.py).

    Same parameter tree as the dense model (submodule names ``encoder`` /
    ``lstm/fwd`` / ``cls_fc1`` / ``cls_bn`` / ``cls_fc2`` / ``cls_fc3``), so
    one trained checkpoint serves both the batched full-sequence path and
    this incremental one. Processes only the chunk's NEW windows (encoder +
    recurrence from the carried ``(h, c)``), updates the scan-accumulated
    mean-pool state, and re-runs the tiny classifier head on the updated
    pool — cost per chunk is independent of the session's history length.

    Unidirectional only (``ICALstm(bidirectional=False)`` checkpoints): the
    reverse direction of a biLSTM reads the future, so no O(1) carry can
    reproduce it — the serving engine refuses streaming for bidirectional
    checkpoints rather than approximate them (docs/ARCHITECTURE.md
    "Serving"). Dropout is eval-mode (identity) by construction; the head
    BatchNorm runs on the checkpoint's running stats, so co-batched sessions
    never perturb each other."""

    input_size: int = 256
    hidden_size: int = 256
    num_cls: int = 2
    num_comps: int = 53
    window_size: int = 20
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, x, h, c, pooled, count, step_valid):
        # x: [B, t, C, W] new windows; h/c/pooled: [B, H]; count: [B];
        # step_valid: [B, t] (1.0 = real window, 0.0 = chunk padding)
        B, t = x.shape[0], x.shape[1]
        flat = x.reshape(B, t, -1)
        cdt = compute_dtype_of(self.compute_dtype)
        enc = nn.relu(
            dense(self.input_size, fan_in=self.num_comps * self.window_size,
                  name="encoder", dtype=cdt)(flat)
        )
        h, c, pooled, count = _StreamLSTM(
            self.hidden_size, self.compute_dtype, name="lstm"
        )(enc, h, c, pooled, count, step_valid)
        # classifier head on the running mean — identical layer stack (and
        # eval semantics) to ICALstm's; Dropout is a train-only no-op there
        o = (pooled / jnp.maximum(count, 1.0)[:, None]).astype(jnp.float32)
        o = dense(256, fan_in=o.shape[-1], name="cls_fc1")(o)
        o = BatchNorm(256, track_running_stats=True, name="cls_bn")(
            o, train=False
        )
        o = nn.relu(o)
        o = nn.relu(dense(64, fan_in=256, name="cls_fc2")(o))
        logits = dense(self.num_cls, fan_in=64, name="cls_fc3")(o)
        return logits, (h, c, pooled, count)


class ICALstm(nn.Module):
    input_size: int = 256
    hidden_size: int = 256
    bidirectional: bool = True
    num_cls: int = 2
    num_comps: int = 53
    window_size: int = 20
    num_layers: int = 1  # parity field; reference builds 1 layer regardless
    double_sigmoid_gates: bool = False
    dropout_rate: float = 0.25
    use_pallas: bool | None = None  # None = auto (kernel on accelerators)
    compute_dtype: str | None = None  # "bfloat16" = mixed precision (f32 accum)
    fused_bidir: bool | None = None  # True = opt-in fused bidir kernel (A/B loser, see BiLSTM)
    sequence_microbatches: int = 0  # ring wavefront microbatches; 0 = auto
    # Sequence parallelism (TPU extension, SURVEY.md §2.2): a bound mesh axis
    # name (parallel.mesh.MODEL_AXIS) shards the window axis S across that
    # axis — the encoder runs on the local chunk, the BiLSTM relays its carry
    # ring-style, and the time mean-pool finishes with an all_gather. Callers
    # pass the FULL [B, S, C, W] batch (replicated over the axis); the model
    # takes its own chunk. Init outside the mesh with sequence_axis=None —
    # param shapes/names are identical (FederatedTask.init_variables does this).
    sequence_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = True, mask=None):
        # x: [B, S, C, W] (windows, components, timepoints-per-window)
        B, S = x.shape[0], x.shape[1]
        flat = x.reshape(B, S, -1)  # [B, S, C*W]
        if self.sequence_axis is not None:
            from ..parallel.sequence import shard_sequence

            n = axis_size(self.sequence_axis)
            if S % n:
                raise ValueError(
                    f"sequence parallelism needs windows ({S}) divisible by "
                    f"the {self.sequence_axis!r} axis size ({n})"
                )
            flat = shard_sequence(flat, self.sequence_axis, axis=1)
        cdt = compute_dtype_of(self.compute_dtype)
        # under compute_dtype the encoder output stays bf16 — it feeds the
        # per-direction i2h projections, which consume bf16 directly
        enc = nn.relu(
            dense(self.input_size, fan_in=self.num_comp_window, name="encoder",
                  dtype=cdt)(flat)
        )
        o, h = BiLSTM(
            self.hidden_size,
            self.bidirectional,
            self.double_sigmoid_gates,
            self.use_pallas,
            self.compute_dtype,
            self.sequence_axis,
            fused_bidir=self.fused_bidir,
            sequence_microbatches=self.sequence_microbatches,
            # dense path: pool inside BiLSTM per direction — same values as
            # mean-pooling the concat (models.py:109) without materializing
            # the lane-misaligned [B, T, H_total] sequence concat
            time_pool=None if self.sequence_axis is not None else "mean",
            name="lstm",
        )(enc)
        if self.sequence_axis is not None:
            # mean over the GLOBAL window axis: local sum, then all_gather
            # (transpose = reduce-scatter, so chunk cotangents route back to
            # the owning device — sound under AD, unlike a bare psum here)
            o = jax.lax.all_gather(
                o.sum(axis=1), self.sequence_axis
            ).sum(axis=0) / S
        o = o.astype(jnp.float32)  # classifier head + BN stay full precision

        # classifier head (models.py:96-104); per-direction width totals
        # hidden_size when bidirectional splits evenly, else 2*(H//2).
        o = nn.Dropout(self.dropout_rate, deterministic=not train)(o)
        o = dense(256, fan_in=o.shape[-1], name="cls_fc1")(o)
        o = BatchNorm(256, track_running_stats=True, name="cls_bn")(
            o, train=train, mask=mask
        )
        o = nn.relu(o)
        o = nn.relu(dense(64, fan_in=256, name="cls_fc2")(o))
        return dense(self.num_cls, fan_in=64, name="cls_fc3")(o)

    @property
    def num_comp_window(self):
        return self.num_comps * self.window_size
