"""Package smoke (VERDICT r2 #8): the wheel installs into a clean target and
the README quick-start runs without the repo checkout on sys.path — against
the self-generated demo fixture, so no reference checkout is needed
(VERDICT r3 #5)."""

import os
import subprocess

import pytest

SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts", "package_smoke.sh")


@pytest.mark.golden
def test_wheel_install_and_quickstart(tmp_path):
    proc = subprocess.run(
        ["bash", SCRIPT, str(tmp_path)], capture_output=True, text=True,
        timeout=600,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "package smoke OK" in proc.stdout
