"""Shared low-rank machinery for the compressed engines (rankDAD / powerSGD).

The reference exposes three knobs (``compspec.json:236-238,268-270``):
``dad_reduction_rank`` (default 10), ``dad_num_pow_iters`` (default 5), and
``dad_tol`` (default 1e-3). Tolerance-based early exit inside jit is a
``lax.while_loop`` whose carry tracks the singular-value estimates — shapes
stay static, only the trip count is dynamic (bounded by ``num_iters``).

Matrix convention: a gradient leaf with ndim ≥ 2 is reshaped to
``[prod(leading), last]`` (Dense kernels are already [in, out]; conv kernels
[h, w, cin, cout] → [h*w*cin, cout]); ndim ≤ 1 leaves are "dense" and bypass
compression.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def is_compressible(g, min_rank_dim: int = 2) -> bool:
    return g.ndim >= 2 and min(_matrix_shape(g)) >= min_rank_dim


def lowrank_rank_groups(grads, rank: int) -> tuple:
    """``(groups, dense)`` — the engine-order wire structure of a low-rank
    factor exchange: ``groups`` is ``[(effective_rank, [(m, n), ...]), ...]``
    sorted by rank class (the exact grouping/order the rankDAD aggregate
    packs its gathers in), ``dense`` the 1-D/non-compressible leaf shapes
    that ride the dense psum path. The structured half of
    :func:`lowrank_wire_bytes`, used by the engines' ``wire_shapes``
    introspection hooks (checks/semantic.py S002)."""
    groups: dict[int, list] = {}
    dense = []
    for g in jax.tree.leaves(grads):
        if is_compressible(g):
            m, n = _matrix_shape(g)
            groups.setdefault(min(rank, m, n), []).append((m, n))
        else:
            dense.append(tuple(g.shape))
    return sorted(groups.items()), dense


def lowrank_wire_bytes(grads, rank: int, itemsize: int, pack: int = 1,
                       dense_pack: int = 1) -> int:
    """Modeled per-round per-DEVICE collective payload of a low-rank factor
    exchange (the shared ``Engine.wire_bytes`` body for rankDAD and
    powerSGD, telemetry/metrics.py): each compressible leaf ships two
    factors ``[m, r]`` + ``[n, r]`` at ``itemsize`` bytes per element with
    the effective rank ``min(rank, m, n)``; 1-D leaves ride the dense f32
    psum path. ``pack`` is the site-packing factor K: a GATHERED factor
    exchange (rankDAD) ships every one of the device's K virtual sites'
    factors, so the factor half scales ×K, while the dense psum half reduces
    locally first and stays K-invariant (powerSGD's psum'd factors are
    likewise K-invariant — it passes ``pack=1``). ``dense_pack`` scales the
    dense 1-D half instead: the robust gather modes (r17) GATHER the dense
    leaves rather than psumming them, so their bytes genuinely scale with K
    too (the legacy psum path keeps ``dense_pack=1``). Pure shape
    arithmetic on THIS module's compressibility criterion — safe on
    tracers, and a criterion change here changes the payload model with
    it."""
    total = 0
    for g in jax.tree.leaves(grads):
        if is_compressible(g):
            m, n = _matrix_shape(g)
            total += min(rank, m, n) * (m + n) * itemsize * pack
        else:
            size = 1
            for d in g.shape:
                size *= d
            total += size * 4 * dense_pack
    return total


def lp_matmul(a, b, dtype=None):
    """``a @ b``, optionally with both operands cast to a low-precision
    ``dtype`` (bf16) while ACCUMULATING in f32 (``preferred_element_type``) —
    the MXU-native mixed-precision contraction. ``dtype=None`` is a plain f32
    matmul. Used for the LARGE power-iteration products ``G@Ω`` / ``GᵀP`` /
    ``G(GᵀP)``; the tiny ``[r, r]`` Gram/Cholesky stays f32 regardless (its
    conditioning drives the CholeskyQR shift analysis in
    :func:`_cholqr_multi`, and it is not where the FLOPs are)."""
    if dtype is None:
        return a @ b
    return jnp.matmul(
        a.astype(dtype), b.astype(dtype), preferred_element_type=jnp.float32
    )


def default_omega(G, r: int, key=None):
    """The per-shape default random init Ω ``[n, r]`` — the draw every solo
    run makes, and the value the rankDAD engine stores at ``init`` so its
    first warm-started round is bit-identical to a cold start."""
    if key is None:
        key = jax.random.PRNGKey(G.shape[0] * 1000003 + G.shape[1])
    return jax.random.normal(key, (G.shape[1], r), jnp.float32)


def _matrix_shape(g):
    m = 1
    for d in g.shape[:-1]:
        m *= d
    return m, g.shape[-1]


def to_matrix(g):
    return g.reshape(_matrix_shape(g))


def from_matrix(mat, like):
    return mat.reshape(like.shape).astype(like.dtype)


def _normalize_cols(Y):
    nc = jnp.linalg.norm(Y, axis=0)
    # exactly-zero columns take canonical basis vectors, so a zero input
    # still yields an ORTHONORMAL Q — matching Householder QR's behavior.
    # powerSGD warm-starts its q factor from the previous round's P; a
    # P=0 here would make q die permanently (q_new = MᵀP = 0 forever)
    # while its error-feedback residual grows unflushed (review, r3).
    fallback = jnp.eye(Y.shape[0], Y.shape[1], dtype=Y.dtype)
    return jnp.where(nc > 0, Y / jnp.maximum(nc, 1e-30), fallback), nc


def _small_cholesky(G):
    """Unrolled Cholesky of tiny batched SPD matrices ``[..., r, r]``.

    PURE jnp ops, no LAPACK custom-call: the TPU ``cholesky`` custom-call
    costs ~1 µs per matrix REGARDLESS of batching (measured: [32, 10, 10]
    ≈ 33 µs, [224, 10, 10] ≈ 231 µs on v5e — the work is sequential per
    matrix inside the call), and the engines issue it inside every power
    iteration. An unrolled textbook Cholesky–Banachiewicz is r static steps
    of fused vector ops, identical math.
    """
    r = G.shape[-1]
    L = jnp.zeros_like(G)
    for j in range(r):
        # j == 0 guards: zero-size contractions fail to partition under
        # shard_map's manual-computation lowering
        s = G[..., j, j] if j == 0 else (
            G[..., j, j] - jnp.sum(L[..., j, :j] * L[..., j, :j], axis=-1)
        )
        ljj = jnp.sqrt(s)
        if j + 1 < r:
            col = G[..., j + 1:, j] if j == 0 else (
                G[..., j + 1:, j] - jnp.einsum(
                    "...ik,...k->...i", L[..., j + 1:, :j], L[..., j, :j]
                )
            )
            L = L.at[..., j + 1:, j].set(col / ljj[..., None])
        L = L.at[..., j, j].set(ljj)
    return L


def _small_tril_inverse(L):
    """Inverse of tiny batched lower-triangular ``[..., r, r]`` by forward
    substitution — r static steps, no ``triangular_solve`` custom-call
    (same per-matrix-cost pathology as :func:`_small_cholesky`)."""
    r = L.shape[-1]
    eye = jnp.eye(r, dtype=L.dtype)
    X = jnp.zeros_like(L)
    for i in range(r):
        row = jnp.broadcast_to(eye[i], L.shape[:-2] + (r,))
        if i > 0:  # zero-size einsum fails under shard_map (see above)
            row = row - jnp.einsum(
                "...k,...kj->...j", L[..., i, :i], X[..., :i, :]
            )
        X = X.at[..., i, :].set(row / L[..., i, i][..., None])
    return X


def _cholqr_once_multi(Ys, shift):
    """One column-normalized shifted CholeskyQR round, LOCKSTEP over a group
    of same-r matrices (possibly different row counts).

    The group's ``[r, r]`` Gram matrices stack and factor through the
    unrolled :func:`_small_cholesky` + :func:`_small_tril_inverse` — zero
    custom-calls (profiled ~45% of rankDAD's compression overhead when the
    LAPACK calls were issued per leaf per iteration on v5e).

    ``Q = Y·L⁻ᵀ`` via the explicit inverse (numerically the same triangular
    system as solving against ``Yᵀ``, which cannot batch across differing
    row counts).
    """
    pairs = [_normalize_cols(Y) for Y in Ys]
    Yn = [p[0] for p in pairs]
    ncs = [p[1] for p in pairs]
    r = Yn[0].shape[-1]
    eye = jnp.eye(r, dtype=Yn[0].dtype)
    Gms = jnp.stack([Y.T @ Y for Y in Yn])  # [L, r, r]
    tr = jnp.trace(Gms, axis1=-2, axis2=-1)[:, None, None]
    Gms = Gms + (shift * tr + 1e-30) * eye
    if jax.default_backend() == "tpu":
        # on TPU the LAPACK custom-calls pay ~1 µs PER MATRIX regardless of
        # batching; the unrolled forms are fused vector ops (the engines
        # call this inside every power iteration). On CPU LAPACK is fine
        # and the unrolled graph only bloats compile time.
        Ls = _small_cholesky(Gms)
        Linv = _small_tril_inverse(Ls)
    else:
        Ls = jnp.linalg.cholesky(Gms)
        Linv = jax.scipy.linalg.solve_triangular(
            Ls, jnp.broadcast_to(eye, Gms.shape), lower=True
        )
    Qs = [Y @ jnp.swapaxes(Linv[i], -1, -2) for i, Y in enumerate(Yn)]
    return Qs, ncs


def _cholqr_multi(Ys):
    """Column-normalized shifted CholeskyQR2 of each ``Y [m_l, r]`` →
    ``([Q_l], [colnorm_l])``, lockstep over the group.

    TPU-first replacement for ``jnp.linalg.qr``: Householder QR lowers to a
    long sequential scalar loop on TPU, while this is two matmuls plus a
    batched ``[r, r]`` Cholesky + triangular inverse per round (r ≤ rank,
    default 10) — MXU/batch friendly, and (unlike an eigh-based Löwdin
    orthonormalization, which was tried and reverted) CONTINUOUS in Y:
    float-noise between the vmapped and unbatched lowerings stays
    proportional instead of being amplified by near-degenerate
    eigen-subspace mixing.

    Each round first normalizes columns, so the trace-relative Cholesky shift
    is a PER-COLUMN relative floor rather than a global one — a naive
    ``shift·trace`` floor is dominated by σ₁ and collapses every direction
    with σᵢ² ≲ √shift·σ₁² (review finding r3; measured rec-error 16× worse on
    a decaying spectrum). With normalization the variant matches Householder
    QR's orthogonality (~6e-7) and reconstruction error on spectra spanning
    4 decades, while staying NaN-safe for rank-deficient / all-zero Y (true
    gradient rank is routinely < r, e.g. bounded by the batch size).
    ``colnorm`` is the pre-normalization column-norm vector of the first
    round — the σ-scale convergence proxy.
    """
    Q1s, colnorms = _cholqr_once_multi(Ys, 1e-6)
    Q2s, _ = _cholqr_once_multi(Q1s, 1e-7)
    return Q2s, colnorms


def _cholqr(Y):
    """Single-matrix convenience over :func:`_cholqr_multi`."""
    Qs, colnorms = _cholqr_multi([Y])
    return Qs[0], colnorms[0]


def subspace_iteration_grouped(groups, num_iters: int, tol: float,
                               matmul_dtype=None, fused: bool = False):
    """Rank-r factorizations ``G ≈ P @ Qᵀ`` for SEVERAL same-rank groups in
    ONE shared ``lax.while_loop``.

    ``groups`` is a list of ``(Gs, rank, omegas)`` triples: each group's
    members share ``r = min(rank, m_l, n_l)``; ``omegas`` is a per-member
    list of warm-start subspaces ``[n_l, r]`` (``None`` entries draw the
    :func:`default_omega` for that member, i.e. a cold start; ``omegas=None``
    cold-starts the whole group). Returns one ``[(P_l, Q_l), ...]`` list per
    group, order preserved.

    Why one loop: rankDAD's leaves fall into a handful of effective-rank
    classes (the flagship ICA-LSTM has r=10 for every big kernel plus r=2 for
    the [64, 2] head), and one ``lax.while_loop`` per class SERIALIZES the
    classes — XLA runs whiles one after another, so the tiny r=2 class adds
    its full trip latency to the r=10 class's. Here every class shares a
    single loop (audit, r6): per-class work is emitted side by side in one
    body, the trip count is the max over all members, and per-member trip
    semantics are kept by the same active-mask freezing as before. Within a
    class the ``[r, r]`` Gram matrices still stack and factor through the
    unrolled batched Cholesky (:func:`_cholqr_once_multi`).

    ``matmul_dtype=jnp.bfloat16`` runs the LARGE products (``G@Ω``, ``GᵀP``,
    ``G(GᵀP)``, the final ``Q``) as bf16×bf16→f32 MXU contractions
    (:func:`lp_matmul`); orthonormalization and the σ-convergence test stay
    f32. Warm starts make this safe in practice: bf16 noise perturbs the
    iterate, but the subspace is re-refined every round from the previous
    round's Ω.

    σ estimates come from the orthonormalization's column norms for free —
    ``‖(G Gᵀ P)ᵢ‖`` estimates σᵢ², so ``sqrt`` puts the convergence test on
    the same σ scale the reference's ``dad_tol`` means. A member stops
    updating once its own relative σ-estimate change drops below ``tol``.
    """
    mm = lp_matmul
    if not groups:
        # a fully non-compressible gradient tree (all 1-D/vector leaves):
        # nothing to factorize — the engines' dense fallback carries the
        # whole exchange. The while_loop below cannot carry an empty tuple.
        return []
    if fused:
        # fused Pallas power iteration (ops/poweriter_pallas.py, r14): one
        # VMEM-resident pallas_call per rank class — same math, same
        # per-member trip semantics, no HBM round trips between
        # refinements. Classes whose padded working set would blow the VMEM
        # budget fall back to the legacy XLA loop below (a trace-time
        # static split; on the flagship shapes every class fits).
        from ..ops import poweriter_pallas as pp

        fusable = [
            i for i, (Gs, rank, _) in enumerate(groups)
            if pp.class_fits_vmem(Gs, rank, matmul_dtype)
        ]
        if fusable:
            results: list = [None] * len(groups)
            fused_out = pp.fused_subspace_iteration_grouped(
                [groups[i] for i in fusable], num_iters, tol,
                matmul_dtype=matmul_dtype,
            )
            for i, res in zip(fusable, fused_out):
                results[i] = res
            rest = [i for i in range(len(groups)) if i not in set(fusable)]
            if rest:
                legacy = subspace_iteration_grouped(
                    [groups[i] for i in rest], num_iters, tol,
                    matmul_dtype=matmul_dtype, fused=False,
                )
                for i, res in zip(rest, legacy):
                    results[i] = res
            return results
    prepped = []  # (Gs_f32, omegas_f32) per group, ranks clamped
    for Gs, rank, omegas in groups:
        Gs = [G.astype(jnp.float32) for G in Gs]
        r = min([rank] + [min(G.shape) for G in Gs])
        if omegas is None:
            omegas = [None] * len(Gs)
        elif len(omegas) != len(Gs):
            raise ValueError(
                f"omegas has {len(omegas)} entries for {len(Gs)} matrices"
            )
        oms = [
            default_omega(G, r) if om is None else om.astype(jnp.float32)
            for G, om in zip(Gs, omegas)
        ]
        prepped.append((Gs, oms))

    init_Ps, init_sigs, init_deltas = [], [], []
    for Gs, oms in prepped:
        Ps, _ = _cholqr_multi([mm(G, om, matmul_dtype) for G, om in zip(Gs, oms)])
        sigs = jnp.stack(
            [jnp.linalg.norm(mm(G.T, P, matmul_dtype), axis=0)
             for G, P in zip(Gs, Ps)]
        )  # [L, r] σ estimates, column order
        # Tie the initial deltas to the Gs so their device-varying annotation
        # matches the loop body's output under shard_map (per-site G ⇒
        # per-site delta).
        deltas0 = jnp.full((len(Gs),), jnp.inf, jnp.float32) + 0.0 * sigs.sum(-1)
        init_Ps.append(tuple(Ps))
        init_sigs.append(sigs)
        init_deltas.append(deltas0)

    def cond(carry):
        i, _, _, deltas = carry
        worst = jnp.max(jnp.stack([jnp.max(d) for d in deltas]))
        return jnp.logical_and(i < num_iters, worst > tol)

    def body(carry):
        i, Ps_all, sigs_all, deltas_all = carry
        out_Ps, out_sigs, out_deltas = [], [], []
        for (Gs, _), Ps, sigs, deltas in zip(
            prepped, Ps_all, sigs_all, deltas_all
        ):
            P_cand, colnorms = _cholqr_multi(
                [mm(G, mm(G.T, P, matmul_dtype), matmul_dtype)
                 for G, P in zip(Gs, Ps)]
            )
            sig_new = jnp.sqrt(jnp.stack(colnorms))  # ‖G Gᵀ p‖ ≈ σ² → σ scale
            delta_new = jnp.linalg.norm(sig_new - sigs, axis=-1) / jnp.maximum(
                jnp.linalg.norm(sigs, axis=-1), 1e-12
            )
            active = deltas > tol  # members still iterating (solo trip counts)
            out_Ps.append(tuple(
                jnp.where(active[l], P_cand[l], Ps[l]) for l in range(len(Gs))
            ))
            out_sigs.append(jnp.where(active[:, None], sig_new, sigs))
            out_deltas.append(jnp.where(active, delta_new, deltas))
        return i + 1, tuple(out_Ps), tuple(out_sigs), tuple(out_deltas)

    _, Ps_all, _, _ = jax.lax.while_loop(
        cond, body,
        (jnp.zeros((), jnp.int32), tuple(init_Ps), tuple(init_sigs),
         tuple(init_deltas)),
    )
    return [
        [(P, mm(G.T, P, matmul_dtype)) for G, P in zip(Gs, Ps)]
        for (Gs, _), Ps in zip(prepped, Ps_all)
    ]


def subspace_iteration_multi(Gs, rank: int, num_iters: int, tol: float,
                             keys=None, omegas=None, matmul_dtype=None):
    """Rank-r factorizations ``G_l ≈ P_l @ Q_lᵀ`` by LOCKSTEP subspace (block
    power) iteration over ONE group of matrices sharing
    ``r = min(rank, m_l, n_l)`` — a group of one over
    :func:`subspace_iteration_grouped`.

    Each P_l is [m_l, r] orthonormal, Q_l = G_lᵀ P_l is [n_l, r].
    ``keys[l]`` overrides the PRNG key for member l's default Ω draw;
    ``omegas[l]`` supplies the subspace itself (warm start) and wins over
    ``keys[l]``. ``None`` entries keep the per-shape default — identical to
    what each solo run drew.
    """
    L = len(Gs)
    if keys is None:
        keys = [None] * L
    elif len(keys) != L:
        raise ValueError(f"keys has {len(keys)} entries for {L} matrices")
    r = min([rank] + [min(G.shape) for G in Gs])
    if omegas is None:
        omegas = [None] * L
    elif len(omegas) != L:
        raise ValueError(f"omegas has {len(omegas)} entries for {L} matrices")
    oms = [
        om if om is not None else default_omega(jnp.asarray(G), r, k)
        for G, om, k in zip(Gs, omegas, keys)
    ]
    return subspace_iteration_grouped(
        [(Gs, rank, oms)], num_iters, tol, matmul_dtype=matmul_dtype
    )[0]


def subspace_iteration(G, rank: int, num_iters: int, tol: float, key=None):
    """Single-matrix rank-r factorization ``G ≈ P @ Qᵀ`` — a group of one
    over :func:`subspace_iteration_multi`. An explicit ``key`` seeds the
    random init Ω; ``None`` draws the per-shape default key (what the
    engines use, so lockstep groups match solo runs)."""
    return subspace_iteration_multi(
        [G], rank, num_iters, tol, keys=None if key is None else [key]
    )[0]


def orthonormalize(P):
    """Orthonormalize columns (shifted CholeskyQR2 — see :func:`_cholqr`)."""
    Q, _ = _cholqr(P)
    return Q
