"""Pretrain k-fold study (VERDICT r2 #5): reproduce the reference's
NB.ipynb cells 6-17 convergence comparison in-repo, reading back our own
logs.json artifacts."""

import os

import pytest

from dinunet_implementations_tpu.analysis import pretrain_study

FSL = "/root/reference/datasets/test_fsl"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(FSL), reason="reference fixture not mounted"
)


@pytest.mark.golden
def test_pretrain_study_shows_faster_convergence(tmp_path):
    """The reference's claim (mean stop epoch 68.5 scratch vs 42.7
    pretrained): the pretrained arm must converge at least as fast, at
    comparable accuracy. 3 folds of the 5-site fixture, seed 0 —
    deterministic on the CPU simulator (measured 37.7 vs 35.0 epochs)."""
    report = pretrain_study(
        FSL, str(tmp_path), num_folds=5, pretrain_epochs=20, folds=[0, 1, 2]
    )
    s = report["arms"]["scratch"]
    p = report["arms"]["pretrained"]
    assert p["mean_best_val_epoch"] <= s["mean_best_val_epoch"], (
        f"pretrained arm converged SLOWER: {p['mean_best_val_epoch']:.1f} vs "
        f"{s['mean_best_val_epoch']:.1f} epochs"
    )
    assert p["mean_test_auc"] >= s["mean_test_auc"] - 0.05, (
        "pretraining degraded accuracy beyond tolerance"
    )
    # report artifacts exist and carry the table
    md = open(os.path.join(tmp_path, "pretrain_study.md")).read()
    assert "| scratch |" in md and "| pretrained |" in md
    csv_text = open(os.path.join(tmp_path, "pretrain_study.csv")).read()
    assert csv_text.count("\n") >= 7  # header + 2 arms x 3 folds


@pytest.mark.golden
def test_engine_comparison_table(tmp_path):
    """nnlogs.ipynb cell-2 equivalent: per-engine [loss, AUC] + wall-clock
    parsed back from our logs.json (fast config: 2 engines, few epochs)."""
    from dinunet_implementations_tpu.analysis import engine_comparison
    from dinunet_implementations_tpu.core.config import TrainConfig

    cfg = TrainConfig(task_id="FS-Classification", epochs=4,
                      validation_epochs=2, patience=10, seed=0)
    report = engine_comparison(
        FSL, str(tmp_path), engines=("dSGD", "rankDAD"), base_cfg=cfg
    )
    assert set(report["engines"]) == {"dSGD", "rankDAD"}
    for row in report["engines"].values():
        loss, auc = row["test_metrics"]
        assert 0.0 <= auc <= 1.0 and loss > 0
        assert row["computation_time"] > 0
        assert row["total_duration"] >= row["computation_time"] * 0.5
    md = open(os.path.join(tmp_path, "engine_comparison.md")).read()
    assert "| dSGD |" in md and "| rankDAD |" in md
