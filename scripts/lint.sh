#!/usr/bin/env bash
# Lint gate: ruff (hard-error style/correctness families, [tool.ruff] in
# pyproject.toml) + jaxlint (the codebase-specific SPMD-invariant analyzer,
# dinunet_implementations_tpu/checks — rules R001-R006, empty baseline).
# Run from anywhere; CI (.github/workflows/ci.yml) runs exactly this script.
set -uo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
rc=0

if command -v ruff >/dev/null 2>&1; then
  echo "== ruff =="
  ruff check . || rc=1
else
  # the container image may not ship ruff; jaxlint below is stdlib-only and
  # always runs, so the SPMD-invariant gate never silently disappears
  echo "[lint] ruff not installed (pip install -e '.[dev]'); skipping style lint" >&2
fi

echo "== jaxlint =="
JAX_PLATFORMS=cpu python -m dinunet_implementations_tpu.checks || rc=1

exit $rc
