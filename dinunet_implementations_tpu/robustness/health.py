"""Per-site health state: the quarantine bookkeeping carried through the
jitted epoch scan.

Three int32 counters per site, stored in ``TrainState.health`` with a leading
``[num_sites]`` axis and sharded over the site mesh axis exactly like engine
state (trainer/steps.py ``_state_specs``). ``num_sites`` counts VIRTUAL
sites: under site packing (r12) each device carries the ``[K]`` block of its
packed sites' counters and the per-round gates are ``[K]`` vector ops — a
quarantine decision lands on the virtual row that blew up, never on the
whole device:

- ``streak`` — consecutive rounds with a non-finite site gradient; resets to
  0 the round the gradient comes back finite;
- ``skips`` — total rounds this site contributed nothing (scheduled drop,
  non-finite gradient, or quarantine);
- ``quarantined`` — sticky 0/1 flag, set once ``streak`` reaches the
  configured threshold (``TrainConfig.quarantine_rounds``). A quarantined
  site is zero-weighted for the rest of the fit; params keep advancing on the
  live sites' aggregate.

Reputation layer (r17 — hostile sites; present only when a robust
aggregation mode is active, ``TrainConfig.robust_agg != "none"``, so the
legacy program stays lowering-identical otherwise):

- ``suspect_streak`` — consecutive rounds this site's anomaly z-score (the
  max of its distance-to-robust-aggregate z and gradient-norm z across the
  live cohort, computed on-device in the rounds scan — trainer/steps.py)
  exceeded ``TrainConfig.reputation_z``; resets the round it drops back;
- ``anomaly`` — exponential moving average of the positive part of that
  z-score (decay 0.9 per live round; held across rounds the site sat out) —
  the per-site reputation score surfaced in ``logs.json``, the telemetry
  sink and the live ``/statusz`` bus.

``suspect_streak`` feeds the SAME sticky-quarantine machinery as the
non-finite streak: once it reaches ``TrainConfig.reputation_rounds`` the
``quarantined`` flag latches and the site is zero-weighted for the rest of
the fit — a persistent byzantine site is excluded exactly like a NaN site.

The counters ride the checkpoint payload, so a resumed run keeps its
quarantine decisions; a rejoining site's slot is zeroed wholesale
(robustness/membership.py ``reset_slot_state`` tree-maps over every health
leaf, the reputation fields included), so a new generation starts with a
clean reputation.
"""

from __future__ import annotations

import numpy as np

#: health keys added by the reputation layer (robust_agg != "none")
REPUTATION_KEYS = ("suspect_streak", "anomaly")


def default_health(num_sites: int, reputation: bool = False) -> dict:
    """Fresh all-healthy counters with the per-site leading axis.
    ``reputation=True`` adds the anomaly-scoring fields (robust-aggregation
    runs only — the extra carried arrays must not exist in the legacy
    program)."""
    # jax deferred to the call (trainer paths): robustness/__init__ is
    # imported by the otherwise jax-free data layer (native_io's retry), and
    # an eager jax import here would lock in backend config before scripts
    # like tests/dcn_worker.py get to set platform/device-count knobs
    import jax.numpy as jnp

    # DISTINCT arrays, not one shared buffer: the epoch program donates
    # the carried state (trainer/steps.py donate_state), and XLA rejects the
    # same buffer appearing twice in a donated argument list
    out = {
        "streak": jnp.zeros((num_sites,), jnp.int32),
        "skips": jnp.zeros((num_sites,), jnp.int32),
        "quarantined": jnp.zeros((num_sites,), jnp.int32),
    }
    if reputation:
        out.update(reputation_fields(num_sites))
    return out


def reputation_fields(num_sites: int) -> dict:
    """Fresh zero reputation-layer health fields (:data:`REPUTATION_KEYS`) —
    the ONE place their names/dtypes are defined; default_health and the
    trainer's jit-boundary structure normalization
    (trainer/steps.py ``_ensure_health``) both build from here."""
    import jax.numpy as jnp

    return {
        "suspect_streak": jnp.zeros((num_sites,), jnp.int32),
        "anomaly": jnp.zeros((num_sites,), jnp.float32),
    }


def health_summary(health) -> dict | None:
    """Host-side summary for results dicts / ``logs.json``: plain int lists,
    with the log-facing key names."""
    if health is None:
        return None
    out = {
        "site_skipped_rounds": [int(v) for v in np.asarray(health["skips"])],
        "site_quarantined": [int(v) for v in np.asarray(health["quarantined"])],
        "site_nonfinite_streak": [int(v) for v in np.asarray(health["streak"])],
    }
    if all(k in health for k in REPUTATION_KEYS):  # reputation layer (r17)
        out["site_anomaly_score"] = [
            float(v) for v in np.asarray(health["anomaly"])
        ]
        out["site_suspect_streak"] = [
            int(v) for v in np.asarray(health["suspect_streak"])
        ]
    return out
