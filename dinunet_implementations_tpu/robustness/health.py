"""Per-site health state: the quarantine bookkeeping carried through the
jitted epoch scan.

Three int32 counters per site, stored in ``TrainState.health`` with a leading
``[num_sites]`` axis and sharded over the site mesh axis exactly like engine
state (trainer/steps.py ``_state_specs``). ``num_sites`` counts VIRTUAL
sites: under site packing (r12) each device carries the ``[K]`` block of its
packed sites' counters and the per-round gates are ``[K]`` vector ops — a
quarantine decision lands on the virtual row that blew up, never on the
whole device:

- ``streak`` — consecutive rounds with a non-finite site gradient; resets to
  0 the round the gradient comes back finite;
- ``skips`` — total rounds this site contributed nothing (scheduled drop,
  non-finite gradient, or quarantine);
- ``quarantined`` — sticky 0/1 flag, set once ``streak`` reaches the
  configured threshold (``TrainConfig.quarantine_rounds``). A quarantined
  site is zero-weighted for the rest of the fit; params keep advancing on the
  live sites' aggregate.

The counters ride the checkpoint payload, so a resumed run keeps its
quarantine decisions.
"""

from __future__ import annotations

import numpy as np


def default_health(num_sites: int) -> dict:
    """Fresh all-healthy counters with the per-site leading axis."""
    # jax deferred to the call (trainer paths): robustness/__init__ is
    # imported by the otherwise jax-free data layer (native_io's retry), and
    # an eager jax import here would lock in backend config before scripts
    # like tests/dcn_worker.py get to set platform/device-count knobs
    import jax.numpy as jnp

    # three DISTINCT arrays, not one shared buffer: the epoch program donates
    # the carried state (trainer/steps.py donate_state), and XLA rejects the
    # same buffer appearing twice in a donated argument list
    return {
        "streak": jnp.zeros((num_sites,), jnp.int32),
        "skips": jnp.zeros((num_sites,), jnp.int32),
        "quarantined": jnp.zeros((num_sites,), jnp.int32),
    }


def health_summary(health) -> dict | None:
    """Host-side summary for results dicts / ``logs.json``: plain int lists,
    with the log-facing key names."""
    if health is None:
        return None
    return {
        "site_skipped_rounds": [int(v) for v in np.asarray(health["skips"])],
        "site_quarantined": [int(v) for v in np.asarray(health["quarantined"])],
        "site_nonfinite_streak": [int(v) for v in np.asarray(health["streak"])],
    }
