from .mesh import (
    MODEL_AXIS,
    SITE_AXIS,
    host_mesh,
    make_site_mesh,
    pack_factor,
    packed_site_mesh,
    replicated,
    site_sharding,
)
from .distributed import distributed_init, distributed_shutdown, multihost_site_mesh
from .collectives import (
    PackedAxis,
    payload_cast,
    payload_dtype,
    payload_uncast,
    site_weight_scale,
    site_all_gather,
    site_count,
    site_index,
    site_mean,
    site_sum,
    site_weighted_mean,
    two_level_psum,
    weighted_site_sum,
)
