"""jaxprlint (checks/semantic.py + checks/lowering.py) — the traced-program
tier.

Four layers:
- negative fixtures that each S-rule must catch: a mis-axed collective and
  an outside-scan collective (S001), an inconsistent / undercounting /
  overcounting wire model (S002), a donated-but-unaliased buffer (S003), an
  f32 upcast on a 16-bit wire path (S004), and a divergent off-program
  (S005);
- baseline round-trip per rule (semantic findings are baseline-suppressed;
  there is no source line for inline markers);
- the wire_bytes cross-check over all four engine corners (dSGD / rankDAD /
  powerSGD / the low-rank engines' non-compressible fallback);
- the acceptance gate: the FULL engine × topology × pipeline matrix traces
  clean against the checked-in (empty) semantic baseline.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.checks import semantic as sem
from dinunet_implementations_tpu.checks.core import (
    apply_baseline,
    load_baseline,
    save_baseline,
)
from dinunet_implementations_tpu.checks.lowering import (
    diff_report,
    normalize_lowering,
)
from dinunet_implementations_tpu.checks.rules import COLLECTIVE_AXIS_ARG
from dinunet_implementations_tpu.core.jaxcompat import shard_map
from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.engines.base import mask_dead_site
from dinunet_implementations_tpu.parallel.collectives import (
    site_weighted_mean,
)
from dinunet_implementations_tpu.telemetry.metrics import (
    modeled_wire_shapes,
    payload_bytes_of,
)


def _rules(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# tier agreement
# ---------------------------------------------------------------------------


def test_ast_and_semantic_collective_tables_agree():
    """Every collective the AST tier (R003) knows maps onto a traced
    primitive the semantic walker audits — the two tiers cannot disagree on
    what counts as a collective."""
    for api_name in COLLECTIVE_AXIS_ARG:
        prim = sem.prim_for(api_name)
        assert prim in sem.COMM_PRIMS | sem.QUERY_PRIMS, (
            f"R003 collective {api_name!r} traces to {prim!r}, which the "
            f"semantic tier does not audit"
        )


# ---------------------------------------------------------------------------
# S001 — collective/mesh audit
# ---------------------------------------------------------------------------


def _rogue_axis_program(in_scan: bool):
    """A shard_map program over a TYPO'D mesh axis name ('sites') — traces
    fine, reduces over something that is not a declared mesh constant."""
    from jax.sharding import Mesh, PartitionSpec as P

    cpus = [d for d in jax.devices() if d.platform == "cpu"]
    mesh = Mesh(np.array(cpus[:2]), ("sites",))

    def inner(x):
        if in_scan:
            def body(c, xs):
                return c + jax.lax.psum(xs, "sites").sum(), ()

            out, _ = jax.lax.scan(body, 0.0, x)
            return out
        return jax.lax.psum(x, "sites").sum()

    f = jax.jit(lambda x: shard_map(
        inner, mesh=mesh, in_specs=P("sites"), out_specs=P(),
        check_vma=False,
    )(x))
    return jax.make_jaxpr(f)(jnp.ones((2, 3)))


def test_s001_rogue_axis_and_outside_scan_flagged():
    audit = sem.audit_jaxpr(_rogue_axis_program(in_scan=False))
    fs = sem.check_collective_axes(audit.collectives, "trace://fixture")
    assert _rules(fs) == ["S001", "S001"]
    msgs = " | ".join(f.message for f in fs)
    assert "'sites'" in msgs and "outside" in msgs.lower()


def test_s001_declared_axis_inside_scan_is_clean():
    audit = sem.audit_jaxpr(_rogue_axis_program(in_scan=True))
    # same program with the axis declared: only the name check applies
    fs = sem.check_collective_axes(
        audit.collectives, "trace://fixture", allowed_axes={"sites"}
    )
    assert fs == []


# ---------------------------------------------------------------------------
# S002 — wire-byte proof
# ---------------------------------------------------------------------------

_MESH_HOST = dict(topology="mesh", pipeline="host")

#: the four engine corners of the acceptance criterion, derived from the
#: semantic tier's own matrix table so this cross-check and the CLI gate
#: can never verify different corners
ENGINE_CORNERS = [
    (name + ("-fallback" if dense else ""), kw, dense)
    for name, kw, dense in sem._ENGINE_CORNERS
]
assert len(ENGINE_CORNERS) == 4 and ENGINE_CORNERS[-1][2]  # incl. fallback


def _trace(engine_name, kw=(), dense=False, precision="32", engine=None,
           **cell_kw):
    cell = sem.TraceCell(
        engine_name.split("-")[0], precision_bits=precision, engine_kw=kw,
        dense_model=dense, **{**_MESH_HOST, **cell_kw},
    )
    return sem.trace_cell(cell, engine=engine)


@pytest.mark.parametrize("name,kw,dense", ENGINE_CORNERS,
                         ids=[c[0] for c in ENGINE_CORNERS])
def test_s002_wire_bytes_verified_for_every_engine(name, kw, dense):
    """The acceptance cross-check: for all four engine corners, the traced
    per-round per-site collective payload equals the engine's wire_bytes
    model exactly, and the structured wire_shapes hook sums to the same."""
    prog = _trace(name, kw, dense)
    shapes = modeled_wire_shapes(prog.engine, prog.state.params)
    total = sum(int(np.prod(s)) * d.itemsize for s, d in shapes)
    assert total == int(payload_bytes_of(prog.engine, prog.state.params))
    fs = sem.check_wire_bytes(
        prog.audit.collectives, prog.engine, prog.state.params, prog.block,
        prog.path,
    )
    assert fs == [], "\n".join(f.format() for f in fs)


def test_s002_pack_unaware_model_flagged_on_packed_cell():
    """The r12 wire-accounting proof: on a packed cell (4 virtual sites per
    device) a wire model that keeps PER-SITE accounting — ignoring that the
    factor gather ships every virtual site's block while psums reduce
    locally first — must be flagged; the real pack-aware engine is clean on
    the same traced program."""
    kw = (("dad_num_pow_iters", 2), ("dad_reduction_rank", 2))
    prog = _trace("rankDAD", kw, topology="fold4")
    assert prog.block == 4
    # the real engine's model matches the traced packed program exactly
    assert sem.check_wire_bytes(
        prog.audit.collectives, prog.engine, prog.state.params, prog.block,
        prog.path,
    ) == []
    # a per-site (pack-unaware) model on the same program: the traced
    # [4, Σ(m+n), r] gather block is unmodeled, its own [1, ...] entry never
    # ships — both coverage directions trip
    base = prog.engine
    naive = dataclasses.replace(
        base,
        wire_shapes=lambda g: base.wire_shapes(g, pack=1),
        wire_bytes=lambda g: base.wire_bytes(g, pack=1),
    )
    fs = sem.check_wire_bytes(
        prog.audit.collectives, naive, prog.state.params, prog.block,
        prog.path,
    )
    snippets = {f.snippet for f in fs}
    assert any(s.startswith("missing") for s in snippets), snippets
    assert any(s.startswith("unmodeled") for s in snippets), snippets


def test_s002_robust_model_on_plain_psum_program_flagged():
    """The r17 robust-wire negative fixture (mirror of the pack-unaware one
    above): an engine that DECLARES the robust gather-mode wire model while
    its traced program still ships the plain weighted psum must trip S002 in
    both directions — the modeled [pack, ...] per-site gather blocks never
    ship (overcounting), and the psum'd dense operands are covered by
    nothing (undercounting). The real trimmed-mean engine is clean on its
    own traced program (the acceptance matrix covers that cell)."""
    prog = _trace("dSGD")  # the legacy psum program
    robust = make_engine("dSGD", robust_agg="trimmed_mean")
    lying = dataclasses.replace(
        prog.engine,
        wire_shapes=robust.wire_shapes,
        wire_bytes=robust.wire_bytes,
    )
    fs = sem.check_wire_bytes(
        prog.audit.collectives, lying, prog.state.params, prog.block,
        prog.path,
    )
    snippets = {f.snippet for f in fs}
    assert any(s.startswith("missing") for s in snippets), snippets
    assert any(s.startswith("unmodeled") for s in snippets), snippets


def test_s002_robust_cells_wire_models_consistent():
    """wire_shapes must sum to wire_bytes for every engine × robust mode at
    pack factors 1 and 4 — the structural half of the robust-mode S002 proof
    (the traced half runs in the acceptance matrix)."""
    params = {
        "dense": jnp.zeros((8, 4), jnp.float32),
        "bias": jnp.zeros((4,), jnp.float32),
    }
    for name in ("dSGD", "rankDAD", "powerSGD"):
        for mode in ("norm_clip", "trimmed_mean", "coordinate_median"):
            eng = make_engine(name, robust_agg=mode, dad_reduction_rank=2)
            for pack in (1, 4):
                shapes = modeled_wire_shapes(eng, params, pack=pack)
                total = sum(
                    int(np.prod(s)) * d.itemsize for s, d in shapes
                )
                assert total == int(
                    payload_bytes_of(eng, params, pack=pack)
                ), (name, mode, pack)


def test_s002_inconsistent_model_flagged():
    bad = dataclasses.replace(
        make_engine("dSGD"), wire_bytes=lambda g: 1, wire_shapes=None
    )
    prog = _trace("dSGD", engine=bad)
    fs = sem.check_wire_bytes(
        prog.audit.collectives, bad, prog.state.params, prog.block, prog.path
    )
    assert "S002" in _rules(fs)
    assert any(f.snippet == "model-inconsistent" for f in fs)


def test_s002_unmodeled_collective_flagged():
    """An aggregate that ships something the wire model doesn't count —
    the undercounting direction."""
    from dinunet_implementations_tpu.parallel.collectives import site_sum

    base = make_engine("dSGD")

    def agg(grads, state, weight, axis_name, live=None):
        out, st = base.aggregate(grads, state, weight, axis_name, live=live)
        # a stray unmodeled payload; site_sum resolves the packed/classic
        # axis form like a real engine would (leading [K] axis when packed)
        site_sum(jnp.zeros((1, 7, 7), jnp.float32), axis_name)
        return out, st

    bad = dataclasses.replace(base, aggregate=agg)
    prog = _trace("dSGD", engine=bad)
    fs = sem.check_wire_bytes(
        prog.audit.collectives, bad, prog.state.params, prog.block, prog.path
    )
    assert any(
        f.rule == "S002" and f.snippet == "unmodeled psum (7, 7)" for f in fs
    ), "\n".join(f.format() for f in fs)


def test_s002_overcounting_model_flagged():
    """A wire model claiming payload that never ships."""
    base = make_engine("dSGD")
    phantom = ((9, 9), np.dtype(np.float32))
    bad = dataclasses.replace(
        base,
        wire_shapes=lambda g: base.wire_shapes(g) + [phantom],
        wire_bytes=lambda g: base.wire_bytes(g) + 9 * 9 * 4,
    )
    prog = _trace("dSGD", engine=bad)
    fs = sem.check_wire_bytes(
        prog.audit.collectives, bad, prog.state.params, prog.block, prog.path
    )
    assert any(
        f.rule == "S002" and f.snippet == "missing (9, 9)" for f in fs
    ), "\n".join(f.format() for f in fs)


# ---------------------------------------------------------------------------
# S003 — donation proof
# ---------------------------------------------------------------------------


def test_s003_aliased_donation_is_clean():
    f = jax.jit(
        lambda s, x: ({"a": s["a"] + 1.0, "b": s["b"] * 2.0}, x.sum()),
        donate_argnums=(0,),
    )
    s = {"a": jnp.ones((4, 4)), "b": jnp.ones((8,))}
    x = jnp.ones((3,))
    comp = f.lower(s, x).compile()
    assert sem.check_donation(comp, (s, x), (0,), "trace://donate") == []


def test_s003_unaliased_donation_flagged():
    """A donated buffer with no same-shape output cannot alias — the silent
    double-residency bug S003 exists to catch."""
    f = jax.jit(lambda s, x: s["a"].sum() + x.sum(), donate_argnums=(0,))
    s = {"a": jnp.ones((16,)), "b": jnp.ones((4, 4))}
    x = jnp.ones((3,))
    comp = f.lower(s, x).compile()
    fs = sem.check_donation(comp, (s, x), (0,), "trace://donate")
    assert _rules(fs) == ["S003", "S003"]  # neither 'a' nor 'b' can alias
    assert any("['b']" in f.snippet for f in fs)
    # the non-donated arg is never flagged
    assert not any("arg1" in f.snippet for f in fs)


def test_s003_real_donated_epoch_aliases_every_state_leaf():
    """The trainer's real default (device pipeline + donated state): every
    TrainState leaf must appear in the compiled executable's aliasing."""
    prog = _trace("dSGD", topology="vmap", pipeline="device", donate=True)
    fs = sem.check_donation(prog.compiled, prog.args, (0,), prog.path)
    assert fs == [], "\n".join(f.format() for f in fs)


# ---------------------------------------------------------------------------
# S004 — precision flow
# ---------------------------------------------------------------------------


def test_s004_f32_wire_upcast_flagged():
    """A 16-bit-wire engine that skips the payload cast: every payload
    collective rides f32 — the compression silently not happening."""
    e16 = make_engine("dSGD", precision_bits="16")

    def agg(grads, state, weight, axis_name, live=None):
        grads, weight = mask_dead_site(grads, weight, live)
        return site_weighted_mean(grads, weight, axis_name), state

    cheat = dataclasses.replace(e16, aggregate=agg)
    prog = _trace("dSGD", precision="16", engine=cheat)
    fs = sem.check_precision_flow(
        prog.audit.collectives, cheat, prog.state.params, prog.block,
        prog.path,
    )
    assert fs and set(_rules(fs)) == {"S004"}
    assert all(f.snippet.startswith("upcast") for f in fs)
    # ...and the byte proof independently disagrees with the model
    fs2 = sem.check_wire_bytes(
        prog.audit.collectives, cheat, prog.state.params, prog.block,
        prog.path,
    )
    assert any(f.snippet == "bytes-mismatch" for f in fs2)


def test_s004_missing_lowp_dot_flagged():
    prog = _trace(
        "rankDAD", (("dad_num_pow_iters", 2), ("dad_reduction_rank", 2)),
        precision="16",
    )
    # the real engine IS clean...
    assert sem.check_precision_flow(
        prog.audit.collectives, prog.engine, prog.state.params, prog.block,
        prog.path, require_lowp_dot=True, dots=prog.audit.dots,
    ) == []
    # ...and the same program with its low-precision dots "lost" is caught
    fs = sem.check_precision_flow(
        prog.audit.collectives, prog.engine, prog.state.params, prog.block,
        prog.path, require_lowp_dot=True,
        dots=[(4, 4, 1)],
    )
    assert [f.snippet for f in fs] == ["no-lowp-dot"]


def _psum_wire_itemsize(fn, *xs):
    """Wire itemsize of the first traced psum operand in ``fn``."""
    audit = sem.audit_jaxpr(jax.make_jaxpr(fn)(*xs))
    site = next(s for s in audit.collectives if s.prim == "psum")
    return site.wire_itemsizes[0]


def _one_site_shard(f):
    from jax.sharding import Mesh, PartitionSpec as P

    cpus = [d for d in jax.devices() if d.platform == "cpu"]
    mesh = Mesh(np.array(cpus[:1]), ("sites",))
    return shard_map(f, mesh=mesh, in_specs=P(), out_specs=P())


def test_s004_walk_not_fooled_by_bf16_touched_mask():
    """An f32 payload multiplied by a same-shape mask that passed through
    bf16 is NOT a 16-bit wire: only the payload's own dataflow may narrow
    the reading. A regression here silently re-greens the S002/S004 proofs
    on an engine that dropped its payload cast but still multiplies by a
    narrow-float mask."""

    def tainted(g):
        mask = jnp.ones_like(g).astype(jnp.bfloat16).astype(jnp.float32)
        return jax.lax.psum(g * mask, "sites")

    assert _psum_wire_itemsize(_one_site_shard(tainted), jnp.ones((8,))) == 4


def test_s004_walk_sees_through_wire_compress_round_trip():
    """The inverse direction: wire_compress's bf16→f32 round trip scaled by
    an f32 scalar still reads as a 2-byte wire — the shared scale does not
    de-quantize the payload."""

    def bf16_wire(g, w):
        p = g.astype(jnp.bfloat16).astype(jnp.float32)
        return jax.lax.psum(p * w, "sites")

    assert _psum_wire_itemsize(
        _one_site_shard(bf16_wire), jnp.ones((8,)), jnp.float32(0.5)
    ) == 2


def test_s004_int8_declared_but_f32_shipped_flagged():
    """The r14 negative fixture: an engine whose wire model DECLARES an int8
    wire but whose aggregate ships raw (unquantized) f32 payloads — S004
    must flag the upcast on every payload and S002's byte totals must
    disagree (the 4x shrink is claimed, not happening)."""
    e8 = make_engine("dSGD", wire_quant="int8")

    def agg(grads, state, weight, axis_name, live=None):
        grads, weight = mask_dead_site(grads, weight, live)
        return site_weighted_mean(grads, weight, axis_name), state

    cheat = dataclasses.replace(e8, aggregate=agg)
    prog = _trace("dSGD", engine=cheat)
    fs = sem.check_precision_flow(
        prog.audit.collectives, cheat, prog.state.params, prog.block,
        prog.path,
    )
    assert fs and set(_rules(fs)) == {"S004"}
    assert all(f.snippet.startswith("upcast") for f in fs)
    assert any("int8" in f.message for f in fs)
    fs2 = sem.check_wire_bytes(
        prog.audit.collectives, cheat, prog.state.params, prog.block,
        prog.path,
    )
    assert any(f.snippet == "bytes-mismatch" for f in fs2)


def test_s004_walk_resolves_int8_quant_chain():
    """The quant→collective→dequant chain (round/clamp → int8 cast →
    dequant mul) reads as a 1-byte wire — the r14 codec's round-trip is
    proven, not re-greened via a dropped cast."""
    from dinunet_implementations_tpu.parallel.collectives import (
        resolve_wire_codec,
    )

    codec = resolve_wire_codec("32", "int8")
    sr = resolve_wire_codec("32", "int8", stochastic=True)

    def int8_wire(g):
        return jax.lax.psum(codec.compress(g), "sites")

    def int8_sr_wire(g):
        return jax.lax.psum(sr.compress(g), "sites")

    x = jnp.linspace(-1.0, 1.0, 8)
    assert _psum_wire_itemsize(_one_site_shard(int8_wire), x) == 1
    assert _psum_wire_itemsize(_one_site_shard(int8_sr_wire), x) == 1


def test_s004_walk_packed_row_scale_does_not_widen():
    """The packed per-row [K, 1, 1] quant scale reaches the dequant mul at
    its own rank-kept shape (no broadcast_in_dim in the jaxpr) — it must
    still read as a scale, not as f32 payload data (the r14
    rankDAD@int8/fold4 cell's regression: the gathered factor block ships
    every virtual site's row, each with its own scale)."""
    from dinunet_implementations_tpu.parallel.collectives import (
        resolve_wire_codec,
    )

    codec = resolve_wire_codec("32", "int8")

    def packed_gather(g):  # g [K, m, n], per-row scales, gathered whole
        return jax.lax.all_gather(
            codec.compress(g, batched=True), "sites", axis=0
        )

    x = jnp.arange(24.0).reshape(4, 3, 2) + 1.0
    audit = sem.audit_jaxpr(
        jax.make_jaxpr(_one_site_shard(packed_gather))(x)
    )
    site = next(s for s in audit.collectives if s.prim == "all_gather")
    assert site.wire_itemsizes[0] == 1


def test_s002_match_prefers_exact_dtype_for_same_shape_payloads():
    """Two same-shape payloads at different dtypes (a bf16 factor next to an
    f32 dense leaf) must pair with their own model entries — first-fit by
    shape alone could cross-pair them, minting a spurious S004 upcast or
    masking a real one."""
    shape = (8, 2)
    aval = jax.ShapeDtypeStruct(shape, jnp.float32)
    sites = [
        sem.CollectiveSite("psum", ("site",), (aval,), 1, (4,)),
        sem.CollectiveSite("psum", ("site",), (aval,), 1, (2,)),
    ]
    expected = [
        (shape, np.dtype(np.float32)),
        (shape, np.dtype(jnp.bfloat16)),
    ]
    matches, missing, leftovers = sem._match_payload(sites, expected)
    assert missing == [] and leftovers == []
    assert {(d.itemsize, traced) for _, d, traced, _ in matches} == {
        (4, 4), (2, 2),
    }


# ---------------------------------------------------------------------------
# S005 — program identity
# ---------------------------------------------------------------------------


def _texts():
    t1 = jax.jit(lambda x: x + 1.0).lower(jnp.ones((3,))).as_text()
    t2 = jax.jit(lambda x: x * 2.0).lower(jnp.ones((3,))).as_text()
    return t1, t2


def test_s005_divergent_off_program_flagged():
    t1, t2 = _texts()
    fs = sem.check_lowering_identity([("fixture-off", t1, t2, True)])
    assert _rules(fs) == ["S005"]
    assert "diverges" in fs[0].message


def test_s005_vanished_divergence_flagged():
    t1, _ = _texts()
    fs = sem.check_lowering_identity([("fixture-opt-out", t1, t1, False)])
    assert _rules(fs) == ["S005"]
    assert "identical" in fs[0].message


def test_s005_identical_pair_clean():
    t1, _ = _texts()
    assert sem.check_lowering_identity([("ok", t1, t1, True)]) == []


def test_differ_normalization_and_first_divergence_report():
    t1, t2 = _texts()
    assert diff_report(t1, t1) is None
    # normalization strips locations/metadata and canonicalizes ids
    lines = normalize_lowering(t1)
    assert not any("loc(" in ln for ln in lines)
    report = diff_report(t1, t2, "add-one", "times-two")
    assert report is not None
    assert "first at line" in report and "add-one" in report


def test_differ_single_insertion_counts_once():
    """One op inserted mid-program is ONE divergence reported at its true
    location — not a positional cascade where every shifted line after the
    insertion reads as differing and the context block shows
    identical-content lines."""
    lines = [f"op{i} = work arg{i}" for i in range(40)]
    a = "\n".join(lines)
    b = "\n".join(lines[:20] + ["opX = extra"] + lines[20:])
    report = diff_report(a, b, "base", "plus-one")
    assert "1 differing line(s)" in report
    assert "first at line 21 (insert)" in report
    assert "opX = extra" in report


# ---------------------------------------------------------------------------
# suppression (baseline) round-trip per rule
# ---------------------------------------------------------------------------


def _finding_fixtures():
    """One representative finding list per S-rule, from the fixtures
    above."""
    audit = sem.audit_jaxpr(_rogue_axis_program(in_scan=False))
    s001 = sem.check_collective_axes(audit.collectives, "trace://fixture")
    bad = dataclasses.replace(
        make_engine("dSGD"), wire_bytes=lambda g: 1, wire_shapes=None
    )
    prog = _trace("dSGD", engine=bad)
    s002 = sem.check_wire_bytes(
        prog.audit.collectives, bad, prog.state.params, prog.block, prog.path
    )
    f = jax.jit(lambda s: s["a"].sum(), donate_argnums=(0,))
    s = {"a": jnp.ones((16,))}
    s003 = sem.check_donation(f.lower(s).compile(), (s,), (0,), "trace://d")
    s004 = sem.check_precision_flow(
        prog.audit.collectives, prog.engine, prog.state.params, prog.block,
        prog.path, require_lowp_dot=True, dots=[],
    )
    t1, t2 = _texts()
    s005 = sem.check_lowering_identity([("fx", t1, t2, True)])
    return {"S001": s001, "S002": s002, "S003": s003, "S004": s004,
            "S005": s005}


def test_semantic_baseline_roundtrip_per_rule(tmp_path):
    """Trigger + baseline-suppression + round-trip for every S-rule: a
    grandfathered finding stops gating, an un-grandfathered one still
    does."""
    fixtures = _finding_fixtures()
    for rule, findings in fixtures.items():
        assert findings, f"{rule} fixture produced no findings"
        assert {f.rule for f in findings} == {rule}
        bl_path = save_baseline(findings, str(tmp_path / f"{rule}.json"))
        baseline = load_baseline(bl_path)
        new, matched = apply_baseline(findings, baseline)
        assert new == [] and matched == len(findings), rule
        fresh = dataclasses.replace(
            findings[0], snippet=findings[0].snippet + " (new)"
        )
        new2, _ = apply_baseline(findings + [fresh], baseline)
        assert new2 == [fresh], rule


# ---------------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------------


def test_cli_semantic_flag_gates_and_emits_json(tmp_path, capsys, monkeypatch):
    from dinunet_implementations_tpu.checks.__main__ import main

    fake = _finding_fixtures()["S005"]
    monkeypatch.setattr(sem, "run_semantic_checks", lambda: list(fake))
    assert main(["--semantic", "--no-baseline", "--format", "json"]) == 1
    rows = [json.loads(ln) for ln in capsys.readouterr().out.splitlines()]
    assert [r["rule"] for r in rows] == ["S005"]
    # grandfathering through a baseline file turns the gate green
    bl = save_baseline(fake, str(tmp_path / "bl.json"))
    assert main(["--semantic", "--baseline-file", bl]) == 0


def test_cli_sarif_format(tmp_path, capsys):
    from dinunet_implementations_tpu.checks.__main__ import main

    bad = tmp_path / "trainer" / "hot.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def f():\n    print('x')\n")
    rc = main([str(tmp_path), "--no-baseline", "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "jaxlint"
    (res,) = run["results"]
    assert res["ruleId"] == "R001"
    assert res["locations"][0]["physicalLocation"]["region"]["startLine"] == 2


# ---------------------------------------------------------------------------
# the acceptance gate
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_full_matrix_scans_clean_with_empty_baseline():
    """The WHOLE engine × topology × pipeline matrix (plus the precision and
    donation corners and the S005 identity gate) traces clean, and the
    checked-in semantic baseline is genuinely empty.

    Slow tier: traces/compiles the full matrix (~30s); the same zero-findings
    gate is enforced on every push by the dedicated ``semantic`` CI job
    (``checks --semantic`` against the empty baseline), so the fast tier
    keeps only the per-rule unit cells above.
    """
    assert load_baseline(sem.SEMANTIC_BASELINE) == []
    findings = sem.run_semantic_checks()
    assert findings == [], "\n".join(f.format() for f in findings)
