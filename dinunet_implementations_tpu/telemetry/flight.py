"""Flight recorder — a crash-safe ring of the process's final seconds.

A daemon that dies (unhandled exception, SIGTERM from a preempting
scheduler, OOM-killer near-miss) used to leave nothing but whatever
metrics.jsonl rows already flushed; the operator reconstructs its last
moments from guesswork. The :class:`FlightRecorder` keeps a BOUNDED
in-memory ring of the most recent spans/events (fed live by the span
tracer via its listener hook) plus its own notes (epoch ticks, holds,
ingest results), and on the way down writes one ``flight_<pid>.json``
containing:

- the ring (the last N spans/events, in order),
- a final MetricsBus snapshot (counters/gauges/histograms at death),
- reason, pid, argv, uptime, wall-clock timestamp.

Dump triggers:

- **cooperative** — the daemon's serve loop calls :meth:`dump` when its
  PreemptionGuard latches SIGTERM/SIGINT (the guard owns the signal
  handlers there; the recorder must not fight it);
- **installed** — :meth:`install` chains ``sys.excepthook`` (and, where no
  guard owns them, SIGTERM) so a crash anywhere still dumps. Previous
  hooks/handlers are preserved and called after the dump.

Dumps are atomic (tmp + rename), append a sequence suffix rather than
overwrite (a crash DURING shutdown keeps both dumps), and never raise —
a broken disk at crash time must not mask the original exception.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque

FLIGHT_PREFIX = "flight_"


def flight_files(dirpath: str) -> list[str]:
    """Recorded dumps under ``dirpath``, oldest first."""
    try:
        names = sorted(
            n for n in os.listdir(dirpath)
            if n.startswith(FLIGHT_PREFIX) and n.endswith(".json")
        )
    except OSError:
        return []
    return [os.path.join(dirpath, n) for n in names]


class FlightRecorder:
    """See module docstring."""

    def __init__(self, out_dir: str = ".", *, capacity: int = 512,
                 bus=None, tracer=None):
        self.out_dir = out_dir
        self.bus = bus
        self._ring: deque = deque(maxlen=max(int(capacity), 1))
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._seq = 0
        self._installed = False
        self._prev_excepthook = None
        self._prev_handlers: dict = {}
        self.dumps: list[str] = []  # paths written this process
        if tracer is not None:
            self.listen(tracer)

    # -- feeding the ring -------------------------------------------------

    def record(self, event: dict) -> None:
        """One event into the ring (the tracer listener's target)."""
        with self._lock:
            self._ring.append(event)

    def listen(self, tracer) -> None:
        """Mirror every span/event/counter the tracer records into the
        ring (bounded — the tracer's own buffer is the complete record,
        the ring is the tail). A disabled tracer (the shared NULL_TRACER)
        never records, so attaching to it would only pin this recorder on
        a process-global listener list forever — skip it."""
        if tracer.enabled:
            tracer.add_listener(self.record)

    def note(self, name: str, **attrs) -> None:
        """A recorder-local instant event — the daemon's serve loop notes
        epoch ticks/holds/ingests here so the ring has content even when
        telemetry (and thus the tracer) is off."""
        self.record({
            "ph": "i", "name": name,
            "ts": round((time.monotonic() - self._t0) * 1e6, 1),
            "src": "flight", **attrs,
        })

    def recent(self, limit: int = 256) -> list[dict]:
        """The newest ``limit`` ring events, oldest first (the ``/tracez``
        payload)."""
        with self._lock:
            events = list(self._ring)
        return events[-limit:]

    # -- dumping ----------------------------------------------------------

    def dump(self, reason: str) -> str | None:
        """Write ``flight_<pid>[_<seq>].json``; returns the path, or None
        when even best-effort writing failed. Never raises."""
        try:
            with self._lock:
                events = list(self._ring)
                self._seq += 1
                seq = self._seq
            payload = {
                "reason": reason,
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "time_unix": time.time(),
                "uptime_s": round(time.monotonic() - self._t0, 3),
                "events": events,
                "bus": self.bus.snapshot() if self.bus is not None else None,
            }
            name = (
                f"{FLIGHT_PREFIX}{os.getpid()}.json" if seq == 1
                else f"{FLIGHT_PREFIX}{os.getpid()}_{seq}.json"
            )
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(self.out_dir, name)
            tmp = path + ".tmp"
            from .sink import _finite  # strict-JSON: non-finite -> null

            with open(tmp, "w") as fh:
                json.dump(
                    _finite(payload), fh, default=str, allow_nan=False
                )
            os.replace(tmp, path)
            self.dumps.append(path)
            return path
        except Exception:
            # the recorder must never mask the original failure
            return None

    # -- crash hooks -------------------------------------------------------

    def install(self, signals=(signal.SIGTERM,)) -> None:
        """Chain the dump into ``sys.excepthook`` and the given signals.
        Signal chaining: after dumping, the PREVIOUS handler runs (or the
        default disposition is restored and the signal re-raised, so a
        plain SIGTERM still terminates). Skip signal installation wherever
        a PreemptionGuard owns the handlers — pass ``signals=()`` and dump
        cooperatively instead."""
        if self._installed:
            return
        self._installed = True
        self._prev_excepthook = sys.excepthook

        def excepthook(exc_type, exc, tb):
            self.note("unhandled-exception", error=repr(exc))
            self.dump(f"crash:{exc_type.__name__}")
            (self._prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)

        sys.excepthook = excepthook

        def handler(signum, frame):
            self.note("signal", signum=signum)
            self.dump(f"signal:{signum}")
            prev = self._prev_handlers.get(signum)
            if callable(prev):
                prev(signum, frame)
            else:
                # restore the default disposition and re-deliver, so the
                # process still dies of the signal it was sent
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)

        try:
            for s in signals:
                self._prev_handlers[s] = signal.signal(s, handler)
        except ValueError:
            # not the main thread: excepthook-only
            self._prev_handlers = {}

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None
        for s, h in self._prev_handlers.items():
            try:
                signal.signal(s, h)
            except (ValueError, TypeError):
                pass
        self._prev_handlers = {}
