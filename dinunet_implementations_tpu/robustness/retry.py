"""Transient-failure retry: jittered exponential backoff with deadlines.

The reference's coordinator/worker topology tolerates a worker that comes up
before the coordinator, or an NFS read that fails once under load, by virtue
of its message-bus retries. Here the equivalents — ``jax.distributed``
initialization racing the coordinator, native batch-IO reads on shared
filesystems, spool admission in the daemon-mode FedRunner — get an explicit
wrapper:

    @with_retry(attempts=3, base_delay=0.5, retry_on=(RuntimeError, OSError))
    def connect(): ...

    init = with_retry(jax.distributed.initialize, attempts=3,
                      deadline_s=120.0, timeout_s=45.0)

Backoff for attempt ``i`` is ``min(base_delay * 2**i, max_delay)`` scaled by
a jitter factor in ``[0.5, 1.5)`` — jittered so a fleet of workers retrying
the same dead coordinator doesn't thundering-herd it. Pass ``seed`` for a
deterministic jitter sequence (tests), and ``sleep`` to observe/skip the
waits.

Deadline semantics (r13 — a hung remote must fail FAST, not retry forever):

- ``deadline_s`` — a wall-clock budget across ALL attempts. Once a failure
  lands past the deadline, the last exception propagates immediately even if
  attempts remain, and every backoff sleep is capped to the remaining
  budget. Measured on ``clock`` (default ``time.monotonic``).
- ``timeout_s`` — a per-attempt cap: the attempt runs on a worker thread and
  a result that doesn't arrive in time raises :class:`RetryTimeout`, which
  is ALWAYS treated as retryable (a timeout is by definition the transient
  class this wrapper exists for). The abandoned attempt's thread cannot be
  killed and may linger until its blocking call returns — acceptable for
  fail-fast semantics on a hung ``jax.distributed.initialize`` or NFS read,
  but don't use ``timeout_s`` around non-reentrant global state unless the
  caller tolerates the zombie attempt finishing late.
"""

from __future__ import annotations

import functools
import logging
import random
import threading
import time

_log = logging.getLogger("dinunet_implementations_tpu.robustness.retry")


class RetryTimeout(TimeoutError):
    """One attempt exceeded ``timeout_s``. The worker thread that ran the
    attempt may still be alive (blocking calls cannot be interrupted); the
    caller only gets control back."""


def _call_with_timeout(f, args, kwargs, timeout_s: float):
    """Run one attempt on a DAEMON thread, abandoning it past ``timeout_s``.

    A bare daemon ``threading.Thread``, not a ThreadPoolExecutor: executor
    workers are non-daemon and ``concurrent.futures`` joins them at
    interpreter exit, so one genuinely hung attempt (a dead NFS mount
    blocking in the kernel) would wedge process shutdown forever — exactly
    the failure mode this timeout exists to escape."""
    result: list = []
    error: list = []

    def run():
        try:
            result.append(f(*args, **kwargs))
        # not swallowed: relayed verbatim to the calling thread below (a
        # thread boundary cannot propagate exceptions any other way)
        except Exception as e:  # jaxlint: disable=R002
            error.append(e)

    t = threading.Thread(target=run, daemon=True, name="with_retry-attempt")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise RetryTimeout(
            f"attempt did not return within timeout_s={timeout_s}"
        )
    if error:
        raise error[0]
    return result[0]


def with_retry(
    fn=None,
    *,
    attempts: int = 3,
    base_delay: float = 0.1,
    max_delay: float = 5.0,
    retry_on: tuple = (OSError,),
    seed: int | None = None,
    sleep=time.sleep,
    describe: str | None = None,
    deadline_s: float | None = None,
    timeout_s: float | None = None,
    retry_on_timeout: bool = True,
    clock=time.monotonic,
):
    """Wrap ``fn`` (decorator or call form) with jittered exponential backoff.

    Retries only exceptions matching ``retry_on`` (plus :class:`RetryTimeout`
    when ``timeout_s`` is set); anything else propagates immediately. After
    ``attempts`` failures — or, with ``deadline_s``, the first failure past
    the wall-clock budget — the last exception propagates.

    ``retry_on_timeout=False`` makes a per-attempt timeout FATAL instead of
    retryable: the abandoned attempt's thread may still be mutating whatever
    the call touches, and for non-reentrant global state
    (``jax.distributed.initialize``) a concurrent second attempt would race
    the zombie — there, a timeout should fail the operation, not retry it.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    if deadline_s is not None and deadline_s <= 0:
        raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
    if timeout_s is not None and timeout_s <= 0:
        raise ValueError(f"timeout_s must be > 0, got {timeout_s}")

    def deco(f):
        catch = tuple(retry_on) + (
            (RetryTimeout,)
            if timeout_s is not None and retry_on_timeout else ()
        )

        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            rng = random.Random(seed)
            name = describe or getattr(f, "__name__", repr(f))
            start = clock()
            for attempt in range(attempts):
                try:
                    if timeout_s is None:
                        return f(*args, **kwargs)
                    return _call_with_timeout(f, args, kwargs, timeout_s)
                except catch as e:
                    if isinstance(e, RetryTimeout) and not retry_on_timeout:
                        # TimeoutError ⊂ OSError, so a retry_on=(OSError,)
                        # entry would otherwise re-catch the timeout the
                        # caller asked to be fatal
                        raise
                    remaining = (
                        None if deadline_s is None
                        else deadline_s - (clock() - start)
                    )
                    if attempt == attempts - 1 or (
                        remaining is not None and remaining <= 0
                    ):
                        if remaining is not None and remaining <= 0:
                            _log.warning(
                                "%s failed (attempt %d/%d) past the %.1fs "
                                "deadline: %s — giving up",
                                name, attempt + 1, attempts, deadline_s, e,
                            )
                        raise
                    delay = min(base_delay * (2 ** attempt), max_delay)
                    delay *= 0.5 + rng.random()  # jitter in [0.5, 1.5)
                    if remaining is not None:
                        # never sleep past the budget; the next failure then
                        # lands at/after the deadline and propagates
                        delay = min(delay, max(remaining, 0.0))
                    _log.warning(
                        "%s failed (attempt %d/%d): %s — retrying in %.2fs",
                        name, attempt + 1, attempts, e, delay,
                    )
                    sleep(delay)

        return wrapped

    return deco if fn is None else deco(fn)
