"""Continuous microbatcher — the request-queue half of the serving path.

One :class:`Microbatcher` per lane (batched inference / streaming step): a
thread-safe queue plus a single dispatch thread that coalesces requests under
a **max-batch / max-delay** admission rule — a dispatch fires as soon as the
pending rows fill the largest shape bucket, or when the OLDEST pending
request has waited ``max_delay_ms``, whichever comes first. The dispatch
callback (serving/engine.py) pads the collected requests into the smallest
bucket that fits and runs ONE pre-compiled executable — the request path
never traces or compiles, whatever the traffic pattern (that is the point of
bucketing: the compiled-shape set is closed at warmup).

Admission details that matter:

- **FIFO with conflict stash**: requests dispatch in arrival order, except a
  request whose ``conflict_key`` collides with one already collected (two
  chunks of the SAME streaming session — the second must see the first's
  updated carry) is stashed for the next dispatch, preserving order.
- **No oversize silently**: a request bigger than the largest bucket is
  rejected at submit with a clear error — splitting is the caller's policy
  decision (the engine's ``stream()`` splits long window runs into
  chunk-bucket pieces before submitting).
- The dispatch thread is a **daemon** and closes via sentinel, so a crashed
  caller never wedges interpreter shutdown (the with_retry lesson, r13).
"""

from __future__ import annotations

import concurrent.futures as _futures
import queue
import threading
import time


class ServingClosed(RuntimeError):
    """Submit after close()."""


class RequestError(RuntimeError):
    """A request the serving path cannot admit (oversize, bad shape)."""


class RequestFuture(_futures.Future):
    """The stdlib future with a bounded default wait: a serving client that
    forgets a timeout hangs 30 s and gets a clear ``TimeoutError``, not a
    forever-block on a lost dispatch."""

    def result(self, timeout: float | None = 30.0):
        return super().result(timeout)


class ChainedFuture:
    """A future over an in-order CHAIN of requests (a multi-chunk
    ``stream()`` call): ``result()`` waits the chain and raises the FIRST
    link's error — an early chunk's dispatch failure must surface, never be
    masked by a later chunk happening to succeed on a carry that silently
    missed the failed chunk's windows."""

    def __init__(self, links: list):
        self._links = links

    def done(self) -> bool:
        return all(f.done() for f in self._links)

    def result(self, timeout: float | None = 30.0):
        out = None
        for f in self._links:
            out = f.result(timeout)
        return out


class Microbatcher:
    """One serving lane's queue + dispatch thread (see module docstring).

    ``dispatch(requests, bucket)`` receives the collected request objects and
    the chosen bucket (row capacity); it must resolve every request's
    ``future``. ``rows_of(req)`` counts a request's bucket rows (samples for
    the batched lane, 1 session for the streaming lane); ``conflict_key``
    (optional) serializes requests that must not share a dispatch."""

    def __init__(self, dispatch, buckets, *, rows_of=None, conflict_key=None,
                 max_delay_ms: float = 2.0, name: str = "lane",
                 on_dispatch=None, bus=None):
        from ..telemetry.bus import NULL_BUS

        if not buckets:
            raise ValueError("need at least one shape bucket")
        self.dispatch = dispatch
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.rows_of = rows_of or (lambda req: len(req.rows))
        self.conflict_key = conflict_key
        self.max_delay_s = max_delay_ms / 1e3
        self.name = name
        self.on_dispatch = on_dispatch
        self.bus = bus if bus is not None else NULL_BUS
        self._q: queue.Queue = queue.Queue()
        self._stash: list = []  # conflict-deferred, ahead of the queue
        self._closed = False
        self._stats_lock = threading.Lock()
        self.stats = {
            "requests": 0, "dispatches": 0, "rows": 0, "pad_rows": 0,
            "bucket_hits": 0, "rejected": 0, "max_queue_depth": 0,
            "deferrals": 0,
        }
        self._thread = threading.Thread(
            target=self._run, name=f"microbatch-{name}", daemon=True
        )
        self._thread.start()

    @property
    def max_rows(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        raise RequestError(
            f"{self.name}: request needs {rows} rows but the largest "
            f"compiled bucket is {self.max_rows} — split the request or "
            f"serve with a bigger bucket set"
        )

    def submit(self, req) -> None:
        if self._closed:
            raise ServingClosed(f"{self.name}: microbatcher is closed")
        rows = self.rows_of(req)
        if rows > self.max_rows:
            self.stats["rejected"] += 1
            raise RequestError(
                f"{self.name}: request of {rows} rows exceeds the largest "
                f"bucket ({self.max_rows})"
            )
        req._submit_t = time.monotonic()
        self._q.put(req)
        # peak depth must be sampled at ENQUEUE too: sampling only at
        # dispatch time (the pre-r16 behavior) under-reported any burst that
        # arrived and drained between two dispatches
        self._note_depth()

    def depth(self) -> int:
        """Instantaneous queue depth (queued + stash-deferred requests) —
        the ONE definition /statusz, drain() and the peak sampler share."""
        return self._q.qsize() + len(self._stash)

    def _note_depth(self) -> int:
        depth = self.depth()
        with self._stats_lock:
            if depth > self.stats["max_queue_depth"]:
                self.stats["max_queue_depth"] = depth
        self.bus.gauge("serving_queue_depth", depth, lane=self.name)
        return depth

    # -- dispatch thread -------------------------------------------------

    def _collect(self, first) -> list:
        """Admission: grow the batch from the queue until the largest bucket
        is full or the FIRST request's max-delay budget runs out."""
        batch = [first]
        rows = self.rows_of(first)
        keys = {self.conflict_key(first)} if self.conflict_key else set()
        deadline = first._submit_t + self.max_delay_s
        while rows < self.max_rows:
            remaining = deadline - time.monotonic()
            nxt = None
            if self._stash:
                # stashed requests (conflict- or overflow-deferred) re-enter
                # ahead of the queue, but only if they don't conflict with
                # this batch
                for i, cand in enumerate(self._stash):
                    if (self.conflict_key is None
                            or self.conflict_key(cand) not in keys):
                        nxt = self._stash.pop(i)
                        break
            if nxt is None:
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:  # close sentinel — finish this batch first
                    self._q.put(None)
                    break
            if self.conflict_key is not None:
                k = self.conflict_key(nxt)
                if k in keys:
                    self._stash.append(nxt)  # same session: next dispatch
                    self._note_deferral("conflict")
                    continue
                keys.add(k)
            if rows + self.rows_of(nxt) > self.max_rows:
                self._stash.append(nxt)  # doesn't fit: keep order, defer
                self._note_deferral("overflow")
                break
            batch.append(nxt)
            rows += self.rows_of(nxt)
        return batch

    def _note_deferral(self, why: str) -> None:
        with self._stats_lock:
            self.stats["deferrals"] += 1
        self.bus.counter("serving_deferrals_total", lane=self.name, why=why)

    def _run(self) -> None:
        while True:
            if self._stash:
                first = self._stash.pop(0)
            else:
                first = self._q.get()
                if first is None:
                    if self._stash:  # drain conflict-deferred tail
                        self._q.put(None)
                        continue
                    return
            batch = self._collect(first)
            rows = sum(self.rows_of(r) for r in batch)
            try:
                bucket = self.bucket_for(rows)
                depth = self._note_depth()
                self.dispatch(batch, bucket)
                self.stats["requests"] += len(batch)
                self.stats["dispatches"] += 1
                self.stats["rows"] += rows
                self.stats["pad_rows"] += bucket - rows
                self.stats["bucket_hits"] += int(rows == bucket)
                self.bus.counter("serving_dispatches_total", lane=self.name)
                self.bus.observe(
                    "serving_batch_occupancy_pct", 100.0 * rows / bucket,
                    lane=self.name,
                )
                if self.on_dispatch is not None:
                    self.on_dispatch(self.name, batch, bucket, rows, depth)
            except Exception as e:
                # the dispatch thread must never die silently: every
                # collected request's waiter gets the error, and the loop
                # keeps serving the next batch
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)

    def close(self, timeout: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout)
