"""Compare two bench.py jsonl artifacts arm-by-arm.

    python scripts/bench_diff.py BASELINE.jsonl CANDIDATE.jsonl \\
        [--stat median|value|min] [--max-regress PCT] [--min-pairs N]

bench.py emits one JSON record per configuration; this tool pairs records
across the two files by identity — the ``arm`` name for A/B artifacts
(bench_rankdad_ab_*.jsonl), else the configuration key (metric, engine,
sites, pack_factor, slices, backend, unit) for sweep artifacts — and
prints, per pair, the baseline and candidate throughput (median of
observations by default), the spread of each, and the % delta. Unpaired
records on either side are listed, never silently dropped.

Exit codes (the CI contract):

- ``--min-pairs N``: exit 1 if fewer than N records paired up — the
  STRUCTURAL gate (a bench emitting a renamed or missing configuration
  fails even when every surviving number looks fine).
- ``--max-regress PCT``: exit 1 if any pair's throughput fell more than
  PCT percent below baseline. Leave it off when the two artifacts come
  from different machines (CI runners vs the committed artifact's host):
  cross-host absolute numbers are not comparable, pairing is.

Stdlib-only; non-JSON lines (bench's human-readable banners) are skipped.
"""

from __future__ import annotations

import argparse
import json
import sys

#: identity fields that name a sweep configuration when no ``arm`` is set
IDENTITY_FIELDS = (
    "metric", "engine", "sites", "pack_factor", "slices", "backend", "unit",
)

#: per-record throughput block bench.py emits
RATE_KEY = "samples_per_sec"


def load_records(path: str) -> list[dict]:
    """JSON records from one bench artifact; non-JSON lines and records
    without a throughput block are skipped (bench interleaves banners)."""
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and isinstance(rec.get(RATE_KEY), dict):
                out.append(rec)
    return out


def pair_key(rec: dict):
    """A record's identity: the A/B ``arm`` name when present, else the
    sweep-configuration tuple."""
    if rec.get("arm") is not None:
        return ("arm", str(rec["arm"]))
    return tuple(
        (f, rec.get(f)) for f in IDENTITY_FIELDS if rec.get(f) is not None
    )


def _key_str(key) -> str:
    if isinstance(key, tuple) and key and key[0] == "arm":
        return f"arm={key[1]}"
    return " ".join(
        f"{f}={v}" for f, v in key
        if f not in ("metric", "unit")
    ) or str(key)


def pair_records(
    base: list[dict], cand: list[dict],
) -> tuple[list[tuple], list, list]:
    """``(pairs, unpaired_base_keys, unpaired_cand_keys)``. Duplicate keys
    within one file keep the LAST record (bench re-runs append)."""
    b = {pair_key(r): r for r in base}
    c = {pair_key(r): r for r in cand}
    pairs = [(k, b[k], c[k]) for k in b if k in c]
    return (
        pairs,
        sorted(_key_str(k) for k in b if k not in c),
        sorted(_key_str(k) for k in c if k not in b),
    )


def diff_rows(pairs: list[tuple], stat: str) -> list[dict]:
    rows = []
    for key, b, c in pairs:
        bv = float(b[RATE_KEY].get(stat, b[RATE_KEY].get("value", 0.0)))
        cv = float(c[RATE_KEY].get(stat, c[RATE_KEY].get("value", 0.0)))
        rows.append({
            "key": _key_str(key),
            "base": bv,
            "cand": cv,
            "base_spread": float(b[RATE_KEY].get("spread") or 0.0),
            "cand_spread": float(c[RATE_KEY].get("spread") or 0.0),
            "delta_pct": (cv - bv) / bv * 100.0 if bv else float("nan"),
        })
    return rows


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python scripts/bench_diff.py",
        description="Pair and diff two bench.py jsonl artifacts "
                    "(same-arm / same-configuration records).",
    )
    p.add_argument("baseline", help="committed artifact (docs/bench_*.jsonl)")
    p.add_argument("candidate", help="fresh bench output to compare")
    p.add_argument("--stat", default="median",
                   choices=("median", "value", "min"),
                   help="which throughput statistic to compare "
                        "(default median of observations)")
    p.add_argument("--max-regress", type=float, default=None, metavar="PCT",
                   help="exit 1 if any pair regressed more than PCT%% "
                        "(only meaningful for same-host artifacts)")
    p.add_argument("--min-pairs", type=int, default=1, metavar="N",
                   help="exit 1 unless at least N records paired (default 1)")
    args = p.parse_args(argv)

    base = load_records(args.baseline)
    cand = load_records(args.candidate)
    pairs, only_base, only_cand = pair_records(base, cand)
    rows = diff_rows(pairs, args.stat)

    print(f"bench_diff: {len(base)} baseline / {len(cand)} candidate "
          f"records, {len(rows)} paired ({args.stat})")
    if rows:
        width = max(len(r["key"]) for r in rows)
        print(f"{'configuration':<{width}}  {'base':>12}  {'cand':>12}"
              f"  {'delta %':>9}  spread b/c")
        for r in rows:
            print(
                f"{r['key']:<{width}}  {r['base']:>12.2f}  "
                f"{r['cand']:>12.2f}  {r['delta_pct']:>+9.2f}  "
                f"{r['base_spread']:.1f}/{r['cand_spread']:.1f}"
            )
    for k in only_base:
        print(f"  baseline-only: {k}")
    for k in only_cand:
        print(f"  candidate-only: {k}")

    rc = 0
    if len(rows) < args.min_pairs:
        print(f"bench_diff: only {len(rows)} pair(s), need "
              f">= {args.min_pairs}", file=sys.stderr)
        rc = 1
    if args.max_regress is not None:
        bad = [r for r in rows if r["delta_pct"] < -args.max_regress]
        for r in bad:
            print(f"bench_diff: {r['key']} regressed "
                  f"{r['delta_pct']:+.2f}% (limit -{args.max_regress}%)",
                  file=sys.stderr)
        if bad:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
