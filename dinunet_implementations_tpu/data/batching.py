"""SPMD batch planning: sites × steps × batch dense arrays with masks.

The reference hides heterogeneous site sizes (73–120 subjects in the FS
fixture) behind round-based orchestration: every round each site pulls
``local_iterations`` batches from its own cycling DataLoader with
``drop_last=True`` for train (``local.py:29``). In one SPMD program all sites
must take the same number of steps per epoch, so we make the step grid dense:

- ``inputs  [S, steps, B, ...]``
- ``labels  [S, steps, B]``
- ``weights [S, steps, B]`` — 1.0 for real examples, 0.0 for padding; the
  trainer weighs per-site gradients by ``weights.sum()`` so aggregation is
  exactly example-weighted (dSGD == pooled SGD invariant).

``pad_mode``:
- ``"wrap"`` (train default): sites with fewer batches than the epoch's
  ``steps`` recycle their shuffled data — every site contributes every round,
  like the reference's cycling DataLoader.
- ``"mask"`` (eval): padding gets weight 0; no sample is seen twice (AUC /
  metric correctness).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .api import SiteArrays


@dataclass
class FedBatches:
    inputs: np.ndarray  # [S, steps, B, ...]
    labels: np.ndarray  # [S, steps, B]
    weights: np.ndarray  # [S, steps, B] float32
    indices: np.ndarray  # [S, steps, B] int32 (position in site inventory; -1 pad)

    @property
    def num_sites(self):
        return self.inputs.shape[0]

    @property
    def steps(self):
        return self.inputs.shape[1]

    @property
    def batch_size(self):
        return self.inputs.shape[2]


def _site_batches(arr: SiteArrays, batch_size: int, order: np.ndarray, drop_last: bool):
    """Chunk one site's (ordered) samples into batches; returns list of index
    arrays, each of length ``batch_size`` except possibly the last."""
    n = len(order)
    if drop_last:
        n = (n // batch_size) * batch_size
    return [order[i : i + batch_size] for i in range(0, n, batch_size)]


def plan_epoch(
    sites: list[SiteArrays],
    batch_size: int,
    seed: int = 0,
    shuffle: bool = True,
    drop_last: bool = True,
    pad_mode: str = "wrap",
) -> FedBatches:
    """Build the dense [S, steps, B, ...] epoch plan (see module docstring)."""
    assert pad_mode in ("wrap", "mask")
    S = len(sites)
    feat_shape = None
    for s in sites:
        if len(s):
            fs = s.inputs.shape[1:]
            assert feat_shape is None or fs == feat_shape, "heterogeneous feature shapes"
            feat_shape = fs
    assert feat_shape is not None, "all sites empty"

    rng = np.random.default_rng(seed)
    per_site: list[list[np.ndarray]] = []
    for s in sites:
        order = rng.permutation(len(s)) if shuffle else np.arange(len(s))
        per_site.append(_site_batches(s, batch_size, order, drop_last))

    steps = max(len(b) for b in per_site)
    assert steps > 0, (
        f"no site yields a batch: batch_size={batch_size} exceeds every "
        f"site's sample count {[len(s) for s in sites]} with "
        f"drop_last={drop_last} — lower batch_size to at most "
        f"{max(len(s) for s in sites)} (FederatedTrainer.fit clamps this "
        "automatically)"
    )

    inputs = np.zeros((S, steps, batch_size) + feat_shape, np.float32)
    labels = np.zeros((S, steps, batch_size), np.int32)
    weights = np.zeros((S, steps, batch_size), np.float32)
    indices = np.full((S, steps, batch_size), -1, np.int32)

    for si, (site, batches) in enumerate(zip(sites, per_site)):
        if pad_mode == "wrap" and batches:
            while len(batches) < steps:  # recycle with a fresh shuffle
                order = rng.permutation(len(site)) if shuffle else np.arange(len(site))
                batches = batches + _site_batches(site, batch_size, order, drop_last)
            batches = batches[:steps]
        for bi, ix in enumerate(batches):
            k = len(ix)
            sel = site.take(ix)
            inputs[si, bi, :k] = sel.inputs
            labels[si, bi, :k] = sel.labels
            weights[si, bi, :k] = 1.0
            indices[si, bi, :k] = sel.indices

    return FedBatches(inputs, labels, weights, indices)


def plan_eval(sites: list[SiteArrays], batch_size: int) -> FedBatches:
    """Deterministic full pass: no shuffle, no drop, mask padding."""
    return plan_epoch(
        sites, batch_size, shuffle=False, drop_last=False, pad_mode="mask"
    )
