"""Native (C++) runtime components.

The reference's only native code is what it inherits from torch — most
relevantly the DataLoader's native worker pool doing the per-item TSV reads
(reference ``comps/fs/__init__.py:33-39`` + ``num_workers``,
``compspec.json:185-192``). This package holds the TPU build's equivalents:
small C++ components compiled on demand with the system toolchain and loaded
via ctypes (no pybind11 dependency), each with a pure-Python fallback so the
framework never hard-requires a compiler at runtime.

Current components:
- ``fastio.cpp`` — threaded batch parser for FreeSurfer aseg TSVs
  (:func:`dinunet_implementations_tpu.data.native_io.read_aseg_batch`).
"""

from __future__ import annotations

import ctypes
import os
import stat
import subprocess
import tempfile

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))


def _cache_dir() -> str:
    """User-owned 0700 cache directory for compiled libraries.

    The library is CDLL-loaded into the training process, so the cache must
    not live at a predictable world-writable path (e.g. bare /tmp) where
    another local user could pre-plant a .so (advisor finding r3). We create
    the directory 0700 and refuse to use it unless it is owned by us and not
    group/other-writable.
    """
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    candidates = [
        os.path.join(base, "dinunet_native"),
        # fallback when $HOME is unwritable (containers): per-uid tmpdir
        os.path.join(
            tempfile.gettempdir(), f"dinunet_native_uid{os.getuid()}"
        ),
    ]
    for path in candidates:
        try:
            os.makedirs(path, mode=0o700, exist_ok=True)
            # lstat + symlink rejection (advisor r4): os.stat follows
            # symlinks, so a pre-planted link at the predictable /tmp
            # fallback pointing at a victim-owned 0700 directory would pass
            # the uid/mode check and redirect our .so writes there.
            st = os.lstat(path)
            # S_ISDIR on the lstat result covers the symlink case too (a
            # symlink's mode is S_IFLNK) and keeps uid/mode/type checks on
            # ONE inode snapshot — separate islink/isdir calls could each
            # observe different filesystem states.
            if (
                stat.S_ISDIR(st.st_mode)
                and st.st_uid == os.getuid()
                and not (st.st_mode & 0o022)
            ):
                return path
        except OSError:
            continue
    raise RuntimeError("no trustworthy native cache directory")


def build_and_load(name: str) -> ctypes.CDLL | None:
    """Compile ``native/<name>.cpp`` into a cached shared library and load it.

    The cache key includes the source mtime+size, so edits rebuild. The cache
    lives in a user-owned 0700 directory (:func:`_cache_dir`) and the .so is
    re-verified as self-owned and non-world/group-writable before CDLL.
    Returns ``None`` on ANY failure (no compiler, compile error, load error)
    — callers must treat native paths as optional accelerations with Python
    fallbacks.
    """
    src = os.path.join(_SRC_DIR, f"{name}.cpp")
    try:
        st = os.stat(src)
        tag = f"{name}_{st.st_mtime_ns:x}_{st.st_size:x}"
        lib_path = os.path.join(_cache_dir(), f"dinunet_native_{tag}.so")
        if not os.path.exists(lib_path):
            tmp = lib_path + f".build{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-o", tmp, src],
                check=True, capture_output=True, timeout=120,
            )
            os.chmod(tmp, 0o700)  # g++ honors umask; pin owner-only
            os.replace(tmp, lib_path)  # atomic publish (concurrent builders)
        lst = os.stat(lib_path)
        if lst.st_uid != os.getuid() or (lst.st_mode & 0o022):
            return None  # not ours / tamperable — refuse to load
        return ctypes.CDLL(lib_path)
    except (OSError, subprocess.SubprocessError, RuntimeError):
        # the optional-acceleration failure modes, each → Python fallback:
        # OSError — g++ missing (FileNotFoundError), stat/chmod/replace on a
        #   read-only cache, or CDLL failing to load the .so;
        # SubprocessError — the compile itself failed (CalledProcessError)
        #   or hit the 120 s timeout (TimeoutExpired);
        # RuntimeError — _cache_dir() found no trustworthy cache directory.
        return None
