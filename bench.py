"""Benchmark: ICA-LSTM federated training throughput, 32 simulated sites.

The north-star metric (BASELINE.json): samples/sec/chip for the ICA-LSTM
fMRI classifier trained across 32 simulated federated sites, vs the
CPU reference baseline. One chip simulates all 32 sites via the vmap-folded
site axis (trainer/steps.py); the measured step is the FULL federated round:
per-site grad, dSGD example-weighted aggregation across the 32 sites, Adam
update — i.e. what the reference needs a 32-container COINSTAC deployment
plus a remote to do.

MEASUREMENT METHODOLOGY (important — the axon tunnel is a lazy backend):
the tunneled PJRT backend evaluates LAZILY PER FETCHED BUFFER. Fetching one
cheap output (a round counter) materializes only that buffer's dependency
chain and can skip nearly all of the training compute; block_until_ready
does not synchronize either. Verified empirically on v5e: fetching
``state.round`` after an epoch cost ~24 ms while materializing the FULL
state cost ~570 ms, and a 3 s host sleep did not advance device work (fully
fetch-driven). Earlier rounds' bench numbers were inflated by this. The
honest recipe used here:

1. chain N epochs (each consumes the previous state),
2. materialize EVERY leaf of the final state (np.asarray over the tree) —
   forcing the entire chain,
3. report the MARGINAL epoch cost between two LONG chains,
   (min T(N) - min T(N/2)) / (N/2), minimizing each chain length over three
   runs SEPARATELY: the tunnel is shared infrastructure whose contention
   only ever ADDS time (observed 2× swings minutes apart), so the minimum
   per endpoint is its least-contended observation. (Minimizing the paired
   differences instead would be downward-biased — contention in the half
   chain subtracts from the difference.)

Baseline: the reference's torch ICALstm (loaded from
/root/reference/comps/icalstm/models.py) doing fwd+bwd+Adam on one CPU site
measured in this environment = 67.3 samples/sec (B=16, 238 ms/iter; falls back
to this recorded constant when the live measurement is unavailable).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} (plus an
``mfu`` field — fraction of v5e bf16 peak sustained by the model's matmul
FLOPs at the measured throughput).
"""

import json
import sys
import time

# Recorded in this environment (see module docstring); re-measured live when
# --live-baseline is passed.
CPU_BASELINE_SAMPLES_PER_SEC = 67.3

NUM_SITES = 32
BATCH_PER_SITE = 16
STEPS_PER_EPOCH = 2
TIMED_EPOCHS = 100  # long chains: the marginal compute must dwarf fetch jitter

# flagship model dims (HCP inputspec, datasets/icalstm/inputspec.json:32-43)
WINDOWS, COMPS, WLEN = 98, 100, 10
ENC_IN, ENC_OUT, HIDDEN = COMPS * WLEN, 256, 348

V5E_BF16_PEAK_FLOPS = 197e12


def chain_epochs(epoch_fn, state0, x, y, w, n: int) -> float:
    """Run ``n`` chained epochs from ``state0`` and FULLY materialize the
    final state (np.asarray over every leaf) — the only synchronization the
    lazy tunneled backend honors. Returns wall-clock seconds. This is the
    shared measurement primitive for bench.py and bench_matrix.py; any
    methodology fix belongs here, once."""
    import jax
    import numpy as np

    s = state0
    t0 = time.time()
    for _ in range(n):
        s, _ = epoch_fn(s, x, y, w)
    jax.tree.map(np.asarray, s)
    return time.time() - t0


def least_contended_marginal(run_chain, n: int, repeats: int = 3,
                             pre_full: float | None = None) -> float:
    """Marginal seconds/epoch between an ``n``-epoch and an ``n/2``-epoch
    chain, taking the MINIMUM of ``repeats`` runs PER ENDPOINT (module
    docstring step 3): tunnel contention only adds time, so each endpoint's
    minimum is its least-contended observation; minimizing paired
    differences instead would be downward-biased. ``run_chain(k)`` must
    return wall-clock seconds for a k-epoch fully-materialized chain.
    ``pre_full`` feeds an already-observed (n+1)-chain timing into the
    full-endpoint minimum (valid for a min estimator; saves a chain)."""
    half = n // 2
    t_half = min(run_chain(half + 1) for _ in range(repeats))
    fulls = [run_chain(n + 1) for _ in range(repeats)]
    if pre_full is not None:
        fulls.append(pre_full)
    return max((min(fulls) - t_half) / (n - half), 1e-9)


def flops_per_sample() -> float:
    """Matmul FLOPs for one training sample (fwd ≈ enc + biLSTM + head;
    train ≈ 3× fwd for fwd+bwd)."""
    h = HIDDEN // 2  # per direction
    enc = WINDOWS * ENC_IN * ENC_OUT * 2
    lstm = WINDOWS * 2 * (ENC_OUT * 4 * h + h * 4 * h) * 2  # both directions
    head = HIDDEN * 256 * 2 + 256 * 64 * 2 + 64 * 2 * 2
    return 3.0 * (enc + lstm + head)


def measure_tpu(fused_bidir: bool | None = None, repeats: int = 5) -> float:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.models import ICALstm
    from dinunet_implementations_tpu.trainer import (
        FederatedTask,
        compile_epoch_aot,
        init_train_state,
        make_optimizer,
        make_train_epoch_fn,
    )

    # bf16 matmuls AND streamed activations with f32 carries/accumulation;
    # the fused Pallas kernel keeps W_ih/W_hh resident in VMEM and streams
    # the raw x once per step (ops/lstm_pallas.py). fused_bidir=False is the
    # A/B arm: two single-direction kernel sweeps instead of the fused
    # bidirectional pooled kernel (VERDICT r4 #1b).
    model = ICALstm(input_size=ENC_OUT, hidden_size=HIDDEN, num_comps=COMPS,
                    window_size=WLEN, num_cls=2, compute_dtype="bfloat16",
                    fused_bidir=fused_bidir)
    task = FederatedTask(model)
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-3)

    S, steps, B = NUM_SITES, STEPS_PER_EPOCH, BATCH_PER_SITE
    rng = np.random.default_rng(0)
    # ship inputs pre-cast to the model's compute dtype (what the input
    # pipeline does for a bf16 model): halves the resident input footprint
    # and removes XLA's whole-input convert+layout copy from the epoch
    x = jnp.asarray(
        rng.normal(size=(S, steps, B, WINDOWS, COMPS, WLEN)).astype(np.float32),
        dtype=jnp.bfloat16,
    )
    y = jnp.asarray((rng.random((S, steps, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)

    state0 = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S
    )
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh=None, local_iterations=1)
    # resident epoch inputs live in the layout the executable wants (the
    # per-epoch on-device relayout copy moves into this one-time device_put)
    epoch_fn, put_x = compile_epoch_aot(epoch_fn, state0, x, y, w)
    x = put_x(x)

    chain_epochs(epoch_fn, state0, x, y, w, 1)  # compile + lazy-runtime warmup
    # 5 repeats per endpoint for the headline: contended windows last minutes,
    # so more samples raise the odds of catching an uncontended one
    dt = least_contended_marginal(
        lambda k: chain_epochs(epoch_fn, state0, x, y, w, k), TIMED_EPOCHS,
        repeats=repeats,
    )

    n_chips = 1  # the folded site axis runs on one chip
    samples = S * steps * B
    return samples / dt / n_chips


def measure_cpu_baseline() -> float:
    """Live re-measurement of the torch reference (optional)."""
    import importlib.util

    import torch

    spec = importlib.util.spec_from_file_location(
        "ref_ica", "/root/reference/comps/icalstm/models.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    m = mod.ICALstm(input_size=ENC_OUT, hidden_size=HIDDEN, bidirectional=True,
                    num_cls=2, num_comps=COMPS, window_size=WLEN)
    opt = torch.optim.Adam(m.parameters(), lr=1e-3)
    crit = torch.nn.CrossEntropyLoss()
    B = 16
    x = torch.randn(B, WINDOWS, COMPS, WLEN)
    y = torch.randint(0, 2, (B,))
    for _ in range(2):
        opt.zero_grad(); out, _ = m(x); crit(out, y).backward(); opt.step()
    t = time.time()
    iters = 4
    for _ in range(iters):
        opt.zero_grad(); out, _ = m(x); crit(out, y).backward(); opt.step()
    return iters * B / (time.time() - t)


def main():
    baseline = CPU_BASELINE_SAMPLES_PER_SEC
    if "--live-baseline" in sys.argv:
        try:
            baseline = measure_cpu_baseline()
        except Exception:
            pass
    if "--ab-bidir" in sys.argv:
        # A/B the fused bidirectional pooled kernel against two
        # single-direction sweeps, same process, interleaved endpoints are
        # not needed — each arm uses the least-contended-minimum estimator.
        for arm, fused in (("fused-bidir", True), ("per-direction", False)):
            v = measure_tpu(fused_bidir=fused, repeats=3)
            print(json.dumps({
                "metric": f"samples/sec/chip (flagship, {arm})",
                "arm": arm, "value": round(v, 2),
                "unit": "samples/sec/chip",
                "mfu": round(v * flops_per_sample() / V5E_BF16_PEAK_FLOPS, 4),
            }), flush=True)
        return
    value = measure_tpu()
    print(json.dumps({
        "metric": "samples/sec/chip (ICA-LSTM, 32 sites, full federated round)",
        "value": round(value, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(value / baseline, 2),
        "mfu": round(value * flops_per_sample() / V5E_BF16_PEAK_FLOPS, 4),
    }))


if __name__ == "__main__":
    main()
