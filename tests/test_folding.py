"""Sites-per-device folding (VERDICT r2 #7: wire `sites_per_device`).

More simulated sites than devices: the trainer runs each device's site block
as an inner vmap nested in shard_map, with cross-site collectives spanning
the (mesh site, fold) axis pair (trainer/steps.py). These tests pin the folded
run against the one-site-per-device run and the all-on-one-device vmap run —
all three must produce identical training (SGD, so the assert is tight).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.parallel.mesh import host_mesh
from dinunet_implementations_tpu.trainer import (
    FederatedTask,
    init_train_state,
    make_optimizer,
    make_train_epoch_fn,
)
from dinunet_implementations_tpu.trainer.steps import make_eval_fn

needs_reference = pytest.mark.skipif(
    not os.path.isdir("/root/reference/datasets/test_fsl"),
    reason="reference fixture not mounted",
)


def _data(S=4, steps=3, B=6, F=10, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(S, steps, B, F)).astype(np.float32))
    y = jnp.asarray((rng.random((S, steps, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)
    return x, y, w


def _run(mesh, data, engine_name="dSGD", epochs=3, **engine_kw):
    model = MSANNet(in_size=10, hidden_sizes=(8, 6), out_size=2)
    task = FederatedTask(model)
    engine = make_engine(engine_name, **engine_kw)
    opt = make_optimizer("sgd", 1e-2)
    x, y, w = data
    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=x.shape[0]
    )
    fn = make_train_epoch_fn(task, engine, opt, mesh, local_iterations=1)
    losses = []
    for _ in range(epochs):
        state, ls = fn(state, x, y, w)
        losses.extend(np.asarray(ls).tolist())
    return jax.tree.map(np.asarray, state), losses


def _assert_states_match(a, b, atol=1e-6):
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(u, v, atol=atol), a.params, b.params
    )
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(u, v, atol=atol),
        a.batch_stats, b.batch_stats,
    )


@pytest.mark.slow
def test_folded_matches_per_device_and_vmap():
    """4 sites on a 2-device mesh (2 folded per device) == 4 sites on a
    4-device mesh == 4 sites vmapped on one device."""
    data = _data()
    s_fold, l_fold = _run(host_mesh(2), data)
    s_full, l_full = _run(host_mesh(4), data)
    s_vmap, l_vmap = _run(None, data)
    np.testing.assert_allclose(l_fold, l_full, atol=1e-6)
    np.testing.assert_allclose(l_fold, l_vmap, atol=1e-6)
    _assert_states_match(s_fold, s_full)
    _assert_states_match(s_fold, s_vmap)


@pytest.mark.slow
def test_folded_rankdad_matches_per_device():
    """rankDAD's factor all_gather must span the (site, fold) axis pair
    (parallel/collectives.py site_all_gather tuple path)."""
    data = _data(seed=1)
    kw = dict(dad_reduction_rank=6, dad_num_pow_iters=3, dad_tol=1e-3)
    s_fold, l_fold = _run(host_mesh(2), data, "rankDAD", **kw)
    s_full, l_full = _run(host_mesh(4), data, "rankDAD", **kw)
    np.testing.assert_allclose(l_fold, l_full, atol=1e-5)
    _assert_states_match(s_fold, s_full, atol=1e-5)


@pytest.mark.slow
def test_folded_powersgd_keeps_per_site_error_feedback():
    """powerSGD's error-feedback residual is per-site engine state; folding
    must keep one residual per SITE (not per device)."""
    data = _data(seed=2)
    kw = dict(dad_reduction_rank=2)
    s_fold, l_fold = _run(host_mesh(2), data, "powerSGD", **kw)
    s_full, l_full = _run(host_mesh(4), data, "powerSGD", **kw)
    np.testing.assert_allclose(l_fold, l_full, atol=1e-5)
    _assert_states_match(s_fold, s_full, atol=1e-5)
    # engine state itself must agree site-for-site
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(u, v, atol=1e-5),
        s_fold.engine_state, s_full.engine_state,
    )


def test_folded_eval_matches_per_device():
    data = _data(seed=3)
    x, y, w = data
    state, _ = _run(host_mesh(4), data, epochs=1)
    model = MSANNet(in_size=10, hidden_sizes=(8, 6), out_size=2)
    task = FederatedTask(model)
    task.init_variables(jax.random.PRNGKey(0), x[0, 0])
    pf, lf, wf = make_eval_fn(task, host_mesh(2))(state, x, y, w)
    pd, ld, wd = make_eval_fn(task, host_mesh(4))(state, x, y, w)
    np.testing.assert_allclose(np.asarray(pf), np.asarray(pd), atol=1e-6)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(ld), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(wf), np.asarray(wd))


@pytest.mark.slow
@needs_reference
def test_fed_runner_sites_per_device(tmp_path):
    """cfg.sites_per_device=5 folds the 5-site FS fixture onto a 1-device
    site mesh; results still come out per site."""
    from dinunet_implementations_tpu.core.config import TrainConfig
    from dinunet_implementations_tpu.runner.fed_runner import FedRunner

    cfg = TrainConfig(
        task_id="FS-Classification", epochs=2, batch_size=8,
        sites_per_device=5, split_ratio=(0.6, 0.2, 0.2), num_class=2,
    )
    runner = FedRunner(
        cfg, data_path="/root/reference/datasets/test_fsl",
        out_dir=str(tmp_path / "out"),
    )
    assert dict(runner.mesh.shape)["site"] == 1
    results = runner.run(verbose=False)
    assert len(results[0]["site_test_metrics"]) == 5
    assert np.isfinite(results[0]["test_metrics"][0][0])


@needs_reference
def test_fed_runner_rejects_nondivisible_fold(tmp_path):
    from dinunet_implementations_tpu.core.config import TrainConfig
    from dinunet_implementations_tpu.runner.fed_runner import FedRunner

    with pytest.raises(ValueError, match="sites_per_device"):
        FedRunner(
            TrainConfig(sites_per_device=2),
            data_path="/root/reference/datasets/test_fsl",
        )


@pytest.mark.slow
def test_folded_eval_with_model_axis():
    """Eval on a (2 site × 2 model) mesh with 4 sites folded 2-per-device —
    the one folding/model-axis combination the train tests don't cover."""
    from dinunet_implementations_tpu.models import ICALstm
    from dinunet_implementations_tpu.parallel.mesh import MODEL_AXIS

    rng = np.random.default_rng(7)
    S, steps, B = 4, 2, 4
    x = jnp.asarray(rng.normal(size=(S, steps, B, 8, 3, 4)).astype(np.float32))
    y = jnp.asarray((rng.random((S, steps, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)

    dense = ICALstm(input_size=12, hidden_size=10, num_comps=3, window_size=4,
                    num_cls=2)
    ring = dense.clone(sequence_axis=MODEL_AXIS)
    t_dense, t_ring = FederatedTask(dense), FederatedTask(ring)
    # resolves has_batch_stats for the ring task (dense's resolves inside
    # init_train_state below)
    t_ring.init_variables(jax.random.PRNGKey(0), x[0, 0])

    state = init_train_state(
        t_dense, make_engine("dSGD"), make_optimizer("sgd", 1e-2),
        jax.random.PRNGKey(0), x[0, 0], num_sites=S,
    )
    pd, ld, wd = make_eval_fn(t_dense, None)(state, x, y, w)
    state_np = jax.tree.map(np.asarray, state)
    pc, lc, wc = make_eval_fn(t_ring, host_mesh(2, model_axis_size=2))(
        state_np, x, y, w
    )
    np.testing.assert_allclose(np.asarray(pc), np.asarray(pd), atol=2e-5)
    np.testing.assert_allclose(np.asarray(lc), np.asarray(ld), atol=1e-4)
    np.testing.assert_array_equal(np.asarray(wc), np.asarray(wd))
