"""ICA-timecourse dataset (fMRI windowed classification).

Reference semantics (``comps/icalstm/__init__.py:16-38,73-77``):

- inventory = ``[data_index, label]`` rows of the labels CSV;
- the data file is a numpy array ``[subjects, components, temporal]``
  (loaded with ``np.load``; despite the fixture's ``.npz`` name the reference
  indexes ``.shape`` directly, i.e. a raw array — we accept both npz and npy);
- each subject is sliced into ``temporal_size // window_size`` windows; window
  ``j`` covers ``[j*window_stride, j*window_stride + window_size)``. NOTE the
  window *count* is derived from ``window_size`` even when ``window_stride``
  differs — overlapping windows leave the tail uncovered. This is the
  reference's behavior (``comps/icalstm/__init__.py:28-33``) and is kept
  bit-for-bit; sample shape is ``[S, C, W]``.
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .api import DataHandle, SiteArrays, SiteDataset


def load_timecourses(path: str) -> np.ndarray:
    """Load the ``[subjects, components, temporal]`` array from .npy/.npz."""
    data = np.load(path)
    if isinstance(data, np.lib.npyio.NpzFile):
        data = data[list(data.files)[0]]
    return np.asarray(data)


def window_timecourses(
    data: np.ndarray, temporal_size: int, window_size: int, window_stride: int
) -> np.ndarray:
    """Slice ``[N, C, T]`` → ``[N, S, C, W]`` with the reference's windowing
    rule (count from window_size, offset from stride)."""
    samples_per_sub = int(temporal_size / window_size)
    n, c, _ = data.shape
    out = np.zeros((n, samples_per_sub, c, window_size), data.dtype)
    for j in range(samples_per_sub):
        lo = j * window_stride
        out[:, j, :, :] = data[:, :, lo : lo + window_size]
    return out


class ICADataset(SiteDataset):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.data = None
        self.window_size = self.cache["window_size"]
        self.window_stride = self.cache["window_stride"]
        self.temporal_size = self.cache["temporal_size"]
        self.num_components = self.cache["num_components"]

    def _load_indices(self, files, **kw):
        data = load_timecourses(self.path(cache_key="data_file"))
        self.data = window_timecourses(
            data, self.temporal_size, self.window_size, self.window_stride
        ).astype(np.float32)
        self.indices += [list(f) for f in files]

    def __getitem__(self, ix) -> dict:
        data_index, y = self.indices[ix]
        return {"inputs": self.data[int(data_index)], "labels": int(y), "ix": ix}

    def as_arrays(self) -> SiteArrays:
        rows = np.asarray([int(i) for i, _ in self.indices])
        return SiteArrays(
            self.data[rows],
            np.asarray([int(y) for _, y in self.indices], np.int32),
            np.arange(len(rows), dtype=np.int32),
        )


class ICADataHandle(DataHandle):
    """Inventory = ``[index, label]`` rows of the labels CSV
    (reference ``comps/icalstm/__init__.py:73-77``)."""

    def list_files(self) -> list:
        path = os.path.join(self.state["baseDirectory"], self.cache["labels_file"])
        with open(path, newline="") as fh:
            reader = csv.reader(fh)
            next(reader)  # header
            return [[int(float(r[0])), int(float(r[1]))] for r in reader if r]
