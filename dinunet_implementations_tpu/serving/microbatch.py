"""Continuous microbatcher — the request-queue half of the serving path.

One :class:`Microbatcher` per lane (batched inference / streaming step): a
thread-safe queue plus a single dispatch thread that coalesces requests under
a **max-batch / max-delay** admission rule — a dispatch fires as soon as the
pending rows fill the largest shape bucket, or when the OLDEST pending
request has waited ``max_delay_ms``, whichever comes first. The dispatch
callback (serving/engine.py) pads the collected requests into the smallest
bucket that fits and runs ONE pre-compiled executable — the request path
never traces or compiles, whatever the traffic pattern (that is the point of
bucketing: the compiled-shape set is closed at warmup).

Admission details that matter:

- **Priority lanes over arrival order (r21)**: collection picks the
  highest-``priority`` pending request first, oldest-first within a
  priority — with every request at the default priority 0 this is exactly
  the pre-r21 FIFO. Priorities reorder only what is CONCURRENTLY pending;
  nothing starves forever because a batch ends at the first request that
  doesn't fit (see below), bounding how far a big low-priority request can
  be overtaken.
- **Deadline shedding (r21)**: a request carrying ``deadline_ms`` that is
  staler than that at collection time is SHED — its future raises
  :class:`RequestError` immediately instead of wasting a dispatch slot on
  an answer the client already gave up on. ``max_queue`` sheds at ADMISSION
  (submit raises) once the lane's depth hits the bound — backpressure
  before queueing, not after.
- **Conflict deferral**: requests dispatch in admission order, except a
  request whose ``conflict_key`` collides with one already collected (two
  chunks of the SAME streaming session — the second must see the first's
  updated carry) stays pending for the next dispatch, preserving order.
- **No oversize silently**: a request bigger than the largest bucket is
  rejected at submit with a clear error — splitting is the caller's policy
  decision (the engine's ``stream()`` splits long window runs into
  chunk-bucket pieces before submitting).
- The dispatch thread is a **daemon** and closes via sentinel, so a crashed
  caller never wedges interpreter shutdown (the with_retry lesson, r13).

``max_delay_s`` is a plain mutable attribute on purpose: the p99-targeted
autotuner (serving/admission.py) retunes it live between dispatches.
"""

from __future__ import annotations

import concurrent.futures as _futures
import queue
import threading
import time


class ServingClosed(RuntimeError):
    """Submit after close()."""


class RequestError(RuntimeError):
    """A request the serving path cannot admit (oversize, bad shape)."""


class RequestFuture(_futures.Future):
    """The stdlib future with a bounded default wait: a serving client that
    forgets a timeout hangs 30 s and gets a clear ``TimeoutError``, not a
    forever-block on a lost dispatch."""

    def result(self, timeout: float | None = 30.0):
        return super().result(timeout)


class ChainedFuture:
    """A future over an in-order CHAIN of requests (a multi-chunk
    ``stream()`` call): ``result()`` waits the chain and raises the FIRST
    link's error — an early chunk's dispatch failure must surface, never be
    masked by a later chunk happening to succeed on a carry that silently
    missed the failed chunk's windows."""

    def __init__(self, links: list):
        self._links = links

    def done(self) -> bool:
        return all(f.done() for f in self._links)

    def result(self, timeout: float | None = 30.0):
        out = None
        for f in self._links:
            out = f.result(timeout)
        return out


class Microbatcher:
    """One serving lane's queue + dispatch thread (see module docstring).

    ``dispatch(requests, bucket)`` receives the collected request objects and
    the chosen bucket (row capacity); it must resolve every request's
    ``future``. ``rows_of(req)`` counts a request's bucket rows (samples for
    the batched lane, 1 session for the streaming lane); ``conflict_key``
    (optional) serializes requests that must not share a dispatch."""

    def __init__(self, dispatch, buckets, *, rows_of=None, conflict_key=None,
                 max_delay_ms: float = 2.0, max_queue: int | None = None,
                 name: str = "lane", on_dispatch=None, bus=None,
                 labels: dict | None = None):
        from ..telemetry.bus import NULL_BUS

        if not buckets:
            raise ValueError("need at least one shape bucket")
        self.dispatch = dispatch
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.rows_of = rows_of or (lambda req: len(req.rows))
        self.conflict_key = conflict_key
        self.max_delay_s = max_delay_ms / 1e3
        self.max_queue = max_queue
        self.name = name
        self.on_dispatch = on_dispatch
        self.bus = bus if bus is not None else NULL_BUS
        # extra label set on every bus series this lane publishes (a fleet
        # replica's {"replica": "<slot>"} — per-replica /metrics series)
        self.labels = dict(labels or {})
        self._q: queue.Queue = queue.Queue()
        # admission-ordered requests awaiting collection; owned by the
        # dispatch thread (submit only touches the queue)
        self._pending: list = []
        self._sentinel = False
        self._seq = 0
        self._closed = False
        self._stats_lock = threading.Lock()
        self.stats = {
            "requests": 0, "dispatches": 0, "rows": 0, "pad_rows": 0,
            "bucket_hits": 0, "rejected": 0, "max_queue_depth": 0,
            "deferrals": 0, "shed": 0,
        }
        self._thread = threading.Thread(
            target=self._run, name=f"microbatch-{name}", daemon=True
        )
        self._thread.start()

    @property
    def max_rows(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        for b in self.buckets:
            if rows <= b:
                return b
        raise RequestError(
            f"{self.name}: request needs {rows} rows but the largest "
            f"compiled bucket is {self.max_rows} — split the request or "
            f"serve with a bigger bucket set"
        )

    def submit(self, req) -> None:
        if self._closed:
            raise ServingClosed(f"{self.name}: microbatcher is closed")
        rows = self.rows_of(req)
        if rows > self.max_rows:
            self.stats["rejected"] += 1
            raise RequestError(
                f"{self.name}: request of {rows} rows exceeds the largest "
                f"bucket ({self.max_rows})"
            )
        if self.max_queue is not None and self.depth() >= self.max_queue:
            # load shedding at ADMISSION: past the depth bound the caller
            # hears "no" immediately instead of queueing into a latency
            # cliff (the answer would blow its deadline anyway)
            self._note_shed("queue_full")
            raise RequestError(
                f"{self.name}: queue full ({self.max_queue} pending) — "
                f"request shed at admission"
            )
        req._submit_t = time.monotonic()
        with self._stats_lock:
            self._seq += 1
            req._seq = self._seq
        self._q.put(req)
        # peak depth must be sampled at ENQUEUE too: sampling only at
        # dispatch time (the pre-r16 behavior) under-reported any burst that
        # arrived and drained between two dispatches
        self._note_depth()

    def depth(self) -> int:
        """Instantaneous queue depth (queued + collection-pending requests)
        — the ONE definition /statusz, drain() and the peak sampler share."""
        return self._q.qsize() + len(self._pending)

    def _note_depth(self) -> int:
        depth = self.depth()
        with self._stats_lock:
            if depth > self.stats["max_queue_depth"]:
                self.stats["max_queue_depth"] = depth
        self.bus.gauge(
            "serving_queue_depth", depth, lane=self.name, **self.labels
        )
        return depth

    # -- dispatch thread -------------------------------------------------

    @staticmethod
    def _order(req) -> tuple:
        """Collection order: highest priority first, then admission order
        (all-default-priority traffic is exactly the pre-r21 FIFO)."""
        return (-getattr(req, "priority", 0), getattr(req, "_seq", 0))

    def _fill(self, block: bool) -> None:
        """Move queued requests into ``_pending`` (optionally blocking for
        the first); latches ``_sentinel`` when close() is seen."""
        if block and not self._sentinel:
            item = self._q.get()
            if item is None:
                self._sentinel = True
            else:
                self._pending.append(item)
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return
            if item is None:
                self._sentinel = True
            else:
                self._pending.append(item)

    def _shed_expired(self) -> None:
        """Deadline admission: fail (don't dispatch) any pending request
        already staler than its own ``deadline_ms``."""
        now = time.monotonic()
        keep = []
        for r in self._pending:
            d = getattr(r, "deadline_ms", None)
            if d is not None and now > r._submit_t + d / 1e3:
                self._note_shed("deadline")
                r.future.set_exception(RequestError(
                    f"{self.name}: request shed — waited "
                    f"{(now - r._submit_t) * 1e3:.1f} ms, past its "
                    f"{d} ms deadline"
                ))
            else:
                keep.append(r)
        self._pending = keep

    def _pick(self, keys: set, space: int, counted: set) -> tuple:
        """``(request, stop)``: pop the best eligible pending request
        (:meth:`_order`, skipping conflicts). ``stop=True`` when the best
        eligible does not fit ``space`` — the batch ends there (order
        fairness: a big request is deferred at most one dispatch, never
        overtaken indefinitely by smaller later arrivals)."""
        best_i = None
        for i, r in enumerate(self._pending):
            if (self.conflict_key is not None and keys
                    and self.conflict_key(r) in keys):
                if r._seq not in counted:
                    counted.add(r._seq)
                    self._note_deferral("conflict")
                continue
            if best_i is None or (
                    self._order(r) < self._order(self._pending[best_i])):
                best_i = i
        if best_i is None:
            return None, False
        r = self._pending[best_i]
        if self.rows_of(r) > space:
            if r._seq not in counted:
                counted.add(r._seq)
                self._note_deferral("overflow")
            return None, True
        return self._pending.pop(best_i), False

    def _collect(self) -> list:
        """Admission: pick the best pending request, then grow the batch
        until the largest bucket is full or that FIRST request's max-delay
        budget runs out (shedding expired requests as they surface)."""
        counted: set = set()
        keys: set = set()
        first, _ = self._pick(keys, self.max_rows, counted)
        if first is None:
            return []
        batch = [first]
        rows = self.rows_of(first)
        if self.conflict_key is not None:
            keys.add(self.conflict_key(first))
        deadline = first._submit_t + self.max_delay_s
        while rows < self.max_rows:
            nxt, stop = self._pick(keys, self.max_rows - rows, counted)
            if stop:
                break
            if nxt is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._sentinel:
                    break
                try:
                    item = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if item is None:
                    self._sentinel = True
                    break
                self._pending.append(item)
                self._fill(block=False)
                self._shed_expired()
                continue
            batch.append(nxt)
            rows += self.rows_of(nxt)
            if self.conflict_key is not None:
                keys.add(self.conflict_key(nxt))
        return batch

    def _note_deferral(self, why: str) -> None:
        with self._stats_lock:
            self.stats["deferrals"] += 1
        self.bus.counter(
            "serving_deferrals_total", lane=self.name, why=why,
            **self.labels,
        )

    def _note_shed(self, why: str) -> None:
        with self._stats_lock:
            self.stats["shed"] += 1
        self.bus.counter(
            "serving_shed_total", lane=self.name, why=why, **self.labels
        )

    def _run(self) -> None:
        while True:
            if not self._pending:
                if self._sentinel:
                    return
                self._fill(block=True)
            else:
                self._fill(block=False)
            self._shed_expired()
            if not self._pending:
                continue
            batch = self._collect()
            if not batch:
                continue
            rows = sum(self.rows_of(r) for r in batch)
            try:
                bucket = self.bucket_for(rows)
                depth = self._note_depth()
                self.dispatch(batch, bucket)
                self.stats["requests"] += len(batch)
                self.stats["dispatches"] += 1
                self.stats["rows"] += rows
                self.stats["pad_rows"] += bucket - rows
                self.stats["bucket_hits"] += int(rows == bucket)
                self.bus.counter(
                    "serving_dispatches_total", lane=self.name, **self.labels
                )
                self.bus.observe(
                    "serving_batch_occupancy_pct", 100.0 * rows / bucket,
                    lane=self.name, **self.labels,
                )
                if self.on_dispatch is not None:
                    self.on_dispatch(self.name, batch, bucket, rows, depth)
            except Exception as e:
                # the dispatch thread must never die silently: every
                # collected request's waiter gets the error, and the loop
                # keeps serving the next batch
                for r in batch:
                    if not r.future.done():
                        r.future.set_exception(e)

    def close(self, timeout: float = 10.0) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout)
