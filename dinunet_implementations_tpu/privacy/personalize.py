"""Personalized per-site heads — a param-path partition mask (FedProx-style).

``TrainConfig.personalize`` names head leaves by path-substring patterns
(e.g. ``("fc_out",)`` for MSANNet's classifier, ``("cls_fc3",)`` for the
ICA-LSTM head). Matched leaves are PARTITIONED OUT of aggregation entirely:

- the global ``TrainState.params`` tree keeps its full structure (optimizer
  state and checkpoints stay schema-stable), but matched leaves FREEZE at
  init — the aggregated gradient carries exact zeros there, so Adam's
  moments stay zero and the global copy never moves;
- each site's REAL head lives in ``TrainState.personal`` — ``{"params":
  head-subtree with [S, ...] leaves, "opt": per-site optimizer state}`` —
  sharded ``P(site)`` like health, checkpointed (R006 covers the field),
  rejoin-reset via ``reset_slot_state`` (a new generation restarts from
  the CURRENT global head copy with a fresh optimizer row, never a
  previous tenant's personalized one), and donation-safe distinct
  buffers;
- the per-site forward runs on ``merge_head(global, personal_row)``; the
  head gradient updates the site's own row with its own optimizer instance
  (same optimizer family/learning rate as the global one), gated on the
  round's contribute mask exactly like engine state — a dead site's head
  freezes;
- engines aggregate (and model wire bytes for) the SHARED subtree only —
  the head bytes leave the wire entirely, proven by S002 when a
  personalized cell is traced;
- eval is per-site by construction: ``make_eval_fn`` merges each site's row
  before the forward, and the per-site scores land in each
  ``local{i}/logs.json`` via the existing per-site test metrics.

``personalize=()`` (default) builds none of this — the epoch program is
lowering-identical to the legacy one (S005 "personalize-off").
"""

from __future__ import annotations

import jax


def leaf_path_of(keypath) -> tuple:
    """THE jax-keypath → tuple-of-string-keys normalizer every privacy/
    membership consumer shares (dpsgd's skip paths, the rejoin head
    lookup) — one definition, so path matching cannot drift between
    modules."""
    out = []
    for k in keypath:
        out.append(str(getattr(k, "key", getattr(k, "idx", k))))
    return tuple(out)


_path_of = leaf_path_of


def head_leaf_paths(params, patterns) -> frozenset:
    """The partition mask: leaf paths (tuples of keys) whose "/"-joined form
    contains any pattern substring. Rejects a mask that matches nothing
    (silent no-op) or everything (no shared model left to federate)."""
    patterns = tuple(p for p in patterns if p)
    if not patterns:
        return frozenset()
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    all_paths = [_path_of(kp) for kp, _ in leaves]
    hit = frozenset(
        p for p in all_paths if any(pat in "/".join(p) for pat in patterns)
    )
    if not hit:
        raise ValueError(
            f"personalize patterns {patterns} match no parameter leaf "
            f"(have e.g. {['/'.join(p) for p in all_paths[:6]]})"
        )
    if len(hit) == len(all_paths):
        raise ValueError(
            f"personalize patterns {patterns} match EVERY parameter leaf — "
            "nothing would be federated"
        )
    return hit


def strip_tree(tree, paths: frozenset, keep_head: bool):
    """The head subtree (``keep_head=True``) or the shared subtree
    (``keep_head=False``) of a params-shaped tree, as a nested dict
    containing only the kept leaves — empty branches pruned, so the engine
    and wire models see exactly the shipped structure."""
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out: dict = {}
    for kp, leaf in leaves:
        path = _path_of(kp)
        if (path in paths) != keep_head:
            continue
        node = out
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = leaf
    return out


def merge_head(full_tree, head_subtree):
    """Full params with the head subtree's leaves swapped in (one site's
    row). The subtree's nesting mirrors :func:`strip_tree`'s output."""
    leaves = jax.tree_util.tree_flatten_with_path(head_subtree)[0]
    merged = full_tree
    for kp, leaf in leaves:
        merged = _set_path(merged, _path_of(kp), leaf)
    return merged


def _set_path(tree, path: tuple, leaf):
    if len(path) == 1:
        return {**tree, path[0]: leaf}
    return {**tree, path[0]: _set_path(tree[path[0]], path[1:], leaf)}


def zero_head(full_tree, paths: frozenset):
    """Full tree with head leaves replaced by zeros — the aggregated
    gradient's form, so the global optimizer provably never moves the
    frozen global head copies (zero grad → zero Adam moments → zero
    update)."""
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten_with_path(full_tree)
    out = [
        jnp.zeros_like(leaf) if _path_of(kp) in paths else leaf
        for kp, leaf in leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def graft_shared(full_template, shared_subtree, paths: frozenset):
    """Rebuild a full-structure tree from the engine's SHARED-subtree
    aggregate: shared leaves from the aggregate, head leaves zero (see
    :func:`zero_head`)."""
    import jax.numpy as jnp

    shared_leaves = {
        _path_of(kp): leaf
        for kp, leaf in jax.tree_util.tree_flatten_with_path(shared_subtree)[0]
    }
    leaves, treedef = jax.tree_util.tree_flatten_with_path(full_template)
    out = []
    for kp, leaf in leaves:
        path = _path_of(kp)
        out.append(
            jnp.zeros_like(leaf) if path in paths
            else shared_leaves[path].astype(leaf.dtype)
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def personal_row_template(params, paths: frozenset, optimizer):
    """One site's fresh personal state: the head subtree (initialized from
    the global init, so personalization starts from the common model) plus
    its own optimizer state. Stacked per site by
    :func:`default_personal`."""
    head = strip_tree(params, paths, keep_head=True)
    return {"params": head, "opt": optimizer.init(head)}


def default_personal(num_sites: int, params, paths: frozenset, optimizer):
    """Fresh ``TrainState.personal``: every leaf stacked to the ``[S, ...]``
    per-site axis — distinct arrays, so state donation never aliases a
    buffer twice."""
    import jax.numpy as jnp

    row = personal_row_template(params, paths, optimizer)
    return jax.tree.map(lambda a: jnp.stack([a] * num_sites), row)
