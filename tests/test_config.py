"""Config system tests (reference parity: compspec.json + inputspec.json)."""

import json

from dinunet_implementations_tpu import (
    AggEngine,
    NNComputation,
    TrainConfig,
    export_compspec,
    load_inputspec,
)


def test_defaults_match_reference_compspec():
    """Defaults mirror reference compspec.json:32-224."""
    cfg = TrainConfig()
    assert cfg.task_id == "FS-Classification"
    assert cfg.mode == "train"
    assert cfg.agg_engine == "dSGD"
    assert cfg.batch_size == 16
    assert cfg.local_iterations == 1
    assert cfg.learning_rate == 1e-3
    assert cfg.epochs == 101
    assert cfg.precision_bits == "32"
    assert cfg.patience == 35
    assert cfg.split_ratio == (0.8, 0.1, 0.1)
    assert cfg.num_folds is None
    assert cfg.fs_args.input_size == 66
    assert cfg.fs_args.hidden_sizes == (256, 128, 64, 32)
    assert cfg.fs_args.num_class == 2
    assert cfg.fs_args.dad_reduction_rank == 10
    assert cfg.fs_args.dad_num_pow_iters == 5
    assert cfg.fs_args.dad_tol == 1e-3
    assert cfg.ica_args.window_size == 10
    # the workload value (datasets/icalstm/inputspec.json, both sites), not the
    # compspec template's 384 — config, bench, and fixtures must agree
    assert cfg.ica_args.hidden_size == 348
    assert cfg.ica_args.seq_len == 13  # dead compspec field, kept for parity


def test_defaults_match_reference_ica_inputspec():
    """Pin ICA defaults against the reference's actual shipped inputspec."""
    import json as _json

    with open("/root/reference/datasets/icalstm/inputspec.json") as f:
        spec = _json.load(f)
    cfg = TrainConfig()
    for site in spec:
        assert cfg.ica_args.hidden_size == site["hidden_size"]["value"]
        assert cfg.ica_args.input_size == site["input_size"]["value"]
        assert cfg.ica_args.window_size == site["window_size"]["value"]
        assert cfg.ica_args.window_stride == site["window_stride"]["value"]
        assert cfg.ica_args.temporal_size == site["temporal_size"]["value"]
        assert cfg.ica_args.num_components == site["num_components"]["value"]


def test_registry_enums():
    assert NNComputation.TASK_FREE_SURFER == "FS-Classification"
    assert NNComputation.TASK_ICA == "ICA-Classification"
    assert AggEngine.DECENTRALIZED_SGD == "dSGD"
    assert AggEngine.RANK_DAD == "rankDAD"
    assert AggEngine.POWER_SGD == "powerSGD"


def test_with_overrides_routes_task_args():
    cfg = TrainConfig().with_overrides(
        {"batch_size": 32, "input_size": 100, "hidden_sizes": [64, 32], "window_size": 20}
    )
    assert cfg.batch_size == 32
    assert cfg.fs_args.input_size == 100
    assert cfg.fs_args.hidden_sizes == (64, 32)
    assert cfg.ica_args.input_size == 100  # shared field name lands in both blocks
    assert cfg.ica_args.window_size == 20


def test_load_inputspec(tmp_path):
    spec = [
        {"labels_file": {"value": "site1_Covariate.csv"}, "input_size": {"value": 66}},
        {"labels_file": {"value": "site2_Covariate.csv"}, "input_size": {"value": 66}},
    ]
    p = tmp_path / "inputspec.json"
    p.write_text(json.dumps(spec))
    sites = load_inputspec(str(p))
    assert len(sites) == 2
    assert sites[0]["labels_file"] == "site1_Covariate.csv"
    assert sites[1]["input_size"] == 66


def test_load_reference_fixture_inputspec():
    """Our loader parses the reference's actual simulator spec unchanged."""
    sites = load_inputspec("/root/reference/datasets/test_fsl/inputspec.json")
    assert len(sites) == 5
    for i, s in enumerate(sites):
        assert s["data_column"] == "freesurferfile"
        assert s["labels_column"] == "isControl"
        assert s["input_size"] == 66
        assert s["hidden_sizes"] == [256, 128, 64, 32]
    cfg = TrainConfig().with_overrides(sites[0])
    assert cfg.fs_args.labels_file == "site1_Covariate.csv"
    assert cfg.fs_args.hidden_sizes == (256, 128, 64, 32)


def test_export_compspec_roundtrip():
    spec = export_compspec()
    inputs = spec["computation"]["input"]
    assert inputs["task_id"]["default"] == "FS-Classification"
    assert inputs["agg_engine"]["conditional"] == {"variable": "mode", "value": "train"}
    assert inputs["FS-Classification_args"]["default"]["dad_reduction_rank"] == 10
    json.dumps(spec)  # must be JSON-serializable


def test_block_dict_overrides():
    """Review finding: dict overrides for dataclass-typed fields must merge."""
    cfg = TrainConfig().with_overrides({"pretrain_args": {"epochs": 5}})
    assert cfg.pretrain_args.epochs == 5
    assert cfg.pretrain_args.patience == 51  # default preserved
    cfg = TrainConfig().with_overrides({"fs_args": {"input_size": 99}})
    assert cfg.fs_args.input_size == 99
    assert cfg.fs_args.hidden_sizes == (256, 128, 64, 32)
    cfg = TrainConfig().with_overrides({"FS-Classification_args": {"input_size": 42}})
    assert cfg.fs_args.input_size == 42


def test_all_tasks_have_args():
    for task in NNComputation.ALL:
        args = TrainConfig(task_id=task).task_args()
        assert args.num_class == 2


def test_resolve_site_configs_cycles():
    import dinunet_implementations_tpu as dt

    cfgs = dt.resolve_site_configs(TrainConfig(), "/root/reference/datasets/icalstm", num_sites=4)
    # 2-entry spec cycles 0,1,0,1 — entry 1 has no data_file, entry 0 does
    assert cfgs[0].ica_args.data_file == cfgs[2].ica_args.data_file == "HCP_AllData_sess1.npz"
    assert cfgs[1].ica_args.hidden_size == 348


def test_with_overrides_keeps_unset_pretrain_args_none():
    cfg = TrainConfig().with_overrides({"batch_size": 8})
    assert cfg.pretrain_args is None
