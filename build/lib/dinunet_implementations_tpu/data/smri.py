"""Structural-MRI (T1w volume) dataset — TPU-build extension.

Follows the ICA dataset's fixture convention (data/ica.py): a numpy archive of
volumes ``[N, D, H, W]`` named by ``data_file`` plus a ``labels_file`` CSV of
``[index, label]`` rows; no reference implementation exists (BASELINE.json
configs list the 3D-CNN sMRI federated classifier as a target workload).
"""

from __future__ import annotations

import numpy as np

from .api import SiteArrays, SiteDataset
from .ica import ICADataHandle, load_timecourses


class SMRIDataset(SiteDataset):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.data = None

    def _load_indices(self, files, **kw):
        self.data = np.asarray(
            load_timecourses(self.path(cache_key="data_file")), np.float32
        )
        self.indices += [list(f) for f in files]

    def __getitem__(self, ix) -> dict:
        data_index, y = self.indices[ix]
        return {"inputs": self.data[int(data_index)], "labels": int(y), "ix": ix}

    def as_arrays(self) -> SiteArrays:
        rows = np.asarray([int(i) for i, _ in self.indices])
        return SiteArrays(
            self.data[rows],
            np.asarray([int(y) for _, y in self.indices], np.int32),
            np.arange(len(rows), dtype=np.int32),
        )


class SMRIDataHandle(ICADataHandle):
    """Same ``[index, label]`` CSV inventory as the ICA handle."""
