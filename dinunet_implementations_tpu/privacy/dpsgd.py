"""In-scan DP-SGD — per-site clipping + calibrated Gaussian noise.

The transform runs inside the per-site phase of the rounds scan
(trainer/steps.py ``site_micro``), on the site's finished round gradient,
BEFORE any engine compression and before a hostile site's AttackPlan
transform (an attacker lies about what it ships; an honest site's DP
mechanism runs first): clip the gradient's global L2 norm to
``dp_clip`` (C), then add ``dp_noise_multiplier·C`` (σ·C) of Gaussian noise
per leaf. What leaves the site — the engine payload, dense or factored —
is then a bounded-sensitivity, noised quantity; the accountant
(privacy/accounting.py) converts the (σ, q, rounds) trajectory to (ε, δ)
— composing at the CONSERVATIVE effective multiplier σ/2, because this
mechanism clips the round-MEAN gradient (record-level sensitivity of
clip(mean) is 2C), not the textbook per-example-clipped sum
(accounting.py MEAN_CLIP_SENSITIVITY_FACTOR).

Determinism contract (the AttackPlan-noise pattern, robustness/attacks.py):
noise is drawn from counter-based keys ``fold_in(fold_in(fold_in(
PRNGKey(dp_seed), site), round), leaf)`` — ``site`` the GLOBAL virtual site
id (``jax.lax.axis_index`` over the bound site axes, identical under
packing and the vmap fold) and ``round`` the global round counter — so the
noise replays bit-identically regardless of epoch chunking, resume point,
or site-packing factor.

Off-state contract: ``dp_clip == 0 and dp_noise_multiplier == 0`` builds no
transform at all — the epoch program is lowering-identical to the legacy
one (S005 "dp-off", checks/semantic.py). Noise without clipping has no
finite sensitivity, hence no DP guarantee: ``dp_noise_multiplier > 0``
REQUIRES ``dp_clip > 0`` (rejected at build). Clipping alone
(``dp_noise_multiplier == 0``) is allowed — a robustness transform with
ε = ∞, reported as such.

Personalized heads (privacy/personalize.py): leaves named by the partition
mask never leave the site, so the mechanism skips them — the clip norm is
computed over, and noise added to, the SHARED (shipped) leaves only.
"""

from __future__ import annotations


def dp_enabled(dp_clip: float, dp_noise_multiplier: float) -> bool:
    """Whether the DP transform exists in the program (trace-time static)."""
    if float(dp_noise_multiplier) < 0.0:
        raise ValueError(
            f"dp_noise_multiplier must be >= 0, got {dp_noise_multiplier}"
        )
    if float(dp_clip) < 0.0:
        raise ValueError(f"dp_clip must be >= 0, got {dp_clip}")
    if float(dp_noise_multiplier) > 0.0 and float(dp_clip) <= 0.0:
        raise ValueError(
            "dp_noise_multiplier > 0 needs dp_clip > 0: noise without a "
            "clipped sensitivity carries no DP guarantee (set dp_clip)"
        )
    return float(dp_clip) > 0.0


def make_dp_fn(dp_clip: float, dp_noise_multiplier: float, dp_seed: int = 0,
               skip_paths: frozenset = frozenset()):
    """Build the traced per-site DP transform, or ``None`` when off.

    Returns ``dp(site_grad, rnd, site_ix) -> site_grad`` on ONE site's
    (unbatched) gradient pytree: ``rnd`` the global round counter,
    ``site_ix`` the global virtual site id — both traced; the clip norm and
    noise scale are trace-time statics closed over from the config.
    ``skip_paths`` names personalized-head leaves (tuple-of-keys paths,
    privacy/personalize.py) excluded from both the clip norm and the noise
    — they never ship, so the mechanism has nothing to protect there."""
    if not dp_enabled(dp_clip, dp_noise_multiplier):
        return None
    import jax
    import jax.numpy as jnp

    clip = float(dp_clip)
    sigma = float(dp_noise_multiplier)
    seed = int(dp_seed)

    def dp(site_grad, rnd, site_ix):
        from .personalize import leaf_path_of

        leaves_p, treedef = jax.tree_util.tree_flatten_with_path(site_grad)
        shared = [
            (i, kp, g) for i, (kp, g) in enumerate(leaves_p)
            if leaf_path_of(kp) not in skip_paths
        ]
        gsq = jnp.zeros((), jnp.float32)
        for _, _, g in shared:
            gsq = gsq + jnp.sum(jnp.square(g.astype(jnp.float32)))
        norm = jnp.sqrt(gsq)
        # multiplicative clip: min(1, C/‖g‖); the max() guard keeps a zero
        # gradient at scale 1 instead of 0/0
        scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-30))
        out = [g for _, g in leaves_p]
        if sigma > 0.0:
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(seed), site_ix), rnd
            )
        for i, _, g in shared:
            v = (g.astype(jnp.float32) * scale)
            if sigma > 0.0:
                v = v + sigma * clip * jax.random.normal(
                    jax.random.fold_in(key, i), g.shape, jnp.float32
                )
            out[i] = v.astype(g.dtype)
        return jax.tree_util.tree_unflatten(treedef, out)

    return dp

