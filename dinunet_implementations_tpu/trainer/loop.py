"""Federated training driver — the capability fold-in of COINNLocal +
COINNRemote + COINNTrainer (SURVEY.md §2.3, §3.2).

One :class:`FederatedTrainer` drives, per fold:

- optional pretrain warm start on the largest site (``pretrain_args``;
  ``compspec.json:120-127`` "Use the site with maximum data to pre-train
  locally as starting point") — realized in SPMD by zero-weighting every other
  site's batches, so the same compiled epoch program serves both phases;
- the epoch loop: one jitted SPMD epoch per call (trainer/steps.py), metric
  validation every ``validation_epochs``, early stopping on
  ``monitor_metric``/``metric_direction`` with ``patience``
  (``local.py:34-36``), best-state tracking + checkpoint;
- final test on the best state; ``logs.json`` / ``test_metrics.csv`` /
  zipped global results, byte-compatible with the reference notebooks
  (trainer/logs.py).
"""

from __future__ import annotations

import dataclasses
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core.config import TrainConfig
from ..data.api import SiteArrays, stack_site_inventory
from ..data.batching import (
    epoch_steps,
    plan_epoch,
    plan_epoch_positions,
    plan_eval,
)
from ..engines import make_engine
from .checkpoint import (
    load_checkpoint,
    load_eval_state,
    load_params,
    save_checkpoint,
)
from ..robustness.faults import poison_inputs
from ..robustness.health import health_summary
from ..robustness.preemption import Preempted, PreemptionGuard
from ..telemetry.tracer import NULL_TRACER, SpanTracer, duration
from .logs import (
    fold_dir,
    health_log_fields,
    log_info,
    log_warning,
    privacy_log_fields,
    telemetry_log_fields,
    write_logs_json,
    write_test_metrics_csv,
    zip_global_results,
)
from .metrics import Averages, ClassificationMetrics, MulticlassMetrics, is_improvement
from .prefetch import EpochPlanPrefetcher
from .steps import (
    FederatedTask,
    TrainState,
    init_train_state,
    make_eval_fn,
    make_optimizer,
    make_train_epoch_fn,
)


class FederatedTrainer:
    def __init__(self, cfg: TrainConfig, model, mesh=None, out_dir: str | None = None,
                 fault_plan=None, bus=None, attack_plan=None):
        """``mesh=None`` folds all sites onto the local device via vmap (one
        chip simulating N sites); a mesh with a ``site`` axis runs the sites
        across its members — one per device slice, or PACKED ``K = S /
        mesh_sites`` per device with two-level aggregation when there are
        more sites than mesh members (parallel/mesh.py packed_site_mesh,
        trainer/steps.py). ``fault_plan`` is an
        optional :class:`~..robustness.faults.FaultPlan` — deterministic
        chaos injection (site drops / NaN poisoning / kill-at-round) threaded
        through the data layer and epoch inputs; masks are traced arrays, so
        injecting faults never changes the compiled program. ``attack_plan``
        is the hostile twin (robustness/attacks.py AttackPlan, r17):
        byzantine gradient transforms injected as a traced ``[S, rounds]``
        code mask — composes with the fault plan; defenses ride
        ``cfg.robust_agg``."""
        self.cfg = cfg
        self.mesh = mesh
        self.out_dir = out_dir
        self.fault_plan = fault_plan
        self.attack_plan = attack_plan
        self.task = FederatedTask(model)
        task_args = dataclasses.asdict(cfg.task_args())
        self.engine = make_engine(
            cfg.agg_engine, precision_bits=cfg.precision_bits, seed=cfg.seed,
            wire_quant=cfg.wire_quant, wire_stochastic=cfg.wire_stochastic,
            fused_poweriter=cfg.fused_poweriter,
            robust_agg=cfg.robust_agg,
            robust_trim_frac=cfg.robust_trim_frac,
            robust_clip_mult=cfg.robust_clip_mult,
            dcn_wire_quant=cfg.dcn_wire_quant,
            secure_agg=cfg.secure_agg,
            secure_agg_seed=cfg.secure_agg_seed,
            **task_args
        )
        # privacy plane (r20, privacy/): validate the DP knobs up front
        # (noise without a clip is rejected — no sensitivity, no guarantee)
        # and open the host-side RDP ledger when the mechanism is noisy.
        # The accountant lives on the trainer so both the batch fit and the
        # daemon's epoch loop step ONE ledger; _fit_impl round-trips it
        # through the checkpoint meta so a resumed fit continues ε
        # accumulation exactly (no double count, no reset).
        from ..privacy import RdpAccountant, dp_enabled

        self._dp_on = dp_enabled(cfg.dp_clip, cfg.dp_noise_multiplier)
        self._dp_noisy = self._dp_on and cfg.dp_noise_multiplier > 0.0
        if not 0.0 < cfg.dp_delta < 1.0:
            raise ValueError(f"dp_delta must be in (0, 1), got {cfg.dp_delta}")
        if cfg.dp_epsilon_budget < 0.0:
            raise ValueError(
                f"dp_epsilon_budget must be >= 0, got {cfg.dp_epsilon_budget}"
            )
        if cfg.dp_epsilon_budget > 0.0 and not self._dp_noisy:
            raise ValueError(
                "dp_epsilon_budget needs dp_noise_multiplier > 0 — a "
                "noiseless mechanism never exhausts any finite ε budget"
            )
        self.dp_accountant = RdpAccountant() if self._dp_noisy else None
        self._dp_epsilon = None  # last reported ε (None = dp off/noiseless)
        # modeled per-round inter-slice (DCN) bytes for the bus rollup —
        # filled at fit time once the site count / pack factor are known;
        # stays 0.0 on single-slice meshes (r18, telemetry/metrics.py)
        self._dcn_bytes_round = 0.0
        self.optimizer = make_optimizer(cfg.optimizer, cfg.learning_rate)
        if cfg.pipeline not in ("device", "host"):
            raise ValueError(
                f"cfg.pipeline must be 'device' or 'host', got {cfg.pipeline!r}"
            )
        # device pipeline (the default): inventory uploaded once per fit,
        # epochs driven by compact index plans gathered on-device; the carried
        # state is donated to the epoch program (see run_epoch/_snapshot)
        self._pipeline = cfg.pipeline
        self._donate = bool(cfg.donate_epoch_state)
        if cfg.compile_cache_dir:
            from ..core.jaxcompat import enable_compile_cache

            enable_compile_cache(cfg.compile_cache_dir)
        # unified telemetry (telemetry/): span tracer + on-device round
        # metrics + manifest/metrics artifacts. Off = a disabled (no-op)
        # tracer and a telemetry-free epoch program (bitwise-equal to the
        # pre-telemetry one).
        if cfg.telemetry not in ("on", "off"):
            raise ValueError(
                f"cfg.telemetry must be 'on' or 'off', got {cfg.telemetry!r}"
            )
        self._telemetry_on = cfg.telemetry == "on"
        if cfg.xprof_dir and cfg.profile_dir:
            raise ValueError(
                "profile_dir (whole-fit trace) and xprof_dir (windowed "
                "capture) are mutually exclusive — jax.profiler supports one "
                "active trace"
            )
        self.tracer = SpanTracer() if self._telemetry_on else NULL_TRACER
        # live metrics (telemetry/bus.py): published into the process-wide
        # bus when telemetry is on (the /statusz exporter's read side), the
        # NULL bus otherwise. Publishing is host-side bookkeeping over
        # values the loop already fetched — it never adds a device sync and
        # never touches the traced program (bus=NULL keeps the epoch
        # program bitwise-identical; the S005 identity gate covers it).
        if bus is not None:
            self.bus = bus
        else:
            from ..telemetry.bus import NULL_BUS, global_bus

            self.bus = global_bus() if self._telemetry_on else NULL_BUS
        self.epoch_fn = make_train_epoch_fn(
            self.task, self.engine, self.optimizer, mesh, cfg.local_iterations,
            rounds_scan_xs=cfg.rounds_scan_xs,
            quarantine_rounds=cfg.quarantine_rounds,
            pipeline=self._pipeline,
            donate_state=self._donate,
            telemetry=self._telemetry_on,
            staleness_bound=cfg.staleness_bound,
            staleness_decay=cfg.staleness_decay,
            overlap_rounds=cfg.overlap_rounds,
            attack_plan=attack_plan,
            robust_agg=cfg.robust_agg,
            reputation_z=cfg.reputation_z,
            reputation_rounds=cfg.reputation_rounds,
            min_slices=cfg.min_slices,
            dp_clip=cfg.dp_clip,
            dp_noise_multiplier=cfg.dp_noise_multiplier,
            dp_seed=cfg.dp_seed,
            personalize=tuple(cfg.personalize),
        )
        self.eval_fn = make_eval_fn(
            self.task, mesh, personalize=tuple(cfg.personalize)
        )
        self._inventory = None  # device-resident site inventory, one per fit
        self._inventory_src = None  # content fingerprint it was built from
        # ship inputs to the device pre-cast to the model's compute dtype
        # (e.g. bf16): the model casts them anyway, and feeding f32 made XLA
        # convert + layout-copy the whole epoch input on-device every epoch
        # (profiled ~10% of the 32-site ICA bench epoch). Labels/weights
        # stay full precision.
        self._input_dtype = getattr(model, "compute_dtype", None) or None
        self._cache: dict = {}  # duration bookkeeping, reference-keyed
        self._last_transfer_bytes = 0  # per-epoch host→device traffic
        # -- elastic-rounds hooks (runner/fed_runner.py FedDaemon, r13) --
        # [S] occupancy mask from the membership table: folded into every
        # epoch's liveness mask (an unoccupied slot is a site whose update
        # never arrives). None = classic batch-job semantics. Setting it
        # forces the liveness input to be FED even without a FaultPlan, so
        # the daemon runs one compiled program whether or not faults are
        # also injected.
        self.membership_mask = None
        # pinned per-epoch step-grid height: the daemon sets this so churn
        # (a bigger site joining) can never change the plan's [S, steps, B]
        # shape and force a retrace. None = derive steps from the site set.
        self.fixed_steps = None
        # pinned inventory row budget ([S, N_max, ...] grid height), same
        # retrace-proofing for the device-resident inventory upload
        self.fixed_inventory_rows = None
        # [num_slices] scheduler grant mask (runner/scheduler.py, r22): a
        # slice the fleet scheduler has not granted to this fit never
        # arrives — folded into the r19 slice-liveness window exactly like
        # membership_mask folds into site liveness. Setting it forces the
        # slice-liveness input to be FED even without a FaultPlan, so one
        # compiled program covers every grow/shrink/preempt/restore grant
        # flip (CompileGuard-assertable). None = no scheduler, r19 behavior.
        self.slice_grant = None

    def _coordinator(self) -> bool:
        """Multi-host runs: only process 0 writes logs/checkpoints (every
        process computes the identical replicated results; concurrent writers
        to a shared output dir would race)."""
        return jax.process_index() == 0

    def _put_batch(self, fb):
        """Device-side epoch arrays: inputs pre-cast to the compute dtype;
        on a mesh, committed ``P(site)`` arrays (multi-host aware)."""
        if self.mesh is not None:
            from ..parallel.distributed import put_site_batch

            return (
                put_site_batch(self.mesh, fb.inputs, self._input_dtype),
                put_site_batch(self.mesh, fb.labels),
                put_site_batch(self.mesh, fb.weights),
            )
        return (
            jnp.asarray(fb.inputs, dtype=self._input_dtype),
            jnp.asarray(fb.labels),
            jnp.asarray(fb.weights),
        )

    # -- building blocks -------------------------------------------------

    def init_state(self, sample_x, num_sites: int | None = None) -> TrainState:
        rng = jax.random.PRNGKey(self.cfg.seed)
        n = num_sites or getattr(self, "_num_sites", 1)
        state = init_train_state(
            self.task, self.engine, self.optimizer, rng, sample_x,
            num_sites=n,
            telemetry=self._telemetry_on,
            staleness_bound=self.cfg.staleness_bound,
            overlap_rounds=self.cfg.overlap_rounds,
            reputation=self.cfg.robust_agg != "none",
            personalize=tuple(self.cfg.personalize),
        )
        from ..parallel.mesh import SITE_AXIS, pack_factor, slice_count

        if self.mesh is not None and slice_count(self.mesh) > 1:
            # per-tier wire accounting for the bus rollup (r18): the modeled
            # per-slice DCN payload per round from the engine's own model,
            # at this fit's pack factor — a static figure the sliced
            # semantic cells verify against the traced program
            from ..telemetry.metrics import dcn_bytes_of

            k = pack_factor(self.mesh, n)
            self._dcn_bytes_round = dcn_bytes_of(
                self.engine, state.params, pack=k,
                sites_per_slice=k * dict(self.mesh.shape)[SITE_AXIS],
                slices=slice_count(self.mesh),
            )
        return self._place_state(state)

    def _place_state(self, state: TrainState) -> TrainState:
        """Commit a host-built state to the mesh's steady-state sharding (the
        one the compiled epoch emits). Freshly-initialized / checkpoint-
        restored states are otherwise uncommitted, and the first epoch_fn
        call after init or resume would compile a SECOND program for the
        uncommitted layout — one silent warmup recompile per fit. Single-
        process meshes only: multi-host arrays are fed per-process
        (put_site_batch) and keep the legacy behavior."""
        from ..parallel.distributed import spans_processes
        from .steps import _state_specs

        if self.mesh is None or spans_processes(self.mesh):
            return state
        from jax.sharding import NamedSharding

        from ..parallel.mesh import site_axis_of

        return jax.tree.map(
            lambda a, spec: jax.device_put(a, NamedSharding(self.mesh, spec)),
            state, _state_specs(state, site_axis_of(self.mesh)),
        )

    def _put_live(self, live):
        """Ship a ``[S, rounds]`` liveness mask like the epoch batches."""
        if live is None:
            return None
        if self.mesh is not None:
            from ..parallel.distributed import put_site_batch

            return put_site_batch(self.mesh, live)
        return jnp.asarray(live)

    def _snapshot(self, state):
        """An independent copy of a state's buffers. With
        ``cfg.donate_epoch_state`` the NEXT epoch_fn call consumes (donates)
        its input state's buffers in place — so any state kept past that
        call (best-state tracking) must be snapshotted, never aliased."""
        if not self._donate:
            return state
        return jax.tree.map(jnp.copy, state)

    def _ensure_inventory(self, train_sites):
        """Device-resident inventory: uploaded once per fit, inputs pre-cast
        to the compute dtype at placement. Keyed by a content fingerprint
        (per-site array identities + sizes), not list identity, so a caller
        rebuilding its site LIST per run_epoch call (``list(sites)``) still
        reuses the resident upload — re-uploading per epoch would silently
        reinstate the dataset-sized transfer this pipeline removes."""
        key = tuple(
            (id(s.inputs), id(s.labels), len(s)) for s in train_sites
        )
        if self._inventory is None or self._inventory_src != key:
            from ..parallel.distributed import put_site_inventory

            with self.tracer.span("inventory-upload"):
                self._inventory = put_site_inventory(
                    self.mesh,
                    stack_site_inventory(
                        train_sites, self.fixed_inventory_rows
                    ),
                    self._input_dtype,
                )
            self._inventory_src = key
        return self._inventory

    def _build_epoch_payload(self, train_sites, epoch: int, batch_size: int,
                             round0: int):
        """One epoch's device-pipeline inputs: the compact index plan plus the
        FaultPlan masks for its global round window — the complete per-epoch
        host→device transfer (index-plan bytes, not dataset bytes). Pure
        function of ``(epoch, round0)``, so the prefetch thread can build
        epoch N+1 while epoch N runs without changing results (the tracer's
        ``plan-build`` spans land on whichever thread ran the build — the
        prefetch thread in steady state)."""
        from ..robustness.faults import fault_window

        with self.tracer.span("plan-build", epoch=epoch):
            plan = plan_epoch_positions(
                train_sites, batch_size,
                seed=self.cfg.seed * 100003 + epoch, pad_mode="wrap",
                steps=self.fixed_steps,
            )
            rounds = plan.steps // max(self.cfg.local_iterations, 1)
            live, nan_mask = fault_window(
                self.fault_plan, plan.num_sites, round0, rounds
            )
            live = self._membership_live(live, plan.num_sites, rounds)
            # the NaN gate is fed whenever the PLAN carries nan_at (a
            # fit-static property), not only in windows that poison — the
            # compiled program must not change between epochs
            poison = (
                nan_mask.astype(np.float32)
                if nan_mask is not None and self.fault_plan.nan_at else None
            )
            # hostile-site attack codes for this window (r17,
            # robustness/attacks.py) — fed whenever the plan attacks at all
            # (fit-static), same one-program reasoning as the NaN gate
            from ..robustness.attacks import attack_window

            attack = attack_window(
                self.attack_plan, plan.num_sites, round0, rounds
            )
            # slice-tier faults (r19): the [num_slices, rounds] whole-slice
            # mask for this window — None off sliced meshes / slice-clean
            # plans, so the r18 program is untouched (S005)
            slice_live = self._slice_window(round0, rounds)
            from ..parallel.distributed import put_epoch_plan

            return put_epoch_plan(
                self.mesh, plan.positions, live, poison, attack, slice_live
            )

    def _slice_window(self, round0: int, rounds: int):
        """The FaultPlan's slice-liveness window for this epoch (r19,
        robustness/faults.py): ``[num_slices, rounds]`` or None. Kills are
        rendered into the mask only on single-process emulation — under the
        supervised multi-process runner they are REAL process deaths
        (runner/dcn_worker.py), and masking them too would keep a restarted
        slice dead forever. A scheduler slice grant (``slice_grant``, r22)
        multiplies in — an ungranted slice looks exactly like a dead one
        (renormalized aggregation, min_slices quorum), and forces the mask
        into existence so grant flips share ONE compiled form with fault
        windows."""
        from ..parallel.mesh import slice_count

        n_sl = slice_count(self.mesh)
        if n_sl <= 1 or (self.fault_plan is None and self.slice_grant is None):
            return None
        win = None
        if self.fault_plan is not None:
            from ..parallel.distributed import spans_processes
            from ..robustness.faults import slice_fault_window

            win = slice_fault_window(
                self.fault_plan, n_sl, round0, rounds,
                include_kills=not spans_processes(self.mesh),
            )
        if self.slice_grant is not None:
            grant = np.asarray(self.slice_grant, np.float32)[:n_sl, None]
            if win is None:
                win = np.broadcast_to(grant, (n_sl, rounds)).copy()
            else:
                win = win * grant
        return win

    def _publish_slice_liveness(self, slice_live) -> None:
        """Per-slice liveness gauges for the live bus (r19): how many of
        this epoch's rounds each slice is scheduled live — the /statusz
        surface for "which slice is the chaos plan (or a supervisor-marked
        death) taking out". Host-side values, no device sync of
        consequence (the mask is tiny and replicated)."""
        if slice_live is None or not self._telemetry_on:
            return
        rows = np.asarray(slice_live)
        for sl_i in range(rows.shape[0]):
            self.bus.gauge(
                "train_slice_live_rounds", float(rows[sl_i].sum()),
                slice=str(sl_i),
            )

    def _membership_live(self, live, num_sites: int, rounds: int):
        """Fold the membership occupancy mask (FedDaemon, r13) into an
        epoch's ``[S, rounds]`` liveness mask: an unoccupied slot never
        arrives. Forces a mask into existence when membership is elastic —
        the daemon's epoch program always takes the liveness input, so churn
        and fault patterns share ONE compiled form."""
        if self.membership_mask is None:
            return live
        occ = np.asarray(self.membership_mask, np.float32)[:num_sites, None]
        if live is None:
            return np.broadcast_to(occ, (num_sites, rounds)).copy()
        return live * occ

    def run_epoch(self, state, train_sites, epoch: int, batch_size=None,
                  plan=None):
        """One training epoch. Device pipeline: gathers batches on-device
        from the resident inventory, driven by ``plan`` (a prefetched
        ``_build_epoch_payload`` result; built inline when None). Host
        pipeline: materializes and ships the dense epoch tensor."""
        if self._pipeline == "device":
            if plan is None:
                plan = self._build_epoch_payload(
                    train_sites, epoch, batch_size or self.cfg.batch_size,
                    round0=int(state.round),
                )
            idx, live, poison, attack, slice_live = plan
            inv_x, inv_y = self._ensure_inventory(train_sites)
            # the device pipeline's ENTIRE per-epoch host→device traffic
            self._last_transfer_bytes = int(sum(
                a.nbytes for a in (idx, live, poison, attack, slice_live)
                if a is not None
            ))
            self._publish_slice_liveness(slice_live)
            state, losses = self.epoch_fn(
                state, inv_x, inv_y, idx, live, poison, attack, slice_live
            )
            return state, self._account_epoch(
                train_sites, np.asarray(losses), batch_size
            )
        fb = plan_epoch(
            train_sites,
            batch_size or self.cfg.batch_size,
            seed=self.cfg.seed * 100003 + epoch,
            pad_mode="wrap",
            steps=self.fixed_steps,
        )
        # deterministic chaos: masks/poison are pure functions of the plan
        # and the GLOBAL round window (robustness/faults.py fault_window —
        # shared with the device path), so resume replays the same fault
        # pattern the uninterrupted run saw
        from ..robustness.faults import fault_window

        live = nan_mask = None
        if self.fault_plan is not None and self.fault_plan.injects_faults():
            # (the injects_faults gate also keeps the int(state.round) fetch
            # — a device sync — off the clean path)
            rounds = fb.steps // max(self.cfg.local_iterations, 1)
            live, nan_mask = fault_window(
                self.fault_plan, fb.num_sites, int(state.round), rounds
            )
        if self.membership_mask is not None:
            live = self._membership_live(
                live, fb.num_sites,
                fb.steps // max(self.cfg.local_iterations, 1),
            )
        if nan_mask is not None and nan_mask.any():
            # data-layer injection: real NaN inputs
            fb = dataclasses.replace(
                fb,
                inputs=poison_inputs(
                    fb.inputs, nan_mask, self.cfg.local_iterations
                ),
            )
        # hostile-site attack codes (r17): a traced [S, rounds] input like
        # the liveness mask, windowed on the same global round counter
        attack = None
        if self.attack_plan is not None and self.attack_plan.injects_attacks():
            from ..robustness.attacks import attack_window

            attack = attack_window(
                self.attack_plan, fb.num_sites, int(state.round),
                fb.steps // max(self.cfg.local_iterations, 1),
            )
        # slice-tier faults (r19): the whole-slice mask, windowed on the
        # same global round counter as the site mask
        slice_live = self._slice_window(
            int(state.round), fb.steps // max(self.cfg.local_iterations, 1)
        ) if (self.fault_plan is not None
              or self.slice_grant is not None) else None
        batch = self._put_batch(fb)
        live_dev = self._put_live(live)
        attack_dev = self._put_live(attack)
        slice_dev = None
        if slice_live is not None:
            from ..parallel.distributed import put_replicated

            slice_dev = put_replicated(self.mesh, slice_live)
        self._last_transfer_bytes = int(
            sum(a.nbytes for a in batch)
            + sum(a.nbytes for a in (live_dev, attack_dev, slice_dev)
                  if a is not None)
        )
        self._publish_slice_liveness(slice_live)
        state, losses = self.epoch_fn(
            state, *batch, live_dev, attack_dev, slice_dev
        )
        return state, self._account_epoch(
            train_sites, np.asarray(losses), batch_size
        )

    def _account_epoch(self, train_sites, losses, batch_size=None):
        """Step the RDP ledger by this epoch's executed rounds and publish
        ε (r20, privacy/accounting.py) — run_epoch is the one place both
        the batch fit and the daemon's serve loop train an epoch, so both
        surfaces share ONE ledger. The conversion runs host-side on values
        the loop already has; no device sync."""
        if self.dp_accountant is None:
            return losses
        from ..privacy import effective_noise_multiplier, sampling_fraction

        q = sampling_fraction(
            batch_size or self.cfg.batch_size, self.cfg.local_iterations,
            [len(s) for s in train_sites],
        )
        # clip-of-mean sensitivity is 2C, not C — compose conservatively
        # at σ/2 (privacy/accounting.py MEAN_CLIP_SENSITIVITY_FACTOR)
        self.dp_accountant.step(
            effective_noise_multiplier(self.cfg.dp_noise_multiplier), q,
            steps=len(losses),
        )
        eps, _ = self.dp_accountant.epsilon(self.cfg.dp_delta)
        self._dp_epsilon = float(eps)
        # the (ε, δ) /statusz surface: train_epsilon next to train_loss
        self.bus.gauge("train_epsilon", self._dp_epsilon)
        return losses

    @staticmethod
    def _new_metrics(num_class: int):
        """Binary: score = positive-class probability (reference semantics,
        AUC on prob[:,1], comps/icalstm/__init__.py:64-65); multiclass:
        argmax-based macro metrics."""
        return ClassificationMetrics() if num_class == 2 else MulticlassMetrics()

    @staticmethod
    def _add_probs(m, probs, labels, weights):
        if isinstance(m, ClassificationMetrics):
            m.add(probs[..., 1].reshape(-1), labels.reshape(-1), weights.reshape(-1))
        else:
            m.add(probs.reshape(-1, probs.shape[-1]), labels.reshape(-1),
                  weights.reshape(-1))
        return m

    def _format_val_line(self, avg, metrics, monitor: str) -> str:
        """Per-epoch validation readout, columns chosen by ``cfg.log_header``
        (the reference's log display header, e.g. ``"Loss|AUC"`` —
        ``local.py:36``, ``compspec.json:256``). Unknown names are skipped;
        falls back to loss + the monitored metric."""
        names = [h.strip().lower() for h in (self.cfg.log_header or "").split("|")]
        parts = []
        for nm in names:
            if nm == "loss":
                parts.append(f"val_loss={avg.avg:.4f}")
            elif nm:
                try:
                    parts.append(f"val_{nm}={metrics.value(nm):.4f}")
                except (KeyError, ValueError):
                    pass
        if not parts:
            score = metrics.value(monitor) if monitor != "loss" else avg.avg
            parts = [f"val_loss={avg.avg:.4f}", f"val_{monitor}={score:.4f}"]
        return " ".join(parts)

    def evaluate(self, state, sites, batch_size=None, per_site: bool = False):
        """Pooled (remote-side) metrics across all sites; with
        ``per_site=True`` also returns each site's own (Averages, metrics) —
        the eval step already computes per-site probs/loss sums, so per-site
        logs (reference ``local{i}/logs.json``) come for free."""
        with self.tracer.span("eval"):
            fb = plan_eval(sites, batch_size or self.cfg.batch_size)
            outs = self.eval_fn(state, *self._put_batch(fb))
            from ..parallel.distributed import fetch_site_outputs

            # [S, steps, B, C] probs + per-site sums; multi-host meshes
            # gather the P(site)-sharded outputs before the host fetch
            probs, loss_sum, wsum = fetch_site_outputs(outs, self.mesh)
        loss = float(loss_sum.sum() / max(wsum.sum(), 1.0))
        m = self._add_probs(
            self._new_metrics(probs.shape[-1]), probs, fb.labels, fb.weights
        )
        avg = Averages().add(loss, wsum.sum())
        if not per_site:
            return avg, m
        site_results = []
        for s in range(probs.shape[0]):
            sm = self._add_probs(
                self._new_metrics(probs.shape[-1]), probs[s], fb.labels[s],
                fb.weights[s],
            )
            savg = Averages().add(
                float(loss_sum[s] / max(wsum[s], 1.0)), wsum[s]
            )
            site_results.append((savg, sm))
        return avg, m, site_results

    # -- the full fit ----------------------------------------------------

    def fit(
        self,
        train_sites: list[SiteArrays],
        val_sites: list[SiteArrays],
        test_sites: list[SiteArrays],
        fold: int = 0,
        verbose: bool = True,
        resume: bool = False,
    ) -> dict:
        cfg = self.cfg
        if cfg.mode.lower() == "test":
            # GUI mode=test (compspec.json mode field): inference only, no
            # training — load the fold's best checkpoint and evaluate.
            return self.test_only(test_sites, fold=fold)
        # telemetry envelope: the whole fit runs under one "fit" span, and
        # the artifact sink (opened inside _fit_impl once paths are known)
        # ALWAYS finalizes — early stop, Preempted, or a crash still leave a
        # complete manifest/metrics.jsonl/trace set on disk.
        self._fit_tel = None
        self._fit_summary: dict = {}
        try:
            with self.tracer.span("fit", fold=fold):
                return self._fit_impl(
                    train_sites, val_sites, test_sites, fold=fold,
                    verbose=verbose, resume=resume,
                )
        finally:
            fit_tel = self._fit_tel
            if fit_tel is not None:
                from ..checks.sanitize import jit_cache_size

                compiles0 = self._fit_summary.pop("_compiles0", 0)
                self._fit_summary["epoch_compiles"] = (
                    (jit_cache_size(self.epoch_fn) or 0) - compiles0
                )
                fit_tel.append(self._fit_summary)
                fit_tel.close()
                self._fit_tel = None

    def _fit_impl(
        self,
        train_sites: list[SiteArrays],
        val_sites: list[SiteArrays],
        test_sites: list[SiteArrays],
        fold: int = 0,
        verbose: bool = True,
        resume: bool = False,
    ) -> dict:
        cfg = self.cfg
        # monotonic clock for every duration (the tracer's clock): wall
        # time can step (NTP, DST) mid-fit and corrupt the checkpointed
        # duration bookkeeping
        t_start = time.perf_counter()
        self._num_sites = len(train_sites)
        if self.mesh is not None:
            from ..parallel.mesh import pack_factor

            # the packed site layout (parallel/mesh.py): S virtual sites
            # shard P(site) into [K, ...] device blocks — fail here with a
            # clear message (not an XLA sharding error) when S doesn't
            # divide over the mesh's site axis
            pack_factor(self.mesh, self._num_sites)
        # Fail fast on splits that are empty at EVERY site; per-site emptiness
        # and too-small sites are handled below (warning / batch-size clamp).
        sizes = [
            (len(a), len(b), len(c))
            for a, b, c in zip(train_sites, val_sites, test_sites)
        ]
        for name, split_sites in (("train", train_sites), ("test", test_sites)):
            if not any(len(s) for s in split_sites):
                raise ValueError(
                    f"the {name} split is empty at every site (site train/"
                    f"val/test sizes: {sizes}; split_ratio="
                    f"{cfg.split_ratio}) — use more subjects per site or a "
                    "split_ratio that gives each split at least one sample "
                    "somewhere"
                )
        # Empty validation EVERYWHERE is a supported configuration
        # (kfold_splits k==2 has no fold left for validation, splits.py:41-45):
        # skip validation-based selection and keep the final state.
        has_val = any(len(s) for s in val_sites)
        min_site = min((len(s) for s in train_sites if len(s)), default=0)
        if 0 < min_site < cfg.batch_size:
            # Heterogeneous-site guard (VERDICT r4 #6): with drop_last train
            # batching, a site smaller than batch_size yields ZERO batches
            # and contributes nothing (or, if every site is small, plan_epoch
            # asserts). Clamp so any demo-sized tree trains, and say so.
            # The clamp stays in the LOCAL cfg only — self.cfg is shared with
            # the caller (FedRunner hands one config object to every fold's
            # trainer), and a fold with small sites must not shrink the batch
            # for later folds (ADVICE r5). The clamped batch size is threaded
            # explicitly to run_epoch/evaluate below.
            if verbose:
                log_warning(
                    f"[warn] batch_size={cfg.batch_size} exceeds the smallest "
                    f"site's train split ({min_site} samples); clamping "
                    f"batch_size to {min_site} for this fold (drop_last "
                    "batching would starve that site). Pass a batch_size <= "
                    f"{min_site} to silence this."
                )
            cfg = cfg.replace(batch_size=min_site)
        if verbose:
            for i, s in enumerate(train_sites):
                if not len(s):
                    log_warning(
                        f"[warn] site {i} has an empty train split "
                        f"(train/val/test sizes: {sizes[i]}) — it will "
                        "contribute nothing to training this fold"
                    )
        state = self.init_state(jnp.ones((cfg.batch_size,) + train_sites[0].inputs.shape[1:], jnp.float32))

        latest_path = best_path = None
        if self.out_dir:
            d = fold_dir(self.out_dir, "remote", cfg.task_id, fold)
            latest_path = os.path.join(d, "checkpoint_latest.msgpack")
            best_path = os.path.join(d, "checkpoint_best.msgpack")
        # a kill inside the rotate window (primary moved to .prev, new primary
        # not yet written) leaves only the .prev generation — still a valid
        # resume point (load_checkpoint falls back to it), so gate on either
        resuming = bool(
            resume and latest_path
            and (os.path.exists(latest_path)
                 or os.path.exists(latest_path + ".prev"))
        )

        # --- telemetry artifact sink (manifest.json + metrics.jsonl +
        # trace files under <out_dir>/telemetry/fold_<k>): one per fit, on
        # the coordinator only (same single-writer rule as checkpoints)
        if self._telemetry_on:
            tel_root = cfg.telemetry_dir or (
                os.path.join(self.out_dir, "telemetry") if self.out_dir else ""
            )
            if tel_root and self._coordinator():
                from ..checks.sanitize import jit_cache_size
                from ..telemetry.sink import FitTelemetry

                self._fit_tel = FitTelemetry.open(
                    os.path.join(tel_root, f"fold_{fold}"), cfg,
                    mesh=self.mesh, fold=fold, tracer=self.tracer,
                    fault_plan=self.fault_plan, attack_plan=self.attack_plan,
                )
                self._fit_summary = {
                    "kind": "summary", "fold": fold, "epochs_run": 0,
                    "best_val_epoch": 0, "best_val_metric": None,
                    # elastic-rounds rollup (robustness/membership.py);
                    # batch-job fits have no membership table → null
                    "membership": None,
                    "_compiles0": jit_cache_size(self.epoch_fn) or 0,
                }
            elif not tel_root and verbose:
                log_warning(
                    "[warn] telemetry='on' but neither out_dir nor "
                    "telemetry_dir is set — spans and device metrics are "
                    "collected but no artifacts will be written"
                )

        # --- warm starts — skipped when resuming: load_checkpoint below
        # replaces the state wholesale, so pretraining first would be pure
        # wasted compute on every restart
        if not resuming:
            # params-only warm start from a saved checkpoint (fresh
            # optimizer/engine state — pretrain-from-file semantics)
            if cfg.pretrained_path:
                state = state.replace(
                    params=load_params(cfg.pretrained_path, state.params)
                )
            # pretrain on the largest site (compspec.json:120-127)
            if cfg.pretrain and cfg.pretrain_args and cfg.pretrain_args.epochs > 0:
                state = self._pretrain(state, train_sites, val_sites, verbose)

        best_metric = None
        best_epoch = 0
        # snapshot, never alias: with donate_epoch_state the next epoch_fn
        # call consumes `state`'s buffers in place (trainer/steps.py)
        best_state = self._snapshot(state)
        since_best = 0
        epoch_losses = []
        iter_durations = []
        start_epoch = 1

        # --- fold resume: restore trainer state + selection/duration
        # bookkeeping from the last validation-boundary checkpoint (meta is
        # embedded in the msgpack, atomically paired with the state)
        if resuming:
            state, meta = load_checkpoint(latest_path, state, with_meta=True)
            state = self._place_state(state)  # avoid a resume-layout recompile
            start_epoch = int(meta.get("epoch", 0)) + 1
            best_metric = meta.get("best_val_metric")
            best_epoch = int(meta.get("best_val_epoch", 0))
            since_best = int(meta.get("since_best", 0))
            epoch_losses = list(meta.get("epoch_losses", []))
            iter_durations = list(meta.get("iter_durations", []))
            self._cache["time_spent_on_computation"] = list(
                meta.get("time_spent_on_computation", [])
            )
            cum = list(meta.get("cumulative_total_duration", []))
            self._cache["cumulative_total_duration"] = cum
            # continue the cumulative wall-clock line from its stored total
            if cum:
                t_start = time.perf_counter() - cum[-1]
            # privacy ledger (r20): resume continues ε accumulation EXACTLY
            # — the checkpointed RDP state replaces the fresh ledger, so an
            # interrupted fit spends the same budget as an uninterrupted
            # one (no double count, no reset; tests/test_privacy.py)
            if self.dp_accountant is not None and meta.get("dp_accountant"):
                from ..privacy import RdpAccountant

                self.dp_accountant = RdpAccountant.from_json(
                    meta["dp_accountant"]
                )
                eps, _ = self.dp_accountant.epsilon(cfg.dp_delta)
                self._dp_epsilon = float(eps)
            # snapshot either way: a load falling back to template leaves
            # (engine-structure change) would otherwise alias `state`
            best_state = self._snapshot(
                load_checkpoint(best_path, state)
                if (os.path.exists(best_path)
                    or os.path.exists(best_path + ".prev"))
                else state
            )

        monitor = cfg.monitor_metric
        direction = cfg.metric_direction

        # opt-in device trace (SURVEY.md §5): TensorBoard-compatible profile
        # of the whole epoch loop, one trace per fold
        if cfg.profile_dir:
            jax.profiler.start_trace(
                os.path.join(cfg.profile_dir, f"fold_{fold}")
            )
        # windowed jax.profiler capture (telemetry/xprof.py): trace only the
        # cfg.xprof_window epoch range — mutually exclusive with profile_dir
        # (checked at construction)
        xprof = None
        if cfg.xprof_dir:
            from ..telemetry.xprof import XprofWindow

            xprof = XprofWindow(
                cfg.xprof_dir, cfg.xprof_window, label=f"fold_{fold}"
            )
        stop_epoch = cfg.epochs
        # kill-at-round chaos arm: track the global round window per epoch so
        # the kill fires exactly once, when training CROSSES the round (a
        # resumed run starts past it and sails through)
        kill_round = (
            self.fault_plan.kill_at_round if self.fault_plan is not None else None
        )
        round_before = int(state.round) if kill_round is not None else 0
        prefetch = None
        if self._pipeline == "device" and start_epoch <= cfg.epochs:
            # double-buffered planner (trainer/prefetch.py): a background
            # thread builds epoch N+1's index plan and dispatches its
            # KB-sized transfer while epoch N's fused dispatch runs. Plans
            # are pure functions of (epoch, global round window) — the round
            # counter extrapolates linearly from here, resume included — so
            # prefetching cannot change results.
            rpe = epoch_steps(train_sites, cfg.batch_size) // max(
                cfg.local_iterations, 1
            )
            round0, first = int(state.round), start_epoch
            prefetch = EpochPlanPrefetcher(
                lambda e: self._build_epoch_payload(
                    train_sites, e, cfg.batch_size, round0 + (e - first) * rpe
                ),
                start_epoch, cfg.epochs,
            )
        guard = PreemptionGuard()
        try:
            with guard:
                for epoch in range(start_epoch, cfg.epochs + 1):
                    e_start = time.perf_counter()
                    if xprof is not None:
                        xprof.epoch_begin(epoch)
                    with self.tracer.span("epoch", epoch=epoch):
                        state, losses = self.run_epoch(
                            state, train_sites, epoch,
                            batch_size=cfg.batch_size,
                            plan=(None if prefetch is None
                                  else prefetch.get(epoch)),
                        )
                    if xprof is not None:
                        xprof.epoch_end(epoch)
                    # all-dead rounds report NaN loss (trainer/steps.py) —
                    # average over the rounds that actually trained
                    lived = losses[np.isfinite(losses)]
                    epoch_loss = float(lived.mean()) if lived.size else float("nan")
                    epoch_losses.append(epoch_loss)
                    # per-iteration durations (reference local_iter_duration is
                    # per-round, NB.ipynb cells 34-36). All rounds of an epoch run in
                    # ONE fused XLA dispatch here, so per-round host timing does not
                    # exist; the truthful equivalent is the epoch time amortized over
                    # its rounds.
                    rounds = max(len(losses), 1)
                    e_seconds = time.perf_counter() - e_start
                    iter_durations.extend([e_seconds / rounds] * rounds)
                    # live metrics: values already on the host (losses were
                    # fetched above) — no extra device sync
                    self.bus.gauge("train_epoch", epoch)
                    self.bus.gauge("train_loss", epoch_loss)
                    self.bus.counter("train_epochs_total")
                    self.bus.counter("train_rounds_total", rounds)
                    self.bus.observe("epoch_ms", e_seconds * 1e3)
                    if self._dcn_bytes_round > 0:
                        # per-tier wire accounting (r18): modeled inter-slice
                        # (DCN) bytes this epoch shipped — the /statusz
                        # surface for "what is the slow hop carrying". A
                        # static per-round model (verified by the sliced
                        # semantic cells), so no device sync.
                        self.bus.counter(
                            "train_dcn_bytes_total",
                            self._dcn_bytes_round * rounds,
                        )
                    if (
                        self._telemetry_on and state.health is not None
                        and "anomaly" in state.health
                    ):
                        # reputation scores onto the live bus (r17): the
                        # /statusz surface for "is a site drifting hostile".
                        # The losses fetch above already synchronized the
                        # epoch, so these tiny [S] reads add no extra
                        # device round trip of consequence.
                        from ..parallel.distributed import fetch_site_outputs

                        anom = fetch_site_outputs(
                            state.health["anomaly"], self.mesh
                        )
                        quar = fetch_site_outputs(
                            state.health["quarantined"], self.mesh
                        )
                        self.bus.gauge(
                            "train_anomaly_max", float(np.max(anom))
                        )
                        self.bus.gauge(
                            "train_quarantined_sites",
                            int(np.sum(np.asarray(quar) > 0)),
                        )
                    if self._fit_tel is not None:
                        self._epoch_row(fold, epoch, epoch_loss, e_start,
                                        state)
                        self._fit_summary["epochs_run"] = len(epoch_losses)

                    if epoch % cfg.validation_epochs == 0:
                        if has_val:
                            val_avg, val_metrics = self.evaluate(
                                state, val_sites, batch_size=cfg.batch_size
                            )
                            score = val_metrics.value(monitor) if monitor != "loss" else val_avg.avg
                            if is_improvement(
                                score, best_metric, direction if monitor != "loss" else "minimize"
                            ):
                                best_metric, best_epoch = score, epoch
                                best_state = self._snapshot(state)
                                since_best = 0
                                if best_path and self._coordinator():  # save-on-best
                                    with self.tracer.span("checkpoint"):
                                        save_checkpoint(
                                            best_path, best_state,
                                            meta={"best_val_epoch": best_epoch,
                                                  "best_val_metric": best_metric, "fold": fold},
                                            rotate=True,
                                        )
                                    if self._fit_tel is not None:
                                        self._fit_tel.event(
                                            "checkpoint", epoch=epoch,
                                            which="best",
                                        )
                            else:
                                since_best += cfg.validation_epochs
                            if verbose:
                                log_info(
                                    f"[fold {fold}] epoch {epoch}: train_loss={epoch_loss:.4f} "
                                    + self._format_val_line(val_avg, val_metrics, monitor)
                                    + (" *" if best_epoch == epoch else "")
                                )
                        else:
                            # no validation anywhere (kfold k==2): the latest
                            # state is the selected state; no early stopping
                            best_epoch, best_state = epoch, self._snapshot(state)
                            if verbose:
                                log_info(
                                    f"[fold {fold}] epoch {epoch}: "
                                    f"train_loss={epoch_loss:.4f} (no validation split)"
                                )
                        stop = since_best >= cfg.patience
                    else:
                        stop = False
                    # durations BEFORE the save so the checkpointed meta's
                    # bookkeeping covers the same epochs as its epoch_losses
                    # (and the save's own IO time stays out of compute time)
                    duration(self._cache, e_start, "time_spent_on_computation")
                    duration(self._cache, t_start, "cumulative_total_duration")
                    # rotating resume point EVERY epoch (ckpt + ckpt.prev,
                    # checksummed): preemption granularity is one epoch, and a
                    # torn/corrupt latest falls back to the previous generation
                    if latest_path and self._coordinator():
                        with self.tracer.span("checkpoint"):
                            save_checkpoint(
                                latest_path, state,
                                meta={"epoch": epoch, "best_val_epoch": best_epoch,
                                      "best_val_metric": best_metric,
                                      "since_best": since_best, "fold": fold,
                                      "epoch_losses": epoch_losses,
                                      "iter_durations": iter_durations,
                                      "time_spent_on_computation": self._cache.get(
                                          "time_spent_on_computation", []),
                                      "cumulative_total_duration": self._cache.get(
                                          "cumulative_total_duration", []),
                                      # the RDP ledger rides the atomically-
                                      # paired meta (r20): resume continues
                                      # ε exactly from this boundary
                                      "dp_accountant": (
                                          self.dp_accountant.to_json()
                                          if self.dp_accountant is not None
                                          else None)},
                                rotate=True,
                            )
                    # -- preemption: a SIGTERM/SIGINT that landed during the
                    # epoch exits here, AFTER the rotating checkpoint, so
                    # resume=True continues bit-exact from this boundary
                    if guard.requested is not None:
                        if self._fit_tel is not None:
                            self._fit_tel.event(
                                "preempted", epoch=epoch,
                                signum=int(guard.requested),
                            )
                        raise Preempted(
                            f"signal {guard.requested} during epoch {epoch}; "
                            f"state saved to {latest_path or '(no out_dir)'}",
                            signum=guard.requested, epoch=epoch,
                        )
                    if kill_round is not None:
                        round_after = int(state.round)
                        if round_before <= kill_round < round_after:
                            if self._fit_tel is not None:
                                self._fit_tel.event(
                                    "preempted", epoch=epoch,
                                    kill_at_round=int(kill_round),
                                )
                            raise Preempted(
                                f"FaultPlan kill_at_round={kill_round} crossed "
                                f"during epoch {epoch}; state saved to "
                                f"{latest_path or '(no out_dir)'}",
                                epoch=epoch,
                            )
                        round_before = round_after
                    # ε-budget exhaustion (r20): the Preempted-style
                    # checkpointed exit, minus the nonzero exit code — the
                    # epoch's rotating checkpoint is already on disk above,
                    # so the fit stops cleanly here and proceeds to the
                    # best-state test with the budget respected
                    if (
                        cfg.dp_epsilon_budget > 0.0
                        and self._dp_epsilon is not None
                        and self._dp_epsilon >= cfg.dp_epsilon_budget
                    ):
                        if self._fit_tel is not None:
                            self._fit_tel.event(
                                "dp-budget", epoch=epoch,
                                epsilon=self._dp_epsilon,
                                budget=cfg.dp_epsilon_budget,
                            )
                        if verbose:
                            log_info(
                                f"[fold {fold}] epoch {epoch}: privacy "
                                f"budget exhausted (ε="
                                f"{self._dp_epsilon:.3f} ≥ "
                                f"{cfg.dp_epsilon_budget}); stopping"
                            )
                        stop_epoch = epoch
                        break
                    if stop:
                        stop_epoch = epoch
                        break
        finally:
            # prompt, leak-free shutdown on EVERY exit — early stop,
            # Preempted (SIGTERM / FaultPlan kill), or a crash: a resumed run
            # must never inherit a live prefetch thread
            if prefetch is not None:
                if self._fit_tel is not None:
                    # stall/queue-depth counters into the summary row, read
                    # BEFORE close() while the stats are final-but-live
                    self._fit_summary.update({
                        f"prefetch_{k}": v
                        for k, v in prefetch.stats().items()
                    })
                prefetch.close()
            if xprof is not None:
                xprof.close()
            if cfg.profile_dir:
                jax.profiler.stop_trace()

        # If the epoch count never hit a validation boundary (epochs <
        # validation_epochs), best_state would be the untrained init — run a
        # final validation so the trained weights compete for selection.
        if best_metric is None and cfg.epochs > 0:
            if has_val:
                val_avg, val_metrics = self.evaluate(
                    state, val_sites, batch_size=cfg.batch_size
                )
                score = val_metrics.value(monitor) if monitor != "loss" else val_avg.avg
                best_metric, best_epoch, best_state = score, stop_epoch, state
            else:
                best_epoch, best_state = stop_epoch, state

        # --- test with the best state (reference: best-epoch checkpoint)
        with self.tracer.span("test"):
            results = self._test_results(best_state, test_sites, best_epoch,
                                         best_metric, stop_epoch, epoch_losses,
                                         batch_size=cfg.batch_size)
        # per-site fault-tolerance counters from the FINAL state (best_state
        # may predate a quarantine event): rounds skipped, quarantine flags
        if state.health is not None:
            from ..parallel.distributed import fetch_site_outputs

            results["site_health"] = health_summary(
                fetch_site_outputs(state.health, self.mesh)
            )
        # per-site round-metric rollup from the FINAL state, same rationale
        if state.telemetry is not None:
            from ..parallel.distributed import fetch_site_outputs
            from ..telemetry.metrics import telemetry_summary

            results["site_telemetry"] = telemetry_summary(
                fetch_site_outputs(state.telemetry, self.mesh)
            )
        # privacy surfaces (r20): the spent (ε, δ) lands in the results
        # dict, logs.json (via _write_outputs) and the telemetry summary
        if self._dp_epsilon is not None:
            results["dp_epsilon"] = self._dp_epsilon
            results["dp_delta"] = cfg.dp_delta
        if self._fit_tel is not None:
            self._fit_summary.update(
                best_val_epoch=int(best_epoch),
                best_val_metric=best_metric,
                dp_epsilon=self._dp_epsilon,
            )
            for key in ("site_skipped_rounds", "site_quarantined"):
                if results.get("site_health"):
                    self._fit_summary[key] = results["site_health"][key]
        if self.out_dir:
            with self.tracer.span("write-outputs"):
                self._write_outputs(results, iter_durations, best_state, fold)
        results["state"] = best_state
        return results

    def test_only(self, test_sites: list[SiteArrays], fold: int = 0) -> dict:
        """``mode="test"``: load the fold's best checkpoint and evaluate —
        reproduces the stored ``test_metrics`` without training."""
        cfg = self.cfg
        if not self.out_dir:
            raise ValueError('mode="test" needs out_dir (to find the checkpoint)')
        d = fold_dir(self.out_dir, "remote", cfg.task_id, fold)
        ckpt = os.path.join(d, "checkpoint_best.msgpack")
        if not os.path.exists(ckpt):
            raise FileNotFoundError(
                f'mode="test" but no trained checkpoint at {ckpt}'
            )
        self._num_sites = len(test_sites)
        state = self.init_state(
            jnp.ones((cfg.batch_size,) + test_sites[0].inputs.shape[1:], jnp.float32)
        )
        # eval needs only params + batch_stats; a full-state restore would tie
        # mode="test" to the training run's site count via engine-state shapes
        params, stats, meta = load_eval_state(ckpt, state.params, state.batch_stats)
        state = state.replace(params=params, batch_stats=stats)
        results = self._test_results(
            state, test_sites,
            int(meta.get("best_val_epoch", 0)), meta.get("best_val_metric"),
            stop_epoch=0, epoch_losses=[],
        )
        results["state"] = state
        return results

    def _test_results(self, state, test_sites, best_epoch, best_metric,
                      stop_epoch, epoch_losses, batch_size=None) -> dict:
        # batch_size threads the fold-local clamp (fit) through to the test
        # eval: values are identical either way (plan_eval mask-pads), but
        # reusing the validation evals' batch shape avoids a second XLA
        # compilation of the eval step at the unclamped shape.
        monitor = self.cfg.monitor_metric
        test_avg, test_metrics, site_results = self.evaluate(
            state, test_sites, batch_size=batch_size, per_site=True
        )
        monitored = test_metrics.value(monitor) if monitor != "loss" else test_avg.avg
        return {
            "agg_engine": self.cfg.agg_engine,
            "best_val_epoch": best_epoch,
            "best_val_metric": best_metric,
            "stopped_epoch": stop_epoch,
            "test_metrics": [[round(test_avg.avg, 5), round(monitored, 5)]],
            "test_scores": {
                n: test_metrics.value(n)
                for n in ("accuracy", "f1", "precision", "recall", "auc")
            },
            "site_test_metrics": [
                [[round(a.avg, 5),
                  round(m.value(monitor) if monitor != "loss" else a.avg, 5)]]
                for a, m in site_results
            ],
            "epoch_losses": epoch_losses,
        }

    # -- internals -------------------------------------------------------

    def _epoch_row(self, fold, epoch, epoch_loss, e_start, state):
        """One per-epoch metrics.jsonl record: loss/timing/transfer plus the
        on-device per-site accumulators. The losses fetch in run_epoch
        already synchronized the epoch, so reading the small [S] telemetry
        arrays here adds no extra device round trip of consequence."""
        from ..parallel.distributed import fetch_site_outputs

        row = {
            "kind": "epoch", "fold": fold, "epoch": epoch,
            "train_loss": epoch_loss,
            "epoch_seconds": round(time.perf_counter() - e_start, 6),
            "transfer_bytes": self._last_transfer_bytes,
            # spent privacy so far (r20, privacy/accounting.py): null when
            # the DP mechanism is off or noiseless (ε = ∞ is reported as
            # null by the strict-JSON contract anyway) — a REQUIRED epoch
            # key, so a DP run's per-epoch ε trail is schema-guaranteed
            "dp_epsilon": self._dp_epsilon,
        }
        t = (
            fetch_site_outputs(state.telemetry, self.mesh)
            if state.telemetry is not None else None
        )
        if t is not None:
            row.update(
                site_grad_sq_last=[float(v) for v in t["grad_sq_last"]],
                site_grad_sq_sum=[float(v) for v in t["grad_sq_sum"]],
                site_grad_sq_max=[float(v) for v in t["grad_sq_max"]],
                site_residual_sq_sum=[
                    float(v) for v in t["residual_sq_sum"]
                ],
                update_sq_last=float(t["update_sq_last"][0]),
                payload_bytes=float(t["payload_bytes"][0]),
                # per-tier split (r18): inter-slice (DCN) bytes shipped so
                # far — 0.0 on single-slice runs
                dcn_bytes=float(t.get("dcn_bytes", [0.0])[0]),
                rounds=int(t["rounds"][0]),
                # slice-quorum holds (r19): rounds the min_slices floor
                # declined so far — 0 off sliced/fault-free runs
                held_rounds=int(t.get("held_rounds", [0])[0]),
            )
        else:  # epoch rows keep one schema even if metrics are absent
            row.update(
                site_grad_sq_last=[], site_grad_sq_sum=[],
                site_grad_sq_max=[], site_residual_sq_sum=[],
                update_sq_last=0.0, payload_bytes=0.0, dcn_bytes=0.0,
                rounds=0, held_rounds=0,
            )
        self._fit_tel.append(row)

    def _pretrain(self, state, train_sites, val_sites, verbose):
        pa = self.cfg.pretrain_args
        largest = int(np.argmax([len(s) for s in train_sites]))
        # zero every other site's examples: same SPMD program, one active site
        masked = [
            s if i == largest else SiteArrays(s.inputs[:0], s.labels[:0], s.indices[:0])
            for i, s in enumerate(train_sites)
        ]
        pre_opt = make_optimizer(self.cfg.optimizer, pa.learning_rate)
        # Pretrain is a single-site warm start: use exact (dSGD) gradients
        # regardless of the configured engine — rankDAD/powerSGD compression
        # during warm-up would diverge from the reference's plain local SGD.
        pre_engine = make_engine("dSGD", precision_bits=self.cfg.precision_bits)
        pre_epoch_fn = make_train_epoch_fn(
            self.task, pre_engine, pre_opt, self.mesh, pa.local_iterations,
            rounds_scan_xs=self.cfg.rounds_scan_xs,
        )
        pre_state = TrainState(
            params=state.params,
            batch_stats=state.batch_stats,
            opt_state=pre_opt.init(state.params),
            engine_state=jax.tree.map(
                lambda a: jnp.stack([a] * self._num_sites), pre_engine.init(state.params)
            ),
            rng=state.rng,
            round=state.round,
            health=state.health,
            # pre_epoch_fn is built telemetry-off (warm-up metrics would
            # pollute the federated accumulators); None matches its program
            telemetry=None,
        )
        with self.tracer.span("pretrain"):
            for epoch in range(1, pa.epochs + 1):
                fb = plan_epoch(
                    masked, pa.batch_size, seed=self.cfg.seed * 7 + epoch,
                    pad_mode="mask",
                )
                pre_state, losses = pre_epoch_fn(pre_state, *self._put_batch(fb))
                if verbose:
                    log_info(f"[pretrain site {largest}] epoch {epoch}: "
                             f"loss={np.asarray(losses).mean():.4f}")
        # warm-started params; fresh optimizer (and health) for the federated
        # phase — pretrain skips/quarantines must not leak into the real run
        return TrainState(
            params=pre_state.params,
            batch_stats=pre_state.batch_stats,
            opt_state=self.optimizer.init(pre_state.params),
            engine_state=state.engine_state,
            rng=state.rng,
            round=pre_state.round,
            health=state.health,
            telemetry=state.telemetry,
            # personalized head rows (r20) survive the warm start untouched
            # — they are fresh common-model rows at this point anyway
            personal=state.personal,
        )

    def _write_outputs(self, results, iter_durations, best_state, fold):
        if not self._coordinator():
            return  # every process computes identical replicated results;
            # only process 0 touches the (shared) output directory
        cfg = self.cfg
        comp = self._cache.get("time_spent_on_computation", [])
        cum = self._cache.get("cumulative_total_duration", [])
        site_tm = results.get("site_test_metrics") or []
        for i in range(self._num_sites):
            d = fold_dir(self.out_dir, f"local{i}", cfg.task_id, fold)
            # Each site's log carries ITS OWN test metrics (reference
            # local.py:51-52 writes genuinely per-site logs). The duration
            # lists are shared by design: all sites execute as one fused SPMD
            # program, so wall-clock is common — the extra key records that.
            write_logs_json(
                d, cfg.agg_engine,
                site_tm[i] if i < len(site_tm) else results["test_metrics"],
                results["best_val_epoch"],
                cum, comp, iter_durations, side="local",
                extra={"site_index": i, "pooled_test_metrics": results["test_metrics"],
                       "durations_shared_across_sites": True,
                       **health_log_fields(results.get("site_health"), i),
                       **telemetry_log_fields(results.get("site_telemetry"), i),
                       **privacy_log_fields(results)},
            )
        d = fold_dir(self.out_dir, "remote", cfg.task_id, fold)
        write_logs_json(
            d, cfg.agg_engine, results["test_metrics"], results["best_val_epoch"],
            cum, comp, iter_durations, side="remote",
            extra={**health_log_fields(results.get("site_health")),
                   **telemetry_log_fields(results.get("site_telemetry")),
                   **privacy_log_fields(results)},
        )
        write_test_metrics_csv(d, fold, results["test_scores"])
        save_checkpoint(
            os.path.join(d, "checkpoint_best.msgpack"),
            best_state,
            meta={"best_val_epoch": results["best_val_epoch"],
                  "best_val_metric": results["best_val_metric"], "fold": fold},
        )
        zip_global_results(
            self.out_dir, num_sites=self._num_sites, task_id=cfg.task_id
        )
