"""Device mesh construction — the communication backend.

This replaces the reference's COINSTAC transport layer (L0): Docker containers
exchanging JSON payloads through a message bus (reference ``entry.py:5``,
``local.py:19``, ``remote.py:13``). In the TPU build, every federated site lives
on a slice of a ``jax.sharding.Mesh`` with a ``"site"`` axis; the local→remote
gradient ship + remote→local broadcast collapses into XLA collectives over ICI
(multi-host: DCN). See SURVEY.md §2.2.

Axes:
  - ``site``  — one federated site per mesh index (or per core-group).
  - ``model`` — optional inner axis for tensor/sequence sharding within a site
                (a TPU-build extension; the reference is single-device per site).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SITE_AXIS = "site"
MODEL_AXIS = "model"
# vmap axis name for sites folded onto one device (several simulated sites per
# chip, e.g. 32 sites on 8 chips): the trainer nests a vmap over the local
# site block inside shard_map, and cross-site collectives run over the
# (SITE_AXIS, FOLD_AXIS) pair. Never a mesh axis.
FOLD_AXIS = "site_fold"


def make_site_mesh(
    num_sites: int | None = None,
    devices: list | None = None,
    model_axis_size: int = 1,
) -> Mesh:
    """Build a ``(site, model)`` mesh.

    ``num_sites`` defaults to ``len(devices) // model_axis_size``. When fewer
    devices than sites are available, callers should fold multiple sites onto
    one device via a batched site dimension instead (see trainer); this function
    requires num_sites * model_axis_size == number of devices used.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_sites is None:
        num_sites = len(devices) // model_axis_size
    need = num_sites * model_axis_size
    if need > len(devices):
        raise ValueError(
            f"need {need} devices for {num_sites} sites × model={model_axis_size}, "
            f"have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(num_sites, model_axis_size)
    return Mesh(arr, (SITE_AXIS, MODEL_AXIS))


def site_sharding(mesh: Mesh, *trailing_axes) -> NamedSharding:
    """Sharding with the leading dim split over ``site`` (per-site data)."""
    return NamedSharding(mesh, P(SITE_AXIS, *trailing_axes))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (global params — all sites hold the same
    weights between rounds, as in the reference where the remote broadcasts the
    aggregated update back to every site)."""
    return NamedSharding(mesh, P())


def host_mesh(num_sites: int, model_axis_size: int = 1) -> Mesh:
    """Mesh over CPU host devices, for the simulator path (tests / local dev).

    Requires ``XLA_FLAGS=--xla_force_host_platform_device_count=N``; this is the
    TPU-build replacement for the reference's Docker-based COINSTAC simulator
    (SURVEY.md §4.1).
    """
    cpus = [d for d in jax.devices() if d.platform == "cpu"]
    if not cpus:
        raise RuntimeError(
            "host_mesh needs CPU host devices; set "
            'jax.config.update("jax_platforms", "cpu") and '
            'jax.config.update("jax_num_cpu_devices", N) before first jax use '
            "(see tests/conftest.py)"
        )
    return make_site_mesh(num_sites, cpus, model_axis_size)
