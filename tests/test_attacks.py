"""Hostile-site tests (r17): AttackPlan semantics, the traced byzantine
transforms, robust aggregation defending the round, the anomaly-scored
reputation quarantine, the FaultPlan delay×NaN interaction, rejoin-after-
quarantine state resets, and the 512-packed-site attack×churn acceptance
gate.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.checks.sanitize import jit_cache_size
from dinunet_implementations_tpu.core.config import FSArgs
from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.parallel import host_mesh
from dinunet_implementations_tpu.robustness import (
    AttackPlan,
    FaultPlan,
    attack_window,
    make_attack_fn,
    parse_attack_plan,
    reset_slot_state,
)
from dinunet_implementations_tpu.robustness.attacks import (
    ATTACK_COLLUDE,
    ATTACK_FREE_RIDER,
    ATTACK_NOISE,
    ATTACK_SCALE,
    ATTACK_SIGN_FLIP,
)
from dinunet_implementations_tpu.robustness.faults import poison_inputs
from dinunet_implementations_tpu.trainer.steps import (
    FederatedTask,
    init_train_state,
    make_optimizer,
    make_train_epoch_fn,
)


# ---------------------------------------------------------------------------
# AttackPlan: declarative semantics, JSON round-trip, window math
# ---------------------------------------------------------------------------


def test_attack_plan_json_roundtrip(tmp_path):
    plan = AttackPlan(
        sign_flip=((2, 0, -1),), scale=((3, 5, 9),), scale_factor=7.5,
        noise=((4, 0, 3),), noise_std=0.5, free_rider=((5, 2, -1),),
        collude=((6, 0, -1), (7, 0, -1)), collude_scale=3.0,
    )
    assert AttackPlan.from_json(plan.to_json()) == plan
    assert AttackPlan.from_json(json.dumps(plan.to_json())) == plan
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(plan.to_json()))
    assert parse_attack_plan(f"@{p}") == plan
    assert parse_attack_plan(str(p)) == plan
    assert parse_attack_plan('{"sign_flip": [[1, 0, -1]]}') == AttackPlan(
        sign_flip=((1, 0, -1),)
    )
    assert parse_attack_plan(None) is None


def test_attack_plan_rejects_malformed():
    with pytest.raises(ValueError, match="triples"):
        AttackPlan(sign_flip=((1, 2),))
    with pytest.raises(ValueError, match="bad AttackPlan"):
        AttackPlan(scale=((-1, 0, 2),))
    with pytest.raises(ValueError, match="bad AttackPlan"):
        AttackPlan(noise=((0, 5, 2),))  # last < first
    with pytest.raises(ValueError, match="unknown AttackPlan keys"):
        AttackPlan.from_json({"sign_flop": []})
    # one attack per (site, round) cell: overlapping windows are ambiguous
    with pytest.raises(ValueError, match="overlap"):
        AttackPlan(sign_flip=((1, 0, 10),), scale=((1, 5, -1),))
    # same site, disjoint windows: fine
    AttackPlan(sign_flip=((1, 0, 4),), scale=((1, 5, -1),))


def test_attack_window_codes_and_chunk_independence():
    plan = AttackPlan(
        sign_flip=((0, 2, 4),), scale=((1, 0, -1),), free_rider=((2, 3, 3),),
    )
    full = plan.codes(4, 0, 8)
    assert full[0, 1] == 0 and (full[0, 2:5] == ATTACK_SIGN_FLIP).all()
    assert (full[1] == ATTACK_SCALE).all()  # -1 = forever
    assert full[2, 3] == ATTACK_FREE_RIDER and full[2, 4] == 0
    assert (full[3] == 0).all()
    # chunk independence: any window split reproduces the same codes
    chunked = np.concatenate(
        [plan.codes(4, r0, 2) for r0 in (0, 2, 4, 6)], axis=1
    )
    np.testing.assert_array_equal(full, chunked)
    assert attack_window(AttackPlan(), 4, 0, 8) is None
    assert attack_window(None, 4, 0, 8) is None
    assert plan.attacker_sites() == (0, 1, 2)


def test_attack_transforms_per_family():
    plan = AttackPlan(
        sign_flip=((0, 0, -1),), scale=((1, 0, -1),), scale_factor=10.0,
        noise=((2, 0, -1),), noise_std=0.1,
        free_rider=((3, 0, -1),), collude=((4, 0, -1), (5, 0, -1)),
        collude_scale=5.0,
    )
    atk = jax.jit(make_attack_fn(plan), static_argnums=())
    g = {"k": jnp.ones((3, 2)), "b": jnp.full((2,), 2.0)}
    rnd = jnp.zeros((), jnp.int32)

    honest = atk(g, jnp.int32(0), rnd, jnp.int32(9))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), honest, g)

    flipped = atk(g, jnp.int32(ATTACK_SIGN_FLIP), rnd, jnp.int32(0))
    np.testing.assert_allclose(np.asarray(flipped["k"]), -1.0)
    scaled = atk(g, jnp.int32(ATTACK_SCALE), rnd, jnp.int32(1))
    np.testing.assert_allclose(np.asarray(scaled["b"]), 20.0)
    rider = atk(g, jnp.int32(ATTACK_FREE_RIDER), rnd, jnp.int32(3))
    assert float(sum(jnp.abs(v).sum() for v in jax.tree.leaves(rider))) == 0.0

    # noise: deterministic per (site, round), different across them
    n1 = atk(g, jnp.int32(ATTACK_NOISE), rnd, jnp.int32(2))
    n2 = atk(g, jnp.int32(ATTACK_NOISE), rnd, jnp.int32(2))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), n1, n2)
    n3 = atk(g, jnp.int32(ATTACK_NOISE), rnd + 1, jnp.int32(2))
    assert not np.allclose(np.asarray(n1["k"]), np.asarray(n3["k"]))
    assert not np.allclose(np.asarray(n1["k"]), np.asarray(g["k"]))

    # collusion: the whole clique ships ONE direction per round, scaled to
    # collude_scale × the member's own gradient norm
    c4 = atk(g, jnp.int32(ATTACK_COLLUDE), rnd, jnp.int32(4))
    c5 = atk(g, jnp.int32(ATTACK_COLLUDE), rnd, jnp.int32(5))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b), c4, c5)
    gn = float(jnp.sqrt(sum(
        jnp.square(v).sum() for v in jax.tree.leaves(g)
    )))
    cn = float(jnp.sqrt(sum(
        jnp.square(v).sum() for v in jax.tree.leaves(c4)
    )))
    np.testing.assert_allclose(cn, 5.0 * gn, rtol=1e-5)
    # and the direction changes per round
    c4r1 = atk(g, jnp.int32(ATTACK_COLLUDE), rnd + 1, jnp.int32(4))
    assert not np.allclose(np.asarray(c4["k"]), np.asarray(c4r1["k"]))


# ---------------------------------------------------------------------------
# the attacked epoch: defense, reputation, compile stability
# ---------------------------------------------------------------------------


def _epoch_corner(num_sites=8, identical=True, seed=0):
    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    task = FederatedTask(model)
    opt = make_optimizer("adam", 1e-2)
    S, steps, B, D = num_sites, 4, 4, model.in_size
    rng = np.random.default_rng(seed)
    if identical:
        one = rng.normal(size=(1, steps, B, D)).astype(np.float32)
        x = jnp.asarray(np.repeat(one, S, axis=0))
    else:
        x = jnp.asarray(rng.normal(size=(S, steps, B, D)).astype(np.float32))
    y = jnp.asarray((np.arange(S * steps * B).reshape(S, steps, B) % 2)
                    .astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)
    return task, opt, x, y, w


@pytest.mark.parametrize("mesh_fn", [lambda: None, lambda: host_mesh(2)],
                         ids=["vmap", "packed-mesh"])
def test_sign_flip_defended_round_matches_clean_round(mesh_fn):
    """With identical sites, the coordinate median of 7 honest gradients +
     1 sign-flipped one IS the honest gradient — the defended attacked run
    reproduces the clean run's parameters (up to fp noise of the differing
    reduction), on the vmap fold AND the packed two-level mesh path. The
    undefended attacked run diverges (the mean is steered by -g). SGD
    optimizer: with identical sites a sign-flip SCALES the honest mean
    without turning it, and Adam's per-coordinate normalization would hide
    exactly that dilution."""
    task, _, x, y, w = _epoch_corner()
    opt = make_optimizer("sgd", 1e-1)
    S = x.shape[0]
    plan = AttackPlan(sign_flip=((3, 0, -1),))
    am = jnp.asarray(attack_window(plan, S, 0, x.shape[1]))

    def run(robust, attacked):
        eng = make_engine("dSGD", robust_agg=robust)
        state = init_train_state(
            task, eng, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S,
            reputation=robust != "none",
        )
        fn = make_train_epoch_fn(
            task, eng, opt, mesh=mesh_fn(), attack_plan=plan,
            robust_agg=robust, reputation_rounds=0,
        )
        s, _ = fn(state, x, y, w, None, am if attacked else None)
        return s

    clean = run("none", attacked=False)
    defended = run("coordinate_median", attacked=True)
    undefended = run("none", attacked=True)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5),
        clean.params, defended.params,
    )
    diffs = [
        float(np.abs(np.asarray(a) - np.asarray(b)).max())
        for a, b in zip(jax.tree.leaves(clean.params),
                        jax.tree.leaves(undefended.params))
    ]
    assert max(diffs) > 1e-3, "the undefended attack did not even steer"


def test_reputation_quarantines_persistent_attacker():
    """An 8-site cohort with one gradient-scaling attacker: the anomaly
    z-score flags exactly the attacker, its suspect streak reaches the
    threshold, and the SAME sticky quarantine flag a NaN streak uses
    latches — honest sites stay clean."""
    task, opt, x, y, w = _epoch_corner(identical=False)
    S = x.shape[0]
    plan = AttackPlan(scale=((2, 0, -1),), scale_factor=50.0)
    am = jnp.asarray(attack_window(plan, S, 0, x.shape[1]))
    eng = make_engine("dSGD", robust_agg="trimmed_mean")
    state = init_train_state(
        task, eng, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S,
        reputation=True,
    )
    fn = make_train_epoch_fn(
        task, eng, opt, mesh=None, attack_plan=plan,
        robust_agg="trimmed_mean", reputation_z=2.0, reputation_rounds=3,
    )
    for _ in range(2):
        state, losses = fn(state, x, y, w, None, am)
    h = jax.tree.map(np.asarray, state.health)
    assert h["quarantined"].tolist() == [0, 0, 1, 0, 0, 0, 0, 0]
    assert h["anomaly"][2] == h["anomaly"].max() and h["anomaly"][2] > 0.3
    assert h["suspect_streak"][2] >= 3
    # once quarantined the attacker is zero-weighted like a NaN site
    assert h["skips"][2] > 0 and (h["skips"][np.arange(S) != 2] == 0).all()
    assert np.isfinite(np.asarray(losses)).all()


def test_attack_pattern_change_never_recompiles():
    """The [S, rounds] code mask is a traced input: flipping WHO attacks
    WHEN between epochs reuses the one compiled program (the FaultPlan
    one-program contract, extended to attacks)."""
    task, opt, x, y, w = _epoch_corner(num_sites=4)
    S, steps = x.shape[0], x.shape[1]
    plan = AttackPlan(sign_flip=((0, 0, -1),), scale=((1, 0, -1),))
    eng = make_engine("dSGD", robust_agg="norm_clip")
    state = init_train_state(
        task, eng, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S,
        reputation=True,
    )
    fn = make_train_epoch_fn(
        task, eng, opt, mesh=None, attack_plan=plan, robust_agg="norm_clip",
    )
    rng = np.random.default_rng(0)
    for _ in range(3):
        am = jnp.asarray(
            rng.integers(0, 3, size=(S, steps)).astype(np.int32)
        )
        state, _ = fn(state, x, y, w, None, am)
    assert jit_cache_size(fn) == 1


def test_attack_mask_without_plan_rejected():
    task, opt, x, y, w = _epoch_corner(num_sites=2)
    eng = make_engine("dSGD")
    state = init_train_state(
        task, eng, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=2
    )
    fn = make_train_epoch_fn(task, eng, opt, mesh=None)
    with pytest.raises(ValueError, match="attack_plan"):
        fn(state, x, y, w, None, jnp.zeros((2, x.shape[1]), jnp.int32))


# ---------------------------------------------------------------------------
# FaultPlan delay_at × NaN poison on the same (site, round)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("staleness", [0, 2], ids=["bulk-sync", "async"])
def test_delayed_then_poisoned_update_is_masked_not_applied_late(staleness):
    """A site that is both STRAGGLING (delay_at) and NaN-POISONED on the
    same round must contribute nothing from that round — in the buffered-
    async mode especially, the poisoned update must never be deposited and
    served late at decayed weight. The delayed+poisoned run is bit-identical
    to the delayed-only run (the poison lands in a round block the site
    never ships), buffers stay NaN-free, and the site's non-finite streak
    stays 0 (it never ARRIVED non-finite)."""
    task, opt, x, y, w = _epoch_corner(num_sites=4, identical=False)
    S, steps = x.shape[0], x.shape[1]
    fault = FaultPlan(delay_at=((1, 1, 2),), nan_at=((1, 1),))
    live = jnp.asarray(fault.liveness(S, 0, steps))
    nan_mask = fault.nan_mask(S, 0, steps)
    assert nan_mask[1, 1] and live[1, 1] == 0  # same (site, round) cell
    x_poisoned = jnp.asarray(poison_inputs(np.asarray(x), nan_mask, 1))

    eng = make_engine("dSGD")
    state = init_train_state(
        task, eng, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S,
        staleness_bound=staleness,
    )
    fn = make_train_epoch_fn(
        task, eng, opt, mesh=None, staleness_bound=staleness,
    )
    s_poisoned, l_poisoned = fn(state, x_poisoned, y, w, live)
    s_delay_only, l_delay = fn(state, x, y, w, live)
    np.testing.assert_array_equal(
        np.asarray(l_poisoned), np.asarray(l_delay)
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
        s_poisoned.params, s_delay_only.params,
    )
    h = jax.tree.map(np.asarray, s_poisoned.health)
    assert h["streak"][1] == 0  # never arrived non-finite
    assert h["quarantined"].sum() == 0
    if staleness:
        for leaf in jax.tree.leaves(s_poisoned.buffers["grads"]):
            assert np.isfinite(np.asarray(leaf)).all(), (
                "a poisoned update was deposited into the staleness buffer"
            )


# ---------------------------------------------------------------------------
# rejoin-after-quarantine: reputation state resets with the slot
# ---------------------------------------------------------------------------


def test_reset_slot_state_clears_reputation_fields():
    """FedDaemon rejoin semantics (r17 satellite): a site rejoining at a new
    generation must start with a clean reputation — reset_slot_state zeroes
    the anomaly score and suspect streak along with the legacy counters,
    and leaves other slots untouched."""
    task, opt, x, y, w = _epoch_corner(identical=False)
    S = x.shape[0]
    plan = AttackPlan(scale=((2, 0, -1),), scale_factor=50.0)
    am = jnp.asarray(attack_window(plan, S, 0, x.shape[1]))
    eng = make_engine("dSGD", robust_agg="trimmed_mean")
    state = init_train_state(
        task, eng, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=S,
        reputation=True,
    )
    fn = make_train_epoch_fn(
        task, eng, opt, mesh=None, attack_plan=plan,
        robust_agg="trimmed_mean", reputation_z=2.0, reputation_rounds=3,
    )
    state, _ = fn(state, x, y, w, None, am)
    h = jax.tree.map(np.asarray, state.health)
    assert h["quarantined"][2] == 1 and h["anomaly"][2] > 0
    before_other = {k: v.copy() for k, v in h.items()}

    reset = reset_slot_state(state, 2, engine=eng)
    hr = jax.tree.map(np.asarray, reset.health)
    for key in ("streak", "skips", "quarantined", "suspect_streak",
                "anomaly"):
        assert hr[key][2] == 0, key
    mask = np.arange(S) != 2
    for key, old in before_other.items():
        np.testing.assert_array_equal(hr[key][mask], old[mask])


# ---------------------------------------------------------------------------
# attacks × membership churn at 512 packed sites — one compiled program
# ---------------------------------------------------------------------------


def test_attack_churn_512_packed_sites_one_compile(tmp_path):
    """The r17 packed acceptance scenario: 512 virtual sites packed
    64/device on the 8-device CPU mesh, trimmed-mean robust aggregation, a
    sign-flip + free-rider AttackPlan composed with straggler faults, and a
    join → leave → rejoin churn sequence — ONE epoch compilation for the
    whole lifetime, finite training throughout."""
    from test_membership import _SyntheticDaemon

    cfg = TrainConfig(
        task_id="FS-Classification", batch_size=4, sites_per_device=64,
        staleness_bound=2, staleness_decay=0.5,
        robust_agg="trimmed_mean", robust_trim_frac=0.1,
        reputation_z=3.0, reputation_rounds=6,
        fs_args=FSArgs(input_size=12, hidden_sizes=(16,)),
    )
    fault = FaultPlan(delay_at=((7, 1, 2),))
    attack = AttackPlan(
        sign_flip=((3, 0, -1), (130, 0, -1)), free_rider=((200, 0, -1),),
    )
    d = _SyntheticDaemon(
        cfg, capacity=512, spool_dir=str(tmp_path / "spool"),
        out_dir=str(tmp_path / "out"), quorum=1, poll_s=0.0,
        fault_plan=fault, attack_plan=attack, verbose=False,
    )
    assert dict(d.mesh.shape)["site"] == 8  # 512 packed 64 per device
    for i in range(500):
        assert d.apply_event(
            {"event": "join", "site": f"s{i}", "data_dir": f"mem://{i}"}
        )
    d._on_membership_change()
    assert d.train_epoch() is not None  # the one and only compilation
    for i in (3, 130, 499):
        d.apply_event({"event": "leave", "site": f"s{i}"})
    d._on_membership_change()
    assert d.train_epoch() is not None
    d.apply_event({"event": "join", "site": "s3", "data_dir": "mem://3"})
    d._on_membership_change()
    assert d.train_epoch() is not None
    assert d.table.generation_of("s3") == 2
    assert jit_cache_size(d.trainer.epoch_fn) == 1, (
        "attack/churn pattern changes retraced the epoch"
    )
    # the rejoined attacker restarted with a clean reputation slot
    slot = d.table.slot_of("s3")
    h = jax.tree.map(np.asarray, d.state.health)
    assert h["anomaly"].shape == (512,)
    summary = d.close()
    assert summary["epochs_run"] == 3
