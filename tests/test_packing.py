"""Site packing (r12): K-sites-per-chip virtualization with two-level
aggregation.

The packed site axis (parallel/mesh.py packed_site_mesh, trainer/steps.py
packed path, parallel/collectives.py PackedAxis) must be invisible to
results: packed(K) == unpacked trajectories per engine and pipeline, chaos
masks address VIRTUAL sites, checkpoints are pack-factor-agnostic (save at
K=4, resume at K=8, bit-exact state round-trip), one compiled program per
fit, and 512 virtual sites train on the 8-device CPU mesh — the fan-out cap
this round exists to break. test_folding.py keeps the deeper (slow)
equivalence runs; these are the tier-1 packing gates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.parallel.mesh import (
    host_mesh,
    pack_factor,
    packed_site_mesh,
)
from dinunet_implementations_tpu.trainer import (
    FederatedTask,
    init_train_state,
    make_optimizer,
    make_train_epoch_fn,
)

ENGINE_KW = {
    "dSGD": {},
    "rankDAD": dict(dad_reduction_rank=2, dad_num_pow_iters=2, dad_tol=1e-3),
    "powerSGD": dict(dad_reduction_rank=2),
}


def _data(S=4, steps=2, B=4, F=6, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(S, steps, B, F)).astype(np.float32))
    y = jnp.asarray((rng.random((S, steps, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)
    return x, y, w


def _build(engine_name, mesh, S, F=6, pipeline="host", seed_model=0,
           **epoch_kw):
    model = MSANNet(in_size=F, hidden_sizes=(8,), out_size=2)
    task = FederatedTask(model)
    engine = make_engine(engine_name, **ENGINE_KW[engine_name])
    opt = make_optimizer("sgd", 1e-2)
    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(seed_model),
        jnp.ones((4, F), jnp.float32), num_sites=S,
    )
    fn = make_train_epoch_fn(
        task, engine, opt, mesh, local_iterations=1, pipeline=pipeline,
        **epoch_kw,
    )
    return fn, state


def _run_epochs(fn, state, data, epochs=2, live=None):
    x, y, w = data
    losses = []
    for _ in range(epochs):
        if live is None:
            state, ls = fn(state, x, y, w)
        else:
            state, ls = fn(state, x, y, w, live)
        losses.extend(np.asarray(ls).tolist())
    return jax.tree.map(np.asarray, state), losses


def _assert_close(a, b, atol=1e-6):
    jax.tree.map(
        lambda u, v: np.testing.assert_allclose(u, v, atol=atol),
        a, b,
    )


# ---------------------------------------------------------------------------
# packed(K) == unpacked equivalence, per engine × pipeline
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("engine", ["dSGD", "rankDAD", "powerSGD"])
def test_packed_matches_unpacked(engine):
    """S=4 virtual sites: K=2 on a 2-device mesh must train identically to
    K=1 on a 4-device mesh (the S ≤ D acceptance gate) AND to the vmap fold
    — the two-level reduction changes the wire, never the math."""
    data = _data(seed=3)
    atol = 1e-6 if engine == "dSGD" else 1e-5
    fn_p, st_p = _build(engine, host_mesh(2), 4)
    fn_u, st_u = _build(engine, host_mesh(4), 4)
    fn_v, st_v = _build(engine, None, 4)
    s_p, l_p = _run_epochs(fn_p, st_p, data)
    s_u, l_u = _run_epochs(fn_u, st_u, data)
    s_v, l_v = _run_epochs(fn_v, st_v, data)
    np.testing.assert_allclose(l_p, l_u, atol=atol)
    np.testing.assert_allclose(l_p, l_v, atol=atol)
    _assert_close(s_p.params, s_u.params, atol)
    _assert_close(s_p.params, s_v.params, atol)
    # per-VIRTUAL-site engine state survives packing site-for-site
    _assert_close(s_p.engine_state, s_u.engine_state, atol)


@pytest.mark.parametrize("engine", ["dSGD", "rankDAD", "powerSGD"])
def test_packed_device_pipeline_matches_host(engine):
    """The device-resident pipeline under packing: on-device gather from the
    [K, N, ...] inventory block + two-level aggregation must be bit-exact
    with the packed host pipeline (one plan, two realizations)."""
    S, N, B, steps, F = 4, 8, 4, 2, 6
    rng = np.random.default_rng(1)
    inv_x = jnp.asarray(rng.normal(size=(S, N, F)).astype(np.float32))
    inv_y = jnp.asarray((rng.random((S, N)) > 0.5).astype(np.int32))
    idx = jnp.asarray(
        rng.integers(0, N, size=(S, steps, B)).astype(np.int32)
    )
    # host realization of the same plan
    flat = np.asarray(idx).reshape(S, -1)
    x = jnp.asarray(
        np.take_along_axis(np.asarray(inv_x), flat[..., None], axis=1)
    ).reshape(S, steps, B, F)
    y = jnp.asarray(
        np.take_along_axis(np.asarray(inv_y), flat, axis=1)
    ).reshape(S, steps, B)
    w = jnp.ones((S, steps, B), jnp.float32)

    mesh = host_mesh(2)  # K=2
    fn_d, st = _build(engine, mesh, S, pipeline="device")
    fn_h, _ = _build(engine, mesh, S, pipeline="host")
    s_d, l_d = _run_epochs(fn_d, st, (inv_x, inv_y, idx))
    s_h, l_h = _run_epochs(fn_h, st, (x, y, w))
    np.testing.assert_array_equal(l_d, l_h)
    jax.tree.map(
        lambda u, v: np.testing.assert_array_equal(u, v),
        s_d.params, s_h.params,
    )


# ---------------------------------------------------------------------------
# chaos: dead VIRTUAL site under packing
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("engine", ["dSGD", "rankDAD", "powerSGD"])
def test_dead_virtual_site_masks_at_virtual_granularity(engine):
    """A liveness mask addressing ONE virtual site inside a packed device
    block must have exactly the unpacked effect: packed(K=2) masked run ==
    unpacked (1/device) masked run, and the dead site's health counters land
    on the right VIRTUAL row."""
    S, steps = 4, 2
    data = _data(S=S, steps=steps, seed=5)
    live = np.ones((S, steps), np.float32)
    live[1, :] = 0.0  # virtual site 1 — the second row of device 0's block
    live = jnp.asarray(live)
    atol = 1e-6 if engine == "dSGD" else 1e-5
    fn_p, st_p = _build(engine, host_mesh(2), S)
    fn_u, st_u = _build(engine, host_mesh(4), S)
    s_p, l_p = _run_epochs(fn_p, st_p, data, epochs=1, live=live)
    s_u, l_u = _run_epochs(fn_u, st_u, data, epochs=1, live=live)
    np.testing.assert_allclose(l_p, l_u, atol=atol)
    _assert_close(s_p.params, s_u.params, atol)
    # the skip landed on virtual row 1 only, in both topologies
    np.testing.assert_array_equal(s_p.health["skips"], s_u.health["skips"])
    assert s_p.health["skips"][1] == steps
    assert s_p.health["skips"][0] == 0


def test_faultplan_chaos_packed_matches_unpacked():
    """FaultPlan-style scheduled drops + NaN poisoning through the DEVICE
    pipeline on a packed mesh: the poison gate rides the plan at [S] virtual
    granularity and the quarantine counters stay per-virtual-site."""
    S, N, B, steps, F = 4, 8, 4, 2, 6
    rng = np.random.default_rng(2)
    inv_x = jnp.asarray(rng.normal(size=(S, N, F)).astype(np.float32))
    inv_y = jnp.asarray((rng.random((S, N)) > 0.5).astype(np.int32))
    idx = jnp.asarray(rng.integers(0, N, size=(S, steps, B)).astype(np.int32))
    poison = np.zeros((S, steps), np.float32)
    poison[2, 0] = 1.0  # NaN-poison virtual site 2, round 0
    poison = jnp.asarray(poison)
    live = jnp.ones((S, steps), jnp.float32)

    def run(mesh):
        fn, st = _build("dSGD", mesh, S, pipeline="device")
        st, ls = fn(st, inv_x, inv_y, idx, live, poison)
        return jax.tree.map(np.asarray, st), np.asarray(ls)

    s_p, l_p = run(host_mesh(2))
    s_u, l_u = run(host_mesh(4))
    np.testing.assert_array_equal(l_p, l_u)
    np.testing.assert_array_equal(
        s_p.health["streak"], s_u.health["streak"]
    )
    # the poisoned round skipped exactly virtual site 2
    assert s_p.health["skips"][2] == 1
    assert int(np.asarray(s_p.health["skips"]).sum()) == 1


# ---------------------------------------------------------------------------
# checkpoint: pack-factor-agnostic state
# ---------------------------------------------------------------------------


def test_checkpoint_saved_at_k4_resumes_at_k8_bit_exact(tmp_path):
    """The checkpoint payload is keyed by VIRTUAL site ([S, ...] arrays) —
    a fit checkpointed at K=4 must restore bit-exactly into a K=8 (and K=2)
    topology, and the resumed trajectories must agree."""
    from dinunet_implementations_tpu.trainer.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    S = 8
    data = _data(S=S, seed=9)
    fn4, st = _build("powerSGD", host_mesh(2), S)  # K=4
    s4, _ = _run_epochs(fn4, st, data, epochs=1)
    path = str(tmp_path / "ckpt.msgpack")
    save_checkpoint(path, s4)

    for mesh_sites, k in ((1, 8), (4, 2)):
        fn_k, st_k = _build("powerSGD", host_mesh(mesh_sites), S)
        restored = load_checkpoint(path, st_k)
        # bit-exact round-trip: every leaf, including the per-virtual-site
        # engine state / health rows, at a DIFFERENT pack factor
        jax.tree.map(
            lambda u, v: np.testing.assert_array_equal(
                np.asarray(u), np.asarray(v)
            ),
            jax.tree.map(np.asarray, s4),
            jax.tree.map(np.asarray, restored),
        )
        # and the continued trajectory matches the K=4 continuation
        s_cont_k, l_k = _run_epochs(fn_k, restored, data, epochs=1)
        s_cont_4, l_4 = _run_epochs(fn4, s4, data, epochs=1)
        np.testing.assert_allclose(l_k, l_4, atol=1e-5)
        _assert_close(s_cont_k.params, s_cont_4.params, atol=1e-5)


# ---------------------------------------------------------------------------
# one compiled program + the 512-site acceptance smoke
# ---------------------------------------------------------------------------


def test_one_compile_under_packing():
    """CompileGuard: a packed fit is ONE compiled SPMD program — chained
    epochs and changing fault masks never recompile."""
    from jax.sharding import NamedSharding

    from dinunet_implementations_tpu.checks.sanitize import jit_cache_size
    from dinunet_implementations_tpu.trainer.steps import _state_specs

    S = 8
    mesh = host_mesh(2)
    data = _data(S=S, seed=4)
    fn, st = _build("dSGD", mesh, S)
    # commit the fresh state to its steady-state sharding first — the
    # trainer's _place_state move (an uncommitted init state costs one
    # warmup recompile by design; that is not what this test gates)
    st = jax.tree.map(
        lambda a, spec: jax.device_put(a, NamedSharding(mesh, spec)),
        st, _state_specs(st),
    )
    live0 = jnp.ones((S, 2), jnp.float32)
    live1 = live0.at[3, :].set(0.0)
    x, y, w = data
    for lv in (live0, live0, live1):  # chained device states, changing mask
        st, _ = fn(st, x, y, w, lv)
    jax.tree.map(np.asarray, st)
    assert jit_cache_size(fn) == 1


def test_512_sites_train_on_8_device_mesh():
    """The acceptance smoke: 512 virtual sites packed 64/device on the
    8-device CPU mesh train as one compiled program with finite losses and
    per-virtual-site state."""
    from dinunet_implementations_tpu.checks.sanitize import jit_cache_size

    S = 512
    mesh = packed_site_mesh(S, 64)
    assert dict(mesh.shape)["site"] == 8
    assert pack_factor(mesh, S) == 64
    data = _data(S=S, steps=1, B=2, seed=11)
    fn, st = _build("dSGD", mesh, S)
    st, losses = _run_epochs(fn, st, data, epochs=1)
    assert np.isfinite(losses).all()
    assert st.health["skips"].shape == (S,)
    assert jit_cache_size(fn) == 1


# ---------------------------------------------------------------------------
# topology helpers + wire-model semantics
# ---------------------------------------------------------------------------


def test_packed_mesh_helpers_validate():
    with pytest.raises(ValueError, match="divide"):
        packed_site_mesh(6, 4)
    with pytest.raises(ValueError, match=">= 1"):
        packed_site_mesh(8, 0)
    mesh = packed_site_mesh(8, 4)
    assert dict(mesh.shape)["site"] == 2
    assert pack_factor(mesh, 8) == 4
    assert pack_factor(None, 8) == 8
    with pytest.raises(ValueError, match="divide"):
        pack_factor(mesh, 7)


def test_wire_models_pack_semantics():
    """Per-device wire accounting (the r12 satellite): psum-shaped
    exchanges (dSGD, powerSGD) are pack-invariant — the local packed-axis
    reduce is free — while rankDAD's factor gather genuinely ships every
    virtual site's factors (×K); its dense 1-D leaves stay K-invariant."""
    from dinunet_implementations_tpu.telemetry.metrics import (
        payload_bytes_of,
    )

    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    task = FederatedTask(model)
    params, _ = task.init_variables(
        jax.random.PRNGKey(0), jnp.ones((4, 6), jnp.float32)
    )
    for name in ("dSGD", "powerSGD"):
        e = make_engine(name, **ENGINE_KW[name])
        assert payload_bytes_of(e, params, pack=64) == payload_bytes_of(
            e, params, pack=1
        )
    rd = make_engine("rankDAD", **ENGINE_KW["rankDAD"])
    b1 = payload_bytes_of(rd, params, pack=1)
    b4 = payload_bytes_of(rd, params, pack=4)
    # dense (1-D bias) bytes are the pack-invariant part
    dense = sum(
        int(np.prod(g.shape)) * 4
        for g in jax.tree.leaves(params) if g.ndim < 2
    )
    assert b4 - dense == 4 * (b1 - dense)
    # and the structured model sums to the scalar model at every pack
    from dinunet_implementations_tpu.telemetry.metrics import (
        modeled_wire_shapes,
    )

    for pack in (1, 4, 64):
        shapes = modeled_wire_shapes(rd, params, pack=pack)
        total = sum(int(np.prod(s)) * d.itemsize for s, d in shapes)
        assert total == int(payload_bytes_of(rd, params, pack=pack))
