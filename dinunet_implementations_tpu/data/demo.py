"""Self-contained demo fixture generator (no reference checkout needed).

The reference bundles its whole 5-site simulator tree in-repo
(``/root/reference/datasets/test_fsl`` — ~430 files of per-site covariate
CSVs + aseg-stats TSVs + ``inputspec.json``), so a fresh clone can run the
simulator immediately. Shipping 430 data files in a wheel is the wrong
trade; instead this module *generates* an equivalent tree on demand, in the
exact simulator layout (``input/local{i}/simulatorRun`` + per-site
``inputspec.json``), with a real class signal so the demo actually trains to
a good AUC.

    python -m dinunet_implementations_tpu.data.demo datasets/demo
    dinunet-tpu --data-path datasets/demo --epochs 20 --out-dir out

Layouts match the reference fixtures:
- FS task: ``siteN_Covariate.csv`` (``freesurferfile,isControl,age``) +
  per-subject ``*_aseg_stats.txt`` name/value TSVs (reference
  ``datasets/test_fsl/input/local*/simulatorRun``).
- ICA task: ``timecourses.npz`` + ``labels.csv``, windowing params in the
  inputspec (reference ``datasets/icalstm/inputspec.json`` shapes, scaled
  down).
"""

from __future__ import annotations

import json
import os

import numpy as np

#: feature names for the generated aseg files — the demo keeps the
#: reference's 66-feature input_size so compspec defaults work unchanged
#: (reference ``compspec.json`` input_size default; fixture files have 66
#: value rows after the header).
N_FS_FEATURES = 66


def make_fs_demo_tree(
    root: str,
    n_sites: int = 4,
    subjects: int = 32,
    n_features: int = N_FS_FEATURES,
    seed: int = 0,
    shift: float = 1.0,
) -> str:
    """Generate an FS-Classification simulator tree under ``root``.

    Class signal: label-1 subjects get a ``+shift``·σ bump in the first
    quarter of the features (on top of per-feature scales spanning ~3
    decades, like real aseg volumes). Per-site subject counts vary ±25%
    around ``subjects`` to mirror the reference fixture's heterogeneous
    sites (73/50/100/80/120).
    """
    rng = np.random.default_rng(seed)
    scales = 10.0 ** rng.uniform(1, 4, size=n_features)  # aseg-like spread
    spec = []
    for i in range(n_sites):
        d = os.path.join(root, "input", f"local{i}", "simulatorRun")
        os.makedirs(d, exist_ok=True)
        n_i = int(subjects * (0.75 + 0.5 * rng.random()))
        y = rng.integers(0, 2, n_i)
        cov = os.path.join(d, f"site{i + 1}_Covariate.csv")
        with open(cov, "w") as fh:
            fh.write("freesurferfile,isControl,age\n")
            for j in range(n_i):
                age = 20 + 50 * rng.random()
                fh.write(
                    f"subject{j}_aseg_stats.txt,"
                    f"{'True' if y[j] else 'False'},{age:.1f}\n"
                )
        for j in range(n_i):
            x = np.abs(rng.normal(1.0, 0.2, n_features))
            if y[j]:
                x[: n_features // 4] += shift * 0.2
            vals = x * scales
            with open(os.path.join(d, f"subject{j}_aseg_stats.txt"), "w") as fh:
                fh.write(f"Measure:volume\tsubject{j}\n")
                for k in range(n_features):
                    fh.write(f"feature-{k}\t{vals[k]:.2f}\n")
        spec.append({k: {"value": v} for k, v in dict(
            labels_file=f"site{i + 1}_Covariate.csv",
            data_column="freesurferfile",
            labels_column="isControl",
            mode="train",
            input_size=n_features,
            hidden_sizes=[256, 128, 64, 32],
            num_class=2,
        ).items()})
    with open(os.path.join(root, "inputspec.json"), "w") as fh:
        json.dump(spec, fh, indent=1)
    return root


def make_ica_demo_tree(
    root: str,
    n_sites: int = 2,
    subjects: int = 24,
    comps: int = 16,
    temporal: int = 80,
    window: int = 10,
    stride: int = 10,
    seed: int = 0,
    shift: float = 0.8,
) -> str:
    """Generate an ICA-Classification simulator tree under ``root``.

    Class signal: label-1 subjects get a ``+shift``·σ mean shift in the
    first quarter of the components.
    """
    rng = np.random.default_rng(seed)
    spec = []
    for i in range(n_sites):
        d = os.path.join(root, "input", f"local{i}", "simulatorRun")
        os.makedirs(d, exist_ok=True)
        y = rng.integers(0, 2, subjects)
        X = rng.normal(size=(subjects, comps, temporal)).astype(np.float32)
        X[:, : comps // 4] += (y[:, None, None] * shift).astype(np.float32)
        np.savez(os.path.join(d, "timecourses.npz"), X)
        with open(os.path.join(d, "labels.csv"), "w") as fh:
            fh.write("index,label\n")
            for j in range(subjects):
                fh.write(f"{j},{int(y[j])}\n")
        spec.append({k: {"value": v} for k, v in dict(
            data_file="timecourses.npz",
            labels_file="labels.csv",
            temporal_size=temporal,
            window_size=window,
            window_stride=stride,
            num_components=comps,
            input_size=32,
            hidden_size=24,
            num_class=2,
        ).items()})
    with open(os.path.join(root, "inputspec.json"), "w") as fh:
        json.dump(spec, fh, indent=1)
    return root


def make_multimodal_demo_tree(
    root: str,
    n_sites: int = 2,
    subjects: int = 24,
    n_features: int = 16,
    comps: int = 8,
    temporal: int = 40,
    window: int = 10,
    stride: int = 10,
    seed: int = 0,
    shift: float = 0.8,
) -> str:
    """Generate a Multimodal-Classification simulator tree under ``root``
    (the r15 graduation of the dormant transformer workload): each site dir
    holds BOTH modalities — the FS covariate CSV + per-subject aseg files
    AND the ICA ``timecourses.npz`` — joined positionally (row i of the
    covariate ↔ subject i of the timecourses), the layout
    data/multimodal.py reads. The inputspec pins demo-sized transformer
    dims (embed 32 / 4 heads / 1 layer) so the fit smoke stays CPU-cheap.

    Class signal in both modalities: label-1 subjects get a ``+shift``·σ
    bump in the first quarter of the FS features and of the ICA components.
    """
    rng = np.random.default_rng(seed)
    scales = 10.0 ** rng.uniform(1, 4, size=n_features)
    spec = []
    for i in range(n_sites):
        d = os.path.join(root, "input", f"local{i}", "simulatorRun")
        os.makedirs(d, exist_ok=True)
        y = rng.integers(0, 2, subjects)
        cov = os.path.join(d, f"site{i + 1}_Covariate.csv")
        with open(cov, "w") as fh:
            fh.write("freesurferfile,isControl,age\n")
            for j in range(subjects):
                fh.write(
                    f"subject{j}_aseg_stats.txt,"
                    f"{'True' if y[j] else 'False'},"
                    f"{20 + 50 * rng.random():.1f}\n"
                )
        for j in range(subjects):
            x = np.abs(rng.normal(1.0, 0.2, n_features))
            if y[j]:
                x[: n_features // 4] += shift * 0.2
            vals = x * scales
            with open(os.path.join(d, f"subject{j}_aseg_stats.txt"), "w") as fh:
                fh.write(f"Measure:volume\tsubject{j}\n")
                for k in range(n_features):
                    fh.write(f"feature-{k}\t{vals[k]:.2f}\n")
        X = rng.normal(size=(subjects, comps, temporal)).astype(np.float32)
        X[:, : comps // 4] += (y[:, None, None] * shift).astype(np.float32)
        np.savez(os.path.join(d, "timecourses.npz"), X)
        spec.append({k: {"value": v} for k, v in dict(
            task_id="Multimodal-Classification",
            labels_file=f"site{i + 1}_Covariate.csv",
            data_column="freesurferfile",
            labels_column="isControl",
            data_file="timecourses.npz",
            fs_input_size=n_features,
            num_components=comps,
            temporal_size=temporal,
            window_size=window,
            window_stride=stride,
            embed_dim=32,
            num_heads=4,
            num_layers=1,
            num_class=2,
        ).items()})
    with open(os.path.join(root, "inputspec.json"), "w") as fh:
        json.dump(spec, fh, indent=1)
    return root


def make_demo_tree(root: str, task: str = "FS-Classification", **kw) -> str:
    """Dispatch by task id; returns ``root``."""
    if task in ("FS-Classification", "FSL", "fs"):
        return make_fs_demo_tree(root, **kw)
    if task in ("ICA-Classification", "ICA", "ica"):
        return make_ica_demo_tree(root, **kw)
    if task in ("Multimodal-Classification", "multimodal", "mm"):
        return make_multimodal_demo_tree(root, **kw)
    raise ValueError(f"unknown demo task {task!r}")


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m dinunet_implementations_tpu.data.demo",
        description="Generate a self-contained demo simulator tree.",
    )
    p.add_argument("root", help="directory to create (e.g. datasets/demo)")
    p.add_argument("--task", default="FS-Classification",
                   help="FS-Classification (default), ICA-Classification or "
                        "Multimodal-Classification")
    p.add_argument("--sites", type=int, default=None)
    p.add_argument("--subjects", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    kw = {"seed": args.seed}
    if args.sites is not None:
        kw["n_sites"] = args.sites
    if args.subjects is not None:
        kw["subjects"] = args.subjects
    make_demo_tree(args.root, args.task, **kw)
    n_files = sum(len(fs) for _, _, fs in os.walk(args.root))
    print(f"demo tree ready: {args.root} ({n_files} files)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
