# Container packaging — the reference ships its computation as a COINSTAC
# Docker image (reference Dockerfile:1-20: coinstac base + pip install +
# CMD python entry.py). The TPU build's equivalent below: a plain Python
# base (TPU runtimes provide their own jax/libtpu pairing — install the
# matching jax[tpu] wheel for your fleet), the package installed from
# source, and the CLI as the entry point.
#
# The clean-environment install + quick-start this image performs is
# exercised outside Docker by scripts/package_smoke.sh (wheel build, fresh
# venv, fixture run) — tests/test_packaging.py keeps it green.

FROM python:3.12-slim

# native toolchain for the optional C++ ingest component (data layer falls
# back to pure Python when absent — see dinunet_implementations_tpu/native)
RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /computation
COPY . .
RUN pip install --no-cache-dir .
# TPU hosts: pip install "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html

ENTRYPOINT ["dinunet-tpu"]
CMD ["--help"]
