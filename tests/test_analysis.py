"""Pretrain k-fold study (VERDICT r2 #5): reproduce the reference's
NB.ipynb cells 6-17 convergence comparison in-repo, reading back our own
logs.json artifacts."""

import os

import pytest

from dinunet_implementations_tpu.analysis import pretrain_study

FSL = "/root/reference/datasets/test_fsl"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(FSL), reason="reference fixture not mounted"
)


@pytest.mark.golden
def test_pretrain_study_shows_faster_convergence(tmp_path):
    """The reference's claim (mean stop epoch 68.5 scratch vs 42.7
    pretrained): the pretrained arm must converge at least as fast, at
    comparable accuracy. 3 folds of the 5-site fixture, seed 0 —
    deterministic on the CPU simulator (measured 37.7 vs 35.0 epochs)."""
    report = pretrain_study(
        FSL, str(tmp_path), num_folds=5, pretrain_epochs=20, folds=[0, 1, 2]
    )
    s = report["arms"]["scratch"]
    p = report["arms"]["pretrained"]
    assert p["mean_best_val_epoch"] <= s["mean_best_val_epoch"], (
        f"pretrained arm converged SLOWER: {p['mean_best_val_epoch']:.1f} vs "
        f"{s['mean_best_val_epoch']:.1f} epochs"
    )
    assert p["mean_test_auc"] >= s["mean_test_auc"] - 0.05, (
        "pretraining degraded accuracy beyond tolerance"
    )
    # report artifacts exist and carry the table
    md = open(os.path.join(tmp_path, "pretrain_study.md")).read()
    assert "| scratch |" in md and "| pretrained |" in md
    csv_text = open(os.path.join(tmp_path, "pretrain_study.csv")).read()
    assert csv_text.count("\n") >= 7  # header + 2 arms x 3 folds


@pytest.mark.golden
def test_engine_comparison_table(tmp_path):
    """nnlogs.ipynb cell-2 equivalent: per-engine [loss, AUC] + wall-clock
    parsed back from our logs.json (fast config: 2 engines, few epochs)."""
    from dinunet_implementations_tpu.analysis import engine_comparison
    from dinunet_implementations_tpu.core.config import TrainConfig

    cfg = TrainConfig(task_id="FS-Classification", epochs=4,
                      validation_epochs=2, patience=10, seed=0)
    report = engine_comparison(
        FSL, str(tmp_path), engines=("dSGD", "rankDAD"), base_cfg=cfg
    )
    assert set(report["engines"]) == {"dSGD", "rankDAD"}
    for row in report["engines"].values():
        loss, auc = row["test_metrics"]
        assert 0.0 <= auc <= 1.0 and loss > 0
        assert row["computation_time"] > 0
        assert row["total_duration"] >= row["computation_time"] * 0.5
    md = open(os.path.join(tmp_path, "engine_comparison.md")).read()
    assert "| dSGD |" in md and "| rankDAD |" in md


# ---------------------------------------------------------------------------
# VERDICT r4 #5: the reference notebooks' LITERAL cell code must parse our
# output tree unmodified (not a reimplementation of their parse).
# ---------------------------------------------------------------------------

NB = "/root/reference/NB.ipynb"
NNLOGS = "/root/reference/nnlogs.ipynb"


def _cell(nb_path, ix):
    import json

    return "".join(json.load(open(nb_path))["cells"][ix]["source"])


def _fabricate_run(out, num_sites=2, folds=range(10), task="FS-Classification"):
    """Build an output tree with the framework's REAL writers (the same code
    every live run uses) and known values."""
    from dinunet_implementations_tpu.trainer.logs import (
        fold_dir,
        write_logs_json,
        write_test_metrics_csv,
        zip_global_results,
    )

    vals = {}
    for k in folds:
        tm = [[round(0.5 + 0.01 * k, 5), round(0.9 - 0.01 * k, 5)]]
        scores = {"accuracy": 0.8 + 0.01 * k, "f1": 0.7 + 0.01 * k,
                  "precision": 0.75, "recall": 0.75, "auc": 0.9}
        d = fold_dir(str(out), "remote", task, k)
        write_logs_json(d, "dSGD", tm, 10 + k, [1.0, 2.0 + k], [0.5, 0.6],
                        [0.1] * 4, side="remote")
        write_test_metrics_csv(d, k, scores)
        for i in range(num_sites):
            dl = fold_dir(str(out), f"local{i}", task, k)
            write_logs_json(dl, "dSGD", tm, 10 + k, [1.0, 2.0 + k],
                            [0.5, 0.6], [0.1] * 4, side="local")
        vals[k] = (tm, scores)
    zip_global_results(str(out), num_sites=num_sites)
    return vals


@pytest.mark.skipif(not os.path.isfile(NNLOGS), reason="reference notebooks not mounted")
def test_nnlogs_cell2_runs_verbatim_on_our_tree(tmp_path, capsys):
    """nnlogs.ipynb cell 2 (the engine table all BASELINE numbers come
    from): listdir walk → site logs.json → find .zip → extract →
    GLOBAL_res/fold_0/logs.json, executed verbatim."""
    import json
    import zipfile

    vals = _fabricate_run(tmp_path, num_sites=2)
    ns = {"zipfile": zipfile, "json": json, "os": os,
          "path": str(tmp_path / "local0"), "r": lambda x: round(x, 2)}
    exec(_cell(NNLOGS, 2), ns)
    out = capsys.readouterr().out
    assert "dSGD: Loss, AUC [[0.5, 0.9]]" in out
    assert ns["remote_log"]["test_metrics"] == vals[0][0]
    assert ns["local_log"]["agg_engine"] == "dSGD"
    # the notebook's extraction really landed on disk
    assert (tmp_path / "local0/simulatorRun/GLOBAL_res/fold_0/logs.json").exists()


@pytest.mark.skipif(not os.path.isfile(NB), reason="reference notebooks not mounted")
def test_nb_study_cells_run_verbatim_on_our_tree(tmp_path, monkeypatch):
    """NB.ipynb cells 2 (stop epochs), 6 (SCORE/EPOCH tables over 10 folds)
    and 9/11 (the boxplot figures, assets/perf_box.png +
    assets/pretrain_box.png) executed verbatim against our writers' tree."""
    import json

    matplotlib = pytest.importorskip("matplotlib")
    pd = pytest.importorskip("pandas")
    sns = pytest.importorskip("seaborn")
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    vals = _fabricate_run(tmp_path, num_sites=1)
    task_dir = str(tmp_path / "remote/simulatorRun/FS-Classification")
    ns = {"os": os, "json": json, "sep": os.sep, "pd": pd, "plt": plt,
          "sns": sns, "base_pth_sc": task_dir, "base_pth_pt": task_dir}
    exec(_cell(NB, 2), ns)  # stop epochs from logs.json
    assert sorted(ns["stopped_sc"]) == [10 + k for k in range(10)]
    exec(_cell(NB, 6), ns)  # SCORE / EPOCH from test_metrics.csv + logs.json
    score = ns["SCORE"]
    assert score[0] == ["Experiment", "Score", "Value"]
    accs = [r[2] for r in score[1:] if r[0] == "Acc. from scratch" and r[1] == "Accuracy"]
    assert accs == [round(0.8 + 0.01 * k, 5) for k in range(10)]
    f1s = [r[2] for r in score[1:] if r[1] == "F1" and r[0] == "Acc. from scratch"]
    assert f1s == [round(0.7 + 0.01 * k, 5) for k in range(10)]
    # figure cells save to a relative assets/ dir
    monkeypatch.chdir(tmp_path)
    os.makedirs("assets", exist_ok=True)
    exec(_cell(NB, 7), ns)   # df = DataFrame(SCORE)
    exec(_cell(NB, 8), ns)   # figsize + seaborn context
    exec(_cell(NB, 9), ns)   # perf_box.png
    plt.close("all")
    exec(_cell(NB, 10), ns)  # df_ep = DataFrame(EPOCH)
    exec(_cell(NB, 11), ns)  # pretrain_box.png
    plt.close("all")
    assert (tmp_path / "assets/perf_box.png").stat().st_size > 0
    assert (tmp_path / "assets/pretrain_box.png").stat().st_size > 0


def test_write_study_figures_without_training(tmp_path):
    """The in-repo figure writer (analysis.write_study_figures) emits both
    boxplots from SCORE/EPOCH-shaped rows."""
    from dinunet_implementations_tpu.analysis import write_study_figures

    score = [["Acc. from scratch", "Accuracy", 0.8], ["Acc. from scratch", "F1", 0.7],
             ["Acc. with pre-training", "Accuracy", 0.85],
             ["Acc. with pre-training", "F1", 0.75]] * 3
    epochs = [["Convergence from scratch.", 60], ["Convergence with pre-training.", 40]] * 3
    paths = write_study_figures(str(tmp_path), score, epochs)
    assert len(paths) == 2
    for p in paths:
        assert os.path.getsize(p) > 0
    assert paths[0].endswith("assets/perf_box.png")
