from .api import (
    DataHandle,
    SiteArrays,
    SiteDataset,
    SiteInventory,
    build_site_dataset,
    stack_site_inventory,
)
from .batching import (
    EpochPlan,
    FedBatches,
    epoch_steps,
    materialize_plan,
    plan_epoch,
    plan_epoch_positions,
    plan_eval,
)
from .freesurfer import FreeSurferDataset, FSVDataHandle, coerce_label, read_aseg_stats
from .ica import ICADataHandle, ICADataset, load_timecourses, window_timecourses
from .splits import kfold_splits, load_split_file, resolve_splits, split_by_ratio
