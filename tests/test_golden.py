"""Golden-metric regression (VERDICT round-1 #2): the rebuild must reach
reference-grade accuracy on the reference's own fixture for all three
aggregation engines.

Reference numbers: 2-site FS-Classification run, ``nnlogs.ipynb`` cell 2
(BASELINE.md): dSGD [0.72688, 0.81404], rankDAD [0.38915, 0.85351],
powerSGD [0.33662, 0.90702] as test [loss, AUC]. Here the full 5-site
``datasets/test_fsl`` fixture trains to convergence (patience-based early
stop, same compspec defaults) and must meet or beat each engine's reference
AUC. Measured on this harness (seed 0): dSGD 0.967, rankDAD 0.914,
powerSGD 0.984 — wall-clock ~12-26s on the 8-device CPU simulator vs the
reference's 695-2339s per engine.
"""

import math
import os

import numpy as np
import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.robustness import AttackPlan
from dinunet_implementations_tpu.runner import FedRunner

FSL = "/root/reference/datasets/test_fsl"

# Only the tests that READ the reference fixture need it mounted; the
# synthetic hard-SNR ICA floors build their own tree and run anywhere.
needs_fsl = pytest.mark.skipif(
    not os.path.isdir(FSL), reason="reference fixture not mounted"
)

REFERENCE_AUC = {  # nnlogs.ipynb cell 2 (BASELINE.md)
    "dSGD": 0.81404,
    "rankDAD": 0.85351,
    "powerSGD": 0.90702,
}


@needs_fsl
@pytest.mark.golden
def test_two_site_matches_reference_setup(tmp_path):
    """VERDICT r2 #9: apples-to-apples with the reference's published table —
    its numbers come from a 2-site run (``fs-lstm_2S``, nnlogs.ipynb cell 2).
    Restrict the fixture to local0/local1 with compspec defaults and assert
    the same [loss, AUC] row beats the reference's dSGD 0.81404."""
    import json

    two = tmp_path / "fsl2"
    (two / "input").mkdir(parents=True)
    for site in ("local0", "local1"):
        os.symlink(
            os.path.join(FSL, "input", site), str(two / "input" / site)
        )
    spec = json.load(open(os.path.join(FSL, "inputspec.json")))
    (two / "inputspec.json").write_text(json.dumps(spec[:2]))

    cfg = TrainConfig(
        agg_engine="dSGD", epochs=101, patience=35,
        split_ratio=(0.7, 0.15, 0.15), seed=0,
    )
    res = FedRunner(cfg, data_path=str(two), out_dir=str(tmp_path / "out")).run(
        verbose=False
    )[0]
    loss, auc = res["test_metrics"][0]
    assert auc >= 0.81404, (
        f"2-site dSGD AUC {auc:.4f} below the reference's 2-site 0.81404"
    )
    assert math.isfinite(loss)


def _make_hard_ica_tree(root, n_sites=3, subjects=24, comps=8, temporal=40,
                        window=5, stride=5, seed=7, shift=0.35):
    """Synthetic ICA simulator tree at a deliberately weak SNR: the class
    signal is a +0.35σ shift in 2 of 8 components (the e2e runner tests use
    an easy +2σ shift on every component). Same layout as the reference's
    fixture convention (datasets/icalstm/inputspec.json shapes, scaled down)."""
    import json as _json

    import numpy as np

    rng = np.random.default_rng(seed)
    spec = []
    for i in range(n_sites):
        d = root / "input" / f"local{i}" / "simulatorRun"
        d.mkdir(parents=True)
        y = rng.integers(0, 2, subjects)
        X = rng.normal(size=(subjects, comps, temporal)).astype(np.float32)
        X[:, :2] += (y[:, None, None] * shift).astype(np.float32)
        np.savez(d / "timecourses.npz", X)
        with open(d / "labels.csv", "w") as fh:
            fh.write("index,label\n")
            for j in range(subjects):
                fh.write(f"{j},{int(y[j])}\n")
        spec.append({k: {"value": v} for k, v in dict(
            data_file="timecourses.npz", labels_file="labels.csv",
            temporal_size=temporal, window_size=window, window_stride=stride,
            num_components=comps, input_size=16, hidden_size=12, num_class=2,
        ).items()})
    (root / "inputspec.json").write_text(_json.dumps(spec))


# Measured seed-0 hard-SNR AUC: 0.94 for dSGD/powerSGD on the r5 v5e/newer-
# jax harness; 0.72 for ALL THREE engines on the jax-0.4.37 CPU container
# (version numerics shift the whole trajectory, engines stay in lockstep —
# and warm- vs cold-started rankDAD agree to 4 decimals either way). The
# floor must hold across harnesses, so it gates at the weaker environment's
# measured value with margin; the engines-agree property is the real gate.
HARD_SNR_FLOOR = {"dSGD": 0.70, "powerSGD": 0.70, "rankDAD": 0.70}

#: seed → hard-SNR AUC floor for rankDAD. Measured on the jax-0.4.37 CPU
#: container: 0.7200/0.9074/0.9815 across seeds 0-2 — warm == cold to 4
#: decimals at every seed. Per the cross-environment rule above (version
#: numerics swing a trajectory by ~0.2), every seed gates at the same
#: conservative floor; the per-seed measured values live in this comment as
#: the record, not as gates.
RANKDAD_SEED_FLOORS = {0: 0.70, 1: 0.70, 2: 0.70}


@pytest.mark.golden
@pytest.mark.parametrize("engine", ["dSGD", "powerSGD", "rankDAD"])
def test_ica_converges_at_hard_snr(engine, tmp_path):
    """VERDICT r2 #6 + r5 weak #5: ICA golden regression — the fixture AUC
    floor for the plain and BOTH compressed engines (rankDAD runs its r6
    default: warm-started subspaces)."""
    _make_hard_ica_tree(tmp_path)
    cfg = TrainConfig(
        task_id="ICA-Classification", agg_engine=engine, epochs=60,
        patience=20, batch_size=8, split_ratio=(0.7, 0.15, 0.15), seed=0,
    )
    res = FedRunner(
        cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out")
    ).run(verbose=False)[0]
    loss, auc = res["test_metrics"][0]
    floor = HARD_SNR_FLOOR[engine]
    assert auc >= floor, (
        f"ICA {engine}: test AUC {auc:.4f} under the {floor} golden floor "
        f"(best_val_epoch={res['best_val_epoch']})"
    )
    assert math.isfinite(loss)


@pytest.mark.golden
@pytest.mark.parametrize("engine", ["dSGD", "rankDAD"])
def test_ica_hard_snr_floor_holds_under_packing(engine, tmp_path):
    """r12 acceptance: the ICA golden floors hold at pack factor K>1 — the
    same hard-SNR tree and floor as the unpacked run above, but with all 3
    virtual sites PACKED onto a 1-member site mesh
    (cfg.sites_per_device=3), i.e. the two-level packed aggregation path in
    trainer/steps.py rather than the vmap fold. A floor regression here
    means packing changed the training math."""
    from dinunet_implementations_tpu.runner import FedRunner as _FR

    _make_hard_ica_tree(tmp_path)
    cfg = TrainConfig(
        task_id="ICA-Classification", agg_engine=engine, epochs=60,
        patience=20, batch_size=8, split_ratio=(0.7, 0.15, 0.15), seed=0,
        sites_per_device=3,
    )
    runner = _FR(cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out"))
    assert dict(runner.mesh.shape)["site"] == 1  # genuinely packed (K=3)
    res = runner.run(verbose=False)[0]
    loss, auc = res["test_metrics"][0]
    floor = HARD_SNR_FLOOR[engine]
    assert auc >= floor, (
        f"packed (K=3) ICA {engine}: test AUC {auc:.4f} under the {floor} "
        f"golden floor (best_val_epoch={res['best_val_epoch']})"
    )
    assert math.isfinite(loss)


@pytest.mark.golden
@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ica_rankdad_warm_start_clears_seed_swept_floor(seed, tmp_path):
    """r6 regression: warm-started rankDAD (the default) must clear the SAME
    seed-swept hard-SNR floors as cold-start — the warm Ω is a perf lever,
    not an accuracy trade. Measured on this harness: warm and cold agree to
    4 decimals at every seed (0.7200/0.9074/0.9815)."""
    _make_hard_ica_tree(tmp_path)
    cfg = TrainConfig(
        task_id="ICA-Classification", agg_engine="rankDAD", epochs=60,
        patience=20, batch_size=8, split_ratio=(0.7, 0.15, 0.15), seed=seed,
    )
    assert cfg.ica_args.dad_warm_start  # warm starts are the default
    res = FedRunner(
        cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out")
    ).run(verbose=False)[0]
    loss, auc = res["test_metrics"][0]
    floor = RANKDAD_SEED_FLOORS[seed]
    assert auc >= floor, (
        f"warm-started rankDAD seed {seed}: AUC {auc:.4f} under the "
        f"measured floor {floor}"
    )
    assert math.isfinite(loss)


#: (engine-agnostic) hard-SNR AUC floor for the 6-site cohort under ONE
#: sign-flip attacker with the coordinate-median defense ON. Measured on the
#: jax-0.4.37 CPU container, seeds 0-2: dSGD 0.787/0.722/0.960, rankDAD
#: 0.778/0.727/0.955 (clean 6-site baseline 0.9067; defense OFF under the
#: same attacker: 0.707/0.716 at seed 0 — and catastrophic 0.38 on the
#: 3-site cohort, where one attacker owns a third of the weight; the
#: defense-off arms are recorded in docs/bench_attacks_ab_r17.jsonl).
#: Gated at the same conservative cross-environment margin as
#: HARD_SNR_FLOOR above.
ATTACK_FLOOR = 0.70


def _attacked_hard_snr_auc(engine, seed, tmp_path):
    """One hard-SNR fit at 6 sites with site 1 sign-flipping every round and
    the coordinate-median defense + reputation layer on."""
    _make_hard_ica_tree(tmp_path, n_sites=6)
    cfg = TrainConfig(
        task_id="ICA-Classification", agg_engine=engine, epochs=60,
        patience=20, batch_size=8, split_ratio=(0.7, 0.15, 0.15), seed=seed,
        robust_agg="coordinate_median", reputation_z=1.8,
        reputation_rounds=4,
    )
    plan = AttackPlan(sign_flip=((1, 0, -1),))
    res = FedRunner(
        cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out"),
        attack_plan=plan,
    ).run(verbose=False)[0]
    return res


@pytest.mark.golden
@pytest.mark.parametrize("engine", ["dSGD", "rankDAD"])
def test_ica_hard_snr_floor_holds_under_sign_flip_attack(engine, tmp_path):
    """r17 acceptance: a byzantine site sign-flipping its gradient EVERY
    round must not break the hard-SNR golden floor when the robust
    aggregation defense is on — and the reputation layer must score the
    attacker as the cohort's top anomaly."""
    res = _attacked_hard_snr_auc(engine, 0, tmp_path)
    loss, auc = res["test_metrics"][0]
    assert auc >= ATTACK_FLOOR, (
        f"{engine} under 1 sign-flip attacker (defense on): AUC {auc:.4f} "
        f"below the {ATTACK_FLOOR} floor "
        f"(best_val_epoch={res['best_val_epoch']})"
    )
    assert math.isfinite(loss)
    health = res["site_health"]
    anom = health["site_anomaly_score"]
    assert int(np.argmax(anom)) == 1, (
        f"reputation layer missed the attacker: anomaly scores {anom}"
    )


@pytest.mark.golden
@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2])
@pytest.mark.parametrize("engine", ["dSGD", "rankDAD"])
def test_ica_attack_floor_seed_swept(engine, seed, tmp_path):
    """Seed sweep of the attacked floor (same policy as the rankDAD
    warm-start sweep: the robustness claim must not rest on one
    trajectory). Measured this harness: dSGD 0.722/0.960, rankDAD
    0.727/0.955 at seeds 1/2."""
    res = _attacked_hard_snr_auc(engine, seed, tmp_path)
    loss, auc = res["test_metrics"][0]
    assert auc >= ATTACK_FLOOR, (
        f"{engine} seed {seed} under attack (defense on): AUC {auc:.4f} "
        f"below the {ATTACK_FLOOR} floor"
    )
    assert math.isfinite(loss)


#: (r20) hard-SNR AUC floor for the 6-site cohort under the FULL privacy
#: stack — in-scan DP-SGD (σ=0.05, C=1.0), secure-aggregation masked wires
#: AND personalized per-site heads, all on at once. Measured on the
#: jax-0.4.37 CPU container, seeds 0-2: 0.818/0.667/0.946 (clean 6-site
#: baseline 0.9067). Isolating at the weakest seed: personalize-only
#: 1.000, secure-agg-only 0.995, dp-only 0.759 — the DP noise is the
#: utility price and the floor RECORDS it (gated at the weakest measured
#: value with the usual cross-environment margin) instead of quietly
#: picking a friendlier σ. docs/ARCHITECTURE.md "Privacy plane (r20)".
PRIVACY_STACK_FLOOR = 0.62


def _privacy_stack_auc(engine, seed, tmp_path):
    """One hard-SNR fit at 6 sites with the full privacy stack on: DP-SGD
    clip+noise in the rounds scan, one-time-padded masked wires, and the
    ICA-LSTM classifier head (cls_fc3) personalized per site."""
    _make_hard_ica_tree(tmp_path, n_sites=6)
    cfg = TrainConfig(
        task_id="ICA-Classification", agg_engine=engine, epochs=60,
        patience=20, batch_size=8, split_ratio=(0.7, 0.15, 0.15), seed=seed,
        dp_clip=1.0, dp_noise_multiplier=0.05,
        secure_agg="mask" if engine == "dSGD" else "off",
        personalize=("cls_fc3",),
    )
    return FedRunner(
        cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out")
    ).run(verbose=False)[0]


@pytest.mark.golden
def test_ica_hard_snr_floor_holds_under_full_privacy_stack(
    tmp_path, monkeypatch
):
    """r20 acceptance: dp on + secure-agg on + personalized heads on, one
    program, one fit — the re-measured golden floor holds, the run
    reports a finite positive ε, and the CompileGuard (DINUNET_SANITIZE)
    asserts the whole stacked fit compiles its epoch exactly once."""
    monkeypatch.setenv("DINUNET_SANITIZE", "compile")
    res = _privacy_stack_auc("dSGD", 0, tmp_path)
    loss, auc = res["test_metrics"][0]
    assert auc >= PRIVACY_STACK_FLOOR, (
        f"full privacy stack: AUC {auc:.4f} below the re-measured "
        f"{PRIVACY_STACK_FLOOR} floor (best_val_epoch="
        f"{res['best_val_epoch']})"
    )
    assert math.isfinite(loss)
    assert res["dp_epsilon"] > 0 and math.isfinite(res["dp_epsilon"])


@pytest.mark.golden
@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 2])
def test_ica_privacy_stack_floor_seed_swept(seed, tmp_path):
    """Seed sweep of the privacy-stack floor (same policy as every other
    floor sweep: the claim must not rest on one trajectory). Measured this
    harness: 0.667/0.946 at seeds 1/2."""
    res = _privacy_stack_auc("dSGD", seed, tmp_path)
    loss, auc = res["test_metrics"][0]
    assert auc >= PRIVACY_STACK_FLOOR, (
        f"privacy stack seed {seed}: AUC {auc:.4f} below the "
        f"{PRIVACY_STACK_FLOOR} floor"
    )
    assert math.isfinite(loss)


@needs_fsl
@pytest.mark.golden
@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("engine", ["dSGD", "rankDAD", "powerSGD"])
def test_engine_converges_to_reference_grade_auc(engine, seed, tmp_path):
    """Seed-swept (VERDICT r4 #4): the reference-beating claim must not rest
    on one trajectory. Measured across seeds 0-2 on the 5-site fixture
    (this harness): dSGD 0.967/0.956/0.997, rankDAD 0.957/0.965/0.997,
    powerSGD 0.963/0.934/1.000 — every one above its engine's reference
    AUC (nnlogs.ipynb cell 2)."""
    cfg = TrainConfig(
        agg_engine=engine, epochs=101, patience=35,
        split_ratio=(0.7, 0.15, 0.15), seed=seed,
    )
    res = FedRunner(cfg, data_path=FSL, out_dir=str(tmp_path)).run(verbose=False)[0]
    loss, auc = res["test_metrics"][0]
    ref = REFERENCE_AUC[engine]
    assert auc >= ref, (
        f"{engine} seed {seed}: converged test AUC {auc:.4f} below the "
        f"reference's {ref:.4f} (best_val_epoch={res['best_val_epoch']}, "
        f"stopped={res['stopped_epoch']})"
    )
    assert loss > 0 and math.isfinite(loss)


@pytest.mark.golden
@pytest.mark.parametrize("wire", ["int8", "fp8"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ica_hard_snr_floor_holds_under_quantized_wires(wire, seed, tmp_path):
    """r14 acceptance: the seed-swept hard-SNR floors hold under int8 and
    fp8 wire quantization (rankDAD, the flagship compression engine — its
    gathered factors ride the codec grid). Measured on the jax-0.4.37 CPU
    container: int8 0.74/0.9074/0.9815 and fp8 0.72/0.9074/0.9815 across
    seeds 0-2 — within a hair of the f32 record (0.72/0.9074/0.9815); the
    conservative cross-environment floor gates, same policy as the r6
    warm-start regression above."""
    _make_hard_ica_tree(tmp_path)
    cfg = TrainConfig(
        task_id="ICA-Classification", agg_engine="rankDAD", epochs=60,
        patience=20, batch_size=8, split_ratio=(0.7, 0.15, 0.15), seed=seed,
        wire_quant=wire,
    )
    res = FedRunner(
        cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out")
    ).run(verbose=False)[0]
    loss, auc = res["test_metrics"][0]
    floor = RANKDAD_SEED_FLOORS[seed]
    assert auc >= floor, (
        f"rankDAD {wire}-wire seed {seed}: AUC {auc:.4f} under the "
        f"measured floor {floor}"
    )
    assert math.isfinite(loss)
