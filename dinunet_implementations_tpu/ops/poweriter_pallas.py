"""Fused Pallas TPU kernel for the rankDAD power-iteration inner loop.

PR 7's attribution artifact (``docs/bench_rankdad_attr_r12.jsonl``) measured
the subspace/power iteration at **82.7% of a cold rankDAD epoch** (14.2% per
trip): the hot loop is a sequence of small matmuls (``G@Ω``, ``GᵀP``,
``G(GᵀP)``) interleaved with CholeskyQR orthonormalizations, each emitted as
separate XLA ops that spill the ``[m, r]``/``[n, r]`` iterates (and re-read
``G``) through HBM on every trip. This kernel fuses ONE rank class's entire
``lax.while_loop`` — init, every power refinement, the convergence test, and
the final back-projection ``Q = GᵀP`` — into a single VMEM-resident
``pallas_call``: ``G`` is read from HBM once, the iterates live in
registers/VMEM for the whole loop, and only the final ``(P, Q)`` factors are
written back.

Layout: a rank class's members (same effective rank r, possibly different
``(m_l, n_l)``) are bucketed by EXACT shape and each bucket stacks
``[L, m, n]`` into one kernel invocation — the flagship's fwd/bwd LSTM
kernel pairs share shapes, so they batch; a differently-shaped member gets
its own call. (Zero-padding the whole class to its max dims would also be
mathematically exact, but was measured to inflate the iteration FLOPs ~5×
on mixed shapes — every member paying ``m̄·n̄`` instead of its own ``m·n`` —
so it is not done.) The batched member axis maps onto TPU sublanes through
the stacked einsums, so the tiny ``[r, r]`` Cholesky work batches across
the bucket exactly like the XLA path (``lowrank._cholqr_once_multi``).

Semantics mirror ``lowrank.subspace_iteration_grouped`` member-for-member:
the same column-normalized shifted CholeskyQR2 (via the SAME unrolled
``_small_cholesky``/``_small_tril_inverse`` helpers — no LAPACK custom-call
exists inside a kernel anyway), the same σ-estimate convergence test, the
same per-member active-mask freezing. One deliberate divergence: each rank
class's fused loop exits on ITS OWN worst member delta instead of the global
max over all classes — converged members are frozen either way, so the
RESULTS are identical; only wasted trips differ (fewer here: a converged
class stops instead of spinning until the slowest class finishes).

``matmul_dtype=bfloat16`` runs the large products as bf16×bf16→f32 MXU
contractions inside the kernel (the ``lp_matmul`` policy,
``engines/lowrank.py``); normalization/Cholesky/σ stay f32.

CPU fallback: ``interpret=True`` whenever the backend is not TPU (the
``_interpret()`` pattern from ``ops/lstm_pallas.py``) — tier-1, the parity
tests, and the paired A/B bench run the same kernel everywhere. VMEM
budget: :func:`class_fits_vmem` estimates the kernel's resident bytes and
callers (``lowrank.subspace_iteration_grouped``) fall back to the legacy
XLA loop for any class that would not fit — a trace-time static decision.

vmap (the r12 packed-sites path): jax's default ``pallas_call`` vmap rule
prepends a grid dimension, which executes SEQUENTIALLY on a TPU core; the
entry point instead carries a ``custom_vmap`` rule that folds the mapped
axis into the member axis (``[K, L, m̄, n̄] → [K·L, m̄, n̄]``) — valid because
every kernel output is member-row-wise (same argument as the LSTM kernel's
batch-row fold).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.custom_batching import custom_vmap
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..engines.lowrank import (
    _small_cholesky,
    _small_tril_inverse,
    default_omega,
)

#: conservative VMEM budget for one fused class (v5e/v4 have ~16 MiB/core;
#: leave headroom for the grid pipeline's other residents)
VMEM_BUDGET_BYTES = 12 * 2**20


def _interpret() -> bool:
    # Pallas TPU kernels run in interpreter mode on CPU (tests / simulators)
    return jax.default_backend() == "cpu"


def class_fits_vmem(Gs, rank: int, matmul_dtype=None,
                    budget: int = VMEM_BUDGET_BYTES) -> bool:
    """Trace-time static estimate of one rank class's kernel residency,
    per EXACT-SHAPE BUCKET (the unit that actually becomes one kernel
    invocation — see :func:`fused_subspace_iteration_grouped`): the
    ``[L, m, n]`` G stack (plus its bf16 copy under mixed precision), ~3
    ``[L, m, r]`` and ~3 ``[L, n, r]`` iterate buffers, and the
    ``[L, r, r]`` Gram scratch. The class fuses iff its LARGEST bucket
    fits. Pure shape arithmetic — safe on tracers."""
    if not Gs:
        return False
    # shapes are static Python ints even on tracers — never traced values
    r = min([rank] + [min(int(d) for d in g.shape) for g in Gs])  # jaxlint: disable=R005
    buckets: dict[tuple, int] = {}
    for g in Gs:
        shape = (int(g.shape[0]), int(g.shape[1]))  # jaxlint: disable=R005
        buckets[shape] = buckets.get(shape, 0) + 1
    for (m, n), L in buckets.items():
        g_bytes = m * n * (4 + (2 if matmul_dtype is not None else 0))
        iter_bytes = 3 * (m + n) * r * 4
        gram_bytes = 4 * r * r * 4
        if L * (g_bytes + iter_bytes + gram_bytes) > budget:
            return False
    return True


# ---------------------------------------------------------------------------
# batched CholeskyQR2 (the in-kernel twin of lowrank._cholqr_multi)
# ---------------------------------------------------------------------------


def _normalize_cols_b(Y):
    """Column-normalize a ``[L, m, r]`` stack; exactly-zero columns take
    canonical basis vectors (same fallback + same reasons as
    ``lowrank._normalize_cols``)."""
    nc = jnp.sqrt(jnp.sum(Y * Y, axis=1))  # [L, r]
    fallback = jnp.broadcast_to(
        jnp.eye(Y.shape[1], Y.shape[2], dtype=Y.dtype)[None], Y.shape
    )
    Yn = jnp.where(
        (nc > 0)[:, None, :], Y / jnp.maximum(nc, 1e-30)[:, None, :], fallback
    )
    return Yn, nc


def _cholqr_once_b(Y, shift):
    """One column-normalized shifted CholeskyQR round over the ``[L, m, r]``
    member stack — the batched form of ``lowrank._cholqr_once_multi``, with
    the same backend split: unrolled Cholesky/triangular-inverse on TPU (a
    Mosaic kernel has no LAPACK custom-calls, and the unrolled form is the
    fast one there anyway), LAPACK in interpret mode (the kernel body
    traces to plain XLA ops on CPU, where LAPACK wins and the unrolled
    graph only bloats compile time — the same reasoning as
    ``lowrank._cholqr_once_multi``)."""
    Yn, nc = _normalize_cols_b(Y)
    r = Yn.shape[-1]
    eye = jnp.eye(r, dtype=Yn.dtype)
    Gm = jnp.einsum("lmr,lms->lrs", Yn, Yn)  # [L, r, r]
    tr = jnp.trace(Gm, axis1=-2, axis2=-1)[:, None, None]
    Gm = Gm + (shift * tr + 1e-30) * eye
    if _interpret():
        Ls = jnp.linalg.cholesky(Gm)
        Linv = jax.scipy.linalg.solve_triangular(
            Ls, jnp.broadcast_to(eye, Gm.shape), lower=True
        )
    else:
        Ls = _small_cholesky(Gm)
        Linv = _small_tril_inverse(Ls)
    Q = jnp.einsum("lmr,lsr->lms", Yn, Linv)  # Y @ L⁻ᵀ per member
    return Q, nc


def _cholqr2_b(Y):
    Q1, colnorms = _cholqr_once_b(Y, 1e-6)
    Q2, _ = _cholqr_once_b(Q1, 1e-7)
    return Q2, colnorms


# ---------------------------------------------------------------------------
# the fused kernel
# ---------------------------------------------------------------------------


def _poweriter_kernel(G_ref, om_ref, P_ref, Q_ref, *, num_iters, tol,
                      mm_name):
    G = G_ref[...]  # [L, m, n] f32, VMEM-resident for the WHOLE loop
    om = om_ref[...]  # [L, n, r] f32
    mmd = jnp.dtype(mm_name) if mm_name is not None else None

    def mm(a, b, spec):
        # the large products at the lp_matmul policy: optional bf16 inputs,
        # f32 accumulation (engines/lowrank.py)
        if mmd is None:
            return jnp.einsum(spec, a, b, preferred_element_type=jnp.float32)
        return jnp.einsum(
            spec, a.astype(mmd), b.astype(mmd),
            preferred_element_type=jnp.float32,
        )

    def col_norms(A):  # [L, x, r] -> [L, r]
        return jnp.sqrt(jnp.sum(A * A, axis=1))

    # init: P0 = cholqr2(G @ Ω), σ0 from ‖(GᵀP)ᵢ‖ — identical to the XLA
    # path's prologue (lowrank.subspace_iteration_grouped)
    P, _ = _cholqr2_b(mm(G, om, "lmn,lnr->lmr"))
    sig = col_norms(mm(G, P, "lmn,lmr->lnr"))  # [L, r]
    delta = jnp.full((G.shape[0],), jnp.inf, jnp.float32)

    def cond(carry):
        i, _, _, d = carry
        return jnp.logical_and(i < num_iters, jnp.max(d) > tol)

    def body(carry):
        i, P, sig, delta = carry
        Y = mm(G, mm(G, P, "lmn,lmr->lnr"), "lmn,lnr->lmr")  # G(GᵀP)
        P_cand, colnorms = _cholqr2_b(Y)
        sig_new = jnp.sqrt(colnorms)  # ‖G Gᵀ p‖ ≈ σ² → σ scale
        delta_new = jnp.sqrt(jnp.sum((sig_new - sig) ** 2, axis=-1)) / (
            jnp.maximum(jnp.sqrt(jnp.sum(sig * sig, axis=-1)), 1e-12)
        )
        active = delta > tol  # members still iterating (solo trip counts)
        P = jnp.where(active[:, None, None], P_cand, P)
        sig = jnp.where(active[:, None], sig_new, sig)
        delta = jnp.where(active, delta_new, delta)
        return i + 1, P, sig, delta

    _, P, _, _ = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), P, sig, delta)
    )
    P_ref[...] = P
    # the back-projection stays fused too: Q = GᵀP reads the resident G one
    # last time instead of round-tripping P through HBM into an XLA matmul
    Q_ref[...] = mm(G, P, "lmn,lmr->lnr")


def _poweriter_call(Gp, omp, r: int, num_iters: int, tol: float, mm_name):
    """One fused ``pallas_call`` for one (padded, stacked) rank class:
    ``[L, m̄, n̄] × [L, n̄, r] → ([L, m̄, r], [L, n̄, r])``. No grid — a single
    invocation whose whole working set is VMEM-resident (class_fits_vmem
    gates callers)."""
    L, m, n = Gp.shape
    kernel = functools.partial(
        _poweriter_kernel, num_iters=num_iters, tol=tol, mm_name=mm_name
    )
    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((L, m, r), jnp.float32),
            jax.ShapeDtypeStruct((L, n, r), jnp.float32),
        ],
        interpret=_interpret(),
    )(Gp, omp)


def _poweriter_vmappable(r: int, num_iters: int, tol: float, mm_name):
    """The kernel entry with a member-axis-fold vmap rule: a mapped axis
    (the r12 packed virtual-site axis K) folds into the member axis L
    instead of becoming a sequential grid dimension — every kernel output
    is member-row-wise, so the fold is exact (frozen members make the
    shared trip count irrelevant to results)."""

    @custom_vmap
    def call(Gp, omp):
        return _poweriter_call(Gp, omp, r, num_iters, tol, mm_name)

    @call.def_vmap
    def _rule(axis_size, in_batched, Gp, omp):
        g_b, o_b = in_batched
        if not g_b:
            Gp = jnp.broadcast_to(Gp[None], (axis_size,) + Gp.shape)
        if not o_b:
            # cold starts under vmap draw ONE per-shape Ω — every virtual
            # site starts from the same subspace, exactly like the legacy
            # path's unbatched default_omega under the engine's vmap
            omp = jnp.broadcast_to(omp[None], (axis_size,) + omp.shape)
        B, L = Gp.shape[0], Gp.shape[1]
        P, Q = _poweriter_call(
            Gp.reshape((B * L,) + Gp.shape[2:]),
            omp.reshape((B * L,) + omp.shape[2:]),
            r, num_iters, tol, mm_name,
        )
        return (
            P.reshape((B, L) + P.shape[1:]),
            Q.reshape((B, L) + Q.shape[1:]),
        ), (True, True)

    return call


# ---------------------------------------------------------------------------
# the grouped entry point (lowrank.subspace_iteration_grouped's fused twin)
# ---------------------------------------------------------------------------


def fused_subspace_iteration_grouped(groups, num_iters: int, tol: float,
                                     matmul_dtype=None):
    """Drop-in fused twin of ``lowrank.subspace_iteration_grouped`` for
    classes that pass :func:`class_fits_vmem`: same ``[(Gs, rank, omegas)]``
    contract, same ``[[(P_l, Q_l), ...], ...]`` result (order preserved).

    One ``pallas_call`` per (rank class, member shape) bucket: members
    sharing an exact ``(m, n)`` stack into one ``[L, m, n]`` kernel
    invocation (the flagship ICA-LSTM's fwd/bwd LSTM kernel pairs), while
    differently-shaped members get their own call. Padding a heterogeneous
    class to its max dims was measured to inflate the power-iteration
    FLOPs ~5x on mixed shapes (every member paying ``m̄·n̄`` instead of its
    own ``m·n``) — more launches beat that by a wide margin, and each
    bucket's loop still exits on its own convergence."""
    mm_name = jnp.dtype(matmul_dtype).name if matmul_dtype is not None else None
    out = []
    for Gs, rank, omegas in groups:
        Gs = [G.astype(jnp.float32) for G in Gs]
        r = min([rank] + [min(G.shape) for G in Gs])
        if omegas is None:
            omegas = [None] * len(Gs)
        elif len(omegas) != len(Gs):
            raise ValueError(
                f"omegas has {len(omegas)} entries for {len(Gs)} matrices"
            )
        oms = [
            default_omega(G, r) if om is None else om.astype(jnp.float32)
            for G, om in zip(Gs, omegas)
        ]
        buckets: dict[tuple, list[int]] = {}
        for i, G in enumerate(Gs):
            buckets.setdefault(tuple(G.shape), []).append(i)
        results: list = [None] * len(Gs)
        for shape, idxs in buckets.items():
            Gp = jnp.stack([Gs[i] for i in idxs])
            omp = jnp.stack([oms[i] for i in idxs])
            P, Q = _poweriter_vmappable(r, num_iters, tol, mm_name)(Gp, omp)
            for l, i in enumerate(idxs):
                results[i] = (P[l], Q[l])
        out.append(results)
    return out
