"""Fault-tolerance tests: FaultPlan determinism, liveness masking through the
engines, NaN quarantine, rotating/checksummed checkpoints, preemption
save-and-exit, retry/backoff, and the chaos acceptance run.

Fast tests stay in tier-1; the kill/chaos integration runs are ``slow``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from dinunet_implementations_tpu import TrainConfig
from dinunet_implementations_tpu.data.api import SiteArrays
from dinunet_implementations_tpu.models import MSANNet
from dinunet_implementations_tpu.parallel import host_mesh
from dinunet_implementations_tpu.robustness import (
    FaultPlan,
    Preempted,
    PreemptionGuard,
    parse_fault_plan,
    poison_inputs,
    with_retry,
)
from dinunet_implementations_tpu.trainer import (
    CorruptCheckpointError,
    FederatedTrainer,
    load_checkpoint,
    save_checkpoint,
)


# ---------------------------------------------------------------------------
# FaultPlan: determinism, JSON/CLI round-trip, data-layer poisoning
# ---------------------------------------------------------------------------


def test_fault_plan_json_roundtrip():
    plan = FaultPlan(drop=((3, 10, -1), (5, 10, 20)), flaky_prob=0.25,
                     flaky_seed=7, nan_at=((4, 2),), kill_at_round=12)
    assert FaultPlan.from_json(plan.to_json()) == plan
    assert FaultPlan.from_json(json.dumps(plan.to_json())) == plan


def test_fault_plan_cli_flag_roundtrip(tmp_path):
    """Tier-1 smoke: a FaultPlan survives the CLI flag surface — inline JSON
    and @file — byte-identically."""
    from dinunet_implementations_tpu.runner.cli import build_parser

    plan = FaultPlan(drop=((1, 2, -1),), nan_at=((0, 1), (1, 1)),
                     kill_at_round=9)
    blob = json.dumps(plan.to_json())
    args = build_parser().parse_args(["--data-path", ".", "--faults", blob])
    assert parse_fault_plan(args.faults) == plan
    f = tmp_path / "plan.json"
    f.write_text(blob)
    args = build_parser().parse_args(["--data-path", ".", "--faults", f"@{f}"])
    assert parse_fault_plan(args.faults) == plan
    assert parse_fault_plan(None) is None
    assert parse_fault_plan("") is None


def test_fault_plan_rejects_malformed():
    with pytest.raises(ValueError, match="flaky_prob"):
        FaultPlan(flaky_prob=1.5)
    with pytest.raises(ValueError, match="drop"):
        FaultPlan(drop=((0, 5),))  # wrong arity
    with pytest.raises(ValueError, match="drop"):
        FaultPlan(drop=((0, 9, 5),))  # last < first
    with pytest.raises(ValueError, match="unknown FaultPlan keys"):
        FaultPlan.from_json({"nope": 1})


def test_fault_plan_liveness_deterministic_and_chunk_independent():
    """The flaky draw is keyed by (seed, site, GLOBAL round): the mask for a
    window never depends on how training chunks rounds into epochs — a
    resumed run replays the exact outage pattern of the uninterrupted one."""
    plan = FaultPlan(drop=((1, 3, 6),), flaky_prob=0.4, flaky_seed=11)
    whole = plan.liveness(4, 0, 12)
    np.testing.assert_array_equal(whole, plan.liveness(4, 0, 12))
    chunked = np.concatenate(
        [plan.liveness(4, 0, 5), plan.liveness(4, 5, 7)], axis=1
    )
    np.testing.assert_array_equal(whole, chunked)
    # the scheduled drop window is exact and inclusive
    clean = FaultPlan(drop=((1, 3, 6),))
    live = clean.liveness(4, 0, 12)
    assert live[1, 2] == 1.0 and live[1, 3] == 0.0
    assert live[1, 6] == 0.0 and live[1, 7] == 1.0
    assert live[0].all() and live[2].all()
    # open-ended drop (-1) holds to the end of any window
    forever = FaultPlan(drop=((0, 2, -1),)).liveness(2, 100, 5)
    assert (forever[0] == 0.0).all() and (forever[1] == 1.0).all()


def test_slice_fault_plan_roundtrip_and_validation():
    """r19 slice-tier windows: JSON/CLI round-trip like every other plan
    field, arity/range validation, and the kill lookup the supervised
    worker's self-kill arm keys on."""
    plan = FaultPlan(
        slice_drop_at=((1, 0, 2), (0, 5, -1)),
        slice_delay_at=((2, 3, 2),),
        kill_slice_at=((1, 4), (1, 9), (3, 2)),
    )
    assert FaultPlan.from_json(plan.to_json()) == plan
    assert FaultPlan.from_json(json.dumps(plan.to_json())) == plan
    assert parse_fault_plan(json.dumps(plan.to_json())) == plan
    assert plan.injects_slice_faults()
    assert not plan.injects_faults()  # slice windows are not site windows
    # the earliest kill round per slice (dcn_worker's deterministic arm)
    assert plan.kill_round_for_slice(1) == 4
    assert plan.kill_round_for_slice(3) == 2
    assert plan.kill_round_for_slice(0) is None
    with pytest.raises(ValueError, match="slice_drop_at"):
        FaultPlan(slice_drop_at=((0, 5),))
    with pytest.raises(ValueError, match="slice_drop_at"):
        FaultPlan(slice_drop_at=((0, 9, 5),))
    with pytest.raises(ValueError, match="slice_delay_at"):
        FaultPlan(slice_delay_at=((0, 1, 0),))
    with pytest.raises(ValueError, match="kill_slice_at"):
        FaultPlan(kill_slice_at=((-1, 2),))


def test_slice_liveness_windows_chunk_independent():
    """slice_liveness is a pure function of GLOBAL rounds (resume/chunking
    never changes the pattern), kills hold to the end of every window, and
    include_kills=False leaves the process-arm faults out of the mask."""
    from dinunet_implementations_tpu.robustness.faults import (
        slice_fault_window,
    )

    plan = FaultPlan(
        slice_drop_at=((0, 2, 3),), slice_delay_at=((1, 5, 2),),
        kill_slice_at=((2, 4),),
    )
    whole = plan.slice_liveness(3, 0, 10)
    chunked = np.concatenate(
        [plan.slice_liveness(3, 0, 4), plan.slice_liveness(3, 4, 6)], axis=1
    )
    np.testing.assert_array_equal(whole, chunked)
    # drop window inclusive; delay covers [round, round+delay)
    assert whole[0, 1] == 1.0 and whole[0, 2] == 0.0
    assert whole[0, 3] == 0.0 and whole[0, 4] == 1.0
    assert whole[1, 4] == 1.0 and whole[1, 5] == 0.0
    assert whole[1, 6] == 0.0 and whole[1, 7] == 1.0
    # a killed slice stays dead to the end of the mask (only a supervisor
    # restart, which re-renders without the kill, revives it)
    assert (whole[2, 4:] == 0.0).all() and (whole[2, :4] == 1.0).all()
    # the process-kill arm: mask rendered without kills
    nokill = plan.slice_liveness(3, 0, 10, include_kills=False)
    assert (nokill[2] == 1.0).all()
    kill_only = FaultPlan(kill_slice_at=((0, 1),))
    assert kill_only.injects_slice_faults()
    assert not kill_only.injects_slice_faults(include_kills=False)
    # the shared window helper mirrors fault_window's None contract
    assert slice_fault_window(None, 2, 0, 4) is None
    assert slice_fault_window(plan, 1, 0, 4) is None  # no slice tier
    assert slice_fault_window(
        kill_only, 2, 0, 4, include_kills=False
    ) is None
    np.testing.assert_array_equal(
        slice_fault_window(plan, 3, 2, 4), plan.slice_liveness(3, 2, 4)
    )


def test_fault_plan_nan_mask_and_poisoning():
    plan = FaultPlan(nan_at=((2, 1), (5, 0)))
    mask = plan.nan_mask(2, 0, 4)  # window covers round 2 only
    assert mask[1, 2] and mask.sum() == 1
    x = np.zeros((2, 8, 3, 4), np.float32)  # [S, steps, B, F]
    out = poison_inputs(x, mask, local_iterations=2)
    assert np.isnan(out[1, 4:6]).all()  # round 2 → steps 4..5
    assert np.isfinite(out[0]).all()
    assert np.isfinite(out[1, :4]).all() and np.isfinite(out[1, 6:]).all()
    assert np.isfinite(x).all()  # original untouched
    assert poison_inputs(x, np.zeros((2, 4), bool), 2) is x  # no-copy fast path


# ---------------------------------------------------------------------------
# liveness masking + quarantine inside the compiled epoch
# ---------------------------------------------------------------------------


def _toy_sites(ns, n=24, d=6, seed=0):
    out = []
    rng = np.random.default_rng(seed)
    for _ in range(ns):
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (X.sum(-1) > 0).astype(np.int32)
        out.append(SiteArrays(X, y, np.arange(n, dtype=np.int32)))
    return out


def _identical_sites(ns, n=24, d=6, seed=3):
    """ns sites holding byte-identical data (so a masked-out site's run can
    be compared against a run without it)."""
    one = _toy_sites(1, n=n, d=d, seed=seed)[0]
    return [SiteArrays(one.inputs.copy(), one.labels.copy(), one.indices.copy())
            for _ in range(ns)]


def _fit(cfg, sites_fn, mesh, fault_plan=None, out_dir=None, resume=False,
         **fit_kw):
    model = MSANNet(in_size=6, hidden_sizes=(8,), out_size=2)
    tr = FederatedTrainer(cfg, model, mesh, out_dir=out_dir,
                          fault_plan=fault_plan)
    res = tr.fit(sites_fn("train"), sites_fn("val"), sites_fn("test"),
                 verbose=False, resume=resume, **fit_kw)
    return tr, res


def test_nan_injection_quarantines_site():
    """A site whose inputs go NaN for quarantine_rounds consecutive rounds is
    auto-quarantined; training completes finite, and — because both sites
    hold identical data — the final params equal a run without the poisoned
    site entirely (the weighted mean renormalizes over live weight only)."""
    # 24 samples / batch 8 → 3 rounds per epoch; poison site 1's rounds 0-2
    plan = FaultPlan(nan_at=((0, 1), (1, 1), (2, 1)))
    cfg = TrainConfig(epochs=3, batch_size=8, quarantine_rounds=3, patience=50)

    def two(which):
        return _identical_sites(2) if which == "train" else _identical_sites(2, n=16, seed=9)

    def one(which):
        return two(which)[:1]

    _, res_faulted = _fit(cfg, two, host_mesh(2), fault_plan=plan)
    _, res_solo = _fit(cfg, one, host_mesh(1))

    health = res_faulted["site_health"]
    assert health["site_quarantined"] == [0, 1]
    assert health["site_skipped_rounds"][0] == 0
    assert health["site_skipped_rounds"][1] == 9  # every round of 3 epochs
    assert np.isfinite(res_faulted["epoch_losses"]).all()
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6),
        res_faulted["state"].params, res_solo["state"].params,
    )


@pytest.mark.parametrize("engine", ["dSGD", "rankDAD", "powerSGD"])
def test_scheduled_dropout_renormalizes_every_engine(engine):
    """A scheduled site drop flows into every engine's aggregate: with two
    identical sites and site 1 dropped from round 0, the aggregate equals the
    single-site run for ALL engines (dead payloads are where-zeroed and the
    weighted mean renormalizes over live weight)."""
    plan = FaultPlan(drop=((1, 0, -1),))
    cfg = TrainConfig(epochs=2, batch_size=8, agg_engine=engine, patience=50)

    def two(which):
        return _identical_sites(2) if which == "train" else _identical_sites(2, n=16, seed=9)

    def one(which):
        return two(which)[:1]

    _, res_faulted = _fit(cfg, two, None, fault_plan=plan)
    _, res_solo = _fit(cfg, one, None)

    health = res_faulted["site_health"]
    assert health["site_quarantined"] == [0, 0]  # dropped ≠ quarantined
    assert health["site_skipped_rounds"] == [0, 6]
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5),
        res_faulted["state"].params, res_solo["state"].params,
    )


def test_quarantine_minus_one_compiles_machinery_out():
    """quarantine_rounds=-1 with no FaultPlan is the static escape hatch: the
    epoch program REALLY carries no fault machinery (the lowered programs
    structurally diverge — checked through the shared normalized differ,
    checks/lowering.py) and trains identically (values match the default
    program bit-for-bit when every site is healthy)."""
    import jax.numpy as jnp
    from dinunet_implementations_tpu.checks.lowering import diff_report
    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.trainer import (
        FederatedTask, init_train_state, make_optimizer, make_train_epoch_fn,
    )

    task = FederatedTask(MSANNet(in_size=6, hidden_sizes=(8,), out_size=2))
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-2)
    state0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                              jnp.ones((4, 6)), num_sites=2)
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(2, 3, 4, 6)).astype(np.float32))
    y = jnp.asarray((rng.random((2, 3, 4)) > 0.5).astype(np.int32))
    w = jnp.ones((2, 3, 4), jnp.float32)
    outs, texts = {}, {}
    for qr in (3, -1):
        fn = make_train_epoch_fn(task, engine, opt, mesh=None,
                                 quarantine_rounds=qr)
        texts[qr] = fn.lower(state0, x, y, w).as_text()
        st, losses = fn(state0, x, y, w)
        outs[qr] = (st, losses)
    # structurally different programs (machinery genuinely compiled out)...
    assert diff_report(texts[3], texts[-1], "qr=3", "qr=-1") is not None
    # ...computing identical values on a healthy run:
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        outs[3][0].params, outs[-1][0].params,
    )
    np.testing.assert_array_equal(np.asarray(outs[3][1]), np.asarray(outs[-1][1]))
    # the opted-out program leaves health untouched (no counters maintained)
    np.testing.assert_array_equal(np.asarray(outs[-1][0].health["skips"]), [0, 0])
    # but a liveness mask still masks even when opted out
    live = jnp.asarray([[1.0, 1.0, 1.0], [0.0, 0.0, 0.0]])
    fn = make_train_epoch_fn(task, engine, opt, mesh=None, quarantine_rounds=-1)
    st_m, _ = fn(state0, x, y, w, live)
    assert np.isfinite(np.asarray(jax.tree.leaves(st_m.params)[0])).all()


def test_fault_masks_do_not_recompile():
    """Masks are traced inputs: a run whose fault pattern CHANGES every epoch
    (flaky drops) compiles the epoch exactly once."""
    plan = FaultPlan(flaky_prob=0.3, flaky_seed=5)
    cfg = TrainConfig(epochs=4, batch_size=8, patience=50)

    def sites(which):
        return _toy_sites(2) if which == "train" else _toy_sites(2, n=16, seed=9)

    tr, res = _fit(cfg, sites, host_mesh(2), fault_plan=plan)
    assert np.isfinite(res["epoch_losses"]).all()
    cache_size = getattr(tr.epoch_fn, "_cache_size", None)
    if cache_size is not None:
        assert cache_size() == 1, "per-mask recompilation"


def test_health_counters_reach_logs_json(tmp_path):
    plan = FaultPlan(drop=((1, 0, -1),))
    cfg = TrainConfig(epochs=2, batch_size=8, patience=50)

    def sites(which):
        return _toy_sites(2) if which == "train" else _toy_sites(2, n=16, seed=9)

    _fit(cfg, sites, host_mesh(2), fault_plan=plan, out_dir=str(tmp_path))
    remote = json.load(open(
        tmp_path / "remote/simulatorRun/FS-Classification/fold_0/logs.json"))
    assert remote["site_skipped_rounds"] == [0, 6]
    assert remote["site_quarantined"] == [0, 0]
    local1 = json.load(open(
        tmp_path / "local1/simulatorRun/FS-Classification/fold_0/logs.json"))
    assert local1["skipped_rounds"] == 6 and local1["quarantined"] == 0


# ---------------------------------------------------------------------------
# rotating / checksummed checkpoints
# ---------------------------------------------------------------------------


def _small_state(mesh_size=2):
    from dinunet_implementations_tpu.engines import make_engine
    from dinunet_implementations_tpu.trainer import (
        FederatedTask, init_train_state, make_optimizer,
    )
    import jax.numpy as jnp

    task = FederatedTask(MSANNet(in_size=6, hidden_sizes=(8,), out_size=2))
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-3)
    return init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                            jnp.ones((4, 6)), num_sites=mesh_size)


def test_checkpoint_rotation_keeps_previous_generation(tmp_path):
    state = _small_state()
    p = str(tmp_path / "ck.msgpack")
    save_checkpoint(p, state, meta={"epoch": 1}, rotate=True)
    assert not os.path.exists(p + ".prev")  # nothing to rotate yet
    save_checkpoint(p, state, meta={"epoch": 2}, rotate=True)
    assert os.path.exists(p + ".prev")
    _, meta = load_checkpoint(p, state, with_meta=True)
    assert meta["epoch"] == 2
    _, meta_prev = load_checkpoint(p + ".prev", state, with_meta=True)
    assert meta_prev["epoch"] == 1


def test_corrupt_checkpoint_falls_back_to_prev(tmp_path):
    state = _small_state()
    p = str(tmp_path / "ck.msgpack")
    save_checkpoint(p, state, meta={"epoch": 1}, rotate=True)
    save_checkpoint(p, state, meta={"epoch": 2}, rotate=True)
    # bit-rot in the latest generation: checksum catches it, loader recovers
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(p, "wb").write(bytes(blob))
    with pytest.warns(UserWarning, match="falling back"):
        _, meta = load_checkpoint(p, state, with_meta=True)
    assert meta["epoch"] == 1
    # a truncated (torn) latest also falls back
    open(p, "wb").write(bytes(blob[:10]))
    with pytest.warns(UserWarning, match="falling back"):
        _, meta = load_checkpoint(p, state, with_meta=True)
    assert meta["epoch"] == 1
    # a MISSING latest with a surviving .prev (kill between rotate and
    # replace) also recovers
    os.remove(p)
    with pytest.warns(UserWarning, match="falling back"):
        _, meta = load_checkpoint(p, state, with_meta=True)
    assert meta["epoch"] == 1


def test_corrupt_checkpoint_without_prev_raises(tmp_path):
    state = _small_state()
    p = str(tmp_path / "ck.msgpack")
    save_checkpoint(p, state, meta={"epoch": 1})
    blob = bytearray(open(p, "rb").read())
    blob[-3] ^= 0xFF
    open(p, "wb").write(bytes(blob))
    with pytest.raises(CorruptCheckpointError, match="checksum"):
        load_checkpoint(p, state)


def test_checkpoint_health_counters_roundtrip(tmp_path):
    import jax.numpy as jnp

    state = _small_state()
    state = state.replace(health={
        "streak": jnp.asarray([0, 2], jnp.int32),
        "skips": jnp.asarray([1, 5], jnp.int32),
        "quarantined": jnp.asarray([0, 1], jnp.int32),
    })
    p = save_checkpoint(str(tmp_path / "ck.msgpack"), state)
    restored = load_checkpoint(p, _small_state())
    np.testing.assert_array_equal(np.asarray(restored.health["skips"]), [1, 5])
    np.testing.assert_array_equal(
        np.asarray(restored.health["quarantined"]), [0, 1])


# ---------------------------------------------------------------------------
# preemption: guard semantics + deterministic kill-at-round resume
# ---------------------------------------------------------------------------


def test_preemption_guard_latches_signal_and_restores_handlers():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as guard:
        assert guard.requested is None
        os.kill(os.getpid(), signal.SIGTERM)
        # delivery is synchronous for self-signals on the main thread
        assert guard.requested == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is before


def test_kill_at_round_saves_then_resume_matches_uninterrupted(tmp_path):
    """The FaultPlan kill arm: training raises Preempted after crossing the
    kill round (checkpoint already saved); resume=True with the SAME plan
    sails past the kill (it only fires when the round is crossed) and lands
    on the uninterrupted run's exact results."""
    cfg = TrainConfig(epochs=6, batch_size=8, patience=50)

    def sites(which):
        return _toy_sites(2, n=40, seed=4) if which == "train" \
            else _toy_sites(2, n=16, seed=5)

    _, res_full = _fit(cfg, sites, host_mesh(2), out_dir=str(tmp_path / "full"))

    # 40 samples / batch 8 → 5 rounds per epoch; kill crossing in epoch 3
    plan = FaultPlan(kill_at_round=12)
    with pytest.raises(Preempted) as exc:
        _fit(cfg, sites, host_mesh(2), fault_plan=plan,
             out_dir=str(tmp_path / "killed"))
    assert exc.value.epoch == 3
    ck = tmp_path / "killed/remote/simulatorRun/FS-Classification/fold_0/checkpoint_latest.msgpack"
    assert ck.exists()

    _, res_res = _fit(cfg, sites, host_mesh(2), fault_plan=plan,
                      out_dir=str(tmp_path / "killed"), resume=True)
    assert res_res["test_metrics"] == res_full["test_metrics"]
    assert res_res["best_val_epoch"] == res_full["best_val_epoch"]
    np.testing.assert_allclose(res_res["epoch_losses"],
                               res_full["epoch_losses"], atol=1e-6)

    # rotate-window crash: a kill between os.replace(ckpt → .prev) and the
    # new primary's write leaves ONLY .prev — resume must fall back to it
    # (one replayed epoch) instead of silently restarting from scratch
    assert os.path.exists(str(ck) + ".prev")
    os.remove(ck)
    with pytest.warns(UserWarning, match="falling back"):
        _, res_prev = _fit(cfg, sites, host_mesh(2), fault_plan=plan,
                           out_dir=str(tmp_path / "killed"), resume=True)
    assert res_prev["test_metrics"] == res_full["test_metrics"]
    np.testing.assert_allclose(res_prev["epoch_losses"],
                               res_full["epoch_losses"], atol=1e-6)


# ---------------------------------------------------------------------------
# retry / backoff + distributed shutdown + runner discovery hardening
# ---------------------------------------------------------------------------


def test_with_retry_retries_then_succeeds():
    calls, delays = [], []

    @with_retry(attempts=3, base_delay=0.1, retry_on=(OSError,), seed=0,
                sleep=delays.append)
    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert flaky() == "ok"
    assert len(calls) == 3 and len(delays) == 2
    # exponential envelope with jitter in [0.5, 1.5)
    assert 0.05 <= delays[0] < 0.15
    assert 0.10 <= delays[1] < 0.30
    # deterministic under a fixed seed
    calls2, delays2 = [], []

    @with_retry(attempts=3, base_delay=0.1, retry_on=(OSError,), seed=0,
                sleep=delays2.append)
    def flaky2():
        calls2.append(1)
        if len(calls2) < 3:
            raise OSError("transient")
        return "ok"

    flaky2()
    assert delays2 == delays


def test_with_retry_exhaustion_and_nonretryable():
    attempts = []

    @with_retry(attempts=2, base_delay=0.0, retry_on=(OSError,),
                sleep=lambda _: None)
    def always_fails():
        attempts.append(1)
        raise OSError("down")

    with pytest.raises(OSError, match="down"):
        always_fails()
    assert len(attempts) == 2

    @with_retry(attempts=3, retry_on=(OSError,), sleep=lambda _: None)
    def wrong_kind():
        attempts.append("v")
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        wrong_kind()
    assert attempts.count("v") == 1  # no retries for non-transient errors


def test_distributed_shutdown_resets_init_flag(monkeypatch):
    from dinunet_implementations_tpu.parallel import distributed as dist

    called = []
    monkeypatch.setattr(jax.distributed, "shutdown", lambda: called.append(1))
    monkeypatch.setattr(dist, "_initialized", True)
    dist.distributed_shutdown()
    assert called == [1] and dist._initialized is False
    # idempotent: a second call must not touch the (dead) runtime again
    dist.distributed_shutdown()
    assert called == [1]


def test_discover_site_dirs_survives_mixed_local_trees(tmp_path):
    """Regression: a ``local`` dir with no digits (e.g. input/local/
    simulatorRun) or digits elsewhere in the path must neither crash the
    numeric sort nor scramble site order."""
    from dinunet_implementations_tpu.runner import discover_site_dirs

    root = tmp_path / "data2"  # digit in the tree, outside the site segment
    for name in ("local", "local10", "local2"):
        (root / "input" / name / "simulatorRun").mkdir(parents=True)
    dirs = discover_site_dirs(str(root))
    names = [p.split(os.sep)[-2] for p in dirs]
    assert names == ["local", "local2", "local10"]  # numeric, not lexicographic
    # no local* dirs → the dataset dir itself is the single site
    assert discover_site_dirs(str(tmp_path / "nope")) == [str(tmp_path / "nope")]


# ---------------------------------------------------------------------------
# chaos integration (slow): SIGTERM crash-resume, dropout convergence floor,
# and the full acceptance scenario
# ---------------------------------------------------------------------------


def _run_worker(out_dir, epochs, resume=False, kill_after_epoch=None,
                timeout=300):
    worker = os.path.join(os.path.dirname(__file__), "preempt_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    args = [sys.executable, "-u", worker, str(out_dir), str(epochs)]
    if resume:
        args.append("--resume")
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    lines, deadline = [], time.monotonic() + timeout
    killed = False
    while proc.poll() is None and time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            continue
        lines.append(line)
        if (kill_after_epoch is not None and not killed
                and f"epoch {kill_after_epoch}:" in line):
            proc.send_signal(signal.SIGTERM)
            killed = True
    try:
        proc.wait(timeout=max(deadline - time.monotonic(), 1))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    lines.extend(proc.stdout.readlines())
    return proc.returncode, "".join(lines)


@pytest.mark.slow
def test_sigterm_crash_resume_equivalence(tmp_path):
    """Kill a real training process with SIGTERM mid-fit: it must save and
    exit 143; resuming must land on the uninterrupted run's exact metrics."""
    rc_full, out_full = _run_worker(tmp_path / "full", epochs=12)
    assert rc_full == 0, out_full
    res_full = json.load(open(tmp_path / "full" / "results.json"))

    kdir = tmp_path / "killed"
    rc_kill, out_kill = _run_worker(kdir, epochs=12, kill_after_epoch=3)
    assert rc_kill == 128 + signal.SIGTERM, out_kill
    assert "PREEMPTED" in out_kill
    assert not (kdir / "results.json").exists()
    ck = kdir / "remote/simulatorRun/FS-Classification/fold_0/checkpoint_latest.msgpack"
    assert ck.exists(), out_kill

    rc_res, out_res = _run_worker(kdir, epochs=12, resume=True)
    assert rc_res == 0, out_res
    res_res = json.load(open(kdir / "results.json"))
    assert res_res["test_metrics"] == res_full["test_metrics"]
    assert res_res["best_val_epoch"] == res_full["best_val_epoch"]
    np.testing.assert_allclose(res_res["epoch_losses"],
                               res_full["epoch_losses"], atol=1e-6)


@pytest.mark.slow
def test_site_dropout_convergence_floor():
    """Losing 2 of 4 sites mid-training must degrade gracefully: the
    federation keeps training on the survivors and still clears a
    reference-grade AUC floor on the separable toy task."""
    cfg = TrainConfig(epochs=15, batch_size=8, patience=50, learning_rate=1e-2)
    # 40 samples / batch 8 → 5 rounds/epoch; sites 2 & 3 die at epoch 6
    plan = FaultPlan(drop=((2, 25, -1), (3, 25, -1)))

    def sites(which):
        n, seed = (40, 1) if which == "train" else (24, 2 if which == "val" else 3)
        return _toy_sites(4, n=n, seed=seed)

    _, res = _fit(cfg, sites, None, fault_plan=plan)
    health = res["site_health"]
    assert health["site_skipped_rounds"][2] == 50  # epochs 6-15 × 5 rounds
    assert health["site_skipped_rounds"][3] == 50
    assert health["site_quarantined"] == [0, 0, 0, 0]
    assert res["test_scores"]["auc"] > 0.85, (
        f"dropout broke convergence: {res['test_scores']}")


@pytest.mark.slow
def test_chaos_acceptance_8_sites(tmp_path):
    """The ISSUE acceptance scenario: 8 sites, 2 dropping mid-training, one
    site NaN-poisoned into quarantine, under a seeded FaultPlan — the run
    completes, quarantines exactly the poisoned site, compiles exactly one
    epoch program (no per-mask recompile), and the kill-at-round arm resumes
    to the uninterrupted faulted baseline's exact metrics."""
    # 24 samples / batch 8 → 3 rounds/epoch, 8 epochs = 24 rounds.
    # Sites 5 & 6 drop from round 9 (epoch 4); site 2's inputs go NaN for
    # rounds 4-6 → quarantined (quarantine_rounds=3) from round 7 on.
    faults = dict(drop=((5, 9, -1), (6, 9, -1)),
                  nan_at=((4, 2), (5, 2), (6, 2)))
    cfg = TrainConfig(epochs=8, batch_size=8, patience=50, quarantine_rounds=3)

    def sites(which):
        n, seed = (24, 1) if which == "train" else (16, 2 if which == "val" else 3)
        return _toy_sites(8, n=n, seed=seed)

    # --- clean run: the compiled-program-count yardstick
    tr_clean, res_clean = _fit(cfg, sites, None)

    # --- faulted, uninterrupted: the kill arm's baseline
    plan = FaultPlan(**faults)
    tr_fault, res_fault = _fit(cfg, sites, None, fault_plan=plan)
    health = res_fault["site_health"]
    assert health["site_quarantined"] == [0, 0, 1, 0, 0, 0, 0, 0]
    # site 2: rounds 4-6 non-finite + quarantined 7..23 → 20 skips
    assert health["site_skipped_rounds"][2] == 20
    # sites 5/6: rounds 9..23 dropped → 15 skips
    assert health["site_skipped_rounds"][5] == 15
    assert health["site_skipped_rounds"][6] == 15
    assert np.isfinite(res_fault["epoch_losses"]).all()

    # no per-mask recompile: same compiled-program count as the clean run
    for tr in (tr_clean, tr_fault):
        cache_size = getattr(tr.epoch_fn, "_cache_size", None)
        if cache_size is not None:
            assert cache_size() == 1

    # --- kill arm: same faults + kill at round 14 (epoch 5), then resume
    plan_kill = FaultPlan(kill_at_round=14, **faults)
    with pytest.raises(Preempted):
        _fit(cfg, sites, None, fault_plan=plan_kill,
             out_dir=str(tmp_path / "killed"))
    _, res_resumed = _fit(cfg, sites, None, fault_plan=plan_kill,
                          out_dir=str(tmp_path / "killed"), resume=True)
    assert res_resumed["test_metrics"] == res_fault["test_metrics"]
    np.testing.assert_allclose(res_resumed["epoch_losses"],
                               res_fault["epoch_losses"], atol=1e-6)
    assert res_resumed["site_health"] == health
