"""RDP accounting for the in-scan DP-SGD mechanism — host-side, stdlib+numpy.

The device side (privacy/dpsgd.py) adds, per site per round, Gaussian noise
``σ·C·ε`` to the clipped (``‖g‖ ≤ C``) round gradient. This module answers
"what (ε, δ) has that spent so far": Rényi differential privacy of the
subsampled Gaussian mechanism (Mironov 2017; Mironov/Talwar/Zhang 2019 —
the TF-Privacy moments accountant), composed additively over rounds and
converted to (ε, δ) by the standard RDP→DP bound.

Semantics and honesty notes (docs/ARCHITECTURE.md "Privacy plane"):

- ε is PER SITE, record-level: each site runs its own (identically
  parameterized) mechanism on its own data, so the accountant tracks one
  trajectory that upper-bounds every site's loss at the cohort's LARGEST
  per-round sampling fraction ``q = B·local_iterations / n_site_min`` (the
  conservative corner — the smallest site samples the largest fraction).
- The trainer draws epoch batches by shuffled partition, not Poisson
  sampling; the subsampled-Gaussian amplification is the standard
  approximation for that regime and is reported as such.
- RDP is computed at INTEGER orders α ∈ {2..64} via the exact
  binomial-expansion upper bound for integer α (log-sum-exp-stable), with
  the q == 1 closed form ``α/(2σ²)`` (no subsampling to amplify).
- The accountant state is a plain (orders, rdp, steps) triple that
  serializes into the checkpoint meta (trainer/loop.py), so a resumed fit
  continues ε accumulation EXACTLY — no double count, no reset
  (tests/test_privacy.py pins resume == uninterrupted).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

#: default Rényi orders: the integer range the TF-Privacy accountant sweeps;
#: small orders bound the high-noise regime, large orders the low-noise one
DEFAULT_ORDERS = tuple(range(2, 65))

#: The in-scan mechanism clips the site's round-MEAN gradient and noises it
#: once (privacy/dpsgd.py), not the per-example-clipped SUM the textbook
#: DP-SGD analysis assumes: under record-level adjacency the sensitivity of
#: clip(mean) is bounded by 2C (both neighbours' outputs merely lie in the
#: C-ball), not C. The ledger therefore composes at the CONSERVATIVE
#: effective multiplier σ/2 — the reported ε is an upper bound on the
#: spend, never an optimistic one. trainer/loop.py and the bench arms both
#: divide by this factor; tests pin the trainer figure against the same
#: constant so the two sides cannot drift.
MEAN_CLIP_SENSITIVITY_FACTOR = 2.0


def effective_noise_multiplier(noise_multiplier: float) -> float:
    """The σ the RDP ledger composes at for the clip-of-mean mechanism
    (see :data:`MEAN_CLIP_SENSITIVITY_FACTOR`)."""
    return float(noise_multiplier) / MEAN_CLIP_SENSITIVITY_FACTOR


def _log_binom(n: int, k: int) -> float:
    return math.lgamma(n + 1) - math.lgamma(k + 1) - math.lgamma(n - k + 1)


def _logsumexp(vals) -> float:
    m = max(vals)
    if not math.isfinite(m):
        return m
    return m + math.log(sum(math.exp(v - m) for v in vals))


def rdp_sampled_gaussian(q: float, noise_multiplier: float, order: int) -> float:
    """One step's RDP at integer ``order`` for the sampled Gaussian mechanism
    with sampling fraction ``q`` and noise multiplier ``σ`` (noise std is
    ``σ·C`` against an L2 sensitivity of ``C``).

    ``q == 1``: the plain Gaussian mechanism, ``α/(2σ²)``. ``0 < q < 1``:
    Mironov et al. 2019's integer-order bound
    ``(1/(α−1))·log Σ_{k=0..α} C(α,k)(1−q)^{α−k} q^k exp(k(k−1)/(2σ²))``.
    ``σ == 0`` is infinite (no noise, no guarantee); ``q == 0`` is 0 (the
    mechanism never touches the data)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"sampling fraction must be in [0, 1], got {q}")
    if order < 2 or int(order) != order:
        raise ValueError(f"integer order >= 2 required, got {order}")
    if noise_multiplier <= 0.0:
        return math.inf
    if q == 0.0:
        return 0.0
    s2 = float(noise_multiplier) ** 2
    if q == 1.0:
        return order / (2.0 * s2)
    a = int(order)
    terms = [
        _log_binom(a, k)
        + (a - k) * math.log1p(-q)
        + (k * math.log(q) if k else 0.0)
        + (k * (k - 1)) / (2.0 * s2)
        for k in range(a + 1)
    ]
    return _logsumexp(terms) / (a - 1)


def rdp_to_epsilon(orders, rdp, delta: float):
    """(ε, best order) from accumulated RDP via the standard conversion
    ``ε = min_α rdp_α + log(1/δ)/(α−1)``."""
    if not 0.0 < delta < 1.0:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    best_eps, best_order = math.inf, None
    for a, r in zip(orders, rdp):
        if not math.isfinite(r):
            continue
        eps = r + math.log(1.0 / delta) / (a - 1)
        if eps < best_eps:
            best_eps, best_order = eps, a
    return best_eps, best_order


def sampling_fraction(batch_size: int, local_iterations: int,
                      site_sizes) -> float:
    """The conservative per-round sampling fraction the accountant composes
    at: each round every site steps ``batch_size·local_iterations`` of its
    own examples, so the smallest non-empty site samples the largest
    fraction — that corner bounds every site's privacy loss. Empty sites
    sample nothing and are ignored; an empty cohort is q = 0."""
    sizes = [int(n) for n in site_sizes if int(n) > 0]
    if not sizes:
        return 0.0
    per_round = max(int(batch_size), 1) * max(int(local_iterations), 1)
    return min(1.0, per_round / min(sizes))


@dataclasses.dataclass
class RdpAccountant:
    """Additive-composition RDP ledger for one fit.

    ``step(noise_multiplier, q, steps)`` composes ``steps`` rounds of the
    sampled Gaussian mechanism; ``epsilon(delta)`` converts to (ε, δ).
    JSON-round-trips through the checkpoint meta so a resumed fit continues
    the EXACT ledger (tests pin resume == uninterrupted, and the CI smoke
    pins ε monotone over epochs)."""

    orders: tuple = DEFAULT_ORDERS
    rdp: np.ndarray = None
    steps: int = 0

    def __post_init__(self):
        if self.rdp is None:
            self.rdp = np.zeros(len(self.orders), np.float64)
        else:
            self.rdp = np.asarray(self.rdp, np.float64)
        if self.rdp.shape != (len(self.orders),):
            raise ValueError(
                f"rdp ledger has {self.rdp.shape} entries for "
                f"{len(self.orders)} orders"
            )

    def step(self, noise_multiplier: float, q: float, steps: int = 1
             ) -> "RdpAccountant":
        """Compose ``steps`` rounds at (σ, q) into the ledger (in place)."""
        if steps < 0:
            raise ValueError(f"steps must be >= 0, got {steps}")
        if steps:
            per = np.array([
                rdp_sampled_gaussian(q, noise_multiplier, a)
                for a in self.orders
            ])
            self.rdp = self.rdp + per * steps
            self.steps += int(steps)
        return self

    def epsilon(self, delta: float):
        """(ε, δ)-DP spent so far: ``(epsilon, best_order)``; ``(inf, None)``
        when no finite order bounds the mechanism (σ = 0) — and ``(0, None)``
        before any step."""
        if self.steps == 0:
            return 0.0, None
        return rdp_to_epsilon(self.orders, self.rdp, delta)

    # -- checkpoint-meta round trip --------------------------------------

    def to_json(self) -> dict:
        return {
            "orders": list(self.orders),
            # inf survives the strict-JSON metrics contract by riding the
            # checkpoint META (json.dumps default allows it) — but keep the
            # ledger finite-or-null anyway so the meta stays jq-friendly
            "rdp": [r if math.isfinite(r) else None for r in self.rdp],
            "steps": int(self.steps),
        }

    @classmethod
    def from_json(cls, blob) -> "RdpAccountant":
        if not isinstance(blob, dict):
            raise ValueError(f"accountant state must be an object, got {blob!r}")
        rdp = np.array([
            math.inf if r is None else float(r) for r in blob["rdp"]
        ])
        return cls(
            orders=tuple(int(a) for a in blob["orders"]),
            rdp=rdp, steps=int(blob.get("steps", 0)),
        )
