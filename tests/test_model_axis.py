"""End-to-end sequence/model-axis parallelism (VERDICT r2 #1).

Round 2 built and unit-tested the ring primitives (tests/test_sequence.py) but
left them unreachable from any config or trainer path. These tests cover the
wiring: ``TrainConfig.model_axis_size`` → a ``(site, model)`` mesh → the model
sharding its sequence axis internally → masked-loss + grad-psum assembly in
the train step (trainer/steps.py) — asserting the sharded run reproduces the
dense run, not just that it executes.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dinunet_implementations_tpu.core.config import TrainConfig
from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.models import ICALstm, MultimodalNet
from dinunet_implementations_tpu.parallel.mesh import MODEL_AXIS, host_mesh
from dinunet_implementations_tpu.runner.registry import get_task
from dinunet_implementations_tpu.trainer import (
    FederatedTask,
    init_train_state,
    make_optimizer,
    make_train_epoch_fn,
)
from dinunet_implementations_tpu.trainer.steps import make_eval_fn


pytestmark = pytest.mark.slow  # shard_map integration tier: every test compiles a multi-device program


def _ica_model(seq_axis=None):
    return ICALstm(
        input_size=12, hidden_size=10, num_comps=3, window_size=4, num_cls=2,
        sequence_axis=seq_axis,
    )


def _epoch_data(S=2, steps=2, B=4, windows=8, comps=3, wlen=4, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(S, steps, B, windows, comps, wlen)).astype(np.float32))
    y = jnp.asarray((rng.random((S, steps, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)
    return x, y, w


def _run_epochs(model, mesh, num_sites, data, epochs=3, optimizer="sgd"):
    task = FederatedTask(model)
    engine = make_engine("dSGD")
    opt = make_optimizer(optimizer, 1e-2)
    x, y, w = data
    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=num_sites
    )
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh, local_iterations=1)
    losses = []
    for _ in range(epochs):
        state, ls = epoch_fn(state, x, y, w)
        losses.extend(np.asarray(ls).tolist())
    return state, losses


def test_ica_train_matches_dense_over_model_axis():
    """Flagship e2e: 2 sites × model_axis 2 (4 devices) must reproduce the
    2-site dense run — same per-round losses AND same final params.

    SGD on purpose: it is linear in the gradient, so the assert is tight.
    (Verified during bring-up: grads match to ~1e-9; under Adam the early
    update is ≈ lr·sign(g), which amplifies that reduction-order noise into
    visible param drift while losses stay identical — covered by the Adam
    loss-trajectory test below.)"""
    data = _epoch_data()
    dense_state, dense_losses = _run_epochs(_ica_model(), host_mesh(2), 2, data)
    ring_state, ring_losses = _run_epochs(
        _ica_model(MODEL_AXIS), host_mesh(2, model_axis_size=2), 2, data
    )
    np.testing.assert_allclose(ring_losses, dense_losses, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6
        ),
        dense_state.params,
        ring_state.params,
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6
        ),
        dense_state.batch_stats,
        ring_state.batch_stats,
    )


def test_ica_adam_loss_trajectory_matches_dense():
    """Under Adam (the production optimizer) the per-round loss trajectory of
    the model-axis run tracks the dense run."""
    data = _epoch_data(seed=7)
    _, dense_losses = _run_epochs(
        _ica_model(), host_mesh(2), 2, data, optimizer="adam"
    )
    _, ring_losses = _run_epochs(
        _ica_model(MODEL_AXIS), host_mesh(2, model_axis_size=2), 2, data,
        optimizer="adam",
    )
    np.testing.assert_allclose(ring_losses, dense_losses, atol=1e-4)


def test_ica_eval_matches_dense_over_model_axis():
    data = _epoch_data()
    x, y, w = data
    dense_state, _ = _run_epochs(_ica_model(), host_mesh(2), 2, data, epochs=1)

    ring_model = _ica_model(MODEL_AXIS)
    ring_task = FederatedTask(ring_model)
    ring_task.init_variables(jax.random.PRNGKey(0), x[0, 0])
    dense_task = FederatedTask(_ica_model())
    dense_task.init_variables(jax.random.PRNGKey(0), x[0, 0])

    ev_dense = make_eval_fn(dense_task, host_mesh(2))
    ev_ring = make_eval_fn(ring_task, host_mesh(2, model_axis_size=2))
    # device-neutral copy: the trained state is committed to the 2-device
    # mesh; the ring eval jit places onto the 4-device mesh itself
    dense_state = jax.tree.map(np.asarray, dense_state)
    pd, ld, wd = ev_dense(dense_state, x, y, w)
    pr, lr, wr = ev_ring(dense_state, x, y, w)
    np.testing.assert_allclose(np.asarray(pr), np.asarray(pd), atol=2e-5)
    np.testing.assert_allclose(np.asarray(lr), np.asarray(ld), atol=1e-4)
    np.testing.assert_array_equal(np.asarray(wr), np.asarray(wd))


def test_multimodal_ring_forward_matches_local():
    """MultimodalNet attention="ring" + internal token sharding == the dense
    local-attention forward, on a real model-axis mesh."""
    rng = np.random.default_rng(1)
    # tokens = 2 + S windows; S=6 → T=8, divisible by the 4-way model axis
    S, C, W = 6, 3, 4
    model_local = MultimodalNet(
        fs_input_size=5, num_comps=C, window_size=W, embed_dim=16, num_heads=2,
        num_layers=2, num_cls=2,
    )
    model_ring = model_local.clone(attention="ring", axis_name=MODEL_AXIS)
    x = jnp.asarray(rng.normal(size=(3, 5 + S * C * W)).astype(np.float32))
    variables = model_local.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, train=False,
    )
    out_local = model_local.apply(variables, x, train=False)

    mesh = host_mesh(1, model_axis_size=4)
    from dinunet_implementations_tpu.core.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    out_ring = shard_map(
        lambda v, xx: model_ring.apply(v, xx, train=False),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False,
    )(variables, x)
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_local), atol=2e-5)


def test_multimodal_ring_grads_match_local():
    """Masked-loss + psum-over-model-axis must assemble the exact full grad
    (the head/chunk double-count trap)."""
    rng = np.random.default_rng(2)
    S, C, W = 6, 2, 3
    model_local = MultimodalNet(
        fs_input_size=4, num_comps=C, window_size=W, embed_dim=8, num_heads=2,
        num_layers=1, num_cls=2,
    )
    model_ring = model_local.clone(attention="ring", axis_name=MODEL_AXIS)
    x = jnp.asarray(rng.normal(size=(2, 4 + S * C * W)).astype(np.float32))
    y = jnp.asarray([0, 1], jnp.int32)
    variables = model_local.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, train=False,
    )

    def loss_local(params):
        logits = model_local.apply({"params": params}, x, train=False)
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    g_local = jax.grad(loss_local)(variables["params"])

    mesh = host_mesh(1, model_axis_size=2)
    from dinunet_implementations_tpu.core.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    def sharded_grad(params):
        def loss_ring(p):
            logits = model_ring.apply({"params": p}, x, train=False)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()
            keep = (jax.lax.axis_index(MODEL_AXIS) == 0).astype(loss.dtype)
            return loss * keep

        g = jax.grad(loss_ring)(params)
        return jax.lax.psum(g, MODEL_AXIS)

    g_ring = shard_map(
        sharded_grad, mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False
    )(variables["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_local, g_ring,
    )


def test_ica_ring_bf16_pallas_tracks_dense():
    """Review-finding regression (r3): ring + compute_dtype=bf16 + the fused
    kernel — the relayed carry must stay f32 at chunk boundaries, so the
    sharded forward tracks the dense forward within bf16 tolerance."""
    from dinunet_implementations_tpu.core.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    rng = np.random.default_rng(11)
    dense = ICALstm(
        input_size=12, hidden_size=10, num_comps=3, window_size=4, num_cls=2,
        compute_dtype="bfloat16", use_pallas=True,
    )
    ring = dense.clone(sequence_axis=MODEL_AXIS)
    x = jnp.asarray(rng.normal(size=(4, 8, 3, 4)).astype(np.float32))
    variables = dense.clone(use_pallas=False, compute_dtype=None).init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, train=False,
    )
    out_dense = dense.apply(variables, x, train=False)
    mesh = host_mesh(1, model_axis_size=2)
    out_ring = shard_map(
        lambda v, xx: ring.apply(v, xx, train=False),
        mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False,
    )(variables, x)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), atol=0.05
    )


def test_ring_dropout_decorrelated_across_chunks():
    """Train-mode dropout in the ring transformer must draw a DIFFERENT mask
    per token chunk: feed every device an identical chunk — correlated
    (tiled) dropout would make all per-device outputs identical."""
    from dinunet_implementations_tpu.core.jaxcompat import shard_map
    from jax.sharding import PartitionSpec as P

    from dinunet_implementations_tpu.models.transformer import TransformerBlock

    rng = np.random.default_rng(5)
    block = TransformerBlock(
        embed_dim=8, num_heads=2, dropout_rate=0.5, attention="ring",
        axis_name=MODEL_AXIS,
    )
    x = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))
    variables = block.clone(attention="local", axis_name=None).init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        x, train=False,
    )
    mesh = host_mesh(1, model_axis_size=4)

    def fn(v, xx):
        out = block.apply(
            v, xx, train=True, rngs={"dropout": jax.random.PRNGKey(2)}
        )
        return jax.lax.all_gather(out, MODEL_AXIS)

    outs = np.asarray(
        shard_map(fn, mesh=mesh, in_specs=(P(), P()), out_specs=P(), check_vma=False)(
            variables, x
        )
    )  # [4 devices, B, T_local, E] — same input chunk everywhere
    diffs = [np.abs(outs[i] - outs[0]).max() for i in range(1, 4)]
    assert all(d > 1e-6 for d in diffs), f"dropout masks tiled across chunks: {diffs}"


def test_fed_runner_builds_model_axis_mesh(tmp_path):
    """cfg.model_axis_size reaches the mesh and the model through FedRunner."""
    from dinunet_implementations_tpu.runner.fed_runner import FedRunner

    # synthetic 2-site ICA tree (shape mirrors tests/test_runner.py's helper)
    import pandas as pd

    rng = np.random.default_rng(3)
    n_sub, comps, T = 12, 3, 16
    for s in range(2):
        d = tmp_path / "input" / f"local{s}" / "simulatorRun"
        d.mkdir(parents=True)
        data = rng.normal(size=(n_sub, comps, T)).astype(np.float32)
        np.savez(d / "tc.npz", data=data)
        pd.DataFrame(
            {"index": list(range(n_sub)), "label": rng.integers(0, 2, n_sub)}
        ).to_csv(d / "labels.csv", index=False)

    cfg = TrainConfig(
        task_id="ICA-Classification",
        epochs=1,
        batch_size=4,
        model_axis_size=2,
        split_ratio=(0.6, 0.2, 0.2),
    )
    cfg = dataclasses.replace(
        cfg,
        ica_args=dataclasses.replace(
            cfg.ica_args,
            data_file="tc.npz", labels_file="labels.csv",
            num_components=comps, temporal_size=T, window_size=4,
            window_stride=4, input_size=8, hidden_size=6,
        ),
    )
    runner = FedRunner(cfg, data_path=str(tmp_path), out_dir=str(tmp_path / "out"))
    assert dict(runner.mesh.shape) == {"site": 2, "model": 2}
    model = get_task(runner.cfg.task_id).build_model(runner.cfg)
    assert model.sequence_axis == MODEL_AXIS
    results = runner.run(verbose=False)
    assert np.isfinite(results[0]["test_metrics"][0][0])


def test_model_axis_requires_enough_devices(tmp_path):
    from dinunet_implementations_tpu.runner.fed_runner import FedRunner

    for s in range(5):  # 5 sites × model 2 = 10 > 8 virtual devices
        (tmp_path / "input" / f"local{s}" / "simulatorRun").mkdir(parents=True)
    with pytest.raises(ValueError, match="model_axis_size"):
        FedRunner(
            TrainConfig(model_axis_size=2), data_path=str(tmp_path),
        )


def test_long_context_ring_trains_512_windows():
    """Long-context capability: a sequence far beyond the reference's ~98
    windows (512), sharded 4-way over the model axis — the ring LSTM carries
    the recurrence across chunks and training stays finite and learns."""
    S_WINDOWS = 512
    rng = np.random.default_rng(13)
    model = ICALstm(
        input_size=8, hidden_size=6, num_comps=2, window_size=3, num_cls=2,
        sequence_axis=MODEL_AXIS,
    )
    task = FederatedTask(model)
    engine = make_engine("dSGD")
    opt = make_optimizer("adam", 1e-2)
    B = 4
    x_np = rng.normal(size=(2, 2, B, S_WINDOWS, 2, 3)).astype(np.float32)
    y = jnp.asarray((rng.random((2, 2, B)) > 0.5).astype(np.int32))
    # plant a class signal so the loss must actually fall
    x_np += np.asarray(y)[..., None, None, None] * 0.5
    x = jnp.asarray(x_np)
    w = jnp.ones((2, 2, B), jnp.float32)
    mesh = host_mesh(2, model_axis_size=4)  # 2 sites x 4-way sequence shard
    state = init_train_state(
        task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=2
    )
    fn = make_train_epoch_fn(task, engine, opt, mesh, local_iterations=1)
    losses = []
    for _ in range(4):
        state, ls = fn(state, x, y, w)
        losses.append(float(np.asarray(ls).mean()))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"loss did not fall: {losses}"


@pytest.mark.parametrize("engine_name,kw", [
    ("rankDAD", dict(dad_reduction_rank=4, dad_num_pow_iters=3, dad_tol=1e-3)),
    ("powerSGD", dict(dad_reduction_rank=4)),
])
def test_compressed_engines_with_model_axis(engine_name, kw):
    """Interaction coverage: compressed engines × sequence parallelism —
    the (2 site × 2 model) run must match the dense 2-site run under SGD
    (engine collectives ride the site axis while the model shards the
    window axis)."""
    data = _epoch_data(seed=17)
    x, y, w = data

    def run(model, mesh):
        task = FederatedTask(model)
        engine = make_engine(engine_name, **kw)
        opt = make_optimizer("sgd", 1e-2)
        state = init_train_state(
            task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=2
        )
        fn = make_train_epoch_fn(task, engine, opt, mesh, local_iterations=1)
        for _ in range(2):
            state, ls = fn(state, x, y, w)
        return jax.tree.map(np.asarray, state), np.asarray(ls)

    s_dense, l_dense = run(_ica_model(), host_mesh(2))
    s_ring, l_ring = run(_ica_model(MODEL_AXIS), host_mesh(2, model_axis_size=2))
    np.testing.assert_allclose(l_ring, l_dense, atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
        s_dense.params, s_ring.params,
    )
    # per-site engine state (e.g. powerSGD residuals) must agree too
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
        s_dense.engine_state, s_ring.engine_state,
    )


def test_folding_combined_with_model_axis():
    """4 sites folded 2-per-device × model_axis 2 — a 4-device (2 site ×
    2 model) mesh with in-device folding — == the plain 4-site vmap run."""
    data = _epoch_data(S=4, seed=19)
    x, y, w = data

    def run(model, mesh):
        task = FederatedTask(model)
        engine = make_engine("dSGD")
        opt = make_optimizer("sgd", 1e-2)
        state = init_train_state(
            task, engine, opt, jax.random.PRNGKey(0), x[0, 0], num_sites=4
        )
        fn = make_train_epoch_fn(task, engine, opt, mesh, local_iterations=1)
        for _ in range(2):
            state, ls = fn(state, x, y, w)
        return jax.tree.map(np.asarray, state), np.asarray(ls)

    s_plain, l_plain = run(_ica_model(), None)
    # mesh: 2 devices on site axis (4 sites folded 2-per-device) × 2 model
    s_combo, l_combo = run(
        _ica_model(MODEL_AXIS), host_mesh(2, model_axis_size=2)
    )
    np.testing.assert_allclose(l_combo, l_plain, atol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
        s_plain.params, s_combo.params,
    )
