"""Multi-host worker entry point — one process per host (or per TPU slice).

Graduated from the r8 test fixture (``tests/dcn_worker.py``) into the real
multi-slice launch path (r18): each invocation joins a ``jax.distributed``
runtime as ONE process of an N-process cluster and trains the shared
federated program over the resulting global mesh. With ``--slices N`` the
mesh is the three-tier ``(slice, site, model)`` topology
(parallel/distributed.py ``multihost_sliced_site_mesh`` via
``TrainConfig.num_slices``) — processes map to slices, so the ONLY
per-round DCN traffic is the inter-slice hop of the hierarchical
aggregation, carrying one (optionally ``--dcn-wire-quant``-quantized)
per-slice partial.

Typical per-slice launch (one process per TPU slice / host)::

    python -m dinunet_implementations_tpu.runner.dcn_worker \
        --coordinator host0:1234 --num-processes 4 --process-id $RANK \
        --slices 4 --data-path /data/tree --out-dir /shared/out

Supervised mode (r19 — runner/supervisor.py): ``--supervise`` makes this
invocation the SUPERVISOR of the fleet instead of a worker. It launches
one worker per ``--process-id`` slot, monitors process exits AND heartbeat
staleness (each slice's lead rank pulses
``<out>/heartbeats/slice_<i>.json`` from a timer thread — staleness
catches hard freezes and dead-mount write blocks; a fleet wedged in a
collective is recovered through the dead peer's exit + drain), records
every slice death in the shared liveness spool
(``<out>/slice_liveness/``), dumps its flight recorder with the slice id +
last heartbeat age, drains the survivors (SIGTERM → checkpoint + clean
exit; SIGKILL past the grace window), computes the CROSS-SLICE CHECKPOINT
CONSENSUS — the newest round where all surviving slices' rotating sidecar
checkpoints (``<out>/slices/slice_<i>/``, written every epoch with a
params-sha256 meta; torn files fall back to ``.prev`` per the PR 2
contract) agree by digest — installs that generation as the fleet resume
point, and relaunches everything with ``--resume``. A preempted slice
costs the run one checkpoint window, never the run itself. The
deterministic chaos arm: a ``--faults`` plan with ``kill_slice_at`` makes
the named slice's worker SIGKILL ITSELF when its round counter crosses
the kill (first launch generation only — restarted incarnations sail
through), so the whole death→consensus→rejoin cycle replays identically
in CI.

Every process computes identical replicated results; only process 0 writes
logs/checkpoints (trainer/loop.py ``_coordinator``). ``--report PATH``
writes a JSON record of the run — mesh shape, per-epoch losses, a params
checksum (bit-compared across processes by the multihost smoke test), the
epoch compile count, and the process-0-only write counters.

Exit codes (every failure path calls ``distributed_shutdown()`` first, so
the runtime is re-entrant and a wedged peer surfaces as a nonzero exit
rather than a hang):

- ``0`` — run completed.
- ``66`` (:data:`UNSUPPORTED_RC`) — capability probe: this jaxlib's CPU
  backend cannot execute multiprocess collectives at all; CI smokes SKIP
  on it instead of failing red. A supervisor propagates it verbatim.
- ``128 + signum`` — cooperative preemption: SIGTERM/SIGINT landed during
  the fit, the rotating checkpoint was saved at the epoch boundary, the
  flight recorder dumped, and the process exited with the shell's
  signal-death convention (e.g. 143 for SIGTERM). ``75`` is the
  deterministic FaultPlan ``kill_at_round`` arm of the same path
  (robustness/preemption.py ``Preempted.exit_code``).
- ``-9`` / ``137`` — the ``kill_slice_at`` chaos arm's self-SIGKILL (an
  abrupt, uncheckpointed death by design: the supervisor must recover it
  from the OTHER slices' checkpoints).
- ``69`` (:data:`~..runner.supervisor.SUPERVISOR_GAVE_UP_RC`) — supervisor
  only: a slice kept dying past ``--max-restarts``.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import signal
import sys

#: exit code for "this backend cannot run multiprocess collectives" — the
#: tier-1/CI smokes skip on it (tests/test_distributed.py)
UNSUPPORTED_RC = 66


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="dcn_worker",
        description="multi-host/multi-slice federated training worker",
    )
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="jax.distributed coordinator address (process 0 "
                        "hosts it); omit with --num-processes 1 for the "
                        "single-process reference run")
    p.add_argument("--num-processes", type=int, default=1)
    p.add_argument("--process-id", type=int, default=0)
    p.add_argument("--data-path", required=True,
                   help="dataset tree (reference simulator layout); every "
                        "process loads the same tree and feeds its own "
                        "addressable mesh slices")
    p.add_argument("--out-dir", default=None,
                   help="shared output dir (process 0 writes; heartbeats, "
                        "the liveness spool and per-slice checkpoint "
                        "sidecars live here too)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="write the run-report JSON here (supervised mode: "
                        "one _p<rank> report per worker)")
    p.add_argument("--slices", type=int, default=1,
                   help="num_slices for the three-tier (slice, site, model) "
                        "mesh; must divide --num-processes (1 = the legacy "
                        "hybrid (site, model) mesh)")
    p.add_argument("--dcn-wire-quant", default="",
                   choices=["", "none", "bf16", "int8", "fp8"],
                   help="inter-slice wire codec (TrainConfig.dcn_wire_quant; "
                        "'' follows --set wire_quant)")
    p.add_argument("--devices-per-process", type=int, default=4,
                   help="virtual CPU devices per process (emulation; "
                        "ignored on real accelerator backends)")
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--task", default="FS-Classification")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--faults", default=None, metavar="JSON|@FILE",
                   help="deterministic FaultPlan (robustness/faults.py) — "
                        "site AND slice-tier windows; kill_slice_at is "
                        "realized as a real self-SIGKILL of the named "
                        "slice's worker (first generation only)")
    p.add_argument("--resume", action="store_true",
                   help="continue from the last rotating checkpoint "
                        "(FedRunner resume; the supervisor always passes "
                        "this on relaunch)")
    p.add_argument("--supervise", action="store_true",
                   help="run as the fleet SUPERVISOR: launch one worker "
                        "per process slot, monitor heartbeats/exits, "
                        "restart dead slices via checkpoint-consensus "
                        "rejoin (module docstring)")
    p.add_argument("--heartbeat-s", type=float, default=2.0,
                   help="worker heartbeat interval (seconds)")
    p.add_argument("--heartbeat-timeout-s", type=float, default=30.0,
                   help="supervisor: heartbeat staleness past this is a "
                        "wedged worker (with_retry deadline semantics "
                        "before the verdict)")
    p.add_argument("--max-restarts", type=int, default=2,
                   help="supervisor: give up (rc 69) after this many "
                        "fleet restarts")
    p.add_argument("--slice-ckpt", action="store_true",
                   help="rotate a per-slice checkpoint sidecar every epoch "
                        "(consensus input; the supervisor passes this to "
                        "its workers)")
    p.add_argument("--restart-generation", type=int, default=1,
                   help=argparse.SUPPRESS)  # supervisor-internal
    p.add_argument("--statusz-port", type=int, default=None, metavar="PORT",
                   help="supervisor: serve the FEDERATED pod-level "
                        "/metrics + /statusz here (the PodCollector "
                        "scrapes every worker's heartbeat-advertised "
                        "statusz port and exact-merges the buses; r23). "
                        "Workers always auto-pick their own port and "
                        "advertise it via the heartbeat")
    p.add_argument("--slo-p99-ms", type=float, default=2000.0,
                   metavar="MS",
                   help="supervisor: p99 target for the pod /statusz SLO "
                        "burn over the fleet-merged epoch_ms histogram")
    p.add_argument("--pod-trace", default=None, metavar="ID",
                   help="pod-wide trace id stamped on every dcn-epoch "
                        "span (the supervisor mints one and passes it to "
                        "all workers, so telemetry.assemble can follow "
                        "one run across processes)")
    p.add_argument("--set", dest="overrides", action="append", default=[],
                   metavar="KEY=VALUE",
                   help="raw TrainConfig overrides (JSON-parsed values)")
    return p.parse_args(argv)


def _config_overrides(pairs):
    out = {}
    for kv in pairs:
        k, _, v = kv.partition("=")
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def _slice_of(process_id: int, num_processes: int, slices: int) -> int:
    """The mesh slice this process belongs to — processes are slice
    granules, contiguous (parallel/distributed.py
    multihost_sliced_site_mesh)."""
    if slices <= 1:
        return 0
    return process_id // max(num_processes // slices, 1)


def _params_checksum(state) -> str:
    """Order-stable digest of the replicated params — every process of a
    correct run reports the SAME hex (params are replicated by the
    aggregation collectives; the multihost smoke bit-compares this across
    processes after one round, and the cross-slice checkpoint consensus
    keys on it). ``addressable_data(0)`` reads the local replica, so no
    cross-process fetch is needed."""
    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state.params):
        a = leaf.addressable_data(0) if hasattr(leaf, "addressable_data") else leaf
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# supervisor entry
# ---------------------------------------------------------------------------


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _report_path(base: str | None, rank: int) -> str | None:
    if not base:
        return None
    root, ext = os.path.splitext(base)
    return f"{root}_p{rank}{ext or '.json'}"


def _supervise(args) -> int:
    """The ``--supervise`` entry: drive a :class:`~.supervisor
    .SliceSupervisor` over per-slice ``dcn_worker`` processes (module
    docstring). Runs withOUT initializing jax.distributed in this process —
    the supervisor is a pure host-side state machine."""
    import subprocess

    from ..telemetry.bus import global_bus
    from ..telemetry.flight import FlightRecorder
    from ..telemetry.tracer import new_trace_id
    from .supervisor import (
        SliceSupervisor,
        consensus_round,
        slice_ckpt_dir,
    )

    out_dir = args.out_dir or "."
    os.makedirs(out_dir, exist_ok=True)
    flight = FlightRecorder(out_dir, bus=global_bus())
    flight.install()  # crash dumps; SIGTERM chained (no guard owns it here)
    # one pod-wide trace id for the whole supervised run: every worker
    # (every generation — a restarted fleet continues the SAME story)
    # stamps it on its dcn-epoch spans, so telemetry.assemble can follow
    # the run across process boundaries
    pod_trace = args.pod_trace or new_trace_id()
    launch = {"generation": 0, "port": None}

    def spawn(rank: int, generation: int):
        if generation != launch["generation"]:
            launch["generation"] = generation
            launch["port"] = _free_port()
        worker_argv = [
            sys.executable, "-m",
            "dinunet_implementations_tpu.runner.dcn_worker",
            "--coordinator", f"127.0.0.1:{launch['port']}",
            "--num-processes", str(args.num_processes),
            "--process-id", str(rank),
            "--data-path", args.data_path,
            "--slices", str(args.slices),
            "--epochs", str(args.epochs),
            "--task", args.task,
            "--batch-size", str(args.batch_size),
            "--devices-per-process", str(args.devices_per_process),
            "--heartbeat-s", str(args.heartbeat_s),
            "--restart-generation", str(generation),
            "--pod-trace", pod_trace,
            "--slice-ckpt",
            "--out-dir", out_dir,
        ]
        if args.dcn_wire_quant:
            worker_argv += ["--dcn-wire-quant", args.dcn_wire_quant]
        if args.faults:
            worker_argv += ["--faults", args.faults]
        if args.resume or generation > 1:
            worker_argv += ["--resume"]
        rep = _report_path(args.report, rank)
        if rep:
            worker_argv += ["--report", rep]
        for kv in args.overrides:
            worker_argv += ["--set", kv]
        # the workers own their backend config (devices-per-process etc.);
        # an inherited XLA device-count flag would double-apply
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
        with open(os.path.join(
            out_dir, f"worker_p{rank}_gen{generation}.log"), "w",
        ) as log:
            # the child dups the fd at spawn; closing ours leaks nothing
            return subprocess.Popen(
                worker_argv, stdout=log, stderr=subprocess.STDOUT, env=env,
            )

    def slice_of(rank: int) -> int:
        return _slice_of(rank, args.num_processes, args.slices)

    def install_consensus(generation: int, dead_slice: int) -> None:
        """Pick the newest round all SURVIVING slices' sidecars agree on
        and install it as the fleet resume point, unless the shared fold
        checkpoint already sits at that epoch (keeping its richer fit
        meta — loss history, early-stop bookkeeping — when it does). The
        decision is PERSISTED under <out>/consensus/ (r23): a flight note
        alone may never reach disk if the supervisor dies before its next
        dump, and the postmortem timeline must name the round chosen."""
        import time as _time

        from ..telemetry.postmortem import CONSENSUS_DIR
        from ..trainer.checkpoint import CorruptCheckpointError, load_meta
        from ..trainer.logs import fold_dir
        from .supervisor import _atomic_json

        decision_path = os.path.join(
            out_dir, CONSENSUS_DIR, f"decision_gen{generation}.json"
        )
        os.makedirs(os.path.dirname(decision_path), exist_ok=True)
        dirs = {
            sl: slice_ckpt_dir(out_dir, sl)
            for sl in range(max(args.slices, 1)) if sl != dead_slice
        }
        agreed = consensus_round(dirs or {
            sl: slice_ckpt_dir(out_dir, sl)
            for sl in range(max(args.slices, 1))
        })
        if agreed is None:
            flight.note("consensus-none", generation=generation)
            _atomic_json(decision_path, {
                "time_unix": _time.time(), "generation": generation,
                "dead_slice": dead_slice, "round": None,
            })
            return  # fleet resumes from the shared fold checkpoint as-is
        rnd, sha, path = agreed
        epoch = load_meta(path).get("epoch")
        resume = os.path.join(
            fold_dir(out_dir, "remote", args.task, 0),
            "checkpoint_latest.msgpack",
        )
        try:
            fold_epoch = load_meta(resume).get("epoch")
        except (OSError, CorruptCheckpointError):
            fold_epoch = None
        if fold_epoch != epoch:
            # torn, missing, or AHEAD of the agreement (the coordinator
            # checkpointed an epoch a now-dead slice never sealed): roll
            # the fleet to the agreed generation
            import shutil

            os.makedirs(os.path.dirname(resume), exist_ok=True)
            shutil.copyfile(path, resume)
        flight.note("consensus-install", round=rnd, epoch=epoch,
                    sha=sha[:12], replaced=fold_epoch != epoch)
        _atomic_json(decision_path, {
            "time_unix": _time.time(), "generation": generation,
            "dead_slice": dead_slice, "round": rnd, "epoch": epoch,
            "sha": sha, "replaced": fold_epoch != epoch,
        })

    sup = SliceSupervisor(
        spawn,
        num_processes=args.num_processes,
        out_dir=out_dir,
        slice_of_process=slice_of,
        heartbeat_timeout_s=args.heartbeat_timeout_s,
        max_restarts=args.max_restarts,
        flight=flight,
        bus=global_bus(),
        on_consensus=install_consensus,
        passthrough_rcs=(UNSUPPORTED_RC,),
    )
    exporter = None
    if args.statusz_port is not None:
        # the pod observability plane (r23): one /statusz + /metrics for
        # the whole fleet — the PodCollector discovers every worker from
        # its heartbeat-advertised port and exact-merges the buses, and
        # the UNCHANGED StatusExporter serves the merged view (the
        # collector duck-types the bus read API)
        from ..telemetry.collector import PodCollector
        from ..telemetry.exporter import StatusExporter

        collector = PodCollector(
            out_dir, local_bus=global_bus(),
            local_labels={"process": "supervisor"},
            status_extra=lambda: {
                "mode": "supervisor",
                "generation": sup.generation,
                "restarts": sup.restarts,
                "pod_trace": pod_trace,
            },
        )
        exporter = StatusExporter(
            collector, port=args.statusz_port, flight=flight,
            statusz=collector.status,
            slo={"histogram": "epoch_ms",
                 "p99_target_ms": args.slo_p99_ms},
        )
        port = exporter.start()
        print(f"[supervise] pod statusz http://127.0.0.1:{port}/statusz "
              f"(federated /metrics, SLO over merged epoch_ms)",
              flush=True)
    rc = sup.run()
    flight.note("supervisor-exit", rc=rc, restarts=sup.restarts)
    if exporter is not None:
        exporter.stop()
    # the supervisor's ring (launches, deaths, consensus, restarts) must
    # reach disk even on a CLEAN exit — it is postmortem evidence, and the
    # per-death dumps only cover the unhappy path
    flight.dump(f"supervisor-exit:rc={rc}")
    try:
        # best-effort pod trace assembly: workers wrote per-process
        # trace_p<rank>_gen<g>.jsonl files; merge them into one Perfetto
        # timeline now so the artifact exists without a second command
        from ..telemetry.assemble import (
            POD_TRACE_DIR,
            POD_TRACE_FILE,
            assemble,
        )

        if os.path.isdir(os.path.join(out_dir, POD_TRACE_DIR)):
            assemble(out_dir, os.path.join(
                out_dir, POD_TRACE_DIR, POD_TRACE_FILE
            ))
    except (OSError, ValueError, TypeError, KeyError) as e:
        # unreadable/torn trace files or a full disk — the assembly is a
        # convenience artifact and must not mask the run's rc
        flight.note("pod-trace-assembly-failed", error=repr(e))
    return rc


# ---------------------------------------------------------------------------
# worker entry
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    args = _parse(argv if argv is not None else sys.argv[1:])
    if args.supervise:
        return _supervise(args)

    # Belt and braces across jax versions: the XLA_FLAGS env var is consumed
    # at backend-client creation (lazy — still effective even when
    # sitecustomize imported jax at interpreter start, as long as no device
    # was queried), and newer jax prefers the jax_num_cpu_devices knob.
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + f" --xla_force_host_platform_device_count="
            f"{args.devices_per_process}"
        ).strip()

    import jax

    if not os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", args.devices_per_process)
        except AttributeError:
            pass  # older jax: the XLA_FLAGS device-count flag applies

    from dinunet_implementations_tpu.parallel import (
        distributed_init,
        distributed_shutdown,
    )
    from dinunet_implementations_tpu.robustness.faults import (
        parse_fault_plan,
    )
    from dinunet_implementations_tpu.robustness.preemption import Preempted
    from dinunet_implementations_tpu.runner.supervisor import (
        Heartbeat,
        heartbeat_path,
        slice_ckpt_dir,
    )
    from dinunet_implementations_tpu.telemetry.flight import FlightRecorder

    try:
        fault_plan = parse_fault_plan(args.faults)
    except (ValueError, OSError) as e:
        print(f"--faults: {e}", file=sys.stderr)
        return 2

    slice_id = _slice_of(args.process_id, args.num_processes, args.slices)
    # one sidecar/heartbeat writer per slice: with several processes per
    # slice (num_processes > slices), slice-mates rotating the same files
    # would race checkpoint.py's exists-then-replace (and shadow each
    # other's pulses); params are replicated, so the slice's FIRST rank
    # writing is lossless
    procs_per_slice = max(args.num_processes // max(args.slices, 1), 1)
    slice_lead = args.process_id % procs_per_slice == 0
    heartbeat = None
    flight = None
    if args.out_dir:
        flight = FlightRecorder(args.out_dir)
        # crash dumps + SIGTERM-outside-the-fit dumps; DURING the fit the
        # PreemptionGuard owns SIGTERM and the Preempted handler below
        # dumps cooperatively (telemetry/flight.py contract)
        flight.install()
        if slice_lead:
            heartbeat = Heartbeat(
                heartbeat_path(args.out_dir, slice_id), slice_id,
                interval_s=args.heartbeat_s,
            ).start()

    multi = distributed_init(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    ) if args.num_processes > 1 else distributed_init()

    import dinunet_implementations_tpu.trainer.loop as loop_mod
    from dinunet_implementations_tpu import TrainConfig
    from dinunet_implementations_tpu.parallel.distributed import (
        spans_processes,
    )
    from dinunet_implementations_tpu.runner import FedRunner

    writes = {"logs": 0, "ckpt": 0}
    _orig_logs = loop_mod.write_logs_json
    _orig_ckpt = loop_mod.save_checkpoint
    _save_checkpoint = loop_mod.save_checkpoint

    def _count_logs(*a, **k):
        writes["logs"] += 1
        return _orig_logs(*a, **k)

    def _count_ckpt(*a, **k):
        writes["ckpt"] += 1
        return _orig_ckpt(*a, **k)

    loop_mod.write_logs_json = _count_logs
    loop_mod.save_checkpoint = _count_ckpt

    # keep the final epoch state visible for the params checksum (the fit
    # result dict carries metrics, not weights) — and the trainer for the
    # CompileGuard-style epoch compile count. In supervised/--slice-ckpt
    # mode the same hook also (a) pulses the heartbeat with round progress,
    # (b) rotates this slice's consensus sidecar, and (c) fires the
    # kill_slice_at self-SIGKILL chaos arm (first generation only).
    final = {"state": None, "trainer": None, "epoch": 0, "round": 0}

    # the pod observability plane (r23): every slice lead serves its OWN
    # /statusz (auto-picked port) and advertises it in the heartbeat, so
    # the supervisor's PodCollector can discover + scrape + merge the
    # fleet's buses with zero configuration. started_unix rides the
    # statusz payload too — the collector cross-checks it against the
    # heartbeat to reject recycled pids.
    exporter = None
    if heartbeat is not None:
        from dinunet_implementations_tpu.telemetry.bus import global_bus
        from dinunet_implementations_tpu.telemetry.exporter import (
            StatusExporter,
        )

        exporter = StatusExporter(
            global_bus(), flight=flight,
            statusz=lambda: {
                "mode": "dcn_worker",
                "process_id": args.process_id,
                "slice": slice_id,
                "generation": args.restart_generation,
                "started_unix": heartbeat.started_unix,
                "epoch": final["epoch"],
                "round": final["round"],
            },
        )
        heartbeat.beat(
            statusz_port=exporter.start(), process=args.process_id,
        )

    def _write_pod_trace() -> None:
        """Flush this process's spans to <out>/pod_trace/ so the
        cross-process assembler (telemetry/assemble.py) can merge them —
        the per-fit sink is coordinator-only, and the pod view needs
        EVERY process's timeline."""
        tr = final["trainer"]
        if (args.out_dir and args.pod_trace and tr is not None
                and tr.tracer.enabled):
            from dinunet_implementations_tpu.telemetry.assemble import (
                POD_TRACE_DIR,
            )

            tr.tracer.write_jsonl(os.path.join(
                args.out_dir, POD_TRACE_DIR,
                f"trace_p{args.process_id}"
                f"_gen{args.restart_generation}.jsonl",
            ))

    _orig_run_epoch = loop_mod.FederatedTrainer.run_epoch
    kill_round = (
        fault_plan.kill_round_for_slice(slice_id)
        if fault_plan is not None and args.restart_generation <= 1 else None
    )
    my_ckpt_dir = (
        slice_ckpt_dir(args.out_dir, slice_id)
        if args.out_dir and args.slice_ckpt and slice_lead else None
    )

    def _record_run_epoch(self, state, *a, **k):
        # first call reads the INPUT state's round (a resumed fit starts
        # past 0; the kill arm must key on genuinely-crossed rounds)
        round_before = (
            final["round"] if final["epoch"] else int(state.round)
        )
        if args.pod_trace:
            # the pod-wide trace id on every epoch span: the assembled
            # Perfetto timeline follows it across process boundaries
            with self.tracer.span(
                "dcn-epoch", trace=args.pod_trace, slice=slice_id,
                process=args.process_id,
                generation=args.restart_generation,
            ):
                out = _orig_run_epoch(self, state, *a, **k)
        else:
            out = _orig_run_epoch(self, state, *a, **k)
        final["state"], final["trainer"] = out[0], self
        # the GLOBAL fit epoch (run_epoch's third positional arg) — a
        # restarted generation resumes at epoch k+1, and the sidecar meta
        # must say so or consensus would compare local counts against the
        # fold checkpoint's global epochs and roll the fleet back wrong
        fit_epoch = a[1] if len(a) > 1 else k.get("epoch", 0)
        final["epoch"] = int(fit_epoch)
        final["round"] = int(out[0].round)
        if heartbeat is not None:
            heartbeat.beat(epoch=final["epoch"], round=final["round"])
        if kill_round is not None and round_before <= kill_round < final["round"]:
            # the chaos arm: die like a preempted slice ACTUALLY dies —
            # abruptly, BEFORE this epoch's sidecar seals, so the
            # supervisor must recover from the other slices' checkpoints
            if flight is not None:
                flight.note("kill-slice", slice=slice_id,
                            round=final["round"])
                flight.dump(f"kill-slice:{slice_id}@round{kill_round}")
            os.kill(os.getpid(), signal.SIGKILL)
        if my_ckpt_dir is not None:
            _save_checkpoint(
                os.path.join(my_ckpt_dir, "checkpoint_latest.msgpack"),
                out[0],
                meta={
                    "round": final["round"], "epoch": final["epoch"],
                    "slice": slice_id,
                    "params_sha256": _params_checksum(out[0]),
                },
                rotate=True,
            )
        return out

    loop_mod.FederatedTrainer.run_epoch = _record_run_epoch

    cfg = TrainConfig(
        task_id=args.task, epochs=args.epochs, validation_epochs=2,
        patience=10, batch_size=args.batch_size,
        split_ratio=(0.7, 0.15, 0.15), seed=0,
        num_slices=args.slices, dcn_wire_quant=args.dcn_wire_quant,
    ).with_overrides(_config_overrides(args.overrides))
    runner = FedRunner(
        cfg, data_path=args.data_path, out_dir=args.out_dir,
        fault_plan=fault_plan,
    )
    try:
        res = runner.run(verbose=False, resume=args.resume)[0]
    except Preempted as p:
        # cooperative preemption (SIGTERM during the fit / kill_at_round):
        # the rotating checkpoint landed at the epoch boundary before this
        # raise — dump the flight ring, tear the runtime down, exit with
        # the documented 128+signum (75 for the deterministic arm)
        if flight is not None:
            flight.note("preempted", signum=p.signum, epoch=p.epoch,
                        slice=slice_id)
            flight.dump(
                f"signal:{p.signum}" if p.signum else "kill_at_round"
            )
        _write_pod_trace()  # a drained survivor's spans are pod evidence
        if heartbeat is not None:
            heartbeat.stop()
        if exporter is not None:
            exporter.stop()
        distributed_shutdown()
        return p.exit_code
    except Exception as e:  # noqa: BLE001 — capability probe, see below
        if heartbeat is not None:
            heartbeat.stop()
        if "Multiprocess computations aren't implemented" in str(e):
            # this jaxlib's CPU backend cannot execute cross-process
            # collectives at all (e.g. 0.4.x): report "unsupported",
            # distinct from a real failure, so callers can skip
            print(f"UNSUPPORTED: {e}", flush=True)
            distributed_shutdown()
            return UNSUPPORTED_RC
        # any other failure still tears the runtime down first: a raise
        # with the distributed client live would leave peers wedged in
        # their next collective with nothing to surface it
        distributed_shutdown()
        raise

    if args.report:
        from dinunet_implementations_tpu.checks.sanitize import jit_cache_size

        trainer = final["trainer"]
        report = {
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "global_devices": len(jax.devices()),
            "local_devices": len(jax.local_devices()),
            "multi": bool(multi),
            "mesh_spans_processes": spans_processes(runner.mesh),
            "mesh_shape": dict(runner.mesh.shape),
            "mesh_axes": list(runner.mesh.axis_names),
            "num_slices": args.slices,
            "slice_id": slice_id,
            "restart_generation": args.restart_generation,
            "epoch_losses": [float(x) for x in res["epoch_losses"]],
            "test_metrics": res["test_metrics"],
            "n_log_writes": writes["logs"],
            "n_ckpt_writes": writes["ckpt"],
            # bit-compared across processes by the multihost smoke: the
            # replicated params after the final round
            "params_sha256": (
                _params_checksum(final["state"])
                if final["state"] is not None else None
            ),
            # the one-epoch-compile-per-process contract (CompileGuard's
            # counter): churnless multi-host training must compile the
            # epoch exactly once in EVERY process
            "epoch_compiles": (
                jit_cache_size(trainer.epoch_fn)
                if trainer is not None else None
            ),
        }
        with open(args.report, "w") as fh:
            json.dump(report, fh)

    _write_pod_trace()
    if heartbeat is not None:
        heartbeat.stop()
    if exporter is not None:
        exporter.stop()
    # clean teardown: leave the runtime re-entrant (the coordinated barrier
    # in shutdown also surfaces a wedged peer as a nonzero exit, instead of
    # letting a caller's timeout mask it)
    distributed_shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
