"""FreeSurfer aseg-volume dataset.

Reference semantics (``comps/fs/__init__.py:11-39``, ``comps/fs/__init__.py:66-71``):

- the site inventory is the index column of the covariate CSV
  (``labels_file``; indexed by ``data_column`` when present);
- labels come from ``labels_column``; string labels coerce via
  ``int(y.strip().lower() == 'true')``; ints/bools cast to int (the reference
  comments that raw int64 wasn't JSON-serializable — irrelevant here but the
  coercion is kept);
- each sample file is a tab-separated table ``name\\tvalue`` with one header
  row (skipped); the feature vector is **normalized by its own max**
  (``df / df.max()`` on a single-column frame = divide the subject's 66
  volumes by that subject's largest volume).

TPU-first difference: ``as_arrays`` reads every file once into a dense
``[n, input_size]`` float32 matrix instead of re-reading TSVs per item per
epoch (reference hot-path pathology, SURVEY.md §3.5).
"""

from __future__ import annotations

import csv
import os

import numpy as np

from .api import DataHandle, SiteArrays, SiteDataset


def _read_covariates(path: str, data_column: str | None):
    """Read the covariate CSV into (index list, {index → row dict})."""
    with open(path, newline="") as fh:
        rows = list(csv.DictReader(fh))
    if not rows:
        return [], {}
    cols = rows[0].keys()
    key = data_column if data_column in cols else next(iter(cols))
    index = [r[key] for r in rows]
    return index, {r[key]: r for r in rows}


def coerce_label(y, bug_compatible: bool = False) -> int:
    """Reference label coercion (``comps/fs/__init__.py:25-31``).

    DOCUMENTED DEVIATION: the reference maps *every* string through
    ``int(y.strip().lower() == 'true')`` — so the string ``"1"`` becomes 0
    there. Here numeric strings parse numerically (``"1"`` → 1), which is
    strictly safer for CSVs exported with 0/1 labels; only the literal
    true/false strings use the boolean rule. Pass ``bug_compatible=True``
    (FSArgs.bug_compatible_labels) to reproduce the reference bit-for-bit.
    """
    if isinstance(y, str):
        low = y.strip().lower()
        if bug_compatible:
            return int(low == "true")
        if low in ("true", "false"):
            return int(low == "true")
        return int(float(y))
    return int(y)


def read_aseg_stats(path: str) -> np.ndarray:
    """Read one aseg-stats TSV → max-normalized float32 feature vector."""
    vals = []
    with open(path) as fh:
        next(fh)  # header row (reference: skiprows=1)
        for line in fh:
            line = line.strip()
            if not line:
                continue
            vals.append(float(line.split("\t")[1]))
    x = np.asarray(vals, np.float64)
    x = x / x.max()
    return x.astype(np.float32)


class FreeSurferDataset(SiteDataset):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.labels = None  # {file → row dict}, lazy like the reference

    def _ensure_labels(self):
        if self.labels is None:
            path = os.path.join(
                self.state["baseDirectory"], self.cache["labels_file"]
            )
            _, self.labels = _read_covariates(path, self.cache.get("data_column"))

    def load_index(self, file):
        self._ensure_labels()
        y = self.labels[file][self.cache["labels_column"]]
        self.indices.append(
            [file, coerce_label(y, self.cache.get("bug_compatible_labels", False))]
        )

    def __getitem__(self, ix) -> dict:
        file, y = self.indices[ix]
        x = read_aseg_stats(os.path.join(self.path(), file))
        return {"inputs": x, "labels": y, "ix": ix}

    def as_arrays(self) -> SiteArrays:
        n = len(self.indices)
        files = [os.path.join(self.path(), f) for f, _ in self.indices]
        mat = None
        if n:
            # native threaded batch parse (native/fastio.cpp) — the first
            # file is read in Python both to learn the feature count and to
            # keep one exercised fallback-path sample per load
            first = read_aseg_stats(files[0])
            from .native_io import read_aseg_batch

            mat = read_aseg_batch(files, len(first))
            if mat is None:  # no compiler / malformed file → pure Python
                mat = np.stack([first] + [read_aseg_stats(f) for f in files[1:]])
        return SiteArrays(
            mat if n else np.zeros((0, 0), np.float32),
            np.asarray([y for _, y in self.indices], np.int32),
            np.arange(n, dtype=np.int32),
        )


class FSVDataHandle(DataHandle):
    """Site inventory = covariate CSV index column
    (reference ``comps/fs/__init__.py:66-71``)."""

    def list_files(self) -> list:
        path = os.path.join(self.state["baseDirectory"], self.cache["labels_file"])
        index, _ = _read_covariates(path, self.cache.get("data_column"))
        return index
