"""Device mesh construction — the communication backend.

This replaces the reference's COINSTAC transport layer (L0): Docker containers
exchanging JSON payloads through a message bus (reference ``entry.py:5``,
``local.py:19``, ``remote.py:13``). In the TPU build, every federated site lives
on a slice of a ``jax.sharding.Mesh`` with a ``"site"`` axis; the local→remote
gradient ship + remote→local broadcast collapses into XLA collectives over ICI
(multi-host: DCN). See SURVEY.md §2.2.

Axes:
  - ``site``  — one federated site per mesh index (or per core-group).
  - ``model`` — optional inner axis for tensor/sequence sharding within a site
                (a TPU-build extension; the reference is single-device per site).

Site packing (r12): the mesh's ``site`` axis is the PHYSICAL half of a
virtual site axis. ``S`` virtual sites pack ``K = sites_per_device`` per mesh
member (:func:`packed_site_mesh`): every ``[S, …]`` per-site array shards
``P(site)`` into contiguous ``[K, …]`` device blocks, so virtual site
``d·K + j`` lives at row ``j`` on mesh member ``d`` (device-major global
order — the same order ``axis_index((site, fold))`` linearizes to inside the
epoch). Aggregation is then two-level (parallel/collectives.py PackedAxis):
a local in-register reduce over the packed rows followed by one cross-device
collective over ``site`` — which is how an 8-device mesh runs 512+ sites in
one compiled SPMD program without site count ever touching device count.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SITE_AXIS = "site"
MODEL_AXIS = "model"
# vmap axis name for sites folded onto one device (several simulated sites per
# chip, e.g. 32 sites on 8 chips): the trainer nests a vmap over the local
# site block inside shard_map, and cross-site collectives run over the
# (SITE_AXIS, FOLD_AXIS) pair. Never a mesh axis.
FOLD_AXIS = "site_fold"


def make_site_mesh(
    num_sites: int | None = None,
    devices: list | None = None,
    model_axis_size: int = 1,
) -> Mesh:
    """Build a ``(site, model)`` mesh.

    ``num_sites`` defaults to ``len(devices) // model_axis_size``. When fewer
    devices than sites are available, callers should fold multiple sites onto
    one device via a batched site dimension instead (see trainer); this function
    requires num_sites * model_axis_size == number of devices used.
    """
    devices = list(devices if devices is not None else jax.devices())
    if num_sites is None:
        num_sites = len(devices) // model_axis_size
    need = num_sites * model_axis_size
    if need > len(devices):
        raise ValueError(
            f"need {need} devices for {num_sites} sites × model={model_axis_size}, "
            f"have {len(devices)}"
        )
    arr = np.array(devices[:need]).reshape(num_sites, model_axis_size)
    return Mesh(arr, (SITE_AXIS, MODEL_AXIS))


def packed_site_mesh(
    num_sites: int,
    sites_per_device: int = 1,
    devices: list | None = None,
    model_axis_size: int = 1,
) -> Mesh:
    """A ``(site, model)`` mesh for ``num_sites`` VIRTUAL sites packed
    ``sites_per_device`` per mesh member.

    The mesh's site axis has ``num_sites // sites_per_device`` entries; the
    trainer's ``P(site)`` sharding then hands each device a contiguous
    ``[sites_per_device, …]`` block of every per-site array (the packed
    layout above). ``sites_per_device=1`` is exactly :func:`make_site_mesh`.
    Raises when the pack factor doesn't divide the site count or the mesh
    doesn't fit the device set.
    """
    if sites_per_device < 1:
        raise ValueError(f"sites_per_device must be >= 1, got {sites_per_device}")
    if num_sites % sites_per_device:
        raise ValueError(
            f"sites_per_device={sites_per_device} must divide the virtual "
            f"site count ({num_sites})"
        )
    return make_site_mesh(
        num_sites // sites_per_device, devices, model_axis_size
    )


def pack_factor(mesh: Mesh | None, num_sites: int) -> int:
    """The site-packing factor K a ``[num_sites, …]`` per-site array gets on
    ``mesh``: virtual sites per device along the mesh's site axis.
    ``mesh=None`` (the vmap-folded single-device topology) packs everything
    onto one device — K = num_sites."""
    if mesh is None:
        return num_sites
    mesh_sites = dict(mesh.shape)[SITE_AXIS]
    if num_sites % mesh_sites:
        raise ValueError(
            f"{num_sites} virtual sites do not divide over the mesh's "
            f"{mesh_sites} site-axis members"
        )
    return num_sites // mesh_sites


def site_sharding(mesh: Mesh, *trailing_axes) -> NamedSharding:
    """Sharding with the leading dim split over ``site`` (per-site data)."""
    return NamedSharding(mesh, P(SITE_AXIS, *trailing_axes))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (global params — all sites hold the same
    weights between rounds, as in the reference where the remote broadcasts the
    aggregated update back to every site)."""
    return NamedSharding(mesh, P())


def host_mesh(num_sites: int, model_axis_size: int = 1) -> Mesh:
    """Mesh over CPU host devices, for the simulator path (tests / local dev).

    Requires ``XLA_FLAGS=--xla_force_host_platform_device_count=N``; this is the
    TPU-build replacement for the reference's Docker-based COINSTAC simulator
    (SURVEY.md §4.1).
    """
    cpus = [d for d in jax.devices() if d.platform == "cpu"]
    if not cpus:
        raise RuntimeError(
            "host_mesh needs CPU host devices; set "
            'jax.config.update("jax_platforms", "cpu") and '
            'jax.config.update("jax_num_cpu_devices", N) before first jax use '
            "(see tests/conftest.py)"
        )
    return make_site_mesh(num_sites, cpus, model_axis_size)
