"""Serving path (r15, fleet r21): AOT-compiled, continuously-batched
inference — now a replicated fleet with train-to-serve CD.

The first surface that ANSWERS a request (ROADMAP item 5): an
:class:`~.engine.InferenceEngine` loads a trained checkpoint (params +
batch_stats only), AOT-compiles one executable per (lane, shape bucket) at
startup against the persistent XLA compile cache, and serves through a
continuous microbatcher with max-batch/max-delay admission — plus an O(1)
per-session streaming lane for causal recurrent heads (device-resident
session-slot carry table, models/icalstm.py ICALstmStream).

r21 stacks three production planes on that engine:

- :class:`~.fleet.ReplicaSet` — N engine replicas across devices with
  session-SHARDED affinity routing, membership generations, and a
  supervisor that restarts crashed replicas (re-homed sessions re-enter
  through the fresh gate, bit-exact);
- :mod:`~.publish` — the FedDaemon checkpoint rotation as a publish
  stream: shadow-lane scoring, zero-recompile donated hot-swaps, and
  SLO-error-budget auto-rollback;
- :mod:`~.admission` — deadline/priority/load-shedding admission on the
  microbatcher with a p99-targeted max-delay autotuner.

    python -m dinunet_implementations_tpu.serving \
        --data-path datasets/demo --checkpoint out/.../checkpoint_best.msgpack \
        --replicas 2 --smoke 100 --out-dir out

See docs/ARCHITECTURE.md "Serving (r15)" and "Serving fleet (r21)".
"""

from .admission import AutotunerDaemon, DelayAutotuner
from .engine import InferenceEngine, ServingError
from .fleet import ReplicaSet, home_slot
from .microbatch import Microbatcher, RequestError, RequestFuture
from .publish import CheckpointWatcher, PublishController, PublishDaemon
from .session import SessionError, SessionTable, init_carry_table

__all__ = [
    "AutotunerDaemon",
    "CheckpointWatcher",
    "DelayAutotuner",
    "InferenceEngine",
    "Microbatcher",
    "PublishController",
    "PublishDaemon",
    "ReplicaSet",
    "RequestError",
    "RequestFuture",
    "ServingError",
    "SessionError",
    "SessionTable",
    "home_slot",
    "init_carry_table",
]
