"""Shared low-rank machinery for the compressed engines (rankDAD / powerSGD).

The reference exposes three knobs (``compspec.json:236-238,268-270``):
``dad_reduction_rank`` (default 10), ``dad_num_pow_iters`` (default 5), and
``dad_tol`` (default 1e-3). Tolerance-based early exit inside jit is a
``lax.while_loop`` whose carry tracks the singular-value estimates — shapes
stay static, only the trip count is dynamic (bounded by ``num_iters``).

Matrix convention: a gradient leaf with ndim ≥ 2 is reshaped to
``[prod(leading), last]`` (Dense kernels are already [in, out]; conv kernels
[h, w, cin, cout] → [h*w*cin, cout]); ndim ≤ 1 leaves are "dense" and bypass
compression.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def is_compressible(g, min_rank_dim: int = 2) -> bool:
    return g.ndim >= 2 and min(_matrix_shape(g)) >= min_rank_dim


def _matrix_shape(g):
    m = 1
    for d in g.shape[:-1]:
        m *= d
    return m, g.shape[-1]


def to_matrix(g):
    return g.reshape(_matrix_shape(g))


def from_matrix(mat, like):
    return mat.reshape(like.shape).astype(like.dtype)


def subspace_iteration(G, rank: int, num_iters: int, tol: float, key=None):
    """Rank-r factorization ``G ≈ P @ Q^T`` by subspace (block power) iteration.

    P is [m, r] orthonormal, Q = G^T P is [n, r]. Early-exits when the relative
    change of the singular-value estimates drops below ``tol`` (the
    ``dad_tol`` semantics), else runs ``num_iters`` (``dad_num_pow_iters``).
    """
    G = G.astype(jnp.float32)
    m, n = G.shape
    r = min(rank, m, n)
    if key is None:
        key = jax.random.PRNGKey(m * 1000003 + n)
    omega = jax.random.normal(key, (n, r), jnp.float32)
    Y = G @ omega  # [m, r]
    P0, _ = jnp.linalg.qr(Y)
    sig0 = jnp.linalg.norm(G.T @ P0, axis=0)  # [r] singular-value estimates

    def cond(carry):
        i, _, _, delta = carry
        return jnp.logical_and(i < num_iters, delta > tol)

    def body(carry):
        i, P, sig, _ = carry
        Y = G @ (G.T @ P)
        P_new, _ = jnp.linalg.qr(Y)
        sig_new = jnp.linalg.norm(G.T @ P_new, axis=0)
        delta = jnp.linalg.norm(sig_new - sig) / jnp.maximum(jnp.linalg.norm(sig), 1e-12)
        return i + 1, P_new, sig_new, delta

    # Tie the initial delta to G so its device-varying annotation matches the
    # loop body's output under shard_map (per-site G ⇒ per-site delta).
    delta0 = jnp.float32(jnp.inf) + 0.0 * jnp.sum(sig0)
    _, P, _, _ = jax.lax.while_loop(cond, body, (jnp.zeros((), jnp.int32), P0, sig0, delta0))
    Q = G.T @ P  # [n, r]
    return P, Q


def orthonormalize(P):
    """QR-based orthonormalization (columns)."""
    Q, _ = jnp.linalg.qr(P)
    return Q
