"""Per-op device-time profile + per-engine cost attribution of the flagship
bench epoch.

Two modes:

1. **Trace** (default): captures a ``jax.profiler`` trace of the 32-site
   ICA-LSTM federated epoch (the bench.py configuration) and prints the top
   device ops by total duration — the tool that found the conv-emitter dW_hh
   lowering, the whole-input relayout copy, and the lane-misaligned BiLSTM
   concat in round 3. ``--engine rankDAD|powerSGD|dSGD`` traces that engine's
   epoch (default dSGD).

2. **Attribution** (``--attribution``): per-engine cost attribution of the
   rankDAD round — compression (power iteration) vs gather vs reconstruction
   — via DIFFERENTIAL epochs rather than trace-name classification (XLA
   fusions don't carry phase names; epoch differentials survive any backend,
   including the lazy axon tunnel):

   - ``dsgd``                 = model grads + optimizer only (the floor);
   - ``exchange-only``        = a stub engine whose factors are canonical
     basis columns (zero power iterations) — pays the packed factor
     all-gather + einsum reconstruction + one GᵀP matmul;
   - ``rankdad-cold-1iter`` / ``-5iter`` (``dad_tol=0`` forces full trips)
     — the slope gives the per-power-iteration cost;
   - ``rankdad-warm-default`` — warm-started Ω with the stock tol, i.e.
     what the engine actually costs after round one.

   Phase costs are differences of interleaved-A/B marginals
   (``bench.interleaved_ab``), printed as JSON lines next to the ANALYTIC
   FLOP/byte count of each phase (exact, from the model's leaf shapes) — the
   "is the residual overhead irreducible compression FLOPs?" receipt.

Usage: python scripts/profile_epoch.py [--aot] [--epochs N] [--engine E]
       python scripts/profile_epoch.py --attribution [--small] [--obs N]
                                       [--epochs N]
  --aot    also apply compile_epoch_aot (the bench's resident-input layout)
  --small  harness-validation dims (CPU-friendly); records dims + backend
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

import bench
from dinunet_implementations_tpu.engines import make_engine
from dinunet_implementations_tpu.telemetry.xprof import (
    capture,
    summarize_device_ops,
    trace_files,
)
from dinunet_implementations_tpu.engines.base import Engine, register_engine
from dinunet_implementations_tpu.engines.lowrank import (
    from_matrix,
    is_compressible,
    to_matrix,
)
from dinunet_implementations_tpu.models import ICALstm
from dinunet_implementations_tpu.trainer import (
    FederatedTask,
    compile_epoch_aot,
    init_train_state,
    make_optimizer,
    make_train_epoch_fn,
)

TRACE_DIR = "/tmp/dinunet_epoch_trace"

ENGINE_KW = {
    "dSGD": {},
    "rankDAD": dict(dad_reduction_rank=10, dad_num_pow_iters=5, dad_tol=1e-3),
    "powerSGD": dict(dad_reduction_rank=10),
}


@register_engine("rankDAD-exchange-only")
def make_rankdad_exchange_only(
    dad_reduction_rank: int = 10, precision_bits="32", **_unused
) -> Engine:
    """rankDAD with the power iteration stubbed out: P = the first r columns
    of the identity, Q = GᵀP. Pays the packed factor gather, the einsum
    reconstruction, and ONE GᵀP matmul (the real engine's final-Q product) —
    so ``T(rankDAD) − T(this)`` isolates the power-iteration (compression)
    cost, and ``T(this) − T(dSGD)`` bounds gather+reconstruction. Attribution
    arm only; its "aggregate" is numerically meaningless. The grouping /
    dense-psum / packed-gather / einsum body deliberately MIRRORS
    engines/rankdad.py's exchange — keep the two in sync or the differential
    stops isolating the power iteration."""
    from dinunet_implementations_tpu.parallel.collectives import (
        payload_dtype,
        site_all_gather_packed,
        site_weight_scale,
    )

    pdtype = payload_dtype(precision_bits)

    def init(grads):
        return {}

    def aggregate(grads, state, weight, axis_name, live=None):
        from dinunet_implementations_tpu.engines.base import mask_dead_site

        # same liveness contract as the real engines (trainer/steps.py passes
        # live= unconditionally)
        grads, weight = mask_dead_site(grads, weight, live)
        scale = site_weight_scale(weight, axis_name)
        leaves, treedef = jax.tree.flatten(grads)
        out: list = [None] * len(leaves)
        groups: dict = {}
        for i, g in enumerate(leaves):
            if is_compressible(g):
                m, n = to_matrix(g).shape
                groups.setdefault(min(dad_reduction_rank, m, n), []).append(i)
            else:
                out[i] = jax.lax.psum(
                    g.astype(jnp.float32) * scale, axis_name
                ).astype(g.dtype)
        # one packed gather per rank class, exactly like the real engine
        for r, idxs in sorted(groups.items()):
            parts = []
            for i in idxs:
                G = to_matrix(leaves[i]).astype(jnp.float32)
                P = jnp.eye(G.shape[0], r, dtype=jnp.float32)
                parts.append(P.astype(pdtype))
                parts.append((G.T @ P * scale).astype(pdtype))
            gathered = site_all_gather_packed(parts, axis_name)
            for k, i in enumerate(idxs):
                G_hat = jnp.einsum(
                    "smr,snr->mn",
                    gathered[2 * k].astype(jnp.float32),
                    gathered[2 * k + 1].astype(jnp.float32),
                )
                out[i] = from_matrix(G_hat, leaves[i])
        return jax.tree.unflatten(treedef, out), state

    return Engine("rankDAD-exchange-only", init, aggregate)


def _compressible_shapes(dims=None):
    """(m, n, r) for every compressible leaf of the flagship (or --small)
    model — the basis of the analytic phase FLOP counts."""
    d = dict(windows=bench.WINDOWS, comps=bench.COMPS, wlen=bench.WLEN,
             enc_out=bench.ENC_OUT, hidden=bench.HIDDEN, batch=4)
    d.update(dims or {})
    model = ICALstm(input_size=d["enc_out"], hidden_size=d["hidden"],
                    num_comps=d["comps"], window_size=d["wlen"], num_cls=2)
    x = jnp.ones((2, d["windows"], d["comps"], d["wlen"]), jnp.float32)
    task = FederatedTask(model)
    params, _ = task.init_variables(jax.random.PRNGKey(0), x)
    shapes = []
    for g in jax.tree.leaves(params):
        if is_compressible(g):
            m, n = to_matrix(g).shape
            shapes.append((m, n, min(10, m, n)))
    return shapes


def analytic_phase_costs(dims, sites: int) -> dict:
    """Exact matmul FLOPs / wire bytes per federated ROUND per site for each
    rankDAD phase (2 FLOPs per MAC), from the leaf shapes."""
    shapes = _compressible_shapes(dims)
    per_iter = sum(4 * m * n * r for m, n, r in shapes)      # GᵀP + G(GᵀP)
    init_final = sum(4 * m * n * r for m, n, r in shapes)    # G@Ω + final GᵀP
    recon = sum(2 * sites * m * n * r for m, n, r in shapes)  # einsum over S
    gather_bytes = sum(4 * r * (m + n) for m, n, r in shapes)  # f32 payload
    return {
        "compressible_leaves": len(shapes),
        "power_iter_flops_per_iter_per_site": per_iter,
        "compression_fixed_flops_per_site": init_final,
        "reconstruction_flops_per_site": recon,
        "gather_bytes_per_site_f32": gather_bytes,
    }


def attribution(argv):
    obs = int(argv[argv.index("--obs") + 1]) if "--obs" in argv else 3
    small = "--small" in argv
    n = int(argv[argv.index("--epochs") + 1]) if "--epochs" in argv else (
        8 if small else 32
    )
    dims = dict(bench.SMALL_DIMS) if small else None
    dad = ENGINE_KW["rankDAD"]
    arms = {
        "dsgd": ("dSGD", {}),
        "exchange-only": ("rankDAD-exchange-only", dict(dad_reduction_rank=10)),
        "rankdad-cold-1iter": ("rankDAD", dict(
            dad, dad_num_pow_iters=1, dad_tol=0.0, dad_warm_start=False)),
        "rankdad-cold-5iter": ("rankDAD", dict(
            dad, dad_num_pow_iters=5, dad_tol=0.0, dad_warm_start=False)),
        "rankdad-warm-default": ("rankDAD", dict(dad, dad_warm_start=True)),
        # r14: the fused Pallas power-iteration twins — the differential
        # against the legacy arms IS the post-fusion power-iteration share
        # (interpret mode on CPU; regen on TPU for the flagship figures)
        "rankdad-cold-5iter-fused": ("rankDAD", dict(
            dad, dad_num_pow_iters=5, dad_tol=0.0, dad_warm_start=False,
            fused_poweriter=True)),
        "rankdad-warm-fused": ("rankDAD", dict(
            dad, dad_warm_start=True, fused_poweriter=True)),
    }
    chains, samples = {}, None
    for arm, (engine, kw) in arms.items():
        chains[arm], samples = bench._setup_epoch(engine, kw, dims=dims)
        chains[arm](1)  # compile before any timing
    dists = bench.interleaved_ab(chains, n, obs=obs)
    marg = {k: v["marginal_seconds_per_epoch"] for k, v in dists.items()}
    sites = (dims or {}).get("sites", bench.NUM_SITES)
    rounds = (dims or {}).get("steps", bench.STEPS_PER_EPOCH)
    base = {
        "metric": "rankDAD per-phase cost attribution (differential epochs)",
        "backend": jax.default_backend(),
        "sites": sites,
        "rounds_per_epoch": rounds,
        "observations_per_arm": obs,
        "chain_epochs": n,
    }
    if dims:
        base["dims"] = dims
    full = marg["rankdad-cold-5iter"]
    phases = [
        ("model+optimizer (dSGD floor)", marg["dsgd"]),
        ("gather+reconstruction (exchange-only − dsgd)",
         marg["exchange-only"] - marg["dsgd"]),
        ("power-iteration, 5 cold trips (cold-5iter − exchange-only)",
         marg["rankdad-cold-5iter"] - marg["exchange-only"]),
        ("power-iteration, per trip ((cold-5iter − cold-1iter)/4)",
         (marg["rankdad-cold-5iter"] - marg["rankdad-cold-1iter"]) / 4),
        ("compression with warm-started Ω (warm-default − exchange-only)",
         marg["rankdad-warm-default"] - marg["exchange-only"]),
        ("power-iteration FUSED, 5 cold trips (fused-cold-5iter − "
         "exchange-only)",
         marg["rankdad-cold-5iter-fused"] - marg["exchange-only"]),
        ("compression FUSED with warm-started Ω (warm-fused − "
         "exchange-only)",
         marg["rankdad-warm-fused"] - marg["exchange-only"]),
    ]
    for arm, dist in dists.items():
        print(json.dumps({
            **base, "kind": "arm", "arm": arm,
            "engine": arms[arm][0], "engine_kw": arms[arm][1],
            "samples_per_sec": bench.throughput_stats(dist, samples),
        }), flush=True)
    for name, sec in phases:
        print(json.dumps({
            **base, "kind": "phase", "phase": name,
            "seconds_per_epoch": round(sec, 6),
            "seconds_per_round": round(sec / rounds, 6),
            "fraction_of_cold_rankdad_epoch": round(sec / full, 4),
        }), flush=True)
    print(json.dumps({
        **base, "kind": "analytic",
        **analytic_phase_costs(dims, sites),
        "model_train_flops_per_sample": round(bench.flops_per_sample_dims(
            (dims or {}).get("windows", bench.WINDOWS),
            (dims or {}).get("comps", bench.COMPS)
            * (dims or {}).get("wlen", bench.WLEN),
            (dims or {}).get("enc_out", bench.ENC_OUT),
            (dims or {}).get("hidden", bench.HIDDEN),
        )),
    }), flush=True)


def main():
    if "--attribution" in sys.argv:
        attribution(sys.argv)
        return
    epochs = 10
    if "--epochs" in sys.argv:
        epochs = int(sys.argv[sys.argv.index("--epochs") + 1])
    engine_name = (sys.argv[sys.argv.index("--engine") + 1]
                   if "--engine" in sys.argv else "dSGD")
    S, steps, B = bench.NUM_SITES, bench.STEPS_PER_EPOCH, bench.BATCH_PER_SITE
    W, C, WL = bench.WINDOWS, bench.COMPS, bench.WLEN
    model = ICALstm(input_size=bench.ENC_OUT, hidden_size=bench.HIDDEN,
                    num_comps=C, window_size=WL, num_cls=2,
                    compute_dtype="bfloat16")
    task = FederatedTask(model)
    engine = make_engine(engine_name, **ENGINE_KW.get(engine_name, {}))
    opt = make_optimizer("adam", 1e-3)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(S, steps, B, W, C, WL)).astype(np.float32),
                    dtype=jnp.bfloat16)
    y = jnp.asarray((rng.random((S, steps, B)) > 0.5).astype(np.int32))
    w = jnp.ones((S, steps, B), jnp.float32)
    state0 = init_train_state(task, engine, opt, jax.random.PRNGKey(0),
                              x[0, 0], num_sites=S)
    epoch_fn = make_train_epoch_fn(task, engine, opt, mesh=None,
                                   local_iterations=1)
    if "--aot" in sys.argv:
        epoch_fn, put_x = compile_epoch_aot(epoch_fn, state0, x, y, w)
        x = put_x(x)

    s = state0
    for _ in range(2):
        s, _ = epoch_fn(s, x, y, w)
    jax.tree.map(np.asarray, s)

    # capture + summarize via telemetry/xprof.py — this script is a thin
    # consumer of the tracer layer, not an owner of trace-parsing code
    with capture(TRACE_DIR, fresh=True):
        s = state0
        for _ in range(epochs):
            s, _ = epoch_fn(s, x, y, w)
        jax.tree.map(np.asarray, s)

    print(f"top 25 device ops for {engine_name} "
          f"(us over {epochs} epochs; trace: {trace_files(TRACE_DIR)[0]})")
    for rec in summarize_device_ops(TRACE_DIR, top=25):
        print(f"{rec['total_us']:10.0f}  x{rec['count']:4d}  "
              f"{rec['name'][:80]}")


if __name__ == "__main__":
    main()
