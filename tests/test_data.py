"""Data layer tests against the reference's real fixture tree."""

import os

import numpy as np
import pytest

from dinunet_implementations_tpu.data import (
    FreeSurferDataset,
    FSVDataHandle,
    ICADataHandle,
    ICADataset,
    build_site_dataset,
    coerce_label,
    plan_epoch,
    plan_eval,
    read_aseg_stats,
    resolve_splits,
    split_by_ratio,
    kfold_splits,
    window_timecourses,
)
from dinunet_implementations_tpu.data.api import SiteArrays

FSL = "/root/reference/datasets/test_fsl/input"

needs_reference = pytest.mark.skipif(
    not os.path.isdir(FSL), reason="reference fixture not mounted"
)
SITE_SIZES = {0: 73, 1: 50, 2: 100, 3: 80, 4: 120}


def _fs_cache(site):
    return {
        "labels_file": f"site{site + 1}_Covariate.csv",
        "data_column": "freesurferfile",
        "labels_column": "isControl",
    }


def _fs_state(site):
    return {"baseDirectory": f"{FSL}/local{site}/simulatorRun"}


@needs_reference
def test_fs_handle_lists_covariate_index():
    h = FSVDataHandle(cache=_fs_cache(0), state=_fs_state(0))
    files = h.list_files()
    assert len(files) == SITE_SIZES[0]
    assert files[0] == "subject0_aseg_stats.txt"


@pytest.mark.parametrize("site", [0, 1])
@needs_reference
def test_fs_dataset_materializes(site):
    ds = build_site_dataset(FreeSurferDataset, FSVDataHandle, _fs_cache(site), _fs_state(site))
    assert len(ds) == SITE_SIZES[site]
    item = ds[0]
    assert item["inputs"].shape == (66,)
    assert item["inputs"].max() == pytest.approx(1.0)  # per-subject max-normalized
    assert item["labels"] in (0, 1)
    arrs = ds.as_arrays()
    assert arrs.inputs.shape == (SITE_SIZES[site], 66)
    np.testing.assert_allclose(arrs.inputs[0], item["inputs"])
    # label parity with the covariate CSV ('False'→0, 'True'→1)
    import csv

    with open(f"{_fs_state(site)['baseDirectory']}/site{site + 1}_Covariate.csv") as fh:
        rows = list(csv.DictReader(fh))
    expect = [int(r["isControl"].strip().lower() == "true") for r in rows]
    np.testing.assert_array_equal(arrs.labels, expect)


def test_coerce_label():
    assert coerce_label("True") == 1
    assert coerce_label(" false ") == 0
    assert coerce_label(True) == 1
    assert coerce_label(0) == 0
    assert coerce_label("1.0") == 1


def test_ica_windowing_matches_reference_loop():
    """Vectorized windowing == the reference's nested python loop
    (comps/icalstm/__init__.py:27-33), incl. the overlap quirk."""
    rng = np.random.default_rng(0)
    data = rng.normal(size=(3, 5, 40))  # N=3 subjects, C=5 comps, T=40
    for w, stride in [(10, 10), (10, 5), (8, 6)]:
        temporal = 40
        got = window_timecourses(data, temporal, w, stride)
        spc = int(temporal / w)
        ref = np.zeros((3, spc, 5, w))
        for i in range(3):
            for j in range(spc):
                ref[i, j] = data[i, :, j * stride : j * stride + w]
        np.testing.assert_allclose(got, ref)


def test_ica_dataset_from_synthetic_fixture(tmp_path):
    rng = np.random.default_rng(1)
    data = rng.normal(size=(6, 4, 20)).astype(np.float32)
    np.save(tmp_path / "tc.npy", data)
    with open(tmp_path / "labels.csv", "w") as fh:
        fh.write("index,label\n")
        for i in range(6):
            fh.write(f"{i},{i % 2}\n")
    cache = {
        "data_file": "tc.npy",
        "labels_file": "labels.csv",
        "window_size": 5,
        "window_stride": 5,
        "temporal_size": 20,
        "num_components": 4,
    }
    state = {"baseDirectory": str(tmp_path)}
    ds = build_site_dataset(ICADataset, ICADataHandle, cache, state)
    assert len(ds) == 6
    assert ds[0]["inputs"].shape == (4, 4, 5)  # [S, C, W]
    arrs = ds.as_arrays()
    assert arrs.inputs.shape == (6, 4, 4, 5)
    np.testing.assert_array_equal(arrs.labels, [0, 1, 0, 1, 0, 1])


def test_split_by_ratio_partition():
    s = split_by_ratio(73, [0.7, 0.15, 0.15], seed=3)
    allix = np.concatenate([s["train"], s["validation"], s["test"]])
    assert len(allix) == 73
    assert len(np.unique(allix)) == 73
    assert len(s["train"]) == int(73 * 0.7)


def test_kfold_partition():
    folds = kfold_splits(50, 10, seed=0)
    assert len(folds) == 10
    for f in folds:
        allix = np.concatenate([f["train"], f["validation"], f["test"]])
        assert len(np.unique(allix)) == 50
        assert len(f["test"]) == 5
    # every sample is in exactly one test fold across folds
    tests = np.concatenate([f["test"] for f in folds])
    assert len(np.unique(tests)) == 50


def test_resolve_splits_precedence(tmp_path):
    import json

    sf = tmp_path / "split0.json"
    sf.write_text(json.dumps({"train": [0, 1], "validation": [2], "test": [3]}))
    out = resolve_splits(4, split_files=["split0.json"], base_dir=str(tmp_path))
    assert out[0]["train"] == [0, 1]
    out = resolve_splits(40, num_folds=4)
    assert len(out) == 4
    out = resolve_splits(40, split_ratio=[0.8, 0.1, 0.1])
    assert len(out) == 1


def _mk_site(n, d=4, seed=0):
    rng = np.random.default_rng(seed)
    return SiteArrays(
        rng.normal(size=(n, d)).astype(np.float32),
        (np.arange(n) % 2).astype(np.int32),
        np.arange(n, dtype=np.int32),
    )


def test_plan_epoch_wrap():
    sites = [_mk_site(40, seed=1), _mk_site(20, seed=2), _mk_site(33, seed=3)]
    fb = plan_epoch(sites, batch_size=16, seed=0, pad_mode="wrap")
    assert fb.inputs.shape == (3, 2, 16, 4)  # steps = 40//16 = 2
    assert fb.weights.min() == 1.0  # wrap: no padding
    # site 1 (20 samples → 1 batch) recycles for step 2
    assert (fb.indices[1] >= 0).all()


def test_plan_eval_mask_covers_all_once():
    sites = [_mk_site(10), _mk_site(7)]
    fb = plan_eval(sites, batch_size=4)
    assert fb.steps == 3
    # site 1: 7 real samples, 5 padded
    assert fb.weights[1].sum() == 7
    real = fb.indices[1][fb.weights[1] > 0]
    np.testing.assert_array_equal(np.sort(real), np.arange(7))
    # padding never counted
    assert (fb.indices[1][fb.weights[1] == 0] == -1).all()


def test_plan_epoch_empty_site_masked():
    sites = [_mk_site(40), _mk_site(5)]  # site 1 < batch_size → 0 train batches
    fb = plan_epoch(sites, batch_size=16, pad_mode="wrap")
    assert fb.weights[1].sum() == 0  # contributes nothing, zero-weighted
    assert fb.weights[0].sum() == 32


def test_kfold_rejects_k1():
    with pytest.raises(ValueError):
        kfold_splits(10, 1)


def test_split_ratio_two_way_no_test_leak():
    s = split_by_ratio(73, [0.8, 0.2], seed=0)
    assert len(s["test"]) == 0
    assert len(s["train"]) + len(s["validation"]) == 73


def test_label_coercion_deviation_and_bug_compat():
    """Documented deviation (VERDICT weak #7): numeric strings parse
    numerically by default; bug_compatible=True reproduces the reference's
    (s.lower() == 'true') rule where "1" -> 0."""
    from dinunet_implementations_tpu.data.freesurfer import coerce_label

    assert coerce_label("true") == 1
    assert coerce_label("False") == 0
    assert coerce_label("1") == 1
    assert coerce_label("0.0") == 0
    assert coerce_label(True) == 1
    # reference bit-compatibility mode: every string is (== 'true')
    assert coerce_label("1", bug_compatible=True) == 0
    assert coerce_label("true", bug_compatible=True) == 1
    assert coerce_label("yes", bug_compatible=True) == 0


def test_demo_tree_fs_layout_and_loadable(tmp_path):
    """The self-contained demo fixture (VERDICT r3 #5) generates the exact
    simulator layout the runner discovers, and its data round-trips through
    the real FS dataset loader."""
    from dinunet_implementations_tpu.data.demo import make_demo_tree
    from dinunet_implementations_tpu.runner.fed_runner import (
        discover_site_dirs,
        load_site_splits,
    )
    from dinunet_implementations_tpu.core.config import (
        TrainConfig,
        resolve_site_configs,
    )

    root = str(tmp_path / "demo")
    make_demo_tree(root, n_sites=3, subjects=10)
    dirs = discover_site_dirs(root)
    assert len(dirs) == 3
    cfg = TrainConfig(split_ratio=(0.7, 0.15, 0.15))
    site_cfgs = resolve_site_configs(cfg, root, num_sites=3)
    assert site_cfgs[1].fs_args.labels_file == "site2_Covariate.csv"
    folds = load_site_splits(site_cfgs[0], dirs, site_cfgs)
    assert len(folds) == 1
    for arrs in folds[0]["train"]:
        assert arrs.inputs.shape[1] == 66
        assert arrs.inputs.dtype == np.float32
        # per-subject row-max normalization applied (values in (0, 1])
        assert arrs.inputs.max() <= 1.0 + 1e-6


def test_demo_tree_ica_layout_and_loadable(tmp_path):
    from dinunet_implementations_tpu.data.demo import make_demo_tree
    from dinunet_implementations_tpu.runner.fed_runner import (
        discover_site_dirs,
        load_site_splits,
    )
    from dinunet_implementations_tpu.core.config import (
        TrainConfig,
        resolve_site_configs,
    )

    root = str(tmp_path / "demo_ica")
    make_demo_tree(root, "ICA-Classification", n_sites=2, subjects=8)
    dirs = discover_site_dirs(root)
    cfg = TrainConfig(task_id="ICA-Classification", split_ratio=(0.7, 0.15, 0.15))
    site_cfgs = resolve_site_configs(cfg, root, num_sites=2)
    folds = load_site_splits(site_cfgs[0], dirs, site_cfgs)
    # [subjects, windows, comps, window_size] per site
    x = folds[0]["train"][0].inputs
    assert x.ndim == 4 and x.shape[1] == 8 and x.shape[3] == 10


def test_plan_epoch_starvation_message():
    """When every site is smaller than batch_size under drop_last, the error
    must spell out the fix (VERDICT r4 #6)."""
    import pytest

    sites = [_mk_site(5), _mk_site(7)]
    with pytest.raises(AssertionError, match="lower batch_size to at most 7"):
        plan_epoch(sites, batch_size=16)


@pytest.mark.slow
def test_demo_tree_small_subjects_trains_with_default_batch(tmp_path):
    """VERDICT r4 #6 crash path: `--subjects 12` + the CLI default
    batch_size=16 used to die with 'no site yields a batch'; the trainer now
    clamps batch_size to the smallest site's train split and runs."""
    from dinunet_implementations_tpu.data.demo import make_demo_tree
    from dinunet_implementations_tpu.runner.fed_runner import FedRunner

    root = str(tmp_path / "demo")
    make_demo_tree(root, n_sites=2, subjects=12)
    runner = FedRunner(
        data_path=root, out_dir=str(tmp_path / "out"), epochs=1,
        validation_epochs=1, batch_size=16,  # the CLI default
    )
    res = runner.run(verbose=False)
    assert res and 0.0 <= res[0]["test_scores"]["auc"] <= 1.0
    # the clamp is fold-local (cfg.replace): the caller's config is untouched
    assert runner.cfg.batch_size == 16
