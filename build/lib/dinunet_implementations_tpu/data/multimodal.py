"""Multimodal FS+ICA dataset — TPU-build extension.

Joins the two reference modalities per subject: the 66 FreeSurfer aseg volumes
(data/freesurfer.py semantics) and the windowed ICA timecourses
(data/ica.py semantics). The two are **packed into one flat float vector**
``[fs_input_size + S*C*W]`` so the standard single-array site-batch pipeline
(data/batching.py) applies unchanged; ``MultimodalNet`` unpacks by static
offsets (models/transformer.py).

Site layout: one directory holding the FS covariate CSV + aseg files AND the
ICA ``data_file``/``labels_file``; subjects are joined positionally (row i of
the covariate CSV ↔ data_index of labels row i).
"""

from __future__ import annotations

import os

import numpy as np

from .api import DataHandle, SiteArrays, SiteDataset
from .freesurfer import _read_covariates, coerce_label, read_aseg_stats
from .ica import load_timecourses, window_timecourses


class MultimodalDataset(SiteDataset):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.fs_feats = None
        self.ica_windows = None

    def _load_indices(self, files, **kw):
        base = self.state["baseDirectory"]
        # FS side
        cov_path = os.path.join(base, self.cache["labels_file"])
        index, rows = _read_covariates(cov_path, self.cache.get("data_column"))
        labels_col = self.cache["labels_column"]
        # ICA side
        tc = load_timecourses(self.path(cache_key="data_file"))
        self.ica_windows = window_timecourses(
            tc,
            self.cache["temporal_size"],
            self.cache["window_size"],
            self.cache["window_stride"],
        ).astype(np.float32)
        n = min(len(index), len(self.ica_windows))
        self.fs_feats = np.stack(
            [read_aseg_stats(os.path.join(base, f)) for f in index[:n]]
        )
        self.indices += [
            [i, coerce_label(rows[index[i]][labels_col])] for i in range(n)
        ]

    def __getitem__(self, ix) -> dict:
        i, y = self.indices[ix]
        packed = np.concatenate(
            [self.fs_feats[int(i)], self.ica_windows[int(i)].reshape(-1)]
        )
        return {"inputs": packed, "labels": int(y), "ix": ix}

    def as_arrays(self) -> SiteArrays:
        rows = np.asarray([int(i) for i, _ in self.indices])
        packed = np.concatenate(
            [self.fs_feats[rows], self.ica_windows[rows].reshape(len(rows), -1)],
            axis=1,
        )
        return SiteArrays(
            packed.astype(np.float32),
            np.asarray([int(y) for _, y in self.indices], np.int32),
            np.arange(len(rows), dtype=np.int32),
        )


class MultimodalDataHandle(DataHandle):
    """Inventory = covariate CSV index (FS convention)."""

    def list_files(self) -> list:
        path = os.path.join(self.state["baseDirectory"], self.cache["labels_file"])
        index, _ = _read_covariates(path, self.cache.get("data_column"))
        return index
